"""The single read point for ``A5GEN_*`` environment knobs (GL012).

The engine grew one escape-hatch env var per subsystem —
``A5GEN_PALLAS``, ``A5GEN_PALLAS_G``, ``A5GEN_PALLAS_INTERPRET``,
``A5GEN_CASCADE_CLOSE``, ``A5GEN_SUPERSTEP``, ``A5GEN_DCN_TIMEOUT``, … —
each with its own ad-hoc ``os.environ`` read.  Sprawled reads make the
knob surface unauditable (graftlint GL012 now flags direct reads outside
this module).  Every accessor here is a thin, *semantics preserving*
wrapper — call sites with bespoke vocabularies keep their own parsing
(``A5GEN_EMIT``), while the on-by-default escape hatches share ONE
off-spelling convention via :func:`env_opt_out` — either way the reads
go through one door.

Deliberately dependency-free (stdlib only): ``ops/`` modules import this
at module top level, and the ``runtime`` package's eager imports
(checkpoint/progress/sinks) are jax-free, so no import cycle exists.
"""

from __future__ import annotations

import os
from typing import Optional


#: The engine's one pre-``A5GEN_`` knob, grandfathered by name: renaming
#: it would break documented user environments (README, PERF.md §10).
_LEGACY_KNOBS = frozenset({"A5_NATIVE"})


def read_env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw accessor: ``os.environ.get`` restricted to the engine's knob
    namespace (``A5GEN_*`` plus the grandfathered ``A5_NATIVE``).  Every
    other helper in this module funnels through here, so "what can the
    environment change?" has one grep-able answer."""
    if not name.startswith("A5GEN_") and name not in _LEGACY_KNOBS:
        raise ValueError(
            f"read_env is the A5GEN_* accessor; got {name!r} "
            "(read other variables with os.environ directly)"
        )
    return os.environ.get(name, default)


def env_str(name: str, default: str = "") -> str:
    """String knob with a non-None default."""
    value = read_env(name)
    return default if value is None else value


def env_is(name: str, literal: str) -> bool:
    """Exact-match test (``A5GEN_PALLAS == "1"`` and friends)."""
    return read_env(name) == literal


#: (name, value) pairs already warned about — accessors like
#: ``close_enabled`` are called from per-word planning loops, and one
#: typo must produce one diagnostic, not one per word.
_WARNED: set = set()


def env_warn_once(name: str, value: str, message: str) -> None:
    """One knob diagnostic per (name, spelling) process-wide.

    THE warn-once seam for every knob accessor — in this module and at
    the bespoke-vocabulary call sites that keep their own parsing
    (``A5GEN_PALLAS`` in ``ops/pallas_expand.py``,
    ``A5GEN_DCN_TIMEOUT`` in ``parallel/multihost.py``).  Accessors are
    called from per-word planning loops and per-superstep drive loops;
    one typo must produce one diagnostic, not one per iteration."""
    if (name, value) in _WARNED:
        return
    _WARNED.add((name, value))
    import sys

    print(f"a5gen: warning: {message}", file=sys.stderr)


def env_opt_out(name: str, default_desc: str) -> bool:
    """Shared parse for the on-by-default escape hatches
    (``A5GEN_SUPERSTEP``, ``A5GEN_CASCADE_CLOSE``, ``A5GEN_PIPELINE``):
    returns True when the hatch is pulled (``off``/``0``/``no``).  Any
    other value outside the on-spellings (empty/``auto``/``on``/``1``)
    warns (once per value) and keeps the default — a typo must not
    silently change behavior."""
    val = env_str(name)
    if val.lower() in ("off", "0", "no"):
        return True
    if val.lower() not in ("", "auto", "on", "1"):
        env_warn_once(
            name, val,
            f"unrecognized {name}={val!r} (want off|0|no or "
            f"on|1|auto); keeping the default ({default_desc})",
        )
    return False


def pipeline_enabled() -> bool:
    """Superstep-pipeline knob: ``A5GEN_PIPELINE`` set to ``off``/``0``/
    ``no`` pins the barriered superstep drive (fetch immediately after
    dispatch) instead of the double-buffered pipeline (PERF.md §18)."""
    return not env_opt_out(
        "A5GEN_PIPELINE", "pipelined superstep drive"
    )


def stream_enabled() -> bool:
    """Streaming-ingestion knob: ``A5GEN_STREAM`` set to ``off``/``0``/
    ``no`` pins whole-dictionary plan materialization instead of the
    chunked streaming pipeline (PERF.md §19) — the one-release escape
    hatch mirroring ``A5GEN_SUPERSTEP``/``A5GEN_PIPELINE``."""
    return not env_opt_out(
        "A5GEN_STREAM", "streaming plan pipeline for chunked dictionaries"
    )


def telemetry_enabled() -> bool:
    """Telemetry knob: ``A5GEN_TELEMETRY`` set to ``off``/``0``/``no``
    disables the hot-path instrumentation — span-timeline appends,
    per-fetch registry updates, progress enrichment (PERF.md §21).
    Counters backing result surfaces (schema/step cache stats) always
    record; the hatch changes observability, never results."""
    return not env_opt_out(
        "A5GEN_TELEMETRY", "telemetry registry + span timeline on"
    )


def pack_enabled() -> bool:
    """Cross-job physical packing knob (PERF.md §22): ``A5GEN_PACK``
    set to ``off``/``0``/``no`` restores the resident engine's per-job
    superstep dispatch (the PR 8 path) instead of fusing compatible
    tenants' block ranges into one dispatch.  The streams are identical
    either way; only fill ratio and dispatch count differ."""
    return not env_opt_out(
        "A5GEN_PACK", "cross-job packed superstep dispatch"
    )


def pair_enabled() -> bool:
    """Pair-lane tier knob (PERF.md §24): ``A5GEN_PAIR`` set to
    ``off``/``0``/``no`` pins K=1 (one candidate per hash lane) instead
    of packing two consecutive combination ranks into each lane where
    the substitution geometry allows.  The candidate/hit streams are
    identical either way; only per-candidate op cost differs.  One-
    release escape hatch, same convention as ``A5GEN_PIPELINE``."""
    return not env_opt_out(
        "A5GEN_PAIR", "pair-lane (K=2) tier on for eligible schemas"
    )


def refuse_threshold() -> "Optional[float]":
    """Dynamic re-fuse fill threshold (PERF.md §28): when a fused
    group's per-round fill drops below this ratio after a tenant
    departs, the engine re-fuses the survivors into a tighter group.
    ``A5GEN_REFUSE`` holds the ratio (0 < r <= 1); ``off``/``0``/``no``
    disables re-fuse; empty/unset keeps the default (0.5); the
    ``within``/``within:<ratio>`` spellings keep re-fuse on but pin
    the within-group-only merge scope (see :func:`refuse_scope`).
    ``Engine(refuse_below=)`` overrides this per engine; an unparseable
    value warns once and keeps the default — a typo must not silently
    stop (or start) retracing groups."""
    val = read_env("A5GEN_REFUSE")
    if val in (None, ""):
        return 0.5
    low = val.lower()
    if low in ("off", "0", "no"):
        return None
    if low == "within":
        return 0.5
    if low.startswith("within:"):
        val = val.split(":", 1)[1]
    try:
        r = float(val)
        if not 0.0 < r <= 1.0:
            raise ValueError
    except ValueError:
        env_warn_once(
            "A5GEN_REFUSE", val,
            f"unrecognized A5GEN_REFUSE={val!r} (want a fill ratio "
            "in (0, 1], within[:ratio], or off|0|no); keeping the "
            "default (0.5)",
        )
        return 0.5
    return r


def refuse_scope() -> str:
    """Re-fuse merge scope (PERF.md §31): ``cross`` (the default —
    thin post-churn survivors merge ACROSS compatible fused groups on
    the engine; the ``pack_candidate`` static key proves safety) or
    ``within`` (each thin group re-fuses only its own survivors — the
    PR 18 behavior and the churn bench's control arm).  Spelled inside
    ``A5GEN_REFUSE`` (``within`` / ``within:<ratio>``) so one knob
    owns the whole re-fuse surface; ``Engine(refuse_scope=)``
    overrides per engine."""
    val = env_str("A5GEN_REFUSE").lower()
    if val == "within" or val.startswith("within:"):
        return "within"
    return "cross"


def split_setting() -> str:
    """Fleet giant-job splitting (``A5GEN_SPLIT``, PERF.md §31):
    ``auto`` (empty/unset default — the router scatters an oversized
    crack job across engines when its word count crosses the split
    threshold and >= 2 engines can take a stripe), ``on``/``1`` (split
    every eligible crack job regardless of size), ``off``/``0``/``no``
    (never auto-split; the explicit ``split`` op still works).  The
    router's ``--split`` flag overrides this per process; an
    unrecognized value warns once and keeps ``auto`` — a typo must not
    silently change placement."""
    val = env_str("A5GEN_SPLIT")
    low = val.lower()
    if low in ("", "auto"):
        return "auto"
    if low in ("on", "1"):
        return "on"
    if low in ("off", "0", "no"):
        return "off"
    env_warn_once(
        "A5GEN_SPLIT", val,
        f"unrecognized A5GEN_SPLIT={val!r} (want auto|on|off); "
        "keeping the default (auto)",
    )
    return "auto"


def tune_profile_setting() -> "Optional[str]":
    """Autotuned-geometry profile loading (``A5GEN_TUNE_PROFILE``,
    PERF.md §29): ``off``/``0``/``no`` disables profile loading (the
    escape hatch — built-in defaults only); empty/unset enables it at
    the default directory (``~/.cache/a5gen/tune``); any other value is
    a directory override (the test/CI spelling).  Returns ``None`` for
    disabled, else the directory string (possibly empty = default)."""
    val = env_str("A5GEN_TUNE_PROFILE")
    if val.lower() in ("off", "0", "no"):
        return None
    return val


def schema_cache_dir() -> "Optional[str]":
    """On-disk PieceSchema cache directory (``A5GEN_SCHEMA_CACHE``;
    empty/unset = no persistent cache).  ``SweepConfig.schema_cache`` /
    ``--schema-cache`` override this per run."""
    return read_env("A5GEN_SCHEMA_CACHE") or None


def schema_cache_max_mb() -> "Optional[float]":
    """LRU size cap (MB) on the on-disk PieceSchema cache
    (``A5GEN_SCHEMA_CACHE_MAX_MB``; empty/unset = unbounded).
    ``SweepConfig.schema_cache_max_mb`` / ``--schema-cache-max-mb``
    override this per run; an unparseable value warns once and keeps
    the cache unbounded — a typo must not start evicting."""
    val = read_env("A5GEN_SCHEMA_CACHE_MAX_MB")
    if val in (None, ""):
        return None
    try:
        mb = float(val)
        if mb <= 0:
            raise ValueError
    except ValueError:
        env_warn_once(
            "A5GEN_SCHEMA_CACHE_MAX_MB", val,
            f"unrecognized A5GEN_SCHEMA_CACHE_MAX_MB={val!r} (want a "
            "positive number of megabytes); keeping the cache "
            "unbounded",
        )
        return None
    return mb


def faults_spec() -> "Optional[str]":
    """Deterministic fault-injection arming (PERF.md §23):
    ``A5GEN_FAULTS`` holds a fault-plan spec (grammar in
    ``runtime/faults.py`` — e.g. ``superstep.dispatch:nth=2``);
    empty/unset = no faults armed.  Parsed by ``runtime/faults.py`` at
    Sweep/Engine construction, never at import; a malformed spec fails
    loudly there — a typo must not silently certify recovery paths the
    faults never exercised."""
    return read_env("A5GEN_FAULTS") or None


def emit_scheme() -> str:
    """Message-emission scheme knob: ``A5GEN_EMIT`` selects between the
    per-slot piece emission (``perslot`` — the default; PERF.md §17) and
    the legacy per-byte unit scan (``bytescan`` — the A/B arm and escape
    hatch, kept for one release).  Unrecognized values warn and keep the
    default — a typo must not silently change the compiled kernels."""
    val = read_env("A5GEN_EMIT")
    if val is None or val in ("", "perslot"):
        return "perslot"
    if val == "bytescan":
        return "bytescan"
    env_warn_once(
        "A5GEN_EMIT", val,
        f"unrecognized A5GEN_EMIT={val!r} (want perslot|bytescan); "
        "keeping the default (perslot)",
    )
    return "perslot"

"""The single read point for ``A5GEN_*`` environment knobs (GL012).

The engine grew one escape-hatch env var per subsystem —
``A5GEN_PALLAS``, ``A5GEN_PALLAS_G``, ``A5GEN_PALLAS_INTERPRET``,
``A5GEN_CASCADE_CLOSE``, ``A5GEN_SUPERSTEP``, ``A5GEN_DCN_TIMEOUT``, … —
each with its own ad-hoc ``os.environ`` read.  Sprawled reads make the
knob surface unauditable (graftlint GL012 now flags direct reads outside
this module).  Every accessor here is a thin, *semantics preserving*
wrapper — call sites keep their bespoke parsing, vocabularies and
warnings (the off-spellings deliberately differ per knob and are pinned
by tests), they just read through one door.

Deliberately dependency-free (stdlib only): ``ops/`` modules import this
at module top level, and the ``runtime`` package's eager imports
(checkpoint/progress/sinks) are jax-free, so no import cycle exists.
"""

from __future__ import annotations

import os
from typing import Optional


#: The engine's one pre-``A5GEN_`` knob, grandfathered by name: renaming
#: it would break documented user environments (README, PERF.md §10).
_LEGACY_KNOBS = frozenset({"A5_NATIVE"})


def read_env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw accessor: ``os.environ.get`` restricted to the engine's knob
    namespace (``A5GEN_*`` plus the grandfathered ``A5_NATIVE``).  Every
    other helper in this module funnels through here, so "what can the
    environment change?" has one grep-able answer."""
    if not name.startswith("A5GEN_") and name not in _LEGACY_KNOBS:
        raise ValueError(
            f"read_env is the A5GEN_* accessor; got {name!r} "
            "(read other variables with os.environ directly)"
        )
    return os.environ.get(name, default)


def env_str(name: str, default: str = "") -> str:
    """String knob with a non-None default."""
    value = read_env(name)
    return default if value is None else value


def env_is(name: str, literal: str) -> bool:
    """Exact-match test (``A5GEN_PALLAS == "1"`` and friends)."""
    return read_env(name) == literal


def emit_scheme() -> str:
    """Message-emission scheme knob: ``A5GEN_EMIT`` selects between the
    per-slot piece emission (``perslot`` — the default; PERF.md §17) and
    the legacy per-byte unit scan (``bytescan`` — the A/B arm and escape
    hatch, kept for one release).  Unrecognized values warn and keep the
    default — a typo must not silently change the compiled kernels."""
    val = read_env("A5GEN_EMIT")
    if val is None or val in ("", "perslot"):
        return "perslot"
    if val == "bytescan":
        return "bytescan"
    import sys

    print(
        f"a5gen: warning: unrecognized A5GEN_EMIT={val!r} "
        "(want perslot|bytescan); keeping the default (perslot)",
        file=sys.stderr,
    )
    return "perslot"

"""Fleet autoscaling: the router-owned elastic control loop
(PERF.md §27, ROADMAP item 1's remaining half).

PR 13's router holds every elasticity signal — per-engine routed
counts, scraped ``jobs_building``/``jobs_staged``/``jobs_queued``,
the admission pending queue, drain state, deaths, and (new) the
health ladder — but could not act on them: a traffic burst queued
and a quarantined engine sat quarantined.  The :class:`Autoscaler`
closes the loop with THREE moves, each riding a seam the fleet
already ships:

* **Scale up** — sustained backlog per capacity engine above
  ``scale_up_at`` for ``up_window`` consecutive ticks spawns one
  engine through the caller's ``spawner`` (``a5gen fleet`` wires
  :func:`runtime.fleet.spawn_engines`; tests wire in-process
  engines).  Placement, affinity, and crash-replay are untouched —
  a new engine is just an ``attach``.
* **Scale down** — sustained backlog below ``scale_down_at`` for
  ``down_window`` ticks drains the idlest engine (the PR 13 drain
  path: no new placements, routed jobs migrate off with their
  checkpoints) and REAPS it once empty (``FleetRouter.detach``).
* **Replace** — a quarantined engine (the §27 health ladder's
  circuit breaker) is drained + reaped the same way, and the min
  floor respawns capacity — the §23 per-engine recovery ladder
  closed at fleet scope.  When the quarantined engine is the LAST
  placeable one, the replacement spawns FIRST and the drain waits
  for the next tick: draining with nowhere to migrate would fail
  the jobs a quarantine promises to preserve.

Hysteresis (the consecutive-tick windows) and ``cooldown_s`` after
every action keep churn from flapping: one noisy scrape can neither
spawn nor reap, and two actions never land back to back.  A failed
spawn (the ``engine.spawn`` injection point) is counted, logged, and
retried after the cooldown — the control loop itself never dies.

The scaler owns ONE thread (``interval_s > 0``) or is ticked manually
(``interval_s=0`` — tests drive ``tick()`` for determinism).  Two
locks, always taken in this order: ``_tick_lock`` serializes whole
ticks (manual ticks and the loop thread coexist), and the inner
``_lock`` guards the mutable state (streaks, cooldown, reap list) in
SHORT critical sections only — router I/O (attach's socket connect,
drain's sends, detach's shutdown + process reap) always runs outside
``_lock``, so ``describe()`` (the client-facing ``stats`` op) never
stalls behind a slow engine shutdown.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from . import faults as faults_mod
from . import telemetry


@dataclass(frozen=True)
class AutoscaleConfig:
    """Elastic policy knobs (``a5gen fleet --autoscale MIN:MAX``).

    ``scale_up_at`` / ``scale_down_at`` are BACKLOG PER CAPACITY
    ENGINE — routed + engine-internal (scraped) jobs plus the router's
    admission-pending depth, divided by the engines able to take
    placements.  The windows are consecutive ``tick()`` observations
    (hysteresis); ``cooldown_s`` spaces actions so churn cannot
    flap."""

    min_engines: int = 1
    max_engines: int = 4
    scale_up_at: float = 2.0
    scale_down_at: float = 0.25
    up_window: int = 2
    down_window: int = 4
    cooldown_s: float = 10.0
    #: control-loop cadence; 0 = no thread (manual ``tick()``).
    interval_s: float = 1.0

    def __post_init__(self) -> None:
        if self.min_engines < 1:
            raise ValueError("autoscale min_engines must be >= 1")
        if self.max_engines < self.min_engines:
            raise ValueError(
                f"autoscale max ({self.max_engines}) must be >= min "
                f"({self.min_engines})"
            )
        if self.scale_down_at >= self.scale_up_at:
            raise ValueError(
                "scale_down_at must sit below scale_up_at "
                f"(got {self.scale_down_at} >= {self.scale_up_at}) — "
                "overlapping thresholds flap"
            )


#: What the spawner returns: (endpoint, engine_id, subprocess-or-None).
SpawnResult = Tuple[str, str, Optional[object]]


class Autoscaler:
    """The router-owned elastic control loop (PERF.md §27)."""

    def __init__(self, router, spawner: Callable[[], SpawnResult],
                 config: Optional[AutoscaleConfig] = None) -> None:
        self.cfg = config if config is not None else AutoscaleConfig()
        self._router = router
        self._spawner = spawner
        #: serializes whole ticks (outer; never held by describe()).
        self._tick_lock = threading.Lock()
        #: guards the mutable state below in SHORT sections (inner —
        #: only ever taken under ``_tick_lock`` or alone).
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0.0
        #: engine ids THIS scaler drained (scale-down / quarantine
        #: replacement) — reaped once their routed set empties.
        self._reaping: List[str] = []
        self._counters0 = {
            name: int(telemetry.counter(f"fleet.{name}").value)
            for name in ("scale_ups", "scale_downs", "spawn_failures")
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        router.autoscaler = self
        if self.cfg.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="a5-fleet-autoscale",
                daemon=True,
            )
            self._thread.start()

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — loop must live
                # The control loop NEVER dies with the fleet it
                # manages: log and keep ticking (a persistent error
                # shows up as counters that stop moving).
                print(
                    f"a5gen: fleet: autoscale tick failed "
                    f"({type(exc).__name__}: {exc}); continuing",
                    file=sys.stderr,
                )

    # -- observability ---------------------------------------------------

    def describe(self) -> dict:
        """The ``stats`` op's ``fleet.autoscale`` section.  Takes only
        the inner state lock — a tick blocked on a slow engine
        shutdown can never stall a stats client."""
        with self._lock:
            reaping = list(self._reaping)
            up, down = self._up_streak, self._down_streak
            cooling = time.monotonic() < self._cooldown_until
        return {
            "min": self.cfg.min_engines,
            "max": self.cfg.max_engines,
            "scale_up_at": self.cfg.scale_up_at,
            "scale_down_at": self.cfg.scale_down_at,
            "up_streak": up,
            "down_streak": down,
            "cooling_down": cooling,
            "reaping": reaping,
            **{
                name: int(
                    telemetry.counter(f"fleet.{name}").value
                ) - base
                for name, base in self._counters0.items()
            },
        }

    # -- the control loop ----------------------------------------------

    def tick(self) -> None:
        """One control observation: reap drained engines, handle
        quarantined ones (replacement-first when they are the last
        capacity), then apply the hysteresis-windowed scale up/down
        policy.  Serialized by ``_tick_lock`` so manual ticks and the
        loop thread coexist; router I/O runs with only that outer
        lock held."""
        with self._tick_lock:
            if self._stop.is_set():
                return
            now = time.monotonic()
            self._reap_pass()
            if self._quarantine_pass(now):
                return  # this tick's action budget went to replacement
            capacity, backlog = self._signals()
            pool = len(capacity)
            per = backlog / max(1, pool)
            with self._lock:
                cooling = now < self._cooldown_until
                action = None
                if pool < self.cfg.min_engines:
                    # Min floor is an invariant, not a trend: replace
                    # lost capacity immediately (cooldown still spaces
                    # retries so a failing spawner cannot storm).
                    if not cooling:
                        action = "up"
                elif per >= self.cfg.scale_up_at and pool < \
                        self.cfg.max_engines:
                    self._up_streak += 1
                    self._down_streak = 0
                    if self._up_streak >= self.cfg.up_window \
                            and not cooling:
                        action = "up"
                elif per <= self.cfg.scale_down_at and pool > \
                        self.cfg.min_engines:
                    self._down_streak += 1
                    self._up_streak = 0
                    if self._down_streak >= self.cfg.down_window \
                            and not cooling:
                        action = "down"
                else:
                    # Between thresholds: the hysteresis dead band —
                    # streaks reset so only SUSTAINED pressure moves
                    # the pool.
                    self._up_streak = 0
                    self._down_streak = 0
            if action == "up":
                self._scale_up(now)
            elif action == "down":
                self._scale_down(capacity, now)

    def _signals(self) -> Tuple[list, float]:
        """Capacity pool + total backlog.  Per-engine backlog is the
        LARGER of the router's live routed count and the engine's
        scraped internal load (runnable+staged+building+queued) — the
        two overlap for router-placed jobs, and max() counts
        attach-mode engines' external clients without double counting
        the fleet's own."""
        from .fleet import scraped_load

        pending = self._router.pending_depth()
        capacity = []
        backlog = float(pending)
        for link in self._router.engines():
            if not link.alive or link.draining or \
                    link.health == "quarantined":
                continue
            capacity.append(link)
            backlog += max(len(link.routed), scraped_load(link.scrape))
        return capacity, backlog

    def _quarantine_pass(self, now: float) -> bool:
        """Circuit-broken engines drain (their jobs migrate off with
        checkpoints) and join the reap list — UNLESS a quarantined
        engine is the last placeable capacity: draining it would
        strand its migrating jobs on 'no live engine' and fail them,
        so the replacement spawns FIRST and the drain waits for the
        next tick (the quarantined engine keeps serving, degraded,
        until somewhere to migrate exists).  Returns True when this
        tick's action went to a replacement spawn."""
        links = self._router.engines()
        placeable_others = {
            q.engine_id: [
                l for l in links
                if l is not q and l.alive and not l.draining
                and l.health != "quarantined"
            ]
            for q in links
            if q.alive and q.health == "quarantined" and not q.draining
        }
        for eid, others in placeable_others.items():
            if not others:
                with self._lock:
                    cooling = now < self._cooldown_until
                if not cooling:
                    self._scale_up(now)
                    return True
                continue  # cooling down: drain waits, jobs keep serving
            try:
                self._router.drain(eid)
            except Exception as exc:  # noqa: BLE001 — engine-scoped
                print(
                    f"a5gen: fleet: draining quarantined engine "
                    f"{eid} failed "
                    f"({type(exc).__name__}: {exc}); retrying "
                    "next tick",
                    file=sys.stderr,
                )
                continue
            with self._lock:
                if eid not in self._reaping:
                    self._reaping.append(eid)
        return False

    def _reap_pass(self) -> None:
        """Detach (shutdown + reap the process of) every drained
        engine whose routed set has emptied — migration is
        asynchronous, so reaping trails draining by however long the
        pause→checkpoint→resubmit round trips take."""
        with self._lock:
            reaping = list(self._reaping)
        for eid in reaping:
            try:
                link = self._router._resolve(eid)
            except Exception:  # noqa: BLE001 — already gone
                with self._lock:
                    if eid in self._reaping:
                        self._reaping.remove(eid)
                continue
            if link.routed and link.alive:
                continue  # still migrating off
            try:
                self._router.detach(eid, shutdown=True)
            except Exception as exc:  # noqa: BLE001 — engine-scoped
                print(
                    f"a5gen: fleet: reaping engine {eid} failed "
                    f"({type(exc).__name__}: {exc}); retrying next "
                    "tick",
                    file=sys.stderr,
                )
                continue
            with self._lock:
                if eid in self._reaping:
                    self._reaping.remove(eid)

    def _scale_up(self, now: float) -> None:
        """Spawn + attach one engine.  The ``engine.spawn`` seam
        (PERF.md §27) makes the failure path mechanically exercisable:
        a failed spawn is counted, logged, and retried after the
        cooldown — never raised out of the control loop.  The spawn +
        attach (seconds of jax import) run outside the state lock."""
        with self._lock:
            self._up_streak = 0
            self._cooldown_until = now + self.cfg.cooldown_s
        proc = None
        try:
            if faults_mod.ACTIVE is not None:
                faults_mod.ACTIVE.fire("engine.spawn")
            endpoint, engine_id, proc = self._spawner()
            self._router.attach(endpoint, engine_id, proc=proc)
        except Exception as exc:  # noqa: BLE001 — spawn is retryable
            telemetry.counter("fleet.spawn_failures").add(1)
            # A spawned-but-unattachable engine must not leak: every
            # cooldown retry would otherwise strand one more live
            # process holding the device and its socket.
            if proc is not None and hasattr(proc, "terminate"):
                try:
                    proc.terminate()
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — best-effort reap
                    try:
                        proc.kill()
                        proc.wait(timeout=5)
                    except Exception:  # noqa: BLE001
                        pass
            print(
                f"a5gen: fleet: engine spawn failed "
                f"({type(exc).__name__}: {exc}); retrying after "
                f"{self.cfg.cooldown_s:g}s cooldown",
                file=sys.stderr,
            )
            return
        telemetry.counter("fleet.scale_ups").add(1)
        print(
            f"a5gen: fleet: scaled UP — spawned engine {engine_id} "
            f"({len(self._router.engines())} attached)",
            file=sys.stderr,
        )

    def _scale_down(self, capacity: list, now: float) -> None:
        """Drain the idlest engine (fewest routed jobs; newest on
        ties, keeping the warm old engines) and queue it for reaping."""
        with self._lock:
            self._down_streak = 0
            self._cooldown_until = now + self.cfg.cooldown_s
        victim = min(
            capacity, key=lambda l: (len(l.routed), -l.index)
        )
        try:
            self._router.drain(victim.engine_id)
        except Exception as exc:  # noqa: BLE001 — engine-scoped
            print(
                f"a5gen: fleet: scale-down drain of "
                f"{victim.engine_id} failed "
                f"({type(exc).__name__}: {exc}); retrying next window",
                file=sys.stderr,
            )
            return
        with self._lock:
            if victim.engine_id not in self._reaping:
                self._reaping.append(victim.engine_id)
        telemetry.counter("fleet.scale_downs").add(1)
        print(
            f"a5gen: fleet: scaled DOWN — draining idle engine "
            f"{victim.engine_id} for reap",
            file=sys.stderr,
        )

"""Deterministic, seeded fault injection at the engine's real seams
(PERF.md §23).

Every bench round so far (r01–r05) died on accelerator-init flakiness,
and the fleet tier (ROADMAP item 1) assumes an engine that survives
device errors, wedged fetches, dead workers and process crashes — but
an untested recovery path is a second bug waiting behind the first.
This module makes every failure mode MECHANICALLY exercisable: a
:class:`FaultPlan` arms named injection points with fire-on-nth-call or
fire-with-probability-under-a-fixed-seed rules, and the production code
asks the plan to fire at each seam.

The hot-path contract: when nothing is armed, a seam costs ONE
module-attribute ``None`` check —

    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("superstep.dispatch")

graftaudit's ``audit_fault_hooks`` pins that shape (a bare always-on
``fire()`` in a drive loop's inner window is a finding), and the
``A5GEN_TELEMETRY``-style rule applies: injection must never change
what an unfaulted run emits.

Named injection points (one per recovery path — CONTRIBUTING requires
new failure paths to add theirs):

========================  ===================================================
``superstep.dispatch``    before each device dispatch (superstep drive AND
                          the per-launch pipeline) — transient device error
``superstep.fetch``       before the drive loop's consumed counters fetch —
                          transient fetch error / ``FetchTimeout``
``packed.pump``           inside ``FusedGroup.pump``'s dispatch fill loop
``admission.build``       inside the engine's admission build (worker thread)
``chunk.compile``         inside the streaming ring's worker compile
``checkpoint.write``      before a checkpoint write (crash-before-write)
``serve.client``          per JSONL op handled by a serve session
``device.init``           at launch-builder entry (accelerator-init flake)
``router.place``          in the fleet router's dispatch (placement +
                          submit-over-the-wire) — a failed placement
``link.send``             before each engine-link socket write — torn
                          engine connection mid-op
``engine.spawn``          in the autoscaler's scale-up (PERF.md §27) —
                          a failed engine spawn backs off to the next tick
========================  ===================================================

Arming: ``A5GEN_FAULTS=<spec>`` (read through ``runtime/env.py``),
``SweepConfig.faults``, or ``Engine(faults=...)``.  The spec grammar is
``point[:key=value,...][;point2:...]`` with keys

* ``nth=N``     fire on the Nth call to the point (1-based; default 1)
* ``p=X``       instead of ``nth``: fire each call with probability X
                under the plan's fixed ``seed`` (deterministic sequence)
* ``seed=N``    the plan-wide RNG seed (default 0)
* ``error=T``   exception type: ``FaultInjected`` (default, transient),
                ``FetchTimeout``, ``WorkerDeath`` (escapes ``except
                Exception`` — the worker-restart seam), ``OSError``
* ``persist``   keep firing on every triggering call (default one-shot)
* ``kill``      SIGKILL the process instead of raising (the crash-
                recovery soak test's deterministic boundary)
* ``delay=S``   sleep S seconds before acting (stall simulation)

Examples::

    A5GEN_FAULTS='superstep.dispatch:nth=2'
    A5GEN_FAULTS='superstep.fetch:error=FetchTimeout,p=0.2,seed=7'
    A5GEN_FAULTS='packed.pump:persist;admission.build:nth=1'
    A5GEN_FAULTS='superstep.fetch:kill,nth=3'

Deliberately dependency-free (stdlib only), like ``env.py`` and
``telemetry.py``: the eager ``runtime`` imports (checkpoint) pull this
in jax-free, and ``ops/`` modules may import it at module top level.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class FaultError(RuntimeError):
    """Base class of every injected (or watchdog-raised) fault."""


class FaultInjected(FaultError):
    """A deliberately injected transient-device-style error: the retry
    supervisors (PERF.md §23) treat it exactly like an
    ``XlaRuntimeError`` — bounded re-dispatch from the last fetched
    boundary."""


class FetchTimeout(FaultError):
    """A consumed device→host fetch exceeded the configured watchdog
    (``SweepConfig.fetch_timeout_s``).  Typed so the supervisor can
    treat a wedged fetch as transient (re-dispatch) instead of hanging
    the drive loop forever; also injectable by name."""


class WorkerDeath(BaseException):
    """An injected worker-thread death: derives from ``BaseException``
    so it escapes the job-scoped ``except Exception`` nets, exercising
    the restart-the-executor-once recovery in ``ChunkCompiler`` and the
    engine's admission worker."""


#: ``error=`` vocabulary of the fault spec.
ERROR_TYPES: Dict[str, type] = {
    "FaultInjected": FaultInjected,
    "FetchTimeout": FetchTimeout,
    "WorkerDeath": WorkerDeath,
    "OSError": OSError,
}

#: The named injection points.  A spec naming anything else fails
#: loudly at parse time — a typo must not silently disarm a fault.
POINTS = frozenset({
    "superstep.dispatch",
    "superstep.fetch",
    "packed.pump",
    "admission.build",
    "chunk.compile",
    "checkpoint.write",
    "serve.client",
    "device.init",
    "router.place",
    "link.send",
    "engine.spawn",
})


def is_transient(exc: BaseException) -> bool:
    """Whether the retry supervisors may recover from ``exc`` by
    re-dispatching from the last fetched boundary: injected transients,
    wedged-fetch timeouts, and the runtime's own device errors
    (``XlaRuntimeError`` — matched by name: this module is jax-free).
    Everything else (a ``ValueError`` from bad inputs, a parity
    failure) propagates immediately — retrying a deterministic bug
    just burns the attempt budget."""
    if isinstance(exc, (FaultInjected, FetchTimeout)):
        return True
    return type(exc).__name__ == "XlaRuntimeError"


def supervise_retry(exc: BaseException, attempts: int, *,
                    attempts_budget: int, backoff_s: float,
                    label: str) -> None:
    """The ONE retry-supervision policy (PERF.md §23), shared by the
    solo drive, the per-launch dispatch, and the packed pump: re-raise
    ``exc`` unless it is transient (:func:`is_transient`) with attempts
    remaining; otherwise count the retry, print the operator notice,
    and sleep the exponential backoff so the caller re-dispatches from
    its last fetched boundary.  Called from an ``except`` block — the
    bare ``raise`` re-raises the active exception with its original
    traceback."""
    if attempts >= int(attempts_budget) or not is_transient(exc):
        raise
    delay = float(backoff_s) * (2.0 ** attempts)
    from . import telemetry

    telemetry.counter("faults.retries").add(1)
    telemetry.counter("faults.backoff_s").add(delay)
    import sys
    import time

    print(
        f"a5gen: transient device error in {label} "
        f"({type(exc).__name__}: {exc}); retry "
        f"{attempts + 1}/{int(attempts_budget)} after {delay:.2f}s "
        "backoff from the last fetched boundary",
        file=sys.stderr,
    )
    time.sleep(delay)


def await_ready(value: object, timeout_s: "Optional[float]") -> None:
    """The fetch watchdog (PERF.md §23), shared by the solo drive and
    the packed pump: when ``timeout_s`` is set, poll the device
    result's readiness (``jax.Array.is_ready``) and raise a typed
    :class:`FetchTimeout` — transient to the supervisors — at the
    deadline, instead of letting a wedged device/tunnel block the
    drive (or the whole serve loop) forever in the fetch.  ``None``/0
    (the default) and values without a readiness probe (plain numpy)
    are no-ops — the caller's blocking fetch stands."""
    if not timeout_s:
        return
    is_ready = getattr(value, "is_ready", None)
    if is_ready is None:
        return
    import time

    deadline = time.monotonic() + float(timeout_s)
    while not is_ready():
        if time.monotonic() >= deadline:
            from . import telemetry

            telemetry.counter("faults.fetch_timeouts").add(1)
            raise FetchTimeout(
                f"device fetch still pending after "
                f"{float(timeout_s):.2f}s (the fetch_timeout_s watchdog)"
            )
        time.sleep(min(0.005, float(timeout_s) / 20.0))


class FaultRule:
    """One armed fault: a point, a trigger, and an action."""

    __slots__ = ("point", "nth", "p", "error", "persist", "kill",
                 "delay_s", "done")

    def __init__(self, point: str, *, nth: Optional[int] = None,
                 p: Optional[float] = None, error: str = "FaultInjected",
                 persist: bool = False, kill: bool = False,
                 delay_s: float = 0.0) -> None:
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} "
                f"(want one of {', '.join(sorted(POINTS))})"
            )
        if error not in ERROR_TYPES:
            raise ValueError(
                f"unknown fault error {error!r} "
                f"(want one of {', '.join(sorted(ERROR_TYPES))})"
            )
        if nth is not None and p is not None:
            raise ValueError("fault rule takes nth= OR p=, not both")
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError(f"fault probability must be in [0, 1], got {p}")
        self.point = point
        self.nth = int(nth) if nth is not None else (1 if p is None else None)
        self.p = p
        self.error = error
        self.persist = bool(persist)
        self.kill = bool(kill)
        self.delay_s = float(delay_s)
        self.done = False


class FaultPlan:
    """An armed set of :class:`FaultRule` s with per-point call
    counters and one seeded RNG — the whole plan is deterministic:
    same rules, same seed, same call sequence ⇒ same firing pattern.

    Thread-safe: the drive loops, the chunk worker, and the admission
    worker all fire concurrently."""

    def __init__(self, rules: "List[FaultRule]", seed: int = 0) -> None:
        import random

        self.rules = list(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: ``(point, call_number)`` log of every firing — the fault-
        #: matrix tests assert against this, never against timing.
        self.fired: List[Tuple[str, int]] = []

    def calls(self, point: str) -> int:
        """How many times ``point`` was reached (fired or not)."""
        with self._lock:
            return self._calls.get(point, 0)

    def fire(self, point: str) -> None:
        """One arrival at ``point``: count it, and raise (or kill) if a
        rule triggers.  Call sites MUST guard with ``faults.ACTIVE is
        not None`` — this method is never the production no-op path."""
        with self._lock:
            count = self._calls.get(point, 0) + 1
            self._calls[point] = count
            rule = None
            for r in self.rules:
                if r.point != point or r.done:
                    continue
                if r.nth is not None:
                    hit = count >= r.nth if r.persist else count == r.nth
                else:
                    hit = self._rng.random() < r.p
                if hit:
                    rule = r
                    if not r.persist:
                        r.done = True
                    break
            if rule is None:
                return
            self.fired.append((point, count))
        if rule.delay_s:
            import time

            time.sleep(rule.delay_s)
        if rule.kill:
            import os
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        raise ERROR_TYPES[rule.error](
            f"injected fault at {point} (call {count})"
        )


def parse_plan(spec: str) -> FaultPlan:
    """Parse the ``A5GEN_FAULTS`` grammar (module docstring) into a
    :class:`FaultPlan`.  Malformed specs raise ``ValueError`` loudly —
    a fault layer that silently disarms on a typo would certify
    recovery paths it never exercised."""
    rules: List[FaultRule] = []
    seed = 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, opts = part.partition(":")
        kw: Dict[str, object] = {}
        for opt in filter(None, (o.strip() for o in opts.split(","))):
            key, eq, val = opt.partition("=")
            if not eq:
                if key in ("persist", "kill"):
                    kw[key] = True
                    continue
                raise ValueError(
                    f"fault option {key!r} needs a value (or is not a "
                    "flag; flags: persist, kill)"
                )
            if key == "nth":
                kw["nth"] = int(val)
            elif key == "p":
                kw["p"] = float(val)
            elif key == "seed":
                seed = int(val)
            elif key == "error":
                kw["error"] = val
            elif key == "delay":
                kw["delay_s"] = float(val)
            else:
                raise ValueError(f"unknown fault option {key!r}")
        rules.append(FaultRule(point.strip(), **kw))  # type: ignore[arg-type]
    if not rules:
        raise ValueError(f"fault spec {spec!r} names no injection points")
    return FaultPlan(rules, seed=seed)


#: The process-wide armed plan; ``None`` (the production state) makes
#: every seam a single attribute-load + ``is not None`` check.
ACTIVE: Optional[FaultPlan] = None

#: The spec string the current ``ACTIVE`` was installed from by
#: :func:`ensure_env` (None = not env-installed — explicit installs own
#: the slot and env changes leave them alone).
_ENV_SPEC: Optional[str] = None


def install(plan: "FaultPlan | str | None") -> Optional[FaultPlan]:
    """Arm ``plan`` process-wide (a spec string is parsed first);
    ``None`` disarms.  Returns the installed plan.  Explicit installs
    take the slot from any env-armed plan."""
    global ACTIVE, _ENV_SPEC
    if isinstance(plan, str):
        plan = parse_plan(plan)
    ACTIVE = plan
    _ENV_SPEC = None
    return plan


def clear() -> None:
    """Disarm (tests' teardown)."""
    install(None)


def ensure_env() -> None:
    """Arm from ``A5GEN_FAULTS`` if set — called at ``Sweep`` and
    ``Engine`` construction (never at import: this module must stay
    eager-import-safe).  Re-reads the variable each call so tests can
    flip it between sweeps; an EXPLICITLY installed plan is never
    overridden, and clearing the variable disarms an env-armed plan."""
    global ACTIVE, _ENV_SPEC
    from .env import faults_spec

    spec = faults_spec()
    if spec == _ENV_SPEC:
        return
    if ACTIVE is not None and _ENV_SPEC is None:
        return  # explicit install wins over the environment
    ACTIVE = parse_plan(spec) if spec else None
    _ENV_SPEC = spec


class armed:
    """Context manager arming ``spec`` and restoring the previous plan
    on exit — the fault-matrix tests' idiom."""

    def __init__(self, spec: "FaultPlan | str | None") -> None:
        self._spec = spec
        self._prev: Optional[FaultPlan] = None
        self._prev_env: Optional[str] = None
        self.plan: Optional[FaultPlan] = None

    def __enter__(self) -> Optional[FaultPlan]:
        global ACTIVE, _ENV_SPEC
        self._prev, self._prev_env = ACTIVE, _ENV_SPEC
        self.plan = install(self._spec)
        return self.plan

    def __exit__(self, *exc: object) -> None:
        global ACTIVE, _ENV_SPEC
        ACTIVE, _ENV_SPEC = self._prev, self._prev_env

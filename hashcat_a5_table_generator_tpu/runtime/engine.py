"""Resident engine service mode (PERF.md §20, ROADMAP item 1).

The north-star workload is heavy traffic from many tenants, but a cold
CLI run pays the whole-program compile before its first candidate
(76.7 s in BENCH_r03).  This module keeps ONE process resident: the
:class:`Engine` owns the process-wide compiled-step cache
(``runtime.sweep._STEP_CACHE``), the on-disk PieceSchema cache, and a
job queue, and multiplexes many tenant sweeps through one drive loop.

The substrate is the machine protocol the sweep runtime exposes
(``Sweep.crack_machine`` / ``Sweep.candidates_machine``): each sweep is
an explicitly resumable generator that yields at every consumed fetch
boundary (a superstep, or a per-launch chunk drain) with its
:class:`CheckpointState` consistent.  The engine's scheduler groups
admitted jobs by static trace config — same-group jobs ride ONE
compiled superstep program (the step cache dedupes the build; N equal
small jobs cost one compile, not N) — and round-robins ``next()``
across the machines, so jobs interleave at superstep boundaries on one
device without ever co-mingling their (word, rank) cursors: per-job hit
attribution is the existing cursor bookkeeping, untouched.

Hits are delivered asynchronously per job: the once-per-superstep fetch
feeds a bounded per-job queue (:meth:`EngineJob.iter_hits`), so a
tenant streams its own hits while the engine keeps serving others.
Pause, resume, and cancel are tenant operations riding
:class:`CheckpointState`: pausing closes the job's machine at its last
fetched boundary and hands back the state object — a migrating job is
just that checkpoint submitted to another engine (same semantic inputs,
any geometry).  A solo job through the engine is byte-identical to
``run_crack``/``run_candidates`` by construction: the engine runs the
SAME generator those paths exhaust.

Front-ends: a Python API (``Engine.submit(...)``), and the ``a5gen
serve`` subcommand speaking JSONL over stdin/stdout or a unix socket
(:func:`serve_stdio` / :func:`serve_socket`) — one line per job
submission or control op, one line per event (hit/done/paused/...).

graftaudit pins the drive loop's discipline
(``tools.graftaudit.transfers.audit_serve_loop``): the serve round
advances each runnable job by exactly ONE boundary tick per round and
never fetches device data itself — the machines own every device→host
round trip, so the one-fetch-per-superstep contract (PERF.md §18)
survives interleaving.
"""

from __future__ import annotations

import copy
import itertools
import json
import queue
import threading
import time
from dataclasses import replace
from typing import (
    TYPE_CHECKING,
    Callable,
    Dict,
    Generator,
    IO,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from . import telemetry
from . import faults as faults_mod
from . import protocol
from .checkpoint import CheckpointState, state_from_doc, state_to_doc
from .sinks import CandidateWriter, HitRecord

if TYPE_CHECKING:
    import socket as _socket

    from ..models.attack import AttackSpec
    from .fuse import FusedGroup
    from .sweep import Sweep, SweepConfig, SweepResult


class JobCancelled(Exception):
    """Raised by :meth:`EngineJob.result` for a cancelled job."""


class JobFailed(Exception):
    """Raised by :meth:`EngineJob.result` for a failed job; ``__cause__``
    is the machine's exception."""


#: End-of-stream sentinel on a job's hit queue.
_HITS_END = object()


class _CtlEvent:
    """A control notification riding a job's async queue between hits
    (today: ``refused`` — the job's fused group was re-fused tighter
    after tenant departure, PERF.md §28).  Carries the event KIND plus
    the constructor kwargs; the serve pump builds the wire doc with the
    typed ``runtime.protocol`` constructor at the emit site (graftwire
    GW001), and the Python API's ``iter_hits`` filters these out — its
    contract stays hits-only."""

    __slots__ = ("kind", "fields")

    def __init__(self, kind: str, fields: dict) -> None:
        self.kind = kind
        self.fields = fields


class EngineJob:
    """One tenant sweep's handle: state, async hits, result, and the
    pause/resume/cancel controls.

    Lifecycle: ``queued`` → ``running`` → one of ``done`` / ``paused`` /
    ``cancelled`` / ``failed``.  All mutation happens on the engine's
    serve thread; the handle's events make the transitions waitable from
    tenant threads."""

    def __init__(self, job_id: str, kind: str, submit_args: dict,
                 hit_queue_depth: int) -> None:
        self.id = job_id
        self.kind = kind  # 'crack' | 'candidates'
        self.state = "queued"
        #: the pause/migrate handoff: a deep copy of the machine's
        #: CheckpointState, set when the job parks (and on done, for
        #: inspection).
        self.checkpoint: Optional[CheckpointState] = None
        self.result_value: "Optional[SweepResult]" = None
        self.error: Optional[BaseException] = None
        #: time-to-first-fetch relative to the machine's start (None
        #: until known) — the warm-vs-cold instrument --serve-ab reads.
        self.ttfc_s: Optional[float] = None
        #: the sweep's span-timeline digest (PERF.md §21), set when the
        #: job settles; the serve front-end attaches it to the
        #: ``done``/``paused`` event.
        self.span_summary: dict = {}
        self._submit_args = submit_args  # engine-side resume/migrate
        self._hits: "queue.Queue" = queue.Queue(maxsize=hit_queue_depth)
        self._settled = threading.Event()  # done/paused/cancelled/failed
        self._pause_req = threading.Event()
        self._cancel_req = threading.Event()

    # -- tenant surface ------------------------------------------------

    def iter_hits(self) -> "Iterator[HitRecord]":
        """Yield this job's :class:`HitRecord` s as they are fetched
        (bounded queue — a slow consumer backpressures the engine:
        while this job's queue is full, NO tenant advances, so crack
        jobs expecting more than ``hit_queue_depth`` hits must drain
        this iterator concurrently, or raise the depth).  Ends when the
        job settles; a paused job's stream ends too (the resumed job
        gets a fresh handle and re-plays checkpointed hits into it)."""
        for item in self._iter_records():
            if isinstance(item, _CtlEvent):
                continue
            yield item

    def _iter_records(self) -> "Iterator[Union[HitRecord, _CtlEvent]]":
        """``iter_hits`` plus the interleaved :class:`_CtlEvent`
        control notifications, in stream order — the serve front-end's
        pump consumes this to forward engine-side events (``refused``)
        to the wire; the Python API filters them out."""
        while True:
            try:
                item = self._hits.get(timeout=0.2)
            except queue.Empty:
                # Settled with an empty queue = end of stream (the
                # settle-side sentinel is best-effort only).
                if self._settled.is_set() and self._hits.empty():
                    return
                continue
            if item is _HITS_END:
                return
            yield item

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Wait until the job settles (done/paused/cancelled/failed)."""
        return self._settled.wait(timeout)

    def result(
        self, timeout: Optional[float] = None
    ) -> "Optional[SweepResult]":
        """Block for the job's :class:`SweepResult`.  Raises
        :class:`JobCancelled` / :class:`JobFailed` accordingly, and
        ``TimeoutError`` if the job has not settled in time (a PAUSED
        job never produces a result — resume it first)."""
        if not self._settled.wait(timeout):
            raise TimeoutError(f"job {self.id} still {self.state}")
        if self.state == "cancelled":
            raise JobCancelled(f"job {self.id} was cancelled")
        if self.state == "failed":
            raise JobFailed(f"job {self.id} failed") from self.error
        if self.state == "paused":
            raise JobFailed(
                f"job {self.id} is paused — resume it (Engine.resume or "
                "submit its checkpoint elsewhere) to get a result"
            )
        return self.result_value

    def request_pause(self) -> None:
        """Ask the engine to park this job at its next superstep
        boundary (non-blocking; see :meth:`pause`)."""
        self._pause_req.set()

    def pause(self, timeout: Optional[float] = None) -> CheckpointState:
        """Park the job at its next fetched boundary and return its
        CheckpointState — the migrate token another engine resumes
        from.  Pausing an already-settled job returns its final state's
        checkpoint if one exists."""
        self.request_pause()
        if not self._settled.wait(timeout):
            raise TimeoutError(f"job {self.id} did not park in time")
        if self.state == "paused":
            return self.checkpoint
        if self.state == "done":
            # Raced completion: the sweep finished before the park.
            return self.checkpoint
        raise JobFailed(
            f"job {self.id} settled as {self.state!r} before pausing"
        ) from self.error

    def cancel(self) -> None:
        """Ask the engine to drop this job at its next boundary
        (non-blocking; in-flight device work is abandoned — the
        machine's close runs the sweep's cleanup)."""
        self._cancel_req.set()

    # -- engine-side helpers (serve thread only) -----------------------

    def _push_hit(self, record: HitRecord) -> None:
        # Bounded backpressure, but never a deadlock the tenant cannot
        # break: a full queue blocks the serve thread (by contract)
        # UNTIL the consumer drains — or this job is cancelled/paused,
        # which drops further queue delivery (the hit already sits in
        # the machine's CheckpointState and the recorder's ordered
        # list, so cancel loses nothing the result reports and a
        # resumed job replays everything from its checkpoint).
        while not (self._cancel_req.is_set() or self._pause_req.is_set()):
            try:
                self._hits.put(record, timeout=0.2)
                return
            except queue.Full:
                continue

    def _push_ctl(self, kind: str, **fields: object) -> None:
        # Best-effort, never blocking: a control notification is
        # informational (stream correctness never depends on it), so a
        # full queue DROPS it rather than stalling the serve thread
        # outside the documented hit backpressure.
        try:
            self._hits.put_nowait(_CtlEvent(kind, fields))
        except queue.Full:
            pass

    def _settle(self, state: str) -> None:
        self.state = state
        self._settled.set()
        try:
            # Best-effort wakeup; iter_hits also terminates on the
            # settled flag, so a full queue cannot block settling.
            self._hits.put_nowait(_HITS_END)
        except queue.Full:
            pass


class _JobRecorder:
    """Hit recorder feeding a job's bounded async queue while keeping
    the ordered list the :class:`SweepResult` reports — the per-job
    delivery seam of the once-per-superstep fetch.

    ``mute``: how many leading emits to withhold from the ASYNC queue
    while still rebuilding the ordered list — a restarted/demoted
    machine (PERF.md §23) replays its checkpointed hits first, and the
    tenant already received exactly those on the same handle."""

    def __init__(self, job: EngineJob, mute: int = 0) -> None:
        self.hits: List[HitRecord] = []
        self._job = job
        self._mute = int(mute)

    def emit(self, record: HitRecord) -> None:
        self.hits.append(record)
        if self._mute > 0:
            self._mute -= 1
            return
        self._job._push_hit(record)


class _Slot:
    """One admitted job on the scheduler: its Sweep, its machine, its
    group (static-trace-config) key, and its affinity token (the
    fleet router's placement signal, ``runtime.fuse.affinity_token``)."""

    def __init__(self, job: EngineJob, sweep: "Sweep",
                 machine: "Generator[None, None, SweepResult]",
                 group: str, seq: int, token: str = "") -> None:
        self.job = job
        self.sweep = sweep
        self.machine = machine
        self.group = group
        self.seq = seq
        self.token = token
        #: engine-level machine restarts consumed (PERF.md §23): a
        #: transiently-failing machine is rebuilt from its own last
        #: boundary up to ``Engine(job_retries=)`` times before the job
        #: is quarantined.
        self.restarts = 0


class Engine:
    """The resident multi-tenant sweep engine (PERF.md §20).

    ``defaults`` seeds every job's :class:`SweepConfig` (a job's
    ``config=`` overrides it wholesale); sharing one geometry across
    jobs is what lets the step cache serve them all from one compiled
    program.  ``auto=True`` (default) runs the serve loop on a daemon
    thread; ``auto=False`` is the embedder's mode — call
    :meth:`run_until_idle` (or :meth:`_admit` + :meth:`_serve_round`)
    yourself, which is also how the tests make pause/cancel timing
    deterministic."""

    def __init__(self, defaults: "Optional[SweepConfig]" = None, *,
                 hit_queue_depth: int = 4096,
                 auto: bool = True, pack: Optional[bool] = None,
                 admission_worker: bool = True,
                 faults: "Optional[object]" = None,
                 job_retries: int = 1,
                 refuse_below: "Optional[float]" = None,
                 refuse_scope: "Optional[str]" = None) -> None:
        from ..ops.packing import schema_cache_stats
        from .sweep import SweepConfig, step_cache_stats

        # Fault arming (PERF.md §23): an explicit plan/spec wins;
        # otherwise A5GEN_FAULTS decides (unset = nothing armed).
        if faults is not None:
            faults_mod.install(faults)
        else:
            faults_mod.ensure_env()
        #: machine restarts granted per job before quarantine
        #: (PERF.md §23's degradation ladder).
        self._job_retries = int(job_retries)
        self.defaults = defaults if defaults is not None else SweepConfig()
        self._hit_queue_depth = int(hit_queue_depth)
        self._pending: "queue.Queue" = queue.Queue()
        self._active: List[_Slot] = []
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._shutdown = False
        self._counts = {
            "jobs_submitted": 0, "jobs_done": 0, "jobs_failed": 0,
            "jobs_cancelled": 0, "jobs_paused": 0, "supersteps_served": 0,
        }
        self._groups: Dict[str, int] = {}
        #: active slots per affinity token (runtime.fuse.affinity_token)
        #: — the resident-group surface the fleet router's placement
        #: reads through the stats op (PERF.md §25).
        self._resident: Dict[str, int] = {}
        #: cross-job physical packing (PERF.md §22): None = the
        #: A5GEN_PACK env hatch decides (on by default); False restores
        #: the PR 8 per-job dispatch path wholesale.
        self._pack = pack
        #: fused tenant groups currently dispatching (runtime.fuse).
        self._fused: List = []
        #: admission-time compile offload (PERF.md §22): plan/prescan/
        #: schema builds run on ONE bounded worker thread (generalizing
        #: the §19 ChunkCompiler pattern) instead of stalling the serve
        #: round — warm-job admission under load stops paying the build
        #: on the multiplexing thread.  None = build synchronously in
        #: ``_admit`` (the pre-§22 behavior).
        self._admit_ex = None
        if admission_worker:
            from concurrent.futures import ThreadPoolExecutor

            self._admit_ex = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="a5-engine-admit"
            )
        #: completed builds: (job, slot | None, exc | None) — the worker
        #: (or the sync path) produces, the serve thread consumes.
        self._built: "queue.Queue" = queue.Queue()
        self._building = 0  # builds in flight (under _lock)
        self._in_build: set = set()  # their jobs, for close(cancel=True)
        #: same-scheduler-key jobs drained from one submission burst are
        #: staged until ALL their builds land, then fused together —
        #: packing needs the whole batch's plans to concatenate.
        #: Mutated under ``_lock`` (``close(cancel=True)`` snapshots it
        #: from the caller thread).
        self._staging: Dict[str, dict] = {}
        self._cancel_all = False  # close(cancel=True) raced activations
        #: dynamic re-fuse (PERF.md §28): the fill threshold below
        #: which a fused group that LOST tenants is re-fused into a
        #: tighter group.  None = the A5GEN_REFUSE env hatch decides
        #: (0.5 by default); 0/0.0 disables re-fuse for this engine.
        self._refuse_below = refuse_below
        #: re-fuse merge scope (PERF.md §31): "cross" (default) lets a
        #: thin-group retrace harvest survivors from OTHER thin
        #: compatible groups too (the pack_candidate key proves safety
        #: in _prepare_fuse's bucketing); "within" pins the pre-§31
        #: one-group-only behavior.  None = A5GEN_REFUSE's within[:thr]
        #: spelling decides.
        if refuse_scope not in (None, "within", "cross"):
            raise ValueError(
                "refuse_scope must be None, 'within' or 'cross'"
            )
        self._refuse_scope_cfg = refuse_scope
        #: survivors detached from a thinned group, their re-fuse build
        #: in flight on the admission worker (under ``_lock``; counted
        #: in ``jobs_active`` — they are load, just not runnable yet).
        self._refusing: List[_Slot] = []
        #: packed-fill instruments (under ``_lock``): the last observed
        #: per-pump fill and the running minimum since engine start —
        #: the post-departure decay surface ``--pack-ab`` reads (the
        #: old fuse-time-only sampling hid masked-lane decay).
        self._fill_last: Optional[float] = None
        self._fill_min: Optional[float] = None
        self._step0 = step_cache_stats()
        self._schema0 = schema_cache_stats()
        self._packed0 = self._packed_counters()
        self._ladder0 = self._ladder_counters()
        self._thread: Optional[threading.Thread] = None
        if auto:
            self._thread = threading.Thread(
                target=self._serve_forever, name="a5-engine-serve",
                daemon=True,
            )
            self._thread.start()

    def _pack_on(self) -> bool:
        if self._pack is not None:
            return bool(self._pack)
        from .env import pack_enabled

        return pack_enabled()

    def _refuse_threshold(self) -> "Optional[float]":
        """The resolved re-fuse fill threshold (PERF.md §28): an
        explicit ``Engine(refuse_below=)`` wins (0/0.0 = disabled);
        otherwise the A5GEN_REFUSE env hatch decides."""
        if self._refuse_below is not None:
            return float(self._refuse_below) or None
        from .env import refuse_threshold

        return refuse_threshold()

    def _refuse_scope(self) -> str:
        """The resolved re-fuse merge scope (PERF.md §31): an explicit
        ``Engine(refuse_scope=)`` wins; otherwise A5GEN_REFUSE's
        ``within[:thr]`` spelling pins the within-group-only control
        and anything else means cross-group merging."""
        if self._refuse_scope_cfg is not None:
            return self._refuse_scope_cfg
        from .env import refuse_scope

        return refuse_scope()

    @staticmethod
    def _packed_counters() -> Dict[str, int]:
        return {
            k: int(telemetry.counter(f"engine.packed_{k}").value)
            for k in ("dispatches", "lanes_occupied", "lanes_total")
        }

    @staticmethod
    def _ladder_counters() -> Dict[str, int]:
        return {
            k: int(telemetry.counter(f"engine.{k}").value)
            for k in ("group_demotions", "job_restarts", "refuse_total",
                      "refuse_cross")
        }

    # -- tenant surface ------------------------------------------------

    def submit(
        self,
        spec: "AttackSpec",
        sub_map: Dict[bytes, List[bytes]],
        words: Sequence[bytes],
        digests: Sequence[bytes] = (),
        *,
        config: "Optional[SweepConfig]" = None,
        kind: str = "crack",
        writer: Optional[CandidateWriter] = None,
        resume_state: Optional[CheckpointState] = None,
        job_id: Optional[str] = None,
        mute: int = 0,
    ) -> EngineJob:
        """Queue one tenant sweep; returns its :class:`EngineJob`
        handle immediately.  ``kind='crack'`` needs ``digests`` and
        streams hits; ``kind='candidates'`` needs a ``writer``.
        ``resume_state`` is a paused job's CheckpointState (this
        engine's or another's) — the migrate handoff; its fingerprint
        must match the job's semantic inputs.  ``mute`` withholds the
        leading N hit emissions from the ASYNC delivery queue (the
        ``_JobRecorder(mute=)`` discipline, PERF.md §23/§25): a
        resumed machine replays its checkpointed hits first, and a
        fleet router that already forwarded N hits downstream passes
        ``mute=N`` so redelivery stays exactly-once — the ordered
        result list still rebuilds in full either way."""
        if kind not in ("crack", "candidates"):
            raise ValueError(f"kind must be 'crack' or 'candidates', "
                             f"got {kind!r}")
        if kind == "candidates" and writer is None:
            raise ValueError("candidates jobs need a writer=")
        if self._shutdown:
            raise RuntimeError("engine is shut down")
        if mute and kind != "crack":
            raise ValueError("mute= only applies to crack jobs (the "
                             "async hit-delivery queue)")
        job = EngineJob(
            job_id if job_id is not None else f"job-{next(self._ids)}",
            kind,
            dict(spec=spec, sub_map=sub_map, words=words, digests=digests,
                 config=config, writer=writer),
            self._hit_queue_depth,
        )
        job._resume_state = resume_state
        job._mute = max(0, int(mute))
        with self._lock:
            self._counts["jobs_submitted"] += 1
        telemetry.counter("engine.jobs_submitted").add(1)
        self._pending.put(job)
        self._wake.set()
        return job

    def resume(self, job: EngineJob) -> EngineJob:
        """Re-admit a PAUSED job from its checkpoint (same engine; for
        cross-engine migration call ``other.submit(..., resume_state=
        job.checkpoint)`` with the same semantic inputs).  Returns a
        fresh handle under the same job id."""
        if job.state != "paused" or job.checkpoint is None:
            raise ValueError(f"job {job.id} is {job.state}, not paused")
        a = job._submit_args
        return self.submit(
            a["spec"], a["sub_map"], a["words"], a["digests"],
            config=a["config"], kind=job.kind, writer=a["writer"],
            resume_state=job.checkpoint, job_id=job.id,
        )

    def stats(self) -> dict:
        """Engine observability: job counts, static-config groups, and
        the compile-amortization counters — compiled-program builds vs
        cache hits (process step cache) and on-disk schema-cache
        activity, both as deltas since this engine started."""
        from ..ops.packing import schema_cache_stats
        from .sweep import _stats_delta, step_cache_stats

        with self._lock:
            counts = dict(self._counts)
            groups = dict(self._groups)
            # Re-fusing survivors (PERF.md §28) are still this engine's
            # load — a router must not see a dip while a rebuild is in
            # flight — so both activity signals count them.
            active = len(self._active) + len(self._refusing)
            refusing = len(self._refusing)
            fill_last = self._fill_last
            fill_min = self._fill_min
            fused = len(self._fused)
            building = self._building
            staged = sum(
                len(stage["ready"]) for stage in self._staging.values()
            )
            resident = set(self._resident)
            for stage in self._staging.values():
                resident.update(
                    s.token for s in stage["ready"] if s.token
                )
        steps = _stats_delta(self._step0, step_cache_stats())
        packed = _stats_delta(self._packed0, self._packed_counters())
        ladder = _stats_delta(self._ladder0, self._ladder_counters())
        return {
            **counts,
            "jobs_active": active,
            # The fleet router's placement signals (PERF.md §25):
            # runnable (= active; the alias names the scheduling
            # state), staged (built, parked for burst peers), building
            # (admission worker), and the resident affinity tokens —
            # jobs whose token matches land here to maximize
            # fuse/compile reuse.
            "jobs_runnable": active,
            "jobs_staged": staged,
            "resident_groups": sorted(resident),
            # The engine's RESOLVED token-relevant defaults: a job doc
            # omitting a config field gets this value (``_job_from_doc``
            # replaces only supplied fields), so a router must fill
            # the same gaps with the same values or its doc tokens
            # never match the resident ones.
            "config_defaults": {
                "lanes": self.defaults.lanes,
                "blocks": self.defaults.num_blocks,
                "superstep": self.defaults.superstep,
                "devices": self.defaults.devices,
                "pair": self.defaults.pair,
            },
            "jobs_queued": self._pending.qsize(),
            "jobs_building": building,
            "groups": groups,
            "programs_compiled": steps.get("misses", 0),
            "program_cache_hits": steps.get("hits", 0),
            "schema_cache": _stats_delta(self._schema0,
                                         schema_cache_stats()),
            # The fleet health ladder's strain signals (PERF.md §27):
            # recovery-ladder activity since THIS engine started — a
            # router scraping rising deltas degrades (and eventually
            # quarantines) the engine instead of placing fresh tenants
            # onto failing hardware.
            "group_demotions": ladder.get("group_demotions", 0),
            "job_restarts": ladder.get("job_restarts", 0),
            # Cross-job packing (PERF.md §22): fused groups currently
            # dispatching, packed dispatches since engine start, and
            # the aggregate fill ratio (occupied / total lanes across
            # packed dispatches; 0 when none ran).
            "fused_groups": fused,
            "packed_dispatches": packed.get("dispatches", 0),
            "packed_fill": (
                packed.get("lanes_occupied", 0)
                / packed["lanes_total"]
                if packed.get("lanes_total") else 0.0
            ),
            # Dynamic re-fuse (PERF.md §28): retraces since engine
            # start, survivors mid-rebuild, and the per-pump fill
            # instruments — last observed and the running minimum —
            # which (unlike the aggregate above) expose POST-departure
            # masked-lane decay the moment it happens.
            "refuse_total": ladder.get("refuse_total", 0),
            # Cross-group merges (PERF.md §31): retraces that harvested
            # survivors from MORE than one thin group in one batch.
            "refuse_cross": ladder.get("refuse_cross", 0),
            "jobs_refusing": refusing,
            "packed_fill_last": (
                fill_last if fill_last is not None else 0.0
            ),
            "packed_fill_min": (
                fill_min if fill_min is not None else 0.0
            ),
        }

    def close(self, *, cancel: bool = False,
              timeout: Optional[float] = None) -> None:
        """Stop serving.  Default drains: queued and active jobs finish
        first; ``cancel=True`` drops them at the next boundary."""
        if cancel:
            with self._lock:
                # One snapshot closes the staging→active move gap: a
                # slot not yet in any list is caught by _cancel_all at
                # its activation.
                self._cancel_all = True
                # Re-fusing survivors cancel like active slots: they
                # reactivate when their rebuild lands and the flag then
                # retires them at their first round.
                slots = list(self._active) + list(self._refusing)
                building = list(self._in_build)
                # Staged-ready slots (built, parked for their burst
                # peers) must cancel too: they activate when their
                # batch releases, and the cancel flag then retires them
                # at their first round, before any machine tick.
                staged = [
                    s.job
                    for stage in self._staging.values()
                    for s in stage["ready"]
                ]
            for slot in slots:
                slot.job.cancel()
            for job in building + staged:
                # Builds in flight on the admission worker finish, then
                # settle cancelled at collection (the cancel-req check
                # in _finish_build).
                job.cancel()
            while True:
                try:
                    job = self._pending.get_nowait()
                except queue.Empty:
                    break
                # Never admitted: settle the handle here so waiters
                # unblock (the serve thread will not see this job).
                self._settle_counts(job, "cancelled")
        self._shutdown = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
        else:
            # Embedder mode has no serve thread to drain the jobs —
            # drain here so close() keeps its settle-everything
            # contract (cancelled slots retire on their next round).
            self.run_until_idle()
        # A submit that raced past the shutdown check may have enqueued
        # AFTER the serve loop exited; nothing will ever admit it —
        # settle the stragglers so no handle waits forever.
        while True:
            try:
                job = self._pending.get_nowait()
            except queue.Empty:
                break
            self._settle_counts(job, "cancelled")
        if self._admit_ex is not None:
            # The drain above consumed every completed build; stop the
            # worker (waits out any still-running build — its job was
            # settled through the cancel path or served by the drain).
            self._admit_ex.shutdown(wait=True)
            self._collect_builds()

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close(cancel=exc[0] is not None)

    # -- scheduler (serve thread) --------------------------------------

    def _serve_forever(self) -> None:
        while True:
            self._admit(wait=False)
            with self._lock:
                idle = not self._active
                building = self._building > 0
            if idle:
                if building:
                    self._wake.wait(0.05)
                    self._wake.clear()
                    continue
                if self._staging and self._flush_staging():
                    continue
                if self._shutdown and self._pending.empty():
                    return
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            self._serve_round()

    def run_until_idle(self) -> None:
        """Manual-mode drive: admit and serve until no job is active,
        building, or queued (embedders owning the loop; tests)."""
        while True:
            self._admit(wait=False)
            with self._lock:
                active = bool(self._active)
                building = self._building > 0
            if active:
                self._serve_round()
                continue
            if building:
                # Nothing to serve yet: block on the next completed
                # build instead of spinning (bounded wait — a worker
                # death would otherwise hang the embedder forever).
                try:
                    item = self._built.get(timeout=0.5)
                except queue.Empty:
                    continue
                self._consume_built(item)
                continue
            if not self._pending.empty():
                continue
            if self._staging and self._flush_staging():
                continue
            return

    def _flush_staging(self) -> bool:
        """Defensive drain: release any staged batches whose peers will
        never arrive (a failed bookkeeping path must degrade to solo
        admission, never to jobs stuck in staging)."""
        released = False
        with self._lock:
            stages = list(self._staging.values())
            self._staging.clear()
        for stage in stages:
            if stage["ready"]:
                self._fuse_and_activate(stage["ready"])
                released = True
        return released

    def _admit(self, wait: bool = True) -> None:
        """Drain the submission queue into scheduler slots.  Each job's
        Sweep build (plan + prescan + schema compile — host work) runs
        on the bounded admission worker (PERF.md §22) so the serve
        round keeps multiplexing the already-running tenants; completed
        builds are collected here, grouped by static trace config (so
        same-config jobs ride one compiled program and run adjacently),
        and — when packing is on — same-burst compatible jobs are fused
        into one packed dispatch group (``runtime.fuse``).

        ``wait=True`` (the manual embedder API's contract: after
        ``_admit()`` every submitted job IS a scheduler slot) blocks
        until the in-flight builds land; the serve loops pass False and
        collect completed builds opportunistically each round."""
        while True:
            try:
                job = self._pending.get_nowait()
            except queue.Empty:
                break
            self._intake(job)
        # The admission-build window IS the packing window: while the
        # worker is still building this burst, peers arriving a few
        # milliseconds apart (one JSONL line at a time through ``a5gen
        # serve``) join the same staging batch — at zero added latency,
        # since admission cannot outrun the build anyway.  The window
        # closes when the worker drains OR at a hard deadline (a client
        # submitting faster than builds complete must not extend it
        # forever — jobs still have to activate and serve), and never
        # opens at all while tenants are RUNNABLE: the serve round must
        # keep multiplexing them during a build (the whole point of the
        # admission offload), so a busy engine collects this burst over
        # its ordinary rounds instead of lingering here.
        with self._lock:
            serving = bool(self._active)
        if self._admit_ex is not None and self._pack_on() and not serving:
            deadline = time.monotonic() + 0.25
            while (
                self._building - self._built.qsize() > 0
                and time.monotonic() < deadline
            ):
                try:
                    job = self._pending.get(timeout=0.002)
                except queue.Empty:
                    continue
                self._intake(job)
        self._collect_builds()
        while wait:
            with self._lock:
                building = self._building > 0
            if not building:
                if self._staging:
                    self._flush_staging()
                return
            try:
                item = self._built.get(timeout=0.5)
            except queue.Empty:
                continue
            self._consume_built(item)

    def _intake(self, job: EngineJob) -> None:
        """One drained submission: honor a pre-admission cancel, stage
        crack jobs for packing, and hand the build to the worker (or
        build inline in sync-admission mode)."""
        if job._cancel_req.is_set():
            self._settle_counts(job, "cancelled")
            return
        if self._pack_on() and job.kind == "crack":
            skey = self._staging_key(job)
            with self._lock:
                stage = self._staging.setdefault(
                    skey, {"need": 0, "ready": []}
                )
                stage["need"] += 1
            job._staging_key = skey
        else:
            job._staging_key = None
        if self._admit_ex is None:
            self._built.put(("job",) + self._safe_build(job))
        else:
            with self._lock:
                self._building += 1
                self._in_build.add(job)
            self._admit_ex.submit(self._worker_build, job)

    def _staging_key(self, job: EngineJob) -> str:
        a = job._submit_args
        cfg = a["config"] if a["config"] is not None else self.defaults
        return f"{job.kind}|{self._group_key(a['spec'], cfg)}"

    def _try_build(
        self, job: EngineJob
    ) -> "Tuple[EngineJob, Optional[_Slot], Optional[BaseException]]":
        try:
            return job, self._build_slot(job), None
        except Exception as exc:  # noqa: BLE001 — job-scoped failure
            return job, None, exc

    def _safe_build(
        self, job: EngineJob
    ) -> "Tuple[EngineJob, Optional[_Slot], Optional[BaseException]]":
        """``_try_build`` with a worker-death net (PERF.md §23): a
        ``BaseException`` escaping the job-scoped ``except Exception``
        (the fault layer's ``WorkerDeath``, a dying thread) must not
        strand the build — it ships across the queue like any failure,
        where ``_finish_build`` applies the restart-once recovery.
        KeyboardInterrupt/SystemExit re-raise: in sync-admission mode
        this runs on the CALLER's thread, and a Ctrl-C must stay a
        Ctrl-C, never become a failed job."""
        try:
            return self._try_build(job)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:  # noqa: BLE001 — worker death
            return job, None, exc

    def _worker_build(self, job: EngineJob) -> None:
        self._built.put(("job",) + self._safe_build(job))
        self._wake.set()

    def _consume_built(self, item: tuple) -> None:
        """One completed admission-worker product: a job build
        (``("job", job, slot, exc)``) or an off-thread fuse build
        (``("fuse", result)`` — activation-on-completion)."""
        if item[0] == "fuse":
            with self._lock:
                self._building -= 1
            return self._finish_fuse(item[1])
        if item[0] == "fuse_death":
            # Worker-death recovery for an off-thread fuse build
            # (mirrors _finish_build's non-Exception branch): restart
            # the executor once, re-run the SAME batch on the fresh
            # worker; a second death settles the batch failed (the
            # retried flag in _worker_fuse).  ``_building`` stays
            # incremented — the resubmitted build's completion
            # decrements it through the ordinary "fuse" path.
            _slots, _exc = item[1], item[2]
            telemetry.counter("faults.worker_restarts").add(1)
            if self._admit_ex is not None:
                from concurrent.futures import ThreadPoolExecutor

                self._admit_ex.shutdown(wait=False)
                # graftrace: owner=collector -- exactly one thread
                # collects builds (the serve thread in auto mode, the
                # embedder in manual mode), so the executor restart is
                # single-writer by construction (PERF.md S23/S26).
                self._admit_ex = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="a5-engine-admit"
                )
                self._admit_ex.submit(self._worker_fuse, _slots, True)
                return
            with self._lock:  # pragma: no cover - sync mode never queues
                self._building -= 1
            return self._finish_fuse({
                "groups": [], "solo": [], "failed": [(list(_slots),
                                                      _exc)],
            })
        if item[0] == "refuse":
            with self._lock:
                self._building -= 1
            return self._finish_refuse(item[1])
        if item[0] == "refuse_death":
            # Same restart-once recovery as "fuse_death"; a second
            # death degrades every survivor to a SOLO rebuild from its
            # carried checkpoint (in _worker_refuse) — a re-fuse must
            # never fail a job.
            _entries, _exc = item[1], item[2]
            telemetry.counter("faults.worker_restarts").add(1)
            if self._admit_ex is not None:
                from concurrent.futures import ThreadPoolExecutor

                self._admit_ex.shutdown(wait=False)
                # graftrace: owner=collector -- exactly one thread
                # collects builds (the serve thread in auto mode, the
                # embedder in manual mode), so the executor restart is
                # single-writer by construction (PERF.md S23/S26).
                self._admit_ex = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="a5-engine-admit"
                )
                self._admit_ex.submit(self._worker_refuse, _entries,
                                      True)
                return
            with self._lock:  # pragma: no cover - sync mode never queues
                self._building -= 1
            return self._finish_refuse({
                "groups": [],
                "solo": [s for s, _st in _entries],
                "failed": [],
                "states": {id(s): st for s, st in _entries},
            })
        return self._finish_build(*item[1:])

    def _collect_builds(self) -> None:
        while True:
            try:
                item = self._built.get_nowait()
            except queue.Empty:
                return
            self._consume_built(item)

    def _finish_build(self, job: EngineJob, slot: "Optional[_Slot]",
                      exc: "Optional[BaseException]") -> None:
        """One completed admission build: settle failures (the worker's
        error propagation seam), honor cancels that raced the build,
        and either activate the slot solo or stage it until its
        submission burst's peers are all built, then fuse."""
        if self._admit_ex is not None:
            with self._lock:
                self._building -= 1
                self._in_build.discard(job)
        if (
            exc is not None
            and not isinstance(exc, Exception)
            and not getattr(job, "_build_retried", False)
        ):
            # Worker-death recovery (PERF.md §23): a BaseException-class
            # failure is the WORKER dying, not the job's inputs being
            # bad — restart the executor once and re-run this build on
            # the fresh worker before propagating.  A second death
            # falls through to the ordinary failed settle below.
            job._build_retried = True
            telemetry.counter("faults.worker_restarts").add(1)
            if self._admit_ex is not None:
                from concurrent.futures import ThreadPoolExecutor

                self._admit_ex.shutdown(wait=False)
                # graftrace: owner=collector -- exactly one thread
                # collects builds (the serve thread in auto mode, the
                # embedder in manual mode), so the executor restart is
                # single-writer by construction (PERF.md S23/S26).
                self._admit_ex = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="a5-engine-admit"
                )
                with self._lock:
                    self._building += 1
                    self._in_build.add(job)
                self._admit_ex.submit(self._worker_build, job)
                return
            return self._finish_build(*self._safe_build(job))
        skey = getattr(job, "_staging_key", None)
        with self._lock:
            stage = self._staging.get(skey) if skey is not None else None
        if exc is not None or job._cancel_req.is_set():
            if exc is not None:
                job.error = exc
                self._settle_counts(job, "failed")
            else:
                self._settle_counts(job, "cancelled")
            if stage is not None:
                with self._lock:
                    stage["need"] -= 1
                self._maybe_release(skey, stage)
            return
        if stage is None:
            self._activate(slot)
            return
        with self._lock:
            stage["ready"].append(slot)
        self._maybe_release(skey, stage)

    def _maybe_release(self, skey: str, stage: dict) -> None:
        with self._lock:
            if len(stage["ready"]) < stage["need"]:
                return
            self._staging.pop(skey, None)
        self._queue_fuse(stage["ready"])

    def _queue_fuse(self, slots: List["_Slot"]) -> None:
        """Off-thread fuse build (PERF.md §22 lever 4 / §24): the
        released batch's HEAVY half — ``pack_candidate`` probes, the
        packed digest re-sort, the plan-array concatenation and device
        upload inside ``build_fused_group`` — runs on the admission
        worker, with activation-on-completion via the built queue; the
        serve round keeps multiplexing running tenants instead of
        stalling behind a large digest list's group build.  Sync-
        admission mode (no worker) keeps the inline build."""
        if self._admit_ex is None:
            return self._fuse_and_activate(slots)
        with self._lock:
            self._building += 1
        telemetry.counter("engine.fuse_builds_offthread").add(1)
        self._admit_ex.submit(self._worker_fuse, slots)

    def _worker_fuse(self, slots: List["_Slot"],
                     retried: bool = False) -> None:
        try:
            res = self._prepare_fuse(slots)
        except (KeyboardInterrupt, SystemExit):  # pragma: no cover
            raise
        except BaseException as exc:  # noqa: BLE001 — worker death
            if isinstance(exc, Exception) or retried:
                # Batch-scoped failure (or a second death): settle the
                # members failed, exactly like a group-build error.
                res = {"groups": [], "solo": [],
                       "failed": [(list(slots), exc)]}
            else:
                # WorkerDeath-class (PERF.md §23): same restart-once +
                # re-run recovery the job-build path gets — ship the
                # death to the collector, which owns the executor.
                self._built.put(("fuse_death", slots, exc))
                self._wake.set()
                return
        self._built.put(("fuse", res))
        self._wake.set()

    def _queue_refuse(self, entries: "List[tuple]") -> None:
        """Off-thread re-fuse build (PERF.md §28): the survivors'
        ``pack_candidate`` probes and the tighter group's plan
        concatenation + device upload run on the admission worker —
        the ONE retrace a re-fuse pays stays off the serve thread (the
        §22/§24 discipline), which keeps multiplexing every other
        tenant meanwhile.  ``entries`` pairs each detached slot with
        the checkpoint captured at its detach boundary; sync-admission
        mode builds inline."""
        if self._admit_ex is None:
            slots = [s for s, _st in entries]
            states = {id(s): st for s, st in entries}
            return self._finish_refuse(
                self._prepare_fuse(slots, states=states)
            )
        with self._lock:
            self._building += 1
        telemetry.counter("engine.fuse_builds_offthread").add(1)
        self._admit_ex.submit(self._worker_refuse, entries)

    def _worker_refuse(self, entries: "List[tuple]",
                       retried: bool = False) -> None:
        slots = [s for s, _st in entries]
        states = {id(s): st for s, st in entries}
        try:
            res = self._prepare_fuse(slots, states=states)
        except (KeyboardInterrupt, SystemExit):  # pragma: no cover
            raise
        except BaseException as exc:  # noqa: BLE001 — worker death
            if isinstance(exc, Exception) or retried:
                # A re-fuse must never fail a job (packing is an
                # optimization): a batch-scoped error — or a second
                # worker death — degrades every survivor to a SOLO
                # rebuild from its carried checkpoint.
                res = {"groups": [], "solo": list(slots), "failed": [],
                       "states": states}
            else:
                self._built.put(("refuse_death", entries, exc))
                self._wake.set()
                return
        self._built.put(("refuse", res))
        self._wake.set()

    def _fuse_and_activate(self, slots: List["_Slot"]) -> None:
        self._finish_fuse(self._prepare_fuse(slots))

    def _prepare_fuse(self, slots: List["_Slot"],
                      states: "Optional[dict]" = None) -> dict:
        """Fuse a released staging batch (the heavy, thread-safe half —
        the slots are not yet active, so no other thread touches their
        sweeps): slots whose full packed keys match (and that are
        individually pack-eligible) form fused groups of the largest
        size ≥ 2 dividing the block count; the rest — unique keys,
        ineligible plans, leftover odd members — take the per-job
        dispatch path, exactly PR 8.  Packing is an optimization, so
        every failure here is contained: an eligibility-probe error
        demotes the job to solo dispatch, and a group-build error
        (schema I/O, device memory on the packed upload) fails ONLY the
        batch it was fusing — never the serve thread.

        ``states`` (a re-fuse build, PERF.md §28) overrides each slot's
        admission-time resume state with the checkpoint captured at its
        detach boundary — cursors are in rank-stride units, so they
        carry over into the tighter group unchanged; the dict rides the
        result so the collector rebuilds each machine from the SAME
        state the probe aligned on."""
        from .fuse import build_fused_group, pack_candidate

        out = {"groups": [], "solo": [], "failed": [],
               "states": dict(states) if states else {}}
        buckets: Dict[tuple, List[tuple]] = {}
        for slot in slots:
            resume = (
                states.get(id(slot)) if states is not None
                else slot.job._resume_state
            )
            try:
                cand = pack_candidate(slot.sweep, resume)
            except Exception:  # noqa: BLE001 — probe error = solo path
                cand = None
            if cand is None:
                out["solo"].append(slot)
            else:
                buckets.setdefault(cand["key"], []).append((slot, cand))
        for _key, members in buckets.items():
            while len(members) >= 2:
                nb = members[0][1]["sweep"].config.num_blocks
                take = len(members)
                while take >= 2 and nb % take:
                    take -= 1
                if take < 2:
                    break
                chosen, members = members[:take], members[take:]
                try:
                    group = build_fused_group([c for _s, c in chosen])
                except Exception as exc:  # noqa: BLE001 — batch-scoped
                    out["failed"].append(([s for s, _c in chosen], exc))
                    continue
                if group is None:
                    out["solo"].extend(s for s, _c in chosen)
                    continue
                out["groups"].append((group, [s for s, _c in chosen]))
            out["solo"].extend(s for s, _c in members)
        return out

    def _finish_fuse(self, res: dict) -> None:
        """Activation-on-completion: the light half of a fuse build,
        always on the collecting (serve/embedder) thread."""
        for group, slots in res["groups"]:
            for slot in slots:
                group.register(slot.sweep)
                self._activate(slot)
            with self._lock:
                self._fused.append(group)
        for slots, exc in res["failed"]:
            for slot in slots:
                slot.machine.close()
                slot.job.error = exc
                self._settle_counts(slot.job, "failed")
        for slot in res["solo"]:
            self._activate(slot)

    def _finish_refuse(self, res: dict) -> None:
        """Activation-on-completion for a re-fuse build (collector
        thread).  Survivors whose packed keys still match ride the new
        tighter group; the rest rebuild SOLO from their carried
        checkpoints — a re-fuse must never fail a job, so failed
        batches degrade to solo rebuilds too, and only a machine-
        rebuild error quarantines that one member."""
        states = res.get("states", {})
        solo = list(res["solo"])
        for slots, _exc in res["failed"]:
            solo.extend(slots)
        for group, slots in res["groups"]:
            fused_any = False
            for slot in slots:
                try:
                    self._machine_from_state(slot,
                                             states.get(id(slot)))
                except Exception as exc:  # noqa: BLE001 — member-scoped
                    # Park the member's segment in the NEW group (it
                    # was built expecting this sweep), then quarantine
                    # just this member.
                    group.leave(slot.sweep)
                    self._unrefuse(slot)
                    self._quarantine(slot, exc)
                    continue
                group.register(slot.sweep)
                self._reactivate(slot)
                fused_any = True
            if fused_any:
                with self._lock:
                    self._fused.append(group)
        for slot in solo:
            try:
                self._machine_from_state(slot, states.get(id(slot)))
            except Exception as exc:  # noqa: BLE001 — member-scoped
                self._unrefuse(slot)
                self._quarantine(slot, exc)
                continue
            self._reactivate(slot)

    def _reactivate(self, slot: "_Slot") -> None:
        """Return a re-fused survivor to the scheduler.  The slot never
        left the group/resident accounting (only ``_active``), so no
        counters move; a cancel/close that raced the rebuild retires it
        at its first round, before any machine tick."""
        if self._cancel_all:
            slot.job.cancel()
        with self._lock:
            if slot in self._refusing:
                self._refusing.remove(slot)
            self._active.append(slot)
            self._active.sort(key=lambda s: (s.group, s.seq))

    def _unrefuse(self, slot: "_Slot") -> None:
        with self._lock:
            if slot in self._refusing:
                self._refusing.remove(slot)

    def _activate(self, slot: "_Slot") -> None:
        if self._cancel_all:
            # close(cancel=True) raced this slot between its snapshots
            # and activation: honor the drop (the serve round retires
            # cancel-flagged slots before any machine tick).
            slot.job.cancel()
        slot.job.state = "running"
        with self._lock:
            self._active.append(slot)
            self._groups[slot.group] = self._groups.get(slot.group,
                                                        0) + 1
            if slot.token:
                self._resident[slot.token] = self._resident.get(
                    slot.token, 0
                ) + 1
            # Same-group jobs adjacent, groups in admission order:
            # warm programs serve their whole group back to back.
            self._active.sort(key=lambda s: (s.group, s.seq))

    def _build_slot(self, job: EngineJob) -> _Slot:
        from .sweep import Sweep

        # The admission-build seam (PERF.md §23): fires on the worker
        # thread (or inline in sync mode); an injected Exception is a
        # job-scoped build failure, an injected WorkerDeath exercises
        # the restart-the-executor-once recovery in _finish_build.
        if faults_mod.ACTIVE is not None:
            faults_mod.ACTIVE.fire("admission.build")
        from .fuse import affinity_token

        a = job._submit_args
        cfg = a["config"] if a["config"] is not None else self.defaults
        sweep = Sweep(a["spec"], a["sub_map"], a["words"], a["digests"],
                      config=cfg)
        if job.kind == "crack":
            recorder = _JobRecorder(job, mute=getattr(job, "_mute", 0))
            machine = sweep.crack_machine(
                recorder, resume=False, state=job._resume_state
            )
        else:
            machine = sweep.candidates_machine(
                a["writer"], resume=False, state=job._resume_state
            )
        return _Slot(job, sweep, machine, self._group_key(a["spec"], cfg),
                     next(self._ids), affinity_token(a["spec"], cfg))

    def _group_key(self, spec: "AttackSpec", cfg: "SweepConfig") -> str:
        """Static-trace-config grouping key: jobs agreeing here trace
        the same program shapes (the step cache's own keys add the
        plan-derived statics; this is the scheduler-visible prefix)."""
        return (
            f"{spec.mode}|{spec.algo}|{spec.min_substitute}"
            f"|{spec.max_substitute}|{cfg.lanes}|{cfg.num_blocks}"
            f"|{cfg.devices}|{cfg.superstep}"
        )

    def _serve_round(self) -> None:
        """One multiplexing round — the resident drive loop graftaudit
        pins (``audit_serve_loop``, PERF.md §20): every runnable job
        advances by exactly ONE fetched-boundary tick per round (one
        ``next()``), so tenants interleave at superstep granularity and
        no job monopolizes the device; the machines own every
        device→host fetch and the one-fetch-per-superstep discipline
        (PERF.md §18) — a fetch here would barrier every tenant behind
        one job's in-flight work.  Control (pause/cancel) is handled at
        the same boundaries, where each machine's CheckpointState is
        consistent by construction.

        Fused tenant groups (PERF.md §22) are pumped FIRST — exactly one
        packed dispatch+fetch per group per round (``runtime.fuse``,
        audited by ``audit_pack_round``) — so every packed member's tick
        below finds its split result already host-side; the member ticks
        themselves stay one-per-job, packed or not."""
        self._pump_groups()
        for slot in self._round_slots():
            if slot.job._cancel_req.is_set():
                self._retire(slot, "cancelled")
                continue
            if slot.job._pause_req.is_set():
                self._park(slot)
                continue
            try:
                next(slot.machine)
            except StopIteration as done:
                self._finish(slot, done.value)
            except Exception as exc:  # noqa: BLE001 — job-scoped failure
                self._recover_job(slot, exc)
            else:
                with self._lock:
                    self._counts["supersteps_served"] += 1
                telemetry.counter("engine.supersteps_served").add(1)

    def _round_slots(self) -> List[_Slot]:
        with self._lock:
            return list(self._active)

    def _pump_groups(self) -> None:
        """One packed dispatch round per fused group; drained groups
        retire (their members already left via the machines' drive
        finallys).  A pump error — after the group's own transient
        retries (PERF.md §23) — is GROUP-scoped and recoverable:
        packing is an optimization, so the members DEMOTE to solo
        machines resuming from their own last fetched boundaries
        instead of failing; every other tenant keeps serving
        untouched."""
        with self._lock:
            groups = list(self._fused)
        pumped = []
        for group in groups:
            try:
                group.pump()
            except Exception as exc:  # noqa: BLE001 — group-scoped
                self._demote_group(group, exc)
            else:
                pumped.append(group)
        # Fill notes run AFTER every group pumped: the cross-scope
        # re-fuse harvest (PERF.md §31) reads the SIBLING groups' fills,
        # and a trigger firing mid-round would see a cohabitant's stale
        # pre-departure fill and skip a thin group it should merge.
        for group in pumped:
            self._note_fill(group)
        for group in groups:
            if group.done:
                with self._lock:
                    if group in self._fused:
                        self._fused.remove(group)

    def _note_fill(self, group: "FusedGroup") -> None:
        """Post-pump fill instrumentation + the dynamic re-fuse trigger
        (PERF.md §28).  The gauges record on EVERY pump — not just at
        fuse time — so the ``--pack-ab`` fill report sees post-
        departure masked-lane decay; ``packed_fill_min`` carries the
        engine-tracked running minimum (``Gauge.set`` overwrites, so
        ``agg="min"`` only merges across engines).  The trigger: a
        group that lost tenants to DEPARTURE (cancel/pause — a member
        draining its range naturally is not churn, and retracing every
        group's tail would be a spurious rebuild) whose last dispatch
        fill dropped below the threshold re-fuses its survivors into a
        tighter group (one
        retrace; checkpoint cursors are in rank-stride units and carry
        over unchanged); a lone survivor rebuilds solo through the
        same path."""
        fill = group.last_fill
        if fill is None:
            return
        with self._lock:
            self._fill_last = fill
            if self._fill_min is None or fill < self._fill_min:
                self._fill_min = fill
            fill_min = self._fill_min
        telemetry.gauge("engine.packed_fill_last").set(fill)
        telemetry.gauge("engine.packed_fill_min",
                        agg="min").set(fill_min)
        thr = self._refuse_threshold()
        if (
            thr is not None
            and fill < thr
            and group.departures > 0
            and group.active_members > 0
            and group._work_remains()
        ):
            self._start_refuse(group, fill)

    def _start_refuse(self, group: "FusedGroup", fill: float) -> None:
        """Detach a thinned group's survivors at their last consumed
        boundaries (serve thread; each machine's close runs the packed
        drive's park finallys) and hand them to the admission worker
        to re-fuse into a tighter group.  Survivors sit in
        ``_refusing`` (not ``_active``) while the build runs — they
        keep their group/resident counts, so reactivation moves no
        counters.  Members with a pending pause/cancel stay behind:
        the round honors their request against the OLD group as
        usual.

        Cross-group scope (PERF.md §31): when the resolved scope is
        "cross", the batch also harvests survivors from OTHER thin
        post-churn groups (each gated by its own departure/fill
        trigger, so a healthy or naturally-tailing cohabitant group is
        never retraced).  Safety comes for free downstream:
        ``_prepare_fuse`` buckets the combined batch by its
        ``pack_candidate`` static key, so only provably-compatible
        survivors merge and the rest rebuild within their own
        buckets."""
        sources = [group]
        thr = self._refuse_threshold()
        if self._refuse_scope() == "cross" and thr is not None:
            with self._lock:
                others = [g for g in self._fused if g is not group]
            sources += [
                g for g in others
                if g.departures > 0
                and g.active_members > 0
                and g.last_fill is not None
                and g.last_fill < thr
                and g._work_remains()
            ]
        members = [
            slot for slot in self._round_slots()
            if getattr(slot.sweep, "_packed_source", None) in sources
            and not slot.job._cancel_req.is_set()
            and not slot.job._pause_req.is_set()
        ]
        if not members:
            return
        telemetry.counter("engine.refuse_total").add(1)
        if len(sources) > 1:
            telemetry.counter("engine.refuse_cross").add(1)
        entries = []
        for slot in members:
            sweep = slot.sweep
            # ttfc is a fact about the job's FIRST machine — capture
            # it before the rebuild resets the sweep's instrument (the
            # _rebuild_machine discipline, PERF.md §21/§23).
            if slot.job.ttfc_s is None and sweep._ttfc[0] is not None:
                slot.job.ttfc_s = sweep._ttfc[0] - sweep._run_t0
            slot.machine.close()
            src = getattr(sweep, "_packed_source", None)
            if src is not None:
                src.leave(sweep)
                sweep._packed_source = None
            entries.append((slot, self._checkpoint_of(slot)))
        with self._lock:
            for slot, _state in entries:
                if slot in self._active:
                    self._active.remove(slot)
                self._refusing.append(slot)
        for slot, _state in entries:
            slot.job._push_ctl("refused", jobs=len(entries), fill=fill)
        self._queue_refuse(entries)

    def _demote_group(
        self, group: "FusedGroup", exc: BaseException
    ) -> None:
        """The degradation ladder's packed rung (PERF.md §23): a fused
        group whose pump failed parks every member's segment and
        rebuilds each member as a SOLO machine from its own last
        consumed boundary — streams stay byte-exact (the checkpoint
        discipline replays exactly the unconsumed blocks), the group
        retires, and the jobs keep running on the per-job dispatch
        path."""
        import sys

        telemetry.counter("engine.group_demotions").add(1)
        members = [
            slot for slot in self._round_slots()
            if getattr(slot.sweep, "_packed_source", None) is group
        ]
        print(
            f"a5gen: engine: packed dispatch failed "
            f"({type(exc).__name__}: {exc}); demoting {len(members)} "
            "tenant(s) to solo dispatch",
            file=sys.stderr,
        )
        for slot in members:
            # A failed rebuild must stay JOB-scoped: quarantine that
            # member (checkpoint attached) and keep demoting the rest —
            # the serve thread never dies here.
            try:
                self._rebuild_machine(slot)
            except Exception as rebuild_exc:  # noqa: BLE001
                self._quarantine(slot, rebuild_exc)

    def _rebuild_machine(self, slot: _Slot) -> None:
        """Fresh machine on the same sweep from its last consumed
        boundary — the shared mechanics of demotion and transient
        restart (PERF.md §23).  Closing the old machine runs the
        drive's cleanup finallys (a packed segment parks); the rebuilt
        machine resumes from a deep copy of the live state, solo.
        Replayed checkpointed hits are muted on the job's async queue
        (the tenant already received them on this handle) while still
        rebuilding the recorder's ordered result list."""
        slot.machine.close()
        sweep = slot.sweep
        # The rebuilt machine resets the sweep's ttfc instrument; the
        # JOB's time-to-first-fetch is a fact about its first machine —
        # capture it now so the done event doesn't report a bogus
        # post-restart value (PERF.md §21's surface must stay honest
        # across §23's recoveries).
        if slot.job.ttfc_s is None and sweep._ttfc[0] is not None:
            slot.job.ttfc_s = sweep._ttfc[0] - sweep._run_t0
        src = getattr(sweep, "_packed_source", None)
        if src is not None:
            src.leave(sweep)
            sweep._packed_source = None
        self._machine_from_state(slot, self._checkpoint_of(slot))

    def _machine_from_state(self, slot: _Slot,
                            state: "Optional[CheckpointState]") -> None:
        """Fresh machine on the slot's sweep from ``state`` — the
        shared tail of demotion, transient restart, and re-fuse
        rebuilds.  Replayed checkpointed hits are muted on the job's
        async queue (the tenant already received them on this handle)
        while still rebuilding the recorder's ordered result list."""
        sweep = slot.sweep
        if state is None:
            state = self._checkpoint_of(slot)
        if slot.job.kind == "crack":
            recorder = _JobRecorder(slot.job, mute=len(state.hits))
            slot.machine = sweep.crack_machine(
                recorder, resume=False, state=state
            )
        else:
            slot.machine = sweep.candidates_machine(
                slot.job._submit_args["writer"], resume=False,
                state=state
            )

    def _recover_job(self, slot: _Slot, exc: BaseException) -> None:
        """The engine half of the degradation ladder (PERF.md §23): a
        machine that raised past the sweep's own retry supervision is
        RESTARTED from its last consumed boundary (transient errors
        only, ``Engine(job_retries=)`` times); past that the job is
        QUARANTINED — settled ``failed`` with its last checkpoint
        attached to the handle (and the serve front-end's ``failed``
        event), so a client can resubmit it to another engine instead
        of losing the sweep's progress."""
        if faults_mod.is_transient(exc) and slot.restarts < \
                self._job_retries:
            slot.restarts += 1
            telemetry.counter("engine.job_restarts").add(1)
            try:
                self._rebuild_machine(slot)
                return
            except Exception as rebuild_exc:  # noqa: BLE001
                exc = rebuild_exc  # fall through to quarantine
        self._quarantine(slot, exc)

    def _quarantine(self, slot: _Slot, exc: BaseException) -> None:
        self._drop(slot)
        slot.job.error = exc
        slot.job.checkpoint = self._checkpoint_of(slot)
        slot.job.span_summary = slot.sweep.timeline.summary()
        self._settle_counts(slot.job, "failed")

    def _drop(self, slot: _Slot) -> None:
        # A packed member must park its segment even when its machine
        # never started (close() on an unstarted generator skips the
        # drive's own leave-in-finally); leave is idempotent.
        src = getattr(slot.sweep, "_packed_source", None)
        if src is not None:
            src.leave(slot.sweep)
        with self._lock:
            if slot in self._active:
                self._active.remove(slot)
            self._groups[slot.group] -= 1
            if not self._groups[slot.group]:
                del self._groups[slot.group]
            if slot.token and slot.token in self._resident:
                self._resident[slot.token] -= 1
                if not self._resident[slot.token]:
                    del self._resident[slot.token]

    def _settle_counts(self, job: EngineJob, state: str) -> None:
        with self._lock:
            self._counts[f"jobs_{state}"] += 1
        telemetry.counter(f"engine.jobs_{state}").add(1)
        job._settle(state)

    def _checkpoint_of(self, slot: _Slot) -> CheckpointState:
        """A stable copy of the machine's live CheckpointState (the
        machine keeps mutating its own on resume elsewhere).  A job
        parked before its machine ever ticked has no active state yet —
        its checkpoint IS the start of the sweep (resume replays from
        the origin cursor), never None: the pause/migrate contract
        always hands back a resumable state."""
        state = slot.sweep.active_state
        if state is None:
            state = CheckpointState(fingerprint=slot.sweep.fingerprint)
        return copy.deepcopy(state)

    def _note_departure(self, slot: _Slot) -> None:
        # A tenant ACTION removed this member from its fused group —
        # the churn signal the re-fuse trigger requires (a member
        # finishing naturally never counts).
        src = getattr(slot.sweep, "_packed_source", None)
        if src is not None:
            src.departures += 1

    def _park(self, slot: _Slot) -> None:
        self._note_departure(slot)
        slot.machine.close()  # runs the sweep's cleanup finallys
        self._drop(slot)
        slot.job.checkpoint = self._checkpoint_of(slot)
        slot.job.span_summary = slot.sweep.timeline.summary()
        self._settle_counts(slot.job, "paused")

    def _retire(self, slot: _Slot, state: str) -> None:
        self._note_departure(slot)
        slot.machine.close()
        self._drop(slot)
        self._settle_counts(slot.job, state)

    def _finish(self, slot: _Slot, result: "SweepResult") -> None:
        self._drop(slot)
        job = slot.job
        job.result_value = result
        job.checkpoint = self._checkpoint_of(slot)
        # A restarted/demoted job's ttfc was captured at rebuild time
        # (the first machine's is the honest one); only fill it here
        # when no recovery pre-seeded it.
        if job.ttfc_s is None:
            ttfc = slot.sweep._ttfc[0]
            job.ttfc_s = (
                ttfc - slot.sweep._run_t0 if ttfc is not None else None
            )
        job.span_summary = slot.sweep.timeline.summary()
        self._settle_counts(job, "done")


# ---------------------------------------------------------------------------
# JSONL service front-end (``a5gen serve``)
# ---------------------------------------------------------------------------
#
# One request per line on stdin (or a unix-socket connection), one event
# per line out.  Ops:
#
#   {"op": "submit", "id": "j1", <job fields>}     -> accepted, hit*, done
#   {"op": "pause",  "id": "j1"}                   -> paused {checkpoint}
#   {"op": "resume", "id": "j1"}                   -> accepted (same id)
#   {"op": "cancel", "id": "j1"}                   -> cancelled
#   {"op": "stats"}                                -> stats
#   {"op": "metrics"}                              -> metrics (registry
#                                   JSON snapshot + Prometheus text)
#   {"op": "shutdown"}  (or EOF)                   -> bye
#
# Job fields: "tables": [paths] or "table_map": {key: [subs...]} inline;
# "dict": wordlist path or "words": [inline strings]; "digests": left-list
# path or "digest_list": [hex strings] (crack mode — omit both for a
# candidates job, which then needs "output": path); "algo", "mode"
# ("default"/"reverse"/"suball"/"suball-reverse"), "table_min"/"table_max";
# "config": SweepConfig subset {lanes, blocks, superstep, devices,
# fetch_chunk, stream_chunk_words, schema_cache, schema_cache_max_mb,
# pod: [index, count] — one rank-stride stripe of a split giant job};
# "checkpoint": a previously returned pause checkpoint (migrate-in);
# "replay_mute": N — withhold the leading N hit emissions from event
# delivery (the fleet router's exactly-once redelivery discipline; the
# job's done counts still report the full stream).


#: SweepConfig fields a JSONL job may override ("blocks" aliases
#: num_blocks to match the CLI flag).
_JOB_CONFIG_FIELDS = {
    "lanes": "lanes", "blocks": "num_blocks", "superstep": "superstep",
    "pair": "pair",
    "devices": "devices", "fetch_chunk": "fetch_chunk",
    "stream_chunk_words": "stream_chunk_words",
    "schema_cache": "schema_cache",
    "schema_cache_max_mb": "schema_cache_max_mb",
    # Robustness knobs (PERF.md §23): an on-disk checkpoint makes a
    # served job survive ENGINE death — restart the engine, read the
    # checkpoint file, resubmit with "checkpoint": <its doc> (the crash
    # soak test's whole loop); the retry knobs tune the drive's
    # transient-error supervision per job.
    "checkpoint_path": "checkpoint_path",
    "checkpoint_every_s": "checkpoint_every_s",
    "retry_attempts": "retry_attempts",
    "retry_backoff_s": "retry_backoff_s",
    "fetch_timeout_s": "fetch_timeout_s",
    # Pod giant-job striping over the wire (PERF.md §31): the fleet
    # router's split scatter drives the SweepConfig.pod cursor
    # arithmetic per shard — "pod": [index, count] scans only that
    # rank-stride stripe of the superstep block lattice, and the
    # shards' hit-stream union is exactly the solo stream.
    "pod": "pod",
}


def _job_from_doc(
    doc: dict, defaults: "SweepConfig", max_word_bytes: int
) -> dict:
    """Parse one submit document into ``Engine.submit`` arguments."""
    from ..models.attack import AttackSpec
    from ..tables.parser import load_tables

    if "table_map" in doc:
        sub_map = {
            k.encode("utf-8"): [v.encode("utf-8") for v in vals]
            for k, vals in doc["table_map"].items()
        }
    elif doc.get("tables"):
        sub_map = load_tables(doc["tables"])
    else:
        raise ValueError("job needs 'tables' (paths) or 'table_map'")
    if "words" in doc:
        words = [w.encode("utf-8") for w in doc["words"]]
    elif doc.get("dict"):
        from ..ops.packing import read_wordlist

        words = read_wordlist(doc["dict"], max_word_bytes=max_word_bytes)
    else:
        raise ValueError("job needs 'dict' (path) or 'words'")
    algo = doc.get("algo", "md5")
    crack = "digests" in doc or "digest_list" in doc
    if "digest_list" in doc:
        digests = [bytes.fromhex(h) for h in doc["digest_list"]]
    elif doc.get("digests"):
        # The CLI's left-list parser (vectorized, hashcat-style lines);
        # a layering exception the front-end owns, not the Engine.
        from ..cli import _read_digests

        digests = _read_digests(doc["digests"], algo)
    else:
        digests = ()
    mode = doc.get("mode", "default")
    if mode not in ("default", "reverse", "suball", "suball-reverse"):
        raise ValueError(f"unknown mode {mode!r}")
    spec = AttackSpec(
        mode=mode, algo=algo,
        min_substitute=int(doc.get("table_min", 0)),
        max_substitute=int(doc.get("table_max", 15)),
    )
    cfg = defaults
    overrides = doc.get("config") or {}
    unknown = set(overrides) - set(_JOB_CONFIG_FIELDS)
    if unknown:
        raise ValueError(f"unknown config field(s): {sorted(unknown)}")
    if overrides.get("pod") is not None:
        # JSON has no tuples; SweepConfig.pod wants (index, count).
        overrides = dict(overrides, pod=tuple(
            int(x) for x in overrides["pod"]
        ))
    if overrides:
        cfg = replace(cfg, **{
            _JOB_CONFIG_FIELDS[k]: v for k, v in overrides.items()
        })
    resume_state = (
        state_from_doc(doc["checkpoint"]) if doc.get("checkpoint") else None
    )
    # The fleet router's exactly-once redelivery knob (PERF.md §25):
    # the first N hit emissions skip the async queue — the client
    # already received exactly those through the router before a
    # migrate/crash-replay resubmission.
    mute = int(doc.get("replay_mute", 0))
    if mute < 0:
        raise ValueError(f"replay_mute must be >= 0, got {mute}")
    kind = "crack" if crack else "candidates"
    writer = None
    if kind == "candidates":
        if not doc.get("output"):
            raise ValueError(
                "candidates jobs (no digests) need 'output': a path the "
                "candidate stream is written to"
            )
        # A migrated-in job resumes FROM its checkpoint cursor — the
        # candidates before it were already written; truncating the
        # output would silently drop them, so resume appends.
        mode = "ab" if resume_state is not None else "wb"
        writer = CandidateWriter(open(doc["output"], mode))
    return dict(spec=spec, sub_map=sub_map, words=words, digests=digests,
                config=cfg, kind=kind, writer=writer,
                resume_state=resume_state, mute=mute)


class _JsonlSession:
    """One JSONL command stream against a shared :class:`Engine`.

    ``jobs``: the job registry — per-session by default (stdin mode);
    the socket server passes ONE dict shared by every connection, so a
    client dropped by the idle watchdog (or a crash) can reconnect and
    pause/cancel/resume its still-running jobs by id (PERF.md §23).
    Ops on an ADOPTED job (registered by another session) emit their
    settling event on THIS session — the original session's pump is
    gone with its socket."""

    def __init__(self, engine: Engine, fin: "IO[str]",
                 fout: "IO[str]", *,
                 max_word_bytes: int = 64 * 1024,
                 jobs: "Optional[Dict[str, EngineJob]]" = None) -> None:
        self._engine = engine
        self._fin = fin
        self._fout = fout
        self._out_lock = threading.Lock()
        self._max_word_bytes = max_word_bytes
        self._jobs: Dict[str, EngineJob] = (
            jobs if jobs is not None else {}
        )
        #: job ids THIS session started a pump thread for (their events
        #: flow there; adopted jobs' op results are emitted inline).
        self._pumped: set = set()
        #: activity stamps (bare clock reads, GL013-clean) the socket
        #: server's idle watchdog polls: a session is stale only when
        #: BOTH directions are — a client quietly waiting for hit/done
        #: events is not idle (PERF.md §23).
        self._last_read = time.monotonic()
        self._last_write = time.monotonic()

    def stale(self, timeout: float) -> bool:
        """No inbound line AND no outbound event for ``timeout``
        seconds — the idle watchdog's half-open test."""
        return (
            time.monotonic() - max(self._last_read, self._last_write)
            >= float(timeout)
        )

    def _emit(self, obj: dict) -> None:
        with self._out_lock:
            self._fout.write(json.dumps(obj) + "\n")
            self._fout.flush()
            # A completed write proves the peer is draining — the
            # watchdog must not drop a client that is merely waiting.
            self._last_write = time.monotonic()

    def _emit_settled(self, job: EngineJob) -> None:
        """The settling event for ``job``'s current terminal state."""
        if job.state == "done":
            res = job.result_value
            self._emit(protocol.ev_done(
                job.id,
                n_hits=res.n_hits, n_emitted=res.n_emitted,
                wall_s=res.wall_s, resumed=res.resumed,
                ttfc_s=job.ttfc_s,
                schema_cache=res.schema_cache,
                spans=job.span_summary,
            ))
        elif job.state == "paused":
            self._emit(protocol.ev_paused(
                job.id, state_to_doc(job.checkpoint),
                spans=job.span_summary,
            ))
        elif job.state == "cancelled":
            self._emit(protocol.ev_cancelled(job.id))
        else:
            # Quarantine (PERF.md §23): a failed job's last checkpoint
            # rides the event so the client can resubmit it to another
            # engine ("checkpoint" on a fresh submit) instead of losing
            # the sweep's progress.
            self._emit(protocol.ev_failed(
                job.id,
                f"{type(job.error).__name__}: {job.error}",
                checkpoint=(
                    state_to_doc(job.checkpoint)
                    if job.checkpoint is not None else None
                ),
            ))

    def _pump_job(self, job: EngineJob) -> None:
        """Per-job event pump (own thread): stream hits as they land,
        then the settling event.  A dead client (socket gone) must not
        wedge the ENGINE: the bounded hit queue backpressures the serve
        thread by contract, so once a write fails the pump keeps
        DRAINING the queue, discarding — the job runs on, adoptable by
        a reconnecting session (PERF.md §23)."""
        client_gone = False
        try:
            for rec in job._iter_records():
                if isinstance(rec, _CtlEvent):
                    # Engine-side control notifications forwarded in
                    # stream order; the typed constructor at the emit
                    # site keeps graftwire's registry authoritative.
                    if rec.kind == "refused":
                        self._emit(protocol.ev_refused(
                            job.id, **rec.fields
                        ))
                    continue
                self._emit(protocol.ev_hit(
                    job.id,
                    digest=rec.digest_hex,
                    plain_hex=rec.candidate.hex(),
                    word_index=rec.word_index,
                    rank=str(rec.variant_rank),
                ))
        except (OSError, ValueError):
            client_gone = True
            for _rec in job.iter_hits():
                pass
        # Terminal states release the candidates writer (flush + close);
        # a PAUSED job keeps it open — resume continues the stream.
        if job.state != "paused":
            writer = job._submit_args.get("writer")
            if writer is not None:
                writer.close()
        if not client_gone:
            try:
                self._emit_settled(job)
            except (OSError, ValueError):
                pass  # client vanished between the last hit and here

    def _handle(self, doc: dict) -> bool:
        """Dispatch one op; returns False on shutdown."""
        # The client-facing seam (PERF.md §23): an injected error here
        # is protocol-scoped — the session reports an ``error`` event
        # and keeps serving; the engine (and every other session) never
        # notices.
        if faults_mod.ACTIVE is not None:
            faults_mod.ACTIVE.fire("serve.client")
        op = protocol.doc_op(doc)
        jid = doc.get("id")
        if op == "shutdown":
            self._emit(protocol.ev_bye())
            return False
        if op == "stats":
            self._emit(protocol.ev_stats(self._engine.stats()))
            return True
        if op == "metrics":
            # The observability surface of a RUNNING engine (PERF.md
            # §21): the process-wide registry as a JSON snapshot plus
            # its Prometheus text exposition — a scrape adapter needs
            # only this op.
            snap = telemetry.snapshot()
            self._emit(protocol.ev_metrics(
                snap, telemetry.to_prometheus(snap)
            ))
            return True
        if op == "submit":
            kw = _job_from_doc(doc, self._engine.defaults,
                               self._max_word_bytes)
            try:
                job = self._engine.submit(job_id=jid, **kw)
            except BaseException:
                # No job (and no pump) exists to own the candidates
                # writer _job_from_doc opened — release it here.
                if kw.get("writer") is not None:
                    kw["writer"].close()
                raise
            self._jobs[job.id] = job
            self._pumped.add(job.id)
            self._emit(protocol.ev_accepted(job.id, job.kind))
            threading.Thread(
                target=self._pump_job, args=(job,),
                name=f"a5-serve-pump-{job.id}", daemon=True,
            ).start()
            return True
        job = self._jobs.get(jid)
        if job is None:
            raise ValueError(f"unknown job id {jid!r}")
        # An op on a job another (dropped) session submitted: that
        # session's pump died with its socket, so the settling event
        # must flow HERE (PERF.md §23).
        adopted = jid not in self._pumped
        if op == "pause":
            job.pause()  # blocks until parked (or raced done)
            if adopted:
                self._emit_settled(job)
        elif op == "resume":
            new = self._engine.resume(job)
            self._jobs[new.id] = new
            self._pumped.add(new.id)
            self._emit(protocol.ev_accepted(
                new.id, new.kind, resumed=True
            ))
            threading.Thread(
                target=self._pump_job, args=(new,),
                name=f"a5-serve-pump-{new.id}", daemon=True,
            ).start()
        elif op == "cancel":
            job.cancel()
            if adopted:
                job.wait()  # settles at the next boundary
                self._emit_settled(job)
        else:
            raise ValueError(f"unknown op {op!r}")
        return True

    def run(self) -> bool:
        """Process the stream; True when an explicit ``shutdown`` op
        ended it (a plain EOF — a disconnecting client — returns False,
        so a socket server keeps serving the other sessions).  A closed
        or torn connection — including the socket server's idle
        watchdog shutting down a stale one (PERF.md §23) — likewise
        ends only THIS session: the client's jobs keep running, and in
        socket mode the shared job registry lets a reconnecting
        session pause/cancel/resume them by id."""
        while True:
            try:
                line = self._fin.readline()
            except (OSError, ValueError):
                # Watchdog-closed or torn connection mid-read.
                return False
            if not line:
                return False  # EOF: client disconnected
            self._last_read = time.monotonic()
            line = line.strip()
            if not line:
                continue
            doc = None
            try:
                doc = json.loads(line)
                keep_going = self._handle(doc)
            except Exception as exc:  # noqa: BLE001 — protocol-scoped
                # Carry the failing op's job id when it named one: a
                # routing layer (PERF.md §25) demuxes events by id, so
                # an id-less error cannot be correlated to the op that
                # caused it.
                self._emit(protocol.ev_error(
                    f"{type(exc).__name__}: {exc}",
                    jid=doc.get("id") if isinstance(doc, dict)
                    else None,
                ))
                continue
            if not keep_going:
                return True


def serve_stdio(engine: Engine, fin: "IO[str]", fout: "IO[str]", *,
                max_word_bytes: int = 64 * 1024) -> None:
    """Serve one JSONL command stream (``a5gen serve`` over stdin)."""
    _JsonlSession(engine, fin, fout,
                  max_word_bytes=max_word_bytes).run()


def serve_socket(engine: Engine, path: str, *,
                 max_word_bytes: int = 64 * 1024,
                 client_timeout: Optional[float] = None,
                 ready: Optional[Callable[[], None]] = None) -> None:
    """Serve JSONL sessions over a unix socket at ``path`` (one session
    per connection, all sharing ``engine``); returns when a session
    sends an explicit ``shutdown`` op — a client that merely
    disconnects (EOF, a health probe) ends only its own session.

    ``client_timeout`` (``serve --client-timeout``, default off): a
    connection with no inbound line AND no outbound event for that
    many seconds is shut down by a per-connection watchdog thread — a
    half-open client cannot pin a server thread forever, while a
    client quietly waiting for results (events still flowing out) is
    never dropped, and no socket timeout ever lands mid-read or
    mid-write (PERF.md §23).  The dropped client's jobs keep running,
    and the job registry is shared across this server's sessions, so a
    reconnecting client pauses/cancels/resumes them by id via the
    existing ops."""
    import os
    import socket

    #: one registry for every connection — reconnection = adoption.
    shared_jobs: Dict[str, EngineJob] = {}

    def _watchdog(conn: "_socket.socket", session: "_JsonlSession",
                  done: threading.Event) -> None:
        interval = max(0.05, float(client_timeout) / 4.0)
        while not done.wait(interval):
            if session.stale(client_timeout):
                # Shutting the socket down unblocks the session's
                # readline (EOF/OSError) and fails any pump write —
                # the session winds down through its ordinary paths.
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return

    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    stop = threading.Event()
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        srv.bind(path)
        srv.listen()
        srv.settimeout(0.2)
        if ready is not None:
            ready()
        while not stop.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue

            def _session(conn: "_socket.socket" = conn) -> None:
                with conn:
                    fin = conn.makefile("r", encoding="utf-8")
                    fout = conn.makefile("w", encoding="utf-8")
                    session = _JsonlSession(
                        engine, fin, fout,
                        max_word_bytes=max_word_bytes,
                        jobs=shared_jobs,
                    )
                    done = threading.Event()
                    if client_timeout:
                        threading.Thread(
                            target=_watchdog,
                            args=(conn, session, done),
                            name="a5-serve-watchdog", daemon=True,
                        ).start()
                    try:
                        shutdown = session.run()
                    finally:
                        done.set()
                if shutdown:
                    stop.set()

            threading.Thread(
                target=_session, name="a5-serve-conn", daemon=True
            ).start()
    finally:
        srv.close()
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

"""Cross-job physical packing: fused superstep dispatch (PERF.md §22).

The resident engine (PERF.md §20) multiplexes tenants onto shared
compiled programs, but each job still gets its own superstep dispatches
— N small jobs pay N dispatch+fetch round trips per round, each with
mostly-masked lanes (a 40-block job in a 512-block launch wastes 92% of
the lane geometry the piece kernels were tuned for).  This module fuses
compatible runnable jobs' block ranges into ONE physical dispatch:

* the packed superstep program (``models.attack.make_superstep_body``
  with ``n_seg``) partitions every scan step's block axis into equal
  per-job segments, cuts each segment's blocks from its own job's
  region of the packed index (``ops.blocks.packed_block_index``), and
  accumulates PER-JOB counter rows in the scan carry — so the single
  per-superstep fetch returns each tenant's own emitted/hit counts;
* each lane's digest membership runs against its own job's target set
  (``ops.membership.digest_member_seg``) — never the union, so packed
  hit counts equal solo hit counts by construction;
* hits land in the shared capped buffer tagged by their packed plan
  row; the host maps rows back to jobs via the fuse bases and hands
  every job exactly the (word, rank) entries its solo sweep would have
  fetched.

The consume side stays in the job machines: :class:`FusedGroup` owns
dispatch and the one unconditional counters fetch per round
(``pump()``, audited by ``graftaudit audit_pack_round``), and each
member machine's ``Sweep._drive_packed`` pulls its own split result —
cursor bookkeeping, fallback interleave, hit re-derivation/
re-verification, checkpointing and the span timeline are the SAME code
the solo drive runs, so per-job streams, checkpoints and telemetry
attribution are byte-identical to solo runs.

Eligibility is deliberately strict — packing is an optimization with a
per-job-dispatch fallback, never a semantics change: jobs fuse only
when they agree on the full static trace config (spec, geometry,
superstep shape, out_width, windowed decision, plan-array trailing
shapes) and each is solo-superstep-eligible with a stride-aligned
cursor.  Streaming jobs, closed (cascade-closure) plans, and candidates
jobs always keep the per-job path.  The packed program keeps the SAME
kernel tier the members' solo sweeps would use — per-slot piece schema
(``pp_*``) AND the fused Pallas kernels' scalar-unit statics (``su_*``,
PERF.md §28) are batch-leading host tables that concatenate row-wise,
so a compatible group compiles to ONE fused kernel launch instead of
dropping to the XLA expansion tier; the emission scheme never changes
WHAT is emitted (PERF.md §17's parity contract), only per-lane
throughput.

Dynamic re-fuse (PERF.md §28): a departed tenant's segment parks as
masked lanes, so packed fill decays monotonically under churn.  The
group reports its per-round fill (``last_fill``); when it drops below
the engine's re-fuse threshold (``A5GEN_REFUSE``), the engine detaches
the survivors at their fetched boundaries and re-fuses them into a
tighter group off the serve thread — one retrace (a new ``n_seg`` is a
new step-cache key), checkpoint cursors carry over unchanged because
all cursor math already walks in rank-stride units.

``A5GEN_PACK=off`` (or ``Engine(pack=False)``) restores the PR 8
per-job dispatch path wholesale.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import faults, telemetry


def static_affinity_token(**fields) -> str:
    """Stable 12-hex token over named static-config fields — the
    hashable spelling of a compatibility key that survives the wire
    (JSON, stats events, router tables).  Field ORDER is canonical
    (sorted by name) and values stringify, so any process computing
    the token from the same facts gets the same string."""
    import hashlib

    blob = "|".join(f"{k}={fields[k]!r}" for k in sorted(fields))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def affinity_token(spec, cfg) -> str:
    """The static-trace-config prefix of :func:`pack_candidate`'s
    compatibility key as a stable hashable token (PERF.md §25).

    This is everything the packed program's trace depends on that is
    knowable WITHOUT building the job's plan — the scheduler-visible
    half of the full key (which further refines on plan-derived
    statics: trailing shapes, piece schema, radix2, pair
    eligibility).  Equal tokens are therefore necessary, not
    sufficient, for two jobs to fuse — exactly the right signal for
    PLACEMENT: a router co-locating equal-token jobs maximizes the
    chance the engine's step cache and fuse path find a match, and a
    token mismatch proves they never will.  The fleet router computes
    the same token from a submit document's doc-level fields
    (``runtime.fleet``); the engine reports its resident slots' tokens
    through the serve ``stats`` op."""
    return static_affinity_token(
        mode=spec.mode, algo=spec.algo,
        table_min=spec.min_substitute, table_max=spec.max_substitute,
        lanes=cfg.lanes, num_blocks=cfg.num_blocks,
        superstep=cfg.superstep, devices=cfg.devices, pair=cfg.pair,
    )


def pack_candidate(sweep, resume_state=None) -> "Optional[dict]":
    """One job's packed-dispatch eligibility probe: returns the fuse
    descriptor (plan, block index, aligned start cursor, and the static
    compatibility key), or None when the job must keep the per-job
    dispatch path (streaming, closed plan, superstep-ineligible,
    misaligned resume cursor, unresolved geometry).

    The compatibility key is everything the packed program's trace (and
    the concatenation of the jobs' plan arrays) depends on: two jobs
    with equal keys can share one packed program AND have their plan
    rows concatenated without padding.
    """
    from ..ops.blocks import superstep_index

    cfg = sweep.config
    if sweep._stream is not None or sweep.plan is None:
        return None
    if cfg.pod is not None:
        # Pod-striped giant jobs advance the block lattice per stripe;
        # the fused group's shared step has no stripe advance, so even
        # equal-pod tenants would replay each other's stripes — refuse
        # packing outright (graftknob GK003 pins this guard).
        return None
    plan = sweep.plan
    if getattr(plan, "close_next", None) is not None:
        # Closed plans carry their own per-plan value tables; merging
        # them would re-index the joint-closure rows — per-job dispatch
        # keeps them exact.
        return None
    steps = sweep._superstep_steps()
    if steps is None or cfg.num_blocks is None:
        return None
    try:
        stride = cfg.resolve_block_stride()
    except ValueError:
        return None
    if stride is None:
        return None
    idx = superstep_index(plan, stride)
    if idx is None:
        return None
    cum, totals, total_blocks = idx
    try:
        n_devices = sweep._resolve_devices()
    except Exception:  # noqa: BLE001 — device probe is env-dependent
        return None
    # The superstep accumulator cap (mirrors Sweep._superstep_static);
    # packed per-segment rows only ever see a SUBSET of these lanes.
    steps = max(1, min(
        steps, ((1 << 31) - 1) // max(1, cfg.lanes * n_devices)
    ))
    # Start cursor: normalize exactly as make_blocks does, then require
    # stride alignment (cross-geometry resumes keep the solo path).
    w, rank = 0, 0
    if resume_state is not None:
        w, rank = resume_state.cursor.word, resume_state.cursor.rank
    while w < plan.batch and (
        plan.fallback[w] or rank >= plan.n_variants[w]
    ):
        w, rank = w + 1, 0
    if w < plan.batch and rank % stride:
        return None
    b0 = total_blocks if w >= plan.batch else int(cum[w]) + rank // stride
    windowed = bool(getattr(plan, "windowed", False))
    # The per-slot piece schema (PERF.md §17), the radix-2 decode
    # collapse, and the fused Pallas kernel verdicts (PERF.md §28) are
    # plan-derived trace statics: compatible tenants must agree on them
    # (the common case — same dictionary shape × same table family
    # yields identical schema structure), and their data tables — the
    # ``pp_*`` piece tables AND the ``su_*`` scalar-unit fields — are
    # batch-leading, so the packed program keeps the SAME kernel tier
    # solo runs use, fused Pallas included.  Emission scheme and kernel
    # tier never change WHAT is emitted (the §17 parity contract).
    from ..models.attack import piece_host_tables, plan_array_keys
    from ..ops.packing import piece_schema_for
    from ..ops.pallas_expand import (
        k_opts_for,
        opts_for,
        scalar_units_for,
    )
    from .sweep import _pieces_static

    pieces = piece_schema_for(
        plan, sweep.ct, cache_dir=sweep._schema_cache_dir(),
        max_mb=sweep._schema_cache_max_mb(),
    )
    radix2 = k_opts_for(plan) == 1
    # Pair-lane tier (PERF.md §24): the ONE decision point is the
    # sweep's own gate, so packed and solo dispatches always agree.
    # Pair-eligibility joins the compatibility key below — a K=2 job
    # and a K=1 job trace different packed programs and never fuse.
    pair_k = sweep._pair_k(plan, pieces, stride)
    if pair_k is not None:
        idx2 = superstep_index(plan, stride * pair_k)
        aligned = w >= plan.batch or rank % (stride * pair_k) == 0
        if idx2 is None or not aligned:
            # int32 overflow at the doubled rank stride, or a resume
            # cursor aligned for K=1 only: this job packs as a K=1
            # tenant (its solo drive degrades the same way).
            pair_k = None
        else:
            idx = idx2
            cum, totals, total_blocks = idx
            b0 = total_blocks if w >= plan.batch else (
                int(cum[w]) + rank // (stride * pair_k)
            )
    rank_stride = stride * (pair_k or 1)
    # Re-apply the int32 accumulator cap with the pair multiplier: a
    # K=2 dispatch emits up to 2× the lanes per step, exactly as the
    # solo drive's cap accounts for (sweep._superstep_static).
    if pair_k is not None:
        steps = max(1, min(
            steps,
            ((1 << 31) - 1) // max(1, cfg.lanes * n_devices * pair_k),
        ))
    # Packed Pallas fast path (PERF.md §28): the fused expand→hash
    # kernel's verdicts, probed exactly as the solo sweep probes them
    # (Sweep._superstep_static).  Both join the compatibility key —
    # members agree on the kernel tier (and its option count) or they
    # never fuse — and the su_* statics join the signature tree so the
    # per-segment schema indirection concatenates like the plan rows.
    # Eligibility at the PACKED value width is witnessed: the packed
    # tables zero-pad narrow members' value rows to the widest member's
    # width, and that member's own gate passed at exactly that width
    # with every other gate input (out_width, windowed, trailing
    # shapes, k) pinned equal by this key.
    fused_opts = opts_for(
        sweep.spec, plan, sweep.ct,
        block_stride=stride, num_blocks=cfg.num_blocks,
    )
    scalar_units = (
        scalar_units_for(plan) if fused_opts is not None else False
    )
    # Trailing-shape signature of the plan + piece arrays: equal
    # signatures concatenate row-wise with no padding, so the packed
    # arrays are byte-wise each job's solo arrays stacked.  Host-array
    # views only — signing an admission must not upload/download the
    # plan through device buffers.
    tree = {k: getattr(plan, k) for k in plan_array_keys(plan)}
    tree.update(piece_host_tables(pieces))
    if fused_opts is not None and scalar_units:
        from ..models.attack import scalar_units_host_tables

        tree.update(scalar_units_host_tables(plan, sweep.ct))
    sig = tuple(
        (k, tuple(v.shape[1:]), str(v.dtype))
        for k, v in sorted(tree.items())
    )
    key = (
        sweep.spec, cfg.lanes, cfg.num_blocks, stride, steps,
        int(cfg.superstep_hit_cap), plan.out_width, windowed, n_devices,
        sweep._pipeline_depth(), sig, _pieces_static(pieces), radix2,
        pair_k, fused_opts, scalar_units,
        # Fault-supervision knobs (PERF.md §23): the group runs ONE
        # retry policy and ONE fetch watchdog for every member, so
        # jobs that disagree on them must not fuse — a fail-fast
        # tenant must never inherit a cohabitant's retry budget.
        int(cfg.retry_attempts), float(cfg.retry_backoff_s),
        cfg.fetch_timeout_s,
    )
    return {
        "sweep": sweep,
        "plan": plan,
        "idx": idx,
        "cum": cum,
        "totals": totals,
        "total_blocks": total_blocks,
        "b0": b0,
        "steps": steps,
        # Cursor math walks in RANK stride units (pair_k × the lane
        # stride); the kernel geometry keeps the lane stride.
        "stride": rank_stride,
        "lane_stride": stride,
        "pair_k": pair_k,
        "n_devices": n_devices,
        "pieces": pieces,
        "radix2": radix2,
        "fused_opts": fused_opts,
        "scalar_units": scalar_units,
        "key": key,
    }


def _packed_digest_arrays(members: Sequence[dict]):
    """Concatenate the members' target digest sets into the segmented
    membership tree: per-segment sorted row runs + stacked bitmaps at a
    common width (the widest member's default sizing — the bitmap is a
    prefilter ANDed with the exact search, so width changes throughput,
    never results) + per-segment row bounds."""
    from ..ops.membership import auto_bitmap_bits, build_digest_set

    def _count(digests) -> int:
        if isinstance(digests, np.ndarray):
            return int(digests.shape[0])
        return len(digests)

    bits = max(
        auto_bitmap_bits(_count(m["sweep"].digests)) for m in members
    )
    sets = [
        build_digest_set(
            m["sweep"].digests, m["sweep"].spec.algo, bitmap_bits=bits
        )
        for m in members
    ]
    rows = np.concatenate([ds.rows for ds in sets])
    bitmap = np.stack([ds.bitmap for ds in sets])
    bounds = np.zeros(len(sets) + 1, dtype=np.int64)
    for j, ds in enumerate(sets):
        bounds[j + 1] = bounds[j] + ds.rows.shape[0]
    return {
        "rows": rows,
        "bitmap": bitmap,
        "row_lo": bounds[:-1].astype(np.int32),
        "row_hi": bounds[1:].astype(np.int32),
    }


def _packed_plan_tree(members: Sequence[dict]):
    """Concatenate the members' plan arrays row-wise into the packed
    plan/table trees.  The jobs' value tables (each tenant brings its
    own substitution table) concatenate too, with every per-word
    value-row pointer (``match_val_start`` / ``pat_val_start``) shifted
    by its job's value-table base — the one place the packed arrays are
    not a plain stack of the solo ones.  Value tables may differ in
    byte width; rows are read under ``val_len`` masks, so zero-padding
    the narrow ones to the common width is unobservable."""
    from ..models.attack import piece_host_tables, plan_array_keys

    trees = []
    for m in members:
        plan = m["plan"]
        tree = {
            k: np.asarray(getattr(plan, k))
            for k in plan_array_keys(plan)
        }
        # The per-slot piece tables ride the plan dict (``pp_*``)
        # exactly as in the solo builders — all batch-leading.
        tree.update({
            k: np.asarray(v)
            for k, v in piece_host_tables(m["pieces"]).items()
        })
        # The fused Pallas kernel's scalar-unit statics (``su_*``,
        # PERF.md §28) concatenate the same way — batch-leading rows
        # whose value fields pack the value WORDS inline (never table
        # indices), so no base shifting applies to them below.
        if m["fused_opts"] is not None and m["scalar_units"]:
            from ..models.attack import scalar_units_host_tables

            tree.update(scalar_units_host_tables(plan, m["sweep"].ct))
        trees.append(tree)
    vb = [np.asarray(m["sweep"].ct.val_bytes) for m in members]
    vl = [np.asarray(m["sweep"].ct.val_len) for m in members]
    vw = max(b.shape[1] for b in vb)
    vb = [
        np.pad(b, ((0, 0), (0, vw - b.shape[1]))) if b.shape[1] < vw
        else b
        for b in vb
    ]
    val_base = 0
    off_key = "match_val_start" if "match_val_start" in trees[0] \
        else "pat_val_start"
    for j, tree in enumerate(trees):
        tree[off_key] = tree[off_key] + np.int32(val_base)
        val_base += vb[j].shape[0]
    plan_tree = {
        k: np.concatenate([t[k] for t in trees]) for k in trees[0]
    }
    table_tree = {
        "val_bytes": np.concatenate(vb),
        "val_len": np.concatenate(vl),
    }
    return plan_tree, table_tree


def build_fused_group(members: Sequence[dict]) -> "Optional[FusedGroup]":
    """Build one :class:`FusedGroup` from ≥2 :func:`pack_candidate`
    descriptors sharing one compatibility key (the engine's job), or
    None when the packed index would overflow int32 — callers then keep
    per-job dispatch."""
    from ..models.attack import packed_superstep_arrays

    cfg = members[0]["sweep"].config
    n_seg = len(members)
    if n_seg < 2 or cfg.num_blocks % n_seg:
        return None
    packed = packed_superstep_arrays(
        [m["plan"] for m in members], [m["idx"] for m in members]
    )
    if packed is None:
        return None
    ss_host, blk_base, row_base = packed
    steps = members[0]["steps"]
    n_devices = members[0]["n_devices"]
    # The tail dispatch's overshot per-segment cursors must stay int32
    # (mirrors Sweep._superstep_static's headroom check).
    if (
        int(blk_base[-1]) + (steps + 1) * cfg.num_blocks * n_devices
        >= (1 << 31)
    ):
        return None
    return FusedGroup(members, ss_host, blk_base, row_base)


class FusedGroup:
    """One fused tenant group: the packed program, its device arrays,
    the per-segment block cursors, and the dispatch/fetch/split loop
    the engine pumps once per serve round.

    The drive contract (graftaudit ``audit_pack_round``): ``pump()``
    dispatches at most ``depth`` packed supersteps ahead through the
    ONE dispatch site (``self._call``), consumes exactly ONE
    unconditional counters fetch per round, fetches the hit slice only
    on hit-bearing supersteps, and never dispatches or fetches inside
    the per-member split loop — per-member work there is pure host
    bookkeeping over the already-materialized arrays.

    A member that pauses, cancels, fails or finishes simply parks its
    segment at its end bound (all its future blocks cut zero-count
    masked lanes) — cohabitants are untouched, no retrace happens, and
    the group retires when every member has left.
    """

    def __init__(self, members: Sequence[dict], ss_host, blk_base,
                 row_base) -> None:
        import jax.numpy as jnp

        from ..models.attack import (
            make_superstep_step,
            superstep_buffers,
        )

        m0 = members[0]
        sweep0 = m0["sweep"]
        spec, cfg = sweep0.spec, sweep0.config
        self.n_seg = len(members)
        self.steps = m0["steps"]
        self.stride = m0["stride"]  # RANK stride (pair_k × lane stride)
        self.pair_k = m0["pair_k"] or 0
        self._hit_cap = int(cfg.superstep_hit_cap)
        self._n_devices = m0["n_devices"]
        self._num_blocks = cfg.num_blocks
        self._lanes = cfg.lanes
        self._nbs = cfg.num_blocks // self.n_seg
        self._blk_base = blk_base
        self._row_base = row_base
        self._members = list(members)
        self._by_sweep: Dict[int, int] = {
            id(m["sweep"]): j for j, m in enumerate(members)
        }
        self._active = [True] * self.n_seg
        self._pending: List[deque] = [deque() for _ in members]
        # Packed-global per-segment cursors; consumed tracks the fetched
        # (lagged) boundary per segment for the drained/ticked guard.
        self._b0 = np.asarray(
            [int(blk_base[j]) + m["b0"] for j, m in enumerate(members)],
            dtype=np.int64,
        )
        self._seg_end = blk_base[1:].astype(np.int64).copy()
        self._consumed = self._b0.copy()
        self._adv = self.steps * self._nbs * self._n_devices
        #: in-flight packed-superstep budget (the members' shared
        #: pipeline depth; surfaced for the drive's stats parity with
        #: the solo "pipelined" flag).
        self.depth = sweep0._pipeline_depth()
        self._inflight: deque = deque()
        self.dispatches = 0
        #: the last consumed round's fill ratio (occupied variant lanes
        #: over the dispatch's lane geometry) — the engine's re-fuse
        #: trigger and the post-departure fill instrument (PERF.md §28)
        #: read this instead of re-deriving it from the counters.
        self.last_fill: Optional[float] = None
        #: members that left by tenant action (cancel/pause — the
        #: engine bumps this at retire/park).  The re-fuse trigger
        #: requires a DEPARTURE: a member draining its range naturally
        #: also thins the group, but retracing a natural tail would
        #: charge every group a spurious rebuild at its end.
        self.departures = 0

        plan_tree, table_tree = _packed_plan_tree(members)
        dig_tree = _packed_digest_arrays(members)
        windowed = bool(getattr(m0["plan"], "windowed", False))
        from .sweep import _pieces_static

        common = dict(
            num_lanes=cfg.lanes, out_width=m0["plan"].out_width,
            block_stride=m0["lane_stride"], num_blocks=cfg.num_blocks,
            steps=self.steps, hit_cap=self._hit_cap,
            total_blocks=int(blk_base[-1]), windowed=windowed,
            n_seg=self.n_seg, pieces=m0["pieces"], radix2=m0["radix2"],
            pair_k=m0["pair_k"],
            # The fused Pallas verdicts (PERF.md §28) — part of the
            # compatibility key, so every member agreed at fuse time;
            # the packed plan tree carries the concatenated su_* rows
            # the kernel's scalar-unit prelude gathers per block.
            fused_expand_opts=m0["fused_opts"],
            fused_scalar_units=m0["scalar_units"],
        )
        skey = ("packed-superstep", spec, self.n_seg, self._n_devices,
                cfg.lanes, cfg.num_blocks, m0["plan"].out_width,
                self.stride, self.steps, self._hit_cap, windowed,
                _pieces_static(m0["pieces"]), m0["radix2"],
                m0["pair_k"], m0["fused_opts"], m0["scalar_units"])
        if self._n_devices == 1:
            self._p = {k: jnp.asarray(v) for k, v in plan_tree.items()}
            self._t = {k: jnp.asarray(v) for k, v in table_tree.items()}
            self._d = {k: jnp.asarray(v) for k, v in dig_tree.items()}
            self._ss = {k: jnp.asarray(v) for k, v in ss_host.items()}
            step = sweep0._get_step(skey, lambda: make_superstep_step(
                spec, **common,
            ))

            def call(b0_rows, bufs):
                return step(
                    self._p, self._t, self._d, self._ss,
                    jnp.asarray(b0_rows.astype(np.int32)), bufs,
                )

            def make_bufs():
                return superstep_buffers(self._hit_cap)
        else:
            from ..parallel.mesh import (
                make_sharded_superstep_step,
                replicate,
                shard_leading,
            )

            mesh = sweep0._get_mesh(self._n_devices)
            skey = skey + tuple(int(d.id) for d in mesh.devices.flat)
            step = sweep0._get_step(
                skey, lambda: make_sharded_superstep_step(
                    spec, mesh, lanes_per_device=cfg.lanes, **{
                        k: v for k, v in common.items()
                        if k != "num_lanes"
                    },
                )
            )
            self._p = replicate(mesh, plan_tree)
            self._t = replicate(mesh, table_tree)
            self._d = replicate(mesh, dig_tree)
            self._ss = replicate(mesh, ss_host)
            nbs, nd, cap = self._nbs, self._n_devices, self._hit_cap

            def call(b0_rows, bufs):
                b0_dev = shard_leading(mesh, np.stack([
                    (b0_rows + d * nbs).astype(np.int32)
                    for d in range(nd)
                ]))
                return step(self._p, self._t, self._d, self._ss,
                            b0_dev, bufs)

            def make_bufs():
                per_dev = cap + 1
                return shard_leading(mesh, {
                    "hit_word": np.full((nd * per_dev,), -1, np.int32),
                    "hit_rank": np.zeros((nd * per_dev,), np.int32),
                })

        self._call = call
        self._make_bufs = make_bufs
        self._free = [make_bufs() for _ in range(self.depth)]
        #: fault-supervision knobs shared with the solo drive
        #: (PERF.md §23); part of the pack_candidate compatibility
        #: key, so every member genuinely agreed on them at fuse time.
        self._retry_attempts = int(cfg.retry_attempts)
        self._retry_backoff_s = float(cfg.retry_backoff_s)
        self._fetch_timeout_s = cfg.fetch_timeout_s

    # -- engine surface ------------------------------------------------

    @property
    def done(self) -> bool:
        """Every member has left (finished, paused, cancelled, failed)."""
        return not any(self._active)

    @property
    def active_members(self) -> int:
        """Members still attached (segment not parked).  The engine's
        re-fuse trigger compares this against ``n_seg`` to tell churn
        fill loss (departed tenants' parked segments) from natural
        tail under-occupancy, which no re-fuse can recover."""
        return int(sum(self._active))

    def register(self, sweep) -> None:
        """Bind a member sweep to its segment (the engine sets
        ``sweep._packed_source`` to this group right after fusing)."""
        sweep._packed_source = self

    def member_cum(self, sweep) -> np.ndarray:
        """The member's OWN solo cumulative block index (job-local) —
        the machine's cursor/replay arithmetic runs against it."""
        return self._members[self._by_sweep[id(sweep)]]["cum"]

    def leave(self, sweep) -> None:
        """Detach a member: park its segment at its end bound (future
        scan steps cut only masked zero-count blocks for it — no
        retrace, cohabitants unharmed) and drop its undelivered
        results.  Idempotent; called from the machine's drive finally
        on completion, pause, cancel and failure alike."""
        j = self._by_sweep[id(sweep)]
        self._active[j] = False
        self._b0[j] = self._seg_end[j]
        self._pending[j].clear()

    def next_result(self, sweep) -> "Optional[dict]":
        """The member's next consumed-superstep result, or None once its
        block range is drained.  The engine pumps before ticking, so a
        runnable member always finds its result here; a tick with no
        result and work remaining is a scheduler bug and fails loudly
        (silently ending the drive would lose keyspace)."""
        j = self._by_sweep[id(sweep)]
        if self._pending[j]:
            return self._pending[j].popleft()
        if self._consumed[j] < self._seg_end[j]:
            raise RuntimeError(
                "packed member ticked without a pumped result — the "
                "engine must pump the fused group once per round before "
                "ticking its members"
            )
        return None

    # -- the packed drive (audit_pack_round pins this shape) -----------

    def pump(self) -> bool:
        """One packed round: dispatch ahead up to ``depth`` supersteps,
        fetch the due one's counters (the ONE unconditional device→host
        round trip), split per-member results into the pending queues.
        Returns False when nothing was produced (group drained).

        Fault supervision (PERF.md §23): a transient device error in
        the dispatch/fetch half is retried — in-flight dispatches
        dropped, buffer sets rebuilt, per-segment cursors reset to
        their last SPLIT boundary (``_consumed``; already-split pending
        results survive, so nothing double-counts) — up to the shared
        ``retry_attempts`` budget; past that (or on a non-transient
        error) the exception propagates and the engine DEMOTES the
        members to solo machines instead of failing them."""
        attempts = 0
        while True:
            try:
                while self._work_remains() and len(self._inflight) < \
                        self.depth:
                    if faults.ACTIVE is not None:
                        faults.ACTIVE.fire("packed.pump")
                    snap = self._b0.copy()
                    self._inflight.append(
                        (snap, time.monotonic(),
                         self._call(snap, self._free.pop()))
                    )
                    self._b0 = np.minimum(
                        self._b0 + self._adv, self._seg_end
                    )
                if not self._inflight:
                    return False
                if not any(self._active):
                    # Every member left mid-flight: nobody will consume
                    # these results — drop the dispatches unfetched
                    # (their hits belong to block ranges the members'
                    # checkpoints will replay).
                    self._inflight.clear()
                    return False
                snap, disp_t, out = self._inflight.popleft()
                faults.await_ready(out["counters"],
                                   self._fetch_timeout_s)
                counters = np.asarray(out["counters"])  # [2, S] rows
            except Exception as exc:  # noqa: BLE001 — typed check inside
                self._recover_pump(exc, attempts)
                attempts += 1
                continue
            break
        overflow = False
        hit_occupancy = 0.0
        entries: List[List[Tuple[int, int]]] = [
            [] for _ in range(self.n_seg)
        ]
        if int(counters[1].sum()):
            dev_hits = np.asarray(out["dev_hits"])
            hit_occupancy = int(dev_hits.max()) / max(self._hit_cap, 1)
            if int(dev_hits.max()) > self._hit_cap:
                overflow = True
            else:
                hw = np.asarray(out["hit_word"])
                hr = np.asarray(out["hit_rank"])
                # Vectorized split: gather every device's valid slots,
                # map packed rows to (segment, job-local row) wholesale
                # — the per-member loop below only ever touches these
                # already-host-side results.
                per_dev = self._hit_cap + 1
                lanes = np.arange(hw.shape[0])
                valid = (lanes % per_dev) < dev_hits[lanes // per_dev]
                rows, ranks = hw[valid], hr[valid]
                segs = np.searchsorted(
                    self._row_base, rows, side="right"
                ) - 1
                locs = rows - self._row_base[segs]
                for j, w_loc, rank in zip(segs.tolist(), locs.tolist(),
                                          ranks.tolist()):
                    entries[j].append((w_loc, rank))
        self._free.append({"hit_word": out["hit_word"],
                           "hit_rank": out["hit_rank"]})
        ne_rows, nh_rows = counters[0].tolist(), counters[1].tolist()
        b_lo_rows = snap.tolist()
        b_hi_rows = np.minimum(snap + self._adv, self._seg_end).tolist()
        base_rows = self._blk_base[:-1].tolist()
        occupied = 0
        for j in range(self.n_seg):
            b_lo, b_hi = b_lo_rows[j], b_hi_rows[j]
            self._consumed[j] = b_hi
            occupied += self._occupied(j, b_lo, b_hi)
            if not self._active[j]:
                continue
            if b_lo >= b_hi:
                # This member's range drained in an earlier superstep —
                # no result to report, so its next tick sees None and
                # finishes NOW instead of idling (with no-op spans and
                # a withheld done event) until the slowest cohabitant
                # drains the group.
                continue
            entries[j].sort()
            self._pending[j].append({
                "ne": ne_rows[j],
                "nh": nh_rows[j],
                "entries": entries[j],
                "overflow": overflow and bool(nh_rows[j]),
                "b_lo": b_lo - base_rows[j],
                "b_hi": b_hi - base_rows[j],
                "disp_t": disp_t,
                "inflight": len(self._inflight),
                "hit_occupancy": hit_occupancy,
            })
        self.dispatches += 1
        # Result-surface counters (Engine.stats()'s packed_dispatches /
        # packed_fill) record even under A5GEN_TELEMETRY=off — the PR 9
        # off-hatch contract: the hatch changes observability, never
        # results (same convention as the step_cache.* counters).
        total = (
            self.steps * self._lanes * self._n_devices
            * max(1, self.pair_k)
        )
        self.last_fill = occupied / max(1, total)
        telemetry.counter("engine.packed_dispatches").add(1)
        telemetry.counter("engine.packed_lanes_occupied").add(occupied)
        telemetry.counter("engine.packed_lanes_total").add(total)
        return True

    # -- host bookkeeping ----------------------------------------------

    def _recover_pump(self, exc: BaseException, attempts: int) -> None:
        """The packed round's transient-recovery step (PERF.md §23):
        the shared gate (:func:`faults.supervise_retry`) re-raises or
        backs off; on retry, drop the in-flight dispatches, rebuild the
        buffer sets, and reset every ACTIVE segment's cursor to its
        last split boundary (parked segments stay parked) so the retry
        re-dispatches exactly the unconsumed work."""
        faults.supervise_retry(
            exc, attempts, attempts_budget=self._retry_attempts,
            backoff_s=self._retry_backoff_s, label="the packed round",
        )
        self._inflight.clear()
        self._free = [self._make_bufs() for _ in range(self.depth)]
        self._b0 = np.where(
            np.asarray(self._active), self._consumed, self._seg_end
        ).astype(np.int64)

    def _work_remains(self) -> bool:
        return bool(np.any(
            np.asarray(self._active) & (self._b0 < self._seg_end)
        ))

    def _occupied(self, j: int, b_lo: int, b_hi: int) -> int:
        """Variant lanes the member's block range [b_lo, b_hi) actually
        occupies (packed-global blocks; zero-count tail blocks excluded)
        — the fill-ratio instrument ``bench.py --pack-ab`` reports."""
        if b_hi <= b_lo:
            return 0
        m = self._members[j]
        base = int(self._blk_base[j])
        blocks = np.arange(b_lo - base, b_hi - base, dtype=np.int64)
        cum = np.asarray(m["cum"], dtype=np.int64)
        totals = np.asarray(m["totals"], dtype=np.int64)
        blocks = blocks[blocks < cum[-1]]
        if not blocks.size:
            return 0
        w = np.searchsorted(cum, blocks, side="right") - 1
        rank0 = (blocks - cum[w]) * self.stride
        return int(np.clip(totals[w] - rank0, 0, self.stride).sum())

"""The declared configuration-knob registry (PERF.md §30).

Eighteen PRs grew the knob surface to ~60 entries spread over five
layers — ``A5GEN_*`` env vars, CLI flags, :class:`SweepConfig` fields,
serve JSONL ``config`` sub-fields, and tune-profile knobs — and the
correctness rules binding them ("trace-affecting knobs must join the
step-cache key", "policy knobs must join ``pack_candidate``'s
compatibility key", "the scheduler-visible prefix must reach
``affinity_token``") existed only as review folklore: PR 12 retrofitted
the retry/watchdog knobs into the pack key and PR 17 the kernel-gate
verdicts, each a latent wrong-fuse bug until caught by hand.  This
module is the one declared answer to "what can configuration change,
and which cache key must know about it?" — the ``protocol.py``/
``env.py`` centralization pattern, one layer up.

``tools/graftknob`` extracts this registry via AST (never importing the
package) and cross-checks every layer surface and key site against it;
``KNOBS.json`` pins it at the repo root with the graftwire semver
discipline (deliberate changes re-pin via ``python -m tools.graftknob
--update-knobs``, which enforces the :data:`KNOBS_VERSION` bump rule:
additions need a minor bump, removals/renames a major).  The README's
"Configuration knobs" section renders from here via ``--update-readme``
and is staleness-gated in CI.

Registry shape (all literals PURE — ``ast.literal_eval`` and ``json``
must round-trip them):

``layers``
    Which of the five layers surface the knob, each with its spelling
    there and (env/cli/config) its declared default.  graftknob GK001
    diffs these against the extracted surfaces in both directions;
    GK005 diffs the defaults against the ``SweepConfig`` dataclass and
    ``argparse`` declarations.

``roles``
    The knob's correctness classes, each mechanically enforced:

    * ``trace`` — changes the traced/compiled program; its ``keys``
      token must appear in the ``Sweep._make_launch`` /
      ``Sweep._superstep_static`` step-cache key (or the
      ``_STEP_ENV_KNOBS`` suffix).  GK002.
    * ``fuse-compat`` — jobs disagreeing on it must not fuse; its
      token must appear in ``pack_candidate``'s compatibility key (or
      gate an early ``return None`` there).  GK003.
    * ``affinity`` — scheduler-visible: its token must reach
      ``affinity_token``'s ``static_affinity_token`` call.  GK004.
    * ``fingerprint`` — changes the semantic candidate stream; its
      token must be a ``sweep_fingerprint`` parameter.  GK004.
    * ``stream-semantics`` — changes WHAT is emitted but reaches the
      fingerprint through a parsed input (``sub_map``/``words``/
      ``digests``); declaration-only, the note says how.
    * ``host-only`` — observability, paths, scheduling, recovery
      budgets: never changes results or compiled programs.

``keys``
    role -> the token that witnesses the knob at its key site (an
    attribute/variable/constant name in the key tuple, a guard read,
    or a ``static_affinity_token`` kwarg / ``sweep_fingerprint``
    parameter name).  Defaults to the knob name when omitted.

``precedence``
    Human-readable resolution order across the declared layers.

``scope``
    ``"runtime"`` (default; GK001 requires the surface to be READ in
    the scanned tree) or ``"tests"`` (documented knobs only the test
    suite reads — exempt from the dead-surface check, still pinned
    and rendered).
"""

from __future__ import annotations

from typing import Any, Dict

__all__ = ["KNOBS_VERSION", "LAYERS", "ROLES", "KNOBS"]

#: Registry version (MAJOR.MINOR): knob/role/surface ADDITIONS bump the
#: minor, removals/renames the major, metadata (defaults, precedence,
#: notes) any re-pin.  ``--update-knobs`` refuses violations.
KNOBS_VERSION = "1.1"

#: The five places a knob can surface.
LAYERS = ("env", "cli", "config", "serve-doc", "tune-profile")

#: The six correctness classes (see module docstring).
ROLES = ("trace", "fuse-compat", "affinity", "fingerprint",
         "stream-semantics", "host-only")

KNOBS: Dict[str, Dict[str, Any]] = {
    # ------------------------------------------------------------------
    # Launch geometry + executor shape (SweepConfig-centric)
    # ------------------------------------------------------------------
    "lanes": {
        "layers": {
            "config": {"surface": "lanes", "default": 131072},
            "cli": {"surface": "--lanes", "default": None},
            "serve-doc": {"surface": "lanes"},
            "tune-profile": {"surface": "lanes"},
        },
        "roles": ["trace", "fuse-compat", "affinity"],
        "keys": {"trace": "lanes", "fuse-compat": "lanes",
                 "affinity": "lanes"},
        "precedence": "explicit > profile > builtin",
        "note": "hash lanes per launch; baked into every traced body",
    },
    "num_blocks": {
        "layers": {
            "config": {"surface": "num_blocks", "default": 1024},
            "cli": {"surface": "--blocks", "default": None},
            "serve-doc": {"surface": "blocks"},
            "tune-profile": {"surface": "num_blocks"},
        },
        "roles": ["trace", "fuse-compat", "affinity"],
        "keys": {"trace": "num_blocks", "fuse-compat": "num_blocks",
                 "affinity": "num_blocks"},
        "precedence": "explicit > profile > builtin",
        "note": "block batch per superstep dispatch",
    },
    "packed_blocks": {
        "layers": {
            "config": {"surface": "packed_blocks", "default": None},
            "cli": {"surface": "--block-layout", "default": "auto"},
            "tune-profile": {"surface": "packed_blocks"},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "stride", "fuse-compat": "stride"},
        "precedence": "explicit > profile > builtin (auto resolves "
                      "per plan)",
        "note": "packed vs fixed-stride block layout; reaches the keys "
                "as the resolved block stride",
    },
    "superstep": {
        "layers": {
            "config": {"surface": "superstep", "default": None},
            "cli": {"surface": "--superstep", "default": None},
            "serve-doc": {"surface": "superstep"},
            "tune-profile": {"surface": "superstep"},
        },
        "roles": ["trace", "fuse-compat", "affinity"],
        "keys": {"trace": "steps", "fuse-compat": "steps",
                 "affinity": "superstep"},
        "precedence": "explicit > profile > builtin (auto); "
                      "A5GEN_SUPERSTEP=off vetoes",
        "note": "device-resident steps per dispatch; off pins the "
                "per-launch pipeline",
    },
    "superstep_hit_cap": {
        "layers": {
            "config": {"surface": "superstep_hit_cap",
                       "default": 4096},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "hit_cap", "fuse-compat":
                 "superstep_hit_cap"},
        "precedence": "config only",
        "note": "on-device hit-buffer rows per superstep (overflow "
                "falls back per block)",
    },
    "fetch_chunk": {
        "layers": {
            "config": {"surface": "fetch_chunk", "default": 16},
            "cli": {"surface": "--fetch-chunk", "default": None},
            "serve-doc": {"surface": "fetch_chunk"},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "steps", "fuse-compat": "steps"},
        "precedence": "explicit > builtin",
        "note": "dispatches per counters fetch; sets the superstep "
                "step count when --superstep is auto",
    },
    "devices": {
        "layers": {
            "config": {"surface": "devices", "default": 1},
            "cli": {"surface": "--devices", "default": 1},
            "serve-doc": {"surface": "devices"},
        },
        "roles": ["trace", "fuse-compat", "affinity"],
        "keys": {"trace": "n_devices", "fuse-compat": "n_devices",
                 "affinity": "devices"},
        "precedence": "explicit > builtin (auto = all local)",
        "note": "data-parallel device count (sharded launches trace "
                "differently)",
    },
    "pair": {
        "layers": {
            "config": {"surface": "pair", "default": None},
            "cli": {"surface": "--pair", "default": "auto"},
            "serve-doc": {"surface": "pair"},
            "tune-profile": {"surface": "pair"},
        },
        "roles": ["trace", "fuse-compat", "affinity"],
        "keys": {"trace": "pair_k", "fuse-compat": "pair_k",
                 "affinity": "pair"},
        "precedence": "explicit > profile > builtin (auto); "
                      "A5GEN_PAIR=off vetoes",
        "note": "pair-lane tier (K=2 candidates per hash lane) for "
                "eligible schemas",
    },
    "pipeline": {
        "layers": {
            "config": {"surface": "pipeline", "default": None},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "_pipeline_depth"},
        "precedence": "config > A5GEN_PIPELINE gate > builtin",
        "note": "superstep double-buffer depth; a fused group runs ONE "
                "depth for every member",
    },
    "max_in_flight": {
        "layers": {
            "config": {"surface": "max_in_flight", "default": 2},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "_pipeline_depth"},
        "precedence": "config only",
        "note": "in-flight launch bound of the non-superstep drive "
                "(and the pipeline-depth fallback)",
    },
    "pod": {
        "layers": {
            "config": {"surface": "pod", "default": None},
            "cli": {"surface": "--giant-job", "default": False},
            "serve-doc": {"surface": "pod"},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "pod", "fuse-compat": "pod"},
        "precedence": "config (CLI --giant-job derives it from the "
                      "pod runtime; the fleet router's split scatter "
                      "drives it per shard through the serve doc)",
        "note": "giant-job block striping (stripe, n_stripes); "
                "pod-striped jobs refuse packed dispatch",
    },
    "stream_chunk_words": {
        "layers": {
            "config": {"surface": "stream_chunk_words",
                       "default": None},
            "cli": {"surface": "--stream-chunk-words",
                    "default": "auto"},
            "serve-doc": {"surface": "stream_chunk_words"},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "_stream"},
        "precedence": "explicit > builtin (auto engages past one "
                      "~64 MB plan chunk); A5GEN_STREAM=off vetoes",
        "note": "streaming plan pipeline chunk size; streaming sweeps "
                "keep per-job dispatch",
    },
    # ------------------------------------------------------------------
    # Robustness + persistence (SweepConfig-centric)
    # ------------------------------------------------------------------
    "retry_attempts": {
        "layers": {
            "config": {"surface": "retry_attempts", "default": 2},
            "serve-doc": {"surface": "retry_attempts"},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "retry_attempts"},
        "precedence": "config only",
        "note": "transient-error retries of the drive supervisor; one "
                "policy per fused group",
    },
    "retry_backoff_s": {
        "layers": {
            "config": {"surface": "retry_backoff_s", "default": 0.05},
            "serve-doc": {"surface": "retry_backoff_s"},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "retry_backoff_s"},
        "precedence": "config only",
        "note": "backoff between transient retries",
    },
    "fetch_timeout_s": {
        "layers": {
            "config": {"surface": "fetch_timeout_s", "default": None},
            "cli": {"surface": "--fetch-timeout", "default": None},
            "serve-doc": {"surface": "fetch_timeout_s"},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "fetch_timeout_s"},
        "precedence": "explicit > builtin (off)",
        "note": "per-fetch watchdog; one watchdog per fused group",
    },
    "checkpoint_path": {
        "layers": {
            "config": {"surface": "checkpoint_path", "default": None},
            "cli": {"surface": "--checkpoint", "default": None},
            "serve-doc": {"surface": "checkpoint_path"},
        },
        "roles": ["host-only"],
        "precedence": "explicit > builtin (off)",
        "note": "on-disk checkpoint file (power-loss-safe writes)",
    },
    "checkpoint_every_s": {
        "layers": {
            "config": {"surface": "checkpoint_every_s",
                       "default": 30.0},
            "cli": {"surface": "--checkpoint-every", "default": 30.0},
            "serve-doc": {"surface": "checkpoint_every_s"},
        },
        "roles": ["host-only"],
        "precedence": "explicit > builtin",
        "note": "checkpoint write cadence",
    },
    "faults": {
        "layers": {
            "config": {"surface": "faults", "default": None},
            "env": {"surface": "A5GEN_FAULTS", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "config > env > unset (no faults armed)",
        "note": "deterministic fault-injection plan (recovery paths "
                "change, declared results never do)",
    },
    "progress": {
        "layers": {
            "config": {"surface": "progress", "default": None},
            "cli": {"surface": "--progress", "default": False},
        },
        "roles": ["host-only"],
        "precedence": "explicit > builtin (off)",
        "note": "stderr progress meter",
    },
    "schema_cache": {
        "layers": {
            "config": {"surface": "schema_cache", "default": None},
            "cli": {"surface": "--schema-cache", "default": None},
            "serve-doc": {"surface": "schema_cache"},
            "env": {"surface": "A5GEN_SCHEMA_CACHE", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "config/cli > env > unset (no persistent cache)",
        "note": "on-disk piece-schema cache directory",
    },
    "schema_cache_max_mb": {
        "layers": {
            "config": {"surface": "schema_cache_max_mb",
                       "default": None},
            "cli": {"surface": "--schema-cache-max-mb",
                    "default": None},
            "serve-doc": {"surface": "schema_cache_max_mb"},
            "env": {"surface": "A5GEN_SCHEMA_CACHE_MAX_MB",
                    "default": None},
        },
        "roles": ["host-only"],
        "precedence": "config/cli > env > unset (unbounded)",
        "note": "LRU size cap on the schema cache",
    },
    "geometry_source": {
        "layers": {
            "config": {"surface": "geometry_source",
                       "default": "explicit"},
        },
        "roles": ["host-only"],
        "precedence": "set by the resolution seam, not by users",
        "note": "provenance marker of the resolved geometry "
                "(explicit/profile/builtin) for stats surfaces",
    },
    # ------------------------------------------------------------------
    # Attack-spec inputs (fingerprint material)
    # ------------------------------------------------------------------
    "mode": {
        "layers": {
            "cli": {"surface": ["-s", "--substitute-all", "-r",
                                "--reverse-sub"], "default": False},
        },
        "roles": ["trace", "fuse-compat", "affinity", "fingerprint",
                  "stream-semantics"],
        "keys": {"trace": "spec", "fuse-compat": "spec",
                 "affinity": "mode", "fingerprint": "mode"},
        "precedence": "cli flags compose the mode; serve jobs pass "
                      "the submit doc's mode field (WIRE_OPS)",
        "note": "attack mode (default/reverse/suball/suball-reverse); "
                "baked into every traced body",
    },
    "algo": {
        "layers": {
            "cli": {"surface": "--algo", "default": "md5"},
        },
        "roles": ["trace", "fuse-compat", "affinity", "fingerprint",
                  "stream-semantics"],
        "keys": {"trace": "spec", "fuse-compat": "spec",
                 "affinity": "algo", "fingerprint": "algo"},
        "precedence": "cli; serve jobs pass the submit doc's algo "
                      "field (WIRE_OPS)",
        "note": "digest algorithm (md5/md4/sha1/ntlm)",
    },
    "table_min": {
        "layers": {
            "cli": {"surface": ["-m", "--table-min"], "default": 0},
        },
        "roles": ["trace", "fuse-compat", "affinity", "fingerprint",
                  "stream-semantics"],
        "keys": {"trace": "spec", "fuse-compat": "spec",
                 "affinity": "table_min",
                 "fingerprint": "min_substitute"},
        "precedence": "cli; serve jobs pass the submit doc's "
                      "table_min field (WIRE_OPS)",
        "note": "minimum substitutions per candidate",
    },
    "table_max": {
        "layers": {
            "cli": {"surface": ["-x", "--table-max"], "default": 15},
        },
        "roles": ["trace", "fuse-compat", "affinity", "fingerprint",
                  "stream-semantics"],
        "keys": {"trace": "spec", "fuse-compat": "spec",
                 "affinity": "table_max",
                 "fingerprint": "max_substitute"},
        "precedence": "cli; serve jobs pass the submit doc's "
                      "table_max field (WIRE_OPS)",
        "note": "maximum substitutions per candidate",
    },
    "dict_file": {
        "layers": {
            "cli": {"surface": "dict_file", "default": None},
        },
        "roles": ["fingerprint", "stream-semantics"],
        "keys": {"fingerprint": "words"},
        "precedence": "cli positional; serve jobs pass dict/words "
                      "doc fields (WIRE_OPS)",
        "note": "the wordlist input",
    },
    "table_files": {
        "layers": {
            "cli": {"surface": ["-t", "--table-files"],
                    "default": []},
        },
        "roles": ["fingerprint", "stream-semantics"],
        "keys": {"fingerprint": "sub_map"},
        "precedence": "cli (repeatable, merged); serve jobs pass "
                      "tables/table_map doc fields (WIRE_OPS)",
        "note": "substitution tables (merged per key)",
    },
    "digests": {
        "layers": {
            "cli": {"surface": "--digests", "default": None},
        },
        "roles": ["fingerprint", "stream-semantics"],
        "keys": {"fingerprint": "digests"},
        "precedence": "cli; serve jobs pass digests/digest_list doc "
                      "fields (WIRE_OPS)",
        "note": "target digest set (crack mode; absent = candidates "
                "mode)",
    },
    # ------------------------------------------------------------------
    # Env-only escape hatches + process-wide gates
    # ------------------------------------------------------------------
    "A5GEN_PALLAS": {
        "layers": {
            "env": {"surface": "A5GEN_PALLAS", "default": None},
        },
        "roles": ["trace"],
        "keys": {"trace": "A5GEN_PALLAS"},
        "precedence": "env only (process-wide kernel selection)",
        "note": "fused Pallas kernel opt-out (off/0/xla/none) or "
                "MD5-compression-only opt-in (1); rides the step-cache "
                "env suffix",
    },
    "A5GEN_PALLAS_G": {
        "layers": {
            "env": {"surface": "A5GEN_PALLAS_G", "default": None},
        },
        "roles": ["trace"],
        "keys": {"trace": "A5GEN_PALLAS_G"},
        "precedence": "env only",
        "note": "blocks per Pallas grid step (default 8); rides the "
                "step-cache env suffix",
    },
    "A5GEN_PALLAS_INTERPRET": {
        "layers": {
            "env": {"surface": "A5GEN_PALLAS_INTERPRET",
                    "default": None},
        },
        "roles": ["trace"],
        "keys": {"trace": "A5GEN_PALLAS_INTERPRET"},
        "precedence": "env only",
        "note": "force interpret-mode pallas_call (the CPU test hook); "
                "rides the step-cache env suffix",
    },
    "A5GEN_EMIT": {
        "layers": {
            "env": {"surface": "A5GEN_EMIT", "default": None},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "pieces", "fuse-compat": "pieces"},
        "precedence": "env only (process-wide compile knob; profiles "
                      "record it but never apply it)",
        "note": "perslot piece emission vs legacy bytescan; reaches "
                "the keys through the piece schema",
    },
    "A5GEN_CASCADE_CLOSE": {
        "layers": {
            "env": {"surface": "A5GEN_CASCADE_CLOSE",
                    "default": None},
        },
        "roles": ["trace"],
        "keys": {"trace": "pieces"},
        "precedence": "env only",
        "note": "suball cascade-closure opt-out; changes the plan/"
                "piece structure the keys carry",
    },
    "A5GEN_SUPERSTEP": {
        "layers": {
            "env": {"surface": "A5GEN_SUPERSTEP", "default": None},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "superstep", "fuse-compat": "steps"},
        "precedence": "env veto over the superstep knob",
        "note": "superstep executor opt-out; selects a differently-"
                "tagged step program and disables packing",
    },
    "A5GEN_PIPELINE": {
        "layers": {
            "env": {"surface": "A5GEN_PIPELINE", "default": None},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "_pipeline_depth"},
        "precedence": "env veto over the pipeline knob",
        "note": "double-buffered superstep pipeline opt-out",
    },
    "A5GEN_STREAM": {
        "layers": {
            "env": {"surface": "A5GEN_STREAM", "default": None},
        },
        "roles": ["fuse-compat"],
        "keys": {"fuse-compat": "_stream"},
        "precedence": "env veto over stream_chunk_words",
        "note": "streaming plan pipeline opt-out",
    },
    "A5GEN_PAIR": {
        "layers": {
            "env": {"surface": "A5GEN_PAIR", "default": None},
        },
        "roles": ["trace", "fuse-compat"],
        "keys": {"trace": "pair_k", "fuse-compat": "pair_k"},
        "precedence": "env veto over the pair knob",
        "note": "pair-lane (K=2) tier opt-out",
    },
    "pack": {
        "layers": {
            "env": {"surface": "A5GEN_PACK", "default": None},
            "cli": {"surface": "--pack", "default": "auto"},
        },
        "roles": ["host-only"],
        "precedence": "cli > env > builtin (on); Engine(pack=) "
                      "overrides per engine",
        "note": "cross-job packed dispatch gate (streams identical "
                "either way; fill/dispatch count differ)",
    },
    "A5GEN_TELEMETRY": {
        "layers": {
            "env": {"surface": "A5GEN_TELEMETRY", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "env only",
        "note": "hot-path telemetry opt-out (result-backing counters "
                "always record)",
    },
    "A5GEN_REFUSE": {
        "layers": {
            "env": {"surface": "A5GEN_REFUSE", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "Engine(refuse_below=) > env > builtin (0.5)",
        "note": "packed-group re-fuse fill threshold; off disables "
                "re-fuse; within[:ratio] keeps re-fuse on but pins "
                "the within-group-only merge scope (the cross-group "
                "control arm)",
    },
    "tune_profile": {
        "layers": {
            "env": {"surface": "A5GEN_TUNE_PROFILE", "default": None},
            "cli": {"surface": ["--profile", "--profile-dir"],
                    "default": None},
        },
        "roles": ["host-only"],
        "precedence": "cli dir > env dir > ~/.cache/a5gen/tune; "
                      "env off disables loading AND writing",
        "note": "autotune profile directory / kill switch (resolved "
                "geometry knobs carry the correctness roles)",
    },
    "A5GEN_DCN_TIMEOUT": {
        "layers": {
            "env": {"surface": "A5GEN_DCN_TIMEOUT", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "env > builtin (600 s)",
        "note": "pod peer-loss watchdog for cross-host collectives",
    },
    "A5_NATIVE": {
        "layers": {
            "env": {"surface": "A5_NATIVE", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "env > builtin (on when the toolchain allows)",
        "note": "C++ oracle fast path opt-out (grandfathered pre-"
                "A5GEN_ name; byte-identical streams)",
    },
    "A5GEN_REFERENCE_BIN": {
        "layers": {
            "env": {"surface": "A5GEN_REFERENCE_BIN",
                    "default": None},
        },
        "roles": ["host-only"],
        "scope": "tests",
        "precedence": "env only",
        "note": "path to a compiled upstream binary (enables the "
                "byte-diff harness in tests)",
    },
    "A5GEN_FORBID_SLOW": {
        "layers": {
            "env": {"surface": "A5GEN_FORBID_SLOW", "default": None},
        },
        "roles": ["host-only"],
        "scope": "tests",
        "precedence": "env only (CI sets 1)",
        "note": "hard-fail collection when a slow-marked test enters "
                "the default tier",
    },
    # ------------------------------------------------------------------
    # CLI-only front-end knobs (host side)
    # ------------------------------------------------------------------
    "threads": {
        "layers": {"cli": {"surface": "--threads", "default": -1}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (-1 = auto)",
        "note": "oracle-backend worker processes (stream byte-"
                "identical at any N)",
    },
    "backend": {
        "layers": {"cli": {"surface": "--backend",
                           "default": "oracle"}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "oracle (CPU reference) vs device (JAX sweep); "
                "byte-exact parity is the repo contract",
    },
    "retries": {
        "layers": {"cli": {"surface": "--retries", "default": 0}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "whole-sweep rebuild+resume attempts after chip/"
                "backend loss (outer loop; distinct from "
                "retry_attempts)",
    },
    "no_resume": {
        "layers": {"cli": {"surface": "--no-resume",
                           "default": False}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "ignore an existing checkpoint file",
    },
    "output": {
        "layers": {"cli": {"surface": "--output", "default": None}},
        "roles": ["host-only"],
        "precedence": "cli; serve candidates jobs pass the output "
                      "doc field (WIRE_OPS)",
        "note": "candidate stream destination (default stdout)",
    },
    "metrics_json": {
        "layers": {"cli": {"surface": "--metrics-json",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "write run metrics JSON",
    },
    "emit_table": {
        "layers": {"cli": {"surface": "--emit-table",
                           "default": None}},
        "roles": ["stream-semantics"],
        "precedence": "cli only",
        "note": "emit a device table layout instead of sweeping "
                "(different output document entirely)",
    },
    "list_layouts": {
        "layers": {"cli": {"surface": "--list-layouts",
                           "default": False}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "print available emit-table layouts and exit",
    },
    "hex_unsafe": {
        "layers": {"cli": {"surface": "--hex-unsafe",
                           "default": False}},
        "roles": ["stream-semantics"],
        "precedence": "cli only",
        "note": "hashcat --hex-charset compat for digest parsing; "
                "reaches the fingerprint through the parsed digests",
    },
    "bug_compat": {
        "layers": {"cli": {"surface": "--bug-compat",
                           "default": False}},
        "roles": ["stream-semantics"],
        "precedence": "cli only",
        "note": "reproduce upstream parser quirks; reaches the "
                "fingerprint through the parsed sub_map",
    },
    "max_word_bytes": {
        "layers": {"cli": {"surface": "--max-word-bytes",
                           "default": 65536}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "per-word input size guard (oversized words fail "
                "loudly, never truncate)",
    },
    "buckets": {
        "layers": {"cli": {"surface": "--buckets",
                           "default": "auto"}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (auto)",
        "note": "packed-wordlist length buckets (throughput only; "
                "--buckets none pins input order)",
    },
    # Pod bring-up (the striping itself is the `pod` knob above).
    "coordinator": {
        "layers": {"cli": {"surface": "--coordinator",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "multi-process pod coordinator HOST:PORT",
    },
    "num_processes": {
        "layers": {"cli": {"surface": "--num-processes",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "pod process count",
    },
    "process_id": {
        "layers": {"cli": {"surface": "--process-id",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "this host's pod process index",
    },
    "pod_hits": {
        "layers": {"cli": {"surface": "--pod-hits",
                           "default": "gathered"}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (gathered)",
        "note": "gather pod hits to process 0 vs per-process local "
                "files",
    },
    # ------------------------------------------------------------------
    # Serve/fleet operational knobs (host side)
    # ------------------------------------------------------------------
    "socket": {
        "layers": {"cli": {"surface": "--socket", "default": None}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (stdio)",
        "note": "serve/fleet unix socket path",
    },
    "engine_id": {
        "layers": {"cli": {"surface": "--engine-id",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli > generated",
        "note": "stable engine identity for fleet stats/placement",
    },
    "client_timeout": {
        "layers": {"cli": {"surface": "--client-timeout",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (off)",
        "note": "idle-session watchdog (both directions quiet)",
    },
    "admission_worker": {
        "layers": {"cli": {"surface": "--admission-worker",
                           "default": "on"}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (on)",
        "note": "build fuse admissions off the serve thread",
    },
    "engines": {
        "layers": {"cli": {"surface": "--engines", "default": None}},
        "roles": ["host-only"],
        "precedence": "cli (required)",
        "note": "fleet pool size or engine socket list",
    },
    "place": {
        "layers": {"cli": {"surface": "--place",
                           "default": "affinity"}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (affinity)",
        "note": "router placement policy (affinity-token vs round-"
                "robin)",
    },
    "poll": {
        "layers": {"cli": {"surface": "--poll", "default": 2.0}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "router health/stats scrape cadence",
    },
    "split": {
        "layers": {
            "env": {"surface": "A5GEN_SPLIT", "default": None},
            "cli": {"surface": "--split", "default": None},
        },
        "roles": ["host-only"],
        "precedence": "cli > env > builtin (auto)",
        "note": "fleet giant-job splitting (auto|on|off): scatter one "
                "oversized crack job across engines as disjoint pod "
                "stripes; host-side routing only — the merged stream "
                "is byte-identical to solo",
    },
    "split_threshold": {
        "layers": {
            "cli": {"surface": "--split-threshold",
                    "default": 4096},
        },
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "word count at which split=auto scatters a crack job "
                "(split=on ignores it; split=off never scatters)",
    },
    "replay_budget": {
        "layers": {"cli": {"surface": "--replay-budget",
                           "default": 1}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "crash-replay attempts per job before quarantine",
    },
    "autoscale": {
        "layers": {"cli": {"surface": "--autoscale",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (off)",
        "note": "MIN:MAX engine autoscaling bounds",
    },
    "scale_up_at": {
        "layers": {"cli": {"surface": "--scale-up-at",
                           "default": 2.0}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "backlog-per-engine threshold to scale up",
    },
    "scale_down_at": {
        "layers": {"cli": {"surface": "--scale-down-at",
                           "default": 0.25}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "load threshold to scale down",
    },
    "scale_window": {
        "layers": {"cli": {"surface": "--scale-window",
                           "default": 2}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "consecutive scrapes over threshold before scaling "
                "(hysteresis)",
    },
    "scale_cooldown": {
        "layers": {"cli": {"surface": "--scale-cooldown",
                           "default": 10.0}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "seconds between scaling actions",
    },
    "engine_capacity": {
        "layers": {"cli": {"surface": "--engine-capacity",
                           "default": 32}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "jobs per engine before admission queues",
    },
    "max_pending": {
        "layers": {"cli": {"surface": "--max-pending",
                           "default": 256}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "router pending-queue bound (typed overload rejection "
                "past it)",
    },
    "per_tenant": {
        "layers": {"cli": {"surface": "--per-tenant", "default": 0}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (0 = unlimited)",
        "note": "per-tenant admission cap",
    },
    "shed_policy": {
        "layers": {"cli": {"surface": "--shed-policy",
                           "default": "reject"}},
        "roles": ["host-only"],
        "precedence": "cli > builtin (reject)",
        "note": "overload shedding policy (reject/oldest/queue)",
    },
    "engine_dir": {
        "layers": {"cli": {"surface": "--engine-dir",
                           "default": None}},
        "roles": ["host-only"],
        "precedence": "cli > tmpdir",
        "note": "directory for spawned engines' sockets/logs",
    },
    # ------------------------------------------------------------------
    # Tune subcommand knobs (host side)
    # ------------------------------------------------------------------
    "tune_words": {
        "layers": {"cli": {"surface": "--words", "default": 512}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "words per autotune arm measurement",
    },
    "tune_seconds": {
        "layers": {"cli": {"surface": "--seconds", "default": 1.0}},
        "roles": ["host-only"],
        "precedence": "cli > builtin",
        "note": "target seconds per autotune arm",
    },
    "tune_smoke": {
        "layers": {"cli": {"surface": "--smoke", "default": False}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "the CI 2x2 autotune matrix",
    },
    "tune_state": {
        "layers": {"cli": {"surface": "--state", "default": None}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "partial-matrix resume file for the autotuner",
    },
    "tune_no_write": {
        "layers": {"cli": {"surface": "--no-write",
                           "default": False}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "measure without persisting a profile",
    },
    "tune_json": {
        "layers": {"cli": {"surface": "--json", "default": False}},
        "roles": ["host-only"],
        "precedence": "cli only",
        "note": "machine-readable autotune result",
    },
}

"""Autotuned launch geometry: per-device-kind profiles + the matrix driver.

Every geometry number in the repo (lanes, blocks, stride 128-vs-256,
emit arm, pair) was hand-picked on one CPU host; the bench trajectory
(r01–r05) never confirmed any of it on hardware.  This module makes the
winning geometry a *persisted, versioned artifact* instead of folklore:

* ``a5gen tune`` / ``bench.py --autotune`` sweep a bounded matrix of
  lanes × stride (→ block batch) × superstep depth × pair × emit arm
  over the production crack contract (:func:`run_autotune`), time each
  arm on the live backend, assert per-arm stream parity (geometry must
  never change WHAT is emitted), and write the winner as a profile.
* The profile lives at ``~/.cache/a5gen/tune/<device_kind>.json``
  (schema-versioned; writes via ``checkpoint.atomic_write_text`` so a
  crash can never tear it).  ``A5GEN_TUNE_PROFILE=off`` disables
  loading; any other non-empty value overrides the directory.
* ``Sweep`` resolves geometry **explicit flag > loaded profile >
  built-in defaults** (:func:`resolve_config`): the CLI/bench leave
  ``SweepConfig.lanes=None`` when the user gave no flag, and the sweep
  fills the gaps from the profile at launch time (the device kind is
  known there).  Explicit constructions — every test, every library
  caller that passes ``lanes=`` — never consult a profile at all.
* Corrupt or unknown-major profiles warn ONCE and fall back to the
  built-in defaults (typed :class:`TuneProfileCorrupt`, the
  ``CheckpointCorrupt`` discipline) — a torn cache file must never
  change results or crash a sweep.

Top level stays stdlib-only (the env/checkpoint discipline): jax is
imported lazily inside the measurement helpers.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import replace
from typing import (
    TYPE_CHECKING,
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

if TYPE_CHECKING:
    from ..models.attack import AttackSpec
    from ..ops.packing import PackedWords
    from .sweep import SweepConfig

from .checkpoint import atomic_write_text
from .env import tune_profile_setting

#: Profile document schema version.  Major bumps are breaking (readers
#: reject via :class:`TuneProfileCorrupt`); minors are additive and
#: ignored by older readers — the checkpoint WIRE_VERSION convention.
TUNE_SCHEMA_VERSION = "1.0"

_TUNE_MAJOR = int(TUNE_SCHEMA_VERSION.split(".", 1)[0])

#: SweepConfig fields a profile may fill (explicit values always win).
#: ``emit`` is recorded in profiles but applied only via ``A5GEN_EMIT``
#: (it is a process-wide compile knob, not a per-sweep field).
PROFILE_KNOBS = ("lanes", "num_blocks", "superstep", "pair", "packed_blocks")

#: (path, reason) pairs already warned about — profile loading runs per
#: sweep construction, and one bad cache file must produce one
#: diagnostic, not one per job.
_WARNED: set = set()


class TuneProfileCorrupt(RuntimeError):
    """A tune profile exists but cannot be used (torn write, hand edit,
    or an unknown schema major).  Carries the path and the reason; the
    loader warns once and falls back to built-in defaults — a bad cache
    file must never change results."""


# ----------------------------------------------------------------------
# Profile location + IO
# ----------------------------------------------------------------------


def profile_dir() -> Optional[str]:
    """Directory profiles live in, or None when loading is disabled."""
    setting = tune_profile_setting()
    if setting is None:
        return None
    return setting or os.path.join(
        os.path.expanduser("~"), ".cache", "a5gen", "tune"
    )


def device_slug(device_kind: str) -> str:
    """Filesystem-safe name for a device kind (``"TPU v4"`` →
    ``"tpu-v4"``)."""
    slug = "".join(
        c if c.isalnum() else "-" for c in device_kind.strip().lower()
    ).strip("-")
    while "--" in slug:
        slug = slug.replace("--", "-")
    return slug or "unknown"


def profile_path(device_kind: str, directory: Optional[str] = None
                 ) -> Optional[str]:
    """Profile path for a device kind, or None when loading is off."""
    d = directory if directory is not None else profile_dir()
    if d is None:
        return None
    return os.path.join(d, f"{device_slug(device_kind)}.json")


def current_device_kind() -> str:
    """The live backend's device kind (``"cpu"``, ``"TPU v4"``, …)."""
    import jax

    return str(jax.devices()[0].device_kind)


def read_profile(path: str) -> Dict[str, Any]:
    """Parse + validate one profile document.  Raises
    :class:`TuneProfileCorrupt` on unparseable JSON, a non-object
    payload, a missing/unknown-major version, or malformed geometry —
    never a raw ``JSONDecodeError`` with no path."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        raise
    except (OSError, ValueError) as exc:
        raise TuneProfileCorrupt(f"{path}: unreadable profile: {exc}")
    if not isinstance(doc, dict):
        raise TuneProfileCorrupt(f"{path}: profile is not a JSON object")
    version = doc.get("version")
    try:
        major = int(str(version).split(".", 1)[0])
    except (TypeError, ValueError):
        raise TuneProfileCorrupt(
            f"{path}: missing/malformed profile version {version!r}"
        )
    if major != _TUNE_MAJOR:
        raise TuneProfileCorrupt(
            f"{path}: profile schema major {major} != supported "
            f"{_TUNE_MAJOR} (re-run `a5gen tune`)"
        )
    geom = doc.get("geometry")
    if not isinstance(geom, dict):
        raise TuneProfileCorrupt(f"{path}: profile has no geometry object")
    for knob in ("lanes", "num_blocks", "superstep"):
        v = geom.get(knob)
        if v is not None and (not isinstance(v, int) or v < 0):
            raise TuneProfileCorrupt(
                f"{path}: geometry.{knob}={v!r} is not a non-negative int"
            )
    if geom.get("lanes") in (0,):
        raise TuneProfileCorrupt(f"{path}: geometry.lanes must be positive")
    return doc


def load_profile(device_kind: str, directory: Optional[str] = None
                 ) -> Optional[Dict[str, Any]]:
    """The forgiving read the runtime uses: None when loading is
    disabled, no profile exists for this device kind, or the file is
    corrupt/unknown-major (warned ONCE per path+reason, built-in
    defaults carry on)."""
    path = profile_path(device_kind, directory)
    if path is None:
        return None
    try:
        return read_profile(path)
    except FileNotFoundError:
        return None
    except TuneProfileCorrupt as exc:
        key = (path, str(exc))
        if key not in _WARNED:
            _WARNED.add(key)
            import sys

            print(
                f"a5gen: warning: ignoring tune profile ({exc}); "
                "using built-in geometry defaults",
                file=sys.stderr,
            )
        return None


def write_profile(
    device_kind: str,
    geometry: Dict[str, Any],
    *,
    bench: Optional[Dict[str, Any]] = None,
    directory: Optional[str] = None,
) -> str:
    """Persist a profile atomically; returns the path written.  The
    directory default honors ``A5GEN_TUNE_PROFILE`` like the loader,
    but an explicit ``directory`` always wins (``a5gen tune -o``)."""
    d = directory if directory is not None else profile_dir()
    if d is None:
        raise ValueError(
            "profile writing is disabled (A5GEN_TUNE_PROFILE=off); pass "
            "an explicit directory to write anyway"
        )
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{device_slug(device_kind)}.json")
    doc = {
        "version": TUNE_SCHEMA_VERSION,
        "device_kind": device_kind,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "geometry": {k: geometry.get(k) for k in PROFILE_KNOBS + ("emit",)},
        "bench": dict(bench or {}),
    }
    atomic_write_text(path, json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return path


# ----------------------------------------------------------------------
# Geometry resolution: explicit flag > profile > built-in defaults
# ----------------------------------------------------------------------


def builtin_geometry(device_kind: str) -> Dict[str, Any]:
    """The pre-autotuner hand-picked defaults, per backend class:
    accelerators want big launches (dispatch/fetch amortization,
    PERF.md §4), the CPU backend peaks far smaller (PERF.md §2);
    accelerator block count stays None = auto (the Sweep resolves it
    per plan once fused-kernel eligibility is known, PERF.md §9b)."""
    on_cpu = device_kind.strip().lower() == "cpu"
    return {
        "lanes": (1 << 17) if on_cpu else (1 << 22),
        "num_blocks": 1024 if on_cpu else None,
    }


def resolve_config(
    cfg: "SweepConfig", device_kind: str, *,
    directory: Optional[str] = None,
) -> "Tuple[SweepConfig, str]":
    """Resolve a ``SweepConfig`` whose geometry was left to the runtime
    (``lanes=None`` — the CLI/bench spelling for "no explicit flag").

    Returns ``(resolved_cfg, source)`` where ``source`` is ``explicit``
    (lanes was set: the config is untouched and no profile is ever
    consulted), ``profile`` (at least one knob came from a loaded
    profile), or ``default`` (built-in defaults filled the gaps).
    Per knob, an explicit (non-None) value always wins over the
    profile, and the profile over the built-ins — so ``--lanes`` plus a
    profile's superstep depth compose the way the flags document."""
    if cfg.lanes is not None:
        return cfg, "explicit"
    prof = load_profile(device_kind, directory)
    geom = prof.get("geometry", {}) if prof else {}
    builtin = builtin_geometry(device_kind)
    updates: Dict[str, Any] = {}
    from_profile = False
    for knob in PROFILE_KNOBS:
        if getattr(cfg, knob) is not None:
            continue  # explicit per-knob value (or pinned off) wins
        if geom.get(knob) is not None:
            updates[knob] = geom[knob]
            from_profile = True
        elif builtin.get(knob) is not None:
            updates[knob] = builtin[knob]
    resolved = replace(cfg, **updates) if updates else cfg
    return resolved, ("profile" if from_profile else "default")


# ----------------------------------------------------------------------
# The autotune matrix driver (a5gen tune / bench.py --autotune)
# ----------------------------------------------------------------------

#: The built-in tune contract: a deterministic synthetic wordlist +
#: substitution table shaped like the bench's production crack contract
#: (mixed hazard-free words, a handful of planted digests so the hit
#: path runs).
_TUNE_SUB_MAP = {
    b"a": [b"4", b"@"],
    b"e": [b"3"],
    b"i": [b"1", b"!"],
    b"o": [b"0"],
    b"s": [b"$", b"5"],
}


def tune_wordlist(n_words: int) -> List[bytes]:
    """Deterministic synthetic dictionary (no RNG: arms and repeat runs
    must sweep the identical keyspace)."""
    stems = [b"password", b"dragons", b"sesame", b"oatmeal", b"passions",
             b"mistrals", b"isotope", b"leopards"]
    return [
        stems[i % len(stems)] + (b"%03d" % (i % 1000))
        for i in range(n_words)
    ]


def default_matrix(
    *,
    lanes: Optional[List[int]] = None,
    strides: Optional[List[int]] = None,
    supersteps: Optional[List[int]] = None,
    pairs: Optional[List[str]] = None,
    emits: Optional[List[str]] = None,
    smoke: bool = False,
) -> List[Dict[str, Any]]:
    """The bounded arm matrix: lanes × stride (block batch =
    lanes/stride) × superstep depth × pair × emit arm.  ``smoke`` is
    the CI 2×2 (lanes × stride only) that must finish in seconds on
    CPU; the full default is sized for one unattended device window.
    New geometry knobs MUST join this matrix (CONTRIBUTING)."""
    if smoke:
        lanes = lanes or [1 << 10, 1 << 12]
        strides = strides or [64, 128]
        supersteps = supersteps or [None]
        pairs = pairs or ["auto"]
        emits = emits or [None]
    else:
        lanes = lanes or [1 << 17, 1 << 20, 1 << 22]
        strides = strides or [128, 256, 512]
        supersteps = supersteps or [8, 16]
        pairs = pairs or ["auto", "off"]
        emits = emits or [None]
    arms = []
    for ln in lanes:
        for st in strides:
            if ln % st:
                continue
            for ss in supersteps:
                for pr in pairs:
                    for em in emits:
                        name = f"lanes{ln}-stride{st}"
                        if ss is not None:
                            name += f"-ss{ss}"
                        if pr != "auto":
                            name += f"-pair_{pr}"
                        if em:
                            name += f"-{em}"
                        arms.append({
                            "name": name,
                            "lanes": ln,
                            "num_blocks": ln // st,
                            "stride": st,
                            "superstep": ss,
                            "pair": pr,
                            "emit": em,
                        })
    return arms


def _arm_config(
    arm: Dict[str, Any], base_kw: Dict[str, Any]
) -> "SweepConfig":
    from .sweep import SweepConfig

    return SweepConfig(
        lanes=int(arm["lanes"]),
        num_blocks=int(arm["num_blocks"]),
        superstep=arm.get("superstep"),
        pair={"auto": None, "on": "on", "off": 0}.get(
            arm.get("pair", "auto"), None
        ),
        **base_kw,
    )


def measure_arm(
    spec: "AttackSpec",
    sub_map: Dict[bytes, List[bytes]],
    packed: "PackedWords",
    digests: Sequence[bytes],
    arm: Dict[str, Any],
    *,
    seconds: float = 1.0,
    base_kw: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Time one arm on the live backend: one untimed warm-up sweep
    (compile + caches), then whole sweeps until ``seconds`` of timed
    wall accumulates.  Returns the arm record; ``emitted_per_sweep``
    is the per-arm parity check — launch geometry must never change
    WHAT is emitted, so every arm of a matrix must agree on it."""
    from .sweep import Sweep

    cfg = _arm_config(arm, dict(base_kw or {}))
    emit = arm.get("emit")
    # Save/restore seam, not a config read: the arm flips the process-
    # wide emit knob and must put back EXACTLY what was there (including
    # unset), which the read_env accessor cannot express.
    old_emit = os.environ.get("A5GEN_EMIT")  # graftlint: disable=GL012
    if emit:
        # The emit arm is a process-wide compile knob; the step cache
        # keys on the resulting piece schema, so flipping it between
        # arms is safe within one process.
        os.environ["A5GEN_EMIT"] = emit
    try:
        res = Sweep(spec, sub_map, packed, digests, config=cfg).run_crack()
        emitted = int(res.n_emitted)
        n_hits = int(res.n_hits)
        sweeps = 0
        t0 = time.monotonic()
        deadline = t0 + max(0.0, float(seconds))
        while True:
            r = Sweep(
                spec, sub_map, packed, digests, config=cfg
            ).run_crack()
            sweeps += 1
            if int(r.n_emitted) != emitted:
                raise RuntimeError(
                    f"autotune arm {arm['name']}: emitted drifted between "
                    f"sweeps ({r.n_emitted} != {emitted})"
                )
            if time.monotonic() >= deadline:
                break
        # The timed window IS this module's product (arm wall -> rate),
        # not instrumentation the telemetry registry should own.
        wall = time.monotonic() - t0  # graftlint: disable=GL013
    finally:
        if emit:
            if old_emit is None:
                os.environ.pop("A5GEN_EMIT", None)
            else:
                os.environ["A5GEN_EMIT"] = old_emit
    rate = emitted * sweeps / wall if wall > 0 else 0.0
    return {
        "arm": arm["name"],
        "geometry": {
            "lanes": int(arm["lanes"]),
            "num_blocks": int(arm["num_blocks"]),
            "stride": int(arm["stride"]),
            "superstep": arm.get("superstep"),
            "pair": arm.get("pair", "auto"),
            "emit": arm.get("emit"),
        },
        "emitted_per_sweep": emitted,
        "hits_per_sweep": n_hits,
        "sweeps": sweeps,
        "seconds": wall,
        "hashes_per_s": rate,
    }


def _read_tune_state(path: Optional[str]) -> Dict[str, Any]:
    if not path or not os.path.exists(path):
        return {"completed": {}}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if not isinstance(doc, dict) or not isinstance(
            doc.get("completed"), dict
        ):
            raise ValueError("not a tune-state object")
        return doc
    except (OSError, ValueError) as exc:
        raise TuneProfileCorrupt(f"{path}: unreadable tune state: {exc}")


def run_autotune(
    *,
    words: int = 512,
    seconds: float = 0.5,
    matrix: Optional[List[Dict[str, Any]]] = None,
    smoke: bool = False,
    state_path: Optional[str] = None,
    on_arm: Optional[Callable[[Dict[str, Any]], None]] = None,
    write: bool = True,
    directory: Optional[str] = None,
    device_kind: Optional[str] = None,
    spec: "Optional[AttackSpec]" = None,
    base_kw: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Sweep the arm matrix over the production crack contract and
    (optionally) persist the winner as this device kind's profile.

    Partial-matrix resume: with ``state_path``, each completed arm's
    record is appended atomically, and a rerun — the orchestrator's
    retry after an init flake, or a fresh process after a kill — skips
    straight past the completed arms to the first unfinished one.

    Per-arm parity: every arm must emit the identical per-sweep
    candidate count (geometry never changes WHAT is emitted); a
    mismatch raises instead of crowning a wrong-stream winner."""
    from ..models.attack import AttackSpec
    from ..ops.packing import pack_words

    if spec is None:
        spec = AttackSpec(mode="default", algo="md5")
    arms = matrix if matrix is not None else default_matrix(smoke=smoke)
    if not arms:
        raise ValueError("autotune needs a non-empty arm matrix")
    wl = tune_wordlist(int(words))
    packed = pack_words(wl)
    # Plant a few real digests so the device hit path (and host
    # re-verification) is part of what every arm pays for.
    import hashlib as _hashlib

    from ..oracle.engines import iter_candidates

    planted = []
    for w in wl[:: max(1, len(wl) // 3)][:3]:
        cand = next(
            iter(iter_candidates(w, _TUNE_SUB_MAP, spec.min_substitute,
                                 spec.max_substitute))
        )
        planted.append(_hashlib.md5(cand).digest())
    state = _read_tune_state(state_path)
    completed: Dict[str, Any] = dict(state.get("completed", {}))
    device = device_kind or current_device_kind()
    records: List[Dict[str, Any]] = []
    for arm in arms:
        prior = completed.get(arm["name"])
        if prior is not None:
            rec = {**prior, "resumed": True}
            records.append(rec)
            if on_arm is not None:
                on_arm(rec)
            continue
        rec = measure_arm(
            spec, _TUNE_SUB_MAP, packed, planted, arm,
            seconds=seconds, base_kw=base_kw,
        )
        rec["device_kind"] = device
        records.append(rec)
        completed[arm["name"]] = rec
        if state_path:
            atomic_write_text(
                state_path,
                json.dumps({"completed": completed}, sort_keys=True) + "\n",
            )
        if on_arm is not None:
            on_arm(rec)
    counts = {r["emitted_per_sweep"] for r in records}
    if len(counts) != 1:
        raise RuntimeError(
            "autotune parity failure: arms disagree on emitted-per-sweep "
            f"({sorted(counts)}); geometry must never change the stream"
        )
    winner = max(records, key=lambda r: r["hashes_per_s"])
    result: Dict[str, Any] = {
        "device_kind": device,
        "arms": records,
        "winner": winner["arm"],
        "geometry": dict(winner["geometry"]),
        "emitted_per_sweep": winner["emitted_per_sweep"],
        "hashes_per_s": winner["hashes_per_s"],
        "profile_path": None,
    }
    if write:
        geometry = dict(winner["geometry"])
        # "auto" knobs stay unset in the profile (None = let the sweep
        # decide), so a smoke tune never pins superstep/pair choices it
        # did not actually measure.
        if geometry.get("pair") == "auto":
            geometry["pair"] = None
        result["profile_path"] = write_profile(
            device, geometry,
            bench={
                "winner": winner["arm"],
                "hashes_per_s": winner["hashes_per_s"],
                "emitted_per_sweep": winner["emitted_per_sweep"],
                "seconds_per_arm": seconds,
                "words": int(words),
                "arms": len(records),
            },
            directory=directory,
        )
    return result

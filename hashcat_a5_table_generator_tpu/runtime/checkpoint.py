"""Sweep cursors and crash-safe checkpoint/resume.

The reference is stateless streaming — a killed run restarts from zero
(SURVEY.md §5 "Checkpoint/resume: ABSENT"). Here a sweep's position is one
tiny cursor, ``(word index, variant rank)``, because the variant space is
indexable (Q10: variant id ↔ choice vector bijection); recovery is exact
replay from the cursor. The checkpoint also carries a fingerprint of every
semantic input (mode, window, table, wordlist, digest set) so a stale file
can never silently resume the wrong sweep — note the fingerprint is
deliberately independent of *launch geometry* (lanes/blocks), so a resumed
run may retune those freely.

Writes are atomic AND durable (:func:`atomic_write_text`: tmp + fsync
+ rename + directory fsync), so a crash mid-checkpoint leaves the
previous checkpoint intact and a power loss cannot tear the rename
itself.  Corrupt or truncated files fail loudly as the typed
:class:`CheckpointCorrupt` — never a raw ``JSONDecodeError`` with no
path, and never a silent fresh start (PERF.md §23).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, field, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import faults


class CheckpointCorrupt(ValueError):
    """A checkpoint/manifest file exists but cannot be parsed (torn
    write, disk corruption, hand edit).  Carries the path and the
    parse failure; the CLI adds a one-line remediation hint."""


class CheckpointWireIncompatible(ValueError):
    """A checkpoint document's ``wire_version`` major does not match
    this build's.  Raised by :func:`state_from_doc` so a cross-engine
    migration (the fleet tier hands checkpoints between processes that
    may run different builds) fails loudly instead of garbling
    cursors."""


def atomic_write_bytes(path: str, blob: bytes) -> None:
    """Crash- and power-loss-safe replace of ``path`` with ``blob``:
    write a same-directory tmp file, flush + fsync the DATA, rename
    over the target, then fsync the DIRECTORY so the rename itself is
    durable.  tmp+rename alone is atomic against a crash between
    syscalls but NOT against power-loss torn writes — without the data
    fsync the rename can land while the blocks behind it never do.
    Checkpoints, bucket manifests, ``--metrics-json`` and the shared
    schema-cache entries (N fleet engines writing one directory) all
    write through here (PERF.md §23/§25).  A failed write cleans its
    tmp file before propagating — concurrent writers must never leave
    litter a reader could mistake for an entry."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    try:
        dirfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # exotic mount: the data fsync above still stands
    try:
        os.fsync(dirfd)
    except OSError:
        pass  # some filesystems refuse directory fsync
    finally:
        os.close(dirfd)


def atomic_write_text(path: str, blob: str) -> None:
    """:func:`atomic_write_bytes` for text payloads (UTF-8)."""
    atomic_write_bytes(path, blob.encode("utf-8"))


#: v2: canonical word encoding is (int64 length vector, concatenated
#: content) so packed batches hash buffer-at-a-time instead of per-word.
FORMAT_VERSION = 2

#: Wire format of the checkpoint DOCUMENT (``state_to_doc`` /
#: ``state_from_doc``) — the pause/migrate handoff the service and
#: fleet tiers ship between processes.  Distinct from FORMAT_VERSION
#: (the cursor encoding): the wire version gates CROSS-BUILD handoffs.
#: Major bumps are breaking (``state_from_doc`` rejects unknown majors
#: with :class:`CheckpointWireIncompatible`); minors are additive and
#: ignored by older readers.
WIRE_VERSION = "1.0"

_WIRE_MAJOR = int(WIRE_VERSION.split(".", 1)[0])

#: ``kind`` marker distinguishing a bucketed sweep's top-level manifest
#: from a single sweep's cursor checkpoint (both live at the user's
#: ``--checkpoint FILE`` path depending on ``--buckets``).
MANIFEST_KIND = "bucket-manifest"


@dataclass(frozen=True)
class SweepCursor:
    """Position in the sweep: next word row, next variant rank within it.

    ``rank`` is a Python int (variant spaces can exceed 2^63; blocks cut
    int32-sized pieces of it, ``ops.blocks.MAX_BLOCK``)."""

    word: int = 0
    rank: int = 0


@dataclass
class CheckpointState:
    """Everything needed to resume a sweep exactly where it stopped."""

    fingerprint: str
    cursor: SweepCursor = field(default_factory=SweepCursor)
    n_emitted: int = 0  # candidates emitted (device + oracle fallback)
    n_hits: int = 0
    hits: List[Tuple[int, int]] = field(default_factory=list)  # (word, rank)
    fallback_done: int = 0  # fallback words fully re-expanded so far
    wall_s: float = 0.0
    #: streaming-ingestion extension (PERF.md §19): the active
    #: ``{"chunk": i, "chunk_words": N}`` when a streaming sweep wrote
    #: the checkpoint.  Purely informational — the (word, rank) cursor
    #: is GLOBAL either way, so a streaming checkpoint resumes under the
    #: whole-dictionary path (which ignores this) and vice versa, and a
    #: resume under a different chunk size just re-derives the chunk
    #: from the cursor.
    stream: Optional[Dict] = None
    version: int = FORMAT_VERSION
    #: forward-compatibility carry (the replicated-ledger handoff
    #: guarantee, ROADMAP item 4): unknown fields of a minor-newer
    #: wire document, preserved verbatim so a
    #: ``state_from_doc -> state_to_doc`` round trip through THIS
    #: build — a pause/migrate hop through an older router — never
    #: strips what a newer engine wrote.  Majors still reject
    #: (:func:`check_wire_version`).
    extra: Dict = field(default_factory=dict)


def sweep_fingerprint(
    mode: str,
    algo: str,
    min_substitute: int,
    max_substitute: int,
    sub_map: Dict[bytes, List[bytes]],
    words: Sequence[bytes],
    digests: Sequence[bytes] = (),
    *,
    digest_lookup: Optional[Any] = None,
) -> str:
    """SHA-256 over a canonical serialization of the sweep's semantic inputs.

    Table entries hash in key order with value-list order preserved (order
    and multiplicity are semantic — Q2 first-option, Q7 duplicates).

    ``words`` may be a ``PackedWords`` batch — hashed buffer-at-a-time
    (little-endian int64 length vector, then the concatenated unpadded
    content bytes), identical to the per-word path for the same word
    sequence but without a Python loop over a rockyou-scale dictionary.
    The fingerprint stays independent of packing width and launch geometry.
    """
    h = hashlib.sha256()
    h.update(f"{mode}|{algo}|{min_substitute}|{max_substitute}|".encode())
    for key in sorted(sub_map):
        h.update(b"K%d:" % len(key) + key)
        for val in sub_map[key]:
            h.update(b"V%d:" % len(val) + val)
    if hasattr(words, "tokens"):  # PackedWords fast path
        lengths = np.ascontiguousarray(words.lengths, dtype="<i8")
        h.update(b"|W%d|" % len(lengths))
        h.update(lengths.tobytes())
        tokens = np.asarray(words.tokens)
        mask = (
            np.arange(tokens.shape[1])[None, :]
            < np.asarray(words.lengths)[:, None]
        )
        h.update(np.ascontiguousarray(tokens[mask]).tobytes())
    else:
        h.update(b"|W%d|" % len(words))
        h.update(
            np.asarray([len(w) for w in words], dtype="<i8").tobytes()
        )
        for w in words:
            h.update(w)
    # The lookup's sorted_blob is the digests in ascending byte order —
    # identical for matrix and list forms of the same set, so checkpoints
    # stay portable across parser paths (and a Sweep-provided lookup
    # reuses its one sort instead of re-sorting here).
    if digest_lookup is None:
        from ..ops.membership import HostDigestLookup

        digest_lookup = HostDigestLookup(digests)
    h.update(b"|D%d|" % len(digest_lookup))
    h.update(digest_lookup.sorted_blob())
    return h.hexdigest()


def state_to_doc(state: CheckpointState) -> Dict:
    """``state`` as a JSON-serializable document — the on-disk
    checkpoint format, also the wire format of the service mode's
    pause/migrate handoff (a paused job IS its checkpoint; ranks
    stringify because variant spaces exceed JSON's safe ints)."""
    doc = asdict(state)
    extra = doc.pop("extra")
    doc["wire_version"] = WIRE_VERSION
    doc["cursor"] = {"word": state.cursor.word, "rank": str(state.cursor.rank)}
    doc["hits"] = [[w, str(r)] for w, r in state.hits]
    # Re-append the unknown fields a minor-newer doc carried; known
    # keys never lose to a stale carry (setdefault, not overwrite).
    for k, v in extra.items():
        doc.setdefault(k, v)
    return doc


def check_wire_version(doc: Dict) -> None:
    """Reject a checkpoint document whose ``wire_version`` major is not
    this build's (:class:`CheckpointWireIncompatible`).  A document
    with NO wire_version predates the field — it is a major-1 doc by
    definition (the wire format has not changed since) and is
    accepted; unparseable values are rejected like unknown majors."""
    wv = doc.get("wire_version")
    if wv is None:
        return
    try:
        major = int(str(wv).split(".", 1)[0])
    except ValueError:
        raise CheckpointWireIncompatible(
            f"checkpoint wire_version {wv!r} is not a MAJOR.MINOR "
            "version string — refusing to migrate a document this "
            "build cannot interpret"
        ) from None
    if major != _WIRE_MAJOR:
        raise CheckpointWireIncompatible(
            f"checkpoint wire_version {wv!r} has major {major}, but "
            f"this build speaks {WIRE_VERSION} — cross-engine "
            "migration across incompatible builds must fail loudly; "
            "finish or restart the job on an engine of the writing "
            "build"
        )


#: Fields a checkpoint wire document must carry to be resumable; the
#: fleet router validates these at CAPTURE time (PERF.md §27) so a
#: malformed document fails the pause/drain that produced it with a
#: typed error instead of exploding later at crash-replay resubmit.
_WIRE_REQUIRED = ("fingerprint", "cursor", "n_emitted", "n_hits",
                  "hits", "wall_s")


def validate_checkpoint_doc(doc: object) -> Dict:
    """Structural validation of a checkpoint WIRE document without
    materializing it: the wire-version major is this build's
    (:func:`check_wire_version`) and every resumable field is present
    (fingerprint, a word/rank cursor, the counters, the hit list).
    Returns the doc (typed as a dict) so capture sites can hold it;
    raises :class:`CheckpointCorrupt` / :class:`CheckpointWireIncompatible`
    on anything a later ``state_from_doc`` would choke on."""
    if not isinstance(doc, dict):
        raise CheckpointCorrupt(
            f"checkpoint document must be a JSON object, got "
            f"{type(doc).__name__}"
        )
    check_wire_version(doc)
    missing = [k for k in _WIRE_REQUIRED if k not in doc]
    if missing:
        raise CheckpointCorrupt(
            f"checkpoint document is missing required field(s) "
            f"{', '.join(missing)} — refusing to hold an unresumable "
            "replay origin"
        )
    cursor = doc["cursor"]
    if not (isinstance(cursor, dict) and "word" in cursor
            and "rank" in cursor):
        raise CheckpointCorrupt(
            "checkpoint cursor must be an object with 'word' and "
            f"'rank', got {cursor!r}"
        )
    return doc


def state_from_doc(doc: Dict) -> CheckpointState:
    """Inverse of :func:`state_to_doc` (no fingerprint validation here —
    the sweep's ``_load_state`` / :func:`load_checkpoint` own that;
    the wire-version major IS validated — see
    :func:`check_wire_version`)."""
    check_wire_version(doc)
    known = {f.name for f in fields(CheckpointState)} | {"wire_version"}
    return CheckpointState(
        fingerprint=doc["fingerprint"],
        cursor=SweepCursor(
            word=int(doc["cursor"]["word"]), rank=int(doc["cursor"]["rank"])
        ),
        n_emitted=int(doc["n_emitted"]),
        n_hits=int(doc["n_hits"]),
        hits=[(int(w), int(r)) for w, r in doc["hits"]],
        fallback_done=int(doc.get("fallback_done", 0)),
        wall_s=float(doc["wall_s"]),
        stream=doc.get("stream"),
        extra={k: v for k, v in doc.items() if k not in known},
    )


def save_checkpoint(path: str, state: CheckpointState) -> None:
    """Durably write ``state`` as JSON (:func:`atomic_write_text`).
    The ``checkpoint.write`` injection point fires BEFORE any byte
    lands, so an injected crash here proves the previous checkpoint
    survives intact (PERF.md §23)."""
    if faults.ACTIVE is not None:
        faults.ACTIVE.fire("checkpoint.write")
    doc = state_to_doc(state)
    blob = json.dumps(doc)
    atomic_write_text(path, blob)
    from . import telemetry

    if telemetry.enabled():
        telemetry.counter("checkpoint.saves").add(1)
        telemetry.counter("checkpoint.bytes_written").add(len(blob))


def load_checkpoint(path: str, fingerprint: str) -> Optional[CheckpointState]:
    """Load and validate a checkpoint; None when absent.

    Raises ``ValueError`` on version or fingerprint mismatch (a checkpoint
    for a *different* sweep is an operator error worth surfacing, not a
    silent fresh start) and :class:`CheckpointCorrupt` on a file that
    exists but cannot be parsed — naming the path and the failure."""
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        doc = _parse_doc(fh.read(), path)
    if doc.get("kind") == MANIFEST_KIND:
        raise ValueError(
            f"checkpoint {path!r} is a bucket manifest written by a "
            "bucketed sweep; resume with the same --buckets, or delete it "
            "to start over"
        )
    if doc.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {path!r} has version {doc.get('version')}, "
            f"expected {FORMAT_VERSION}"
        )
    if doc.get("fingerprint") != fingerprint:
        raise ValueError(
            f"checkpoint {path!r} was written by a different sweep "
            "(mode/window/table/wordlist/digests changed); delete it to "
            "start over"
        )
    try:
        return state_from_doc(doc)
    except CheckpointWireIncompatible:
        # A different-build checkpoint is an operator error with its
        # own remediation (run it on the writing build), not file
        # corruption — keep the typed error.
        raise
    except (KeyError, TypeError, ValueError) as exc:
        # Valid JSON, broken schema (hand edit, partial restore): same
        # typed error as a torn file — the caller's remediation is
        # identical either way.
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is corrupt: field parse failed "
            f"({type(exc).__name__}: {exc})"
        ) from exc


def _parse_doc(raw: str, path: str) -> Dict:
    try:
        return json.loads(raw)
    except json.JSONDecodeError as exc:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is corrupt or truncated: not valid "
            f"JSON ({exc})"
        ) from exc


def save_bucket_manifest(path: str, fingerprints: Dict[int, str]) -> None:
    """Atomically write the bucketed sweep's top-level checkpoint at the
    user's ``--checkpoint FILE`` path: a manifest mapping each bucket width
    to its per-bucket checkpoint file (``{path}.w{width}``) and that
    bucket's semantic fingerprint.  FILE therefore always exists for a
    bucketed run, and a resume under different ``--buckets`` (or a legacy
    single-file checkpoint) fails loudly instead of silently restarting."""
    doc = {
        "version": FORMAT_VERSION,
        "kind": MANIFEST_KIND,
        "buckets": {
            str(width): {
                "file": os.path.basename(f"{path}.w{width}"),
                "fingerprint": fp,
            }
            for width, fp in sorted(fingerprints.items())
        },
    }
    atomic_write_text(path, json.dumps(doc))


def check_bucket_manifest(path: str, fingerprints: Dict[int, str]) -> bool:
    """Validate an existing manifest at ``path`` against this run's bucket
    fingerprints; returns False when absent.

    Raises ``ValueError`` when the file is a legacy single-sweep checkpoint
    (the pre-manifest layout — resuming it under bucketing would silently
    restart from zero) or when the bucket set / any fingerprint differs
    (``--buckets`` or sweep inputs changed)."""
    if not os.path.exists(path):
        return False
    with open(path) as fh:
        doc = _parse_doc(fh.read(), path)
    if doc.get("kind") != MANIFEST_KIND:
        raise ValueError(
            f"checkpoint {path!r} is a single-sweep checkpoint, not a "
            "bucket manifest; it would be ignored by a bucketed sweep — "
            "rerun with --buckets none to resume it, or delete it to "
            "start over"
        )
    if doc.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint manifest {path!r} has version "
            f"{doc.get('version')}, expected {FORMAT_VERSION}"
        )
    want = {
        str(width): fp for width, fp in fingerprints.items()
    }
    got = {
        w: entry.get("fingerprint")
        for w, entry in doc.get("buckets", {}).items()
    }
    if got != want:
        raise ValueError(
            f"checkpoint manifest {path!r} was written with different "
            "buckets or sweep inputs (--buckets/mode/window/table/wordlist/"
            "digests changed); delete it and its .w* files to start over"
        )
    return True

"""Shared host-side helpers (hash reference impls, encoding)."""

from .md4 import md4  # noqa: F401
from .hexenc import hex_notation_encode  # noqa: F401

"""hashcat ``$HEX[...]`` output encoding.

The reference streams raw candidate bytes to stdout (``main.go:65-67``);
hashcat's convention for plains containing unprintable bytes or line breaks
is ``$HEX[..]``. The sweep runtime's candidate sink emits raw bytes by
default (reference-compatible) and can opt into ``$HEX[]`` wrapping for
candidates that would corrupt line-oriented output.
"""

from __future__ import annotations


def hex_notation_encode(data: bytes) -> bytes:
    """Wrap ``data`` as ``$HEX[...]`` (lowercase hex, hashcat style)."""
    return b"$HEX[" + data.hex().encode("ascii") + b"]"


def needs_hex_notation(data: bytes) -> bool:
    """True when raw emission would corrupt line-oriented output: embedded
    newline / carriage return, or a literal ``$HEX[`` prefix that a consumer
    would mis-decode."""
    return b"\n" in data or b"\r" in data or data.startswith(b"$HEX[")

"""Pure-Python MD4 (RFC 1320) for host-side NTLM work.

OpenSSL 3 removed ``md4`` from ``hashlib`` on most builds, but the sweep
runtime needs host MD4 for oracle-fallback words in NTLM mode (the device
path has its own uint32-lane MD4 in ``ops.hashes``; the two are
cross-checked in tests). NTLM(password) = MD4(UTF-16LE(password)).
"""

from __future__ import annotations

import struct

_R2 = (0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15)
_R3 = (0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15)
_MASK = 0xFFFFFFFF


def _rotl(x: int, s: int) -> int:
    return ((x << s) | (x >> (32 - s))) & _MASK


def md4(data: bytes) -> bytes:
    """MD4 digest of ``data`` (16 bytes)."""
    ml = (len(data) * 8) & 0xFFFFFFFFFFFFFFFF
    data = data + b"\x80"
    data = data + b"\x00" * ((56 - len(data)) % 64)
    data = data + struct.pack("<Q", ml)

    a, b, c, d = 0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476
    for off in range(0, len(data), 64):
        x = struct.unpack("<16I", data[off : off + 64])
        aa, bb, cc, dd = a, b, c, d
        # Round 1: F(b,c,d) = (b & c) | (~b & d)
        for i in range(16):
            s = (3, 7, 11, 19)[i % 4]
            if i % 4 == 0:
                a = _rotl((a + ((b & c) | (~b & d)) + x[i]) & _MASK, s)
            elif i % 4 == 1:
                d = _rotl((d + ((a & b) | (~a & c)) + x[i]) & _MASK, s)
            elif i % 4 == 2:
                c = _rotl((c + ((d & a) | (~d & b)) + x[i]) & _MASK, s)
            else:
                b = _rotl((b + ((c & d) | (~c & a)) + x[i]) & _MASK, s)
        # Round 2: G(b,c,d) = (b & c) | (b & d) | (c & d), +0x5A827999
        for i in range(16):
            k = _R2[i]
            s = (3, 5, 9, 13)[i % 4]
            if i % 4 == 0:
                a = _rotl((a + ((b & c) | (b & d) | (c & d)) + x[k] + 0x5A827999) & _MASK, s)
            elif i % 4 == 1:
                d = _rotl((d + ((a & b) | (a & c) | (b & c)) + x[k] + 0x5A827999) & _MASK, s)
            elif i % 4 == 2:
                c = _rotl((c + ((d & a) | (d & b) | (a & b)) + x[k] + 0x5A827999) & _MASK, s)
            else:
                b = _rotl((b + ((c & d) | (c & a) | (d & a)) + x[k] + 0x5A827999) & _MASK, s)
        # Round 3: H(b,c,d) = b ^ c ^ d, +0x6ED9EBA1
        for i in range(16):
            k = _R3[i]
            s = (3, 9, 11, 15)[i % 4]
            if i % 4 == 0:
                a = _rotl((a + (b ^ c ^ d) + x[k] + 0x6ED9EBA1) & _MASK, s)
            elif i % 4 == 1:
                d = _rotl((d + (a ^ b ^ c) + x[k] + 0x6ED9EBA1) & _MASK, s)
            elif i % 4 == 2:
                c = _rotl((c + (d ^ a ^ b) + x[k] + 0x6ED9EBA1) & _MASK, s)
            else:
                b = _rotl((b + (c ^ d ^ a) + x[k] + 0x6ED9EBA1) & _MASK, s)
        a = (a + aa) & _MASK
        b = (b + bb) & _MASK
        c = (c + cc) & _MASK
        d = (d + dd) & _MASK

    return struct.pack("<4I", a, b, c, d)


def ntlm(password: bytes) -> bytes:
    """NTLM digest: MD4 over the byte-wise UTF-16LE expansion (each input
    byte followed by 0x00 — matching the device kernel's byte-level
    expansion in ``ops.hashes.utf16le_expand``, not Python ``str`` codecs:
    candidates are raw byte strings, not unicode text)."""
    return md4(bytes(b for ch in password for b in (ch, 0)))

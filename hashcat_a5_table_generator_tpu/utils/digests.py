"""Host-side digest functions (jax-free).

Used by the oracle backend, oracle-fallback words, and hit re-verification;
each must agree byte-for-byte with the device kernels in ``ops.hashes``
(cross-checked in tests/test_hashes.py and tests/test_runtime.py).
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict

from .md4 import md4, ntlm

HOST_DIGEST: Dict[str, Callable[[bytes], bytes]] = {
    "md5": lambda b: hashlib.md5(b).digest(),
    "sha1": lambda b: hashlib.sha1(b).digest(),
    "md4": md4,
    "ntlm": ntlm,
}

"""The ``@audited_entry`` registry: the package's semantic-audit surface.

``tools/graftaudit`` (the jaxpr/HLO-level audit tier — PERF.md §16) needs
a mechanical answer to "which compiled programs must uphold which
invariants?".  This module is that answer: kernels and step builders
declare themselves with :func:`audited_entry`, and the audit driver pairs
each registered name with a concrete launch configuration
(``tools/graftaudit/harness.py``) to trace, lower, and check.

Stdlib-only on purpose — importing this module must never pull in jax or
``tools/``; registration is metadata, the heavy lifting lives entirely in
the audit tool.  The registry is therefore safe to populate at import
time from ``ops/``, ``models/`` and ``parallel/``.

Entry kinds (what the audit does with the entry):

* ``"pallas_kernel"``  — a fused Pallas wrapper; traced (interpret mode,
  CPU) for op-count budgets (``KERNEL_BUDGETS.json``), static bounds and
  grid-overlap checks, and kernel float-purity.
* ``"integer_stage"``  — a hash/membership primitive whose whole trace
  must stay in integer dtypes (no float ``convert_element_type`` leaks).
* ``"fused_body"``     — an end-to-end expand→hash→membership body;
  lowered + XLA-compiled (CPU) for dead-stage detection (the PERF.md §15
  DCE trap) and host-transfer audits.
* ``"sharded_body"``   — same checks through ``shard_map`` on a 1-device
  mesh (the sharded twins must not lose stages either).

``stages``: the pipeline stages whose primitives must survive into the
optimized module (any of ``"expand"``, ``"hash"``, ``"membership"``) —
only meaningful for the body kinds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Tuple, TypeVar

#: Decoration preserves the wrapped callable's exact type (the strict-
#: typed ``models``/``ops`` surfaces must not erase to bare Callable).
_F = TypeVar("_F", bound=Callable)

#: Valid ``kind`` values, in the order the audit reports them.
ENTRY_KINDS = (
    "pallas_kernel",
    "integer_stage",
    "fused_body",
    "sharded_body",
)

#: Valid ``stages`` members (see ``tools/graftaudit/stages.py`` for the
#: source-module marker sets each one maps to).
PIPELINE_STAGES = ("expand", "hash", "membership")


@dataclass(frozen=True)
class AuditedEntry:
    """One registered audit target (metadata only — no example inputs)."""

    name: str
    fn: Callable
    kind: str
    #: Pipeline stages that must survive XLA optimization (body kinds).
    stages: Tuple[str, ...] = ()
    #: Key into ``KERNEL_BUDGETS.json`` when this entry also anchors an
    #: op-count budget family (pallas kernels; the harness may register
    #: several budget configs per entry).
    budget_keys: Tuple[str, ...] = ()
    module: str = ""
    qualname: str = ""


#: name -> entry; populated by decoration at module import.
AUDIT_REGISTRY: Dict[str, AuditedEntry] = {}


def audited_entry(
    name: str,
    *,
    kind: str,
    stages: Tuple[str, ...] = (),
    budget_keys: Tuple[str, ...] = (),
) -> Callable[[_F], _F]:
    """Register the decorated callable as a semantic-audit entry point.

    Pure bookkeeping: the callable is returned unchanged (zero runtime
    overhead on the hot path), and duplicate names raise at import time
    so two kernels can never silently shadow one audit slot.
    """
    if kind not in ENTRY_KINDS:
        raise ValueError(
            f"audited_entry {name!r}: unknown kind {kind!r}; "
            f"one of {ENTRY_KINDS}"
        )
    for stage in stages:
        if stage not in PIPELINE_STAGES:
            raise ValueError(
                f"audited_entry {name!r}: unknown stage {stage!r}; "
                f"members must be in {PIPELINE_STAGES}"
            )

    def deco(fn: _F) -> _F:
        existing = AUDIT_REGISTRY.get(name)
        if existing is not None and (
            existing.module != fn.__module__
            or existing.qualname != fn.__qualname__
        ):
            raise ValueError(
                f"audited_entry {name!r} registered twice "
                f"({existing.module}.{existing.qualname} and "
                f"{fn.__module__}.{fn.__qualname__})"
            )
        # Same module+qualname: idempotent re-registration, so
        # importlib.reload of an audited module (a pattern the test
        # suite uses) refreshes the entry instead of raising.
        AUDIT_REGISTRY[name] = AuditedEntry(
            name=name,
            fn=fn,
            kind=kind,
            stages=tuple(stages),
            budget_keys=tuple(budget_keys),
            module=fn.__module__,
            qualname=fn.__qualname__,
        )
        return fn

    return deco


def registered_entries() -> Dict[str, AuditedEntry]:
    """Snapshot of the registry (import the audited modules first — the
    audit driver does; see ``tools/graftaudit/harness.py``)."""
    return dict(AUDIT_REGISTRY)

"""Fused end-to-end attack pipelines (expand -> hash -> membership)."""

from .attack import (  # noqa: F401
    AttackSpec,
    block_arrays,
    build_plan,
    digest_arrays,
    make_candidates_step,
    make_crack_step,
    pack_bits,
    plan_arrays,
    table_arrays,
    unpack_bits,
)

"""The flagship pipeline: fused expand -> hash -> digest-membership steps.

The reference's whole runtime is "generate candidates, write to stdout, let
hashcat hash and match" (``main.go:58-99`` + ``README.MD:69``). On TPU the
three stages run as ONE jitted program per block batch, so candidate bytes
never leave the device: mixed-radix decode + splice (``ops.expand_matches`` /
``ops.expand_suball``), uint32-lane MD5/SHA1/MD4/NTLM (``ops.hashes``), and
bitmap + binary-search membership (``ops.membership``). Only two scalars and
two small masks come back per launch — XLA fuses the elementwise chain, and
the minor arrays (tables, plans, digest rows) ride along as device residents.

Two step flavors:

* :func:`make_crack_step` — expand, hash, match; returns a packed per-lane
  hit bitmask plus counts. Hits are *rare*, so the host re-derives hit
  candidate bytes from (block, rank) cursors via :func:`decode_variant`
  instead of shipping the full candidate buffer back — and per-lane
  word/emit arrays never leave the device at all.
* :func:`make_candidates_step` — expand only; returns the candidate buffer
  for the stdout sink (the reference-compatible mode; device->host copy is
  the price of emitting every candidate, exactly like the reference's
  channel->stdout funnel at ``main.go:58-68``).

All step builders return **jitted functions of device-array pytrees**; the
``*_arrays`` helpers convert host plan/table/digest objects into those
pytrees once per sweep.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..audit import audited_entry
from ..ops.blocks import BlockBatch, pad_batch
from ..ops.expand_matches import MatchPlan, build_match_plan, expand_matches
from ..ops.expand_suball import SubAllPlan, build_suball_plan, expand_suball
from ..ops.hashes import HASH_FNS
from ..ops.membership import DigestSet, digest_member
from ..ops.packing import PackedWords
from ..tables.compile import CompiledTable

#: Host plan objects (mode-dispatched) and device-pytree aliases.
Plan = Union[MatchPlan, SubAllPlan]
ArrayTree = Dict[str, jnp.ndarray]

#: The four reference generation modes (``main.go:80-92``).
MODES = ("default", "reverse", "suball", "suball-reverse")


@dataclass(frozen=True)
class AttackSpec:
    """Static attack configuration — everything that shapes the compiled
    program (mode/algo pick the kernel graph; the window is baked in)."""

    mode: str = "default"
    algo: str = "md5"
    min_substitute: int = 0
    max_substitute: int = 15

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"unknown mode {self.mode!r}; one of {MODES}")
        if self.algo not in HASH_FNS:
            raise ValueError(
                f"unknown algo {self.algo!r}; one of {tuple(HASH_FNS)}"
            )

    @property
    def effective_min(self) -> int:
        """Default mode silently bumps ``min 0 -> 1`` (Q1, main.go:169-171);
        every other mode emits the original word at ``min == 0``."""
        if self.mode == "default":
            return max(1, self.min_substitute)
        return self.min_substitute


def build_plan(
    spec: AttackSpec, ct: CompiledTable, packed: PackedWords, **kwargs: Any
) -> Plan:
    """Mode-dispatched host plan construction.

    Match plans get the spec's EFFECTIVE window so a tight ``-m/-x`` can
    switch to count-windowed enumeration (``expand_matches.build_match_plan``)
    instead of masking the full mixed-radix space.
    """
    if spec.mode in ("default", "reverse"):
        return build_match_plan(
            ct, packed, first_option_only=spec.mode == "reverse",
            min_substitute=spec.effective_min,
            max_substitute=spec.max_substitute, **kwargs
        )
    return build_suball_plan(
        ct, packed, first_option_only=spec.mode == "suball-reverse",
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute, **kwargs
    )


# ---------------------------------------------------------------------------
# Host object -> device pytree converters
# ---------------------------------------------------------------------------


def table_arrays(ct: CompiledTable) -> Dict[str, jnp.ndarray]:
    return {
        "val_bytes": jnp.asarray(ct.val_bytes),
        "val_len": jnp.asarray(ct.val_len),
    }


def plan_array_keys(plan: Plan) -> Tuple[str, ...]:
    """The plan fields :func:`plan_arrays` ships to device, in order —
    exposed so host-side consumers (the cross-job fuse layer's
    compatibility signatures and row concatenation, PERF.md §22) can
    walk the SAME field set without materializing device buffers."""
    if isinstance(plan, MatchPlan):
        keys = ("tokens", "lengths", "match_pos", "match_len", "match_radix",
                "match_val_start")
        if plan.windowed:
            keys = keys + ("win_v",)
    elif isinstance(plan, SubAllPlan):
        keys = ("tokens", "lengths", "pat_radix", "pat_val_start",
                "seg_orig_start", "seg_orig_len", "seg_pat")
        if plan.windowed:
            keys = keys + ("win_v",)
        if plan.close_next is not None:
            # Cascade-closed plans carry their own value table (compiled
            # rows + closed-cascade rows) and the joint-index fields; the
            # kernels use cval_* INSTEAD of table_arrays' val_*.
            keys = keys + ("close_next", "close_mul",
                           "cval_bytes", "cval_len")
    else:
        raise TypeError(f"unknown plan type {type(plan)!r}")
    return keys


def plan_arrays(plan: Plan) -> Dict[str, jnp.ndarray]:
    return {k: jnp.asarray(getattr(plan, k)) for k in plan_array_keys(plan)}


def block_arrays(
    batch: BlockBatch, *, num_blocks: int | None = None
) -> Dict[str, jnp.ndarray]:
    """Device pytree of a block batch; ``num_blocks`` pads to a static block
    count so repeated launches keep one compiled program (pass the same value
    as ``make_blocks(..., max_blocks=...)``)."""
    if num_blocks is not None:
        batch = pad_batch(batch, num_blocks)
    return {
        "word": jnp.asarray(batch.word),
        "base": jnp.asarray(batch.base_digits),
        "count": jnp.asarray(batch.count),
        "offset": jnp.asarray(batch.offset),
    }


def digest_arrays(ds: DigestSet) -> Dict[str, jnp.ndarray]:
    return {"rows": jnp.asarray(ds.rows), "bitmap": jnp.asarray(ds.bitmap)}


def _expand(
    spec: AttackSpec, plan: ArrayTree, table: ArrayTree, blocks: ArrayTree,
    *, num_lanes: int, out_width: int, block_stride: "int | None" = None,
    radix2: bool = False, pieces=None, pair_k: "int | None" = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Trace-time kernel dispatch; returns (cand, cand_len, word_row, emit).

    ``radix2`` (static): all plan radices <= 2 (``k_opts == 1``) — the
    decode collapses to bit extraction (``expand_matches.decode_digits``).
    ``pieces`` (static): the plan's ``packing.PieceSchema`` — selects the
    per-slot piece splice (PERF.md §17); device tables ride the plan dict
    (``pp_*``, :func:`piece_arrays`).
    ``pair_k`` (static): the pair-lane tier (K=2, PERF.md §24) — blocks
    then cover ``pair_k * block_stride`` candidate ranks and every
    returned array has ``pair_k * num_lanes`` candidate rows
    (rank ``= pair_k * r + p``); gate via ``pallas_expand.pair_for``.
    """
    common = dict(
        num_lanes=num_lanes,
        out_width=out_width,
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute,
        block_stride=block_stride,
        radix2=radix2,
        pieces=pieces,
        pair_k=pair_k,
        piece_tables=(
            {k[3:]: v for k, v in plan.items() if k.startswith("pp_")}
            or None
        ) if pieces is not None else None,
    )
    if spec.mode in ("default", "reverse"):
        return expand_matches(
            plan["tokens"], plan["lengths"], plan["match_pos"],
            plan["match_len"], plan["match_radix"], plan["match_val_start"],
            table["val_bytes"], table["val_len"],
            blocks["word"], blocks["base"], blocks["count"], blocks["offset"],
            win_v=plan.get("win_v"),
            **common,
        )
    return expand_suball(
        plan["tokens"], plan["lengths"], plan["pat_radix"],
        plan["pat_val_start"], plan["seg_orig_start"], plan["seg_orig_len"],
        plan["seg_pat"],
        plan.get("cval_bytes", table["val_bytes"]),
        plan.get("cval_len", table["val_len"]),
        blocks["word"], blocks["base"], blocks["count"], blocks["offset"],
        win_v=plan.get("win_v"),
        close_next=plan.get("close_next"), close_mul=plan.get("close_mul"),
        **common,
    )


def pack_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """Pack a bool ``[N]`` lane mask into ``uint32[ceil(N/32)]`` (lane
    ``i*32+j`` -> bit ``j`` of word ``i``). The crack step returns hits in
    this form: a launch's per-lane outputs are its dominant device->host
    payload (~12 MB of masks at 2^21 lanes), and over the remote-device
    tunnel that transfer costs more than the launch's compute — 32x smaller
    outputs keep the launch loop device-bound. Decode with
    :func:`unpack_bits`."""
    n = mask.shape[0]
    nw = -(-n // 32)
    padded = jnp.pad(mask.astype(jnp.uint32), (0, nw * 32 - n))
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :]
    return jnp.sum(padded.reshape(nw, 32) << shifts, axis=1,
                   dtype=jnp.uint32)


def unpack_bits(bits: np.ndarray, num_lanes: int) -> np.ndarray:
    """Host inverse of :func:`pack_bits`: ``uint32[ceil(N/32)] -> bool[N]``."""
    raw = np.ascontiguousarray(np.asarray(bits))
    if raw.dtype != np.uint32:
        raise TypeError(f"expected uint32 bit words, got {raw.dtype}")
    bytes_ = raw.view(np.uint8)
    if sys.byteorder != "little":  # pragma: no cover - TPU hosts are LE
        bytes_ = raw.byteswap().view(np.uint8)
    return np.unpackbits(bytes_, bitorder="little")[:num_lanes].astype(bool)


def scalar_units_host_tables(plan: Plan, ct: CompiledTable
                             ) -> Dict[str, np.ndarray]:
    """``pallas_expand.scalar_units_fields`` as HOST arrays under their
    plan-dict names (``su_*``) — the one naming map, shared by
    :func:`scalar_units_arrays` (which device-puts them) and the
    cross-job fuse layer (which signatures and concatenates them
    host-side, PERF.md §28).  All fields are batch-leading and carry
    value WORDS inline (never table indices), so compatible tenants'
    rows concatenate like the plan arrays with no base shifting.
    Empty when the plan doesn't qualify."""
    from ..ops.pallas_expand import scalar_units_fields

    fields = scalar_units_fields(plan, ct)
    if not fields:
        return {}
    return {f"su_{k}": np.asarray(v) for k, v in fields.items()}


def scalar_units_arrays(plan: Plan, ct: CompiledTable) -> Dict[str, jnp.ndarray]:
    """Device copies of ``pallas_expand.scalar_units_fields``, namespaced
    for the plan dict (``su_*``).  Callers merge them into
    :func:`plan_arrays`' output when the fused kernel may take launches:
    the wrappers then replace their per-launch [NB, M, L] precompute with
    word-row gathers (PERF.md §12).  Empty when the plan doesn't qualify
    — the plan dict's pytree structure stays stable per sweep."""
    return {
        k: jnp.asarray(v)
        for k, v in scalar_units_host_tables(plan, ct).items()
    }


def piece_host_tables(pieces) -> Dict[str, np.ndarray]:
    """A ``packing.PieceSchema``'s data tables as HOST arrays under
    their plan-dict names (``pp_*``) — the one naming map, shared by
    :func:`piece_arrays` (which device-puts them) and the cross-job
    fuse layer (which signatures and concatenates them host-side,
    PERF.md §22)."""
    if pieces is None:
        return {}
    out = {}
    if pieces.gl is not None:
        out["pp_pl"] = pieces.gl
    if pieces.gw is not None:
        out["pp_pw"] = pieces.gw
    if pieces.gw16 is not None:
        out["pp_pw16"] = pieces.gw16
    if pieces.sel_bit is not None:
        out["pp_sbit"] = pieces.sel_bit
    if pieces.sel_slot is not None:
        out["pp_sslot"] = pieces.sel_slot
    return out


def piece_arrays(pieces) -> Dict[str, jnp.ndarray]:
    """Device copies of a ``packing.PieceSchema``'s data tables,
    namespaced for the plan dict (``pp_*``) like
    :func:`scalar_units_arrays` — shipped once per sweep so the wrappers
    and the XLA splice prep launches with row gathers only."""
    return {
        k: jnp.asarray(v) for k, v in piece_host_tables(pieces).items()
    }


def make_fused_lane_body(
    spec: AttackSpec, *, num_lanes: int, out_width: int,
    block_stride: int | None = None,
    fused_expand_opts: int | None = None,
    fused_scalar_units: bool = False,
    radix2: bool = False,
    pieces=None,
    n_seg: int | None = None,
    pair_k: int | None = None,
) -> Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]:
    """The lane-level fused expand->hash->match core.

    ``lane_body(plan, table, digests, blocks) -> (hit bool[N], emit
    bool[N])`` — shared by :func:`make_fused_body` (which packs the hit
    mask into the per-launch fetch contract) and the superstep executor
    (:func:`make_superstep_step`, which consumes raw lane masks on device
    and never ships them to the host).  Knob semantics are
    :func:`make_fused_body`'s.

    ``n_seg`` (static): the cross-job packed dispatch (PERF.md §22) —
    the lane axis is partitioned into ``n_seg`` equal contiguous
    job-segment spans, and each lane's digest is tested against its own
    segment's target set via :func:`ops.membership.digest_member_seg`
    (``digests`` then carries the stacked per-segment
    rows/bitmap/row_lo/row_hi).  Everything before membership is
    per-lane arithmetic over the packed plan rows, so segmentation
    changes nothing there.

    ``pair_k`` (static): the pair-lane tier (PERF.md §24) — each lane
    carries ``pair_k`` (= 2) consecutive candidate ranks, so the body's
    hit/emit masks cover ``pair_k * num_lanes`` candidates (rank
    ``= 2r + p`` at row ``2r + p``) and membership simply runs over the
    doubled candidate axis.  Gate via ``pallas_expand.pair_for``.
    """
    from ..ops.pallas_md5 import maybe_pallas_hash_fn

    # A5GEN_PALLAS=1 on a TPU backend swaps in the VMEM-resident Pallas MD5
    # compression (ops.pallas_md5; falls back per-geometry) — selected at
    # trace-build time, so the flag picks the compiled program.
    hash_fn = maybe_pallas_hash_fn(spec.algo, HASH_FNS[spec.algo])

    def expand_and_hash(
        plan: ArrayTree, table: ArrayTree, blocks: ArrayTree
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        if fused_expand_opts is not None:
            from ..ops.pallas_expand import (
                fused_expand_md5,
                fused_expand_suball_md5,
            )

            common = dict(
                num_lanes=num_lanes, out_width=out_width,
                min_substitute=spec.effective_min,
                max_substitute=spec.max_substitute,
                block_stride=block_stride, k_opts=fused_expand_opts,
                scalar_units=fused_scalar_units,
                pair=pair_k is not None,
                # su_*/pp_* entries (scalar_units_arrays/piece_arrays):
                # word-level fields precomputed once per sweep; the
                # wrapper preps by gathering.
                pre={k[3:]: v for k, v in plan.items()
                     if k.startswith(("su_", "pp_"))} or None,
                # Per-slot piece emission (PERF.md §17): the schema is
                # static trace structure, its tables ride `pre`.
                pieces=pieces,
                algo=spec.algo,
                # Count-windowed plans carry win_v; the kernel walks the
                # suffix-count DP in place of the mixed-radix decode.
                win_v=plan.get("win_v"),
            )
            if spec.mode in ("default", "reverse"):
                return fused_expand_md5(
                    plan["tokens"], plan["lengths"], plan["match_pos"],
                    plan["match_len"], plan["match_radix"],
                    plan["match_val_start"],
                    table["val_bytes"], table["val_len"],
                    blocks["word"], blocks["base"], blocks["count"],
                    **common,
                )
            return fused_expand_suball_md5(
                plan["tokens"], plan["lengths"], plan["pat_radix"],
                plan["pat_val_start"], plan["seg_orig_start"],
                plan["seg_orig_len"], plan["seg_pat"],
                plan.get("cval_bytes", table["val_bytes"]),
                plan.get("cval_len", table["val_len"]),
                blocks["word"], blocks["base"], blocks["count"],
                close_next=plan.get("close_next"),
                close_mul=plan.get("close_mul"),
                **common,
            )
        cand, cand_len, word_row, emit = _expand(
            spec, plan, table, blocks, num_lanes=num_lanes,
            out_width=out_width, block_stride=block_stride, radix2=radix2,
            pieces=pieces, pair_k=pair_k,
        )
        del word_row  # hit cursors are host-derived from lane indices
        return hash_fn(cand, cand_len), emit

    if n_seg is not None and num_lanes % n_seg:
        raise ValueError(
            f"packed lane axis ({num_lanes}) must divide into n_seg "
            f"({n_seg}) equal segment spans"
        )
    #: candidate rows per launch — the lane axis × the pair multiplier.
    num_cands = num_lanes * (pair_k or 1)

    def lane_body(
        plan: ArrayTree, table: ArrayTree, digests: ArrayTree,
        blocks: ArrayTree,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        state, emit = expand_and_hash(plan, table, blocks)
        if n_seg is None:
            member = digest_member(state, digests["rows"],
                                   digests["bitmap"])
        else:
            from ..ops.membership import digest_member_seg

            seg = (
                jnp.arange(num_cands, dtype=jnp.int32)
                // jnp.int32(num_cands // n_seg)
            )
            member = digest_member_seg(
                state, digests["rows"], digests["bitmap"],
                digests["row_lo"], digests["row_hi"], seg,
            )
        return member & emit, emit

    return lane_body


@audited_entry(
    "models.make_fused_body",
    kind="fused_body",
    stages=("expand", "hash", "membership"),
)
def make_fused_body(spec: AttackSpec, *, num_lanes: int, out_width: int,
                    block_stride: int | None = None,
                    fused_expand_opts: int | None = None,
                    fused_scalar_units: bool = False,
                    radix2: bool = False,
                    pieces=None,
                    pair_k: int | None = None) -> Callable[..., ArrayTree]:
    """The un-jitted fused expand->hash->match body, shared by the
    single-device step and the shard_map'd step (which psums the counts).

    ``body(plan, table, digests, blocks) -> dict`` with the packed per-lane
    hit mask ``hit_bits`` (``uint32[ceil(lanes/32)]``, see
    :func:`pack_bits`) and *local* scalar counts ``n_emitted``/``n_hits``.
    Hit word/rank cursors are host-derived from lane indices
    (:func:`lane_cursor`), so lanes are the only per-hit payload.

    ``block_stride``: static lanes-per-block for fixed-stride batches
    (``make_blocks(fixed_stride=...)``) — the TPU fast path; ``None`` keeps
    the variable-offset layout.

    ``fused_expand_opts``: static per-key option count K enabling the fused
    Pallas decode+splice+MD5 kernel (``ops.pallas_expand``) in place of the
    XLA expand+hash pair. Callers gate via ``pallas_expand.opts_for`` —
    eligibility is a plan/table property this builder cannot see.

    ``fused_scalar_units``: selects the fused kernel's K=1 scalar-units
    fast path (PERF.md §11). Callers gate via
    ``pallas_expand.scalar_units_for`` — the unique-start property lives
    on the host plan.
    """
    lane_body = make_fused_lane_body(
        spec, num_lanes=num_lanes, out_width=out_width,
        block_stride=block_stride, fused_expand_opts=fused_expand_opts,
        fused_scalar_units=fused_scalar_units, radix2=radix2,
        pieces=pieces, pair_k=pair_k,
    )

    def body(
        plan: ArrayTree, table: ArrayTree, digests: ArrayTree,
        blocks: ArrayTree,
    ) -> ArrayTree:
        hit, emit = lane_body(plan, table, digests, blocks)
        return {
            "hit_bits": pack_bits(hit),
            "n_emitted": jnp.sum(emit.astype(jnp.int32)),
            "n_hits": jnp.sum(hit.astype(jnp.int32)),
        }

    return body


def superstep_arrays(plan: Plan, stride: int,
                     idx: "tuple | None" = None) -> "ArrayTree | None":
    """Device copies of the fixed-stride block index for the superstep
    executor's ON-DEVICE block cutter (``ops.blocks.superstep_index``
    narrowed to int32), shipped ONCE per sweep like ``plan_arrays``:

    * ``cum`` int32 [B+1] — cumulative block index (fallback and finished
      words occupy zero width, exactly as the host fast cutter sees them),
    * ``totals`` int32 [B] — per-word variant totals,
    * ``radix`` int32 [B, P] — per-slot radices for the device-side
      mixed-radix base decompose (unused by windowed plans, whose block
      bases are scalar ranks),
    * ``total`` int32 [] — the sweep's block count, carried as DATA so
      sweeps of different sizes (streaming chunks, PERF.md §19) share
      one compiled superstep program instead of baking the bound into
      the trace.

    Returns None when the plan cannot be cut in int32 on device (huge
    words / cursor overflow) — callers then keep the per-launch path.
    ``idx``: a precomputed ``ops.blocks.superstep_index`` result, so a
    caller that already built the host index (the sweep runtime — per
    CHUNK on the streaming worker thread) doesn't pay the O(batch)
    cumulative build twice.
    """
    from ..ops.blocks import superstep_index

    if idx is None:
        idx = superstep_index(plan, stride)
    if idx is None:
        return None
    cum, totals, total_blocks = idx
    return {
        "cum": jnp.asarray(cum),
        "totals": jnp.asarray(totals),
        "radix": jnp.asarray(np.asarray(plan.pat_radix, dtype=np.int32)),
        "total": jnp.asarray(np.int32(total_blocks)),
    }


def packed_superstep_arrays(
    plans: Sequence[Plan], idxs: Sequence[tuple],
) -> "tuple[ArrayTree, np.ndarray, np.ndarray] | None":
    """Device copies of SEVERAL plans' block indexes fused into one
    packed superstep index (PERF.md §22) — the per-segment job-row twin
    of :func:`superstep_arrays`.  ``idxs`` are the plans'
    ``ops.blocks.superstep_index`` results (one per job, same stride).

    The returned tree replaces the solo ``total`` bound with per-segment
    ``seg_end`` rows (job ``j``'s blocks end at ``seg_end[j]``, carried
    as DATA), and the cutter arrays cover the concatenated packed row
    space; ``radix`` requires every plan to agree on ``num_slots``
    (packed-group eligibility, enforced by the fuse layer).  Returns
    ``(ss tree, blk_base int64[S+1], row_base int64[S+1])`` — the host
    keeps the bases to map packed rows/blocks back to per-job ones — or
    ``None`` when the packed index would overflow int32.
    """
    from ..ops.blocks import packed_block_index

    packed = packed_block_index(idxs)
    if packed is None:
        return None
    cum, totals, blk_base, row_base, seg_end = packed
    radix = np.concatenate(
        [np.asarray(p.pat_radix, dtype=np.int32) for p in plans]
    )
    ss = {
        "cum": jnp.asarray(cum),
        "totals": jnp.asarray(totals),
        "radix": jnp.asarray(radix),
        "seg_end": jnp.asarray(seg_end),
    }
    return ss, blk_base, row_base


@audited_entry(
    "models.make_superstep_body",
    kind="fused_body",
    stages=("expand", "hash", "membership"),
)
def make_superstep_body(
    spec: AttackSpec, *, num_lanes: int, out_width: int, block_stride: int,
    num_blocks: int, steps: int, hit_cap: int, total_blocks: int,
    windowed: bool = False, step_advance: "int | None" = None,
    fused_expand_opts: int | None = None, fused_scalar_units: bool = False,
    radix2: bool = False, pieces=None, n_seg: int | None = None,
    pair_k: int | None = None,
) -> Callable[..., ArrayTree]:
    """The un-jitted superstep executor: ``steps`` fused
    expand->hash->membership launches in ONE device program, with the
    block cutting done on device (PERF.md §15).

    ``body(plan, table, digests, ss, b0, bufs) -> dict`` where ``ss`` is
    :func:`superstep_arrays`' tree, ``b0`` an int32 scalar — the global
    fixed-stride block index the superstep starts at — and ``bufs`` one
    of the driver's alternating device hit-buffer sets
    (``{"hit_word", "hit_rank"}`` int32 ``[hit_cap + 1]``; PERF.md §18).
    The scan's compacting scatter writes THIS superstep's hits into the
    incoming buffers (no in-body allocation or reset: the host reads
    only the first ``dev_hits`` entries, all freshly written, so stale
    tails are harmless), which lets the jit wrapper DONATE them — the
    pipelined driver cycles two sets so superstep N+1 can be dispatched
    into set B before set A's counters are fetched.  A ``lax.scan``
    carries the block cursor: each step cuts its ``num_blocks`` blocks
    from ``ss`` (searchsorted over the cumulative index + mixed-radix
    decompose — the device twin of ``ops.blocks``' vectorized host
    cutter), runs the fused lane body, and accumulates

    * ``counters`` int32 [2] — ``[n_emitted, n_hits]`` stacked so the
      driver's per-superstep completion barrier is ONE device→host
      fetch (the scalars also ride along unstacked for the bench and
      the sharded reducers; callers bound ``steps * num_lanes`` below
      2^31);
    * ``hit_word`` / ``hit_rank`` int32 [hit_cap + 1] — the donated
      buffers, hits compacted in cursor order (slot ``hit_cap`` is the
      trash slot).  Hits are RARE, so the scatter runs under a
      ``lax.cond`` only on steps whose hit count is nonzero; entries
      past ``hit_cap`` are dropped on device and the host detects the
      overflow from ``n_hits`` (``dev_hits``) and replays the superstep
      through the per-launch path — never a dropped hit.
    * ``dev_hits`` int32 [1] — this device's own hit count (the overflow
      test under ``shard_map``, where ``n_hits`` is the global psum).

    ``step_advance``: global blocks consumed per scan step —
    ``num_blocks`` on one device, ``num_blocks * n_devices`` under the
    sharded executor (every device advances past the whole launch), and
    ``num_blocks * total_stripes`` under the pod giant-job mode, where
    the lattice spans every process's devices (PERF.md §29).
    ``total_blocks``: blocks in the sweep; the tail superstep's
    out-of-range blocks cut zero-count (fully masked) blocks, so no tail
    special-casing exists anywhere.  When the ``ss`` tree carries the
    bound as data (``ss["total"]``, the post-§19 contract) this static
    value is only a fallback — sweeps of different length then share one
    compiled program (streaming chunk plans).

    ``n_seg``: the cross-job packed dispatch (PERF.md §22).  The block
    axis of every scan step is partitioned into ``n_seg`` equal
    contiguous job segments (``num_blocks // n_seg`` blocks each);
    ``b0`` becomes an int32 ``[n_seg]`` row of per-job packed block
    cursors, ``ss`` carries per-segment end bounds (``seg_end``,
    :func:`packed_superstep_arrays`) in place of ``total``, and the
    scan carry accumulates PER-SEGMENT counter rows — ``counters`` is
    int32 ``[2, n_seg]`` (row 0 emitted, row 1 hits, one column per
    job), so per-job counts survive the single per-superstep fetch and
    packed-vs-solo count parity holds by construction.  Hits land in
    the shared buffers tagged by their PACKED plan row (the host maps
    rows back to jobs via the fuse layer's row bases).  Membership runs
    per segment (:func:`ops.membership.digest_member_seg`) so no lane
    is ever tested against another tenant's digests.
    """
    lane_body = make_fused_lane_body(
        spec, num_lanes=num_lanes, out_width=out_width,
        block_stride=block_stride, fused_expand_opts=fused_expand_opts,
        fused_scalar_units=fused_scalar_units, radix2=radix2,
        pieces=pieces, n_seg=n_seg, pair_k=pair_k,
    )
    stride = block_stride
    # Pair-lane tier (PERF.md §24): a block's CANDIDATE rank span is
    # ``pair_k`` × its lane span — every rank cursor below walks in
    # rank_stride units while the launch geometry stays ``num_lanes``
    # lanes (hit ranks come back as true candidate ranks ``2r + p``).
    rank_stride = block_stride * (pair_k or 1)
    advance = int(step_advance or num_blocks)
    if n_seg is not None and num_blocks % n_seg:
        raise ValueError(
            f"packed dispatch needs num_blocks ({num_blocks}) divisible "
            f"by n_seg ({n_seg})"
        )
    if pair_k is not None and windowed:
        raise ValueError("the pair tier requires full enumeration")

    def cut_blocks(ss: ArrayTree, b0: jnp.ndarray):
        """One launch's blocks from the device-resident index: the exact
        arithmetic of ``ops.blocks._make_blocks_stride_fast`` in int32.
        Packed (``n_seg``): each job segment's blocks come from its own
        cursor row and stop at its own ``seg_end`` bound."""
        if n_seg is None:
            b = b0 + jnp.arange(num_blocks, dtype=jnp.int32)
        else:
            nbs = num_blocks // n_seg
            off = jnp.arange(num_blocks, dtype=jnp.int32)
            seg_of_block = off // jnp.int32(nbs)
            b = b0[seg_of_block] + (off - seg_of_block * jnp.int32(nbs))
        cum, totals = ss["cum"], ss["totals"]
        nwords = totals.shape[0]
        w = jnp.clip(
            jnp.searchsorted(cum, b, side="right").astype(jnp.int32) - 1,
            0, max(nwords - 1, 0),
        )
        # Blocks past the sweep's end keep count 0 (their lanes fail the
        # rank < count test, like pad_batch's padding); the where also
        # discards the wrapped int32 products out-of-range blocks compute.
        # The bound rides the ss tree as DATA (``superstep_arrays``), so
        # different-size sweeps — streaming chunks — reuse one compiled
        # program; ``total_blocks`` stays the static fallback for direct
        # callers with pre-§19 ss trees.  Packed dispatches bound each
        # segment by its own job's end instead.
        if n_seg is None:
            tot = ss.get("total")
            valid = b < (jnp.int32(total_blocks) if tot is None else tot)
        else:
            valid = b < ss["seg_end"][seg_of_block]
        rank0 = jnp.where(valid, (b - cum[w]) * jnp.int32(rank_stride), 0)
        count = jnp.where(
            valid, jnp.clip(totals[w] - rank0, 0, rank_stride), 0
        )
        p = ss["radix"].shape[1]
        if windowed:
            # Windowed plans cursor by scalar rank in slot 0 (the device
            # unranks through win_v), mirroring make_blocks.
            base = jnp.zeros((num_blocks, p), jnp.int32)
            base = base.at[:, 0].set(rank0)
        else:
            rad = ss["radix"][w]  # [NB, P]
            digs = []
            t = rank0
            for s in range(p):
                r = rad[:, s]
                digs.append(t % r)
                t = t // r
            base = jnp.stack(digs, axis=1)
        blocks = {
            "word": w,
            "base": base,
            "count": count,
            "offset": jnp.arange(num_blocks, dtype=jnp.int32)
            * jnp.int32(stride),
        }
        return blocks, rank0

    def body(
        plan: ArrayTree, table: ArrayTree, digests: ArrayTree,
        ss: ArrayTree, b0: jnp.ndarray, bufs: ArrayTree,
    ) -> ArrayTree:
        # Candidate-row axis: lanes × pair multiplier; ``lane_in`` is
        # the in-block CANDIDATE rank, so hit ranks are exact under the
        # pair tier (rank = rank0 + 2r + p).
        lane = jnp.arange(num_lanes * (pair_k or 1), dtype=jnp.int32)
        blk = lane // jnp.int32(rank_stride)
        lane_in = lane - blk * jnp.int32(rank_stride)

        def one(carry, _):
            b0c, ne, nh, hw, hr = carry
            blocks, rank0 = cut_blocks(ss, b0c)
            hit, emit = lane_body(plan, table, digests, blocks)
            if n_seg is None:
                ne_step = jnp.sum(emit.astype(jnp.int32))
                nh_step = jnp.sum(hit.astype(jnp.int32))
                nh_sofar = nh
                nh_any = nh_step
                b_adv = jnp.int32(advance)
            else:
                # Per-segment counter rows: each job's lanes are one
                # contiguous span, so the segment sums are a reshape.
                ne_step = jnp.sum(
                    emit.reshape(n_seg, -1).astype(jnp.int32), axis=1
                )
                nh_step = jnp.sum(
                    hit.reshape(n_seg, -1).astype(jnp.int32), axis=1
                )
                nh_sofar = jnp.sum(nh)
                nh_any = jnp.sum(nh_step)
                b_adv = jnp.int32(advance // n_seg)

            def record(bufs):
                hw0, hr0 = bufs
                # Compacting scatter: hit lanes land at consecutive
                # buffer slots in lane (= cursor) order; non-hit lanes
                # and overflow all target the trash slot [hit_cap].
                pos = nh_sofar + jnp.cumsum(hit.astype(jnp.int32)) - 1
                idx = jnp.where(
                    hit, jnp.minimum(pos, hit_cap), hit_cap
                )
                w_lane = blocks["word"][blk]
                r_lane = rank0[blk] + lane_in
                return hw0.at[idx].set(w_lane), hr0.at[idx].set(r_lane)

            hw, hr = jax.lax.cond(
                nh_any > 0, record, lambda bufs: bufs, (hw, hr)
            )
            carry = (
                b0c + b_adv,
                ne + ne_step,
                nh + nh_step,
                hw,
                hr,
            )
            return carry, None

        zero = (
            jnp.zeros((), jnp.int32) if n_seg is None
            else jnp.zeros((n_seg,), jnp.int32)
        )
        init = (
            jnp.asarray(b0, jnp.int32), zero, zero,
            bufs["hit_word"], bufs["hit_rank"],
        )
        (_, ne, nh, hw, hr), _ = jax.lax.scan(
            one, init, None, length=steps
        )
        if n_seg is None:
            counters, ne_tot, nh_tot = jnp.stack([ne, nh]), ne, nh
        else:
            counters = jnp.stack([ne, nh])  # [2, n_seg] — per-job rows
            ne_tot, nh_tot = jnp.sum(ne), jnp.sum(nh)
        return {
            "counters": counters,
            "n_emitted": ne_tot,
            "n_hits": nh_tot,
            "dev_hits": nh_tot[None],
            "hit_word": hw,
            "hit_rank": hr,
        }

    return body


def superstep_buffers(hit_cap: int) -> ArrayTree:
    """One device hit-buffer set for the superstep executor (slot
    ``hit_cap`` is the trash slot).  The pipelined driver allocates TWO
    and alternates them (PERF.md §18); contents never need resetting —
    the body's compacting scatter overwrites every entry the host will
    read."""
    return {
        "hit_word": jnp.full((hit_cap + 1,), -1, jnp.int32),
        "hit_rank": jnp.zeros((hit_cap + 1,), jnp.int32),
    }


def _buffer_donation() -> "tuple[int, ...]":
    """``donate_argnums`` for the superstep step's ``bufs`` argument:
    donation lets XLA alias each superstep's output hit buffers to the
    incoming set (true double buffering — no per-superstep allocation).
    The CPU backend does not implement donation and would warn on every
    compile, so only real accelerators request it; the driver's buffer
    cycling is semantically identical either way."""
    return () if jax.default_backend() == "cpu" else (5,)


def make_superstep_step(spec: AttackSpec, **kwargs: Any
                        ) -> Callable[..., ArrayTree]:
    """Jitted :func:`make_superstep_body` (single device).  ``step(plan,
    table, digests, ss, b0, bufs) -> dict``; pass ``b0`` as an int32
    scalar array so consecutive supersteps reuse one compiled program,
    and ``bufs`` one of the driver's alternating
    :func:`superstep_buffers` sets (donated off-CPU)."""
    return jax.jit(make_superstep_body(spec, **kwargs),
                   donate_argnums=_buffer_donation())


def make_crack_step(spec: AttackSpec, *, num_lanes: int, out_width: int,
                    block_stride: int | None = None,
                    fused_expand_opts: int | None = None,
                    fused_scalar_units: bool = False,
                    radix2: bool = False,
                    pieces=None) -> Callable[..., ArrayTree]:
    """Build the fused expand->hash->match step (single device).

    Returns ``step(plan, table, blocks, digests) -> dict`` with the packed
    hit bitmask ``hit_bits`` (:func:`pack_bits`) and scalar counts.
    """
    body = make_fused_body(spec, num_lanes=num_lanes, out_width=out_width,
                           block_stride=block_stride,
                           fused_expand_opts=fused_expand_opts,
                           fused_scalar_units=fused_scalar_units,
                           radix2=radix2, pieces=pieces)

    def step(
        plan: ArrayTree, table: ArrayTree, blocks: ArrayTree,
        digests: ArrayTree,
    ) -> ArrayTree:
        return body(plan, table, digests, blocks)

    return jax.jit(step)


def make_candidates_body(
    spec: AttackSpec, *, num_lanes: int, out_width: int,
    block_stride: "int | None" = None, radix2: bool = False,
    pieces=None,
) -> Callable[
    [ArrayTree, ArrayTree, ArrayTree],
    Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray],
]:
    """The un-jitted expand-only body, shared by the single-device
    candidates step and the shard_map'd candidates step.

    ``body(plan, table, blocks) -> (cand, cand_len, word_row, emit)``.
    """

    def body(
        plan: ArrayTree, table: ArrayTree, blocks: ArrayTree
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        return _expand(
            spec, plan, table, blocks, num_lanes=num_lanes,
            out_width=out_width, block_stride=block_stride, radix2=radix2,
            pieces=pieces,
        )

    return body


def make_candidates_step(
    spec: AttackSpec, *, num_lanes: int, out_width: int,
    block_stride: "int | None" = None, radix2: bool = False,
    pieces=None,
) -> Callable[
    [ArrayTree, ArrayTree, ArrayTree],
    Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray],
]:
    """Build the expand-only step for the stdout-candidates sink.

    Returns ``step(plan, table, blocks) -> (cand, cand_len, word_row, emit)``.
    """
    return jax.jit(
        make_candidates_body(spec, num_lanes=num_lanes, out_width=out_width,
                             block_stride=block_stride, radix2=radix2,
                             pieces=pieces)
    )


# ---------------------------------------------------------------------------
# Host-side variant decode (hit reporting)
# ---------------------------------------------------------------------------


def decode_variant(
    plan: Plan, ct: CompiledTable, spec: AttackSpec, word_idx: int, rank: int
) -> bytes:
    """Reconstruct the candidate bytes of one variant on the host.

    Hits come back as device lanes -> (word, variant rank) via
    :func:`lane_cursor`; this rebuilds the candidate exactly as the device
    kernels splice it. Raises ``ValueError`` for ranks the device would not
    emit (overlap clashes or count-window misses) — callers only pass ranks
    the device flagged.
    """
    radices = [int(r) for r in plan.pat_radix[word_idx]]
    if getattr(plan, "windowed", False):
        from ..ops.expand_matches import unrank_windowed

        digits = unrank_windowed(plan.win_v[word_idx], radices, rank)
    else:
        digits = []
        r = rank
        for radix in radices:
            digits.append(r % radix)
            r //= radix
        if r:
            raise ValueError(f"rank {rank} out of range for word {word_idx}")
    word = bytes(plan.tokens[word_idx, : plan.lengths[word_idx]])

    # Cascade-closed plans read from the plan's extended value table.
    cval = getattr(plan, "cval_bytes", None)
    val_bytes = ct.val_bytes if cval is None else cval
    val_lens = ct.val_len if cval is None else plan.cval_len

    def val(vrow: int) -> bytes:
        return bytes(val_bytes[vrow, : val_lens[vrow]])

    if isinstance(plan, MatchPlan):
        chosen = [
            (int(plan.match_pos[word_idx, s]), int(plan.match_len[word_idx, s]),
             int(plan.match_val_start[word_idx, s]) + d - 1)
            for s, d in enumerate(digits)
            if d > 0
        ]
        count = len(chosen)
        if not (spec.effective_min <= count <= spec.max_substitute):
            raise ValueError("variant outside the count window")
        out = []
        cursor = 0
        for pos, klen, vrow in sorted(chosen):
            if pos < cursor:
                raise ValueError("variant has overlapping matches")
            out.append(word[cursor:pos])
            out.append(val(vrow))
            cursor = pos + klen
        out.append(word[cursor:])
        return b"".join(out)

    # Substitute-all plans: walk the static segment list.
    count = sum(1 for s, d in enumerate(digits) if d > 0 and radices[s] > 1)
    if not (spec.effective_min <= count <= spec.max_substitute):
        raise ValueError("variant outside the count window")
    out = []
    close_next = getattr(plan, "close_next", None)
    for g in range(plan.num_segments):
        slot = int(plan.seg_pat[word_idx, g])
        start = int(plan.seg_orig_start[word_idx, g])
        length = int(plan.seg_orig_len[word_idx, g])
        if slot < 0 or digits[slot] == 0:
            out.append(word[start : start + length])
        else:
            jd = digits[slot] - 1
            if close_next is not None:
                # Joint closure index: own digit scaled by the successor
                # radix product, plus each successor's digit at its place.
                mul = plan.close_mul[word_idx, slot]
                jd = (digits[slot] - 1) * int(mul[0])
                for s_i in range(close_next.shape[2]):
                    nxt = int(close_next[word_idx, slot, s_i])
                    if nxt >= 0:
                        jd += digits[nxt] * int(mul[1 + s_i])
            vrow = int(plan.pat_val_start[word_idx, slot]) + jd
            out.append(val(vrow))
    return b"".join(out)


def lane_cursor(
    plan: Plan, batch: BlockBatch, lanes: Sequence[int]
) -> List[Tuple[int, int]]:
    """Map device lane indices back to (word_row, global variant rank).

    The block's ``base_digits`` encode its starting rank in the word's
    mixed-radix space; the global rank is that base plus the in-block rank.
    """
    offsets = batch.offset
    windowed = getattr(plan, "windowed", False)
    out = []
    for lane in lanes:
        blk = int(np.searchsorted(offsets, lane, side="right")) - 1
        rank_in_block = int(lane) - int(offsets[blk])
        w = int(batch.word[blk])
        if windowed:
            # Windowed blocks cursor by scalar rank in slot 0.
            base_rank = int(batch.base_digits[blk, 0])
        else:
            base_rank = 0
            scale = 1
            for s in range(plan.num_slots):
                base_rank += int(batch.base_digits[blk, s]) * scale
                scale *= int(plan.pat_radix[w, s])
        out.append((w, base_rank + rank_in_block))
    return out

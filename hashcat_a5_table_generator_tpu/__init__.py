"""tpu-a5: TPU-native substitution-attack candidate engine.

A brand-new, TPU-first framework with the capabilities of the reference
``A113L/hashcat_a5_table_generator`` (a Go CLI reimplementing hashcat-legacy's
``-a 5`` table-lookup attack as a standalone candidate generator): substitution
tables (``key=value`` lines, ``$HEX[]`` notation), four generation engines
(default / reverse / substitute-all / substitute-all-reverse), candidate
streaming — plus, beyond the reference, on-device Cartesian expansion, batched
MD5/SHA1/NTLM hashing and digest-set membership as fused JAX/XLA kernels with
the wordlist sharded across a TPU mesh.

Layer map (cf. SURVEY.md §1):
  tables/    — L0+L2: table parsing, merging, $HEX codec, layout emitters,
               compilation to dense device arrays
  oracle/    — L3 (CPU): byte-exact reference engines (the parity anchor)
  ops/       — L3 (TPU): expansion / hash / membership kernels
  models/    — fused end-to-end attack pipelines (expand→hash→membership)
  parallel/  — L5: mesh construction, shard_map pipelines, collectives
  runtime/   — sweep scheduler, cursors, checkpoint/resume, progress, sinks
  utils/     — shared helpers
  native/    — C++ host-side hot paths (wordlist packing) + ctypes bindings
"""

__version__ = "0.4.0"

from .tables.parser import (  # noqa: F401
    HexDecodeError,
    decode_hex_notation,
    merge_substitution_tables,
    parse_substitution_table,
    read_substitution_table,
)
from .oracle.engines import (  # noqa: F401
    ReferencePanic,
    iter_candidates,
    process_word,
    process_word_reverse,
    process_word_substitute_all,
    process_word_substitute_all_reverse,
)

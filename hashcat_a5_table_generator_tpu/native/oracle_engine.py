"""ctypes binding for the native default-mode oracle engine (oracle.cpp).

The reference's primary path (engine A, the recursive DFS) is the hot
loop of ``--backend oracle`` candidates mode; the Python generators are
the parity ANCHOR but cost ~4e5 candidates/s/core.  This binding streams
the identical byte stream from C++ at an order of magnitude more — and
falls back to the Python engine whenever the toolchain, the build, or
the mode doesn't fit (``A5_NATIVE=0`` forces the fallback, same knob as
the packer).

Scope: default mode only (no ``bug_compat`` concerns — Q3 is a
reverse-mode bug), raw byte output (``$HEX[]`` wrapping keeps the Python
path).  tests/test_native.py pins the stream byte-for-byte against
``oracle.engines.process_word`` across the quirk suite.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence

from ..runtime.env import env_str

_SRC = pathlib.Path(__file__).with_name("oracle.cpp")
_ABI = 4
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

_SINK_FN = ctypes.CFUNCTYPE(
    ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.c_void_p
)

#: Chunk granularity for the candidate stream callback.
_CHUNK_BYTES = 1 << 18


def _cache_dir() -> pathlib.Path:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return pathlib.Path(root) / "a5native"


def _build() -> Optional[pathlib.Path]:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"liba5oracle-{tag}.so"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        # c++20: heterogeneous unordered_map lookup (string_view probes
        # without a per-probe std::string allocation).
        "g++", "-O3", "-std=c++20", "-shared", "-fPIC",
        "-o", str(tmp), str(_SRC),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        print(
            f"a5native: oracle build failed ({e}); using the Python engine",
            file=sys.stderr,
        )
        return None
    os.replace(tmp, out)
    return out


def load() -> Optional[ctypes.CDLL]:
    """The native oracle library, building on first use; None => Python."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if env_str("A5_NATIVE", "1") == "0":
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:
        print(f"a5native: oracle load failed ({e}); using the Python engine",
              file=sys.stderr)
        return None
    if lib.a5_oracle_abi() != _ABI:
        print("a5native: oracle ABI mismatch; using the Python engine",
              file=sys.stderr)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.a5_oracle_table_new.argtypes = [
        u8p, i32p, ctypes.c_int32, u8p, i32p, i32p,
    ]
    lib.a5_oracle_table_new.restype = ctypes.c_void_p
    lib.a5_oracle_table_free.argtypes = [ctypes.c_void_p]
    lib.a5_oracle_table_free.restype = None
    lib.a5_oracle_process_word.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int64, _SINK_FN, ctypes.c_void_p,
    ]
    lib.a5_oracle_process_word.restype = ctypes.c_int64
    lib.a5_oracle_suball_word.argtypes = lib.a5_oracle_process_word.argtypes
    lib.a5_oracle_suball_word.restype = ctypes.c_int64
    lib.a5_oracle_suball_reverse_word.argtypes = (
        lib.a5_oracle_process_word.argtypes
    )
    lib.a5_oracle_suball_reverse_word.restype = ctypes.c_int64
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


#: Recursion in the C++ default engine is one frame per substitution;
#: cap the window so a pathological --table-max cannot blow the native
#: stack (the Python engine handles larger windows, failing with a clean
#: RecursionError where applicable).
MAX_NATIVE_SUBST = 512

#: The suball engine recurses once per PRESENT pattern — bound the table
#: size so pathological key counts keep the Python engine.
MAX_NATIVE_SUBALL_PATTERNS = 4096


def default_engine_eligible(
    sub_map: Dict[bytes, Sequence[bytes]],
    *,
    substitute_all: bool,
    reverse: bool,
    crack: bool,
    hex_unsafe: bool,
    max_substitute: int,
) -> bool:
    """The ONE eligibility predicate for the native candidate stream,
    shared by the CLI and the --threads workers (they must never drift:
    both paths must pick the same engine for the same input).  Default,
    substitute-all, or substitute-all-reverse mode (plain reverse —
    engine B — keeps Python: Q3 offset-bug modeling and panic
    semantics), candidates output, no $HEX[] wrapping
    (per-candidate inspection stays Python), bounded window (native
    stack: per-substitution frames in engine A, per-present-pattern
    frames in engines C/D), and no table value embedding line terminators
    (the stream counts candidates by newline).  Plain reverse (engine B)
    stays Python — it models the reference's Q3 offset bug and panic
    semantics, which belong in the anchor; suball-reverse (engine D) has
    no such bugs and is native."""
    return (
        not crack
        and not hex_unsafe
        and (not reverse or substitute_all)
        and 0 <= max_substitute <= MAX_NATIVE_SUBST
        and (not (substitute_all or reverse)
             or len(sub_map) <= MAX_NATIVE_SUBALL_PATTERNS)
        and all(
            b"\n" not in v and b"\r" not in v
            for vals in sub_map.values() for v in vals
        )
    )


class NativeDefaultOracle:
    """One compiled table, reusable across words (default engine only).

    ``stream_word(word, min_sub, max_sub, sink)`` calls ``sink(chunk)``
    with newline-terminated candidate chunks in exact engine-A order and
    returns the candidate count.
    """

    def __init__(self, sub_map: Dict[bytes, Sequence[bytes]]) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native oracle unavailable")
        self._lib = lib
        keys = list(sub_map.keys())
        keys_blob = b"".join(keys)
        key_lens = (ctypes.c_int32 * len(keys))(*[len(k) for k in keys])
        vals: List[bytes] = []
        val_start = [0]
        for k in keys:
            vals.extend(sub_map[k])
            val_start.append(len(vals))
        vals_blob = b"".join(vals)
        val_lens = (ctypes.c_int32 * max(1, len(vals)))(
            *([len(v) for v in vals] or [0])
        )
        starts = (ctypes.c_int32 * (len(keys) + 1))(*val_start)
        kb = (ctypes.c_uint8 * max(1, len(keys_blob))).from_buffer_copy(
            keys_blob or b"\0"
        )
        vb = (ctypes.c_uint8 * max(1, len(vals_blob))).from_buffer_copy(
            vals_blob or b"\0"
        )
        self._table = lib.a5_oracle_table_new(
            kb, key_lens, len(keys), vb, val_lens, starts
        )
        if not self._table:
            raise RuntimeError("native oracle table construction failed")

    def _stream(self, c_fn, word: bytes, min_sub: int, max_sub: int,
                sink: Callable[[bytes], None]) -> int:
        """Shared ctypes plumbing for both engines.

        ctypes callbacks cannot raise through the C frame: capture the
        sink's exception, tell the C++ loop to ABORT (nonzero return),
        and re-raise here — a BrokenPipeError/ENOSPC/interrupt must not
        silently truncate the stream while reporting success."""
        err: list = []

        def _cb(data, length, _ctx):
            try:
                sink(ctypes.string_at(data, length))
                return 0
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err.append(e)
                return 1

        cb = _SINK_FN(_cb)  # keep alive for the call's duration
        wb = (ctypes.c_uint8 * max(1, len(word))).from_buffer_copy(
            word or b"\0"
        )
        n = int(c_fn(
            self._table, wb, len(word), min_sub, max_sub,
            _CHUNK_BYTES, cb, None,
        ))
        if err:
            raise err[0]
        return n

    def stream_word(
        self,
        word: bytes,
        min_sub: int,
        max_sub: int,
        sink: Callable[[bytes], None],
    ) -> int:
        return self._stream(self._lib.a5_oracle_process_word, word,
                            min_sub, max_sub, sink)

    def stream_word_suball(
        self,
        word: bytes,
        min_sub: int,
        max_sub: int,
        sink: Callable[[bytes], None],
    ) -> int:
        """Engine C (substitute-all) stream — same contract as
        :meth:`stream_word`, mirroring
        ``engines.process_word_substitute_all`` byte-for-byte."""
        return self._stream(self._lib.a5_oracle_suball_word, word,
                            min_sub, max_sub, sink)

    def stream_word_suball_reverse(
        self,
        word: bytes,
        min_sub: int,
        max_sub: int,
        sink: Callable[[bytes], None],
    ) -> int:
        """Engine D (substitute-all reverse) stream, mirroring
        ``engines.process_word_substitute_all_reverse`` byte-for-byte
        (first option per pattern — Q2; subsets from the full set down)."""
        return self._stream(self._lib.a5_oracle_suball_reverse_word, word,
                            min_sub, max_sub, sink)

    def iter_word(self, word: bytes, min_sub: int, max_sub: int,
                  *, substitute_all: bool = False, reverse: bool = False):
        """LAZY per-candidate iterator over the native stream (the
        sweep's oracle-fallback path consumes candidates one by one).

        The C++ enumeration runs on a producer thread pushing chunks into
        a small bounded queue (ctypes releases the GIL during the C call,
        so producer and consumer genuinely overlap); closing the
        generator aborts the enumeration through the sink protocol — a
        huge hazard word neither buffers unboundedly nor outlives its
        consumer."""
        import queue as queue_mod
        import threading

        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=4)
        stop = threading.Event()
        DONE = object()

        class _Abort(BaseException):
            pass

        def sink(blob: bytes) -> None:
            while True:
                if stop.is_set():
                    raise _Abort()
                try:
                    q.put(blob, timeout=0.1)
                    return
                except queue_mod.Full:
                    continue

        if substitute_all and reverse:
            stream = self.stream_word_suball_reverse
        elif substitute_all:
            stream = self.stream_word_suball
        elif reverse:
            raise ValueError("plain reverse has no native engine")
        else:
            stream = self.stream_word

        def produce() -> None:
            try:
                stream(word, min_sub, max_sub, sink)
            except _Abort:
                pass
            except BaseException as e:  # noqa: BLE001 — re-raised below
                try:
                    q.put(e, timeout=5.0)
                except queue_mod.Full:
                    pass
            while True:  # DONE must land even against a full queue
                if stop.is_set():
                    return
                try:
                    q.put(DONE, timeout=0.1)
                    return
                except queue_mod.Full:
                    continue

        th = threading.Thread(target=produce, daemon=True,
                              name="a5-native-oracle")
        th.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield from item.split(b"\n")[:-1]
        finally:
            stop.set()
            while th.is_alive():  # drain so the producer can exit
                try:
                    q.get_nowait()
                except queue_mod.Empty:
                    pass
                th.join(timeout=0.05)

    def close(self) -> None:
        if getattr(self, "_table", None):
            self._lib.a5_oracle_table_free(self._table)
            self._table = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

// Native default-mode oracle engine (engine A): byte-exact, stream-order-
// exact reimplementation of oracle/engines.py::process_word — the
// reference's primary path (recursive DFS, longest-key-first probes,
// scan resumes past replacement text, min==0 bumped to 1 by the CALLER'S
// contract being preserved here too).  The Python oracle remains the
// parity anchor; tests/test_native.py pins this engine byte-for-byte
// against it (including duplicate multiplicity, Q7).
//
// C ABI + ctypes (no pybind11 in this environment); output streams
// through a chunk callback so candidate floods never materialize in one
// allocation.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const noexcept {
    return std::hash<std::string_view>{}(sv);
  }
  size_t operator()(const std::string& s) const noexcept {
    return std::hash<std::string_view>{}(std::string_view(s));
  }
};

struct Table {
  std::unordered_map<std::string, std::vector<std::string>, SvHash,
                     std::equal_to<>>
      map;
  // Keys in ascending byte order (== Python sorted(bytes)) — the
  // substitute-all engines enumerate and cascade in this order (Q4
  // canonicalization, mirroring engines.unique_patterns_in_word).
  std::vector<std::string> sorted_keys;
  size_t kmax = 0;
};

// Returns 0 to continue, nonzero to abort the enumeration (the Python
// side uses this to surface sink exceptions — ctypes callbacks cannot
// raise through the C frame, so a swallowed BrokenPipeError would
// otherwise run the whole candidate space and report success).
typedef int32_t (*a5_sink_fn)(const uint8_t* data, int64_t len, void* ctx);

struct Emit {
  std::string out;
  size_t chunk;
  a5_sink_fn sink;
  void* uctx;
  int64_t count = 0;
  bool aborted = false;

  void ship() {
    if (sink(reinterpret_cast<const uint8_t*>(out.data()),
             static_cast<int64_t>(out.size()), uctx) != 0)
      aborted = true;
    out.clear();
  }
  void line(const std::string& cand) {
    out.append(cand);
    out.push_back('\n');
    ++count;
    if (out.size() >= chunk) ship();
  }
  void flush() {
    if (!out.empty() && !aborted) ship();
  }
};

// Mirrors engines.process_word's inner generate(): for each position from
// `start`, probe key lengths longest-first; on a match splice each option,
// emit when the count is in [min, max], and recurse past the replacement.
void generate(const Table& t, Emit& e, const std::string& current, int count,
              size_t start, int min_sub, int max_sub) {
  if (e.aborted) return;
  const size_t n = current.size();
  for (size_t i = start; i < n; ++i) {
    size_t maxkl = n - i < t.kmax ? n - i : t.kmax;
    for (size_t kl = maxkl; kl >= 1; --kl) {
      auto it = t.map.find(std::string_view(current).substr(i, kl));
      if (it == t.map.end()) continue;
      for (const std::string& sub : it->second) {
        int nc = count + 1;
        if (nc > max_sub) continue;
        std::string nw;
        nw.reserve(n - kl + sub.size());
        nw.append(current, 0, i);
        nw.append(sub);
        nw.append(current, i + kl, n - i - kl);
        if (nc >= min_sub) e.line(nw);
        generate(t, e, nw, nc, i + sub.size(), min_sub, max_sub);
        if (e.aborted) return;
      }
    }
  }
}

// Python bytes.replace semantics, including the empty-pattern case
// (b"abc".replace(b"", b"X") == b"XaXbXcX") — the oracle engines' spec is
// the PYTHON anchor, which canonicalizes the reference's Go behavior.
std::string replace_all(const std::string& s, const std::string& pat,
                        const std::string& rep) {
  std::string out;
  if (pat.empty()) {
    out.reserve(s.size() + (s.size() + 1) * rep.size());
    out.append(rep);
    for (char c : s) {
      out.push_back(c);
      out.append(rep);
    }
    return out;
  }
  out.reserve(s.size());
  size_t pos = 0;
  while (true) {
    size_t hit = s.find(pat, pos);
    if (hit == std::string::npos) {
      out.append(s, pos, s.size() - pos);
      return out;
    }
    out.append(s, pos, hit - pos);
    out.append(rep);
    pos = hit + pat.size();
  }
}

struct SuballCtx {
  const std::string* word;
  const std::vector<const std::string*>* patterns;  // sorted, present
  const std::vector<const std::vector<std::string>*>* options;
  std::vector<const std::string*> chosen;  // per pattern, null = skip
  int min_sub, max_sub;
  Emit* e;
};

// Mirrors engines.process_word_substitute_all's generate(): options
// first (in table order), then skip; leaf emits the sorted-order
// ReplaceAll cascade when the chosen count is in [min, max].
void gen_suball(SuballCtx& c, size_t pos, int count) {
  if (c.e->aborted) return;
  if (pos >= c.patterns->size()) {
    if (count >= c.min_sub && count <= c.max_sub) {
      std::string result = *c.word;
      for (size_t p = 0; p < c.patterns->size(); ++p) {
        if (c.chosen[p] != nullptr)
          result = replace_all(result, *(*c.patterns)[p], *c.chosen[p]);
      }
      c.e->line(result);
    }
    return;
  }
  // Prune option branches that already exceed the window: count never
  // decreases along a path, so such subtrees cannot emit (identical
  // output to the unpruned Python anchor, exponentially less dead work
  // for tight windows over many patterns).
  if (count + 1 <= c.max_sub) {
    for (const std::string& sub : *(*c.options)[pos]) {
      c.chosen[pos] = &sub;
      gen_suball(c, pos + 1, count + 1);
      if (c.e->aborted) return;
    }
  }
  c.chosen[pos] = nullptr;
  gen_suball(c, pos + 1, count);
}

// Mirrors engines.process_word_substitute_all_reverse's
// generate_subsets(): emit the current subset when in-window, then
// remove each still-chosen pattern from `pos` upward and recurse —
// every subset visited exactly once, full set first.
struct SuballRevCtx {
  const std::string* word;
  const std::vector<const std::string*>* patterns;  // sorted, present
  const std::vector<const std::string*>* first_opt;  // per pattern or null
  std::vector<char> chosen;
  int min_sub, max_sub;
  Emit* e;
};

void gen_suball_rev(SuballRevCtx& c, size_t pos, int count) {
  if (c.e->aborted) return;
  if (count < c.min_sub) return;
  if (count <= c.max_sub) {
    std::string result = *c.word;
    for (size_t p = 0; p < c.patterns->size(); ++p) {
      if (c.chosen[p])
        result = replace_all(result, *(*c.patterns)[p], *(*c.first_opt)[p]);
    }
    c.e->line(result);
  }
  if (count <= c.min_sub) return;
  for (size_t i = pos; i < c.patterns->size(); ++i) {
    if (!c.chosen[i]) continue;
    c.chosen[i] = 0;
    gen_suball_rev(c, i + 1, count - 1);
    c.chosen[i] = 1;
    if (c.e->aborted) return;
  }
}

}  // namespace

extern "C" {

int32_t a5_oracle_abi() { return 4; }

// Flattened table: nk keys (keys_blob + key_lens), each key's options are
// value rows [val_start[k], val_start[k+1]) into (vals_blob + val_lens).
void* a5_oracle_table_new(const uint8_t* keys_blob, const int32_t* key_lens,
                          int32_t nk, const uint8_t* vals_blob,
                          const int32_t* val_lens,
                          const int32_t* val_start) {
  Table* t = new Table();
  std::vector<int64_t> voff(1, 0);
  int32_t nv = val_start[nk];
  for (int32_t v = 0; v < nv; ++v) voff.push_back(voff.back() + val_lens[v]);
  int64_t koff = 0;
  for (int32_t k = 0; k < nk; ++k) {
    std::string key(reinterpret_cast<const char*>(keys_blob) + koff,
                    static_cast<size_t>(key_lens[k]));
    koff += key_lens[k];
    std::vector<std::string> vals;
    for (int32_t v = val_start[k]; v < val_start[k + 1]; ++v) {
      vals.emplace_back(reinterpret_cast<const char*>(vals_blob) + voff[v],
                        static_cast<size_t>(val_lens[v]));
    }
    if (key.size() > t->kmax) t->kmax = key.size();
    t->sorted_keys.push_back(key);
    t->map.emplace(std::move(key), std::move(vals));
  }
  std::sort(t->sorted_keys.begin(), t->sorted_keys.end());
  return t;
}

void a5_oracle_table_free(void* table) { delete static_cast<Table*>(table); }

// Default engine over one word; candidates stream through `sink` as
// newline-terminated chunks (<= chunk_bytes + one candidate each).
// Returns the candidate count.  min==0 is bumped to 1 (Q1), matching
// engines.process_word.
int64_t a5_oracle_process_word(void* table, const uint8_t* word, int32_t wlen,
                               int32_t min_sub, int32_t max_sub,
                               int64_t chunk_bytes, a5_sink_fn sink,
                               void* ctx) {
  const Table& t = *static_cast<Table*>(table);
  if (min_sub == 0) min_sub = 1;
  Emit e{std::string(), static_cast<size_t>(chunk_bytes), sink, ctx};
  e.out.reserve(static_cast<size_t>(chunk_bytes) + 256);
  std::string w(reinterpret_cast<const char*>(word),
                static_cast<size_t>(wlen));
  if (t.kmax > 0) generate(t, e, w, 0, 0, min_sub, max_sub);
  e.flush();
  return e.count;
}

// Substitute-all engine over one word (engine C,
// engines.process_word_substitute_all): per unique PRESENT pattern
// (ascending byte order), choose one option or skip; leaves in-window
// emit the sorted-order ReplaceAll cascade.  No Q1 bump here — suball
// emits the original word at min == 0.
int64_t a5_oracle_suball_word(void* table, const uint8_t* word, int32_t wlen,
                              int32_t min_sub, int32_t max_sub,
                              int64_t chunk_bytes, a5_sink_fn sink,
                              void* ctx) {
  const Table& t = *static_cast<Table*>(table);
  Emit e{std::string(), static_cast<size_t>(chunk_bytes), sink, ctx};
  e.out.reserve(static_cast<size_t>(chunk_bytes) + 256);
  std::string w(reinterpret_cast<const char*>(word),
                static_cast<size_t>(wlen));
  // Present patterns, sorted (mirrors unique_patterns_in_word: an empty
  // key matches any non-empty word).
  std::vector<const std::string*> patterns;
  std::vector<const std::vector<std::string>*> options;
  for (const std::string& k : t.sorted_keys) {
    bool present = k.empty() ? !w.empty() : w.find(k) != std::string::npos;
    if (!present) continue;
    patterns.push_back(&k);
    options.push_back(&t.map.find(std::string_view(k))->second);
  }
  SuballCtx c{&w, &patterns, &options,
              std::vector<const std::string*>(patterns.size(), nullptr),
              min_sub, max_sub, &e};
  gen_suball(c, 0, 0);
  e.flush();
  return e.count;
}

// Substitute-all REVERSE engine (engine D,
// engines.process_word_substitute_all_reverse): start from every present
// pattern substituted with its FIRST option (Q2) and enumerate subsets
// down to the window floor.
int64_t a5_oracle_suball_reverse_word(void* table, const uint8_t* word,
                                      int32_t wlen, int32_t min_sub,
                                      int32_t max_sub, int64_t chunk_bytes,
                                      a5_sink_fn sink, void* ctx) {
  const Table& t = *static_cast<Table*>(table);
  Emit e{std::string(), static_cast<size_t>(chunk_bytes), sink, ctx};
  e.out.reserve(static_cast<size_t>(chunk_bytes) + 256);
  std::string w(reinterpret_cast<const char*>(word),
                static_cast<size_t>(wlen));
  std::vector<const std::string*> patterns;
  std::vector<const std::string*> first_opt;
  for (const std::string& k : t.sorted_keys) {
    bool present = k.empty() ? !w.empty() : w.find(k) != std::string::npos;
    if (!present) continue;
    patterns.push_back(&k);
    const auto& opts = t.map.find(std::string_view(k))->second;
    first_opt.push_back(opts.empty() ? nullptr : &opts[0]);
  }
  // Mirrors the Python early-return: fewer PRESENT patterns than the
  // window floor emits nothing (optionless patterns still count here).
  if (static_cast<int>(patterns.size()) >= min_sub) {
    int count0 = 0;
    std::vector<char> chosen(patterns.size(), 0);
    for (size_t p = 0; p < patterns.size(); ++p) {
      if (first_opt[p] != nullptr) {
        chosen[p] = 1;
        ++count0;
      }
    }
    SuballRevCtx c{&w, &patterns, &first_opt, std::move(chosen),
                   min_sub, max_sub, &e};
    gen_suball_rev(c, 0, count0);
  }
  e.flush();
  return e.count;
}

}  // extern "C"

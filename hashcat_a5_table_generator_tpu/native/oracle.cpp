// Native default-mode oracle engine (engine A): byte-exact, stream-order-
// exact reimplementation of oracle/engines.py::process_word — the
// reference's primary path (recursive DFS, longest-key-first probes,
// scan resumes past replacement text, min==0 bumped to 1 by the CALLER'S
// contract being preserved here too).  The Python oracle remains the
// parity anchor; tests/test_native.py pins this engine byte-for-byte
// against it (including duplicate multiplicity, Q7).
//
// C ABI + ctypes (no pybind11 in this environment); output streams
// through a chunk callback so candidate floods never materialize in one
// allocation.

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const noexcept {
    return std::hash<std::string_view>{}(sv);
  }
  size_t operator()(const std::string& s) const noexcept {
    return std::hash<std::string_view>{}(std::string_view(s));
  }
};

struct Table {
  std::unordered_map<std::string, std::vector<std::string>, SvHash,
                     std::equal_to<>>
      map;
  size_t kmax = 0;
};

// Returns 0 to continue, nonzero to abort the enumeration (the Python
// side uses this to surface sink exceptions — ctypes callbacks cannot
// raise through the C frame, so a swallowed BrokenPipeError would
// otherwise run the whole candidate space and report success).
typedef int32_t (*a5_sink_fn)(const uint8_t* data, int64_t len, void* ctx);

struct Emit {
  std::string out;
  size_t chunk;
  a5_sink_fn sink;
  void* uctx;
  int64_t count = 0;
  bool aborted = false;

  void ship() {
    if (sink(reinterpret_cast<const uint8_t*>(out.data()),
             static_cast<int64_t>(out.size()), uctx) != 0)
      aborted = true;
    out.clear();
  }
  void line(const std::string& cand) {
    out.append(cand);
    out.push_back('\n');
    ++count;
    if (out.size() >= chunk) ship();
  }
  void flush() {
    if (!out.empty() && !aborted) ship();
  }
};

// Mirrors engines.process_word's inner generate(): for each position from
// `start`, probe key lengths longest-first; on a match splice each option,
// emit when the count is in [min, max], and recurse past the replacement.
void generate(const Table& t, Emit& e, const std::string& current, int count,
              size_t start, int min_sub, int max_sub) {
  if (e.aborted) return;
  const size_t n = current.size();
  for (size_t i = start; i < n; ++i) {
    size_t maxkl = n - i < t.kmax ? n - i : t.kmax;
    for (size_t kl = maxkl; kl >= 1; --kl) {
      auto it = t.map.find(std::string_view(current).substr(i, kl));
      if (it == t.map.end()) continue;
      for (const std::string& sub : it->second) {
        int nc = count + 1;
        if (nc > max_sub) continue;
        std::string nw;
        nw.reserve(n - kl + sub.size());
        nw.append(current, 0, i);
        nw.append(sub);
        nw.append(current, i + kl, n - i - kl);
        if (nc >= min_sub) e.line(nw);
        generate(t, e, nw, nc, i + sub.size(), min_sub, max_sub);
        if (e.aborted) return;
      }
    }
  }
}

}  // namespace

extern "C" {

int32_t a5_oracle_abi() { return 2; }

// Flattened table: nk keys (keys_blob + key_lens), each key's options are
// value rows [val_start[k], val_start[k+1]) into (vals_blob + val_lens).
void* a5_oracle_table_new(const uint8_t* keys_blob, const int32_t* key_lens,
                          int32_t nk, const uint8_t* vals_blob,
                          const int32_t* val_lens,
                          const int32_t* val_start) {
  Table* t = new Table();
  std::vector<int64_t> voff(1, 0);
  int32_t nv = val_start[nk];
  for (int32_t v = 0; v < nv; ++v) voff.push_back(voff.back() + val_lens[v]);
  int64_t koff = 0;
  for (int32_t k = 0; k < nk; ++k) {
    std::string key(reinterpret_cast<const char*>(keys_blob) + koff,
                    static_cast<size_t>(key_lens[k]));
    koff += key_lens[k];
    std::vector<std::string> vals;
    for (int32_t v = val_start[k]; v < val_start[k + 1]; ++v) {
      vals.emplace_back(reinterpret_cast<const char*>(vals_blob) + voff[v],
                        static_cast<size_t>(val_lens[v]));
    }
    if (key.size() > t->kmax) t->kmax = key.size();
    t->map.emplace(std::move(key), std::move(vals));
  }
  return t;
}

void a5_oracle_table_free(void* table) { delete static_cast<Table*>(table); }

// Default engine over one word; candidates stream through `sink` as
// newline-terminated chunks (<= chunk_bytes + one candidate each).
// Returns the candidate count.  min==0 is bumped to 1 (Q1), matching
// engines.process_word.
int64_t a5_oracle_process_word(void* table, const uint8_t* word, int32_t wlen,
                               int32_t min_sub, int32_t max_sub,
                               int64_t chunk_bytes, a5_sink_fn sink,
                               void* ctx) {
  const Table& t = *static_cast<Table*>(table);
  if (min_sub == 0) min_sub = 1;
  Emit e{std::string(), static_cast<size_t>(chunk_bytes), sink, ctx};
  e.out.reserve(static_cast<size_t>(chunk_bytes) + 256);
  std::string w(reinterpret_cast<const char*>(word),
                static_cast<size_t>(wlen));
  if (t.kmax > 0) generate(t, e, w, 0, 0, min_sub, max_sub);
  e.flush();
  return e.count;
}

}  // extern "C"

// Host-side wordlist hot path: scan + pack, C ABI for ctypes.
//
// The reference's entire input layer is Go's bufio.Scanner feeding goroutines
// (main.go:70-94). Here the analogous hot path — splitting a rockyou-class
// dictionary into lines and packing them into fixed-width uint8 batches for
// device upload — runs as native code: one pass over the mmap'd file for
// line structure, one cache-friendly pass per width bucket for packing.
// Python (ops/packing.py) remains the reference implementation; outputs are
// bit-identical (contract-tested) and the Python path is the automatic
// fallback when this library is unavailable.
//
// Line semantics mirror bufio.ScanLines: split on '\n', drop one trailing
// '\r' per line, final unterminated line counts. Unlike the reference, an
// oversized line is an ERROR (-2), not a silent end of input (Q8).

#include <cstdint>
#include <cstddef>

extern "C" {

// Count lines in data[0..n). Returns the line count.
int64_t a5_count_lines(const uint8_t* data, int64_t n) {
    if (n == 0) return 0;
    int64_t lines = 0;
    for (int64_t i = 0; i < n; ++i) lines += (data[i] == '\n');
    if (data[n - 1] != '\n') ++lines;  // unterminated final line
    return lines;
}

// Scan line structure into offsets/lengths (caller sizes them via
// a5_count_lines). A line's payload excludes '\n' and one trailing '\r'.
// Returns 0 on success, or -2 with *bad_line set when a payload exceeds
// max_word (the anti-Q8 contract: surface, never truncate).
int32_t a5_scan_lines(const uint8_t* data, int64_t n, int64_t max_word,
                      int64_t* offsets, int32_t* lengths, int64_t* bad_line) {
    int64_t line = 0, start = 0;
    for (int64_t i = 0; i <= n; ++i) {
        bool eof_tail = (i == n && start < i);
        if (i < n ? (data[i] == '\n') : eof_tail) {
            int64_t len = i - start;
            if (len > 0 && data[start + len - 1] == '\r') --len;
            if (len > max_word) {
                if (bad_line) *bad_line = line;
                return -2;
            }
            offsets[line] = start;
            lengths[line] = static_cast<int32_t>(len);
            ++line;
            start = i + 1;
        }
    }
    return 0;
}

// Pack rows[sel[i]] into tokens[i * width .. ) zero-padded, i in [0, m).
// sel may be null (identity: rows 0..m-1). Rows longer than width return -3
// (callers bucket by length first, so this is a programming error).
int32_t a5_pack(const uint8_t* data, const int64_t* offsets,
                const int32_t* lengths, const int64_t* sel, int64_t m,
                int32_t width, uint8_t* tokens, int32_t* out_lengths) {
    for (int64_t i = 0; i < m; ++i) {
        int64_t row = sel ? sel[i] : i;
        int32_t len = lengths[row];
        if (len > width) return -3;
        const uint8_t* src = data + offsets[row];
        uint8_t* dst = tokens + i * width;
        int32_t j = 0;
        for (; j < len; ++j) dst[j] = src[j];
        for (; j < width; ++j) dst[j] = 0;
        out_lengths[i] = len;
    }
    return 0;
}

// ABI version tag so the Python loader can reject a stale build.
int32_t a5_native_abi(void) { return 1; }

}  // extern "C"

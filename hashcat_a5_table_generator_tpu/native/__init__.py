"""ctypes bindings for the native wordlist scanner/packer.

Build-on-first-use: ``g++ -O3 -shared`` into a per-source-hash cache under
``~/.cache/a5native`` (no pip, no pybind11 — the C ABI + ctypes per the
environment's binding guidance). Every entry point degrades to the numpy
reference implementation in ``ops.packing`` when the toolchain or build is
unavailable, and ``A5_NATIVE=0`` forces the fallback.

The contract — byte-identical outputs to ``ops.packing`` — is enforced by
tests/test_native.py across CRLF, unterminated tails, empty lines and the
anti-Q8 oversized-line error.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import sys
from typing import List, Optional, Tuple

import numpy as np

from ..runtime.env import env_str
from ..ops.packing import (
    DEFAULT_BUCKETS,
    DEFAULT_MAX_WORD_BYTES,
    PackedWords,
    aligned_width,
    validate_buckets,
)

_SRC = pathlib.Path(__file__).with_name("packer.cpp")
_ABI = 1
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _cache_dir() -> pathlib.Path:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return pathlib.Path(root) / "a5native"


def _build() -> Optional[pathlib.Path]:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"liba5native-{tag}.so"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".tmp{os.getpid()}.so")
    # No -march=native: the cache key is source-hash only, and the scan/pack
    # passes are memory-bound — a portable -O3 binary avoids SIGILL when the
    # cache directory is shared across heterogeneous machines.
    cmd = [
        "g++", "-O3", "-shared", "-fPIC",
        "-o", str(tmp), str(_SRC),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
    except (OSError, subprocess.SubprocessError) as e:
        print(
            f"a5native: build failed ({e}); using numpy fallback",
            file=sys.stderr,
        )
        return None
    os.replace(tmp, out)
    return out


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None => use fallback."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if env_str("A5_NATIVE", "1") == "0":
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError as e:
        print(f"a5native: load failed ({e}); using numpy fallback",
              file=sys.stderr)
        return None
    if lib.a5_native_abi() != _ABI:
        print("a5native: ABI mismatch; using numpy fallback", file=sys.stderr)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.a5_count_lines.argtypes = [u8p, ctypes.c_int64]
    lib.a5_count_lines.restype = ctypes.c_int64
    lib.a5_scan_lines.argtypes = [u8p, ctypes.c_int64, ctypes.c_int64,
                                  i64p, i32p, i64p]
    lib.a5_scan_lines.restype = ctypes.c_int32
    lib.a5_pack.argtypes = [u8p, i64p, i32p, i64p, ctypes.c_int64,
                            ctypes.c_int32, u8p, i32p]
    lib.a5_pack.restype = ctypes.c_int32
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def scan_wordlist_bytes(
    data: bytes, *, max_word_bytes: int = DEFAULT_MAX_WORD_BYTES
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Line structure of a wordlist buffer: (buffer, offsets, lengths).

    Matches ``ops.packing.read_wordlist`` semantics exactly (ScanLines +
    anti-Q8 error). Raises ValueError on an oversized line."""
    lib = load()
    buf = np.frombuffer(data, dtype=np.uint8)
    if lib is None:
        # numpy fallback mirroring the native pass
        from ..ops.packing import read_wordlist_lines

        return read_wordlist_lines(data, max_word_bytes=max_word_bytes)
    n = np.int64(len(data))
    count = lib.a5_count_lines(_u8(buf), n) if len(data) else 0
    offsets = np.zeros(max(1, count), dtype=np.int64)
    lengths = np.zeros(max(1, count), dtype=np.int32)
    bad = np.zeros(1, dtype=np.int64)
    rc = lib.a5_scan_lines(
        _u8(buf), n, np.int64(max_word_bytes), _i64(offsets), _i32(lengths),
        _i64(bad),
    )
    if rc == -2:
        raise ValueError(
            f"line {int(bad[0])} exceeds {max_word_bytes} bytes (Q8)"
        )
    return buf, offsets[:count], lengths[:count]


def pack_rows(
    buf: np.ndarray,
    offsets: np.ndarray,
    lengths: np.ndarray,
    sel: Optional[np.ndarray],
    width: int,
    *,
    index: Optional[np.ndarray] = None,
) -> PackedWords:
    """Pack selected rows into a PackedWords batch of ``width``."""
    lib = load()
    m = len(sel) if sel is not None else len(offsets)
    tokens = np.zeros((m, width), dtype=np.uint8)
    out_len = np.zeros(m, dtype=np.int32)
    if index is None:
        index = (
            sel.astype(np.int64) if sel is not None
            else np.arange(m, dtype=np.int64)
        )
    if lib is None:
        rows = sel if sel is not None else np.arange(m)
        for i, r in enumerate(rows):
            ln = int(lengths[r])
            tokens[i, :ln] = buf[offsets[r] : offsets[r] + ln]
            out_len[i] = ln
        return PackedWords(tokens=tokens, lengths=out_len, index=index)
    sel64 = None if sel is None else np.ascontiguousarray(sel, dtype=np.int64)
    rc = lib.a5_pack(
        _u8(buf), _i64(offsets), _i32(lengths),
        _i64(sel64) if sel64 is not None else None,
        np.int64(m), np.int32(width), _u8(tokens), _i32(out_len),
    )
    if rc != 0:
        raise ValueError(f"a5_pack failed with {rc} (row longer than width?)")
    return PackedWords(tokens=tokens, lengths=out_len, index=index)


def read_packed(
    path: str,
    *,
    width: Optional[int] = None,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> PackedWords:
    """File → one PackedWords batch (the native fast path for the sweep
    runtime; equivalent to ``pack_words(read_wordlist(path))``)."""
    with open(path, "rb") as fh:
        data = fh.read()
    buf, offsets, lengths = scan_wordlist_bytes(
        data, max_word_bytes=max_word_bytes
    )
    if width is None:
        width = aligned_width(int(lengths.max()) if len(lengths) else 0)
    return pack_rows(buf, offsets, lengths, None, width)


def bucket_widths(
    lengths: np.ndarray, buckets: Tuple[int, ...] = DEFAULT_BUCKETS
) -> np.ndarray:
    """Vectorized bucket-width assignment, matching
    ``ops.packing.bucket_words``: the smallest bucket boundary covering the
    word, else the word's own power-of-two width (min 4)."""
    lengths = np.asarray(lengths, dtype=np.int64)
    b = np.asarray(validate_buckets(buckets), dtype=np.int64)
    idx = np.searchsorted(b, lengths, side="left")
    over = idx >= len(b)
    widths = (
        np.where(over, 0, b[np.minimum(idx, len(b) - 1)])
        if len(b)
        else np.zeros(len(lengths), dtype=np.int64)
    )
    if over.any():
        pow2 = np.maximum(
            4, 2 ** np.ceil(np.log2(np.maximum(lengths, 1))).astype(np.int64)
        )
        widths = np.where(over, pow2, widths)
    return widths.astype(np.int64)


def read_packed_buckets(
    path: str,
    *,
    buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> "dict[int, PackedWords]":
    """File → ``{bucket_width: PackedWords}`` (native fast path for the
    bucketed sweep; equivalent to ``bucket_words(read_wordlist(path))``).

    Each batch keeps its words' original dictionary positions in ``index``,
    so hits and per-word reporting stay global.  One oversized line no
    longer inflates every lane's width — only its own bucket's
    (VERDICT r1 weak #6 / SURVEY §5 long-context).
    """
    with open(path, "rb") as fh:
        data = fh.read()
    buf, offsets, lengths = scan_wordlist_bytes(
        data, max_word_bytes=max_word_bytes
    )
    if len(lengths) == 0:
        return {}
    widths = bucket_widths(lengths, buckets)
    out: "dict[int, PackedWords]" = {}
    for width in sorted(int(w) for w in np.unique(widths)):
        sel = np.nonzero(widths == width)[0].astype(np.int64)
        out[width] = pack_rows(buf, offsets, lengths, sel, width)
    return out

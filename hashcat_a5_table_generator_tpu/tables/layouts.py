"""Built-in keyboard-layout substitution maps and the ``.table`` emitter.

The reference ships six hand-authored ``.table`` artifacts (SURVEY.md §2.2) —
qwerty→azerty, qwerty→cyrillic (ЙЦУКЕН), qwerty→greek, greek→hebrew
transliteration, czech diacritics and german umlauts — and its README describes
a whole family of direction-reversed variants (``azerty-qwerty.table`` is
referenced at ``README.MD:112,147,154`` but not checked in). Here those layouts
are first-class data: ordered ``(key, value)`` pair lists in keyboard scan
order, an emitter that regenerates each checked-in artifact **byte-identically**
(golden-tested against the reference files), and utilities to derive new
tables (direction inversion, bidirectional merge) instead of hand-authoring
them.

A Layout is an ordered sequence of pairs, NOT a dict: the reference format
allows repeated keys (alternative substitutions append in file order —
``main.go:141``) and repeated key=value lines (multiplicity matters, Q7), and
the emitted line order must round-trip byte-exactly.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Tuple

from .parser import SubstitutionMap

Pair = Tuple[str, str]


@dataclass(frozen=True)
class Layout:
    """An ordered substitution layout plus its on-disk serialization style."""

    name: str
    pairs: Tuple[Pair, ...]
    eol: str = "\n"  # qwerty-azerty.table uses CRLF; the other artifacts LF
    description: str = ""

    def to_table_bytes(self) -> bytes:
        """Serialize to reference ``.table`` format (one key=value per line,
        trailing newline). Keys containing ``=`` or leading ``#``/whitespace
        would not survive a parse round-trip, so they are $HEX[]-escaped."""
        lines = []
        for key, value in self.pairs:
            lines.append(f"{_escape_key(key)}={_escape_value(value)}{self.eol}")
        return "".join(lines).encode("utf-8")

    def to_substitution_map(self) -> SubstitutionMap:
        """Parsed form: key bytes -> ordered list of value bytes (with
        append-per-key multiplicity, exactly as the parser would produce)."""
        out: Dict[bytes, List[bytes]] = {}
        for key, value in self.pairs:
            out.setdefault(key.encode("utf-8"), []).append(value.encode("utf-8"))
        return out

    def inverted(self, name: str | None = None) -> "Layout":
        """Swap substitution direction (e.g. qwerty→greek ⇒ greek→qwerty),
        preserving pair order — the reference's naming convention for this is
        ``B-A.table`` from ``A-B.table`` (``README.MD:146-148``)."""
        return replace(
            self,
            name=name or _invert_name(self.name),
            pairs=tuple((v, k) for k, v in self.pairs),
        )

    def merged_with(self, other: "Layout", name: str) -> "Layout":
        """Concatenate two layouts (order preserved) — how the reference's
        bidirectional qwerty-azerty table is structured (both directions in
        one file, SURVEY.md §2.2)."""
        return replace(self, name=name, pairs=self.pairs + other.pairs)


def _invert_name(name: str) -> str:
    parts = name.split("-")
    return "-".join(reversed(parts)) if len(parts) == 2 else f"{name}-inverted"


def _needs_hex(text: str) -> bool:
    # Anything that would not survive a parse round-trip verbatim: leading /
    # trailing whitespace (TrimSpace), embedded line breaks (line structure),
    # or a literal "$HEX[" prefix (would be decoded on re-parse).
    return (
        text != text.strip()
        or "\n" in text
        or "\r" in text
        or text.startswith("$HEX[")
    )


def _hex_escape(text: str) -> str:
    return "$HEX[" + text.encode("utf-8").hex() + "]"


def _escape_key(key: str) -> str:
    # An empty key is emitted raw: the line "=value" parses back to the empty
    # key (main.go:123 SplitN semantics), whereas "$HEX[]" would NOT decode
    # (the reference's len<7 passthrough keeps it as a literal 6-byte key).
    if key and ("=" in key or key.startswith("#") or _needs_hex(key)):
        return _hex_escape(key)
    return key


def _escape_value(value: str) -> str:
    if _needs_hex(value):
        return _hex_escape(value)
    return value


def _pairs(spec: str, eol: str = "\n") -> Tuple[Pair, ...]:
    """Parse an inline ``k=v`` spec (first ``=`` splits, like the reference)."""
    out = []
    for line in spec.strip("\n").split("\n"):
        k, _, v = line.partition("=")
        out.append((k, v))
    return tuple(out)


# --- Built-in layouts, in the reference artifacts' exact line order ---------

QWERTY_CYRILLIC = Layout(
    "qwerty-cyrillic",
    _pairs(
        "q=й\nQ=Й\nw=ц\nW=Ц\ne=у\nE=У\nr=к\nR=К\nt=е\nT=Е\ny=н\nY=Н\n"
        "u=г\nU=Г\ni=ш\nI=Ш\no=щ\nO=Щ\np=з\nP=З\na=ф\nA=Ф\ns=ы\nS=Ы\n"
        "d=в\nD=В\nf=а\nF=А\ng=п\nG=П\nh=р\nH=Р\nj=о\nJ=О\nk=л\nK=Л\n"
        "l=д\nL=Д\n;=ж\n;=Ж\n'=э\n'=Э\nz=я\nZ=Я\nx=ч\nX=Ч\nc=с\nC=С\n"
        "v=м\nV=М\nb=и\nB=И\nn=т\nN=Т\nm=ь\nM=Ь\n,=б\n,=Б\n.=ю\n.=Ю"
    ),
    description="Full qwerty→ЙЦУКЕН, upper+lower; ';' ''' ',' '.' have 2 options",
)

QWERTY_GREEK = Layout(
    "qwerty-greek",
    _pairs(
        '"=:\n;=΄\n`=;\na=α\nb=β\nc=ψ\nd=δ\ne=ρ\nf=φ\ng=γ\nh=η\ni=ο\n'
        "j=ξ\nk=κ\nl=λ\nm=μ\nn=ν\no=π\nq=ς\nr=τ\ns=σ\nt=υ\nu=ι\nv=ω\n"
        "w=ε\nx=χ\ny=θ\nz=ζ"
    ),
    description="qwerty→greek incl. punctuation, lowercase only",
)

GREEK_HEBREW = Layout(
    "greek-hebrew",
    _pairs(
        "ς=ק\nε=ר\nρ=א\nτ=ט\nυ=ו\nθ=ן\nι=י\nο=ח\nπ=פ\nα=ש\nσ=ד\nδ=ג\n"
        "φ=כ\nγ=ע\nη=י\nξ=ח\nκ=ל\nλ=ך\n΄=ף\n'=ף\nζ=ז\nχ=ס\nψ=ב\nω=מ\n"
        "β=נ\nν=מ\nμ=צ\n,=ת\n.=ץ"
    ),
    description="greek→hebrew transliteration, both sides multi-byte UTF-8",
)

CZECH = Layout(
    "czech",
    _pairs(
        "A=Á\nE=É\nI=Í\nO=Ó\nU=Ú\nY=Ý\na=á\ne=é\ni=í\no=ó\nu=ú\ny=ý\n"
        "C=Č\nD=Ď\nE=Ě\nN=Ň\nR=Ř\nS=Š\nT=Ť\nZ=Ž\nc=č\nd=ď\ne=ě\nn=ň\n"
        "r=ř\ns=š\nt=ť\nz=ž\nU=Ů\nu=ů"
    ),
    description="ASCII→czech diacritics; E/U/u have 2 options (length-changing)",
)

GERMAN = Layout(
    "german",
    _pairs("A=ä\nO=ö\nU=ü\na=ä\no=ö\nu=ü\nss=ß\nZ=ß"),
    description="German umlauts + multi-char key ss=ß",
)

QWERTY_AZERTY = Layout(
    "qwerty-azerty",
    _pairs(
        "q=a\nw=z\na=q\n;=m\nz=w\nm=,\n,=;\n.=:\n/=!\n1=&\n2=é\n3=\"\n"
        "4='\n5=(\n6=§\n7=è\n8=!\n9=ç\n0=à\n-=)\n/=-\n*=$\nm=;\n,=m\n"
        ";=,\n:=.\n!=/\n&=1\né=2\n\"=3\n'=4\n(=5\n§=6\nè=7\n!=8\nç=9\n"
        "à=0\n)=-\n-=/\n$=*\nQ=A\nW=Z\nA=Q\nZ=W\n;=M\nM=;\n,=M\nQ=a\n"
        "W=z\nA=q\nZ=w\nM=,"
    ),
    eol="\r\n",  # the checked-in artifact is CRLF-terminated
    description="qwerty↔azerty both directions merged + case pairs",
)

BUILTIN_LAYOUTS: Dict[str, Layout] = {
    layout.name: layout
    for layout in (
        QWERTY_CYRILLIC,
        QWERTY_GREEK,
        GREEK_HEBREW,
        CZECH,
        GERMAN,
        QWERTY_AZERTY,
    )
}

#: Derived layouts the reference documents but never checked in
#: (``README.MD:112,147,154``): direction-reversed variants.
DERIVED_LAYOUTS: Dict[str, Layout] = {
    inv.name: inv
    for inv in (
        QWERTY_CYRILLIC.inverted(),  # cyrillic-qwerty
        QWERTY_GREEK.inverted(),  # greek-qwerty
        GREEK_HEBREW.inverted(),  # hebrew-greek
        QWERTY_AZERTY.inverted(),  # azerty-qwerty
    )
}


def get_layout(name: str) -> Layout:
    try:
        return BUILTIN_LAYOUTS.get(name) or DERIVED_LAYOUTS[name]
    except KeyError:
        known = sorted(BUILTIN_LAYOUTS) + sorted(DERIVED_LAYOUTS)
        raise KeyError(f"unknown layout {name!r}; built-ins: {known}") from None


def emit_table(layout: Layout, path: str) -> None:
    """Write a layout to a ``.table`` file in the reference format."""
    with open(path, "wb") as fh:
        fh.write(layout.to_table_bytes())

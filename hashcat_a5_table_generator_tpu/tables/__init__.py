"""Substitution-table subsystem: parsing, merging, $HEX codec, layout emitters,
and compilation of merged tables into dense arrays for the TPU backend."""

from .parser import (  # noqa: F401
    HexDecodeError,
    TableLineError,
    decode_hex_notation,
    merge_substitution_tables,
    parse_substitution_table,
    read_substitution_table,
)

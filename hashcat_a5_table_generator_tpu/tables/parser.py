"""Substitution-table parser (layer L2 of SURVEY.md §1).

Byte-exact reimplementation of the reference's table ingestion
(``readSubstitutionTable`` + ``decodeHexNotation``, reference ``main.go:108-162``),
with the parity-critical behaviors preserved:

* line format ``key=value``, split at the FIRST ``=`` only
  (``main.go:123``): the key may not contain a literal ``=`` (use ``$HEX[3d]``),
  the value may; a line ``=x`` (or ``==x``) yields an *empty key* entry, which is
  inert in default/reverse modes (match length >= 1) but live in the
  substitute-all modes (SURVEY.md §2.1).
* blank lines and ``#`` comments skipped (``main.go:118-121``); lines without
  ``=`` silently skipped (``main.go:124-126``).
* ``$HEX[...]`` decoding on both sides; embedded spaces stripped;
  case-insensitive hex; a malformed hex side causes the LINE to be logged and
  skipped, not a fatal error (``main.go:129-139``).
* keys and values are arbitrary **byte strings** — multi-char keys
  (``ss=ß``) and multi-byte UTF-8 both work; values are appended per key, so
  duplicate lines produce duplicate candidates downstream (no dedupe — Q7).
* merging multiple table files appends values per key in file order
  (``main.go:40-50``).

Known, documented divergences from the Go binary (degenerate inputs only):

* Go trims lines with the Unicode-aware ``strings.TrimSpace``. We trim
  Unicode whitespace when the line is valid UTF-8 and ASCII whitespace
  otherwise; ASCII control chars 0x1c-0x1f are stripped by Python's
  ``str.strip`` but not by Go's ``unicode.IsSpace``.
* Go's ``bufio.Scanner`` aborts the whole file on a line longer than 64 KiB
  (the caller then ``log.Fatal``'s). We raise :class:`TableLineError` for the
  same condition (configurable via ``max_line_bytes``).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterable, List, Mapping

logger = logging.getLogger("tpu_a5.tables")

SubstitutionMap = Dict[bytes, List[bytes]]

#: Go bufio.Scanner's default MaxScanTokenSize (reference main.go:117; Q8).
GO_SCANNER_LIMIT = 64 * 1024


class HexDecodeError(ValueError):
    """A ``$HEX[...]`` payload failed to decode (odd length / non-hex chars).

    Mirrors the error from Go's ``hex.DecodeString`` (``main.go:157-159``); at
    the file level the offending line is logged and skipped, matching
    ``main.go:129-139``.
    """


class TableLineError(ValueError):
    """A table line exceeded the scanner limit (Go would abort the file)."""


def _trim_space(line: bytes) -> bytes:
    """Approximate Go ``strings.TrimSpace`` on raw bytes (see module docstring)."""
    try:
        return line.decode("utf-8").strip().encode("utf-8")
    except UnicodeDecodeError:
        return line.strip(b" \t\n\v\f\r")


def decode_hex_notation(value: bytes) -> bytes:
    """Decode hashcat ``$HEX[...]`` notation to raw bytes (``main.go:147-162``).

    Pass-through (returned as-is) when the value is not wrapped in
    ``$HEX[``...``]`` or is shorter than 7 bytes — so the 6-byte literal
    ``$HEX[]`` is returned verbatim, exactly as in the reference
    (``main.go:149``). Embedded spaces are stripped (space-delimited hex is
    accepted, reference ``README.MD:172-176``); hex digits are
    case-insensitive. Raises :class:`HexDecodeError` on a malformed payload.
    """
    if len(value) < 7 or not value.startswith(b"$HEX[") or not value.endswith(b"]"):
        return value
    hex_str = value[5:-1].replace(b" ", b"")
    try:
        return bytes.fromhex(hex_str.decode("ascii"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise HexDecodeError(f"invalid hex string {hex_str!r}: {exc}") from None


def parse_substitution_table(
    data: bytes,
    *,
    source: str = "<bytes>",
    max_line_bytes: int = GO_SCANNER_LIMIT,
    on_skip: Callable[[str], None] | None = None,
) -> SubstitutionMap:
    """Parse table bytes into ``{key: [value, ...]}`` (``main.go:108-144``).

    ``on_skip`` is invoked with a message for each line skipped due to a bad
    ``$HEX[]`` payload (default: logged to stderr, as the reference does with
    ``log.Printf``). Lines with no ``=`` are skipped *silently*, matching the
    reference (``main.go:124-126``).
    """
    report = on_skip if on_skip is not None else logger.warning
    substitutions: SubstitutionMap = {}
    for raw in data.split(b"\n"):
        if raw.endswith(b"\r"):  # bufio.ScanLines drops a trailing \r
            raw = raw[:-1]
        if len(raw) > max_line_bytes:
            raise TableLineError(
                f"{source}: line longer than {max_line_bytes} bytes "
                "(Go bufio.Scanner would abort here — Q8)"
            )
        line = _trim_space(raw)
        if not line or line.startswith(b"#"):
            continue
        parts = line.split(b"=", 1)
        if len(parts) != 2:
            continue  # silently skipped, main.go:124-126
        key_part, value_part = parts
        try:
            key = decode_hex_notation(key_part)
        except HexDecodeError as exc:
            report(f"Error decoding hex notation in key: {line!r} - {exc}")
            continue
        try:
            value = decode_hex_notation(value_part)
        except HexDecodeError as exc:
            report(f"Error decoding hex notation in value: {line!r} - {exc}")
            continue
        substitutions.setdefault(key, []).append(value)
    return substitutions


def read_substitution_table(
    path: str,
    *,
    max_line_bytes: int = GO_SCANNER_LIMIT,
    on_skip: Callable[[str], None] | None = None,
) -> SubstitutionMap:
    """Read and parse one table file (reference ``readSubstitutionTable``)."""
    with open(path, "rb") as fh:
        data = fh.read()
    return parse_substitution_table(
        data, source=path, max_line_bytes=max_line_bytes, on_skip=on_skip
    )


def merge_substitution_tables(
    tables: Iterable[Mapping[bytes, List[bytes]]],
) -> SubstitutionMap:
    """Merge parsed tables in order, APPENDING values per key (``main.go:40-50``).

    Later tables add *alternative* substitutions for existing keys; there is no
    dedupe, so the same mapping in two files yields duplicate candidates (Q7).
    """
    merged: SubstitutionMap = {}
    for table in tables:
        for key, values in table.items():
            merged.setdefault(key, []).extend(values)
    return merged


def load_tables(paths: Iterable[str], **kwargs: Any) -> SubstitutionMap:
    """Read + merge several table files, as the reference driver does."""
    return merge_substitution_tables(
        read_substitution_table(p, **kwargs) for p in paths
    )

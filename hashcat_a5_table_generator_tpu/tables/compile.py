"""Table compiler: substitution map -> dense, fixed-shape device arrays.

The reference keeps its merged table as a Go ``map[string][]string`` and probes
it per byte position inside the generation recursion (``main.go:182-185``). A
TPU enumerates variants by index arithmetic over fixed-shape tensors, so the
map is compiled once, host-side, into:

* a **key matrix** ``key_bytes[K, key_width] / key_len[K]`` with keys in
  canonical sorted-bytes order (the same order the oracle's substitute-all
  engines use for pattern enumeration — Q4 canonicalization), and a CSR-style
  value table ``val_bytes[V, val_width] / val_len[V]`` with per-key slices
  ``val_start[K] / val_count[K]`` preserving merge/append order and duplicate
  multiplicity (Q7);
* a **single-byte LUT** ``byte_to_key[256]`` (-1 = no single-byte key) for the
  dominant transliteration-table case;
* fast-path predicates: ``cascade_hazard[K, K]`` — ``hazard[p, q]`` is True
  when pattern ``q`` sorts AFTER ``p`` and the canonical sorted-order
  ReplaceAll cascade (oracle Q4 semantics) could match ``q`` against text
  *touching* a value ``v`` inserted by ``p`` — and ``has_empty_key``
  (a ``=x`` table line; live only in substitute-all modes). A value inserted
  by ``p`` can only ever be re-matched by patterns applied after it, i.e.
  patterns sorting strictly after ``p``; earlier-sorted patterns have already
  run. A ``q`` match touching ``v`` either (a) lies inside ``v``, (b) crosses
  ``v``'s left boundary (so ``q`` ends with a nonempty prefix of ``v``),
  (c) crosses its right boundary (``q`` starts with a nonempty suffix of
  ``v``), or (d) spans all of ``v`` plus context on both sides (``v`` a
  proper substring of ``q`` — including ``v == b""``, where the splice joins
  previously separated context). These conditions are word-independent and
  conservative: they flag every word where the span-splice fast path could
  diverge from the ReplaceAll cascade, at the cost of some exact-but-flagged
  words. ``cascade_free`` (no hazard at all) holds for monodirectional
  transliteration tables (qwerty-cyrillic, greek-hebrew, czech, german,
  qwerty-greek); bidirectional tables like qwerty-azerty have hazards.

  The hazard cases split further: ``cascade_crossing[K, K]`` flags the
  BOUNDARY cases (b)-(d) only. A hazard pair that is containment-only
  (``cascade_hazard & ~cascade_crossing`` — every possible ``q`` match
  against an inserted ``v`` lies wholly inside ``v``) is a pure value
  REWRITE: the effect of the later ReplaceAll on the span is exactly
  ``v.replace(q, chosen_u)``, computable at plan-build time. The
  substitute-all planner (``ops.expand_suball``) closes such cascades on
  device — each affected pattern slot gets a joint value table over its
  own digit and its hazard-successors' digits — so containment-hazard
  words (the 10.2% fallback share of qwerty-azerty, PERF.md §5) stay on
  the device path; only crossing cases (and cap overflows) remain
  oracle-routed.

Everything here is host-side numpy; the arrays are uploaded to device once per
sweep and shared by every batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Mapping, Sequence

import numpy as np

SubstitutionMap = Mapping[bytes, Sequence[bytes]]


@dataclass(frozen=True)
class CompiledTable:
    """A substitution map in dense device-ready form.

    Keys are sorted bytewise (canonical pattern order); values keep their
    merged append order and multiplicity. All arrays are numpy (host); callers
    move them to device with ``jnp.asarray`` / ``jax.device_put``.
    """

    keys: tuple  # tuple[bytes] in sorted order (host-side convenience)
    key_bytes: np.ndarray  # uint8 [K, key_width]
    key_len: np.ndarray  # int32 [K]
    val_start: np.ndarray  # int32 [K] — CSR offset into value table
    val_count: np.ndarray  # int32 [K]
    val_bytes: np.ndarray  # uint8 [V, val_width]
    val_len: np.ndarray  # int32 [V]
    byte_to_key: np.ndarray  # int32 [256] — key index of single-byte key, or -1
    max_key_len: int
    max_val_len: int
    cascade_hazard: np.ndarray  # bool [K, K] — see module docstring
    cascade_crossing: np.ndarray  # bool [K, K] — boundary cases (b)-(d) only
    has_empty_key: bool  # a b"" key exists (inert outside substitute-all)

    @property
    def cascade_free(self) -> bool:
        """True when NO sorted-order ReplaceAll cascade can re-match inserted
        text, so the all-or-none span-splice fast path is exact for every
        word and every chosen-pattern subset."""
        return not bool(self.cascade_hazard.any())

    @property
    def num_keys(self) -> int:
        return int(self.key_bytes.shape[0])

    @property
    def num_values(self) -> int:
        # Not val_bytes.shape[0]: a zero-pair table pads one value row so
        # device gathers stay in-bounds, but it holds zero actual values.
        return int(self.val_count.sum())

    @property
    def all_keys_single_byte(self) -> bool:
        return self.max_key_len <= 1 and not self.has_empty_key

    def key_index(self, key: bytes) -> int:
        """Index of ``key`` in canonical order (host-side; -1 if absent)."""
        try:
            return self.keys.index(key)
        except ValueError:
            return -1

    def values_of(self, key_idx: int) -> List[bytes]:
        """Host-side value list of a key, in merged order (for oracles/tests)."""
        s = int(self.val_start[key_idx])
        c = int(self.val_count[key_idx])
        return [
            bytes(self.val_bytes[i, : self.val_len[i]]) for i in range(s, s + c)
        ]


def boundary_match_possible(v: bytes, q: bytes) -> bool:
    """Could a ReplaceAll of pattern ``q`` match text CROSSING a boundary of
    inserted text ``v`` — the module docstring's cases (b)-(d)?
    Word-independent over-approximation over arbitrary surrounding context.
    Containment (case (a)) is deliberately NOT flagged: a fully-contained
    re-match is a pure value rewrite, which the cascade-closure plans apply
    statically (``ops.expand_suball``)."""
    if len(v) < len(q) and v in q:  # (d) spans v plus context on both sides
        return True
    for n in range(1, min(len(q), len(v) + 1)):
        if q[-n:] == v[:n]:  # (b) crosses v's left boundary
            return True
        if q[:n] == v[-n:]:  # (c) crosses v's right boundary
            return True
    return False


def _touching_match_possible(v: bytes, q: bytes) -> bool:
    """Could a ReplaceAll of pattern ``q`` match text touching an inserted
    value ``v``? Word-independent over-approximation — see the module
    docstring's (a)-(d). Every real cascade divergence satisfies one of
    these: a match intersecting ``v`` covers a prefix, suffix, or all of
    ``v``, with any overhang coming from surrounding context."""
    # (a) contained in the inserted text, else a boundary crossing.
    return q in v or boundary_match_possible(v, q)


def compile_table(sub_map: SubstitutionMap) -> CompiledTable:
    """Compile a parsed/merged substitution map into dense arrays.

    Zero-key edge cases produce shape-(0, 1) key matrices so downstream
    jnp code never sees a zero-width axis; the VALUE arrays additionally
    keep at least one (zero) row because device kernels gather value rows
    by index (``num_values`` still reports the true count).
    """
    keys = sorted(sub_map.keys())
    k = len(keys)
    max_key_len = max((len(key) for key in keys), default=0)
    key_width = max(max_key_len, 1)

    key_bytes = np.zeros((k, key_width), dtype=np.uint8)
    key_len = np.zeros((k,), dtype=np.int32)
    val_start = np.zeros((k,), dtype=np.int32)
    val_count = np.zeros((k,), dtype=np.int32)

    flat_values: List[bytes] = []
    for i, key in enumerate(keys):
        key_bytes[i, : len(key)] = np.frombuffer(key, dtype=np.uint8)
        key_len[i] = len(key)
        vals = list(sub_map[key])
        val_start[i] = len(flat_values)
        val_count[i] = len(vals)
        flat_values.extend(bytes(v) for v in vals)

    v = len(flat_values)
    max_val_len = max((len(x) for x in flat_values), default=0)
    val_width = max(max_val_len, 1)
    # A zero-PAIR table (every input line skipped) keeps one zero row: the
    # device kernels gather value rows by clamped index, and a 0-row axis
    # makes even the never-selected gather out of bounds (val_count is all
    # zero, so no lane ever chooses the padding row).
    val_bytes = np.zeros((max(v, 1), val_width), dtype=np.uint8)
    val_len = np.zeros((max(v, 1),), dtype=np.int32)
    for i, value in enumerate(flat_values):
        val_bytes[i, : len(value)] = np.frombuffer(value, dtype=np.uint8)
        val_len[i] = len(value)

    byte_to_key = np.full((256,), -1, dtype=np.int32)
    for i, key in enumerate(keys):
        if len(key) == 1:
            byte_to_key[key[0]] = i

    cascade_hazard = np.zeros((k, k), dtype=bool)
    cascade_crossing = np.zeros((k, k), dtype=bool)
    for p in range(k):
        for q in range(p + 1, k):  # only later-sorted patterns can re-match
            # keys[q] is never empty here: b"" sorts first, so it cannot be a
            # later-sorted pattern (tables with an empty key are excluded from
            # the fast path via has_empty_key regardless).
            key_q = keys[q]
            cascade_hazard[p, q] = any(
                _touching_match_possible(
                    flat_values[val_start[p] + j], key_q
                )
                for j in range(val_count[p])
            )
            cascade_crossing[p, q] = any(
                boundary_match_possible(
                    flat_values[val_start[p] + j], key_q
                )
                for j in range(val_count[p])
            )

    return CompiledTable(
        keys=tuple(keys),
        key_bytes=key_bytes,
        key_len=key_len,
        val_start=val_start,
        val_count=val_count,
        val_bytes=val_bytes,
        val_len=val_len,
        byte_to_key=byte_to_key,
        max_key_len=max_key_len,
        max_val_len=max_val_len,
        cascade_hazard=cascade_hazard,
        cascade_crossing=cascade_crossing,
        has_empty_key=b"" in sub_map,
    )

"""Byte-exact CPU reference engines — the parity anchor for the TPU backend."""

from .engines import (  # noqa: F401
    ReferencePanic,
    iter_candidates,
    process_word,
    process_word_reverse,
    process_word_substitute_all,
    process_word_substitute_all_reverse,
)
from .keyspace import (  # noqa: F401
    count_candidates,
    find_spans,
    unique_patterns,
)

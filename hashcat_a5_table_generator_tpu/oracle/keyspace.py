"""Exact keyspace arithmetic for the four generation modes.

The reference enumerates recursively and never counts (its only "planning" is
the ``-r`` mode's early return, ``main.go:227-229``). The TPU backend needs the
keyspace *closed form* — per-word candidate counts and an index<->variant
bijection — because variants are enumerated by index arithmetic instead of
recursion (SURVEY.md §5 "long-context": a huge single word's variant range is
split across chips as an exact integer partition).

Counting model (proved against the oracle in tests/test_keyspace.py):

* default mode (``processWord``, ``main.go:168-205``): each emission
  corresponds to exactly one pair (S, c) where S is a set of pairwise
  non-overlapping match spans of the ORIGINAL word (matches never cross a
  replacement boundary because the scan resumes at ``i+len(sub)`` — Q6),
  |S| in [max(1, min), max] (Q1), and c assigns one option to each span.
  Count = sum over such S of the product of option counts.
* reverse mode (``processWordReverse``): same span family with a single
  option per span (Q2), |S| in [min, min(max, n_matches)], including the
  empty set when min == 0; early-return 0 when n_matches < min.
* substitute-all: choices over the sorted unique patterns present; count =
  sum_{k in [min, min(max, n)]} e_k(r_1..r_n) (elementary symmetric in the
  per-pattern option counts).
* substitute-all reverse: subsets of the pattern set, first option only:
  sum_{k in [min, min(max, n)]} C(n, k); 0 when n < min.
"""

from __future__ import annotations

from math import comb
from typing import List, Mapping, Sequence, Tuple

from .engines import find_match_positions, unique_patterns_in_word

SubstitutionMap = Mapping[bytes, Sequence[bytes]]

Span = Tuple[int, int, int]  # (start, key_length, n_options)


def find_spans(word: bytes, sub_map: SubstitutionMap) -> List[Span]:
    """All match spans of ``word`` with their option counts, in scan order."""
    return [(s, k, len(subs)) for s, k, subs in find_match_positions(word, sub_map)]


def unique_patterns(word: bytes, sub_map: SubstitutionMap) -> List[bytes]:
    """Sorted unique patterns present in ``word`` (substitute-all site list)."""
    return unique_patterns_in_word(word, sub_map)


def _span_subset_poly(
    spans: Sequence[Span], length: int, max_degree: int, *, weighted: bool
) -> List[int]:
    """Coefficients p[k] = number of non-overlapping span subsets of size k
    (weighted by the product of option counts when ``weighted``), truncated at
    ``max_degree``. DP over byte positions, O(length * n_spans_per_pos)."""
    starts: dict[int, List[Span]] = {}
    for sp in spans:
        starts.setdefault(sp[0], []).append(sp)

    # f[j] = poly for the suffix word[j:]; computed right-to-left.
    f = [0] * (max_degree + 1)
    f[0] = 1
    suffix = {length: f}
    for j in range(length - 1, -1, -1):
        poly = list(suffix[j + 1])
        for start, key_length, n_opts in starts.get(j, ()):
            tail = suffix[j + key_length]
            w = n_opts if weighted else 1
            for k in range(max_degree):
                if tail[k]:
                    poly[k + 1] += w * tail[k]
        suffix[j] = poly
    return suffix[0]


def count_default(
    word: bytes, sub_map: SubstitutionMap, min_substitute: int, max_substitute: int
) -> int:
    """Emissions of the default engine (Q1: min 0 is bumped to 1)."""
    lo = max(1, min_substitute)
    if lo > max_substitute:
        return 0
    # Non-overlapping span subsets never exceed len(word) members, so the DP
    # degree is clamped there regardless of how large -x is.
    hi = min(max_substitute, len(word))
    if lo > hi:
        return 0
    poly = _span_subset_poly(find_spans(word, sub_map), len(word), hi, weighted=True)
    return sum(poly[lo : hi + 1])


def count_reverse(
    word: bytes, sub_map: SubstitutionMap, min_substitute: int, max_substitute: int
) -> int:
    """Emissions of the reverse engine (first option only, empty set at min 0)."""
    spans = find_spans(word, sub_map)
    if len(spans) < min_substitute:
        return 0
    hi = min(max_substitute, len(spans))
    if min_substitute > hi:
        return 0
    poly = _span_subset_poly(spans, len(word), hi, weighted=False)
    return sum(poly[min_substitute : hi + 1])


def _truncated_elementary_symmetric(radii: Sequence[int], max_degree: int) -> List[int]:
    """Coefficients of prod_i (1 + r_i x), truncated at ``max_degree``."""
    poly = [0] * (max_degree + 1)
    poly[0] = 1
    for r in radii:
        for k in range(min(max_degree, len(radii)), 0, -1):
            poly[k] += r * poly[k - 1]
    return poly


def count_substitute_all(
    word: bytes, sub_map: SubstitutionMap, min_substitute: int, max_substitute: int
) -> int:
    """Emissions of the substitute-all engine: choice vectors over unique
    patterns with the number of chosen patterns in [min, max] (Q10)."""
    radii = [len(sub_map[p]) for p in unique_patterns_in_word(word, sub_map)]
    hi = min(max_substitute, len(radii))
    if min_substitute > hi:
        return 0
    poly = _truncated_elementary_symmetric(radii, hi)
    return sum(poly[min_substitute : hi + 1])


def count_substitute_all_reverse(
    word: bytes, sub_map: SubstitutionMap, min_substitute: int, max_substitute: int
) -> int:
    """Emissions of the substitute-all reverse engine: one per subset of the
    pattern set with size in [min, min(max, n)]; 0 when n < min."""
    n = len(unique_patterns_in_word(word, sub_map))
    if n < min_substitute:
        return 0
    return sum(comb(n, k) for k in range(min_substitute, min(max_substitute, n) + 1))


def count_candidates(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int = 0,
    max_substitute: int = 15,
    *,
    substitute_all: bool = False,
    reverse: bool = False,
) -> int:
    """Exact number of candidates the reference emits for ``word`` in a mode."""
    if substitute_all:
        fn = count_substitute_all_reverse if reverse else count_substitute_all
    else:
        fn = count_reverse if reverse else count_default
    return fn(word, sub_map, min_substitute, max_substitute)

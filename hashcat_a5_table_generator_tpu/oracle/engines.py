"""CPU oracle: byte-exact reimplementation of the reference's four generation
engines (layer L3, reference ``main.go:168-440``).

This module is the **parity anchor** of the framework: every TPU kernel is
tested against these generators. Candidates are produced as a stream of
``bytes``; per-word order is the reference's deterministic DFS order (Q9), so a
single-threaded run over a wordlist reproduces the Go binary at ``--threads 1``
byte-for-byte (modulo Q4, below).

The verified behavioral contract it implements (SURVEY.md §2.4):

* **Q1** — default mode silently bumps ``min 0 -> 1`` (``main.go:169-171``):
  the original word is never emitted there, but ``-r``, ``-s`` and ``-s -r``
  all DO emit it when ``min == 0``.
* **Q2** — the reverse modes apply only ``subs[0]``, the first-listed option
  per key (``main.go:253``, ``main.go:396``).
* **Q3** — reverse mode applies combos in descending position order while
  accumulating a splice offset as if ascending (``main.go:249-257``); with
  length-changing substitutions this corrupts positions (verified: ``ab`` with
  ``a=XX, b=YY`` at exactly 2 subs emits ``aXXY``). Reproduced by default
  (``bug_compat=True``); ``bug_compat=False`` applies correct offsets.
  Inputs that would make the Go binary panic on an out-of-range splice raise
  :class:`ReferencePanic`.
* **Q4** — the substitute-all modes apply chosen replacements by sequential
  ReplaceAll in *Go map iteration order* (nondeterministic,
  ``main.go:338-341``). We canonicalize to **sorted pattern order** — the only
  deliberate divergence, and only observable when one replacement's output
  contains another chosen pattern.
* **Q5** — matching is byte-oriented; default mode probes longest key first at
  each position (``main.go:177``).
* **Q6** — replacement text is never re-matched (recursion resumes at
  ``i + len(sub)``, ``main.go:197``); original bytes after it still are.
* **Q7** — no dedupe anywhere: duplicate table options and convergent paths
  yield duplicate candidates; multiplicity is part of parity.
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, Iterator, List, Mapping, Sequence, Tuple

SubstitutionMap = Mapping[bytes, Sequence[bytes]]


class ReferencePanic(RuntimeError):
    """The Go reference would panic (slice out of range) on this input.

    Only reachable in reverse mode with ``bug_compat=True`` and
    length-shrinking substitutions whose buggy offsets (Q3) push a splice
    start below zero or past the end of the intermediate string.
    """


def _max_key_len(sub_map: SubstitutionMap) -> int:
    return max((len(k) for k in sub_map), default=0)


def process_word(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int,
    max_substitute: int,
) -> Iterator[bytes]:
    """Default engine (reference ``processWord``, ``main.go:168-205``).

    Recursive DFS over byte positions; at each position keys are probed
    longest-first (Q5); after a substitution the scan resumes past the
    replacement text (Q6). ``min == 0`` is bumped to 1, so the unmodified word
    is never emitted (Q1).
    """
    if min_substitute == 0:
        min_substitute = 1
    # Probing every key length from the remaining length down to 1 as the
    # reference does (main.go:177) is O(n) dict probes per position; lengths
    # above the longest key can never match, so clamping to it is
    # semantics-preserving and keeps the oracle usable on long words.
    kmax = _max_key_len(sub_map)

    def generate(current: bytes, count: int, start: int) -> Iterator[bytes]:
        for i in range(start, len(current)):
            for key_length in range(min(len(current) - i, kmax), 0, -1):
                subs = sub_map.get(current[i : i + key_length])
                if subs is None:
                    continue
                for sub in subs:
                    new_word = current[:i] + sub + current[i + key_length :]
                    new_count = count + 1
                    if new_count > max_substitute:
                        continue
                    if new_count >= min_substitute:
                        yield new_word
                    yield from generate(new_word, new_count, i + len(sub))

    yield from generate(word, 0, 0)


def find_match_positions(
    word: bytes, sub_map: SubstitutionMap
) -> List[Tuple[int, int, Sequence[bytes]]]:
    """All ``(start, key_length, subs)`` matches, in the reference's scan order
    (ascending start, then ascending key length — ``main.go:215-225``)."""
    kmax = _max_key_len(sub_map)
    positions: List[Tuple[int, int, Sequence[bytes]]] = []
    for i in range(len(word)):
        for key_length in range(1, min(len(word) - i, kmax) + 1):
            subs = sub_map.get(word[i : i + key_length])
            if subs is not None:
                positions.append((i, key_length, subs))
    return positions


def _combinations_desc(n: int, k: int) -> Iterator[Tuple[int, ...]]:
    """Index combinations in the reference's order (``generateCombinations``,
    ``main.go:263-281``): each combo in descending index order, combos ordered
    by descending leading index (n=3,k=2 -> (2,1),(2,0),(1,0))."""
    # itertools.combinations over reversed(range(n)) yields exactly the
    # reference's recursive enumeration order.
    return combinations(range(n - 1, -1, -1), k)


def _valid_substitution_positions(
    combo: Sequence[int], positions: Sequence[Tuple[int, int, Sequence[bytes]]]
) -> bool:
    """Overlap filter (``validSubstitutionPositions``, ``main.go:283-305``)."""
    intervals = sorted(
        (positions[idx][0], positions[idx][0] + positions[idx][1] - 1)
        for idx in combo
    )
    for prev, cur in zip(intervals, intervals[1:]):
        if cur[0] <= prev[1]:
            return False
    return True


def process_word_reverse(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int,
    max_substitute: int,
    *,
    bug_compat: bool = True,
) -> Iterator[bytes]:
    """Reverse engine (``processWordReverse``, ``main.go:208-261``).

    Enumerates C(n, k) over all match positions from ``min(max, n)`` down to
    ``min`` (emitting the original word for the k=0 combo when ``min == 0`` —
    Q1), filters overlapping combos, and applies only ``subs[0]`` per position
    (Q2). ``bug_compat=True`` reproduces the Q3 offset bug exactly.
    """
    positions = find_match_positions(word, sub_map)
    total = len(positions)
    if total < min_substitute:
        return
    actual_max = min(max_substitute, total)

    for sub_count in range(actual_max, min_substitute - 1, -1):
        for combo in _combinations_desc(total, sub_count):
            if not _valid_substitution_positions(combo, positions):
                continue
            apply_order = combo if bug_compat else sorted(combo)
            result = word
            offset = 0
            for idx in apply_order:
                start, key_length, subs = positions[idx]
                sub = subs[0]
                actual_start = start + offset
                if actual_start < 0 or actual_start + key_length > len(result):
                    raise ReferencePanic(
                        f"slice bounds out of range applying combo {combo} to "
                        f"{word!r} (buggy offset {offset}, main.go:254-255)"
                    )
                result = result[:actual_start] + sub + result[actual_start + key_length :]
                offset += len(sub) - key_length
            yield result


def unique_patterns_in_word(word: bytes, sub_map: SubstitutionMap) -> List[bytes]:
    """Sorted unique table patterns occurring in ``word``
    (``main.go:313-326``). The scan checks every pattern at every byte offset,
    so an empty key (from a ``=x`` table line) matches any non-empty word —
    faithful to the Go code, where it triggers ReplaceAll-with-empty-pattern
    insertion behavior in the substitute-all modes."""
    found = {p for p in sub_map if (p in word if p else bool(word))}
    return sorted(found)


def _replace_all_cascade(
    word: bytes, chosen: Mapping[bytes, bytes]
) -> bytes:
    """Sequential ReplaceAll over the chosen patterns (``main.go:338-341``).

    Canonicalized to sorted-pattern order (Q4 — the reference uses Go's
    randomized map iteration order; sorted order is our documented choice).
    """
    result = word
    for pattern in sorted(chosen):
        result = result.replace(pattern, chosen[pattern])
    return result


def process_word_substitute_all(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int,
    max_substitute: int,
) -> Iterator[bytes]:
    """Substitute-all / transliteration engine (``processWordSubstituteAll``,
    ``main.go:308-365``) — the headline feature.

    For each unique pattern present in the word (sorted), the recursion either
    picks one of its options or skips it; at each leaf, if the number of
    *chosen distinct patterns* is within ``[min, max]``, every occurrence of
    each chosen pattern is replaced (ReplaceAll cascade). The original word is
    emitted for the empty choice when ``min == 0`` (Q1).
    """
    patterns = unique_patterns_in_word(word, sub_map)

    def generate(chosen: Dict[bytes, bytes], pos: int) -> Iterator[bytes]:
        if pos >= len(patterns):
            if min_substitute <= len(chosen) <= max_substitute:
                yield _replace_all_cascade(word, chosen)
            return
        pattern = patterns[pos]
        for sub in sub_map[pattern]:
            yield from generate({**chosen, pattern: sub}, pos + 1)
        yield from generate(chosen, pos + 1)

    yield from generate({}, 0)


def process_word_substitute_all_reverse(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int,
    max_substitute: int,
) -> Iterator[bytes]:
    """Substitute-all reverse engine (``processWordSubstituteAllReverse``,
    ``main.go:369-440``).

    Starts from ALL unique patterns substituted (first option only — Q2) and
    recursively removes patterns in index order, visiting every subset of the
    pattern set exactly once, from the full set down to ``min`` — emitting
    those whose size is within ``[min, max]``.
    """
    patterns = unique_patterns_in_word(word, sub_map)
    if len(patterns) < min_substitute:
        return
    all_subs = {p: sub_map[p][0] for p in patterns if sub_map[p]}

    def generate_subsets(chosen: Dict[bytes, bytes], pos: int) -> Iterator[bytes]:
        count = len(chosen)
        if count < min_substitute:
            return
        if count <= max_substitute:
            yield _replace_all_cascade(word, chosen)
        if count <= min_substitute:
            return
        for i in range(pos, len(patterns)):
            pattern = patterns[i]
            if pattern not in chosen:
                continue
            rest = {k: v for k, v in chosen.items() if k != pattern}
            yield from generate_subsets(rest, i + 1)

    yield from generate_subsets(all_subs, 0)


def iter_candidates(
    word: bytes,
    sub_map: SubstitutionMap,
    min_substitute: int = 0,
    max_substitute: int = 15,
    *,
    substitute_all: bool = False,
    reverse: bool = False,
    bug_compat: bool = True,
) -> Iterator[bytes]:
    """Mode dispatcher, mirroring the reference driver (``main.go:80-92``)."""
    if substitute_all:
        if reverse:
            return process_word_substitute_all_reverse(
                word, sub_map, min_substitute, max_substitute
            )
        return process_word_substitute_all(
            word, sub_map, min_substitute, max_substitute
        )
    if reverse:
        return process_word_reverse(
            word, sub_map, min_substitute, max_substitute, bug_compat=bug_compat
        )
    return process_word(word, sub_map, min_substitute, max_substitute)

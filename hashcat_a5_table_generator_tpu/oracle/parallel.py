"""Multi-process oracle: a real ``--threads N`` for the byte-exact engines.

The reference bounds per-word goroutines with ``--threads``
(``main.go:36-38``, ``main.go:70-94``) at the cost of nondeterministic
cross-word interleave on the shared output channel.  Here N worker
*processes* expand words round-robin (worker ``w`` owns words
``w, w+N, ...``) and the parent drains their per-word output **in word
order**, so the stream is byte-identical to ``--threads 1`` — the
reference's single-thread order — at any N.  A strictly stronger
contract than the reference's, at the same parallelism.

Workers run the same :func:`oracle.engines.iter_candidates` generators
and the same :class:`runtime.sinks.CandidateWriter` encoding (``$HEX[]``
wrapping included) into in-memory chunks, so the merged stream cannot
drift from the sequential path.  Crack mode ships only (digest, plain)
hits — candidates never cross the process boundary.

Linux ``fork`` start method: workers inherit the word list and table by
copy-on-write; nothing is pickled per word.
"""

from __future__ import annotations

import io
import multiprocessing as mp
import traceback
from typing import (
    TYPE_CHECKING,
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

if TYPE_CHECKING:  # runtime import cycle + optional toolchain
    # multiprocessing.Queue is a typeshed *function*; the class generic
    # usable in annotations lives in multiprocessing.queues.
    from multiprocessing.queues import Queue as MpQueue

    from ..native.oracle_engine import NativeDefaultOracle
    from ..ops.membership import HostDigestLookup
    from ..runtime.sinks import CandidateWriter

#: Flush worker output to the parent at this granularity: large enough to
#: amortize queue overhead, small enough to bound memory at
#: N workers x queue depth x chunk.
_CHUNK_BYTES = 1 << 18

#: Per-worker queue depth (backpressure: a fast worker blocks instead of
#: buffering unboundedly ahead of the in-order writer).
_QUEUE_DEPTH = 8

_ERROR = -1  # sentinel word index carrying a worker traceback


def _maybe_native(
    sub_map: Dict[bytes, List[bytes]], kw: Dict[str, Any], *,
    hex_unsafe: bool,
) -> "Optional[NativeDefaultOracle]":
    """A NativeDefaultOracle when the ONE shared predicate admits this
    mode/config, else None — the single engine-selection point for both
    worker kinds (candidates pass their writer's hex_unsafe; crack passes
    False, since potfile hit lines never $HEX[]-wrap candidates)."""
    try:
        from ..native.oracle_engine import (
            NativeDefaultOracle,
            available,
            default_engine_eligible,
        )

        if default_engine_eligible(
            sub_map,
            substitute_all=bool(kw.get("substitute_all")),
            reverse=bool(kw.get("reverse")),
            crack=False,
            hex_unsafe=hex_unsafe,
            max_substitute=int(kw.get("max_substitute", 15)),
        ) and available():
            return NativeDefaultOracle(sub_map)
    except Exception:  # pragma: no cover - toolchain-dependent
        pass
    return None



def _worker_candidates(
    wid: int,
    n_workers: int,
    words: Sequence[bytes],
    sub_map: Dict[bytes, List[bytes]],
    kw: Dict[str, Any],
    hex_unsafe: bool,
    out_q: "MpQueue[Tuple[int, Any, bool]]",
) -> None:
    """Expand words ``wid, wid+N, ...``; emit per-word encoded chunks
    ``(word_idx, (blob, n_candidates), last)`` in word order.

    Default and substitute-all non-``$HEX[]`` runs use the native C++
    engines when the toolchain provides them — same byte stream, ~17x
    faster (the ONE shared predicate:
    ``native.oracle_engine.default_engine_eligible``)."""
    from ..runtime.sinks import CandidateWriter
    from .engines import iter_candidates

    native = _maybe_native(sub_map, kw, hex_unsafe=hex_unsafe)

    try:
        for i in range(wid, len(words), n_workers):
            if native is not None:
                # Stream chunks straight to the queue (bounded memory for
                # huge words); an empty final marker closes the word.
                if kw.get("substitute_all") and kw.get("reverse"):
                    stream = native.stream_word_suball_reverse
                elif kw.get("substitute_all"):
                    stream = native.stream_word_suball
                else:
                    stream = native.stream_word
                stream(
                    words[i], kw.get("min_substitute", 0),
                    kw.get("max_substitute", 15),
                    lambda blob: out_q.put(
                        (i, (blob, blob.count(b"\n")), False)
                    ),
                )
                out_q.put((i, (b"", 0), True))
                continue
            buf = io.BytesIO()
            writer = CandidateWriter(buf, hex_unsafe=hex_unsafe)
            sent = 0
            for cand in iter_candidates(words[i], sub_map, **kw):
                writer.emit(cand)
                if buf.tell() >= _CHUNK_BYTES:
                    out_q.put(
                        (i, (buf.getvalue(), writer.n_written - sent),
                         False)
                    )
                    sent = writer.n_written
                    buf.seek(0)
                    buf.truncate()
            out_q.put((i, (buf.getvalue(), writer.n_written - sent), True))
    except BaseException:
        out_q.put((_ERROR, traceback.format_exc().encode(), True))


def _worker_crack(
    wid: int,
    n_workers: int,
    words: Sequence[bytes],
    sub_map: Dict[bytes, List[bytes]],
    kw: Dict[str, Any],
    algo: str,
    digests: "HostDigestLookup",
    out_q: "MpQueue[Tuple[int, Any, bool]]",
) -> None:
    """Hash every candidate of this worker's words; emit per-word hit
    lists ``(word_idx, [(digest_hex, cand)], True)``.  Generation feeds
    from the native engines when the mode fits (hashing stays Python —
    hashlib's C MD5 — but generation dominated the loop)."""
    from ..utils.digests import HOST_DIGEST
    from .engines import iter_candidates

    native = _maybe_native(sub_map, kw, hex_unsafe=False)

    def word_iter(word: bytes) -> "Any":
        if native is not None:
            return native.iter_word(
                word, kw.get("min_substitute", 0),
                kw.get("max_substitute", 15),
                substitute_all=bool(kw.get("substitute_all")),
                reverse=bool(kw.get("reverse")),
            )
        return iter_candidates(word, sub_map, **kw)

    try:
        lookup = digests  # a HostDigestLookup, built once pre-fork (COW)
        host_digest = HOST_DIGEST[algo]
        for i in range(wid, len(words), n_workers):
            hits: List[Tuple[str, bytes]] = []
            for cand in word_iter(words[i]):
                dig = host_digest(cand)
                if dig in lookup:
                    hits.append((dig.hex(), cand))
            out_q.put((i, hits, True))
    except BaseException:
        out_q.put((_ERROR, traceback.format_exc().encode(), True))


class OracleWorkerError(RuntimeError):
    """A worker process raised; carries its traceback text."""


def _fork_ctx() -> mp.context.BaseContext:
    """The fork start context (workers inherit words/tables by
    copy-on-write; args are never pickled) — with a clear error where
    fork does not exist (Windows) instead of a raw ValueError."""
    if "fork" not in mp.get_all_start_methods():
        raise OracleWorkerError(
            "--threads N needs the fork start method (Linux); "
            "use --threads 1 on this platform"
        )
    return mp.get_context("fork")


def _drain_in_order(
    queues: "Sequence[MpQueue[Tuple[int, Any, bool]]]",
    procs: Sequence[mp.Process],
    n_words: int,
    n_workers: int,
    consume: Callable[[int, Any], None],
) -> None:
    """Pull each word's items from its owner's queue, in global word
    order (each worker produces ITS words in increasing order, so
    per-queue arrival order matches).  A worker that dies WITHOUT its
    error sentinel (OOM kill, segfault) is detected by liveness checks
    on queue timeouts instead of hanging the parent forever."""
    import queue as queue_mod

    for i in range(n_words):
        q = queues[i % n_workers]
        while True:
            try:
                idx, payload, last = q.get(timeout=30.0)
            except queue_mod.Empty:
                p = procs[i % n_workers]
                if not p.is_alive() and q.empty():
                    raise OracleWorkerError(
                        f"oracle worker {i % n_workers} died without a "
                        f"traceback (exitcode {p.exitcode}) — killed by "
                        "the OS? (out of memory?)"
                    )
                continue
            if idx == _ERROR:
                raise OracleWorkerError(payload.decode())
            assert idx == i, f"worker stream out of order: {idx} != {i}"
            consume(i, payload)
            if last:
                break


def run_candidates_parallel(
    words: Sequence[bytes],
    sub_map: Dict[bytes, List[bytes]],
    writer: "CandidateWriter",
    *,
    n_workers: int,
    hex_unsafe: bool = False,
    **iter_kw: Any,
) -> int:
    """Stream every word's candidates to ``writer`` in reference
    (``--threads 1``) order using ``n_workers`` processes.  Returns the
    number of candidate lines written."""
    words = list(words)
    n_workers = max(1, min(n_workers, len(words) or 1))
    ctx = _fork_ctx()
    # Warm the native oracle build/load ONCE pre-fork: children inherit
    # the loaded library instead of racing N cold g++ builds.
    try:
        from ..native.oracle_engine import available as _native_available

        _native_available()
    except Exception:  # pragma: no cover - toolchain-dependent
        pass
    queues = [ctx.Queue(maxsize=_QUEUE_DEPTH) for _ in range(n_workers)]
    procs = [
        ctx.Process(
            target=_worker_candidates,
            args=(w, n_workers, words, sub_map, iter_kw, hex_unsafe,
                  queues[w]),
            daemon=True,
        )
        for w in range(n_workers)
    ]
    for p in procs:
        p.start()
    wrote = [0]

    def consume(i: int, payload: Tuple[bytes, int]) -> None:
        blob, n = payload
        if blob:
            writer.write_block(blob, n)
            wrote[0] += n

    try:
        _drain_in_order(queues, procs, len(words), n_workers, consume)
    finally:
        for p in procs:
            p.terminate()
            p.join(timeout=10)
    return wrote[0]


def run_crack_parallel(
    words: Sequence[bytes],
    sub_map: Dict[bytes, List[bytes]],
    digests: "Any",
    algo: str,
    on_hit: Callable[[str, bytes], None],
    *,
    n_workers: int,
    **iter_kw: Any,
) -> int:
    """Oracle crack across ``n_workers`` processes; ``on_hit(digest_hex,
    cand)`` fires in reference word order.  Returns the hit count."""
    from ..ops.membership import HostDigestLookup

    words = list(words)
    n_workers = max(1, min(n_workers, len(words) or 1))
    ctx = _fork_ctx()
    # Warm the native oracle build/load ONCE pre-fork (see
    # run_candidates_parallel): crack workers use the engine too.
    try:
        from ..native.oracle_engine import available as _native_available

        _native_available()
    except Exception:  # pragma: no cover - toolchain-dependent
        pass
    # Build the sorted lookup ONCE pre-fork: workers inherit it by
    # copy-on-write instead of each re-sorting a hashmob-scale matrix.
    lookup = (digests if isinstance(digests, HostDigestLookup)
              else HostDigestLookup(digests))
    queues = [ctx.Queue(maxsize=_QUEUE_DEPTH) for _ in range(n_workers)]
    procs = [
        ctx.Process(
            target=_worker_crack,
            args=(w, n_workers, words, sub_map, iter_kw, algo, lookup,
                  queues[w]),
            daemon=True,
        )
        for w in range(n_workers)
    ]
    for p in procs:
        p.start()
    n_hits = [0]

    def consume(i: int, hits: List[Tuple[str, bytes]]) -> None:
        for dig_hex, cand in hits:
            on_hit(dig_hex, cand)
            n_hits[0] += 1

    try:
        _drain_in_order(queues, procs, len(words), n_workers, consume)
    finally:
        for p in procs:
            p.terminate()
            p.join(timeout=10)
    return n_hits[0]

"""The command-line surface: reference-compatible flags plus the TPU engine.

The reference CLI (kong struct, ``main.go:18-26``) is one positional arg and
six flags; those are reproduced verbatim so existing invocations keep
working (``README.MD``'s documented names are outdated — kong's actual
surface is the contract, Q11):

  a5gen DICT_FILE -t TABLE [-t TABLE ...] [-m MIN] [-x MAX]
        [--threads N] [-s] [-r]

New surface (the engine lift, ``BASELINE.json`` north star):

* ``--backend {oracle,device}`` — ``oracle`` streams the byte-exact CPU
  engines in reference ``--threads 1`` order (file order, DFS order —
  SURVEY.md Q9); ``device`` runs the JAX sweep runtime (TPU when available,
  multiset-per-word parity, rank order within words).
* ``--algo``, ``--digests FILE`` — crack mode: hash on device, match a
  digest list, print ``digest:plain`` hits instead of candidates.
* ``--checkpoint FILE`` / ``--checkpoint-every S`` — resumable sweeps.
* ``--emit-table NAME`` / ``--list-layouts`` — the layout-map → ``.table``
  emitter (regenerates the reference's checked-in artifacts byte-exactly).
* ``--coordinator HOST:PORT --num-processes N --process-id I`` — the pod
  story (SURVEY.md §2.3/§5): every host runs the same command with its own
  rank; each sweeps a contiguous dictionary stripe on its local devices
  (``parallel.multihost``), hit records all-gather over DCN, and process 0
  reports the combined result.  A 2-host crack launch looks like::

      host0$ a5gen rockyou.txt -t qwerty-cyrillic.table --backend device \
                 --digests left.txt --coordinator host0:8476 \
                 --num-processes 2 --process-id 0
      host1$ a5gen rockyou.txt -t qwerty-cyrillic.table --backend device \
                 --digests left.txt --coordinator host0:8476 \
                 --num-processes 2 --process-id 1

* ``--progress``, ``--lanes``, ``--blocks``, ``--hex-unsafe``,
  ``--bug-compat`` (reproduce the reference's Q3 reverse-offset bug in the
  oracle), ``--max-word-bytes`` (the anti-Q8 guard, default 64 KiB).

``--threads N`` parallelizes the ORACLE backend across N worker processes
with an in-order merge, so the stream stays byte-identical to
``--threads 1`` at any N (``oracle.parallel``; stronger than the
reference, whose goroutines interleave output nondeterministically,
``main.go:70-94``). The device backend batches its own parallelism and
ignores the flag.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional, Sequence

from .oracle.engines import iter_candidates
from .utils.digests import HOST_DIGEST
from .tables.layouts import BUILTIN_LAYOUTS, DERIVED_LAYOUTS, get_layout, emit_table
from .tables.parser import load_tables

PROG = "a5gen"
DIGEST_BYTES = {"md5": 16, "md4": 16, "ntlm": 16, "sha1": 20}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog=PROG,
        description=(
            "TPU-native table-lookup candidate engine (hashcat -a 5 style): "
            "apply substitution tables to a dictionary and stream variants, "
            "or hash them on-device against a digest list."
        ),
    )
    # --- reference-compatible surface (main.go:18-26) ---------------------
    ap.add_argument("dict_file", nargs="?",
                    help="dictionary file, one word per line")
    ap.add_argument("-t", "--table-files", action="append", default=[],
                    metavar="FILE",
                    help="substitution table (repeatable; later tables "
                         "append alternative substitutions per key)")
    ap.add_argument("-m", "--table-min", type=int, default=0,
                    help="minimum substitutions per candidate (default 0)")
    ap.add_argument("-x", "--table-max", type=int, default=15,
                    help="maximum substitutions per candidate (default 15)")
    ap.add_argument("--threads", type=int, default=-1,
                    help="oracle backend: expand words across N worker "
                         "processes; the stream STAYS byte-identical to "
                         "--threads 1 (in-order merge — stronger than the "
                         "reference, whose threads interleave output "
                         "nondeterministically). <=1 or unset = "
                         "sequential. The device backend batches its own "
                         "parallelism and ignores this")
    ap.add_argument("-s", "--substitute-all", action="store_true",
                    help="substitution-cipher mode: choose per unique "
                         "pattern, not per occurrence")
    ap.add_argument("-r", "--reverse-sub", action="store_true",
                    help="reverse mode: start from most-substituted, "
                         "first option per key only")
    # --- engine surface ---------------------------------------------------
    ap.add_argument("--backend", choices=("oracle", "device"),
                    default="oracle",
                    help="oracle: byte-exact CPU reference engines in "
                         "deterministic DFS order; device: JAX sweep "
                         "(TPU when available; per-word multiset parity)")
    ap.add_argument("--algo", choices=sorted(DIGEST_BYTES), default="md5",
                    help="hash algorithm for --digests mode")
    ap.add_argument("--digests", metavar="FILE",
                    help="hex digest list (one per line); switches to crack "
                         "mode: print digest:plain hits instead of "
                         "candidates")
    ap.add_argument("--checkpoint", metavar="FILE",
                    help="checkpoint path for resumable sweeps "
                         "(device backend)")
    ap.add_argument("--checkpoint-every", type=float, default=30.0,
                    metavar="SECONDS", help="checkpoint interval")
    ap.add_argument("--no-resume", action="store_true",
                    help="ignore an existing checkpoint and start over")
    ap.add_argument("--retries", type=int, default=0, metavar="N",
                    help="re-run a failed device sweep up to N times, "
                         "resuming from the last checkpoint (chip loss / "
                         "backend errors — SURVEY.md §5). Crack mode is "
                         "exactly-once: hits dedupe across attempts. "
                         "Candidates mode requires --checkpoint and is "
                         "at-least-once: candidates emitted since the last "
                         "checkpoint repeat after a retry (bound the window "
                         "with --checkpoint-every; a notice marks each "
                         "retry on stderr)")
    ap.add_argument("--fetch-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="device backend: watchdog on each consumed "
                         "device fetch — a fetch still pending after "
                         "SECONDS raises a typed FetchTimeout, which "
                         "the drive's transient-retry supervisor "
                         "re-dispatches from the last fetched boundary "
                         "(PERF.md §23). Default off: CPU sweeps and "
                         "cold compiles legitimately stall longer than "
                         "any sane timeout")
    ap.add_argument("--progress", action="store_true",
                    help="periodic JSON progress lines on stderr")
    ap.add_argument("--lanes", type=int, default=None,
                    help="variant lanes per device per launch (default: "
                         "this device kind's autotune profile when one "
                         "exists — `a5gen tune`, PERF.md §29 — else 2^22 "
                         "on accelerators and 2^17 on CPU; "
                         "A5GEN_TUNE_PROFILE=off pins the built-ins)")
    ap.add_argument("--blocks", type=int, default=None,
                    help="device block slots per launch (default: the "
                         "autotune profile when one exists, else auto — "
                         "on accelerators the sweep picks the measured best "
                         "stride for the engaged kernel, 512/256 fused vs "
                         "128 XLA; 1024 on CPU)")
    ap.add_argument("--fetch-chunk", type=_positive_int, default=None,
                    metavar="N",
                    help="crack mode: max launches whose counts accumulate "
                         "on device between host fetches (a fetch costs a "
                         "full round trip over remote-device links; chunks "
                         "grow adaptively 1..N; default: the sweep "
                         "runtime's tuned value — PERF.md §4b)")
    ap.add_argument("--superstep", type=_superstep_arg, default=None,
                    metavar="N|auto|off",
                    help="crack mode: fuse N launches into one device "
                         "dispatch via the device-resident superstep "
                         "executor — block cutting runs ON DEVICE from "
                         "per-sweep index arrays and the host fetches "
                         "counters + hits once per superstep (PERF.md "
                         "§15). 'auto' (default) engages when the plan "
                         "and geometry qualify, with --fetch-chunk steps "
                         "per superstep; 'off' keeps the per-launch "
                         "pipeline (A5GEN_SUPERSTEP=off is the env "
                         "equivalent). The candidate/hit streams are "
                         "identical either way")
    ap.add_argument("--pair", choices=("auto", "on", "off"),
                    default="auto",
                    help="crack mode: pair-lane tier — pack 2 candidates "
                         "per hash lane where the substitution geometry "
                         "allows (schema-compile decides eligibility; "
                         "PERF.md §24). 'auto' (default) engages when "
                         "eligible; 'on' additionally WARNS when the "
                         "plan is ineligible and K=1 runs; 'off' keeps "
                         "K=1 (A5GEN_PAIR=off is the env equivalent). "
                         "The candidate/hit streams are identical "
                         "either way")
    ap.add_argument("--stream-chunk-words", type=_stream_chunk_arg,
                    default="auto", metavar="N|auto|off",
                    help="device backend: compile the dictionary's plan "
                         "in word CHUNKS on a host worker thread while "
                         "the device sweeps the previous chunk, freeing "
                         "consumed chunks — resident plan memory stays "
                         "O(chunk) at any dictionary size, and time-to-"
                         "first-candidate drops to one chunk's schema "
                         "compile plus a light whole-dictionary prescan "
                         "(PERF.md §19). 'auto' (default) engages "
                         "when the dictionary spans more than one "
                         "~64 MB-of-plan chunk; 'off' always "
                         "materializes the whole plan "
                         "(A5GEN_STREAM=off is the env equivalent); N "
                         "chunks at N words. The candidate/hit streams "
                         "and checkpoints are identical either way")
    ap.add_argument("--schema-cache", metavar="DIR",
                    help="device backend: persist compiled per-slot "
                         "piece schemas under DIR (keyed by wordlist x "
                         "table digest + format version), so repeat "
                         "sweeps of the same inputs skip schema "
                         "compilation (A5GEN_SCHEMA_CACHE is the env "
                         "equivalent)")
    ap.add_argument("--schema-cache-max-mb", type=float, default=None,
                    metavar="MB",
                    help="LRU size cap on the --schema-cache directory: "
                         "after each write, oldest-atime entries are "
                         "evicted until the cache fits (long-lived "
                         "service processes must not grow it without "
                         "bound; A5GEN_SCHEMA_CACHE_MAX_MB is the env "
                         "equivalent; default unbounded)")
    ap.add_argument("--block-layout", choices=("auto", "packed", "stride"),
                    default="auto",
                    help="variant-block layout: 'packed' = tightly-packed "
                         "variable offsets (no lanes wasted on word tails; "
                         "lane->block is a per-lane binary search), "
                         "'stride' = fixed lanes-per-block (stride = "
                         "lanes/blocks; arithmetic lane->block map). "
                         "Default 'auto' picks stride whenever the "
                         "block count divides lanes evenly — it measures "
                         "faster on every backend (PERF.md §4c); the "
                         "layouts are stream-identical")
    ap.add_argument("--devices", type=_devices_arg, default=1, metavar="N",
                    help="shard the sweep over N local devices via a 1-D "
                         "mesh ('auto' = all local devices; default 1)")
    ap.add_argument("--buckets", type=_buckets_arg, default="auto",
                    metavar="W1,W2,...",
                    help="length-bucket boundaries for the device backend: "
                         "one compiled program per bucket width, so one "
                         "long line does not inflate every lane. 'none' = "
                         "single global width, strict dictionary-order "
                         "candidate stream. Default: 16,32,64 in crack mode "
                         "(--digests); none in candidates mode, so the "
                         "stream diffs against the reference without a "
                         "bucket-major permutation")
    ap.add_argument("--coordinator", metavar="HOST:PORT",
                    help="multi-host sweep: jax.distributed coordinator "
                         "address (run the same command on every host with "
                         "its own --process-id); each host sweeps a "
                         "contiguous stripe of the dictionary on its local "
                         "devices, and hit records are all-gathered over "
                         "the host network")
    ap.add_argument("--num-processes", type=int, default=None, metavar="N",
                    help="multi-host sweep: total participating processes")
    ap.add_argument("--process-id", type=int, default=None, metavar="I",
                    help="multi-host sweep: this process's rank in [0, N)")
    ap.add_argument("--giant-job", action="store_true",
                    help="pod-sharded giant-job mode (crack only, "
                         "PERF.md §29): instead of striping the "
                         "DICTIONARY across hosts, every process sweeps "
                         "the SAME full wordlist and the superstep block "
                         "lattice is striped across ALL the pod's chips — "
                         "one oversized keyspace job, checkpointable and "
                         "resumable as ONE job whose (word, rank) cursor "
                         "is interchangeable with a single-device sweep's. "
                         "Requires --coordinator and the superstep "
                         "executor; combine with --pod-hits local for the "
                         "elastic variant")
    ap.add_argument("--pod-hits", choices=("gathered", "local"),
                    default="gathered",
                    help="multi-host hit reporting: 'gathered' (default) "
                         "all-gathers hit records and process 0 prints the "
                         "combined stream; 'local' prints each host's own "
                         "stripe's hits on its own stdout with NO "
                         "cross-host collectives — fully elastic (a dead "
                         "peer cannot block survivors; relaunch only its "
                         "stripe)")
    ap.add_argument("--profile", metavar="DIR",
                    help="write a jax.profiler trace of the device sweep to "
                         "DIR (inspect with TensorBoard / Perfetto); host "
                         "stages are annotated (block cutting, output fetch)")
    ap.add_argument("--profile-dir", metavar="DIR", dest="profile",
                    help="alias of --profile: wrap the sweep in "
                         "jax.profiler.trace(DIR) with per-superstep "
                         "TraceAnnotation phase spans — a guarded no-op "
                         "when the profiler is unavailable on this jax "
                         "version (PERF.md §21)")
    ap.add_argument("--metrics-json", metavar="FILE",
                    help="after the sweep, write the final telemetry "
                         "snapshot (metrics registry + per-sweep span "
                         "summary) as JSON to FILE; A5GEN_TELEMETRY=off "
                         "disables the instrumentation (PERF.md §21)")
    ap.add_argument("--hex-unsafe", action="store_true",
                    help="wrap line-corrupting candidates in $HEX[...]")
    ap.add_argument("--bug-compat", action="store_true",
                    help="reproduce the reference's reverse-mode offset bug "
                         "(Q3) in the oracle backend")
    ap.add_argument("--max-word-bytes", type=int, default=64 * 1024,
                    help="reject dictionary lines longer than this instead "
                         "of silently truncating input (reference Q8)")
    # --- layout emitter ---------------------------------------------------
    ap.add_argument("--emit-table", metavar="LAYOUT",
                    help="write a built-in layout as a .table file to stdout "
                         "(or --output) and exit")
    ap.add_argument("--output", metavar="FILE",
                    help="output path for --emit-table")
    ap.add_argument("--list-layouts", action="store_true",
                    help="list built-in and derived layouts and exit")
    return ap


def _buckets_arg(value: str):
    """--buckets: comma-separated ascending widths, 'none', or 'auto'
    (mode-dependent default: 16,32,64 in crack mode, none in candidates)."""
    if value == "auto":
        return "auto"
    if value == "none":
        return None
    try:
        widths = tuple(int(v) for v in value.split(","))
        if not widths or any(w < 4 for w in widths) or any(
            a >= b for a, b in zip(widths, widths[1:])
        ):
            raise ValueError
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"must be ascending widths >= 4 (e.g. 16,32,64) or 'none', "
            f"got {value!r}"
        )
    return widths


def _superstep_arg(value: str):
    """--superstep: 'auto' (None — engage when eligible), 'off' (0), or
    a positive steps-per-superstep count."""
    if value == "auto":
        return None
    if value == "off":
        return 0
    try:
        n = int(value)
        if n < 1:
            raise ValueError
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"must be a positive integer, 'auto', or 'off', got {value!r}"
        )
    return n


def _stream_chunk_arg(value: str):
    """--stream-chunk-words: 'auto' (engage when the dictionary spans
    >1 auto-sized chunk), 'off' (always whole-dictionary), or a positive
    chunk word count."""
    if value in ("auto", "off"):
        return value
    try:
        n = int(value)
        if n < 1:
            raise ValueError
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"must be a positive integer, 'auto', or 'off', got {value!r}"
        )
    return n


def _positive_int(value: str):
    try:
        n = int(value)
        if n < 1:
            raise ValueError
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"must be a positive integer, got {value!r}"
        )
    return n


def _devices_arg(value: str):
    """--devices: positive int, or 'auto' (None) = all local devices."""
    if value == "auto":
        return None
    try:
        n = int(value)
        if n < 1:
            raise ValueError
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"must be a positive integer or 'auto', got {value!r}"
        )
    return n


def _mode(args) -> str:
    if args.substitute_all:
        return "suball-reverse" if args.reverse_sub else "suball"
    return "reverse" if args.reverse_sub else "default"


_HEX_LUT = None


def _parse_digest_blob(data: bytes, want: int, path: str) -> "list | None":
    """Vectorized left-list parse: the whole file as one numpy pass.

    Hashmob-scale left lists run to tens of millions of lines; the
    per-line ``fromhex`` loop costs minutes there, this path seconds.
    Returns None — caller falls back to the exact per-line loop — on
    inputs the vector path doesn't model (leading whitespace) AND on any
    malformed line, so error messages always come from the loop and
    match it exactly."""
    import numpy as np

    global _HEX_LUT
    if _HEX_LUT is None:
        lut = np.full(256, 255, dtype=np.uint8)
        for i in range(10):
            lut[ord("0") + i] = i
        for i in range(6):
            lut[ord("a") + i] = 10 + i
            lut[ord("A") + i] = 10 + i
        _HEX_LUT = lut

    if not data:
        return []
    if not data.endswith(b"\n"):
        data += b"\n"  # one whole-blob copy, only for newline-less tails
    arr = np.frombuffer(data, dtype=np.uint8)
    nl = np.flatnonzero(arr == 10)
    starts = np.concatenate(([0], nl[:-1] + 1)).astype(np.int64)
    ends = nl
    # Strip one trailing \r (CRLF files).
    lens = ends - starts
    has_cr = (lens > 0) & (arr[np.maximum(ends - 1, 0)] == 13)
    lens = lens - has_cr
    first = arr[np.minimum(starts, arr.shape[0] - 1)]
    nonblank = lens > 0
    if bool((nonblank & ((first == 32) | (first == 9))).any()):
        return None  # leading whitespace: slow path owns full strip()
    keep = nonblank & (first != ord("#"))
    ks, kl = starts[keep], lens[keep]
    if ks.shape[0] == 0:
        return []
    # The digest is the first field: exactly 2*want hex chars, then end
    # of line or ':'.
    sep_pos = np.minimum(ks + 2 * want, arr.shape[0] - 1)
    bad = (kl < 2 * want) | ((kl > 2 * want) & (arr[sep_pos] != ord(":")))
    if int(ks[-1]) + 2 * want > arr.shape[0]:
        return None  # short final line: the loop reports it exactly
    # No per-element clamp: the scalar bound check above covers the only
    # line that could overrun (ks is ascending); int32 offsets while the
    # file fits (hashmob-scale lists can exceed 2 GiB — then int64).
    off_t = np.int32 if arr.shape[0] < (1 << 31) else np.int64
    if bool(bad.any()):
        return None  # malformed line somewhere: loop raises the exact error
    # Decode in bounded chunks: the [C, 2*want] gather/index intermediates
    # cost ~70x the digest width per row, which at hashmob scale (50M+
    # lines) would otherwise peak several GiB above the output matrix.
    n = ks.shape[0]
    mat = np.empty((n, want), dtype=np.uint8)
    chunk = 1 << 20
    rng = np.arange(2 * want, dtype=off_t)
    for lo in range(0, n, chunk):
        sub = ks[lo:lo + chunk].astype(off_t)[:, None] + rng
        nib = _HEX_LUT[arr[sub]]
        if bool((nib == 255).any()):
            return None  # bad hex: loop raises the exact error
        mat[lo:lo + chunk] = (nib[:, 0::2] << 4) | nib[:, 1::2]
    return mat


def _read_digests(path: str, algo: str):
    """Load a digest left-list: returns an ``[N, digest_bytes] uint8``
    matrix (vectorized fast path) or a ``List[bytes]`` (fallback) — both
    accepted by the sweep and :func:`ops.membership.build_digest_set`."""
    want = DIGEST_BYTES[algo]
    with open(path, "rb") as fh:
        data = fh.read()
    fast = _parse_digest_blob(data, want, path)
    if fast is not None:
        return fast
    out: List[bytes] = []
    # split(b"\n"), not splitlines(): file iteration splits on \n only (a
    # lone \r is line CONTENT — e.g. a CR-separated file is one long bad
    # line), and the vector path above models the same rule.
    for ln, raw in enumerate(data.split(b"\n"), 1):
        line = raw.strip()
        if not line or line.startswith(b"#"):
            continue
        # hashcat-style lines may carry :salt/:plain suffixes; the
        # digest is the first field.
        field = line.split(b":", 1)[0]
        try:
            dig = bytes.fromhex(field.decode("ascii"))
        except (UnicodeDecodeError, ValueError) as e:
            raise SystemExit(
                f"{path}:{ln}: not a hex digest: {field[:40]!r} ({e})"
            )
        if len(dig) != want:
            raise SystemExit(
                f"{path}:{ln}: {len(dig)}-byte digest, {algo} needs {want}"
            )
        out.append(dig)
    return out


def _run_emit_table(args) -> int:
    layout = get_layout(args.emit_table)
    if args.output:
        emit_table(layout, args.output)
    else:
        sys.stdout.buffer.write(layout.to_table_bytes())
    return 0


def _run_list_layouts() -> int:
    for name in sorted(BUILTIN_LAYOUTS):
        print(f"{name}\t(built-in)\t{BUILTIN_LAYOUTS[name].description}")
    for name in sorted(DERIVED_LAYOUTS):
        print(f"{name}\t(derived)\t{DERIVED_LAYOUTS[name].description}")
    return 0


def native_default_eligible(sub_map, mode: str, crack: bool,
                            hex_unsafe: bool,
                            max_substitute: int = 15) -> bool:
    """Whether the C++ default-engine oracle can serve this run (thin
    shim over the ONE shared predicate,
    ``native.oracle_engine.default_engine_eligible`` — the --threads
    workers use the same one, so the two paths can never drift)."""
    from .native.oracle_engine import default_engine_eligible

    return default_engine_eligible(
        sub_map,
        substitute_all=mode.startswith("suball"),
        reverse=mode in ("reverse", "suball-reverse"),
        crack=crack,
        hex_unsafe=hex_unsafe,
        max_substitute=max_substitute,
    )


def _native_default_engine(args, sub_map, mode: str, crack: bool,
                           hex_unsafe: "bool | None" = None):
    """A ready NativeDefaultOracle, or None (ineligible / no toolchain /
    A5_NATIVE=0 — the Python engines remain the behavior).
    ``hex_unsafe`` overrides the flag for callers whose output never
    wraps (crack's potfile lines)."""
    hu = args.hex_unsafe if hex_unsafe is None else hex_unsafe
    if not native_default_eligible(sub_map, mode, crack, hu,
                                   args.table_max):
        return None
    try:
        from .native.oracle_engine import NativeDefaultOracle, available

        if not available():
            return None
        return NativeDefaultOracle(sub_map)
    except Exception as e:  # pragma: no cover - toolchain-dependent
        print(f"{PROG}: native oracle unavailable ({e}); Python engine",
              file=sys.stderr)
        return None


def _run_oracle(args, sub_map, words) -> int:
    """Reference semantics, reference order (--threads 1): word order,
    DFS order within each word (Q9)."""
    from .runtime.sinks import CandidateWriter, potfile_line

    from .ops.membership import HostDigestLookup

    mode = _mode(args)
    crack = args.digests is not None
    iter_kw = dict(
        min_substitute=args.table_min,
        max_substitute=args.table_max,
        substitute_all=mode.startswith("suball"),
        reverse=mode in ("reverse", "suball-reverse"),
        bug_compat=args.bug_compat,
    )
    if args.threads and args.threads > 1:
        # Multi-process oracle (oracle.parallel): same byte stream, N
        # cores — the in-order merge keeps --threads 1 order at any N.
        from .oracle.parallel import (
            run_candidates_parallel,
            run_crack_parallel,
        )

        with CandidateWriter(hex_unsafe=args.hex_unsafe) as writer:
            if crack:
                def on_hit(dig_hex: str, cand: bytes) -> None:
                    writer.write_block(potfile_line(dig_hex, cand), 1)
                    writer.flush()

                n_hits = run_crack_parallel(
                    words, sub_map,
                    _read_digests(args.digests, args.algo), args.algo,
                    on_hit, n_workers=args.threads, **iter_kw,
                )
            else:
                run_candidates_parallel(
                    words, sub_map, writer, n_workers=args.threads,
                    hex_unsafe=args.hex_unsafe, **iter_kw,
                )
        if crack:
            print(f"{n_hits} hits", file=sys.stderr)
        return 0
    native_eng = _native_default_engine(args, sub_map, mode, crack)
    if native_eng is not None:
        # Engines A, C and D (default / substitute-all / suball-reverse)
        # stream from the C++ oracle — the same byte stream ~17x faster
        # (native/oracle.cpp; parity pinned by tests/test_native.py).
        stream = {
            "suball": native_eng.stream_word_suball,
            "suball-reverse": native_eng.stream_word_suball_reverse,
        }.get(mode, native_eng.stream_word)
        with CandidateWriter(hex_unsafe=args.hex_unsafe) as writer:
            for word in words:
                stream(
                    word, args.table_min, args.table_max,
                    lambda b: writer.write_block(b, b.count(b"\n")),
                )
        return 0
    digest_set = HostDigestLookup(
        _read_digests(args.digests, args.algo) if crack else ()
    )
    host_digest = HOST_DIGEST[args.algo]
    # Crack mode iterates candidates (hash + membership per candidate);
    # generation dominates that loop, so the native engines feed it too
    # when the mode fits (output identical; only the iterator changes).
    crack_native = (
        _native_default_engine(args, sub_map, mode, crack=False,
                               hex_unsafe=False)
        if crack and mode in ("default", "suball", "suball-reverse")
        else None
    )

    def word_iter(word):
        if crack_native is not None:
            return crack_native.iter_word(
                word, args.table_min, args.table_max,
                substitute_all=mode.startswith("suball"),
                reverse=mode == "suball-reverse",
            )
        return iter_candidates(word, sub_map, **iter_kw)

    n_hits = 0
    with CandidateWriter(hex_unsafe=args.hex_unsafe) as writer:
        for word in words:
            for cand in word_iter(word):
                if crack:
                    dig = host_digest(cand)
                    if dig in digest_set:
                        n_hits += 1
                        writer.write_block(
                            potfile_line(dig.hex(), cand), 1
                        )
                        # Hits are rare and precious: land each one
                        # immediately (matches HitRecorder's per-hit flush).
                        writer.flush()
                else:
                    writer.emit(cand)
    if crack:
        print(f"{n_hits} hits", file=sys.stderr)
    return 0


class _DedupRecorder:
    """Hit recorder wrapper that drops (word, rank) duplicates.

    Used by the --retries loop: after an attempt dies mid-sweep, the next
    attempt's resume replays every checkpointed hit into its recorder —
    correct for a fresh process, duplicate output within one retrying
    process. The wrapper spans attempts, so each hit prints once per
    process while a genuinely fresh resume still prints the full list."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self._seen = set()

    def emit(self, record) -> None:
        key = (record.word_index, record.variant_rank)
        if key in self._seen:
            return
        self._seen.add(key)
        self.inner.emit(record)

    @property
    def hits(self):
        """The deduplicated hit list (Sweep.run_crack returns
        ``recorder.hits`` — the wrapper must keep the recorder contract)."""
        return self.inner.hits


def _print_routing(res) -> None:
    """Word-routing summary (stderr): device-clean / cascade-closed /
    oracle-fallback counts — the instrument behind the closure acceptance
    numbers (PERF.md §14). Silent when the whole dictionary is clean."""
    r = getattr(res, "routing", None) or {}
    if not (r.get("device_closed") or r.get("oracle_fallback")):
        return
    print(
        f"{PROG}: word routing: {r.get('device_clean', 0)} device-clean, "
        f"{r.get('device_closed', 0)} device-closed, "
        f"{r.get('oracle_fallback', 0)} oracle-fallback",
        file=sys.stderr,
    )


def _print_superstep(res) -> None:
    """Superstep-executor summary (stderr): supersteps run, launches per
    fetch, overflow replays — the per-launch-overhead instrument behind
    PERF.md §15.  Silent when the per-launch pipeline ran."""
    s = getattr(res, "superstep", None) or {}
    if not s.get("supersteps"):
        return
    pair = f", pair K={s['pair']}" if s.get("pair") else ""
    print(
        f"{PROG}: superstep: {s['supersteps']} supersteps x "
        f"{s.get('launches_per_fetch', 0)} launches/fetch "
        f"({s.get('replays', 0)} overflow replays{pair})",
        file=sys.stderr,
    )


def _print_stream(res) -> None:
    """Streaming-ingestion summary (stderr): chunks swept, compile
    overlap, peak resident plan bytes — the instruments behind the §19
    acceptance numbers.  Silent when the whole-dictionary path ran."""
    s = getattr(res, "stream", None) or {}
    if not s.get("chunks_swept"):
        return
    # A resumed streaming sweep reports its chunk position (the
    # CheckpointState.stream marker that placed it there).
    resumed = (
        f", resumed at chunk {s['resumed_chunk']}"
        if getattr(res, "resumed", False) and "resumed_chunk" in s
        else ""
    )
    print(
        f"{PROG}: stream: {s['chunks_swept']}/{s.get('chunks', 0)} chunks "
        f"x {s.get('chunk_words', 0)} words{resumed}, "
        f"{100.0 * s.get('overlap_ratio', 0.0):.0f}% compile overlapped, "
        f"peak plan {s.get('peak_resident_plan_bytes', 0) / 1e6:.1f} MB "
        f"(ttfc {s.get('ttfc_s', 0.0):.2f}s)",
        file=sys.stderr,
    )


def _print_geometry(res) -> None:
    """Resolved-geometry provenance (stderr, PERF.md §29): printed when
    the launch-time resolution seam filled the geometry (profile or
    built-in defaults), so no reported rate is ambiguous about which
    geometry produced it.  Silent for explicit flags — the caller
    already knows what they asked for."""
    src = getattr(res, "geometry_source", "explicit")
    g = getattr(res, "geometry", None) or {}
    if src == "explicit" or not g:
        return
    origin = (
        f"autotune profile ({g.get('device_kind')})" if src == "profile"
        else "built-in defaults"
    )
    print(
        f"{PROG}: geometry: lanes={g.get('lanes')} "
        f"blocks={g.get('num_blocks')} superstep={g.get('superstep')} "
        f"pair={g.get('pair')} — from {origin}",
        file=sys.stderr,
    )


def _run_with_retries(make_attempt, retries: int, *, default_resume: bool,
                      label: str, retry_notice: str = ""):
    """Elastic recovery (SURVEY.md §5): candidate generation is pure and
    cursors are durable, so a chip/backend loss is survived by rebuilding
    the sweep (fresh compiled steps, fresh device buffers) and resuming
    from the last checkpoint. ``make_attempt(resume: bool)`` runs one
    attempt; the first honors ``default_resume`` (--no-resume), later ones
    always resume."""
    import time as _time

    attempt = 0
    resume = default_resume
    while True:
        try:
            return make_attempt(resume)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # noqa: BLE001 — backend loss is not typed
            attempt += 1
            if attempt > retries:
                raise
            print(
                f"{PROG}: {label} attempt failed "
                f"({type(e).__name__}: {e}); retry {attempt}/{retries} "
                f"from last checkpoint{retry_notice}",
                file=sys.stderr,
            )
            resume = True  # later attempts always resume
            _time.sleep(min(2.0 * attempt, 10.0))


def _maybe_exit_pod_local(args, nprocs: int) -> None:
    """Elastic-mode exit: ``--pod-hits local`` promises a dead peer can
    never block a survivor, so the cooperative shutdown barrier must not
    run — ``parallel.multihost.pod_local_done_exit`` implements the
    done/dead wait (process 0 lingers as coordination host) and leaves
    via ``os._exit``.  (``--profile`` keeps the normal exit so the trace
    finalizes; a degraded pod may then report a coordination error at
    shutdown.)"""
    if nprocs > 1 and args.pod_hits == "local" and not args.profile:
        from .parallel.multihost import pod_local_done_exit

        pod_local_done_exit()


def _die_peer_loss(e) -> None:
    """Loud multihost abort: a peer died and the collective timed out.

    The survivor's checkpoint is already on disk (each host checkpoints
    its own stripe cursor), so the printed instructions make relaunching
    the pod a correct resume.  ``os._exit`` — the timed-out all-gather
    thread holds the distributed client and cannot be joined.
    """
    import os

    print(f"{PROG}: FATAL: {e}", file=sys.stderr)
    print(
        f"{PROG}: recovery: relaunch the pod (same command on every host); "
        "each host resumes its own stripe from --checkpoint and "
        "already-reported hits are deduped",
        file=sys.stderr,
    )
    sys.stderr.flush()
    sys.stdout.flush()
    os._exit(3)


def _write_metrics_json(path, sweeps, *, pod_gather: bool = False) -> None:
    """``--metrics-json`` (PERF.md §21): the process-wide telemetry
    registry snapshot plus each built sweep's span-timeline summary
    (bucketed sweeps report one summary per width).  Written AFTER the
    sweep so the snapshot is final; under ``A5GEN_TELEMETRY=off`` the
    file still lands, with whatever the always-on counters recorded.

    ``pod_gather``: gathered multihost runs all-gather every host's
    snapshot through the registry's fixed-order merge
    (``parallel.multihost.allgather_metrics`` — every process must
    call it, which holds because the pod convention is the same
    command, hence the same flag, on every host) and mark the doc
    ``pod_merged``.  The pod paths build their sweeps internally, so
    ``spans`` stays {} there — per-stripe span aggregates still ride
    the merged registry (``sweep.host_gap_s``/``dead_host_s``/…).
    Elastic mode (``--pod-hits local``) promises zero collectives, so
    it writes the host-local snapshot."""
    if not path:
        return
    import json

    from .runtime import telemetry

    spans = {}
    for obj in sweeps:
        inner = getattr(obj, "sweeps", None)
        if inner is not None:  # BucketedSweep: per-width timelines
            for width, s in inner.items():
                spans[f"w{width}"] = s.timeline.summary()
        else:
            spans["sweep"] = obj.timeline.summary()
    if pod_gather:
        from .parallel.multihost import allgather_metrics

        doc = {"metrics": allgather_metrics(), "spans": spans,
               "pod_merged": True}
    else:
        doc = {"metrics": telemetry.snapshot(), "spans": spans}
    # Same crash/power-loss discipline as checkpoints (PERF.md §23): a
    # metrics file a collector scrapes must never be observed torn.
    from .runtime.checkpoint import atomic_write_text

    atomic_write_text(path, json.dumps(doc, indent=2) + "\n")


def _run_device(args, sub_map, packed) -> int:
    """``packed`` is a PackedWords batch or a ``{width: PackedWords}``
    bucket dict (native fast path) — the device backend never materializes
    a Python word list."""
    from .models.attack import AttackSpec
    from .runtime.bucketed import BucketedSweep
    from .runtime.progress import ProgressReporter
    from .runtime.sinks import CandidateWriter, HitRecorder
    from .runtime.sweep import Sweep, SweepConfig

    spec = AttackSpec(
        mode=_mode(args),
        algo=args.algo,
        min_substitute=args.table_min,
        max_substitute=args.table_max,
    )
    # Multi-host topology comes up FIRST: jax.distributed.initialize must
    # run before anything initializes the XLA backend (parallel.multihost).
    pid, nprocs = 0, 1
    if (
        args.coordinator is not None
        or args.num_processes is not None
        or args.process_id is not None
    ):
        from .parallel import multihost

        pid, nprocs = multihost.initialize(
            args.coordinator, args.num_processes, args.process_id
        )
        print(f"{PROG}: distributed process {pid}/{nprocs}", file=sys.stderr)
        if nprocs > 1 and args.retries:
            # A lone retrying process would desync the pod's collectives;
            # pod-level recovery is relaunching the job (each host resumes
            # its own stripe checkpoint).
            print(
                f"{PROG}: warning: --retries is single-process only; "
                "ignored under --coordinator (relaunch the pod to resume)",
                file=sys.stderr,
            )
            args.retries = 0
    bucketed = isinstance(packed, dict)
    if nprocs > 1 and not args.giant_job:
        # Each process sweeps (and reports progress over) only its own
        # dictionary stripe.
        from .parallel.multihost import stripe_n_words

        n_words = stripe_n_words(packed, nprocs, pid)
    else:
        # Single process — or the giant-job mode, where every process
        # sweeps the FULL wordlist (the block lattice is what's striped).
        n_words = (
            sum(p.batch for p in packed.values()) if bucketed else packed.batch
        )
    progress = ProgressReporter(n_words) if args.progress else None
    # Launch geometry left unset resolves at launch time inside the
    # Sweep (PERF.md §29): explicit flag > this device kind's autotune
    # profile (`a5gen tune`; A5GEN_TUNE_PROFILE=off disables) > the
    # built-in backend-sized defaults (2^22 lanes on accelerators /
    # 2^17 on CPU; accelerator block count auto per plan).  Passing
    # lanes=None through is the "no explicit flag" spelling the
    # resolution seam keys on.
    cfg_kw = {}
    if args.fetch_chunk is not None:
        cfg_kw["fetch_chunk"] = args.fetch_chunk
    cfg = SweepConfig(
        lanes=args.lanes,
        num_blocks=args.blocks,
        devices=args.devices,
        superstep=args.superstep,
        pair={"auto": None, "on": "on", "off": 0}[args.pair],
        stream_chunk_words=args.stream_chunk_words,
        schema_cache=args.schema_cache,
        schema_cache_max_mb=args.schema_cache_max_mb,
        **cfg_kw,
        fetch_timeout_s=args.fetch_timeout,
        packed_blocks={"auto": None, "packed": True, "stride": False}[
            args.block_layout
        ],
        checkpoint_path=args.checkpoint,
        checkpoint_every_s=args.checkpoint_every,
        progress=progress,
    )

    built_sweeps: list = []

    def make_sweep(digests=()):
        s = (
            BucketedSweep(spec, sub_map, packed, digests, config=cfg)
            if bucketed
            else Sweep(spec, sub_map, packed, digests, config=cfg)
        )
        built_sweeps.append(s)
        return s

    # --profile/--profile-dir: guarded — a no-op (with the sweep still
    # running) wherever jax.profiler is unavailable (PERF.md §21).
    from .runtime.telemetry import profiler_trace

    trace_ctx = profiler_trace(args.profile)

    with trace_ctx:
        if args.digests is not None:
            digests = _read_digests(args.digests, args.algo)
            if nprocs > 1:
                from .parallel.multihost import (
                    PeerLossError,
                    run_crack_giant,
                    run_crack_multihost,
                )

                # Gathered: the combined hit stream is identical on every
                # process; process 0 is the conventional reporter.  Local
                # (elastic): every host streams its own stripe's hits.
                # --giant-job swaps the word-striped pod sweep for the
                # block-striped ONE-job mode (PERF.md §29).
                gather = args.pod_hits == "gathered"
                recorder = (
                    HitRecorder(sys.stdout.buffer)
                    if (pid == 0 or not gather) else None
                )
                runner = (
                    run_crack_giant if args.giant_job
                    else run_crack_multihost
                )
                try:
                    res = runner(
                        spec, sub_map, packed, digests, cfg,
                        recorder=recorder, resume=not args.no_resume,
                        gather=gather,
                    )
                except PeerLossError as e:
                    _die_peer_loss(e)
            else:
                recorder = _DedupRecorder(HitRecorder(sys.stdout.buffer))
                res = _run_with_retries(
                    lambda resume: make_sweep(digests).run_crack(
                        recorder, resume=resume
                    ),
                    args.retries,
                    default_resume=not args.no_resume,
                    label="crack sweep",
                )
            if nprocs > 1 and args.pod_hits == "local":
                print(
                    f"{PROG}: process {pid}/{nprocs} stripe: "
                    f"{res.n_hits} hits, {res.n_emitted} candidates hashed",
                    file=sys.stderr,
                )
            elif pid == 0:
                print(
                    f"{res.n_hits} hits, {res.n_emitted} candidates hashed",
                    file=sys.stderr,
                )
            _print_routing(res)
            _print_geometry(res)
            _print_superstep(res)
            _print_stream(res)
            _write_metrics_json(
                args.metrics_json, built_sweeps,
                pod_gather=nprocs > 1 and args.pod_hits == "gathered",
            )
            _maybe_exit_pod_local(args, nprocs)
            return 0
        with CandidateWriter(hex_unsafe=args.hex_unsafe) as writer:
            if nprocs > 1:
                from .parallel.multihost import (
                    PeerLossError,
                    run_candidates_multihost,
                )

                # Each process streams ITS stripe to its own stdout;
                # concatenating the per-host outputs in process order
                # yields the single-host stream for unbucketed input (the
                # candidates-mode default). With explicit --buckets each
                # host's stream is bucket-major over its own stripe, so
                # the concatenation is a per-word-preserving permutation
                # of the single-host bucket-major stream.
                try:
                    res = run_candidates_multihost(
                        spec, sub_map, packed, writer, cfg,
                        resume=not args.no_resume,
                        gather=args.pod_hits == "gathered",
                    )
                    _print_routing(res)
                except PeerLossError as e:
                    _die_peer_loss(e)
            else:
                res = _run_with_retries(
                    lambda resume: make_sweep().run_candidates(
                        writer, resume=resume
                    ),
                    args.retries,
                    default_resume=not args.no_resume,
                    label="candidates sweep",
                    retry_notice=(
                        "; candidates since that checkpoint repeat "
                        "(at-least-once stream)"
                    ),
                )
                _print_routing(res)
                _print_stream(res)
    _write_metrics_json(
        args.metrics_json, built_sweeps,
        pod_gather=nprocs > 1 and args.pod_hits == "gathered",
    )
    _maybe_exit_pod_local(args, nprocs)
    return 0


def _build_serve_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog=f"{PROG} serve",
        description=(
            "Resident engine service mode (PERF.md §20): compile once, "
            "serve many sweeps. Jobs arrive as JSONL on stdin (or a unix "
            "socket), interleave at superstep boundaries on one device, "
            "and share compiled programs and the schema cache; events "
            "(hit/done/paused/...) stream back as JSONL on stdout."
        ),
    )
    ap.add_argument("--socket", metavar="PATH",
                    help="listen on a unix socket instead of stdin "
                         "(one JSONL session per connection, all "
                         "sharing the engine)")
    ap.add_argument("--engine-id", metavar="ID", default=None,
                    help="identity label on this engine's telemetry "
                         "series and fleet-router scrapes (PERF.md "
                         "§25); default pid@host")
    ap.add_argument("--lanes", type=int, default=None,
                    help="default variant lanes per launch for jobs "
                         "that don't override it (same default as the "
                         "sweep CLI)")
    ap.add_argument("--blocks", type=int, default=None,
                    help="default device block slots per launch")
    ap.add_argument("--devices", type=_devices_arg, default=1, metavar="N",
                    help="default device count per job")
    ap.add_argument("--superstep", type=_superstep_arg, default=None,
                    metavar="N|auto|off", help="default superstep knob")
    ap.add_argument("--pair", choices=("auto", "on", "off"),
                    default="auto", help="default pair-lane knob "
                    "(PERF.md §24)")
    ap.add_argument("--stream-chunk-words", type=_stream_chunk_arg,
                    default="auto", metavar="N|auto|off",
                    help="default streaming-ingestion knob")
    ap.add_argument("--schema-cache", metavar="DIR",
                    help="on-disk PieceSchema cache shared by every job")
    ap.add_argument("--schema-cache-max-mb", type=float, default=None,
                    metavar="MB",
                    help="LRU size cap on the schema cache (long-lived "
                         "process hygiene; default unbounded)")
    ap.add_argument("--max-word-bytes", type=int, default=64 * 1024,
                    help="reject job dictionary lines longer than this")
    ap.add_argument("--client-timeout", type=float, default=None,
                    metavar="SECONDS",
                    help="--socket only: close a connection whose "
                         "client has sent nothing for SECONDS while no "
                         "events flowed out either (a half-open client "
                         "must not pin a server thread forever; a "
                         "client quietly waiting for its job's results "
                         "is NOT idle; PERF.md §23). The dropped "
                         "client's jobs keep running, and the job "
                         "registry is shared across connections, so a "
                         "reconnecting session pauses/cancels/resumes "
                         "them by id. Default off")
    ap.add_argument("--pack", choices=("auto", "on", "off"),
                    default="auto",
                    help="cross-job packed superstep dispatch (PERF.md "
                         "§22): fuse compatible tenants' block ranges "
                         "into one dispatch. auto = on unless "
                         "A5GEN_PACK=off; off = the per-job dispatch "
                         "path")
    ap.add_argument("--admission-worker", choices=("on", "off"),
                    default="on",
                    help="build admitted jobs' plans on a bounded "
                         "worker thread instead of the serve round "
                         "(PERF.md §22); off = synchronous admission")
    return ap


def _run_serve(argv: Sequence[str]) -> int:
    """``a5gen serve``: one resident engine, jobs over JSONL."""
    args = _build_serve_parser().parse_args(argv)
    from .runtime import telemetry
    from .runtime.engine import Engine, serve_socket, serve_stdio
    from .runtime.sweep import SweepConfig

    # Serve mode always runs labeled: a router's merged scrape must
    # distinguish members, and a lone engine's label is harmless.
    telemetry.set_engine_id(
        args.engine_id or telemetry.default_engine_id()
    )

    if args.lanes is None or args.blocks is None:
        # Engine defaults must be CONCRETE (affinity tokens and
        # config_defaults hash them), so serve resolves the geometry
        # eagerly at startup instead of deferring to the per-sweep
        # launch seam: explicit flag > autotune profile > built-ins
        # (PERF.md §29; the lanes/blocks knobs only — per-job
        # superstep/pair semantics stay with the job docs).
        from .runtime.tune import current_device_kind, resolve_config

        kind = current_device_kind()
        # lanes=None engages the seam even when --lanes was given; the
        # per-knob merge below keeps any explicit flag.
        resolved, source = resolve_config(
            SweepConfig(lanes=None, num_blocks=args.blocks), kind
        )
        if args.lanes is None:
            args.lanes = resolved.lanes
        if args.blocks is None:
            args.blocks = resolved.num_blocks
        if source == "profile":
            print(
                f"{PROG}: geometry defaults from autotune profile "
                f"({kind}): lanes={args.lanes} blocks={args.blocks}",
                file=sys.stderr,
            )
    defaults = SweepConfig(
        lanes=args.lanes,
        num_blocks=args.blocks,
        devices=args.devices,
        superstep=args.superstep,
        pair={"auto": None, "on": "on", "off": 0}[args.pair],
        stream_chunk_words=args.stream_chunk_words,
        schema_cache=args.schema_cache,
        schema_cache_max_mb=args.schema_cache_max_mb,
    )
    engine = Engine(
        defaults,
        pack={"auto": None, "on": True, "off": False}[args.pack],
        admission_worker=args.admission_worker == "on",
    )
    print(f"{PROG}: serving on "
          f"{args.socket or 'stdin'} (JSONL; op=shutdown or EOF ends)",
          file=sys.stderr)
    try:
        if args.socket:
            serve_socket(engine, args.socket,
                         max_word_bytes=args.max_word_bytes,
                         client_timeout=args.client_timeout)
        else:
            serve_stdio(engine, sys.stdin, sys.stdout,
                        max_word_bytes=args.max_word_bytes)
    finally:
        engine.close()
    return 0


def _build_fleet_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog=f"{PROG} fleet",
        description=(
            "Fleet mode (PERF.md §25): a front-end router over a pool "
            "of engine processes.  Speaks the SAME JSONL serve "
            "protocol upstream (submit/pause/resume/cancel/stats/"
            "metrics/shutdown pass through; drain/migrate added), "
            "places jobs by static-trace-config affinity to maximize "
            "fuse/compile reuse, rebalances via pause→checkpoint→"
            "resubmit, and survives engine death by crash-replaying "
            "routed jobs from their last router-held checkpoints with "
            "exactly-once hit redelivery."
        ),
    )
    ap.add_argument("--engines", required=True, metavar="N|SOCK,...",
                    help="an integer spawns N local engine processes "
                         "(sharing this command's geometry flags and "
                         "one schema cache); a comma-separated list "
                         "of unix-socket paths attaches to engines "
                         "already running")
    ap.add_argument("--socket", metavar="PATH",
                    help="listen for clients on a unix socket instead "
                         "of stdin")
    ap.add_argument("--place", choices=("affinity", "round-robin"),
                    default="affinity",
                    help="job placement: static-trace-config affinity "
                         "(default; co-locate compatible jobs for "
                         "fuse/compile reuse) or round-robin (the "
                         "--fleet-ab control arm)")
    ap.add_argument("--poll", type=float, default=2.0, metavar="S",
                    help="engine health-scrape cadence (stats op + "
                         "liveness; 0 disables)")
    ap.add_argument("--replay-budget", type=int, default=1, metavar="N",
                    help="checkpoint-bearing engine failures "
                         "(quarantine) are resubmitted to another "
                         "engine up to N times per job")
    # Giant-job striping (PERF.md §31).
    ap.add_argument("--split", choices=("auto", "on", "off"),
                    default=None,
                    help="giant-job striping: scatter one oversized "
                         "crack job across every free engine as "
                         "disjoint rank-stride shard ranges and merge "
                         "the hit streams back into one (word,rank)-"
                         "ordered client stream (auto: only jobs with "
                         "at least --split-threshold words; on: any "
                         "crack job when 2+ engines are free; off: "
                         "never; default: $A5GEN_SPLIT or auto)")
    ap.add_argument("--split-threshold", type=int, default=4096,
                    metavar="N",
                    help="auto split mode: minimum wordlist size (in "
                         "words) before a submit is scattered")
    # Elastic tier (PERF.md §27): autoscaling + admission control.
    ap.add_argument("--autoscale", metavar="MIN:MAX", default=None,
                    help="enable the autoscaler (spawn mode only): "
                         "keep between MIN and MAX engines, spawning "
                         "on sustained backlog and draining+reaping "
                         "idle or quarantined ones (--engines N is "
                         "the initial size, clamped into [MIN,MAX])")
    ap.add_argument("--scale-up-at", type=float, default=2.0,
                    metavar="F",
                    help="autoscale: backlog per engine (routed + "
                         "queued + building + router-pending) that, "
                         "sustained over the hysteresis window, "
                         "spawns an engine")
    ap.add_argument("--scale-down-at", type=float, default=0.25,
                    metavar="F",
                    help="autoscale: backlog per engine below which "
                         "(sustained) the idlest engine drains and "
                         "reaps")
    ap.add_argument("--scale-window", type=int, default=2, metavar="N",
                    help="autoscale hysteresis: consecutive "
                         "observations over/under threshold before "
                         "acting (scale-down uses 2N — shrinking is "
                         "the cheaper mistake to delay)")
    ap.add_argument("--scale-cooldown", type=float, default=10.0,
                    metavar="S",
                    help="autoscale: seconds between scale actions "
                         "(flap damping; failed spawns retry after "
                         "this too)")
    ap.add_argument("--engine-capacity", type=int, default=32,
                    metavar="N",
                    help="admission control: routed jobs one engine "
                         "accepts before new submits queue on the "
                         "router (0 = unbounded, the pre-elastic "
                         "behavior)")
    ap.add_argument("--max-pending", type=int, default=256, metavar="N",
                    help="admission control: the BOUNDED router-side "
                         "pending queue; past it, submits are "
                         "rejected typed ({\"error\": \"overloaded\", "
                         "\"retry_after_s\": ...}) per --shed-policy")
    ap.add_argument("--per-tenant", type=int, default=0, metavar="N",
                    help="admission control: max unsettled jobs per "
                         "submit-doc 'tenant' (0 = off; docs without "
                         "a tenant are exempt)")
    ap.add_argument("--shed-policy",
                    choices=("reject", "queue", "oldest"),
                    default="reject",
                    help="what a full pending queue does to a new "
                         "submit: reject it typed (default), shed the "
                         "oldest pending job (deadline-carrying jobs "
                         "first) to admit it, or queue unboundedly "
                         "(the legacy escape hatch)")
    ap.add_argument("--engine-dir", metavar="DIR", default=None,
                    help="spawn mode: directory for engine sockets "
                         "(default: a temp dir)")
    # Spawn-mode engine flags (mirror `a5gen serve`); also seed the
    # router's affinity-token defaults in both modes.
    ap.add_argument("--lanes", type=int, default=None)
    ap.add_argument("--blocks", type=int, default=None)
    ap.add_argument("--superstep", type=_superstep_arg, default=None,
                    metavar="N|auto|off")
    ap.add_argument("--pair", choices=("auto", "on", "off"),
                    default="auto")
    ap.add_argument("--schema-cache", metavar="DIR", default=None,
                    help="the FLEET ARTIFACT STORE: one on-disk "
                         "PieceSchema cache directory shared by every "
                         "engine, so each plan×table compiles once "
                         "fleet-wide (spawn mode default: a shared "
                         "temp dir)")
    ap.add_argument("--schema-cache-max-mb", type=float, default=None,
                    metavar="MB")
    return ap


def _run_fleet(argv: Sequence[str]) -> int:
    """``a5gen fleet``: router + engine pool, serve protocol upstream."""
    import os
    import tempfile

    args = _build_fleet_parser().parse_args(argv)
    from .runtime.fleet import (
        FleetRouter,
        serve_fleet_socket,
        serve_fleet_stdio,
        spawn_engines,
    )
    from .runtime.sweep import SweepConfig

    defaults = SweepConfig(
        lanes=args.lanes, num_blocks=args.blocks,
        superstep=args.superstep,
        pair={"auto": None, "on": "on", "off": 0}[args.pair],
        schema_cache=args.schema_cache,
        schema_cache_max_mb=args.schema_cache_max_mb,
    )
    autoscale = None
    scale_cfg = None
    if args.autoscale is not None:
        if not args.engines.isdigit():
            raise SystemExit(
                f"{PROG}: --autoscale needs spawn mode (--engines N); "
                "attached engines' lifetimes belong to their owners"
            )
        lo, _, hi = args.autoscale.partition(":")
        try:
            autoscale = (int(lo), int(hi))
        except ValueError:
            raise SystemExit(
                f"{PROG}: --autoscale wants MIN:MAX integers, got "
                f"{args.autoscale!r}"
            ) from None
        from .runtime.autoscale import AutoscaleConfig

        # Validate the WHOLE elastic config before any engine spawns:
        # a bad bound or threshold pair must fail the command cleanly,
        # not traceback after processes are already running.
        try:
            scale_cfg = AutoscaleConfig(
                min_engines=autoscale[0],
                max_engines=autoscale[1],
                scale_up_at=args.scale_up_at,
                scale_down_at=args.scale_down_at,
                up_window=args.scale_window,
                down_window=2 * args.scale_window,
                cooldown_s=args.scale_cooldown,
                interval_s=max(args.poll, 0.5)
                if args.poll > 0 else 1.0,
            )
        except ValueError as exc:
            raise SystemExit(f"{PROG}: --autoscale: {exc}") from None
    router = FleetRouter(place=args.place, poll_s=args.poll,
                         replay_budget=args.replay_budget,
                         defaults=defaults,
                         engine_capacity=args.engine_capacity,
                         max_pending=args.max_pending,
                         per_tenant=args.per_tenant,
                         shed_policy=args.shed_policy,
                         split=args.split,
                         split_threshold=args.split_threshold)
    spawned = False
    scaler = None
    try:
        if args.engines.isdigit():
            spawned = True
            eng_dir = args.engine_dir or tempfile.mkdtemp(
                prefix="a5-fleet-"
            )
            cache = args.schema_cache or os.path.join(
                eng_dir, "schema-cache"
            )
            eng_args = ["--schema-cache", cache]
            if args.lanes is not None:
                eng_args += ["--lanes", str(args.lanes)]
            if args.blocks is not None:
                eng_args += ["--blocks", str(args.blocks)]
            if args.superstep is not None:
                eng_args += ["--superstep",
                             "off" if args.superstep == 0
                             else str(args.superstep)]
            if args.pair != "auto":
                eng_args += ["--pair", args.pair]
            if args.schema_cache_max_mb is not None:
                eng_args += ["--schema-cache-max-mb",
                             str(args.schema_cache_max_mb)]
            n0 = int(args.engines)
            if autoscale is not None:
                n0 = max(autoscale[0], min(n0, autoscale[1]))
            specs = spawn_engines(n0, eng_dir, engine_args=eng_args)
            for sock_path, eid, proc in specs:
                router.attach(sock_path, eid, proc=proc)
            if scale_cfg is not None:
                import itertools as _it

                from .runtime.autoscale import Autoscaler

                counter = _it.count(n0)

                def _spawn_one():
                    (spec,) = spawn_engines(
                        1, eng_dir, engine_args=eng_args,
                        start_index=next(counter),
                    )
                    return spec

                scaler = Autoscaler(router, _spawn_one, scale_cfg)
        else:
            for ep in args.engines.split(","):
                ep = ep.strip()
                if ep:
                    router.attach(ep)
        n = len(router.engines())
        elastic = (
            f", elastic {scaler.cfg.min_engines}:"
            f"{scaler.cfg.max_engines}" if scaler is not None else ""
        )
        print(f"{PROG}: fleet of {n} engine(s){elastic}, routing on "
              f"{args.socket or 'stdin'} (JSONL; op=shutdown ends)",
              file=sys.stderr)
        if args.socket:
            serve_fleet_socket(router, args.socket)
        else:
            serve_fleet_stdio(router, sys.stdin, sys.stdout)
    finally:
        # Spawn mode owns its engines' lifetimes; attach mode leaves
        # them serving for their other clients.
        router.close(shutdown_engines=spawned)
    return 0


def _build_tune_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog=f"{PROG} tune",
        description=(
            "Geometry autotuner (PERF.md §29): sweep lanes x stride "
            "(block batch) x superstep depth x pair x emit arm over the "
            "production crack contract on the live backend, assert "
            "per-arm stream parity, and write the winner as this device "
            "kind's profile (~/.cache/a5gen/tune/<device_kind>.json; "
            "A5GEN_TUNE_PROFILE overrides the directory or disables "
            "loading). Sweeps with no explicit --lanes then load the "
            "profile by default."
        ),
    )
    ap.add_argument("--words", type=int, default=512, metavar="N",
                    help="synthetic tune-contract dictionary size "
                         "(deterministic; default 512)")
    ap.add_argument("--seconds", type=float, default=1.0, metavar="S",
                    help="timed wall per arm after the warm-up sweep "
                         "(default 1.0)")
    ap.add_argument("--smoke", action="store_true",
                    help="the CI 2x2 matrix (lanes x stride only) — "
                         "finishes in seconds on CPU")
    ap.add_argument("--state", metavar="FILE",
                    help="partial-matrix resume state: each completed "
                         "arm's record is appended atomically, and a "
                         "rerun skips straight past completed arms "
                         "(the bench orchestrator's retry seam)")
    ap.add_argument("--profile-dir", metavar="DIR", default=None,
                    help="write the profile under DIR instead of the "
                         "A5GEN_TUNE_PROFILE / ~/.cache default")
    ap.add_argument("--no-write", action="store_true",
                    help="measure and report only; do not persist a "
                         "profile")
    ap.add_argument("--json", action="store_true",
                    help="print the full result document as JSON on "
                         "stdout (arm records included) instead of the "
                         "summary table")
    return ap


def _run_tune(argv: Sequence[str]) -> int:
    """``a5gen tune``: run the autotune matrix and persist the winner."""
    import json as _json

    args = _build_tune_parser().parse_args(argv)
    from .runtime.tune import TuneProfileCorrupt, run_autotune

    def on_arm(rec) -> None:
        note = " (resumed)" if rec.get("resumed") else ""
        print(
            f"{PROG}: tune: {rec['arm']}: "
            f"{rec['hashes_per_s']:.3e} hashes/s "
            f"({rec['sweeps']} sweeps x {rec['emitted_per_sweep']} "
            f"candidates){note}",
            file=sys.stderr,
        )

    try:
        result = run_autotune(
            words=args.words,
            seconds=args.seconds,
            smoke=args.smoke,
            state_path=args.state,
            on_arm=on_arm,
            write=not args.no_write,
            directory=args.profile_dir,
        )
    except (TuneProfileCorrupt, RuntimeError, ValueError) as exc:
        print(f"{PROG}: tune failed: {exc}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(result, indent=2, sort_keys=True))
    else:
        g = result["geometry"]
        print(
            f"{PROG}: tune winner on {result['device_kind']}: "
            f"{result['winner']} — lanes={g['lanes']} "
            f"blocks={g['num_blocks']} stride={g.get('stride')} "
            f"superstep={g.get('superstep')} pair={g.get('pair')} "
            f"at {result['hashes_per_s']:.3e} hashes/s",
            file=sys.stderr,
        )
        if result.get("profile_path"):
            print(
                f"{PROG}: profile written: {result['profile_path']} "
                "(loaded by default for sweeps with no explicit "
                "--lanes; A5GEN_TUNE_PROFILE=off disables)",
                file=sys.stderr,
            )
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    # jax-free import: the typed corrupt-checkpoint error gets its
    # remediation hint here (PERF.md §23).
    from .runtime.checkpoint import CheckpointCorrupt

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        # Subcommand surface: the resident service mode has its own
        # flag set (job semantics arrive per JSONL submission, not as
        # process flags).
        return _run_serve(list(argv[1:]))
    if argv and argv[0] == "fleet":
        # Fleet mode (PERF.md §25): router + engine pool — jax-free in
        # the router process; the engines are where device work runs.
        return _run_fleet(list(argv[1:]))
    if argv and argv[0] == "tune":
        # Geometry autotuner (PERF.md §29): sweep the arm matrix on the
        # live backend and persist the winner as this device kind's
        # profile, which the runtime then loads by default.
        return _run_tune(list(argv[1:]))
    ap = build_parser()
    args = ap.parse_args(argv)
    if args.list_layouts:
        return _run_list_layouts()
    if args.emit_table:
        try:
            return _run_emit_table(args)
        except KeyError as e:
            ap.error(str(e.args[0]) if e.args else str(e))
    if not args.dict_file:
        ap.error("dict_file is required (or use --emit-table)")
    if not args.table_files:
        ap.error("at least one -t/--table-files is required")
    if args.table_min > args.table_max:
        ap.error(
            f"--table-min {args.table_min} > --table-max {args.table_max}"
        )
    if (
        args.retries
        and args.backend == "device"
        and args.digests is None
        and not args.checkpoint
    ):
        ap.error(
            "--retries in candidates mode requires --checkpoint (a retry "
            "without one would re-emit the whole candidate stream)"
        )
    if args.giant_job and args.digests is None:
        # Candidates mode streams the full keyspace from each process —
        # a block stripe has no merge discipline there (PERF.md §29).
        ap.error("--giant-job is crack mode only (requires --digests)")
    if args.backend == "device" and args.bug_compat:
        # The Q3 reverse-offset bug (main.go:249-257) is reproduced only by
        # the oracle engines; the device plans emit corrected bytes. Honor
        # the flag rather than silently diverging.
        if args.reverse_sub and not args.substitute_all:
            print(
                f"{PROG}: warning: --bug-compat requires the oracle "
                "reverse engine (the device plan emits corrected offsets); "
                "routing this sweep through --backend oracle",
                file=sys.stderr,
            )
            args.backend = "oracle"
        else:
            print(
                f"{PROG}: warning: --bug-compat only affects reverse mode "
                "(-r without -s); it has no effect on this sweep",
                file=sys.stderr,
            )
    if args.backend == "oracle":
        for flag, name in (
            (args.checkpoint, "--checkpoint"),
            (args.no_resume, "--no-resume"),
            (args.progress, "--progress"),
            (args.devices != 1, "--devices"),
            (args.profile, "--profile"),
            (args.coordinator is not None, "--coordinator"),
            (args.num_processes is not None, "--num-processes"),
            (args.process_id is not None, "--process-id"),
            (args.giant_job, "--giant-job"),
            (args.retries, "--retries"),
        ):
            if flag:
                print(
                    f"{PROG}: warning: {name} has no effect with "
                    "--backend oracle (the oracle streams statelessly)",
                    file=sys.stderr,
                )
    try:
        sub_map = load_tables(args.table_files)
    except OSError as e:
        raise SystemExit(f"{PROG}: cannot read table: {e}")
    try:
        if args.backend == "oracle":
            from .ops.packing import read_wordlist  # numpy-only module

            words = read_wordlist(
                args.dict_file, max_word_bytes=args.max_word_bytes
            )
            return _run_oracle(args, sub_map, words)
        # Device backend: the native scanner/packer is the wordlist hot
        # path (numpy fallback engages transparently when unavailable).
        from . import native

        if args.buckets == "auto":
            # Crack mode gets the perf default (per-width compiled programs);
            # candidates mode defaults to one global width so the stream
            # keeps strict dictionary order — diffable against the
            # reference without a bucket-major permutation.
            args.buckets = (16, 32, 64) if args.digests is not None else None
        if args.buckets is not None:
            packed = native.read_packed_buckets(
                args.dict_file,
                buckets=args.buckets,
                max_word_bytes=args.max_word_bytes,
            )
            if args.digests is None and sum(
                1 for p in packed.values() if p.batch
            ) > 1:
                print(
                    f"{PROG}: notice: --buckets reorders a mixed-length "
                    "candidate stream bucket-major (per-word multisets "
                    "unchanged); pass --buckets none for strict "
                    "dictionary order",
                    file=sys.stderr,
                )
        else:
            packed = native.read_packed(
                args.dict_file, max_word_bytes=args.max_word_bytes
            )
        return _run_device(args, sub_map, packed)
    except CheckpointCorrupt as e:
        # Typed corrupt/truncated-checkpoint error (PERF.md §23): name
        # the file and the failure, and say what to do about it.
        raise SystemExit(
            f"{PROG}: {e}\n"
            f"{PROG}: remediation: delete (or restore from backup) the "
            "named checkpoint file, or rerun with --no-resume to start "
            "the sweep over"
        )
    except ValueError as e:
        raise SystemExit(f"{PROG}: {e}")
    except OSError as e:
        raise SystemExit(f"{PROG}: cannot read {args.dict_file}: {e}")


if __name__ == "__main__":
    sys.exit(main())

"""TPU compute kernels (layer L3, device side): word packing, variant
expansion, hash primitives (MD5/SHA1/NTLM) and digest membership.

All kernels operate on fixed-shape padded byte tensors (``uint8[B, L]`` plus
length vectors) so XLA sees static shapes end to end (SURVEY.md §5
"long-context": variable-length words become padded buffers with masks, and a
word's variant space is split by exact integer index ranges, never by dynamic
shapes)."""

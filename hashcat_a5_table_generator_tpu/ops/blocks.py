"""Variant-space block scheduler, shared by every expansion kernel.

A *block* is ``(word, base_digits, count)``: a contiguous rank range of one
word's mixed-radix variant space. Blocks are the unit of device work, of
cross-chip splitting for huge single-word spaces (SURVEY.md §5
"long-context"), and of sweep checkpoint/resume — the host cuts arbitrary
``[cursor, cursor + n)`` ranges with Python-bigint divmods, and the device
adds the in-block rank to ``base_digits`` with mixed-radix carries, so
everything on device stays int32.

Any expansion plan can be scheduled here as long as it exposes ``batch``,
``num_slots``, ``n_variants`` (per-word Python ints — these can exceed 2^63),
``fallback`` (words the runtime routes through the CPU oracle instead), and
``pat_radix[B, P]`` (per-slot radices, 1 on inactive slots).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

#: Per-block variant-count cap: in-block ranks must fit int32.
MAX_BLOCK = 1 << 30


@dataclass(frozen=True)
class BlockBatch:
    """A device launch's worth of work blocks."""

    word: np.ndarray  # int32 [NB] — row into the plan's word batch
    base_digits: np.ndarray  # int32 [NB, P] — mixed-radix start digits
    count: np.ndarray  # int32 [NB] — variants in this block (< 2^31)
    offset: np.ndarray  # int32 [NB] — exclusive prefix sum of count

    @property
    def total(self) -> int:
        return int(self.offset[-1] + self.count[-1]) if len(self.count) else 0


def digits_of(rank: int, radices: Sequence[int]) -> List[int]:
    """Mixed-radix digits of ``rank`` (slot 0 least significant), host bigint."""
    out = []
    for r in radices:
        out.append(rank % r)
        rank //= r
    return out


def make_blocks(
    plan,
    *,
    start_word: int = 0,
    start_rank: int = 0,
    max_variants: int,
    max_block: int = MAX_BLOCK,
    max_blocks: int | None = None,
    fixed_stride: int | None = None,
) -> Tuple[BlockBatch, int, int]:
    """Cut up to ``max_variants`` of the plan's variant space into blocks,
    starting at (start_word, start_rank). Returns (batch, next_word,
    next_rank) — the resume cursor. Fallback words are skipped (the runtime
    routes them through the oracle). ``max_blocks`` caps the number of blocks
    cut (the budget may go unfilled) so callers can pad to a static block
    count and keep jit shapes stable across launches.

    ``fixed_stride``: the TPU-fast layout — every block owns exactly
    ``stride`` consecutive LANES (``offset[b] == b * stride``) and at most
    ``stride`` variants, so the device maps lane -> block with one constant
    divide instead of a per-lane binary search, and block fields broadcast
    per block instead of gathering per lane (``expand_matches.block_stride``;
    see PERF.md). A word's final partial block leaves its tail lanes masked
    — that is the price, bounded by ``stride/2`` lanes per word on average.
    ``max_variants`` then budgets lane SPAN (``stride`` per block), matching
    the launch's lane count, and ``max_block`` is ignored (``stride`` caps
    every block).
    """
    words: List[int] = []
    bases: List[List[int]] = []
    counts: List[int] = []
    p = plan.num_slots
    budget = max_variants
    w, rank = start_word, start_rank
    while w < plan.batch and budget > 0:
        if max_blocks is not None and len(words) >= max_blocks:
            break
        if fixed_stride is not None and budget < fixed_stride:
            break
        total = plan.n_variants[w]
        if plan.fallback[w] or rank >= total:
            w, rank = w + 1, 0
            continue
        if fixed_stride is not None:
            take = min(fixed_stride, total - rank)
            spent = fixed_stride
        else:
            take = min(budget, total - rank, max_block)
            spent = take
        words.append(w)
        if getattr(plan, "windowed", False):
            # Windowed plans cursor by scalar rank (int32 by eligibility);
            # the device unranks through the plan's win_v DP table.
            bases.append([rank] + [0] * (p - 1))
        else:
            radices = [int(plan.pat_radix[w, s]) for s in range(p)]
            bases.append(digits_of(rank, radices))
        counts.append(take)
        budget -= spent
        rank += take
        if rank >= total:
            w, rank = w + 1, 0
    counts_arr = np.asarray(counts, dtype=np.int32)
    if fixed_stride is not None:
        offset = (
            np.arange(len(counts), dtype=np.int32) * np.int32(fixed_stride)
        )
    elif len(counts):
        offset = np.concatenate([[0], np.cumsum(counts_arr[:-1])]).astype(
            np.int32
        )
    else:
        offset = np.zeros((0,), dtype=np.int32)
    batch = BlockBatch(
        word=np.asarray(words, dtype=np.int32),
        base_digits=np.asarray(bases, dtype=np.int32).reshape(len(words), p),
        count=counts_arr,
        offset=offset,
    )
    return batch, w, rank


def pad_batch(batch: BlockBatch, num_blocks: int) -> BlockBatch:
    """Pad a batch to exactly ``num_blocks`` blocks with zero-count blocks.

    Padding blocks carry ``offset == total`` so their lanes fail the
    ``rank < count`` test and are masked; a static block count keeps the
    jitted step's input shapes stable across launches (no retraces).
    """
    k = len(batch.count)
    if k > num_blocks:
        raise ValueError(f"batch has {k} blocks > num_blocks {num_blocks}")
    if k == num_blocks:
        return batch
    pad = num_blocks - k
    total = batch.total
    return BlockBatch(
        word=np.pad(batch.word, (0, pad)).astype(np.int32),
        # make_blocks always shapes base_digits (k, P) — even at k == 0 — so
        # padding preserves the plan's slot width unconditionally.
        base_digits=np.pad(batch.base_digits, ((0, pad), (0, 0))).astype(np.int32),
        count=np.pad(batch.count, (0, pad)).astype(np.int32),
        offset=np.concatenate(
            [batch.offset, np.full(pad, total, dtype=np.int32)]
        ).astype(np.int32),
    )

"""Variant-space block scheduler, shared by every expansion kernel.

A *block* is ``(word, base_digits, count)``: a contiguous rank range of one
word's mixed-radix variant space. Blocks are the unit of device work, of
cross-chip splitting for huge single-word spaces (SURVEY.md §5
"long-context"), and of sweep checkpoint/resume — the host cuts arbitrary
``[cursor, cursor + n)`` ranges with Python-bigint divmods, and the device
adds the in-block rank to ``base_digits`` with mixed-radix carries, so
everything on device stays int32.

Any expansion plan can be scheduled here as long as it exposes ``batch``,
``num_slots``, ``n_variants`` (per-word Python ints — these can exceed 2^63),
``fallback`` (words the runtime routes through the CPU oracle instead), and
``pat_radix[B, P]`` (per-slot radices, 1 on inactive slots).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

#: Per-block variant-count cap: in-block ranks must fit int32.
MAX_BLOCK = 1 << 30

#: Words whose variant total reaches this are cut by the scalar path only:
#: the vectorized cutter works in int64 block/rank arithmetic, which a
#: ~2^60-variant word would overflow. (No shipped table comes anywhere
#: close; the cap exists for correctness, not tuning.)
_HUGE_WORD = 1 << 60


def _stride_index(plan, stride: int):
    """Per-(plan, stride) cumulative block index for the vectorized cutter.

    ``cum[w]`` = global index of word ``w``'s first block when every
    non-fallback word is cut into ``ceil(total / stride)`` fixed-stride
    blocks; fallback and huge words occupy zero / capped width (huge words
    force the scalar path — ``huge`` marks them). Cached on the plan object
    (plans are frozen; ``object.__setattr__`` is the sanctioned backdoor) so
    the O(batch) pass runs once per sweep, not once per launch.
    """
    cache = getattr(plan, "_stride_index_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(plan, "_stride_index_cache", cache)
    if stride in cache:
        return cache[stride]
    b = plan.batch
    widths = np.zeros(b + 1, dtype=np.int64)
    totals = np.zeros(b, dtype=np.int64)
    huge = np.zeros(b, dtype=bool)
    fallback = plan.fallback
    total_width = 0  # Python int: overflow-proof running sum of widths
    for i, t in enumerate(plan.n_variants):
        if fallback[i]:
            continue
        if t >= _HUGE_WORD:
            # Width 1, not ceil(t/stride): any window whose searchsorted
            # lands on a huge word bails to the scalar path (huge[w].any()
            # in the fast cutter), so the fast path never decodes a huge
            # word's ranks — a single slot keeps the cumsum small instead
            # of adding ~2^53 per huge word (advisor r4: ~1024 such words
            # silently overflowed the int64 cumsum).
            huge[i] = True
            totals[i] = _HUGE_WORD
            widths[i + 1] = 1
            total_width += 1
        else:
            totals[i] = t
            w_i = -(-t // stride)
            widths[i + 1] = w_i
            total_width += w_i
    if total_width >= (1 << 62):
        # Cumulative block index would overflow int64 (needs ~2^55 words
        # just below the huge cap): scalar path only for this stride.
        cache[stride] = None
        return None
    entry = (np.cumsum(widths), totals, huge)
    cache[stride] = entry
    return entry


def _make_blocks_stride_fast(
    plan, cum, totals, huge, start_word: int, start_rank: int,
    nb_cap: int, stride: int,
) -> "Tuple[BlockBatch, int, int] | None":
    """Vectorized fixed-stride cutter: the whole launch is one searchsorted
    over the cumulative block index plus a vectorized mixed-radix decompose
    — replacing the per-block Python loop (~4.4 µs/block; at 16k+ blocks
    per launch the scalar cutter cost more than the launch's device time).
    Returns None when the window touches a huge word (scalar path handles
    those exactly)."""
    p = plan.num_slots
    b0 = int(cum[start_word]) + start_rank // stride
    b1 = min(b0 + nb_cap, int(cum[-1]))
    nb = b1 - b0
    if nb <= 0:
        # Distinguish 'sweep complete' from 'no block budget' (advisor r4:
        # nb_cap == 0 with unfinished words must not report completion —
        # a silent-keyspace-loss hazard for future make_blocks callers).
        done = b0 >= int(cum[-1])
        return (
            BlockBatch(
                word=np.zeros(0, np.int32),
                base_digits=np.zeros((0, p), np.int32),
                count=np.zeros(0, np.int32),
                offset=np.zeros(0, np.int32),
            ),
            plan.batch if done else start_word,
            0 if done else start_rank,
        )
    blocks = np.arange(b0, b1, dtype=np.int64)
    w = (np.searchsorted(cum, blocks, side="right") - 1).astype(np.int64)
    if huge[w].any():
        return None
    rank0 = (blocks - cum[w]) * stride  # int64 [nb]
    count = np.minimum(stride, totals[w] - rank0).astype(np.int32)
    if getattr(plan, "windowed", False):
        bases = np.zeros((nb, p), dtype=np.int32)
        bases[:, 0] = rank0.astype(np.int32)  # int32 by plan eligibility
    else:
        radices = plan.pat_radix[w].astype(np.int64)  # [nb, p]
        bases = np.empty((nb, p), dtype=np.int64)
        t = rank0.copy()
        for s in range(p):
            r = radices[:, s]
            bases[:, s] = t % r
            t //= r
        bases = bases.astype(np.int32)
    if b1 == int(cum[-1]):
        w_next, rank_next = plan.batch, 0
    else:
        w_next = int(np.searchsorted(cum, b1, side="right") - 1)
        rank_next = int(b1 - cum[w_next]) * stride
    batch = BlockBatch(
        word=w.astype(np.int32),
        base_digits=bases,
        count=count,
        offset=np.arange(nb, dtype=np.int32) * np.int32(stride),
    )
    return batch, w_next, rank_next


@dataclass(frozen=True)
class BlockBatch:
    """A device launch's worth of work blocks."""

    word: np.ndarray  # int32 [NB] — row into the plan's word batch
    base_digits: np.ndarray  # int32 [NB, P] — mixed-radix start digits
    count: np.ndarray  # int32 [NB] — variants in this block (< 2^31)
    offset: np.ndarray  # int32 [NB] — exclusive prefix sum of count

    @property
    def total(self) -> int:
        return int(self.offset[-1] + self.count[-1]) if len(self.count) else 0


def digits_of(rank: int, radices: Sequence[int]) -> List[int]:
    """Mixed-radix digits of ``rank`` (slot 0 least significant), host bigint."""
    out = []
    for r in radices:
        out.append(rank % r)
        rank //= r
    return out


def make_blocks(
    plan,
    *,
    start_word: int = 0,
    start_rank: int = 0,
    max_variants: int,
    max_block: int = MAX_BLOCK,
    max_blocks: int | None = None,
    fixed_stride: int | None = None,
) -> Tuple[BlockBatch, int, int]:
    """Cut up to ``max_variants`` of the plan's variant space into blocks,
    starting at (start_word, start_rank). Returns (batch, next_word,
    next_rank) — the resume cursor. Fallback words are skipped (the runtime
    routes them through the oracle). ``max_blocks`` caps the number of blocks
    cut (the budget may go unfilled) so callers can pad to a static block
    count and keep jit shapes stable across launches.

    ``fixed_stride``: the TPU-fast layout — every block owns exactly
    ``stride`` consecutive LANES (``offset[b] == b * stride``) and at most
    ``stride`` variants, so the device maps lane -> block with one constant
    divide instead of a per-lane binary search, and block fields broadcast
    per block instead of gathering per lane (``expand_matches.block_stride``;
    see PERF.md). A word's final partial block leaves its tail lanes masked
    — that is the price, bounded by ``stride/2`` lanes per word on average.
    ``max_variants`` then budgets lane SPAN (``stride`` per block), matching
    the launch's lane count, and ``max_block`` is ignored (``stride`` caps
    every block).
    """
    p = plan.num_slots
    budget = max_variants
    w, rank = start_word, start_rank
    if fixed_stride is not None:
        # Mirror the scalar loop's cursor normalization (it lazily advances
        # past finished and fallback words), then try the vectorized cutter.
        while w < plan.batch and (
            plan.fallback[w] or rank >= plan.n_variants[w]
        ):
            w, rank = w + 1, 0
        if rank % fixed_stride == 0 and (
            w >= plan.batch or plan.n_variants[w] < _HUGE_WORD
        ):
            # Misaligned ranks (cross-geometry checkpoint resume) keep the
            # scalar path; they re-align at the next word boundary.  A huge
            # START word also keeps it: huge words occupy one slot in the
            # cumulative index, so ``cum[w] + rank // stride`` would land
            # inside later words' block ranges.
            entry = _stride_index(plan, fixed_stride)
            if entry is not None:
                cum, totals, huge = entry
                nb_cap = budget // fixed_stride
                if max_blocks is not None:
                    nb_cap = min(nb_cap, max_blocks)
                fast = _make_blocks_stride_fast(
                    plan, cum, totals, huge, w, rank, nb_cap, fixed_stride
                )
                if fast is not None:
                    return fast
    words: List[int] = []
    bases: List[List[int]] = []
    counts: List[int] = []
    while w < plan.batch and budget > 0:
        if max_blocks is not None and len(words) >= max_blocks:
            break
        if fixed_stride is not None and budget < fixed_stride:
            break
        total = plan.n_variants[w]
        if plan.fallback[w] or rank >= total:
            w, rank = w + 1, 0
            continue
        if fixed_stride is not None:
            take = min(fixed_stride, total - rank)
            spent = fixed_stride
        else:
            take = min(budget, total - rank, max_block)
            spent = take
        words.append(w)
        if getattr(plan, "windowed", False):
            # Windowed plans cursor by scalar rank (int32 by eligibility);
            # the device unranks through the plan's win_v DP table.
            bases.append([rank] + [0] * (p - 1))
        else:
            radices = [int(plan.pat_radix[w, s]) for s in range(p)]
            bases.append(digits_of(rank, radices))
        counts.append(take)
        budget -= spent
        rank += take
        if rank >= total:
            w, rank = w + 1, 0
    counts_arr = np.asarray(counts, dtype=np.int32)
    if fixed_stride is not None:
        offset = (
            np.arange(len(counts), dtype=np.int32) * np.int32(fixed_stride)
        )
    elif len(counts):
        offset = np.concatenate([[0], np.cumsum(counts_arr[:-1])]).astype(
            np.int32
        )
    else:
        offset = np.zeros((0,), dtype=np.int32)
    batch = BlockBatch(
        word=np.asarray(words, dtype=np.int32),
        base_digits=np.asarray(bases, dtype=np.int32).reshape(len(words), p),
        count=counts_arr,
        offset=offset,
    )
    return batch, w, rank


def superstep_index(plan, stride: int):
    """int32 view of the fixed-stride block index for the DEVICE-side
    cutter (``models.attack.make_superstep_step``): the superstep executor
    cuts each launch's blocks on device from these per-sweep arrays, so
    the host stops paying a per-launch cutting pass (PERF.md §15).

    Returns ``(cum int32[B+1], totals int32[B], total_blocks int)`` or
    ``None`` when the plan cannot be cut in pure int32 on device:

    * any huge word (``>= _HUGE_WORD``; the host scalar cutter owns those),
    * any per-word variant total at/above ``MAX_BLOCK`` (device ranks and
      hit cursors are int32),
    * a cumulative block index that overflows int32.

    The arrays are exactly ``_stride_index``'s (same cache), narrowed —
    so the device cutter and the host fast cutter can never disagree.
    """
    entry = _stride_index(plan, stride)
    if entry is None:
        return None
    cum, totals, huge = entry
    if huge.any():
        return None
    if len(totals) and int(totals.max()) >= MAX_BLOCK:
        return None
    total_blocks = int(cum[-1])
    if total_blocks >= (1 << 31):
        return None
    return cum.astype(np.int32), totals.astype(np.int32), total_blocks


def packed_block_index(
    idxs: Sequence[Tuple[np.ndarray, np.ndarray, int]],
) -> "Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None":
    """Concatenate several plans' fixed-stride block indexes (each a
    :func:`superstep_index` result) into ONE packed int32 index for the
    cross-job packed superstep dispatch (PERF.md §22).

    Job ``j``'s blocks occupy the contiguous global range
    ``[blk_base[j], blk_base[j] + total_j)`` and its plan rows the range
    ``[row_base[j], row_base[j] + B_j)``; the packed cumulative index is
    each job's ``cum`` shifted by its block base, so the device cutter's
    ``searchsorted`` maps a packed block index straight to a packed plan
    row — zero-width rows (fallback/finished words) can never cover a
    block, exactly as in the solo index.

    Returns ``(cum int32[B_total+1], totals int32[B_total],
    blk_base int64[S+1], row_base int64[S+1], seg_end int32[S])`` or
    ``None`` when the packed cumulative index would overflow int32
    (callers then keep per-job dispatch).
    """
    blk_base = np.zeros(len(idxs) + 1, dtype=np.int64)
    row_base = np.zeros(len(idxs) + 1, dtype=np.int64)
    for j, (cum_j, totals_j, total_j) in enumerate(idxs):
        blk_base[j + 1] = blk_base[j] + total_j
        row_base[j + 1] = row_base[j] + totals_j.shape[0]
    if blk_base[-1] >= (1 << 31):
        return None
    cum = np.concatenate(
        [
            np.asarray(cum_j[:-1], dtype=np.int64) + blk_base[j]
            for j, (cum_j, _t, _n) in enumerate(idxs)
        ]
        + [blk_base[-1:]]
    ).astype(np.int32)
    totals = np.concatenate(
        [np.asarray(t, dtype=np.int32) for _c, t, _n in idxs]
    )
    seg_end = blk_base[1:].astype(np.int32)
    return cum, totals, blk_base, row_base, seg_end


def block_cursor(plan, stride: int, cum: np.ndarray, b: int
                 ) -> Tuple[int, int]:
    """Host (word, rank) cursor of global fixed-stride block index ``b``
    — the same convention ``_make_blocks_stride_fast`` returns as its
    next cursor, so superstep boundaries and per-launch cursors are
    interchangeable in checkpoints."""
    if b >= int(cum[-1]):
        return plan.batch, 0
    w = int(np.searchsorted(cum, b, side="right") - 1)
    return w, int(b - cum[w]) * stride


def pad_batch(batch: BlockBatch, num_blocks: int) -> BlockBatch:
    """Pad a batch to exactly ``num_blocks`` blocks with zero-count blocks.

    Padding blocks carry ``offset == total`` so their lanes fail the
    ``rank < count`` test and are masked; a static block count keeps the
    jitted step's input shapes stable across launches (no retraces).
    """
    k = len(batch.count)
    if k > num_blocks:
        raise ValueError(f"batch has {k} blocks > num_blocks {num_blocks}")
    if k == num_blocks:
        return batch
    pad = num_blocks - k
    total = batch.total
    return BlockBatch(
        word=np.pad(batch.word, (0, pad)).astype(np.int32),
        # make_blocks always shapes base_digits (k, P) — even at k == 0 — so
        # padding preserves the plan's slot width unconditionally.
        base_digits=np.pad(batch.base_digits, ((0, pad), (0, 0))).astype(np.int32),
        count=np.pad(batch.count, (0, pad)).astype(np.int32),
        offset=np.concatenate(
            [batch.offset, np.full(pad, total, dtype=np.int32)]
        ).astype(np.int32),
    )

"""Pallas TPU kernel for single-block MD5 (SURVEY.md §7 step 4's "drop to
Pallas where XLA fusion is insufficient").

PERF.md §3: post-expansion the fused step retires ~1000 int32 ops/lane at
~8 GOP/s — two orders below the VPU roofline — because XLA materializes
large intermediates between the unrolled round chain's fusion groups. This
kernel keeps the whole 64-round compression in VMEM registers:

* the message is pre-padded OUTSIDE the kernel by the shared
  :func:`..ops.hashes.pad_message` layout (tested against hashlib), then
  laid out as ``uint32[N/128, 16, 128]`` so every message word is a
  perfect ``(sublane, lane)`` int32 tile and every round operates on
  ``(rows, 128)`` vectors — the VPU's native shape;
* the 64 rounds are unrolled straight-line inside the kernel (statically
  indexed message words, rotate = shift|shift), state lives in VMEM tiles;
* output is ``uint32[N/128, 4, 128]``, transposed back to the ``[N, 4]``
  state-word layout the membership stage consumes.

Scope: messages that fit ONE 64-byte MD5 block (padded width <= 55 bytes —
every shipped bucket width up to 52 qualifies; the reference's own hot path
is short candidates, ``main.go:175-201``). The public wrapper falls back to
the XLA path for anything else, so callers can use it unconditionally.

Wired behind ``A5GEN_PALLAS=1`` (``models.attack.make_fused_body``) until
on-chip A/B timing confirms the win; interpret-mode CPU tests pin
word-exactness against ``ops.hashes.md5`` and hashlib
(tests/test_pallas_md5.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..audit import audited_entry
from ..runtime.env import env_is
from .hashes import _MD5_INIT, _MD5_K, _MD5_S, _blocks_for_width, pad_message

_U32 = jnp.uint32

#: Lane rows per grid step: (ROWS, 16, 128) uint32 inputs = ROWS * 8 KiB in
#: VMEM — 64 rows keeps the working set ~0.5 MiB, far under the ~16 MiB VMEM.
_ROWS_PER_TILE = 64


def _md5_kernel(w_ref, out_ref):
    """One grid step: ``w_ref`` is ``uint32[R, 16, 128]`` (message words),
    ``out_ref`` is ``uint32[R, 4, 128]`` (digest state words)."""
    m = [w_ref[:, j, :] for j in range(16)]
    a = jnp.full_like(m[0], _U32(_MD5_INIT[0]))
    b = jnp.full_like(m[0], _U32(_MD5_INIT[1]))
    c = jnp.full_like(m[0], _U32(_MD5_INIT[2]))
    d = jnp.full_like(m[0], _U32(_MD5_INIT[3]))
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        rot = a + f + _U32(_MD5_K[i]) + m[g]
        s = _MD5_S[i]
        rotated = (rot << _U32(s)) | (rot >> _U32(32 - s))
        a, d, c, b = d, c, b, b + rotated
    out_ref[:, 0, :] = a + _U32(_MD5_INIT[0])
    out_ref[:, 1, :] = b + _U32(_MD5_INIT[1])
    out_ref[:, 2, :] = c + _U32(_MD5_INIT[2])
    out_ref[:, 3, :] = d + _U32(_MD5_INIT[3])


def pallas_supported(num_lanes: int, width: int) -> bool:
    """Static eligibility: one MD5 block and a whole number of lane tiles."""
    return (
        _blocks_for_width(width) == 1
        and num_lanes % (128 * _ROWS_PER_TILE) == 0
    )


@audited_entry("ops.md5_pallas", kind="pallas_kernel")
def md5_pallas(
    msg: jnp.ndarray, length: jnp.ndarray, *, interpret: bool = False
) -> jnp.ndarray:
    """MD5 state words via the Pallas kernel; same contract as
    ``ops.hashes.md5`` (``uint8[N, W]``, ``int32[N]`` -> ``uint32[N, 4]``).
    Falls back to the XLA path when the geometry is ineligible."""
    from jax.experimental import pallas as pl

    n, width = msg.shape
    if not pallas_supported(n, width):
        from .hashes import md5

        return md5(msg, length)

    words, _ = pad_message(msg, length, big_endian_length=False)  # [N, 16]
    rows = n // 128
    x = words.reshape(rows, 128, 16).transpose(0, 2, 1)  # [rows, 16, 128]
    grid = (rows // _ROWS_PER_TILE,)
    out = pl.pallas_call(
        _md5_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 4, 128), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_ROWS_PER_TILE, 16, 128), lambda i: (i, 0, 0)
            )
        ],
        out_specs=pl.BlockSpec(
            (_ROWS_PER_TILE, 4, 128), lambda i: (i, 0, 0)
        ),
        interpret=interpret,
    )(x)
    return out.transpose(0, 2, 1).reshape(n, 4)


def maybe_pallas_hash_fn(algo: str, hash_fn):
    """The ``A5GEN_PALLAS=1`` hook: returns the Pallas-backed hash for MD5
    on a TPU backend, the given XLA ``hash_fn`` otherwise. Either way
    the returned callable keeps the hash contract
    ``uint8[B, W], int32[B] -> uint32[B, 4]``. Checked at trace-build
    time (the flag selects the compiled program, not a runtime
    branch)."""
    if algo == "md5" and env_is("A5GEN_PALLAS", "1"):
        # Check the DEVICE platform, not the backend name: the remote
        # tunnel registers a backend whose name differs from its device
        # platform ("tpu" devices behind an "axon" backend).
        try:
            on_tpu = jax.devices()[0].platform == "tpu"
        except Exception as e:  # pragma: no cover - backend-dependent
            import sys

            # The user explicitly asked for Pallas; a swallowed device-
            # enumeration error must not silently route to the slow path.
            print(
                f"a5gen: warning: A5GEN_PALLAS=1 but device enumeration "
                f"failed ({type(e).__name__}: {e}); using the XLA hash path",
                file=sys.stderr,
            )
            on_tpu = False
        if on_tpu:
            return md5_pallas
    return hash_fn

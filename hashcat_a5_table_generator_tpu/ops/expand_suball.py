"""Substitute-all (``-s``) expansion as index arithmetic — the flagship kernel.

The reference's transliteration engine (``processWordSubstituteAll``,
``main.go:308-365``) recursively assigns each unique pattern present in a word
one of its options *or skip*, then applies a ReplaceAll cascade at every leaf.
That keyspace is a product space: with patterns ``p_1..p_P`` present and
``r_i = options(p_i) + 1`` (the +1 is "skip"), every candidate is one digit
vector of the mixed-radix number ``Π r_i`` (SURVEY.md Q10). So instead of
recursion, the TPU enumerates **variant ids** and decodes them:

    variant id --mixed-radix decode--> digit vector
              --digit per pattern--> chosen option (0 = skip)
              --segment gather--> candidate bytes

The word is pre-split (host side, :func:`build_suball_plan`) into SEGMENTS —
alternating unclaimed gaps and pattern-occurrence spans. A variant's candidate
is the concatenation of each segment's bytes: the original slice for gaps and
un-chosen spans, the chosen option's value for chosen spans. Output offsets
are one prefix sum; bytes are two gathers. No recursion, no dynamic shapes.

Exactness ("fast path") conditions, checked per word at plan time:

* greedy leftmost occurrences of different patterns don't overlap — otherwise
  WHICH occurrences get replaced depends on the chosen subset, not the word;
* the table has no empty key (a ``=x`` line makes ReplaceAll insert between
  every character — oracle-only semantics);
* any cascade hazard among the word's present patterns
  (``CompiledTable.cascade_hazard`` — the sorted-order ReplaceAll cascade
  re-matching inserted text) is **closable**: every possible re-match lies
  wholly INSIDE an inserted value (containment, never boundary-crossing —
  ``CompiledTable.cascade_crossing``), so the cascade's effect on a span is
  a statically-known value rewrite. Closable hazard slots get a **joint
  value table** built at plan time (:func:`_close_pattern_set`): slot ``p``
  with hazard successors ``q1 < q2 < ...`` stores one pre-cascaded value row
  per joint digit combination ``(d_p, d_q1, ...)`` — exactly
  ``v.replace(q1, u1).replace(q2, u2)...`` in sorted-pattern order — and the
  kernels address it with a mixed-radix index over the successors' decoded
  digits (``close_next`` / ``close_mul``). Words whose hazards all close
  this way run on device (``closed=True``); the device stream stays
  word-multiset-identical to the oracle by construction.

Words failing these checks — cross-pattern overlaps, empty keys, and
*genuinely pathological* hazards (boundary-crossing rewrites, splice-joining
empty values, or joint tables past the closure caps) — get ``fallback=True``
and are routed through the byte-exact CPU oracle by the runtime. With
closure, the bidirectional qwerty-azerty table's hazard words (10.2% of a
rockyou-class dictionary, ~23% of its candidates — PERF.md §5) run on
device; only the (vanishing) cap-overflow words still fall back.
``A5GEN_CASCADE_CLOSE=off`` disables closure (every hazard word falls back,
the pre-closure behavior) — the escape hatch and the A/B lever.

Work unit: a **block** ``(word, base_digits, count)`` covering a contiguous
range of the word's variant space. Blocks are how huge single-word spaces are
split across chips (SURVEY.md §5 "long-context") and how sweep cursors resume:
the host cuts arbitrary [cursor, cursor+n) ranges with bigint divmods, and the
device adds the in-block rank to ``base_digits`` with mixed-radix carries —
everything on device stays uint32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..runtime.env import env_opt_out
from ..tables.compile import CompiledTable, boundary_match_possible
from .blocks import (  # noqa: F401  — re-exported: this module defined them first
    MAX_BLOCK,
    BlockBatch,
    digits_of,
    make_blocks,
)
from .expand_matches import (
    decode_digits,
    key_deltas,
    lane_fields,
    rounded_out_width,
    variant_totals,
    windowed_plan_fields,
)
from .packing import PackedWords

#: Cascade-closure caps. A hazard slot's joint value table covers its own
#: options × every successor's radix; past these bounds the word stays on
#: the oracle (the table would bloat the plan and the Pallas kernel's
#: K-way select). MAX_CLOSE_OPTS=12 covers every common qwerty-azerty
#: hazard set ({a,q}: 2 rows; {",",m}: 9; {A,Q,q} / {",",";"}: 12); only
#: words holding 3+ mutually-hazardous patterns (e.g. , ; m together)
#: overflow.
MAX_CLOSE_SUCC = 3
MAX_CLOSE_OPTS = 12


def close_enabled() -> bool:
    """Cascade closure is ON by default; ``A5GEN_CASCADE_CLOSE`` set to
    ``off``/``0``/``no`` reverts to routing every hazard word through the
    CPU oracle (the pre-closure behavior — escape hatch and A/B lever)."""
    return not env_opt_out("A5GEN_CASCADE_CLOSE", "device cascade closure")


def _close_pattern_set(
    ct: CompiledTable, kis: Tuple[int, ...], first_option_only: bool
) -> "Optional[Tuple[List[List[int]], List[Optional[List[bytes]]]]]":
    """Try to close the ReplaceAll cascade for a word whose present patterns
    are ``kis`` (ascending key indices; caller guarantees no cross-pattern
    overlaps and no empty key — those words stay oracle-routed).

    Walks each slot's *reachable span texts* stage by stage through the
    later-sorted patterns: original span bytes are safe by the overlap
    invariant (any match touching an unreplaced span would be an
    occurrence-claim conflict in the original word), so only inserted /
    rewritten values are tracked. A later pattern that could match CROSSING
    a reachable text's boundary (``tables.compile.boundary_match_possible``
    — includes the empty-value splice join) makes the word genuinely
    pathological; a pattern matching INSIDE one becomes a hazard successor
    and forks the reachable set by its options. Multi-level rewrites
    (a successor's replacement re-matched by a later pattern) are handled
    by the same walk — the successor list simply grows.

    Returns ``(succ, rows)`` per local slot — ``succ[i]``: ascending local
    slot indices of slot i's hazard successors; ``rows[i]``: the closed
    value table (None when slot i needs no closure), one pre-cascaded row
    per joint digit combination in lexicographic ``(d_i, d_j1, d_j2, ...)``
    order with the LAST successor's digit varying fastest — or None when
    the word is pathological (boundary crossing or closure caps)."""
    keys = [ct.keys[ki] for ki in kis]
    vals: List[List[bytes]] = []
    for ki in kis:
        s0, c = int(ct.val_start[ki]), int(ct.val_count[ki])
        if first_option_only:
            c = min(1, c)
        vals.append([
            bytes(ct.val_bytes[s0 + o, : ct.val_len[s0 + o]])
            for o in range(c)
        ])
    n = len(kis)
    succ: List[List[int]] = []
    rows: List[Optional[List[bytes]]] = []
    for i in range(n):
        reach = list(dict.fromkeys(vals[i]))
        s_i: List[int] = []
        for j in range(i + 1, n):
            q = keys[j]
            if any(boundary_match_possible(t, q) for t in reach):
                return None  # splice/crossing rewrite: oracle only
            if any(q in t for t in reach):
                s_i.append(j)
                if len(s_i) > MAX_CLOSE_SUCC:
                    return None
                reach = list(dict.fromkeys(
                    reach
                    + [t.replace(q, u) for t in reach for u in vals[j]]
                ))
        if s_i:
            jopts = len(vals[i])
            for j in s_i:
                jopts *= len(vals[j]) + 1
            if jopts > MAX_CLOSE_OPTS:
                return None
            out: List[bytes] = []

            def build(t: bytes, idx: int) -> None:
                if idx == len(s_i):
                    out.append(t)
                    return
                j = s_i[idx]
                build(t, idx + 1)  # successor skipped (digit 0)
                for u in vals[j]:
                    # Sorted-pattern cascade order: successors ascend, so
                    # the replace chain IS the oracle's Q4 order.
                    build(t.replace(keys[j], u), idx + 1)

            for v in vals[i]:
                build(v, 0)
            rows.append(out)
        else:
            rows.append(None)
        succ.append(s_i)
    return succ, rows


#: Pattern-set closure record: the _close_pattern_set result (successor
#: lists + closed value rows per local slot), shared by every word whose
#: present-pattern set matches.
_SetClosure = Tuple[List[List[int]], List[Optional[List[bytes]]]]


def _closure_fields(
    ct: CompiledTable,
    closure_sets: Dict[Tuple[int, ...], _SetClosure],
    word_sets: Dict[Tuple[int, ...], List[int]],
    key_radix: np.ndarray,
    pat_val_start: np.ndarray,
    num_p: int,
    batch: int,
):
    """Materialize plan fields from pattern-SET closures (shared by both
    plan builders; mutates ``pat_val_start`` rows of closed slots to point
    into the extended value table). All work is per distinct pattern set
    (azerty-class dictionaries have a handful), with the set's word rows
    assigned by one fancy index each — no per-word Python loop, matching
    the fast builder's scaling contract.

    ``closure_sets`` maps a present-pattern key-index tuple to its
    ``(succ, rows)`` closure; ``word_sets`` maps the same keys to the
    ascending word rows holding that set; ``key_radix`` is the per-key
    ``options + 1`` (options already clamped for suball-reverse).

    Returns ``(close_next [B,P,S], close_mul [B,P,S+1], cval_bytes,
    cval_len, close_opts, wmax)`` — ``close_mul[..., 0]`` is the OWN
    digit's multiplier (1 on non-closed slots, so the uniform device
    address ``val_start + (d-1)*mul0 + Σ d_succ*mul_s`` degenerates to the
    classic ``val_start + d - 1``); ``wmax [B, num_p]`` holds each closed
    slot's widest pre-cascaded row (-1 elsewhere) for output-width sizing.
    Closed value rows are deduplicated by ``(key, successor-key tuple)``;
    insertion order is by each set's FIRST word row, so the fast and
    scalar builders produce identical extended tables."""
    s_max = 1
    for succ, rows in closure_sets.values():
        for sl, r in enumerate(rows):
            if r is not None:
                s_max = max(s_max, len(succ[sl]))
    close_next = np.full((batch, num_p, s_max), -1, dtype=np.int32)
    close_mul = np.zeros((batch, num_p, s_max + 1), dtype=np.int32)
    close_mul[:, :, 0] = 1
    wmax = np.full((batch, num_p), -1, dtype=np.int64)
    v0 = int(ct.val_bytes.shape[0])
    ext_rows: List[bytes] = []
    ext_base: Dict[tuple, int] = {}
    close_opts = 0
    for kis in sorted(word_sets, key=lambda k: word_sets[k][0]):
        succ, rows = closure_sets[kis]
        rws = np.asarray(word_sets[kis], dtype=np.int64)
        for sl, r in enumerate(rows):
            if r is None:
                continue
            key = (kis[sl], tuple(kis[j] for j in succ[sl]))
            if key not in ext_base:
                ext_base[key] = v0 + len(ext_rows)
                ext_rows.extend(r)
            pat_val_start[rws, sl] = ext_base[key]
            mul = 1
            for s_i in range(len(succ[sl]) - 1, -1, -1):
                j = succ[sl][s_i]
                close_next[rws, sl, s_i] = j
                close_mul[rws, sl, 1 + s_i] = mul
                mul *= int(key_radix[kis[j]])
            close_mul[rws, sl, 0] = mul
            close_opts = max(close_opts, len(r))
            wmax[rws, sl] = max((len(x) for x in r), default=0)
    width = max(
        int(ct.val_bytes.shape[1]),
        max((len(x) for x in ext_rows), default=1),
        1,
    )
    e = len(ext_rows)
    cval_bytes = np.zeros((v0 + e, width), dtype=np.uint8)
    cval_bytes[:v0, : ct.val_bytes.shape[1]] = ct.val_bytes
    cval_len = np.zeros((v0 + e,), dtype=np.int32)
    cval_len[:v0] = ct.val_len
    for r_i, x in enumerate(ext_rows):
        if x:
            cval_bytes[v0 + r_i, : len(x)] = np.frombuffer(x, dtype=np.uint8)
        cval_len[v0 + r_i] = len(x)
    return close_next, close_mul, cval_bytes, cval_len, close_opts, wmax


@dataclass(frozen=True)
class SubAllPlan:
    """Device-ready per-word expansion plan for substitute-all mode.

    Axes: B words, P pattern slots (slot order = sorted-pattern order, slot 0
    is the least-significant mixed-radix digit), G segments (in word order).
    """

    tokens: np.ndarray  # uint8 [B, L]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — wordlist ordinals (from PackedWords)
    pat_radix: np.ndarray  # int32 [B, P] — options+1, 1 on inactive slots
    pat_val_start: np.ndarray  # int32 [B, P] — CSR into table val rows
    seg_orig_start: np.ndarray  # int32 [B, G]
    seg_orig_len: np.ndarray  # int32 [B, G] — 0 on inactive segments
    seg_pat: np.ndarray  # int32 [B, G] — pattern slot, -1 for gaps
    n_variants: Tuple[int, ...]  # python bigints — Π radix per word, or the
    #                              windowed totals when ``windowed``
    fallback: np.ndarray  # bool [B] — word needs the CPU oracle
    out_width: int  # static candidate-buffer width (uint32-aligned)
    windowed: bool = False  # count-windowed enumeration active
    win_v: "np.ndarray | None" = None  # int32 [B, P+1, K+2] suffix counts
    #   (see expand_matches.MatchPlan.win_v — identical scheme over
    #   pattern slots)
    # --- cascade closure (all None/0 when no word needed closure) --------
    closed: "np.ndarray | None" = None  # bool [B] — device-closed words
    close_next: "np.ndarray | None" = None  # int32 [B, P, S] — successor
    #   slots of each pattern slot (-1 inactive)
    close_mul: "np.ndarray | None" = None  # int32 [B, P, S+1] — joint value
    #   index multipliers; column 0 multiplies the slot's OWN digit-1
    cval_bytes: "np.ndarray | None" = None  # uint8 [V+E, W] — plan value
    #   table: the compiled table's rows + closed-cascade rows (device
    #   kernels use this INSTEAD of table_arrays' val_bytes when present)
    cval_len: "np.ndarray | None" = None  # int32 [V+E]
    close_opts: int = 0  # widest closed joint table (rows per slot)

    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def num_slots(self) -> int:
        return int(self.pat_radix.shape[1])

    @property
    def num_segments(self) -> int:
        return int(self.seg_orig_start.shape[1])


def _build_suball_plan_fast(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool,
    out_width: "int | None",
    min_substitute: "int | None",
    max_substitute: "int | None",
    force_windowed: "bool | None" = None,
) -> "SubAllPlan | None":
    """Vectorized plan construction for every table WITHOUT an empty key
    (the ``=x`` line routes all words to the oracle — rare and cheap, so
    it keeps the scalar path).

    The scan vectorizes per key: single-byte keys are one byte-LUT lookup;
    multi-byte keys use shifted compares plus an O(L) greedy pass that
    reproduces ``bytes.find``'s non-overlapping occurrence walk. The
    scalar path's word-level fallback flag is equivalent to "some pair of
    occurrences overlaps": if no claim conflict fires, every key's
    occurrence loop completes, so claimed spans ARE the independent
    occurrence sets and are disjoint; conversely any overlap between
    independent occurrences is detected when the later-sorted key claims.
    Cross-pattern cascade hazards reduce to a presence×hazard matmul.

    For fallback words the scalar path records the PARTIAL spans claimed
    before the conflict; those segment fields are dead (the block cutter
    skips fallback words, the oracle re-derives their candidates), so this
    path stores the independent spans instead and only guarantees segment
    equality on non-fallback rows; pattern-slot fields ARE equal
    everywhere because both paths neutralize fallback rows to radix 1
    before the windowed decision (tests pin exactly this contract; width
    sizing also considers only non-fallback rows). The per-word Python
    loop this replaces took ~30 s for a 300k-word dictionary — longer
    than the whole device sweep.
    """
    if ct.has_empty_key or ct.num_keys == 0:
        return None
    tokens, lengths = packed.tokens, packed.lengths
    b, width = tokens.shape
    if b == 0 or width == 0:
        return None  # degenerate shapes: keep the scalar reference path
    j = np.arange(width)
    in_word = j[None, :] < lengths[:, None]
    k = ct.num_keys

    # Occurrence scan: per-position key index / span length, coverage
    # deltas for the overlap test, presence and span counts per word.
    occ_key = np.full((b, width), -1, dtype=np.int32)
    occ_len = np.zeros((b, width), dtype=np.int32)
    cover_delta = np.zeros((b, width + 1), dtype=np.int32)
    present = np.zeros((b, k), dtype=bool)
    span_count = np.zeros(b, dtype=np.int64)

    if ct.max_key_len >= 1:
        ki1 = np.where(in_word, ct.byte_to_key[tokens], -1)  # [B, L]
        m1 = ki1 >= 0
        occ_key = np.where(m1, ki1, occ_key)
        occ_len = np.where(m1, 1, occ_len)
        cover_delta[:, :width] += m1
        cover_delta[:, 1:] -= m1
        r1, c1 = np.nonzero(m1)
        present[r1, ki1[r1, c1]] = True
        span_count += m1.sum(axis=1)

    for kidx in np.nonzero((ct.key_len >= 2) & (ct.key_len <= width))[0]:
        klen = int(ct.key_len[kidx])
        key = ct.key_bytes[kidx]
        match = (j[None, :] + klen) <= lengths[:, None]
        for t in range(klen):
            match[:, : width - t] &= tokens[:, t:] == key[t]
            if t:
                match[:, width - t:] = False
        # Greedy non-overlapping same-key occurrences (bytes.find walk).
        sel = np.zeros((b, width), dtype=bool)
        next_free = np.zeros(b, dtype=np.int32)
        for jj in range(width - klen + 1):
            take = match[:, jj] & (jj >= next_free)
            sel[:, jj] = take
            next_free = np.where(take, jj + klen, next_free)
        occ_key = np.where(sel, np.int32(kidx), occ_key)
        occ_len = np.where(sel, np.int32(klen), occ_len)
        cover_delta[:, :width] += sel
        cover_delta[:, klen:] -= sel[:, : width + 1 - klen]
        present[:, kidx] |= sel.any(axis=1)
        span_count += sel.sum(axis=1)

    coverage = np.cumsum(cover_delta[:, :width], axis=1)  # [B, L]
    overlap_mask = (coverage > 1).any(axis=1)
    hazard_mask = np.zeros(b, dtype=bool)
    if ct.cascade_hazard.any():
        hz = ct.cascade_hazard.astype(np.int32)
        m = present.astype(np.int32) @ hz  # hazardous-predecessor counts
        hazard_mask = ((m > 0) & present).any(axis=1)
    fallback_mask = overlap_mask | hazard_mask

    # Cascade closure: containment-only hazard words keep the device path
    # (their hazard slots get joint value tables — see the module
    # docstring). Closure analysis runs once per present-pattern SET:
    # azerty-class tables have a handful of distinct hazard sets across a
    # whole dictionary, and every downstream materialization stays
    # set-level too (one fancy index per set — no per-word Python loop).
    closed_mask = np.zeros(b, dtype=bool)
    closure_sets: Dict[Tuple[int, ...], _SetClosure] = {}
    word_sets: Dict[Tuple[int, ...], List[int]] = {}
    if close_enabled() and bool(hazard_mask.any()):
        set_cache: Dict[Tuple[int, ...], "Optional[_SetClosure]"] = {}
        for i in np.nonzero(hazard_mask & ~overlap_mask)[0]:
            kis = tuple(int(x) for x in np.nonzero(present[i])[0])
            if kis not in set_cache:
                set_cache[kis] = _close_pattern_set(
                    ct, kis, first_option_only
                )
            cl = set_cache[kis]
            if cl is not None:
                fallback_mask[i] = False
                if any(r is not None for r in cl[1]):
                    closure_sets[kis] = cl
                    word_sets.setdefault(kis, []).append(int(i))
                    closed_mask[i] = True
                # All-None rows: the (conservative) table-level hazard
                # never manifests under this mode's option set (e.g. the
                # hazard value is clamped away in suball-reverse) — the
                # plain span-splice path is exact, so the word is CLEAN,
                # not closed.

    # Slots: the word's present keys in ascending order. Fallback rows
    # are neutralized below (radix 1) in BOTH paths, so dead rows never
    # influence the windowed-enumeration decision and pat_* fields agree
    # everywhere.
    num_p = max(1, int(present.sum(axis=1).max()))
    krank = np.cumsum(present, axis=1) - 1  # [B, K]
    vc = ct.val_count.astype(np.int64)
    options = np.minimum(1, vc) if first_option_only else vc
    key_radix = (options + 1).astype(np.int32)
    pat_radix = np.ones((b, num_p), dtype=np.int32)
    pat_val_start = np.zeros((b, num_p), dtype=np.int32)
    pw, pk = np.nonzero(present)
    slot_of = krank[pw, pk]
    pat_radix[pw, slot_of] = key_radix[pk]
    pat_val_start[pw, slot_of] = ct.val_start[pk]
    # Closure fields before neutralization: closed words keep live radices
    # and get their hazard slots re-pointed into the extended value table.
    close_next = close_mul = cval_bytes = cval_len = wmax = None
    close_opts = 0
    if closure_sets:
        (close_next, close_mul, cval_bytes, cval_len, close_opts,
         wmax) = _closure_fields(
            ct, closure_sets, word_sets, key_radix, pat_val_start, num_p, b
        )
    pat_radix[fallback_mask] = 1
    pat_val_start[fallback_mask] = 0

    # Segments: spans start where an occurrence starts; gaps start at
    # word-open or right after covered text. (Fallback rows may hold
    # overlapping spans — their fields are dead, see docstring.)
    covered = coverage > 0
    prev_covered = np.zeros_like(covered)
    prev_covered[:, 1:] = covered[:, :-1]
    span_start = occ_len > 0
    seg_start_mask = in_word & (
        span_start | (~covered & ((j[None, :] == 0) | prev_covered))
    )
    num_g = 2 * max(1, int(span_count.max())) + 1
    seg_rank = np.cumsum(seg_start_mask, axis=1) - 1
    srows, scols = np.nonzero(seg_start_mask)
    gidx = seg_rank[srows, scols]
    if len(gidx) and int(gidx.max()) >= num_g:
        num_g = int(gidx.max()) + 1  # safety: never truncate segments
    # Segment end = next segment's start in the same row, else word end
    # (for spans that equals start + key length on non-fallback rows).
    nxt = np.empty_like(scols)
    if len(scols):
        nxt[:-1] = scols[1:]
        nxt[-1] = 0
    same_row = np.zeros(len(srows), dtype=bool)
    if len(srows):
        same_row[:-1] = srows[1:] == srows[:-1]
    seg_end = np.where(same_row, nxt, lengths[srows])
    seg_orig_start = np.zeros((b, num_g), dtype=np.int32)
    seg_orig_len = np.zeros((b, num_g), dtype=np.int32)
    seg_pat = np.full((b, num_g), -1, dtype=np.int32)
    seg_orig_start[srows, gidx] = scols
    is_span = span_start[srows, scols]
    seg_orig_len[srows, gidx] = np.where(
        is_span, occ_len[srows, scols], (seg_end - scols).astype(np.int32)
    )
    s_ki = np.clip(occ_key[srows, scols], 0, k - 1)
    seg_pat[srows, gidx] = np.where(
        is_span, krank[srows, s_ki], -1
    ).astype(np.int32)

    # Output growth per occurrence (non-fallback rows size the buffer —
    # fallback words never reach the device).
    delta_per_key = key_deltas(ct, limit_first_option=False)
    orows, ocols = np.nonzero(occ_len > 0)
    word_delta = np.zeros(b, dtype=np.int64)
    np.add.at(word_delta, orows, delta_per_key[occ_key[orows, ocols]])
    # Closed words: a rewritten row can outgrow the table's widest value
    # (v.replace can lengthen), so their growth re-sums over the closed
    # tables' widest rows — vectorized over the closed occurrences via
    # the wmax [B, P] matrix (same scatter scheme as the base delta).
    if wmax is not None:
        in_closed = closed_mask[orows]
        r2, c2 = orows[in_closed], ocols[in_closed]
        ki2 = occ_key[r2, c2]
        w2 = wmax[r2, krank[r2, ki2]]
        contrib = np.where(
            w2 >= 0,
            np.maximum(0, w2 - occ_len[r2, c2]),
            delta_per_key[ki2],
        )
        word_delta[closed_mask] = 0
        np.add.at(word_delta, r2, contrib)
    word_delta[fallback_mask] = 0
    max_delta = int(word_delta.max())
    if out_width is None:
        out_width = rounded_out_width(width, max_delta)

    n_variants = variant_totals(pat_radix)
    for i in np.nonzero(fallback_mask)[0]:
        n_variants[int(i)] = 0

    windowed, win_v, n_variants = windowed_plan_fields(
        pat_radix, n_variants, min_substitute, max_substitute,
        zero_mask=fallback_mask, force=force_windowed,
    )
    return SubAllPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        pat_radix=pat_radix,
        pat_val_start=pat_val_start,
        seg_orig_start=seg_orig_start,
        seg_orig_len=seg_orig_len,
        seg_pat=seg_pat,
        n_variants=tuple(n_variants),
        fallback=fallback_mask,
        out_width=out_width,
        windowed=windowed,
        win_v=win_v,
        closed=closed_mask if closure_sets else None,
        close_next=close_next,
        close_mul=close_mul,
        cval_bytes=cval_bytes,
        cval_len=cval_len,
        close_opts=close_opts,
    )


def build_suball_plan(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool = False,
    out_width: int | None = None,
    min_substitute: int | None = None,
    max_substitute: int | None = None,
    force_windowed: bool | None = None,
) -> SubAllPlan:
    """Host-side plan construction (numpy + bytes.find; the C++ packer will
    take this over for the file-to-plan hot path).

    ``first_option_only=True`` builds the ``-s -r`` (substitute-all reverse)
    space: the reference enumerates every subset of present patterns with
    only ``subs[0]`` applied (Q2, ``main.go:393-398``), which is exactly this
    plan with every radix clamped to 2. Its per-word multiset equals the
    oracle's subset lattice (each subset emitted once, size windowed)."""
    fast = _build_suball_plan_fast(
        ct, packed, first_option_only=first_option_only,
        out_width=out_width, min_substitute=min_substitute,
        max_substitute=max_substitute, force_windowed=force_windowed,
    )
    if fast is not None:
        return fast
    b, width = packed.tokens.shape
    hazard = ct.cascade_hazard

    per_word: List[dict] = []
    closure_sets: Dict[Tuple[int, ...], _SetClosure] = {}
    word_sets: Dict[Tuple[int, ...], List[int]] = {}
    set_cache: Dict[Tuple[int, ...], "Optional[_SetClosure]"] = {}
    max_p = 1
    max_s = 1
    for i in range(b):
        word = packed.word(i)
        slots: List[int] = []  # key indices, ascending = sorted patterns
        spans: List[Tuple[int, int, int]] = []  # (start, klen, slot)
        claimed = np.zeros(len(word), dtype=bool)
        overlap = ct.has_empty_key
        for ki, key in enumerate(ct.keys):
            if not key or overlap:
                continue
            pos = word.find(key)
            if pos < 0:
                continue
            slot = len(slots)
            slots.append(ki)
            while pos >= 0:
                end = pos + len(key)
                if claimed[pos:end].any():
                    overlap = True  # cross-pattern overlap: subset-dependent
                    break
                claimed[pos:end] = True
                spans.append((pos, len(key), slot))
                pos = word.find(key, end)
        hazardous = False
        if not overlap and len(slots) > 1:
            ks = np.asarray(slots)
            hazardous = bool(hazard[np.ix_(ks, ks)].any())
        fallback = overlap or hazardous
        closure = None
        if hazardous and not overlap and close_enabled():
            kis = tuple(slots)
            if kis not in set_cache:
                set_cache[kis] = _close_pattern_set(
                    ct, kis, first_option_only
                )
            cl = set_cache[kis]
            if cl is not None:
                fallback = False
                if any(r is not None for r in cl[1]):
                    closure = cl
                    closure_sets[kis] = cl
                    word_sets.setdefault(kis, []).append(i)
                # else: hazard never manifests under this option set —
                # clean, not closed (mirrors the fast path).
        spans.sort()
        per_word.append({"slots": slots, "spans": spans,
                         "fallback": fallback, "closure": closure})
        max_p = max(max_p, len(slots))
        max_s = max(max_s, len(spans))

    num_p, num_g = max_p, 2 * max_s + 1
    pat_radix = np.ones((b, num_p), dtype=np.int32)
    pat_val_start = np.zeros((b, num_p), dtype=np.int32)
    seg_orig_start = np.zeros((b, num_g), dtype=np.int32)
    seg_orig_len = np.zeros((b, num_g), dtype=np.int32)
    seg_pat = np.full((b, num_g), -1, dtype=np.int32)
    n_variants: List[int] = []
    fallback_mask = np.zeros((b,), dtype=bool)
    max_delta = 0

    for i, info in enumerate(per_word):
        fallback_mask[i] = info["fallback"]
        total = 1
        for slot, ki in enumerate(info["slots"]):
            options = min(1, int(ct.val_count[ki])) if first_option_only else int(ct.val_count[ki])
            pat_radix[i, slot] = options + 1
            pat_val_start[i, slot] = ct.val_start[ki]
            total *= options + 1
        n_variants.append(total if not info["fallback"] else 0)

        # Segments: gap before each span, the span, and a final gap to len.
        g = 0
        cursor = 0
        delta = 0
        for start, klen, slot in info["spans"]:
            if start > cursor:
                seg_orig_start[i, g] = cursor
                seg_orig_len[i, g] = start - cursor
                g += 1
            seg_orig_start[i, g] = start
            seg_orig_len[i, g] = klen
            seg_pat[i, g] = slot
            g += 1
            cursor = start + klen
            ki = info["slots"][slot]
            closure = info["closure"]
            if closure is not None and closure[1][slot] is not None:
                # Closed slot: growth is bounded by the joint table's
                # widest pre-cascaded row, not the raw value rows.
                widest = max(len(x) for x in closure[1][slot])
            else:
                vs, vc = int(ct.val_start[ki]), int(ct.val_count[ki])
                widest = max(
                    (int(ct.val_len[vs + o]) for o in range(vc)),
                    default=klen,
                )
            delta += max(0, widest - klen)
        word_len = int(packed.lengths[i])
        if cursor < word_len:
            seg_orig_start[i, g] = cursor
            seg_orig_len[i, g] = word_len - cursor
            g += 1
        max_delta = max(max_delta, delta)

    if out_width is None:
        out_width = max(4, -(-(width + max_delta) // 4) * 4)

    # Closure fields before neutralization (mirrors the fast path).
    close_next = close_mul = cval_bytes = cval_len = None
    close_opts = 0
    closed_mask = np.zeros((b,), dtype=bool)
    if closure_sets:
        for rws in word_sets.values():
            closed_mask[rws] = True
        vc_k = ct.val_count.astype(np.int64)
        opts_k = np.minimum(1, vc_k) if first_option_only else vc_k
        close_next, close_mul, cval_bytes, cval_len, close_opts, _ = (
            _closure_fields(
                ct, closure_sets, word_sets,
                (opts_k + 1).astype(np.int32),
                pat_val_start, num_p, b,
            )
        )

    # Neutralize fallback rows (mirrored in the fast path): their slots
    # are dead — the oracle re-derives those words — and must not sway
    # the global windowed-enumeration decision below.
    pat_radix[fallback_mask] = 1
    pat_val_start[fallback_mask] = 0

    # Count-windowed enumeration for tight -m/-x windows (same DP scheme
    # as match plans — the suball count is "distinct patterns chosen",
    # which is exactly "digits > 0 over slots with options"). Fallback
    # words keep the oracle route: totals forced to 0, matching the
    # full-enumeration convention above.
    windowed, win_v, n_variants = windowed_plan_fields(
        pat_radix, n_variants, min_substitute, max_substitute,
        zero_mask=fallback_mask, force=force_windowed,
    )

    return SubAllPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        pat_radix=pat_radix,
        pat_val_start=pat_val_start,
        seg_orig_start=seg_orig_start,
        seg_orig_len=seg_orig_len,
        seg_pat=seg_pat,
        n_variants=tuple(n_variants),
        fallback=fallback_mask,
        out_width=out_width,
        windowed=windowed,
        win_v=win_v,
        closed=closed_mask if closure_sets else None,
        close_next=close_next,
        close_mul=close_mul,
        cval_bytes=cval_bytes,
        cval_len=cval_len,
        close_opts=close_opts,
    )


def expand_suball(
    tokens: jnp.ndarray,  # uint8 [B, L]
    lengths: jnp.ndarray,  # int32 [B]
    pat_radix: jnp.ndarray,  # int32 [B, P]
    pat_val_start: jnp.ndarray,  # int32 [B, P]
    seg_orig_start: jnp.ndarray,  # int32 [B, G]
    seg_orig_len: jnp.ndarray,  # int32 [B, G]
    seg_pat: jnp.ndarray,  # int32 [B, G]
    val_bytes: jnp.ndarray,  # uint8 [V, val_width] — compiled table values
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, P]
    blk_count: jnp.ndarray,  # int32 [NB]
    blk_offset: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
    block_stride: int | None = None,
    win_v: jnp.ndarray | None = None,
    radix2: bool = False,
    close_next: jnp.ndarray | None = None,  # int32 [B, P, S]
    close_mul: jnp.ndarray | None = None,  # int32 [B, P, S+1]
    pieces=None,  # packing.PieceSchema — per-slot emission (PERF.md §17)
    piece_tables: "dict | None" = None,  # device copies of pieces' arrays
    pair_k: "int | None" = None,  # pair-lane tier (K=2, PERF.md §24)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode + materialize ``num_lanes`` variants.

    Returns ``(cand uint8[N, out_width], cand_len int32[N], word_row int32[N],
    emit bool[N])`` — ``emit`` folds together lane validity (rank in range)
    and the min/max chosen-pattern-count window.

    ``pair_k=2`` selects the pair-lane tier (PERF.md §24; contract as in
    ``expand_matches.expand_matches``): one decode covers candidate
    ranks ``2r``/``2r+1`` per lane, and the schema's pair gate
    guarantees slot 0 drives only column 0 — so the partner's variant
    vector differs in that single column.  Outputs interleave to
    ``2 * num_lanes`` candidate rows.

    ``block_stride``: fixed-stride batch layout — constant-divide lane ->
    block plus per-block broadcasts instead of per-lane searchsorted +
    gathers (see ``expand_matches.expand_matches``). ``win_v``: windowed
    plans unrank only in-window digit vectors (``expand_matches.
    decode_digits``; block bases are scalar ranks). ``close_next`` /
    ``close_mul``: cascade-closed plans address a slot's value row by the
    joint index over its own and its hazard-successors' digits (the
    ``val_bytes`` passed must then be the plan's extended ``cval_bytes``).
    """
    n = num_lanes
    p = pat_radix.shape[1]
    g = seg_orig_start.shape[1]

    if pair_k:
        from .expand_matches import pair_lane_fields

        if pair_k != 2:
            raise ValueError(f"pair_k must be 2 or None, got {pair_k}")
        if (
            pieces is None or not pieces.pair_ok or win_v is not None
            or close_next is not None
        ):
            raise ValueError(
                "the pair-lane tier needs a pair-eligible PieceSchema, "
                "full enumeration, and no cascade closure; gate via "
                "pallas_expand.pair_for_config"
            )
        rank, ok0, ok1, w, base, field = pair_lane_fields(
            blk_word, blk_base, blk_count,
            num_lanes=n, block_stride=block_stride,
        )
        lane_ok = ok0  # per-member masks consumed below
        rank_c = rank * 2
        max_rank = 2 * block_stride
    else:
        rank, lane_ok, w, base, field = lane_fields(
            blk_word, blk_base, blk_count, blk_offset,
            num_lanes=n, block_stride=block_stride,
        )
        rank_c = rank
        max_rank = block_stride or n
    radix = field(pat_radix)  # [N, P]
    spat_w = field(seg_pat)  # [N, G]
    pvs_w = field(pat_val_start)  # [N, P]
    olen_w = field(seg_orig_len)  # [N, G]
    ostart_w = field(seg_orig_start)  # [N, G]
    tokens_w = field(tokens)  # [N, L]

    digits = decode_digits(
        rank_c, base, radix, field, win_v, p, max_rank=max_rank,
        radix2=radix2,
    )  # [N, P]

    active = radix > 1
    chosen_count = jnp.sum((digits > 0) & active, axis=1)

    # Per-slot value-row offset: the joint closure index for closed plans
    # (successor digits gathered once and folded in with their mixed-radix
    # multipliers), plain ``digit - 1`` otherwise.
    if close_next is not None:
        cn = field(close_next)  # [N, P, S]
        cm = field(close_mul)  # [N, P, S+1]
        s_ax = cn.shape[2]
        idx = jnp.clip(cn, 0, p - 1).reshape(-1, p * s_ax)
        dsucc = jnp.take_along_axis(digits, idx, axis=1).reshape(
            -1, p, s_ax
        )
        jd = (digits - 1) * cm[:, :, 0] + jnp.sum(
            jnp.where(cn >= 0, dsucc * cm[:, :, 1:], 0), axis=2
        )
    else:
        jd = digits - 1

    if pieces is not None:
        # Per-slot piece emission (the XLA twin of the piece kernels):
        # schema columns are the plan's pattern segments in word order;
        # each column's variant index is its owning slot's digit (joint
        # value index + 1 under cascade closure — expand_matches.
        # splice_pieces is the shared materializer).
        from .expand_matches import piece_device_tables, splice_pieces

        tabs = piece_tables or piece_device_tables(pieces)
        sslot = (piece_tables or {}).get("sslot")
        if sslot is None:
            sslot = jnp.asarray(pieces.sel_slot)
        sslot_w = field(sslot)  # [N, C]
        col_d = jnp.take_along_axis(digits, sslot_w, axis=1)
        if close_next is not None:
            col_jd = jnp.take_along_axis(jd, sslot_w, axis=1)
            col_var = jnp.where(col_d > 0, 1 + col_jd, 0)
        else:
            col_var = col_d
        if pair_k:
            from .expand_matches import (
                interleave_pairs,
                splice_pieces_pair,
            )

            d0 = digits[:, 0]
            d0p = jnp.minimum(d0 + 1, radix[:, 0] - 1)
            # Pair gate: slot 0 drives column 0 (and only it) on every
            # launched row; garbage rows may alias — masked by emit.
            col0p = jnp.where(sslot_w[:, 0] == 0, d0p, col_var[:, 0])
            out0, len0, out1, len1 = splice_pieces_pair(
                pieces, tabs, field, digits, col0p,
                lambda c: col_var[:, c], n=n, out_width=out_width,
            )
            act0 = active[:, 0]
            cc1 = chosen_count + (
                (d0p > 0) & act0
            ).astype(jnp.int32) - ((d0 > 0) & act0).astype(jnp.int32)
            window = lambda ok, cc: (  # noqa: E731
                ok & (cc >= min_substitute) & (cc <= max_substitute)
            )
            return (
                interleave_pairs(out0, out1),
                interleave_pairs(len0, len1).astype(jnp.int32),
                interleave_pairs(w, w),
                interleave_pairs(
                    window(ok0, chosen_count), window(ok1, cc1)
                ),
            )
        out, out_len = splice_pieces(
            pieces, tabs, field, lambda c: col_var[:, c],
            n=n, out_width=out_width,
        )
        emit = (
            lane_ok
            & (chosen_count >= min_substitute)
            & (chosen_count <= max_substitute)
        )
        return out, out_len.astype(jnp.int32), w, emit

    # Per-segment output lengths and value rows for this variant.
    is_span = spat_w >= 0
    safe_slot = jnp.where(is_span, spat_w, 0)
    seg_digit = jnp.take_along_axis(digits, safe_slot, axis=1)
    seg_digit = jnp.where(is_span, seg_digit, 0)
    chosen = seg_digit > 0
    vstart = jnp.take_along_axis(pvs_w, safe_slot, axis=1)
    seg_jd = jnp.take_along_axis(jd, safe_slot, axis=1)
    opt_row = jnp.where(chosen, vstart + seg_jd, 0)
    seg_len = jnp.where(chosen, val_len[opt_row], olen_w)  # [N, G]

    seg_end = jnp.cumsum(seg_len, axis=1)  # inclusive ends [N, G]
    out_len = seg_end[:, -1]
    seg_start_out = seg_end - seg_len

    # Gather output bytes: for each out position j, locate its segment.
    j = jnp.arange(out_width, dtype=jnp.int32)[None, :]  # [1, W]
    seg_of_j = jnp.sum(
        (j[:, :, None] >= seg_end[:, None, :]).astype(jnp.int32), axis=2
    )  # [N, W] — first segment whose inclusive end exceeds j
    seg_of_j = jnp.clip(seg_of_j, 0, g - 1)

    take = lambda a: jnp.take_along_axis(a, seg_of_j, axis=1)  # noqa: E731
    rel = j - take(seg_start_out)
    rep = take(chosen.astype(jnp.int32)) > 0
    src_val_row = take(opt_row)
    src_orig = take(ostart_w) + rel

    vw = val_bytes.shape[1]
    from_val = val_bytes[src_val_row, jnp.clip(rel, 0, vw - 1)]
    lw = tokens.shape[1]
    from_word = jnp.take_along_axis(
        tokens_w, jnp.clip(src_orig, 0, lw - 1), axis=1
    )
    out = jnp.where(rep, from_val, from_word)
    out = jnp.where(j < out_len[:, None], out, jnp.uint8(0))

    emit = (
        lane_ok
        & (chosen_count >= min_substitute)
        & (chosen_count <= max_substitute)
    )
    return out, out_len.astype(jnp.int32), w, emit

"""Substitute-all (``-s``) expansion as index arithmetic — the flagship kernel.

The reference's transliteration engine (``processWordSubstituteAll``,
``main.go:308-365``) recursively assigns each unique pattern present in a word
one of its options *or skip*, then applies a ReplaceAll cascade at every leaf.
That keyspace is a product space: with patterns ``p_1..p_P`` present and
``r_i = options(p_i) + 1`` (the +1 is "skip"), every candidate is one digit
vector of the mixed-radix number ``Π r_i`` (SURVEY.md Q10). So instead of
recursion, the TPU enumerates **variant ids** and decodes them:

    variant id --mixed-radix decode--> digit vector
              --digit per pattern--> chosen option (0 = skip)
              --segment gather--> candidate bytes

The word is pre-split (host side, :func:`build_suball_plan`) into SEGMENTS —
alternating unclaimed gaps and pattern-occurrence spans. A variant's candidate
is the concatenation of each segment's bytes: the original slice for gaps and
un-chosen spans, the chosen option's value for chosen spans. Output offsets
are one prefix sum; bytes are two gathers. No recursion, no dynamic shapes.

Exactness ("fast path") conditions, checked per word at plan time:

* the table has no cascade hazard among the word's present patterns
  (``CompiledTable.cascade_hazard``) — otherwise the sorted-order ReplaceAll
  cascade could re-match inserted text;
* greedy leftmost occurrences of different patterns don't overlap — otherwise
  WHICH occurrences get replaced depends on the chosen subset, not the word;
* the table has no empty key (a ``=x`` line makes ReplaceAll insert between
  every character — oracle-only semantics).

Words failing these checks get ``fallback=True`` and are routed through the
byte-exact CPU oracle by the runtime; all six reference tables except the
bidirectional qwerty-azerty are fast-path for every word.

Work unit: a **block** ``(word, base_digits, count)`` covering a contiguous
range of the word's variant space. Blocks are how huge single-word spaces are
split across chips (SURVEY.md §5 "long-context") and how sweep cursors resume:
the host cuts arbitrary [cursor, cursor+n) ranges with bigint divmods, and the
device adds the in-block rank to ``base_digits`` with mixed-radix carries —
everything on device stays uint32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from ..tables.compile import CompiledTable
from .blocks import (  # noqa: F401  — re-exported: this module defined them first
    MAX_BLOCK,
    BlockBatch,
    digits_of,
    make_blocks,
)
from .expand_matches import (
    decode_digits,
    key_deltas,
    lane_fields,
    rounded_out_width,
    variant_totals,
    windowed_plan_fields,
)
from .packing import PackedWords


@dataclass(frozen=True)
class SubAllPlan:
    """Device-ready per-word expansion plan for substitute-all mode.

    Axes: B words, P pattern slots (slot order = sorted-pattern order, slot 0
    is the least-significant mixed-radix digit), G segments (in word order).
    """

    tokens: np.ndarray  # uint8 [B, L]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — wordlist ordinals (from PackedWords)
    pat_radix: np.ndarray  # int32 [B, P] — options+1, 1 on inactive slots
    pat_val_start: np.ndarray  # int32 [B, P] — CSR into table val rows
    seg_orig_start: np.ndarray  # int32 [B, G]
    seg_orig_len: np.ndarray  # int32 [B, G] — 0 on inactive segments
    seg_pat: np.ndarray  # int32 [B, G] — pattern slot, -1 for gaps
    n_variants: Tuple[int, ...]  # python bigints — Π radix per word, or the
    #                              windowed totals when ``windowed``
    fallback: np.ndarray  # bool [B] — word needs the CPU oracle
    out_width: int  # static candidate-buffer width (uint32-aligned)
    windowed: bool = False  # count-windowed enumeration active
    win_v: "np.ndarray | None" = None  # int32 [B, P+1, K+2] suffix counts
    #   (see expand_matches.MatchPlan.win_v — identical scheme over
    #   pattern slots)

    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def num_slots(self) -> int:
        return int(self.pat_radix.shape[1])

    @property
    def num_segments(self) -> int:
        return int(self.seg_orig_start.shape[1])


def _build_suball_plan_fast(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool,
    out_width: "int | None",
    min_substitute: "int | None",
    max_substitute: "int | None",
) -> "SubAllPlan | None":
    """Vectorized plan construction for every table WITHOUT an empty key
    (the ``=x`` line routes all words to the oracle — rare and cheap, so
    it keeps the scalar path).

    The scan vectorizes per key: single-byte keys are one byte-LUT lookup;
    multi-byte keys use shifted compares plus an O(L) greedy pass that
    reproduces ``bytes.find``'s non-overlapping occurrence walk. The
    scalar path's word-level fallback flag is equivalent to "some pair of
    occurrences overlaps": if no claim conflict fires, every key's
    occurrence loop completes, so claimed spans ARE the independent
    occurrence sets and are disjoint; conversely any overlap between
    independent occurrences is detected when the later-sorted key claims.
    Cross-pattern cascade hazards reduce to a presence×hazard matmul.

    For fallback words the scalar path records the PARTIAL spans claimed
    before the conflict; those segment fields are dead (the block cutter
    skips fallback words, the oracle re-derives their candidates), so this
    path stores the independent spans instead and only guarantees segment
    equality on non-fallback rows; pattern-slot fields ARE equal
    everywhere because both paths neutralize fallback rows to radix 1
    before the windowed decision (tests pin exactly this contract; width
    sizing also considers only non-fallback rows). The per-word Python
    loop this replaces took ~30 s for a 300k-word dictionary — longer
    than the whole device sweep.
    """
    if ct.has_empty_key or ct.num_keys == 0:
        return None
    tokens, lengths = packed.tokens, packed.lengths
    b, width = tokens.shape
    if b == 0 or width == 0:
        return None  # degenerate shapes: keep the scalar reference path
    j = np.arange(width)
    in_word = j[None, :] < lengths[:, None]
    k = ct.num_keys

    # Occurrence scan: per-position key index / span length, coverage
    # deltas for the overlap test, presence and span counts per word.
    occ_key = np.full((b, width), -1, dtype=np.int32)
    occ_len = np.zeros((b, width), dtype=np.int32)
    cover_delta = np.zeros((b, width + 1), dtype=np.int32)
    present = np.zeros((b, k), dtype=bool)
    span_count = np.zeros(b, dtype=np.int64)

    if ct.max_key_len >= 1:
        ki1 = np.where(in_word, ct.byte_to_key[tokens], -1)  # [B, L]
        m1 = ki1 >= 0
        occ_key = np.where(m1, ki1, occ_key)
        occ_len = np.where(m1, 1, occ_len)
        cover_delta[:, :width] += m1
        cover_delta[:, 1:] -= m1
        r1, c1 = np.nonzero(m1)
        present[r1, ki1[r1, c1]] = True
        span_count += m1.sum(axis=1)

    for kidx in np.nonzero((ct.key_len >= 2) & (ct.key_len <= width))[0]:
        klen = int(ct.key_len[kidx])
        key = ct.key_bytes[kidx]
        match = (j[None, :] + klen) <= lengths[:, None]
        for t in range(klen):
            match[:, : width - t] &= tokens[:, t:] == key[t]
            if t:
                match[:, width - t:] = False
        # Greedy non-overlapping same-key occurrences (bytes.find walk).
        sel = np.zeros((b, width), dtype=bool)
        next_free = np.zeros(b, dtype=np.int32)
        for jj in range(width - klen + 1):
            take = match[:, jj] & (jj >= next_free)
            sel[:, jj] = take
            next_free = np.where(take, jj + klen, next_free)
        occ_key = np.where(sel, np.int32(kidx), occ_key)
        occ_len = np.where(sel, np.int32(klen), occ_len)
        cover_delta[:, :width] += sel
        cover_delta[:, klen:] -= sel[:, : width + 1 - klen]
        present[:, kidx] |= sel.any(axis=1)
        span_count += sel.sum(axis=1)

    coverage = np.cumsum(cover_delta[:, :width], axis=1)  # [B, L]
    fallback_mask = (coverage > 1).any(axis=1)
    if ct.cascade_hazard.any():
        hz = ct.cascade_hazard.astype(np.int32)
        m = present.astype(np.int32) @ hz  # hazardous-predecessor counts
        fallback_mask |= ((m > 0) & present).any(axis=1)

    # Slots: the word's present keys in ascending order. Fallback rows
    # are neutralized below (radix 1) in BOTH paths, so dead rows never
    # influence the windowed-enumeration decision and pat_* fields agree
    # everywhere.
    num_p = max(1, int(present.sum(axis=1).max()))
    krank = np.cumsum(present, axis=1) - 1  # [B, K]
    vc = ct.val_count.astype(np.int64)
    options = np.minimum(1, vc) if first_option_only else vc
    key_radix = (options + 1).astype(np.int32)
    pat_radix = np.ones((b, num_p), dtype=np.int32)
    pat_val_start = np.zeros((b, num_p), dtype=np.int32)
    pw, pk = np.nonzero(present)
    slot_of = krank[pw, pk]
    pat_radix[pw, slot_of] = key_radix[pk]
    pat_val_start[pw, slot_of] = ct.val_start[pk]
    pat_radix[fallback_mask] = 1
    pat_val_start[fallback_mask] = 0

    # Segments: spans start where an occurrence starts; gaps start at
    # word-open or right after covered text. (Fallback rows may hold
    # overlapping spans — their fields are dead, see docstring.)
    covered = coverage > 0
    prev_covered = np.zeros_like(covered)
    prev_covered[:, 1:] = covered[:, :-1]
    span_start = occ_len > 0
    seg_start_mask = in_word & (
        span_start | (~covered & ((j[None, :] == 0) | prev_covered))
    )
    num_g = 2 * max(1, int(span_count.max())) + 1
    seg_rank = np.cumsum(seg_start_mask, axis=1) - 1
    srows, scols = np.nonzero(seg_start_mask)
    gidx = seg_rank[srows, scols]
    if len(gidx) and int(gidx.max()) >= num_g:
        num_g = int(gidx.max()) + 1  # safety: never truncate segments
    # Segment end = next segment's start in the same row, else word end
    # (for spans that equals start + key length on non-fallback rows).
    nxt = np.empty_like(scols)
    if len(scols):
        nxt[:-1] = scols[1:]
        nxt[-1] = 0
    same_row = np.zeros(len(srows), dtype=bool)
    if len(srows):
        same_row[:-1] = srows[1:] == srows[:-1]
    seg_end = np.where(same_row, nxt, lengths[srows])
    seg_orig_start = np.zeros((b, num_g), dtype=np.int32)
    seg_orig_len = np.zeros((b, num_g), dtype=np.int32)
    seg_pat = np.full((b, num_g), -1, dtype=np.int32)
    seg_orig_start[srows, gidx] = scols
    is_span = span_start[srows, scols]
    seg_orig_len[srows, gidx] = np.where(
        is_span, occ_len[srows, scols], (seg_end - scols).astype(np.int32)
    )
    s_ki = np.clip(occ_key[srows, scols], 0, k - 1)
    seg_pat[srows, gidx] = np.where(
        is_span, krank[srows, s_ki], -1
    ).astype(np.int32)

    # Output growth per occurrence (non-fallback rows size the buffer —
    # fallback words never reach the device).
    delta_per_key = key_deltas(ct, limit_first_option=False)
    orows, ocols = np.nonzero(occ_len > 0)
    word_delta = np.zeros(b, dtype=np.int64)
    np.add.at(word_delta, orows, delta_per_key[occ_key[orows, ocols]])
    word_delta[fallback_mask] = 0
    max_delta = int(word_delta.max())
    if out_width is None:
        out_width = rounded_out_width(width, max_delta)

    n_variants = variant_totals(pat_radix)
    for i in np.nonzero(fallback_mask)[0]:
        n_variants[int(i)] = 0

    windowed, win_v, n_variants = windowed_plan_fields(
        pat_radix, n_variants, min_substitute, max_substitute,
        zero_mask=fallback_mask,
    )
    return SubAllPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        pat_radix=pat_radix,
        pat_val_start=pat_val_start,
        seg_orig_start=seg_orig_start,
        seg_orig_len=seg_orig_len,
        seg_pat=seg_pat,
        n_variants=tuple(n_variants),
        fallback=fallback_mask,
        out_width=out_width,
        windowed=windowed,
        win_v=win_v,
    )


def build_suball_plan(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool = False,
    out_width: int | None = None,
    min_substitute: int | None = None,
    max_substitute: int | None = None,
) -> SubAllPlan:
    """Host-side plan construction (numpy + bytes.find; the C++ packer will
    take this over for the file-to-plan hot path).

    ``first_option_only=True`` builds the ``-s -r`` (substitute-all reverse)
    space: the reference enumerates every subset of present patterns with
    only ``subs[0]`` applied (Q2, ``main.go:393-398``), which is exactly this
    plan with every radix clamped to 2. Its per-word multiset equals the
    oracle's subset lattice (each subset emitted once, size windowed)."""
    fast = _build_suball_plan_fast(
        ct, packed, first_option_only=first_option_only,
        out_width=out_width, min_substitute=min_substitute,
        max_substitute=max_substitute,
    )
    if fast is not None:
        return fast
    b, width = packed.tokens.shape
    hazard = ct.cascade_hazard

    per_word: List[dict] = []
    max_p = 1
    max_s = 1
    for i in range(b):
        word = packed.word(i)
        slots: List[int] = []  # key indices, ascending = sorted patterns
        spans: List[Tuple[int, int, int]] = []  # (start, klen, slot)
        claimed = np.zeros(len(word), dtype=bool)
        fallback = ct.has_empty_key
        for ki, key in enumerate(ct.keys):
            if not key or fallback:
                continue
            pos = word.find(key)
            if pos < 0:
                continue
            slot = len(slots)
            slots.append(ki)
            while pos >= 0:
                end = pos + len(key)
                if claimed[pos:end].any():
                    fallback = True  # cross-pattern overlap: subset-dependent
                    break
                claimed[pos:end] = True
                spans.append((pos, len(key), slot))
                pos = word.find(key, end)
        if not fallback and len(slots) > 1:
            ks = np.asarray(slots)
            fallback = bool(hazard[np.ix_(ks, ks)].any())
        spans.sort()
        per_word.append({"slots": slots, "spans": spans, "fallback": fallback})
        max_p = max(max_p, len(slots))
        max_s = max(max_s, len(spans))

    num_p, num_g = max_p, 2 * max_s + 1
    pat_radix = np.ones((b, num_p), dtype=np.int32)
    pat_val_start = np.zeros((b, num_p), dtype=np.int32)
    seg_orig_start = np.zeros((b, num_g), dtype=np.int32)
    seg_orig_len = np.zeros((b, num_g), dtype=np.int32)
    seg_pat = np.full((b, num_g), -1, dtype=np.int32)
    n_variants: List[int] = []
    fallback_mask = np.zeros((b,), dtype=bool)
    max_delta = 0

    for i, info in enumerate(per_word):
        fallback_mask[i] = info["fallback"]
        total = 1
        for slot, ki in enumerate(info["slots"]):
            options = min(1, int(ct.val_count[ki])) if first_option_only else int(ct.val_count[ki])
            pat_radix[i, slot] = options + 1
            pat_val_start[i, slot] = ct.val_start[ki]
            total *= options + 1
        n_variants.append(total if not info["fallback"] else 0)

        # Segments: gap before each span, the span, and a final gap to len.
        g = 0
        cursor = 0
        delta = 0
        for start, klen, slot in info["spans"]:
            if start > cursor:
                seg_orig_start[i, g] = cursor
                seg_orig_len[i, g] = start - cursor
                g += 1
            seg_orig_start[i, g] = start
            seg_orig_len[i, g] = klen
            seg_pat[i, g] = slot
            g += 1
            cursor = start + klen
            ki = info["slots"][slot]
            vs, vc = int(ct.val_start[ki]), int(ct.val_count[ki])
            widest = max(
                (int(ct.val_len[vs + o]) for o in range(vc)), default=klen
            )
            delta += max(0, widest - klen)
        word_len = int(packed.lengths[i])
        if cursor < word_len:
            seg_orig_start[i, g] = cursor
            seg_orig_len[i, g] = word_len - cursor
            g += 1
        max_delta = max(max_delta, delta)

    if out_width is None:
        out_width = max(4, -(-(width + max_delta) // 4) * 4)

    # Neutralize fallback rows (mirrored in the fast path): their slots
    # are dead — the oracle re-derives those words — and must not sway
    # the global windowed-enumeration decision below.
    pat_radix[fallback_mask] = 1
    pat_val_start[fallback_mask] = 0

    # Count-windowed enumeration for tight -m/-x windows (same DP scheme
    # as match plans — the suball count is "distinct patterns chosen",
    # which is exactly "digits > 0 over slots with options"). Fallback
    # words keep the oracle route: totals forced to 0, matching the
    # full-enumeration convention above.
    windowed, win_v, n_variants = windowed_plan_fields(
        pat_radix, n_variants, min_substitute, max_substitute,
        zero_mask=fallback_mask,
    )

    return SubAllPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        pat_radix=pat_radix,
        pat_val_start=pat_val_start,
        seg_orig_start=seg_orig_start,
        seg_orig_len=seg_orig_len,
        seg_pat=seg_pat,
        n_variants=tuple(n_variants),
        fallback=fallback_mask,
        out_width=out_width,
        windowed=windowed,
        win_v=win_v,
    )


def expand_suball(
    tokens: jnp.ndarray,  # uint8 [B, L]
    lengths: jnp.ndarray,  # int32 [B]
    pat_radix: jnp.ndarray,  # int32 [B, P]
    pat_val_start: jnp.ndarray,  # int32 [B, P]
    seg_orig_start: jnp.ndarray,  # int32 [B, G]
    seg_orig_len: jnp.ndarray,  # int32 [B, G]
    seg_pat: jnp.ndarray,  # int32 [B, G]
    val_bytes: jnp.ndarray,  # uint8 [V, val_width] — compiled table values
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, P]
    blk_count: jnp.ndarray,  # int32 [NB]
    blk_offset: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
    block_stride: int | None = None,
    win_v: jnp.ndarray | None = None,
    radix2: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode + materialize ``num_lanes`` variants.

    Returns ``(cand uint8[N, out_width], cand_len int32[N], word_row int32[N],
    emit bool[N])`` — ``emit`` folds together lane validity (rank in range)
    and the min/max chosen-pattern-count window.

    ``block_stride``: fixed-stride batch layout — constant-divide lane ->
    block plus per-block broadcasts instead of per-lane searchsorted +
    gathers (see ``expand_matches.expand_matches``). ``win_v``: windowed
    plans unrank only in-window digit vectors (``expand_matches.
    decode_digits``; block bases are scalar ranks).
    """
    n = num_lanes
    p = pat_radix.shape[1]
    g = seg_orig_start.shape[1]

    rank, lane_ok, w, base, field = lane_fields(
        blk_word, blk_base, blk_count, blk_offset,
        num_lanes=n, block_stride=block_stride,
    )
    radix = field(pat_radix)  # [N, P]
    spat_w = field(seg_pat)  # [N, G]
    pvs_w = field(pat_val_start)  # [N, P]
    olen_w = field(seg_orig_len)  # [N, G]
    ostart_w = field(seg_orig_start)  # [N, G]
    tokens_w = field(tokens)  # [N, L]

    digits = decode_digits(
        rank, base, radix, field, win_v, p, max_rank=block_stride or n,
        radix2=radix2,
    )  # [N, P]

    active = radix > 1
    chosen_count = jnp.sum((digits > 0) & active, axis=1)

    # Per-segment output lengths and value rows for this variant.
    is_span = spat_w >= 0
    seg_digit = jnp.take_along_axis(
        digits, jnp.where(is_span, spat_w, 0), axis=1
    )
    seg_digit = jnp.where(is_span, seg_digit, 0)
    chosen = seg_digit > 0
    vstart = jnp.take_along_axis(
        pvs_w, jnp.where(is_span, spat_w, 0), axis=1
    )
    opt_row = jnp.where(chosen, vstart + seg_digit - 1, 0)
    seg_len = jnp.where(chosen, val_len[opt_row], olen_w)  # [N, G]

    seg_end = jnp.cumsum(seg_len, axis=1)  # inclusive ends [N, G]
    out_len = seg_end[:, -1]
    seg_start_out = seg_end - seg_len

    # Gather output bytes: for each out position j, locate its segment.
    j = jnp.arange(out_width, dtype=jnp.int32)[None, :]  # [1, W]
    seg_of_j = jnp.sum(
        (j[:, :, None] >= seg_end[:, None, :]).astype(jnp.int32), axis=2
    )  # [N, W] — first segment whose inclusive end exceeds j
    seg_of_j = jnp.clip(seg_of_j, 0, g - 1)

    take = lambda a: jnp.take_along_axis(a, seg_of_j, axis=1)  # noqa: E731
    rel = j - take(seg_start_out)
    rep = take(chosen.astype(jnp.int32)) > 0
    src_val_row = take(opt_row)
    src_orig = take(ostart_w) + rel

    vw = val_bytes.shape[1]
    from_val = val_bytes[src_val_row, jnp.clip(rel, 0, vw - 1)]
    lw = tokens.shape[1]
    from_word = jnp.take_along_axis(
        tokens_w, jnp.clip(src_orig, 0, lw - 1), axis=1
    )
    out = jnp.where(rep, from_val, from_word)
    out = jnp.where(j < out_len[:, None], out, jnp.uint8(0))

    emit = (
        lane_ok
        & (chosen_count >= min_substitute)
        & (chosen_count <= max_substitute)
    )
    return out, out_len.astype(jnp.int32), w, emit

"""Pallas TPU kernels fusing mixed-radix decode + splice + hash per block.

Why (PERF.md §3/§4): with the f32 decode and chunked fetches landed, the
fused XLA step still spends its device time on `[N, 1]`-shaped decode/splice
fusions tiled ``T(1, 128)`` — one of eight VPU sublanes busy — plus ~5 ms of
materialized block-field broadcasts per 2^22-lane launch. This kernel walks
the same math on ``(G, S)`` tiles (G = 8 blocks per grid step, S = lanes
per block), with every block field loaded once into VMEM per step and the
MD5 message built directly in 16 uint32 words — candidate bytes never exist
in HBM at all.

Scope (``eligible``): all four generation modes — match plans
(default/reverse, ``main.go:168-261`` semantics via ``ops.expand_matches``'s
non-overlapping-match formulation) and substitute-all plans (``-s``/
``-s -r``, ``main.go:308-440`` via ``ops.expand_suball``'s segment
formulation) — every shipped hash (MD5/MD4/SHA-1/NTLM; up to three
chained hash blocks, i.e. candidates to 183 bytes — 91 for NTLM whose
UTF-16LE expansion doubles bytes — with each lane's digest selected
after its own padding block),
fixed-stride layout with stride a multiple of 128, full-enumeration AND
count-windowed plans (the in-kernel suffix-count DP walk,
``_decode_tile_windowed``),
table values <= 4 bytes (packed into one u32 per option). Everything else
keeps the XLA path; the wrapper never silently changes semantics —
ineligible configurations must not call it
(``models.attack.make_fused_body`` gates on ``eligible``).

Parity contract: for every EMITTED lane the digest equals the XLA
expand + ``ops.hashes.HASH_FNS[algo]`` path bit-for-bit, and the emit mask
itself is identical (interpret-mode suite: tests/test_pallas_expand.py).
Non-emitted lanes may hold garbage state — overlap-clash lanes build a
nonsense message by construction in both paths, and both mask them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..audit import audited_entry
from ..runtime.env import env_is, env_warn_once, read_env
from .hashes import (
    _MD4_G,
    _MD4_H,
    _MD4_INIT,
    _MD5_INIT,
    _MD5_K,
    _MD5_S,
    _SHA1_INIT,
    _SHA1_K,
    DIGEST_WORDS,
)

_U32 = jnp.uint32
_I32 = jnp.int32

#: Blocks per grid step: (G, S) tiles fill all 8 VPU sublanes at S >= 128.
#: ``A5GEN_PALLAS_G`` overrides (e.g. 16/32) for on-chip geometry probes —
#: larger G amortizes per-step block-field loads over more lanes at the
#: cost of VMEM; read once at import, consulted at kernel-build time.
#: Malformed or non-positive values warn and keep the default (same
#: convention as ``enabled_by_env``: a typo must not break — or silently
#: reshape — the fast path).


def _grid_height_from_env() -> int:
    raw = read_env("A5GEN_PALLAS_G")
    if raw is None or raw == "":
        return 8
    try:
        g = int(raw)
        if g <= 0:
            raise ValueError("must be positive")
    except ValueError:
        env_warn_once(
            "A5GEN_PALLAS_G", raw,
            f"invalid A5GEN_PALLAS_G={raw!r} "
            "(want a positive integer); using 8",
        )
        return 8
    return g


_G = _grid_height_from_env()

#: Soft caps keeping the fully-unrolled kernel's compile time bounded.
#: _MAX_OPTIONS bounds the K-way value-select width, which for
#: cascade-closed suball plans spans JOINT closure tables
#: (expand_suball.MAX_CLOSE_OPTS=12: qwerty-azerty's widest common hazard
#: sets reach 12 rows); plain plans stay capped at the historical
#: _MAX_RAW_OPTIONS per-key bound (opts_for_config) so the widening never
#: grows non-closed kernels.
_MAX_SLOTS = 24
_MAX_TOKENS = 64
_MAX_OPTIONS = 12
_MAX_RAW_OPTIONS = 8
_MAX_SEGMENTS = 64  # suball kernel only (match kernels pass 0)
#: Windowed plans: suffix-count DP column bound (window <= 8 per the
#: plan-side eligibility, +2 DP columns).
_MAX_WIN_K2 = 10


def eligible(
    *,
    mode: str,
    algo: str,
    windowed: bool,
    block_stride: "int | None",
    num_blocks: int,
    out_width: int,
    num_slots: int,
    token_width: int,
    max_val_len: int,
    max_options: int,
    num_segments: int = 0,
    win_k2: int = 0,
) -> bool:
    """Static eligibility for the fused expand+MD5 kernel (see module doc).

    Callers own plan/table knowledge (``runtime.sweep``, ``bench.py``): all
    arguments are host-static facts about the launch configuration.
    ``win_k2``: the windowed plan's DP column count (``win_v.shape[2]``,
    0 when not windowed) — the in-kernel suffix-count walk handles
    count-windowed plans directly.
    """
    return (
        mode in ("default", "reverse", "suball", "suball-reverse")
        and algo in ("md5", "md4", "sha1", "ntlm")
        and (not windowed or 2 <= win_k2 <= _MAX_WIN_K2)
        and block_stride is not None
        and block_stride % 128 == 0
        # In-kernel ranks run up to the stride; the f32 divide in
        # _exact_div is only exact below 2^24 (expand_matches mirrors
        # this bound as _F32_DECODE_MAX_RANK).
        and block_stride <= (1 << 24)
        and num_blocks % _G == 0
        and num_blocks > 0
        # Up to _MAX_HASH_BLOCKS chained hash blocks: the longest
        # candidate (doubled under NTLM's UTF-16LE expansion) plus
        # terminator and length must fit 64 * n bytes.
        and 0 < out_width
        and (out_width * (2 if algo == "ntlm" else 1) + 9
             <= 64 * _MAX_HASH_BLOCKS)
        and 1 <= num_slots <= _MAX_SLOTS
        and 1 <= token_width <= _MAX_TOKENS
        and 1 <= max_val_len <= 4
        and 1 <= max_options <= _MAX_OPTIONS
        # Since the per-position segment resolution moved to an XLA
        # precompute, the kernel's cost no longer scales with segment
        # count; the cap now only bounds the [NB, GS, L] precompute.
        and num_segments <= _MAX_SEGMENTS
    )


def k_opts_for(plan) -> int:
    """Static per-key option count K (Python int scalar) — the DECODE's
    radix bound, from the plan's ``pat_radix`` int32 ``[B, P]`` slot-radix
    matrix. Works for match AND substitute-all plans. Single source shared
    by production gating (:func:`opts_for`), the parity tests, and the A/B
    probe, so they can never drift apart."""
    return max(1, int(plan.pat_radix.max()) - 1)


def k_vals_for(plan) -> int:
    """Static VALUE-SELECT width (Python int scalar), from the plan's
    int32 ``[B, P]`` slot-radix matrix widened to the joint closure
    tables of a cascade-closed suball plan (``SubAllPlan.close_opts`` —
    a closed slot's value row is addressed by its own AND its
    successors' digits, so the K-way select must span the joint table).
    Equals :func:`k_opts_for` for every non-closed plan."""
    return max(k_opts_for(plan), int(getattr(plan, "close_opts", 0) or 0))


def enabled_by_env() -> bool:
    """The fused expansion kernel is ON by default on TPU; ``A5GEN_PALLAS``
    set to ``off``/``0``/``xla``/``none`` opts out (``expand`` still force-
    opts in, for symmetry with the hash-only kernel's ``A5GEN_PALLAS=1`` —
    which selects *that* kernel and therefore also opts this one out).
    Unrecognized values warn and keep the default — a typo must not
    silently disable the fast path."""
    val = read_env("A5GEN_PALLAS")
    if val is None or val == "":
        return True
    if val == "expand":
        return True
    if val in ("off", "0", "xla", "none", "1"):
        return False
    env_warn_once(
        "A5GEN_PALLAS", val,
        f"unrecognized A5GEN_PALLAS={val!r} "
        "(want expand|off|0|xla|none|1); keeping the default "
        "(fused kernel on for eligible TPU configs)",
    )
    return True


def _interpret_by_env() -> bool:
    """``A5GEN_PALLAS_INTERPRET=1`` forces interpret-mode pallas_call in
    the production wrappers.  Test/debug hook: it lets the full sweep
    runtime drive the REAL kernel path (gates, precomputes, launch
    plumbing) on the CPU backend, where compiled pallas is unavailable —
    the e2e wiring test uses it so a threading bug cannot hide until a
    TPU run."""
    return env_is("A5GEN_PALLAS_INTERPRET", "1")


def _on_tpu() -> bool:
    """Device platform, not backend name: the remote tunnel fronts "tpu"
    devices behind a differently-named backend (see ops.pallas_md5)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - backend-dependent
        return False


def opts_for_config(spec, plan, ct, *, block_stride, num_blocks,
                    require_tpu: bool = True) -> "int | None":
    """Pure eligibility gate (no env check): returns the static option
    count K for ``make_fused_body(fused_expand_opts=)`` when the launch
    configuration is eligible, else None.  ``spec``/``plan``/``ct`` are the
    attack spec, host plan (match or substitute-all — the body routes by
    mode), and compiled table.  ``require_tpu=False`` skips the device
    probe (interpret-mode tests, A/B probes that pin the platform)."""
    if require_tpu and not _on_tpu():
        return None
    # Value-select width: joint closure tables widen K past the raw
    # per-key option count, and the closed rows live in the plan's own
    # value table (whose width bounds the u32 packing). The RAW per-key
    # count keeps its historical cap — the wider _MAX_OPTIONS admits only
    # the closure tables, never bigger plain kernels.
    if k_opts_for(plan) > _MAX_RAW_OPTIONS:
        return None
    max_options = k_vals_for(plan)
    cval = getattr(plan, "cval_bytes", None)
    max_val_len = int(ct.max_val_len if cval is None else cval.shape[1])
    ok = eligible(
        mode=spec.mode,
        algo=spec.algo,
        windowed=bool(getattr(plan, "windowed", False)),
        block_stride=block_stride,
        num_blocks=int(num_blocks),
        out_width=int(plan.out_width),
        num_slots=int(plan.num_slots),
        token_width=int(plan.tokens.shape[1]),
        max_val_len=max_val_len,
        max_options=max_options,
        num_segments=int(getattr(plan, "num_segments", 0)),
        win_k2=(int(plan.win_v.shape[2])
                if getattr(plan, "win_v", None) is not None else 0),
    )
    return max_options if ok else None


def opts_for(spec, plan, ct, *, block_stride, num_blocks) -> "int | None":
    """Production gate: :func:`opts_for_config` under the env opt-out
    (:func:`enabled_by_env`).  Returns the static option count K (int
    scalar) when the fused kernel should run, None otherwise.
    Default-on on TPU devices; the XLA expand+hash pair remains for
    ineligible configs and non-TPU backends."""
    if not enabled_by_env():
        return None
    if env_is("A5GEN_PALLAS", "expand") and not _on_tpu():
        # An EXPLICIT opt-in deserves a diagnostic when it can't be
        # honored; the default-on (env unset) case falls back silently.
        # Once per process, not per launch — opts_for runs per job.
        env_warn_once(
            "A5GEN_PALLAS", "expand",
            "A5GEN_PALLAS=expand but no TPU device; "
            "using the XLA expand+hash path",
        )
        return None
    return opts_for_config(
        spec, plan, ct, block_stride=block_stride, num_blocks=num_blocks
    )


def pair_for_config(spec, plan, pieces, *,
                    block_stride: "int | None") -> "int | None":
    """Pure pair-lane eligibility (PERF.md §24; no env check): returns
    the static candidates-per-lane K (a Python int scalar, 2) when
    this launch configuration can take the pair tier, else None.

    Wrapper-level half of the gate (the schema-level half lives in
    ``packing.build_piece_schema``'s ``pair_ok``): a pair-eligible
    per-slot schema, full enumeration (the windowed DP walks a
    different rank order, so consecutive ranks do not share a
    decompose), a single hash block (the whole point is amortizing the
    one compression's message build — multi-block lanes have no idle
    schedule words to elide), a fixed-stride layout whose DOUBLED
    in-block candidate ranks stay inside the exact-f32-divide range,
    and no cascade closure (``pair_ok`` already excludes it).
    """
    if pieces is None or not getattr(pieces, "pair_ok", False):
        return None
    if getattr(plan, "windowed", False):
        return None
    if getattr(plan, "close_next", None) is not None:
        return None
    if block_stride is None or 2 * block_stride > (1 << 24):
        return None
    scale = 2 if spec.algo == "ntlm" else 1
    if _hash_blocks_for(int(plan.out_width), scale) != 1:
        return None
    return 2


def pair_for(spec, plan, pieces, *,
             block_stride: "int | None") -> "int | None":
    """Production pair-lane gate: :func:`pair_for_config` under the
    ``A5GEN_PAIR`` escape hatch.  Returns the static candidates-per-
    lane count (a Python int scalar, 2) when the pair tier should run,
    None otherwise."""
    from ..runtime.env import pair_enabled

    if not pair_enabled():
        return None
    return pair_for_config(spec, plan, pieces, block_stride=block_stride)


def _exact_div(r, rs):
    """Floor ``r // rs`` via f32 divide + ±1 fixup (exact for |r| < 2^24;
    in-kernel ranks are < the block stride). Mirrors
    ``expand_matches._exact_div`` — the VPU has no native s32 divide."""
    q = jnp.floor(
        r.astype(jnp.float32) / rs.astype(jnp.float32)
    ).astype(_I32)
    q = q - (q * rs > r).astype(_I32)
    q = q + ((q + 1) * rs <= r).astype(_I32)
    return q


def _decode_tile_windowed(rank, base, winv, radix, m, g, s, k_opts):
    """Count-windowed digit decode on a (G, S) tile: the scalar windowed
    rank ``base[:, 0] + rank`` walks only in-window digit vectors through
    the suffix-count DP rows ``winv[G, M+1, K2]`` (mirrors
    ``expand_matches.decode_digits``'s windowed branch bit-for-bit).

    Division-free: the per-slot quotient ``d - 1 = r2 // safe`` is at most
    ``radix - 2 <= K - 1`` for every in-window lane, so a K-1-step
    subtractive chain computes quotient and remainder exactly — and never
    overflows i32, unlike ``i * safe`` compare ladders (windowed totals
    run to 2^30).  Out-of-range lanes decode garbage and are clipped;
    emit masks them (same contract as the XLA path)."""
    k2 = int(winv.shape[2])
    big_r = base[:, 0][:, None] + rank
    jcnt = jnp.zeros((g, s), _I32)
    digits = []
    for sl in range(m):
        # jcnt increments at most once per slot, so at slot sl only
        # columns 0..sl are reachable — bounding the unrolled selects
        # there drops the statically dead compare+where pairs.
        kc = min(sl + 1, k2)
        rows = [winv[:, sl + 1, c][:, None] for c in range(min(kc + 1, k2))]
        masks = [jcnt == c for c in range(kc)]
        vn0 = jnp.zeros((g, s), _I32)
        vn1 = jnp.zeros((g, s), _I32)
        for c in range(kc):
            vn0 = vn0 + jnp.where(masks[c], rows[c], 0)
            if c + 1 < k2:
                vn1 = vn1 + jnp.where(masks[c], rows[c + 1], 0)
        not_chosen = big_r < vn0
        r2 = big_r - vn0
        safe = jnp.maximum(vn1, 1)
        # Chosen digits run 1..radix-1 <= k_opts, so the quotient needs at
        # most k_opts-1 subtractive steps (zero for K=1 tables: d is 1).
        q = jnp.zeros((g, s), _I32)
        rr = r2
        for _ in range(max(0, k_opts - 1)):
            ge = (rr >= safe).astype(_I32)
            rr = rr - ge * safe
            q = q + ge
        d = jnp.where(not_chosen, 0, 1 + q)
        big_r = jnp.where(not_chosen, big_r, rr)
        digits.append(jnp.clip(d, 0, radix[:, sl][:, None] - 1))
        jcnt = jcnt + jnp.where(not_chosen, 0, 1)
    return digits


def _decode_tile_radix2(rank, base, radix, m, g, s):
    """Mixed-radix decode specialized to radices <= 2 (K=1 tables — every
    shipped 1:1 layout map): active slots' digits are successive BITS of
    the rank, so the f32 divide chain collapses to shift/mask + a binary
    carry (PERF.md §7 lever 2).  Exactly equivalent to
    :func:`_decode_tile` for radix-1/2 slots (radix-1 slots emit digit 0
    and pass the carry through, matching the general ge-fixup)."""
    digits = []
    carry = jnp.zeros((g, s), _I32)
    nbits = jnp.zeros((g, 1), _I32)
    for sl in range(m):
        active_b = radix[:, sl][:, None] > 1
        active = active_b.astype(_I32)
        bit = (rank >> nbits) & 1
        t = base[:, sl][:, None] + bit * active + carry
        digits.append(jnp.where(active_b, t & 1, 0))
        carry = jnp.where(active_b, t >> 1, carry)
        nbits = nbits + active
    return digits


def scalar_units_for(plan) -> "bool | str":
    """Host gate for the K=1 *scalar-units* fast path (PERF.md §11).

    K=1 plans (every shipped 1:1 layout map) have all radices <= 2, so a
    lane's chosen-slot vector is exactly the binary digits of
    ``packed_base + rank`` — and the per-byte unit resolution becomes bit
    tests against block-uniform precomputes.  Match plans additionally
    need at most one match START per byte position (mixed key lengths can
    collide there — ``find_matches`` appends one match per matching
    length); the packed start encode holds a single slot per position.
    Substitute-all plans qualify unconditionally: segments are disjoint
    by construction.  Count-windowed plans qualify too: the decode stays
    the suffix-count DP walk, but its chosen bits pack into the same
    vector and the bitmask unit scheme applies unchanged.

    Returns ``"single"`` when additionally every active match span is one
    byte (all shipped 1:1 layout maps): overlaps are then impossible and
    the kernel drops its coverage bitmask entirely.  Both truthy values
    thread through ``fused_scalar_units`` unchanged."""
    if k_opts_for(plan) != 1:
        return False
    if getattr(plan, "close_next", None) is not None:
        # Cascade-closed plans: a span's VALUE depends on other slots'
        # digits (the joint closure index), so the block-uniform per-byte
        # value fields the scalar kernel relies on don't exist. The
        # general kernel carries closed plans. (Gate on the FIELD, like
        # the wrapper's raise — never on a derived count.)
        return False
    mp = getattr(plan, "match_pos", None)
    if mp is None:
        return True
    return _scalar_units_tier(mp, plan.match_len, plan.match_radix)


def _scalar_units_tier(
    match_pos: np.ndarray,
    match_len: np.ndarray,
    match_radix: np.ndarray,
) -> "bool | str":
    """The unique-start verdict from concrete match arrays.

    Shared by the host gate (:func:`scalar_units_for`) and the wrapper's
    re-validation (:func:`_check_scalar_units_gate`) so the two can never
    drift apart.  ``match_pos/match_len/match_radix`` are ``[B, M]``
    int arrays (host numpy or concrete device values)."""
    mp = np.asarray(match_pos)
    act = np.asarray(match_radix) > 1
    if not np.where(act, np.asarray(match_len) > 1, False).any():
        # Single-byte spans: at most one key can match at a position, so
        # start uniqueness is automatic.
        return "single"
    m = mp.shape[1]
    # Inactive (padding) slots sit at distinct negative positions so they
    # can never collide with real starts or each other.
    pos = np.where(act, mp, -1 - np.arange(m, dtype=mp.dtype)[None, :])
    srt = np.sort(pos, axis=1)
    return not bool((srt[:, 1:] == srt[:, :-1]).any())


def _check_scalar_units_gate(
    scalar_units: "bool | str",
    match_pos: "jnp.ndarray",
    match_len: "jnp.ndarray",
    match_radix: "jnp.ndarray",
) -> None:
    """Re-validate a caller-passed ``scalar_units`` verdict host-side.

    The K=1 fast kernel packs one match START per byte position; a truthy
    ``scalar_units`` for a plan with colliding starts silently corrupts
    the packed startp encode (production always gates via
    :func:`scalar_units_for`, but the wrapper must not trust a bypassed
    gate).  Runs only when the match arrays are concrete — inside a trace
    (tracer arguments) the host plan is unavailable and the caller's
    verdict necessarily stands."""
    if any(
        isinstance(a, jax.core.Tracer)
        for a in (match_pos, match_len, match_radix)
    ):
        return
    tier = _scalar_units_tier(match_pos, match_len, match_radix)
    if not tier:
        raise ValueError(
            "scalar_units was passed truthy but the plan has colliding "
            "match starts (scalar_units_for(plan) is False); the K=1 "
            "fast kernel would corrupt the packed start encode. Gate "
            "via scalar_units_for(plan)."
        )
    if scalar_units == "single" and tier != "single":
        raise ValueError(
            'scalar_units="single" was passed but the plan has active '
            "multi-byte match spans (scalar_units_for(plan) returns "
            'True, not "single"); the single-span kernel drops its '
            "coverage bitmask and would mis-splice overlapping spans. "
            "Gate via scalar_units_for(plan)."
        )


def scalar_units_fields(plan, ct, *, _row_chunk=None) -> "dict | None":
    """Word-level numpy precomputes for the scalar-units fast path.

    The per-byte coverage / start / value fields the wrappers need are
    WORD-level facts (geometry and K=1 values don't depend on the block),
    yet the in-XLA precompute rebuilt them from block-gathered arrays on
    every launch — [NB, M, L] reductions costing a measurable slice of
    launch wall (PERF.md §12).  Computing them here once per sweep turns
    the per-launch prep into pure row gathers.

    Returns ``{"weight", "bitpos" [B, M|P] i32, "startp"|"ownbit",
    "svl" [B, L] u8, "svw" [B, L] u32, +"ins_bits" [B, L] i32 (match
    bitmask tier), +"isstart" [B, L] u8 (suball)}`` as numpy arrays, or
    None when the plan doesn't qualify.  Per-byte fields are u8 where
    they fit (hashmob-scale dictionaries: millions of words x L bytes),
    the wrappers widen after the block gather; the [chunk, M|GS, L]
    intermediates are computed in bounded row chunks for the same
    reason.  Cached on the plan object (plans are frozen; keyed by the
    table identity)."""
    tier = scalar_units_for(plan)
    if not tier:
        return None
    cache = getattr(plan, "_scalar_fields_cache", None)
    if cache is not None and cache[0] is ct and _row_chunk is None:
        return cache[1]
    radix = np.asarray(plan.pat_radix)
    act = (radix > 1).astype(np.int32)
    bitpos = np.cumsum(act, axis=1) - act
    weight = (act << bitpos).astype(np.int32)
    tokens = np.asarray(plan.tokens)
    b, length_axis = tokens.shape
    val_bytes = np.asarray(ct.val_bytes)
    val_len = np.asarray(ct.val_len)
    vw_packed = np.zeros(val_bytes.shape[0], np.uint32)
    for k in range(val_bytes.shape[1]):
        vw_packed |= val_bytes[:, k].astype(np.uint32) << np.uint32(8 * k)
    jj = np.arange(length_axis, dtype=np.int32)[None, None, :]
    is_match = getattr(plan, "match_pos", None) is not None
    out = {"weight": weight, "bitpos": bitpos}
    bl = (b, length_axis)
    if is_match:
        out["startp"] = np.empty(bl, np.uint8)
        out["svl"] = np.empty(bl, np.uint8)
        out["svw"] = np.empty(bl, np.uint32)
        if tier != "single":
            out["ins_bits"] = np.empty(bl, np.int32)
        vs = np.asarray(plan.match_val_start)
        rows = np.clip(vs, 0, val_bytes.shape[0] - 1)
        vw_slot = vw_packed[rows]  # [B, M] (K=1: option 0)
        vl_slot = val_len[rows].astype(np.int32)
        mpos = np.asarray(plan.match_pos)
        mlen = np.asarray(plan.match_len)
        chunk = _row_chunk or max(
            1, (64 << 20) // max(1, mpos.shape[1] * length_axis))
    else:
        out["ownbit"] = np.empty(bl, np.uint8)
        out["isstart"] = np.empty(bl, np.uint8)
        out["svl"] = np.empty(bl, np.uint8)
        out["svw"] = np.empty(bl, np.uint32)
        st = np.asarray(plan.seg_orig_start)
        sl = np.asarray(plan.seg_orig_len)
        sp = np.asarray(plan.seg_pat)
        vs = np.asarray(plan.pat_val_start)
        rows = np.clip(vs, 0, val_bytes.shape[0] - 1)
        vw_slot = vw_packed[rows]
        vl_slot = val_len[rows].astype(np.int32)
        chunk = _row_chunk or max(
            1, (64 << 20) // max(1, sp.shape[1] * length_axis))
    for lo in range(0, b, chunk):
        hi = min(lo + chunk, b)
        r = slice(lo, hi)
        if is_match:
            stt = ((jj == mpos[r, :, None])
                   & (act[r, :, None] > 0))  # [C, M, L], <=1 slot per j
            startp = (stt * (bitpos[r, :, None] + 1)).sum(1)
            out["startp"][r] = np.where(startp == 0, 31, startp - 1)
            out["svl"][r] = (stt * vl_slot[r, :, None]).sum(1)
            out["svw"][r] = (stt.astype(np.uint32)
                             * vw_slot[r, :, None]).sum(1, dtype=np.uint32)
            if tier != "single":
                ps = mpos[r, :, None]
                inside = (jj >= ps) & (jj < ps + mlen[r, :, None])
                out["ins_bits"][r] = (inside * weight[r, :, None]).sum(1)
        else:
            if sp.shape[1]:
                st3 = st[r, :, None]
                covered = (sl[r, :, None] > 0) & (jj >= st3) & (
                    jj < st3 + sl[r, :, None])  # [C, GS, L]
                slotat = np.where(covered, sp[r, :, None], -1).max(axis=1)
                startat = np.where(covered, st3, 0).max(axis=1)
            else:
                slotat = np.full((hi - lo, length_axis), -1, np.int32)
                startat = np.zeros((hi - lo, length_axis), np.int32)
            owned = slotat >= 0
            sl_clip = np.clip(slotat, 0, radix.shape[1] - 1)
            rows_i = np.arange(lo, hi)[:, None]
            own_act = act[rows_i, sl_clip] > 0
            out["ownbit"][r] = np.where(
                owned & own_act, bitpos[rows_i, sl_clip], 31)
            out["isstart"][r] = (
                owned & (startat == np.arange(length_axis)[None, :]))
            out["svl"][r] = np.where(owned, vl_slot[rows_i, sl_clip], 0)
            out["svw"][r] = np.where(owned, vw_slot[rows_i, sl_clip],
                                     np.uint32(0))
    if _row_chunk is None:
        object.__setattr__(plan, "_scalar_fields_cache", (ct, out))
    return out


def _popcount_tile(cb):
    """SWAR popcount of a nonnegative i32 tile (values < 2^26 here:
    packed chosen-slot vectors over <= 24 active slots plus block carry)."""
    u = cb.astype(_U32)
    u = u - ((u >> 1) & _U32(0x55555555))
    u = (u & _U32(0x33333333)) + ((u >> 2) & _U32(0x33333333))
    u = (u + (u >> 4)) & _U32(0x0F0F0F0F)
    u = u + (u >> 8)
    u = (u + (u >> 16)) & _U32(0x3F)
    return u.astype(_I32)


def _make_scalar_kernel(
    *, g: int, s: int, kind: str, length_axis: int, out_width: int,
    min_substitute: int, max_substitute: int, algo: str = "md5",
    max_val_len: int = 4, single_span: bool = False,
    windowed: bool = False, num_slots: "int | None" = None,
):
    """K=1 scalar-units kernel body (PERF.md §11), shared by match and
    substitute-all plans.

    The chosen-slot vector IS ``pbase + rank`` (one add — no mixed-radix
    decode loop), the substitution count is its popcount, and the per-byte
    unit loop runs on block-uniform precomputes: per (block, byte j)
    ``a_j``/``b_j`` resolve coverage and starts (match: ``ins_bits`` with
    one bit per active slot + the starting slot's bit position, sentinel
    31; suball: the owning pattern slot's bit position, sentinel 31, + a
    span-start 0/1), and ``svl``/``svw`` carry the (single) value's
    length/packed word.  Sentinel 31 is safe: chosen vectors stay below
    2^26 (<= 24 active slots + the in-block rank carry), so bit 31 is 0.

    Ref shapes per grid step (all VMEM):
      tok[G, L] i32, wlen[G, 1] i32, count[G, 1] i32, pbase[G, 1] i32,
      a_j[G, L] i32, b_j[G, L] i32, svl[G, L] i32, svw[G, L] u32.
    Outputs: state[G, KS, S] u32, emit[G, S] i32 — identical contract to
    :func:`_make_kernel`.

    ``single_span`` (match only, host-gated: every active match span is
    one byte — all shipped layout maps): coverage equals start, overlaps
    are impossible, so the ``a_j`` coverage-bitmask ref is DROPPED (the
    kernel takes 7 refs) and the clash test vanishes.

    ``windowed`` (count-windowed plans): the decode stays the in-kernel
    suffix-count DP walk (``_decode_tile_windowed``, K=1 quotient path),
    but the chosen bits it yields pack into the same ``cb`` vector (one
    shift-OR per slot via a ``bitpos[G, M]`` ref), so the whole bitmask
    unit scheme above applies unchanged. The ``pbase`` ref is then the
    raw base tile (scalar windowed ranks in slot 0) and three refs are
    added: ``winv[G, M+1, K2]``, ``radix[G, M]``, ``bitpos[G, M]``
    (``num_slots`` sizes the DP walk).
    """
    assert 0 < out_width and _hash_blocks_for(
        out_width, 2 if algo == "ntlm" else 1
    ) <= _MAX_HASH_BLOCKS, out_width
    assert kind in ("match", "suball"), kind
    assert not (single_span and kind != "match")
    assert not windowed or num_slots is not None

    def kernel(tok, wlen, count, pbase, *rest):
        if windowed:
            winv, radix, bitpos, rest = rest[0], rest[1], rest[2], rest[3:]
        if single_span:
            b_j, svl, svw, state_ref, emit_ref = rest
            a_j = None
        else:
            a_j, b_j, svl, svw, state_ref, emit_ref = rest
        rank = jax.lax.broadcasted_iota(_I32, (g, s), 1)
        lane_ok = rank < count[:, 0][:, None]
        if not windowed:
            cb = pbase[:, 0][:, None] + rank
        else:
            digits = _decode_tile_windowed(
                rank, pbase, winv, radix, num_slots, g, s, 1
            )
            cb = jnp.zeros((g, s), _I32)
            for sl in range(num_slots):
                cb = cb | (
                    (digits[sl] > 0).astype(_I32) << bitpos[:, sl][:, None]
                )
        chosen_count = _popcount_tile(cb)
        wl = wlen[:, 0][:, None]  # loop-invariant: hoisted once

        clash = jnp.zeros((g, s), jnp.bool_)
        cum = jnp.zeros((g, s), _I32)
        unit_start = []
        unit_len = []
        unit_word = []
        for j in range(length_axis):
            if kind == "match" and single_span:
                started = ((cb >> b_j[:, j][:, None]) & 1) == 1
                cov = started.astype(_I32)
            elif kind == "match":
                ab = cb & a_j[:, j][:, None]
                cov = (ab != 0).astype(_I32)
                clash = clash | ((ab & (ab - 1)) != 0)
                started = ((cb >> b_j[:, j][:, None]) & 1) == 1
            else:
                ch = ((cb >> a_j[:, j][:, None]) & 1) == 1
                cov = ch.astype(_I32)
                started = ch & (b_j[:, j][:, None] > 0)
            in_word = j < wl
            ul = jnp.where(
                in_word,
                jnp.where(started, svl[:, j][:, None], 1 - cov),
                0,
            )
            tok_j = tok[:, j][:, None].astype(_U32)
            unit_start.append(cum)
            unit_len.append(ul)
            unit_word.append(jnp.where(started, svw[:, j][:, None], tok_j))
            cum = cum + ul
        out_len = cum

        state = _grouped_hash_units(
            algo, unit_start, unit_len, unit_word, out_len, g, s,
            max_val_len=max_val_len, out_width=out_width,
        )
        for w_i, sw in enumerate(state):
            state_ref[:, w_i, :] = sw

        emit = (
            lane_ok
            & (chosen_count >= min_substitute)
            & (chosen_count <= max_substitute)
        )
        if kind == "match" and not single_span:
            emit = emit & ~clash
        emit_ref[:, :] = emit.astype(_I32)

    return kernel


def _scalar_units_prelude(radix_b, blk_base):
    """Shared packing for both scalar-units fast paths: active mask,
    active-rank bit positions, per-slot bit weights (``1 << bitpos`` for
    active slots, 0 for padding), and the block base digit vector packed
    to one plain integer per block."""
    act = (radix_b > 1).astype(_I32)
    bitpos = jnp.cumsum(act, axis=1) - act
    weight = act << bitpos
    pbase = jnp.sum(blk_base * weight, axis=1)[:, None]  # [NB, 1]
    return act, bitpos, weight, pbase


def _launch_scalar_units(
    kind, inputs, *, block_stride, length_axis, out_width,
    min_substitute, max_substitute, algo, nb, num_lanes, interpret,
    max_val_len=4, single_span=False, windowed=False, num_slots=None,
):
    """Shared kernel-build + launch tail for both scalar-units fast paths
    (``inputs`` = the 8-ref tuple of :func:`_make_scalar_kernel`, 7 when
    ``single_span`` drops the coverage bitmask, +3 when ``windowed``
    selects the DP decode)."""
    kernel = _make_scalar_kernel(
        g=_G, s=block_stride, kind=kind, length_axis=length_axis,
        out_width=out_width, min_substitute=min_substitute,
        max_substitute=max_substitute, algo=algo,
        max_val_len=max_val_len, single_span=single_span,
        windowed=windowed, num_slots=num_slots,
    )
    return _launch_fused(
        kernel, inputs, nb=nb, stride=block_stride, num_lanes=num_lanes,
        n_state=DIGEST_WORDS[algo], interpret=interpret,
    )


def _decode_tile(rank, base, radix, m, g, s):
    """Mixed-radix digit decode on a (G, S) tile: base digits + in-block
    rank with carries (f32 divides — ranks are < the block stride).
    Returns the per-slot digit list."""
    digits = []
    r = rank
    carry = jnp.zeros((g, s), _I32)
    for sl in range(m):
        rs = radix[:, sl][:, None]
        q = _exact_div(r, rs)
        t = base[:, sl][:, None] + (r - q * rs) + carry
        ge = (t >= rs).astype(_I32)
        digits.append(t - ge * rs)
        carry = ge
        r = q
    return digits


#: Hash blocks the fused kernels will chain: 3 covers candidates to 183
#: bytes (the 64-byte dictionary bucket expanded by 2-byte values).
_MAX_HASH_BLOCKS = 3


def _hash_blocks_for(out_width: "int | None", scale: int) -> int:
    """Static hash-block count for a launch: the longest emitted
    candidate (``out_width`` bytes, doubled under utf16) plus terminator
    and 8-byte length must fit ``64 * n`` bytes."""
    if out_width is None:
        return 1
    return max(1, -(-(int(out_width) * scale + 9) // 64))


def _place_word(msg, nw_data, off, blen, word, j_span, term_hi=None):
    """OR ``word``'s low ``blen`` bytes into the ``msg`` word list at byte
    offset ``off`` (all (G, S) tiles; blen in 0..4 — 5 for a terminator-
    folded final piece).  ``j_span``: static cap on the highest word index
    the piece's LO part can reach (its hi half spills one further).
    ``term_hi``: lanes whose folded piece is 5 bytes — the 5th byte rides
    the hi word at the piece's own sub-word offset.  The byte-scan
    emission's placement primitive (PERF.md §7a lever 1); the per-slot
    piece kernels use the window-bounded :func:`_place_piece` instead
    (PERF.md §18)."""
    sh8 = (blen * 8) & 31
    mask = (_U32(1) << sh8.astype(_U32)) - _U32(1)
    mask = jnp.where(blen >= 4, _U32(0xFFFFFFFF), mask)
    wm = word & mask
    sh = (_U32(8) * (off & 3).astype(_U32))
    lo = wm << sh
    # Shift-by-32 is undefined: mask the amount and select instead.
    hi = jnp.where(sh > 0, wm >> ((_U32(32) - sh) & _U32(31)), _U32(0))
    if term_hi is not None:
        hi = hi | jnp.where(term_hi, _U32(0x80) << sh, _U32(0))
    widx = off >> 2
    sel_prev = None
    for w_i in range(min(nw_data, j_span + 1)):
        sel = widx == w_i
        contrib = jnp.where(sel, lo, _U32(0))
        if sel_prev is not None:
            contrib = contrib | jnp.where(sel_prev, hi, _U32(0))
        msg[w_i] = msg[w_i] | contrib
        sel_prev = sel
    # hi spill past the last lo word (within the message bound).
    w_last = min(nw_data, j_span + 1)
    if w_last < nw_data:
        msg[w_last] = msg[w_last] | jnp.where(sel_prev, hi, _U32(0))


def _or_into(msg, w_i: int, contrib) -> None:
    """OR ``contrib`` into message word ``w_i``, tracking statically-zero
    words: a ``None`` entry means "no byte can ever land here", so the
    first contribution ASSIGNS instead of ORing and untouched words stay
    ``None`` all the way into the compression rounds, which skip their
    adds entirely (the zero-word elision half of the MD5-floor attack,
    PERF.md §24 — a short single-block message leaves most of the 16
    schedule words statically zero)."""
    msg[w_i] = contrib if msg[w_i] is None else msg[w_i] | contrib


def _place_piece(msg, nw_data, off, wd, *, floor, cap):
    """OR one PRE-MASKED piece word into the message at byte offset
    ``off`` — the piece kernels' hierarchical placement (PERF.md §18).

    Pre-masked: the schema's ``gw``/``gw16`` tables zero every byte past
    a variant's placed length, so no ``blen`` mask is built here — the
    byte length drops out of placement entirely and only the offset
    remains.  ``floor``/``cap`` are the group word's static reachable
    byte window (``PieceGroup.off_floor``/``off_cap`` plus the word's
    ``4*w``): for every EMITTED lane ``floor <= off <= cap``, so the
    select chain runs only over the window's words ``floor//4..cap//4``
    (the hi half spills one word further) instead of scanning from word
    0.  Degenerate windows collapse further:

    * ``off`` a Python int (every prior group's placed length is static)
      — the whole dynamic scatter becomes a static shift-OR;
    * ``floor//4 == cap//4`` — the lo word index is static even though
      the sub-word shift is not: no selects, just shifts and ORs.

    Masked garbage lanes may carry out-of-window offsets; their bytes
    land nowhere (or in their own garbage message), never in another
    lane's."""
    if isinstance(off, int):
        w_i = off >> 2
        sh = 8 * (off & 3)
        if w_i < nw_data:
            _or_into(msg, w_i, wd << _U32(sh) if sh else wd)
        if sh and w_i + 1 < nw_data:
            _or_into(msg, w_i + 1, wd >> _U32(32 - sh))
        return
    sh = _U32(8) * (off & 3).astype(_U32)
    lo = wd << sh
    # Shift-by-32 is undefined: mask the amount and select instead.
    hi = jnp.where(sh > 0, wd >> ((_U32(32) - sh) & _U32(31)), _U32(0))
    w_lo = max(0, floor >> 2)
    w_hi = min(cap >> 2, nw_data - 1)
    if w_lo >= nw_data:
        return
    if w_lo == w_hi:
        _or_into(msg, w_lo, lo)
        if w_lo + 1 < nw_data:
            _or_into(msg, w_lo + 1, hi)
        return
    widx = off >> 2
    sel_prev = None
    for w_i in range(w_lo, w_hi + 1):
        sel = widx == w_i
        contrib = jnp.where(sel, lo, _U32(0))
        if sel_prev is not None:
            contrib = contrib | jnp.where(sel_prev, hi, _U32(0))
        _or_into(msg, w_i, contrib)
        sel_prev = sel
    if w_hi + 1 < nw_data:
        _or_into(msg, w_hi + 1, jnp.where(sel_prev, hi, _U32(0)))


def _shift_msg_static(src, dbytes: int, nw: int):
    """Byte-shift a sparse message word list by a STATIC ``dbytes``
    (positive = toward higher offsets): the pair tier's suffix
    derivation (PERF.md §24).  The suffix groups' bytes are placed ONCE
    into an isolated accumulator; the partner's copy is this pure
    word-level funnel shift — 2 static shifts + 1 OR per populated
    word, with no per-lane masks (``None`` entries are statically zero
    and propagate)."""
    if dbytes == 0:
        return list(src[:nw])
    out = []
    for w in range(nw):
        b0 = 4 * w - dbytes
        w0, r = b0 >> 2, b0 & 3
        lo = src[w0] if 0 <= w0 < len(src) else None
        hi = src[w0 + 1] if 0 <= w0 + 1 < len(src) else None
        acc = None
        if lo is not None:
            acc = lo if r == 0 else lo >> _U32(8 * r)
        if r and hi is not None:
            part = hi << _U32(32 - 8 * r)
            acc = part if acc is None else acc | part
        out.append(acc)
    return out


def _merge_msgs(nw: int, *parts):
    """Word-wise OR of sparse message word lists (``None`` = statically
    zero) into one ``nw``-word list — the pair tier's final member
    assembly: shared prefix ∪ member overlay ∪ (shifted) suffix."""
    out = []
    for w in range(nw):
        acc = None
        for p in parts:
            t = p[w] if w < len(p) else None
            if t is None:
                continue
            acc = t if acc is None else acc | t
        out.append(acc)
    return out


def _length_words(msg, end, *, big_endian_length, hash_blocks):
    """Fold the 64-bit message bit length into the padding block's length
    words: word ``16k + 14`` (LE) / byte-swapped ``16k + 15`` (BE) for the
    block whose window holds the lane's terminator+length (shared by both
    emission schemes — see :func:`_message_from_units`)."""
    bits = (end * 8).astype(_U32)
    if big_endian_length:
        # SHA-1: the 64-bit BE bit length occupies the padding block's
        # bytes 56..63; its low 32 bits are that block's LE word 15
        # byte-swapped (the BE high half, word 14, stays data-or-zero —
        # zero in the padding block for <2^29-bit messages).
        bits = (
            ((bits & _U32(0xFF)) << 24)
            | ((bits & _U32(0xFF00)) << 8)
            | ((bits >> 8) & _U32(0xFF00))
            | (bits >> 24)
        )
    lw = 15 if big_endian_length else 14
    if hash_blocks == 1:
        msg[lw] = bits
    else:
        # Per-lane padding block k: terminator + 8-byte length fit block
        # k iff end <= 64*(k+1) - 9.  Later blocks are ignored by the
        # state select, so the LAST block's length word can be
        # unconditional; inner blocks' must not clobber longer lanes'
        # data words.  ``None`` entries are statically zero (the piece
        # kernels' sparse message lists) — the OR degrades to an assign.
        for k in range(hash_blocks):
            if k + 1 == hash_blocks:
                _or_into(msg, 16 * k + lw, bits)
            else:
                fits = end <= (64 * (k + 1) - 9)
                _or_into(msg, 16 * k + lw, jnp.where(fits, bits, _U32(0)))
    return msg


def _message_from_units(unit_start, unit_len, unit_word, out_len, g, s,
                        *, big_endian_length=False, utf16=False,
                        max_unit_len=4, out_width=None, hash_blocks=1,
                        with_end=False):
    """Assemble the padded message (``16 * hash_blocks`` u32 words on
    (G, S) tiles, little-endian byte order — SHA-1 byte-swaps in its
    schedule) from per-unit output spans: unit j contributes bytes
    ``unit_word[j]`` at offsets ``unit_start[j] .. +unit_len[j]``; 0x80
    terminator after the data; bit length in the LAST WORDS OF EACH
    LANE'S OWN padding block — word ``16k + 14`` (LE) / byte-swapped
    ``16k + 15`` (BE) for the block ``k`` whose 64-byte window holds the
    lane's terminator+length (later blocks are ignored by the per-lane
    state select in :func:`_hash_units`, so their length words may hold
    anything for shorter lanes).

    ``utf16``: NTLM's hashcat-style expansion — every candidate byte
    becomes the code unit ``byte | 0x0000``, i.e. byte offsets double and
    odd bytes stay zero (matching ``ops.hashes.utf16le_expand``).

    A unit at index j starts at candidate offset <= ``max_unit_len * j``
    (every prior unit contributes at most ``max_unit_len`` bytes — the
    table's value width, 1..4), bounding its word span: for the shipped
    2-byte-value layouts the per-unit select chains halve versus the
    generic <=4-bytes bound.  ``out_width`` (when given) likewise bounds
    the terminator scan — emitted candidates never exceed it, and
    overlong lanes are masked garbage by contract.

    Placement is whole-unit, not per-byte (PERF.md §7's top lever): the
    unit's <=4 masked bytes shift as one u32 into a (lo, hi) word pair
    straddling the dynamic byte offset, and each pair scatters into the
    message with one select chain per touched word — ~2x fewer VPU ops
    than placing each byte separately.  For utf16 the unit first expands
    into two 2-code-unit pieces (even byte offsets, same machinery)."""
    scale = 2 if utf16 else 1
    msg = [jnp.zeros((g, s), _U32) for _ in range(16 * hash_blocks)]
    # Data (and the terminator) can reach every word except the LAST
    # block's two length words; inner blocks' words 14/15 hold data for
    # lanes long enough to need the next block.
    nw_data = 16 * hash_blocks - 2

    def place(off, blen, word, j_span, term_hi=None):
        """Whole-unit placement (see :func:`_place_word`)."""
        _place_word(msg, nw_data, off, blen, word, j_span, term_hi=term_hi)

    mul = max(1, int(max_unit_len))
    # Terminator fold (PERF.md §7a ranked lever 3): ``cum`` is monotone
    # and trailing units are zero-length, so the FINAL unit's piece ends
    # at ``out_len`` for EVERY lane — appending the 0x80 terminator to
    # that one piece replaces the whole per-word terminator scan below.
    # utf16 keeps the scan: its expanded terminator (byte ``2*out_len``)
    # can land past both split pieces' 4-byte windows.
    fold_term = not utf16 and len(unit_start) > 0
    for j in range(len(unit_start)):
        us, ul, uw = unit_start[j], unit_len[j], unit_word[j]
        # Highest word index unit j's LO part can reach: its start offset
        # is at most mul*j (hi spills one word further inside place()).
        span = (scale * mul * j) // 4
        if not utf16:
            if fold_term and j == len(unit_start) - 1:
                # Clear the piece's bytes at/above ``ul`` (ungrouped token
                # units carry garbage there), plant 0x80 at byte ``ul``;
                # a full 4-byte piece's terminator rides the hi word.
                sh_t = _U32(8) * (ul & 3).astype(_U32)
                ge4 = ul >= 4
                keep = jnp.where(
                    ge4, _U32(0xFFFFFFFF), (_U32(1) << sh_t) - _U32(1)
                )
                uw = (uw & keep) | jnp.where(
                    ge4, _U32(0), _U32(0x80) << sh_t
                )
                place(us, ul + 1, uw, span, term_hi=ge4)
            else:
                place(us, ul, uw, span)
        else:
            # Bytes b0..b3 -> code units (b0|b1<<16) at 2*us and
            # (b2|b3<<16) at 2*us+4.
            lo16 = (uw & _U32(0xFF)) | ((uw & _U32(0xFF00)) << 8)
            hi16 = ((uw >> 16) & _U32(0xFF)) | (
                ((uw >> 24) & _U32(0xFF)) << 16
            )
            off = us * 2
            blen_lo = jnp.minimum(ul, 2) * 2
            blen_hi = jnp.maximum(ul - 2, 0) * 2
            place(off, blen_lo, lo16, span)
            place(off + 4, blen_hi, hi16, span + 1)
    end = out_len * scale
    if not fold_term:
        mark = _U32(0x80) << (_U32(8) * (end & 3).astype(_U32))
        widx = end >> 2
        # Emitted candidates end at <= out_width bytes, so the terminator
        # can only land in the first (out_width*scale)//4 + 1 words;
        # overlong lanes are masked garbage either way.
        n_term = (nw_data if out_width is None
                  else min(nw_data, (int(out_width) * scale) // 4 + 1))
        for w_i in range(n_term):
            msg[w_i] = msg[w_i] | jnp.where(widx == w_i, mark, _U32(0))
    msg = _length_words(msg, end, big_endian_length=big_endian_length,
                        hash_blocks=hash_blocks)
    return (msg, end) if with_end else msg


def _md5_rounds(msg, g, s, init=None):
    """The unrolled 64-round MD5 compression on (G, S) u32 tiles (same
    chain as ops.pallas_md5). Returns the four output state words;
    ``init`` chains a previous block's state (None = the IV).

    ``None`` message entries are STATICALLY zero (the piece kernels'
    sparse message lists, see :func:`_or_into`): their schedule adds are
    elided — for a short single-block message that removes one add per
    round per untouched word (4 uses × ~8 idle words at the §7a
    geometry), a direct cut into the ~640-op MD5 floor (PERF.md §24)."""
    if init is None:
        init = tuple(jnp.full((g, s), _U32(k)) for k in _MD5_INIT)
    a, b, c, d = init
    for i in range(64):
        # Mux forms of the round functions (3 ops instead of 4 — the
        # classic identity ``(x&y)|(~x&z) == z ^ (x & (y ^ z))``); bit-
        # identical to ops.hashes' reference forms, ~32 fewer eqns per
        # compression (PERF.md §24's direct floor cut).
        if i < 16:
            f = d ^ (b & (c ^ d))
            gidx = i
        elif i < 32:
            f = c ^ (d & (b ^ c))
            gidx = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            gidx = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            gidx = (7 * i) % 16
        rot = a + f + _U32(_MD5_K[i])
        if msg[gidx] is not None:
            rot = rot + msg[gidx]
        sh = _MD5_S[i]
        rotated = (rot << _U32(sh)) | (rot >> _U32(32 - sh))
        a, d, c, b = d, c, b, b + rotated
    return (a + init[0], b + init[1], c + init[2], d + init[3])


def _rotl_tile(x, sh: int):
    return (x << _U32(sh)) | (x >> _U32(32 - sh))


def _md4_rounds(msg, g, s, init=None):
    """Unrolled MD4 (RFC 1320 — the NTLM core) on (G, S) u32 tiles,
    mirroring ``ops.hashes._md4_block``; ``init`` chains blocks.
    ``None`` message entries are statically zero — their adds are
    elided (see :func:`_md5_rounds`)."""
    if init is None:
        init = tuple(jnp.full((g, s), _U32(k)) for k in _MD4_INIT)
    a, b, c, d = init

    def addm(x, k):
        return x if msg[k] is None else x + msg[k]

    # Mux/majority identities as in :func:`_md5_rounds` — bit-identical,
    # one fewer eqn per round.
    for j, k in enumerate(range(16)):
        a2 = _rotl_tile(addm(a + (d ^ (b & (c ^ d))), k),
                        (3, 7, 11, 19)[j % 4])
        a, b, c, d = d, a2, b, c
    for j, k in enumerate(_MD4_G):
        a2 = _rotl_tile(
            addm(a + ((b & (c | d)) | (c & d)), k) + _U32(0x5A827999),
            (3, 5, 9, 13)[j % 4],
        )
        a, b, c, d = d, a2, b, c
    for j, k in enumerate(_MD4_H):
        a2 = _rotl_tile(
            addm(a + (b ^ c ^ d), k) + _U32(0x6ED9EBA1),
            (3, 9, 11, 15)[j % 4],
        )
        a, b, c, d = d, a2, b, c
    return (a + init[0], b + init[1], c + init[2], d + init[3])


def _sha1_rounds(msg, g, s, init=None):
    """Unrolled 80-round SHA-1 on (G, S) u32 tiles: byte-swaps the shared
    little-endian message layout into the big-endian schedule, rolling
    16-word window for the expansion (mirrors ``ops.hashes._sha1_block``).
    ``None`` message entries are statically zero — their byte-swaps,
    schedule xors, and round adds are elided (see :func:`_md5_rounds`;
    the 64-word expansion makes the propagation worth more here)."""
    def bswap(x):
        return None if x is None else (
            ((x & _U32(0xFF)) << 24)
            | ((x & _U32(0xFF00)) << 8)
            | ((x >> 8) & _U32(0xFF00))
            | (x >> 24)
        )

    w = [bswap(m) for m in msg]
    for t in range(16, 80):
        terms = [x for x in (w[t - 3], w[t - 8], w[t - 14], w[t - 16])
                 if x is not None]
        if not terms:
            w.append(None)
            continue
        acc = terms[0]
        for x in terms[1:]:
            acc = acc ^ x
        w.append(_rotl_tile(acc, 1))
    if init is None:
        init = tuple(jnp.full((g, s), _U32(k)) for k in _SHA1_INIT)
    a, b, c, d, e = init
    for t in range(80):
        # Mux/majority identities as in :func:`_md5_rounds`.
        if t < 20:
            f = d ^ (b & (c ^ d))
        elif t < 40:
            f = b ^ c ^ d
        elif t < 60:
            f = (b & (c | d)) | (c & d)
        else:
            f = b ^ c ^ d
        tmp = _rotl_tile(a, 5) + f + e + _U32(_SHA1_K[t // 20])
        if w[t] is not None:
            tmp = tmp + w[t]
        e, d, c, b, a = d, c, _rotl_tile(b, 30), a, tmp
    return (a + init[0], b + init[1], c + init[2], d + init[3],
            e + init[4])


def _hash_units(algo, unit_start, unit_len, unit_word, out_len, g, s,
                max_unit_len=4, out_width=None):
    """Message assembly + compression for one algo; returns the state-word
    tuple (4 for MD5/MD4/NTLM, 5 for SHA-1).

    Long launches (``out_width`` past one hash block) build a
    ``16 * n``-word message and chain up to ``n`` compressions; each
    lane's digest is the state after ITS OWN padding block (terminator +
    length fit block k iff ``end <= 64*(k+1) - 9``), selected per lane —
    shorter lanes simply ignore the later blocks' garbage."""
    utf16 = algo == "ntlm"
    scale = 2 if utf16 else 1
    nblocks = _hash_blocks_for(out_width, scale)
    msg, end = _message_from_units(unit_start, unit_len, unit_word,
                                   out_len, g, s, utf16=utf16,
                                   big_endian_length=algo == "sha1",
                                   max_unit_len=max_unit_len,
                                   out_width=out_width,
                                   hash_blocks=nblocks, with_end=True)
    return _compress_message(algo, msg, end, g, s, hash_blocks=nblocks)


def _compress_message(algo, msg, end, g, s, *, hash_blocks):
    """Chain ``hash_blocks`` compressions over an assembled message and
    select each lane's digest after ITS OWN padding block (terminator +
    length fit block k iff ``end <= 64*(k+1) - 9``) — shared by both
    emission schemes."""
    rounds = {"md5": _md5_rounds, "md4": _md4_rounds, "ntlm": _md4_rounds,
              "sha1": _sha1_rounds}[algo]
    state = rounds(msg[:16], g, s)
    if hash_blocks == 1:
        return state
    final = state
    for k in range(1, hash_blocks):
        state = rounds(msg[16 * k:16 * (k + 1)], g, s, init=state)
        needs_k = end > (64 * k - 9)  # lane's padding block is >= k
        final = tuple(
            jnp.where(needs_k, sw, fw) for sw, fw in zip(state, final)
        )
    return final


def _grouped_hash_units(algo, unit_start, unit_len, unit_word, out_len,
                        g, s, *, max_val_len, out_width):
    """:func:`_hash_units` behind unit grouping, shared by every kernel.

    With values <= 2 bytes, ``4 // mul`` adjacent units always fit one
    u32 — merging them halves (2-byte values) or quarters (1-byte) the
    per-unit placement select chains. Unit words hold exactly their
    length's bytes (packed values zero-pad, tokens are one byte), so
    only zero-length units need masking, and the intra-group shift stays
    <= 8*(4 - mul) < 32.  The span bound is unchanged: merged unit k
    starts at most ``mul*gsz*k = eff_mul*k`` bytes in.
    """
    mu = max(1, max_val_len)
    gsz = max(1, 4 // mu)
    length_axis = len(unit_start)
    if gsz > 1:
        g_start, g_len, g_word = [], [], []
        for k in range(0, length_axis, gsz):
            acc_w = jnp.zeros((g, s), _U32)
            acc_l = jnp.zeros((g, s), _I32)
            for t in range(k, min(k + gsz, length_axis)):
                w_m = jnp.where(unit_len[t] > 0, unit_word[t], _U32(0))
                acc_w = acc_w | (w_m << (acc_l.astype(_U32) * _U32(8)))
                acc_l = acc_l + unit_len[t]
            g_start.append(unit_start[k])
            g_len.append(acc_l)
            g_word.append(acc_w)
        unit_start, unit_len, unit_word = g_start, g_len, g_word
    return _hash_units(algo, unit_start, unit_len, unit_word, out_len,
                       g, s, max_unit_len=mu * gsz, out_width=out_width)


def _shr_static(x, n: int):
    """``x >> n`` for a static shift up to 63 on i32 tiles.  Shifts past
    31 are split in two (packed chosen vectors stay below 2^26, so the
    result there is exactly 0 — never implementation-defined)."""
    if n <= 31:
        return x >> n if n else x
    return (x >> 31) >> (n - 31)


def _select_rows(idx, rows, g, s):
    """N-way variant select on (G, S) tiles: ``rows[idx]`` per lane.
    ``rows`` are (G,) ref slices (block-uniform variant words/lengths),
    broadcast once along the lane axis; one ``lax.select_n`` replaces the
    compare-select chain."""
    cases = [jax.lax.broadcast_in_dim(r, (g, s), (0,)) for r in rows]
    if len(cases) == 1:
        return cases[0]
    return jax.lax.select_n(idx, *cases)


def _make_piece_kernel(
    *, g: int, s: int, kind: str, schema, num_slots: int, k_opts: int,
    out_width: int, min_substitute: int, max_substitute: int,
    algo: str = "md5", scalar: bool = False, windowed: bool = False,
    close_s: "int | None" = None, pair: bool = False,
):
    """Per-slot piece-emission kernel body (PERF.md §17/§18) — ONE
    builder for every tier (match/suball × scalar/general × full/
    windowed × closed).

    The unit scheme's O(L) per-byte resolution is replaced by the plan's
    :class:`ops.packing.PieceSchema`: per emission GROUP the kernel forms
    a variant index from the group's slots' digits (scalar tiers: a bit
    field of the packed chosen vector), selects the group's precomputed
    word(s) and placed length with one ``select_n`` each (u16 table rows
    for ``packed16`` groups, widened after the select), places the
    word(s) via the window-bounded :func:`_place_piece` scatter at the
    lane-local prefix offset, and advances the prefix sum — which stays
    a Python int through any run of fixed-length groups, collapsing
    their placement to static shift-ORs (the hierarchical-placement
    lever, PERF.md §18).  Literal gaps, skip bytes, value bytes AND the
    0x80 terminator live in the host tables (the tail group's bytes
    carry the terminator, which under NTLM's UTF-16LE expansion lands
    as exactly the padded message's ``80 00`` pair — no terminator scan
    remains in any tier).

    Ref order (VMEM per grid step): ``count[G, 1]``, then the decode refs
    — scalar full: ``pbase[G, 1]``; windowed: ``base[G, M]``,
    ``radix[G, M]``, ``winv[G, M+1, K2]``; general: ``base[G, M]``,
    ``radix[G, M]`` — then suball selector refs (scalar: ``selbit[G, C]``
    (+ ``bitpos[G, P]`` when windowed); general: ``selslot[G, C]``), then
    closure refs (``cnext``/``cmul``), then the piece tables — the wide
    groups' ``gw[G, NGW, VM, NW] u32`` (absent when every group packs to
    u16), the narrow groups' ``gw16[G, NG16, VM] u16`` (absent when none
    does), and ``gl[G, NGD, VM] i32`` — the DYNAMIC-length groups' rows
    only, indexed by ``grp.gl_idx`` (absent when every group is fixed:
    all-fixed schemas ship no length table, PERF.md §19).
    Outputs: ``state[G, KS, S] u32``, ``emit[G, S] i32`` — identical
    contract to :func:`_make_kernel`.

    ``pair`` (the pair-lane tier, PERF.md §24): every lane covers the
    two consecutive candidate ranks ``2r``/``2r+1`` of its block
    (blocks then span ``2s`` ranks; ``count`` counts CANDIDATES).  The
    schema's pair gate guarantees the partner's digit vector is the
    base's with slot 0's digit + 1 and that only the ``pair_g0`` group's
    variant differs, so the kernel decodes ONCE, selects every group's
    word/length ONCE (plus one extra select pair for ``pair_g0``'s
    partner variant ``idx + 1``), shares the prefix groups' placement,
    and derives the partner message by patching ``pair_g0``'s words —
    forking the suffix placement only when the pair's placed-length
    delta is nonzero (offsets shift by the schema's static
    ``pair_dmin``/``pair_dmax`` bounds).  Both members' compressions
    run (each with the zero-word elision), and the outputs interleave:
    ``state[G, KS, 2S]`` / ``emit[G, 2S]`` with member ``p`` of lane
    ``r`` at column ``2r + p`` — candidate rank order.
    """
    utf16 = algo == "ntlm"
    scale = 2 if utf16 else 1
    hash_blocks = _hash_blocks_for(out_width, scale)
    assert 0 < out_width and hash_blocks <= _MAX_HASH_BLOCKS, out_width
    assert kind in ("match", "suball"), kind
    groups = schema.groups
    closed = bool(schema.closed)
    if pair:
        assert schema.pair_ok and hash_blocks == 1 and not windowed \
            and close_s is None, "pair gate bypassed"
    pair_g0 = schema.pair_g0 if pair else -1
    pair_static = pair and schema.pair_dmin == schema.pair_dmax
    pair_d = schema.pair_dmin if pair_static else None

    def kernel(count, *rest):
        rest = list(rest)
        pbase = base = radix = winv = None
        if scalar and not windowed:
            pbase = rest.pop(0)
        else:
            base = rest.pop(0)
            radix = rest.pop(0)
            if windowed:
                winv = rest.pop(0)
        selbit = selslot = bitpos = None
        if kind == "suball":
            if scalar:
                if windowed:
                    bitpos = rest.pop(0)
                selbit = rest.pop(0)
            else:
                selslot = rest.pop(0)
        cnext = cmul = None
        if close_s is not None:
            cnext = rest.pop(0)
            cmul = rest.pop(0)
        gw = rest.pop(0) if schema.gw is not None else None
        gw16 = rest.pop(0) if schema.gw16 is not None else None
        gl = rest.pop(0) if schema.gl is not None else None
        state_ref, emit_ref = rest

        rank = jax.lax.broadcasted_iota(_I32, (g, s), 1)
        if pair:
            # Each lane owns candidate ranks 2r / 2r+1; ``count`` counts
            # candidates (up to 2s).
            cand0 = rank * 2
            ok0 = cand0 < count[:, 0][:, None]
            ok1 = cand0 + 1 < count[:, 0][:, None]
            lane_ok = ok0
            rank_c = cand0
        else:
            lane_ok = rank < count[:, 0][:, None]
            rank_c = rank

        # --- decode: digits and/or the packed chosen vector -------------
        digits = cb = None
        if scalar and not windowed:
            # Pair: blocks start at even ranks and rank_c is even, so
            # cb's bit 0 (slot 0's chosen bit) is 0 on EVERY lane — the
            # partner is cb | 1, never materialized: only the pair
            # group's variant index (+1) and the chosen count (+1) see
            # it.
            cb = pbase[:, 0][:, None] + rank_c
        elif windowed:
            digits = _decode_tile_windowed(
                rank, base, winv, radix, num_slots, g, s, k_opts
            )
        else:
            decode = _decode_tile_radix2 if k_opts == 1 else _decode_tile
            digits = decode(rank_c, base, radix, num_slots, g, s)
        d0p = None
        if pair and digits is not None:
            # Partner digit of slot 0: the pair gate guarantees even
            # radix, so digit + 1 never carries; the min only guards
            # masked garbage lanes (and inactive radix-1 words, whose
            # partner lanes are masked by ok1).
            d0p = jnp.minimum(digits[0] + 1, radix[:, 0][:, None] - 1)
        if scalar and windowed:
            # Pack the DP walk's chosen bits so the piece selectors read
            # one vector (match plans: slot c IS bit c — active slots are
            # a prefix; suball: per-block bit positions).
            cb = jnp.zeros((g, s), _I32)
            for sl in range(num_slots):
                bit = (digits[sl] > 0).astype(_I32)
                if kind == "match":
                    cb = cb | (bit << sl)
                else:
                    cb = cb | (bit << bitpos[:, sl][:, None])
        if cb is not None:
            chosen_count = _popcount_tile(cb)
        else:
            chosen_count = jnp.zeros((g, s), _I32)
            for sl in range(num_slots):
                chosen_count = chosen_count + (digits[sl] > 0).astype(_I32)
        cc1 = None
        if pair:
            if cb is not None:
                cc1 = chosen_count + 1  # partner flips bit 0 (0 -> 1)
            else:
                cc1 = chosen_count + (d0p > 0).astype(_I32) - (
                    digits[0] > 0
                ).astype(_I32)

        # Cascade closure (suball general only): per-slot JOINT value
        # index over the slot's own and its successors' digits — same
        # unrolled compare-select as the byte-scan kernel.
        joint = None
        if close_s is not None:
            joint = []
            for sl in range(num_slots):
                acc = (digits[sl] - 1) * cmul[:, sl, 0][:, None]
                for s_i in range(close_s):
                    nt = cnext[:, sl, s_i][:, None]
                    ds = jnp.zeros((g, s), _I32)
                    for t2 in range(sl + 1, num_slots):
                        ds = jnp.where(nt == t2, digits[t2], ds)
                    acc = acc + ds * cmul[:, sl, 1 + s_i][:, None]
                joint.append(acc)

        def col_variant(c):
            """Column c's variant index (0 = skip) as a (G, S) i32."""
            if kind == "match":
                d = digits[c]
            else:  # suball general: digit of the owning pattern slot
                d = jnp.zeros((g, s), _I32)
                jc = jnp.zeros((g, s), _I32) if closed else None
                for sl in range(num_slots):
                    here = selslot[:, c][:, None] == sl
                    d = jnp.where(here, digits[sl], d)
                    if closed:
                        jc = jnp.where(here, joint[sl], jc)
                if closed:
                    return jnp.where(d > 0, 1 + jc, 0)
            return d

        # --- per-group emission ------------------------------------------
        # The running offset stays a PYTHON INT (``cum_static``) while
        # every group so far has a fixed placed length (``len_fixed``) —
        # a run of fixed groups costs zero offset arithmetic and their
        # placement collapses to static shift-ORs; the first varying
        # group switches to the dynamic prefix sum (PERF.md §18).
        # Message words start as ``None`` (statically zero) so untouched
        # schedule words skip their compression adds (PERF.md §24).
        #
        # Pair bookkeeping: groups BEFORE ``pair_g0`` place into the
        # shared ``msgA``.  With a STATIC length delta (the schema's
        # bounds coincide — every shipped fixed-width value layout) the
        # pair group's two variants land in per-member OVERLAYS at the
        # SAME offset, the suffix groups place ONCE into the isolated
        # ``msgS`` accumulator, and the partner's suffix is derived by
        # a pure static funnel shift of ``msgS`` (no second placement,
        # no masks — PERF.md §24's "no second splice").  A dynamic
        # delta FORKS ``msgB`` instead: suffix groups place twice, the
        # partner's offsets shifted per lane.
        msgA = [None] * (16 * hash_blocks)
        msgB = None
        msgS = None
        ovA = ovB = None
        delta = 0  # partner-minus-base placed length (int or tile)
        delta_msg = 0  # the same in message space (× utf16 scale)
        nw_data = 16 * hash_blocks - 2
        cum_static = 0
        cum = None  # dynamic offset once any group's length varies
        for gi, grp in enumerate(groups):
            n_var, n_words = grp.n_variants, grp.n_words
            if gi == pair_g0:
                if pair_static:
                    ovA = [None] * len(msgA)
                    ovB = [None] * len(msgA)
                    msgS = [None] * len(msgA)
                    delta = pair_d
                    delta_msg = pair_d * scale
                else:
                    msgB = list(msgA)
            if grp.len_fixed == 0:
                continue  # empty in every launched word: nothing placed
            idx = idx1 = None
            if n_var > 1:
                sel = grp.sel_cols
                if cb is not None:
                    if kind == "match" and sel == tuple(
                        range(sel[0], sel[0] + len(sel))
                    ):
                        # Adjacent slots: one bit-field extract indexes
                        # the whole merged group.
                        idx = _shr_static(cb, sel[0]) & (
                            (1 << len(sel)) - 1
                        )
                    else:
                        idx = jnp.zeros((g, s), _I32)
                        for i, c in enumerate(sel):
                            if kind == "match":
                                bit = _shr_static(cb, c) & 1
                            else:
                                bit = (
                                    cb >> selbit[:, c][:, None]
                                ) & 1
                            idx = idx | (bit << i)
                elif len(sel) == 1:
                    # Clamp: padding columns (words with fewer pattern
                    # segments than the column axis) alias slot 0, whose
                    # digit/joint index can exceed this column's variant
                    # rows — every row of a padding column is empty, so
                    # any in-range row is correct, but select_n with an
                    # out-of-range index is undefined on TPU.
                    idx = jnp.minimum(col_variant(sel[0]), n_var - 1)
                else:  # merged binary columns under a digit decode
                    idx = jnp.zeros((g, s), _I32)
                    for i, c in enumerate(sel):
                        idx = idx | (
                            (col_variant(c) > 0).astype(_I32) << i
                        )
                if gi == pair_g0:
                    # Partner variant: column 0 is the group's lowest
                    # factor and its base digit/bit is even, so the
                    # partner index is idx + 1.  cb lanes are always
                    # even in bit 0 (no clamp needed); digit-decoded
                    # garbage lanes clamp like the base select.
                    idx1 = idx + 1 if cb is not None else jnp.minimum(
                        idx + 1, n_var - 1
                    )

            def sel_words(index):
                words = []
                for w in range(n_words):
                    if grp.packed16:
                        # u16 variant table: halved VMEM loads; widen
                        # after the select (one convert per group).
                        wd = _select_rows(
                            index,
                            [gw16[:, grp.tab_idx, v] for v in range(n_var)],
                            g, s,
                        ).astype(_U32)
                    else:
                        wd = _select_rows(
                            index,
                            [gw[:, grp.tab_idx, v, w] for v in range(n_var)],
                            g, s,
                        )
                    words.append(wd)
                return words

            def split_pieces(words):
                """(msg-space byte delta, tile, floor, cap) per placed
                word — utf16 expands each u32 into its two code-unit
                pieces ONCE, so shared suffix groups never convert
                twice."""
                out = []
                # Static Python list of selected word tiles, never a
                # traced value.
                for w, wd in enumerate(words):  # graftlint: disable=GL005
                    floor = grp.off_floor + 4 * w
                    cap = grp.off_cap + 4 * w
                    if not utf16:
                        out.append((4 * w, wd, floor, cap))
                        continue
                    # Bytes b0..b3 -> code units (b0|b1<<16) at 2*off
                    # and (b2|b3<<16) at 2*off+4; the terminator
                    # pseudo-byte expands to the message's 80 00 pair.
                    lo16 = (wd & _U32(0xFF)) | ((wd & _U32(0xFF00)) << 8)
                    out.append((8 * w, lo16, 2 * floor, 2 * cap))
                    if not grp.packed16:
                        # packed16 rows are u16: bytes 2-3 are
                        # statically zero, so the hi pair would OR
                        # nothing.
                        hi16 = ((wd >> 16) & _U32(0xFF)) | (
                            ((wd >> 24) & _U32(0xFF)) << 16
                        )
                        out.append((8 * w + 4, hi16, 2 * floor + 4,
                                    2 * cap + 4))
                return out

            def place(target, pieces_list, off_msg, shift=0):
                """Place a group's pieces at message-space offset
                ``off_msg`` (+ static window ``shift`` for the pair
                suffix: the partner's reachable window moves by the
                static delta bounds)."""
                lo_x = shift if isinstance(shift, int) else (
                    schema.pair_dmin * scale
                )
                hi_x = shift if isinstance(shift, int) else (
                    schema.pair_dmax * scale
                )
                # Static Python list of (offset, tile, window) pieces,
                # never a traced value.
                for doff, tile, fl, cp in pieces_list:  # noqa: E501  # graftlint: disable=GL005
                    o = off_msg if doff == 0 else off_msg + doff
                    _place_piece(target, nw_data, o, tile,
                                 floor=fl + lo_x, cap=cp + hi_x)

            off0 = cum_static if cum is None else cum
            off_msg = off0 * scale if scale != 1 else off0
            piecesA = split_pieces(sel_words(idx))
            if gi != pair_g0:
                if msgS is not None:
                    # Pair suffix, static delta: placed ONCE into the
                    # isolated accumulator — the partner's copy is the
                    # finalize-time funnel shift.
                    place(msgS, piecesA, off_msg)
                else:
                    place(msgA, piecesA, off_msg)
                    if msgB is not None:
                        # Pair suffix, dynamic delta: same selected
                        # words, the partner's offsets shifted by the
                        # per-lane length delta (windows widened by the
                        # schema's static bounds).
                        place(msgB, piecesA, off_msg + delta_msg,
                              shift=delta)
            else:
                piecesB = split_pieces(sel_words(idx1))
                if ovA is not None:
                    place(ovA, piecesA, off_msg)
                    place(ovB, piecesB, off_msg)
                else:
                    place(msgA, piecesA, off_msg)
                    place(msgB, piecesB, off_msg)
            if grp.len_fixed is not None:
                if cum is None:
                    cum_static += grp.len_fixed
                else:
                    cum = cum + grp.len_fixed
            else:
                l = _select_rows(
                    idx, [gl[:, grp.gl_idx, v] for v in range(n_var)], g, s
                )
                if gi == pair_g0 and not pair_static:
                    lB = _select_rows(
                        idx1, [gl[:, grp.gl_idx, v] for v in range(n_var)],
                        g, s,
                    )
                    delta = lB - l
                    delta_msg = delta * scale if scale != 1 else delta
                if cum is not None:
                    cum = cum + l
                else:
                    cum = l if cum_static == 0 else l + cum_static
        # The tail group's placed bytes include the terminator.
        if cum is None:  # every group fixed: the whole length is static
            out_len = jnp.full((g, s), cum_static - 1, _I32)
        else:
            out_len = cum - 1

        def window(cc):
            return (cc >= min_substitute) & (cc <= max_substitute)

        if not pair:
            end = out_len * scale if scale != 1 else out_len
            msg = _length_words(msgA, end,
                                big_endian_length=algo == "sha1",
                                hash_blocks=hash_blocks)
            state = _compress_message(algo, msg, end, g, s,
                                      hash_blocks=hash_blocks)
            for w_i, sw in enumerate(state):
                state_ref[:, w_i, :] = sw
            emit = lane_ok & window(chosen_count)
            emit_ref[:, :] = emit.astype(_I32)
            return

        # --- pair finalize: two single-block compressions ---------------
        zero_d = isinstance(delta, int) and delta == 0
        out_lenB = out_len if zero_d else out_len + delta
        endA = out_len * scale if scale != 1 else out_len
        endB = endA if zero_d else (
            out_lenB * scale if scale != 1 else out_lenB
        )
        if ovA is not None:
            # Static delta: member messages assemble from the shared
            # prefix, each member's overlay of the pair group, and the
            # once-placed suffix — the partner's suffix a pure static
            # funnel shift (PERF.md §24).
            nw = 16 * hash_blocks
            mA = _merge_msgs(nw, msgA, ovA, msgS)
            mB = _merge_msgs(
                nw, msgA, ovB,
                # Emitted lanes' data bytes never reach the length
                # words (single-block gate), so the shifted suffix is
                # capped at the data words.
                _shift_msg_static(msgS, delta_msg, nw_data),
            )
        else:
            mA, mB = msgA, msgB
        mA = _length_words(mA, endA, big_endian_length=algo == "sha1",
                           hash_blocks=1)
        mB = _length_words(mB, endB, big_endian_length=algo == "sha1",
                           hash_blocks=1)
        stateA = _compress_message(algo, mA, endA, g, s, hash_blocks=1)
        stateB = _compress_message(algo, mB, endB, g, s, hash_blocks=1)
        # Members land in contiguous HALVES of the doubled lane axis;
        # the wrapper interleaves to candidate-rank order outside the
        # kernel (host-level XLA — free in the vreg budget).
        for w_i, (swA, swB) in enumerate(zip(stateA, stateB)):
            state_ref[:, w_i, :s] = swA
            state_ref[:, w_i, s:] = swB
        emit_ref[:, :s] = (ok0 & window(chosen_count)).astype(_I32)
        emit_ref[:, s:] = (ok1 & window(cc1)).astype(_I32)

    return kernel


def _make_kernel(
    *, g: int, s: int, m: int, length_axis: int, k_opts: int,
    out_width: int, min_substitute: int, max_substitute: int,
    algo: str = "md5", win_k2: "int | None" = None,
    max_val_len: int = 4,
):
    """Build the per-step kernel body (fully unrolled straight-line trace).

    Ref shapes per grid step (all VMEM):
      tok[G, L] i32, wlen[G, 1] i32, radix[G, M] i32, base[G, M] i32,
      count[G, 1] i32, inside[G, M, L] i32 0/1 (byte j inside slot sl's
      match span), start[G, M, L] i32 0/1 (byte j starts it),
      [winv[G, M+1, K2] i32 — windowed plans only],
      vopt[G, M, K] u32 (value bytes little-endian-packed), vlen[G, M, K] i32
    Outputs: state[G, KS, S] u32 (hash state words, KS = DIGEST_WORDS[algo]),
    emit[G, S] i32.

    ``win_k2``: the suffix-count DP's column count for count-windowed
    plans (None = full enumeration); selects the windowed decode and the
    extra ``winv`` input.
    """
    # Single-hash-block scope: every emitted candidate (out_len <=
    # out_width, doubled for NTLM) plus its terminator must fit below the
    # length words.
    assert 0 < out_width and _hash_blocks_for(
        out_width, 2 if algo == "ntlm" else 1
    ) <= _MAX_HASH_BLOCKS, out_width

    def kernel(tok, wlen, radix, base, count, inside, start,
               *rest):
        if win_k2 is not None:
            winv, vopt, vlen, state_ref, emit_ref = rest
        else:
            winv = None
            vopt, vlen, state_ref, emit_ref = rest
        rank = jax.lax.broadcasted_iota(_I32, (g, s), 1)
        lane_ok = rank < count[:, 0][:, None]

        if winv is not None:
            digits = _decode_tile_windowed(
                rank, base, winv, radix, m, g, s, k_opts
            )
        else:
            decode = _decode_tile_radix2 if k_opts == 1 else _decode_tile
            digits = decode(rank, base, radix, m, g, s)
        chosen = [d > 0 for d in digits]
        chosen_i = [c.astype(_I32) for c in chosen]
        chosen_count = jnp.zeros((g, s), _I32)
        for c in chosen_i:
            chosen_count = chosen_count + c

        # --- per-slot selected value word/length (K-way compare select) --
        val_w = []
        val_l = []
        for sl in range(m):
            vw = jnp.zeros((g, s), _U32)
            vl = jnp.zeros((g, s), _I32)
            for k in range(k_opts):
                # K=1: digit 1 is the only option — `chosen` IS the select.
                sel = chosen[sl] if k_opts == 1 else digits[sl] == (k + 1)
                vw = jnp.where(sel, vopt[:, sl, k][:, None], vw)
                vl = jnp.where(sel, vlen[:, sl, k][:, None], vl)
            val_w.append(vw)
            val_l.append(vl)

        # --- unit scheme over original byte positions (splice-compare) ---
        # Match GEOMETRY is block-uniform: whether byte j is inside /
        # starts slot sl's span depends only on the block's (pos, mlen),
        # so the span compares are precomputed in XLA (`inside`/`start`
        # refs, [G, M, L] 0/1) and the per-lane work here is just
        # chosen-AND + accumulate (PERF.md §7 lever 1).
        wl = wlen[:, 0][:, None]  # loop-invariant: hoisted once
        clash = jnp.zeros((g, s), jnp.bool_)
        cum = jnp.zeros((g, s), _I32)
        unit_start = []
        unit_len = []
        unit_word = []  # u32 source: value word when started, else token byte
        for j in range(length_axis):
            cover = jnp.zeros((g, s), _I32)
            started = jnp.zeros((g, s), _I32)
            svw = jnp.zeros((g, s), _U32)
            svl = jnp.zeros((g, s), _I32)
            for sl in range(m):
                ins = inside[:, sl, j][:, None]
                cover = cover + chosen_i[sl] * ins
                at_start = chosen_i[sl] * start[:, sl, j][:, None]
                started = started | at_start
                at_b = at_start > 0
                svw = jnp.where(at_b, val_w[sl], svw)
                svl = jnp.where(at_b, val_l[sl], svl)
            clash = clash | (cover > 1)
            in_word = j < wl
            is_start = started > 0
            ul = jnp.where(
                in_word,
                jnp.where(is_start, svl,
                          jnp.where(cover > 0, 0, 1)),
                0,
            )
            tok_j = tok[:, j][:, None].astype(_U32)
            unit_start.append(cum)
            unit_len.append(ul)
            unit_word.append(jnp.where(is_start, svw, tok_j))
            cum = cum + ul
        out_len = cum

        # --- message build + compression (shared helpers) ---------------
        # The terminator lands after the data (within bounds for emitted
        # lanes; clash lanes may exceed — garbage words, masked).
        state = _grouped_hash_units(
            algo, unit_start, unit_len, unit_word, out_len, g, s,
            max_val_len=max_val_len, out_width=out_width,
        )
        for w_i, sw in enumerate(state):
            state_ref[:, w_i, :] = sw

        emit = (
            lane_ok
            & ~clash
            & (chosen_count >= min_substitute)
            & (chosen_count <= max_substitute)
        )
        emit_ref[:, :] = emit.astype(_I32)

    return kernel


def _validate_geometry(blk_word, block_stride: int, num_lanes: int) -> int:
    """Shared launch-shape checks for both fused wrappers; returns NB."""
    nb = blk_word.shape[0]
    if nb * block_stride != num_lanes:
        raise ValueError(
            f"fused kernel needs num_lanes == blocks * stride, got "
            f"{num_lanes} != {nb} * {block_stride}"
        )
    if nb % _G:
        # grid = nb // _G would silently skip the trailing blocks, leaving
        # their state/emit rows uninitialized output memory.
        raise ValueError(
            f"fused kernel needs the block count divisible by {_G} "
            f"(blocks per grid step), got {nb}"
        )
    return nb


def _pack_val_options(val_bytes, val_len, vstart_b, k_opts: int):
    """Per-(block, slot, option) value words/lengths: each <=4-byte table
    value packs little-endian into one u32; option k of a slot lives at CSR
    row ``vstart + k`` (clipped — digits never select past the radix)."""
    vw = val_bytes.shape[1]
    val_word = jnp.zeros((val_bytes.shape[0],), _U32)
    for k in range(vw):
        val_word = val_word | (
            val_bytes[:, k].astype(_U32) << _U32(8 * k)
        )
    k_idx = jnp.arange(k_opts, dtype=_I32)[None, None, :]
    opt_rows = jnp.clip(
        vstart_b[:, :, None] + k_idx, 0, val_bytes.shape[0] - 1
    )
    return val_word[opt_rows], val_len[opt_rows]


def _launch_fused(kernel, inputs, *, nb, stride, num_lanes, n_state,
                  interpret, pair: bool = False):
    """Shared pallas_call epilogue for both fused wrappers: G-row block
    specs derived from each input's trailing shape, (state, emit) outputs
    reshaped to the flat lane contract. ``n_state`` = hash state words
    (4 for MD5/MD4/NTLM, 5 for SHA-1).  ``pair``: the pair-lane tier
    (PERF.md §24) — each lane yields TWO candidates, so the output lane
    axis doubles (candidate ``2r + p`` at row ``2r + p``)."""
    from jax.experimental import pallas as pl

    mult = 2 if pair else 1
    s_out = stride * mult

    def row_spec(trail):
        return pl.BlockSpec(
            (_G,) + tuple(trail), lambda i: (i,) + (0,) * len(trail)
        )

    # Inside shard_map the outputs vary over whatever mesh axes the
    # inputs vary over (the per-device block batches) — shard_map's
    # check_vma rejects a bare ShapeDtypeStruct there, so propagate the
    # union of the inputs' varying axes explicitly.  Older JAX (< 0.6)
    # has neither jax.typeof nor the vma field; its shard_map tracks
    # replication differently, so a plain ShapeDtypeStruct is correct
    # there.
    typeof = getattr(jax, "typeof", None)
    if typeof is not None:
        vma = frozenset()
        for x in inputs:
            vma = vma | getattr(typeof(x), "vma", frozenset())
        out_shape = [
            jax.ShapeDtypeStruct((nb, n_state, s_out), jnp.uint32,
                                 vma=vma),
            jax.ShapeDtypeStruct((nb, s_out), jnp.int32, vma=vma),
        ]
    else:
        out_shape = [
            jax.ShapeDtypeStruct((nb, n_state, s_out), jnp.uint32),
            jax.ShapeDtypeStruct((nb, s_out), jnp.int32),
        ]

    state, emit = pl.pallas_call(
        kernel,
        grid=(nb // _G,),
        in_specs=[row_spec(x.shape[1:]) for x in inputs],
        out_specs=[row_spec((n_state, s_out)), row_spec((s_out,))],
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    if pair:
        # The kernel writes members into contiguous halves of the
        # doubled lane axis; interleave to candidate-rank order
        # (row 2r + p) here, outside the budget-counted kernel.
        state = jnp.stack(
            [state[..., :stride], state[..., stride:]], axis=-1
        ).transpose(0, 2, 3, 1).reshape(num_lanes * mult, n_state)
        emit = jnp.stack(
            [emit[:, :stride], emit[:, stride:]], axis=-1
        ).reshape(num_lanes * mult) > 0
        return state, emit
    state = state.transpose(0, 2, 1).reshape(num_lanes, n_state)
    emit = emit.reshape(num_lanes) > 0
    return state, emit


def _piece_tables(pieces, pre, blk_word):
    """Per-block piece tables for the piece kernels: device copies from
    ``pre`` (``piece_arrays`` — shipped once per sweep) when present,
    else the schema's own host arrays (trace-time constants; the harness
    and direct calls).  Returns the ref tuple in kernel order — the u32
    ``gw`` block rows, the u16 ``gw16`` rows, then the sliced ``gl``
    lengths (each omitted when the schema has no groups in that table;
    all-fixed schemas ship no ``gl`` at all, PERF.md §19)."""
    if pre is not None and any(k in pre for k in ("pl", "pw", "pw16")):
        gw_all = pre.get("pw")
        gw16_all = pre.get("pw16")
        gl_all = pre.get("pl")
    else:
        gw_all = None if pieces.gw is None else jnp.asarray(pieces.gw)
        gw16_all = (
            None if pieces.gw16 is None else jnp.asarray(pieces.gw16)
        )
        gl_all = None if pieces.gl is None else jnp.asarray(pieces.gl)
    tabs = ()
    if gw_all is not None:
        tabs += (gw_all[blk_word],)
    if gw16_all is not None:
        tabs += (gw16_all[blk_word],)
    if gl_all is not None:
        tabs += (gl_all[blk_word].astype(_I32),)
    return tabs


@audited_entry(
    "ops.fused_expand_md5",
    kind="pallas_kernel",
    budget_keys=("scalar", "scalar-solo", "sha1", "general",
                 "2-hash-block", "ntlm"),
)
def fused_expand_md5(
    tokens: jnp.ndarray,  # uint8 [B, L] — plan token matrix
    lengths: jnp.ndarray,  # int32 [B]
    match_pos: jnp.ndarray,  # int32 [B, M]
    match_len: jnp.ndarray,  # int32 [B, M]
    match_radix: jnp.ndarray,  # int32 [B, M]
    match_val_start: jnp.ndarray,  # int32 [B, M]
    val_bytes: jnp.ndarray,  # uint8 [V, VW<=4]
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, M]
    blk_count: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
    block_stride: int,
    k_opts: int,
    algo: str = "md5",
    win_v: "jnp.ndarray | None" = None,  # int32 [B, M+1, K2] (windowed)
    scalar_units: bool = False,
    pre: "dict | None" = None,  # scalar_units_fields device arrays
    pieces=None,  # packing.PieceSchema — per-slot emission (PERF.md §17)
    interpret: bool = False,
    pair: bool = False,  # pair-lane tier (K=2, PERF.md §24)
):
    """Fused decode+splice+hash for a fixed-stride launch.

    ``pair`` (gate via :func:`pair_for_config`): the pair-lane tier —
    blocks cover ``2 * block_stride`` candidate ranks on
    ``block_stride`` lanes (``blk_count`` counts candidates), and the
    returned arrays have ``2 * num_lanes`` candidate rows, member ``p``
    of lane ``r`` at row ``2r + p``.

    Returns ``(state uint32[N, K], emit bool[N])`` (K =
    ``DIGEST_WORDS[algo]``) — the same contract as ``expand_matches`` +
    ``ops.hashes.HASH_FNS[algo]`` restricted to what the crack step
    consumes. Callers must have checked :func:`eligible`.  ``win_v``
    (count-windowed plans) switches the in-kernel decode to the
    suffix-count DP walk; block base cursors are then scalar ranks.
    ``scalar_units`` (host-gated via :func:`scalar_units_for`) selects the
    K=1 fast kernel (PERF.md §11) for full-enumeration launches;
    ``pre`` (the device copy of :func:`scalar_units_fields`) replaces the
    in-trace [NB, M, L] precompute with word-row gathers (PERF.md §12).
    """
    interpret = interpret or _interpret_by_env()
    nb = _validate_geometry(blk_word, block_stride, num_lanes)
    m = match_pos.shape[1]
    length_axis = tokens.shape[1]
    if pair and (pieces is None or not pieces.pair_ok
                 or win_v is not None):
        raise ValueError(
            "pair=True needs a pair-eligible PieceSchema and full "
            "enumeration; gate via pair_for_config"
        )

    if pieces is not None:
        # Per-slot piece emission (PERF.md §17): the whole byte-position
        # scan is replaced by the schema's precomputed group tables.
        scalar = bool(scalar_units) and k_opts == 1
        tabs = _piece_tables(pieces, pre, blk_word)
        if scalar and win_v is None:
            if pre is not None and "weight" in pre:
                pbase = jnp.sum(
                    blk_base * pre["weight"][blk_word], axis=1
                )[:, None]
            else:
                _, _, _, pbase = _scalar_units_prelude(
                    match_radix[blk_word], blk_base
                )
            inputs = (blk_count[:, None], pbase) + tabs
        else:
            inputs = (blk_count[:, None], blk_base,
                      match_radix[blk_word])
            if win_v is not None:
                inputs = inputs + (win_v[blk_word],)
            inputs = inputs + tabs
        kernel = _make_piece_kernel(
            g=_G, s=block_stride, kind="match", schema=pieces,
            num_slots=m, k_opts=k_opts, out_width=out_width,
            min_substitute=min_substitute, max_substitute=max_substitute,
            algo=algo, scalar=scalar, windowed=win_v is not None,
            pair=pair,
        )
        return _launch_fused(
            kernel, inputs, nb=nb, stride=block_stride,
            num_lanes=num_lanes, n_state=DIGEST_WORDS[algo],
            interpret=interpret, pair=pair,
        )
    if pair:
        raise ValueError(
            "pair=True requires the per-slot piece emission tier "
            "(pieces); the byte-scan kernels keep K=1"
        )

    # Block-level gathers (NB rows — the cheap granularity): per-block word
    # fields and per-(block, slot, option) packed value words.
    tok_b = tokens[blk_word].astype(_I32)  # [NB, L]
    wlen_b = lengths[blk_word][:, None]  # [NB, 1]
    pos_b = match_pos[blk_word]  # [NB, M]
    mlen_b = match_len[blk_word]
    radix_b = match_radix[blk_word]
    count_b = blk_count[:, None]  # [NB, 1]
    vopt_b, vlen_b = _pack_val_options(
        val_bytes, val_len, match_val_start[blk_word], k_opts
    )
    # Block-uniform span masks ([NB, M, L] 0/1): byte j inside / starting
    # slot sl's match span — hoists the kernel's per-(byte, slot) span
    # compares out to XLA (PERF.md §7 lever 1).
    jj = jnp.arange(length_axis, dtype=jnp.int32)[None, None, :]
    ps = pos_b[:, :, None]
    inside_b = ((jj >= ps) & (jj < ps + mlen_b[:, :, None])).astype(_I32)
    start_b = (jj == ps).astype(_I32)

    if scalar_units and k_opts == 1:
        # A bypassed scalar_units_for gate must raise, not silently
        # corrupt the packed startp encode (checked host-side when the
        # match arrays are concrete).
        _check_scalar_units_gate(
            scalar_units, match_pos, match_len, match_radix
        )
        # K=1 scalar-units fast path (PERF.md §11): pack each active
        # slot's chosen bit at its active-rank position; per-byte
        # coverage / start / value fields become block-uniform [NB, L]
        # arrays (the host gate guarantees at most one start per
        # position).
        single = scalar_units == "single"
        if pre is not None:
            # Word-level fields precomputed once per sweep
            # (scalar_units_fields): the launch prep is row gathers.
            bitpos = pre["bitpos"][blk_word]
            pbase = jnp.sum(
                blk_base * pre["weight"][blk_word], axis=1
            )[:, None]
            # Per-byte fields ship u8 (hashmob-scale memory); widen
            # after the block gather.
            startp = pre["startp"][blk_word].astype(_I32)
            svl_j = pre["svl"][blk_word].astype(_I32)
            svw_j = pre["svw"][blk_word]
            ins_bits = None if single else pre["ins_bits"][blk_word]
        else:
            act, bitpos, weight, pbase = _scalar_units_prelude(
                radix_b, blk_base
            )
            stt = start_b * act[:, :, None]  # [NB, M, L], <=1 slot per j
            startp = jnp.sum(stt * (bitpos + 1)[:, :, None], axis=1)
            startp = jnp.where(startp == 0, 31, startp - 1)
            svl_j = jnp.sum(stt * vlen_b[:, :, 0][:, :, None], axis=1)
            svw_j = jnp.sum(
                stt.astype(_U32) * vopt_b[:, :, 0][:, :, None], axis=1
            )
            ins_bits = None if single else jnp.sum(
                inside_b * weight[:, :, None], axis=1
            )
        if win_v is None:  # full enumeration: cb = packed base + rank
            head = (tok_b, wlen_b, count_b, pbase)
        else:  # windowed: DP decode in-kernel, bits packed via bitpos
            head = (tok_b, wlen_b, count_b, blk_base, win_v[blk_word],
                    radix_b, bitpos)
        if single:  # one-byte spans: coverage == start, no clash ref
            inputs = head + (startp, svl_j, svw_j)
        else:
            inputs = head + (ins_bits, startp, svl_j, svw_j)
        return _launch_scalar_units(
            "match", inputs,
            block_stride=block_stride, length_axis=length_axis,
            out_width=out_width, min_substitute=min_substitute,
            max_substitute=max_substitute, algo=algo, nb=nb,
            num_lanes=num_lanes, interpret=interpret,
            max_val_len=int(val_bytes.shape[1]), single_span=single,
            windowed=win_v is not None,
            num_slots=None if win_v is None else m,
        )

    kernel = _make_kernel(
        g=_G, s=block_stride, m=m, length_axis=length_axis, k_opts=k_opts,
        out_width=out_width, min_substitute=min_substitute,
        max_substitute=max_substitute, algo=algo,
        win_k2=None if win_v is None else int(win_v.shape[2]),
        max_val_len=int(val_bytes.shape[1]),
    )
    inputs = [tok_b, wlen_b, radix_b, blk_base, count_b,
              inside_b, start_b]
    if win_v is not None:
        inputs.append(win_v[blk_word])
    inputs += [vopt_b, vlen_b]
    return _launch_fused(
        kernel,
        tuple(inputs),
        nb=nb, stride=block_stride, num_lanes=num_lanes,
        n_state=DIGEST_WORDS[algo], interpret=interpret,
    )


def _make_suball_kernel(
    *, g: int, s: int, p: int, length_axis: int,
    k_opts: int, out_width: int, min_substitute: int, max_substitute: int,
    algo: str = "md5", win_k2: "int | None" = None,
    max_val_len: int = 4, close_s: "int | None" = None,
):
    """Per-step kernel body for substitute-all plans (``-s`` / ``-s -r``).

    Segment geometry is per-BLOCK data ((G, 1) tiles — cheap), only the
    chosen/skip digit of a segment's pattern slot is per-lane. Per original
    byte position: the first byte of a CHOSEN pattern segment emits the
    selected value's bytes, its other bytes emit nothing, and every other
    in-word byte passes through — exactly ``ops.expand_suball``'s segment
    cumsum, re-expressed per position so the shared unit/message helpers
    apply. No overlap/clash concept exists here (plans pre-resolve spans;
    non-closable hazard words never reach the device).

    Ref shapes per grid step: tok[G, L] i32, wlen[G, 1] i32,
    pradix[G, P] i32, base[G, P] i32, count[G, 1] i32, slotat[G, L] i32
    (pattern slot owning byte j, -1 free), startat[G, L] i32 (its span
    start), vopt[G, P, K] u32, vlen[G, P, K] i32.
    Outputs: state[G, KS, S] u32 (KS = DIGEST_WORDS[algo]), emit[G, S] i32.

    ``close_s`` (cascade-closed plans, ``expand_suball`` closure): static
    successor-axis width; adds two refs after vlen — cnext[G, P, S] i32
    (successor slot of each pattern slot, -1 inactive) and
    cmul[G, P, S+1] i32 (joint value index multipliers, col 0 = own
    digit's) — and the K-way value select runs on the JOINT index
    ``(d-1)*mul0 + Σ d_succ*mul_s`` instead of ``d-1``. None (every
    non-closed plan) traces the exact pre-closure kernel.
    """
    assert 0 < out_width and _hash_blocks_for(
        out_width, 2 if algo == "ntlm" else 1
    ) <= _MAX_HASH_BLOCKS, out_width

    def kernel(tok, wlen, pradix, base, count, slotat, startat,
               *rest):
        rest = list(rest)
        winv = rest.pop(0) if win_k2 is not None else None
        vopt, vlen = rest[0], rest[1]
        rest = rest[2:]
        if close_s is not None:
            cnext, cmul = rest[0], rest[1]
            rest = rest[2:]
        state_ref, emit_ref = rest
        rank = jax.lax.broadcasted_iota(_I32, (g, s), 1)
        lane_ok = rank < count[:, 0][:, None]

        if winv is not None:
            digits = _decode_tile_windowed(
                rank, base, winv, pradix, p, g, s, k_opts
            )
        else:
            decode = _decode_tile_radix2 if k_opts == 1 else _decode_tile
            digits = decode(rank, base, pradix, p, g, s)
        chosen_count = jnp.zeros((g, s), _I32)
        for sl in range(p):
            active = pradix[:, sl][:, None] > 1
            chosen_count = chosen_count + (
                active & (digits[sl] > 0)
            ).astype(_I32)

        # Cascade closure: per-slot JOINT value index over the slot's own
        # and its successors' digits. Successor digits resolve through an
        # unrolled compare-select (cnext is per-block data; `digits` is a
        # static list) — only traced for closed plans.
        if close_s is not None:
            joint = []
            for sl in range(p):
                acc = (digits[sl] - 1) * cmul[:, sl, 0][:, None]
                for s_i in range(close_s):
                    nt = cnext[:, sl, s_i][:, None]  # [G, 1]
                    ds = jnp.zeros((g, s), _I32)
                    # Successors are always LATER slots (sorted-pattern
                    # order), so the compare-select only spans sl+1..p-1.
                    for t2 in range(sl + 1, p):
                        ds = jnp.where(nt == t2, digits[t2], ds)
                    acc = acc + ds * cmul[:, sl, 1 + s_i][:, None]
                joint.append(acc)

        # Per-slot selected value word/length (K-way compare select).
        val_w = []
        val_l = []
        for sl in range(p):
            vw = jnp.zeros((g, s), _U32)
            vl = jnp.zeros((g, s), _I32)
            for k in range(k_opts):
                # K=1: digit 1 is the only option (radix-1 slots always
                # decode 0, so `> 0` is safe for padded slots too).
                if close_s is not None:
                    sel = (digits[sl] > 0) & (joint[sl] == k)
                elif k_opts == 1:
                    sel = digits[sl] > 0
                else:
                    sel = digits[sl] == (k + 1)
                vw = jnp.where(sel, vopt[:, sl, k][:, None], vw)
                vl = jnp.where(sel, vlen[:, sl, k][:, None], vl)
            val_w.append(vw)
            val_l.append(vl)

        # Per-position segment resolution: block-uniform, so the whole
        # (position, segment) scan is precomputed in XLA — ``slotat`` /
        # ``startat`` [G, L] give the pattern slot owning byte j (-1 free)
        # and its span start (PERF.md §7 lever 1).
        wl = wlen[:, 0][:, None]  # loop-invariant: hoisted once
        unit_start = []
        unit_len = []
        unit_word = []
        cum = jnp.zeros((g, s), _I32)
        for j in range(length_axis):
            slot_at_j = slotat[:, j][:, None]
            start_at_j = startat[:, j][:, None]
            # Lane-level: the digit / value of the slot owning position j.
            digit_at_j = jnp.zeros((g, s), _I32)
            vw_at_j = jnp.zeros((g, s), _U32)
            vl_at_j = jnp.zeros((g, s), _I32)
            for sl in range(p):
                here = slot_at_j == sl
                digit_at_j = jnp.where(here, digits[sl], digit_at_j)
                vw_at_j = jnp.where(here, val_w[sl], vw_at_j)
                vl_at_j = jnp.where(here, val_l[sl], vl_at_j)
            chosen_here = (slot_at_j >= 0) & (digit_at_j > 0)
            is_start = chosen_here & (j == start_at_j)
            in_word = j < wl
            ul = jnp.where(
                in_word,
                jnp.where(is_start, vl_at_j,
                          jnp.where(chosen_here, 0, 1)),
                0,
            )
            tok_j = tok[:, j][:, None].astype(_U32)
            unit_start.append(cum)
            unit_len.append(ul)
            unit_word.append(jnp.where(is_start, vw_at_j, tok_j))
            cum = cum + ul
        out_len = cum

        state = _grouped_hash_units(
            algo, unit_start, unit_len, unit_word, out_len, g, s,
            max_val_len=max_val_len, out_width=out_width,
        )
        for w_i, sw in enumerate(state):
            state_ref[:, w_i, :] = sw

        emit = (
            lane_ok
            & (chosen_count >= min_substitute)
            & (chosen_count <= max_substitute)
        )
        emit_ref[:, :] = emit.astype(_I32)

    return kernel


@audited_entry(
    "ops.fused_expand_suball_md5",
    kind="pallas_kernel",
    budget_keys=("suball",),
)
def fused_expand_suball_md5(
    tokens: jnp.ndarray,  # uint8 [B, L] — plan token matrix
    lengths: jnp.ndarray,  # int32 [B]
    pat_radix: jnp.ndarray,  # int32 [B, P]
    pat_val_start: jnp.ndarray,  # int32 [B, P]
    seg_orig_start: jnp.ndarray,  # int32 [B, GS]
    seg_orig_len: jnp.ndarray,  # int32 [B, GS]
    seg_pat: jnp.ndarray,  # int32 [B, GS]
    val_bytes: jnp.ndarray,  # uint8 [V, VW<=4]
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, P]
    blk_count: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
    block_stride: int,
    k_opts: int,
    algo: str = "md5",
    win_v: "jnp.ndarray | None" = None,  # int32 [B, P+1, K2] (windowed)
    scalar_units: bool = False,
    pre: "dict | None" = None,  # scalar_units_fields device arrays
    pieces=None,  # packing.PieceSchema — per-slot emission (PERF.md §17)
    interpret: bool = False,
    close_next: "jnp.ndarray | None" = None,  # int32 [B, P, S] (closure)
    close_mul: "jnp.ndarray | None" = None,  # int32 [B, P, S+1]
    pair: bool = False,  # pair-lane tier (K=2, PERF.md §24)
):
    """Fused decode+splice+hash for substitute-all fixed-stride launches.

    Same contract as :func:`fused_expand_md5` (including the ``win_v``
    count-windowed decode and the K=1 ``scalar_units`` fast path —
    non-closed substitute-all plans qualify unconditionally, segments are
    disjoint); callers must have checked :func:`eligible` with the plan's
    ``num_segments``.  ``close_next`` / ``close_mul`` (cascade-closed
    plans): per-slot joint value addressing — ``val_bytes`` must then be
    the plan's extended ``cval_bytes`` and ``k_opts`` its
    :func:`k_vals_for` width; closed plans never take the scalar-units
    path (``scalar_units_for`` returns False for them).
    """
    interpret = interpret or _interpret_by_env()
    nb = _validate_geometry(blk_word, block_stride, num_lanes)
    p = pat_radix.shape[1]
    gs = seg_pat.shape[1]
    length_axis = tokens.shape[1]
    if close_next is not None and scalar_units:
        raise ValueError(
            "cascade-closed plans cannot take the scalar-units kernel "
            "(joint value tables are per-lane, not block-uniform); gate "
            "via scalar_units_for(plan)"
        )
    if pair and (
        pieces is None or not pieces.pair_ok or win_v is not None
        or close_next is not None
    ):
        raise ValueError(
            "pair=True needs a pair-eligible PieceSchema, full "
            "enumeration, and no cascade closure; gate via "
            "pair_for_config"
        )

    if pieces is not None:
        # Per-slot piece emission (PERF.md §17): segments ARE the pieces;
        # gap segments fold into the schema's literal prefixes.
        scalar = bool(scalar_units) and k_opts == 1
        tabs = _piece_tables(pieces, pre, blk_word)
        if scalar:
            if pre is not None and "sbit" in pre:
                selbit_b = pre["sbit"][blk_word].astype(_I32)
            else:
                selbit_b = jnp.asarray(
                    pieces.sel_bit
                )[blk_word].astype(_I32)
        inputs = (blk_count[:, None],)
        if scalar and win_v is None:
            if pre is not None and "weight" in pre:
                pbase = jnp.sum(
                    blk_base * pre["weight"][blk_word], axis=1
                )[:, None]
            else:
                _, _, _, pbase = _scalar_units_prelude(
                    pat_radix[blk_word], blk_base
                )
            inputs += (pbase, selbit_b)
        elif scalar:
            if pre is not None and "bitpos" in pre:
                bitpos_b = pre["bitpos"][blk_word]
            else:
                _, bitpos_b, _, _ = _scalar_units_prelude(
                    pat_radix[blk_word], blk_base
                )
            inputs += (blk_base, pat_radix[blk_word], win_v[blk_word],
                       bitpos_b, selbit_b)
        else:
            if pre is not None and "sslot" in pre:
                selslot_b = pre["sslot"][blk_word]
            else:
                selslot_b = jnp.asarray(
                    pieces.sel_slot
                )[blk_word].astype(_I32)
            inputs += (blk_base, pat_radix[blk_word])
            if win_v is not None:
                inputs += (win_v[blk_word],)
            inputs += (selslot_b,)
            if close_next is not None:
                inputs += (close_next[blk_word], close_mul[blk_word])
        inputs += tabs
        kernel = _make_piece_kernel(
            g=_G, s=block_stride, kind="suball", schema=pieces,
            num_slots=p, k_opts=k_opts, out_width=out_width,
            min_substitute=min_substitute, max_substitute=max_substitute,
            algo=algo, scalar=scalar, windowed=win_v is not None,
            close_s=(None if close_next is None
                     else int(close_next.shape[2])),
            pair=pair,
        )
        return _launch_fused(
            kernel, inputs, nb=nb, stride=block_stride,
            num_lanes=num_lanes, n_state=DIGEST_WORDS[algo],
            interpret=interpret, pair=pair,
        )
    if pair:
        raise ValueError(
            "pair=True requires the per-slot piece emission tier "
            "(pieces); the byte-scan kernels keep K=1"
        )

    tok_b = tokens[blk_word].astype(_I32)
    wlen_b = lengths[blk_word][:, None]
    pradix_b = pat_radix[blk_word]
    sstart_b = seg_orig_start[blk_word]
    slen_b = seg_orig_len[blk_word]
    spat_b = seg_pat[blk_word]
    count_b = blk_count[:, None]
    vopt_b, vlen_b = _pack_val_options(
        val_bytes, val_len, pat_val_start[blk_word], k_opts
    )
    # Precompute the per-position segment resolution in XLA (segments are
    # disjoint, block-uniform): slotat[NB, L] = pattern slot owning byte
    # j (-1 free), startat[NB, L] = that segment's span start.
    if gs:
        jj = jnp.arange(length_axis, dtype=jnp.int32)[None, None, :]
        st3 = sstart_b[:, :, None]
        covered = (
            (slen_b[:, :, None] > 0) & (jj >= st3)
            & (jj < st3 + slen_b[:, :, None])
        )  # [NB, GS, L]
        slotat_b = jnp.where(covered, spat_b[:, :, None], -1).max(axis=1)
        startat_b = jnp.where(covered, st3, 0).max(axis=1)
    else:  # no segments: every byte passes through
        slotat_b = jnp.full((nb, length_axis), -1, jnp.int32)
        startat_b = jnp.zeros((nb, length_axis), jnp.int32)

    if scalar_units and k_opts == 1:
        # K=1 scalar-units fast path (PERF.md §11): the owning pattern
        # slot's chosen bit sits at its active-rank position; per-byte
        # fields resolve to block-uniform [NB, L] arrays via the
        # already-computed segment ownership (``slotat_b``/``startat_b``).
        if pre is not None:  # word-level fields: launch prep is gathers
            bitpos = pre["bitpos"][blk_word]
            pbase = jnp.sum(
                blk_base * pre["weight"][blk_word], axis=1
            )[:, None]
            ownbit = pre["ownbit"][blk_word].astype(_I32)
            isstart = pre["isstart"][blk_word].astype(_I32)
            svl_j = pre["svl"][blk_word].astype(_I32)
            svw_j = pre["svw"][blk_word]
        else:
            act, bitpos, _, pbase = _scalar_units_prelude(
                pradix_b, blk_base
            )
            sl_clip = jnp.clip(slotat_b, 0, p - 1)
            owned = slotat_b >= 0
            own_act = jnp.take_along_axis(act, sl_clip, axis=1) > 0
            ownbit = jnp.where(
                owned & own_act,
                jnp.take_along_axis(bitpos, sl_clip, axis=1),
                31,
            )
            jj2 = jnp.arange(length_axis, dtype=jnp.int32)[None, :]
            isstart = (owned & (startat_b == jj2)).astype(_I32)
            svl_j = jnp.where(
                owned,
                jnp.take_along_axis(vlen_b[:, :, 0], sl_clip, axis=1), 0
            )
            svw_j = jnp.where(
                owned,
                jnp.take_along_axis(vopt_b[:, :, 0], sl_clip, axis=1),
                _U32(0),
            )
        if win_v is None:
            head = (tok_b, wlen_b, count_b, pbase)
        else:
            head = (tok_b, wlen_b, count_b, blk_base, win_v[blk_word],
                    pradix_b, bitpos)
        return _launch_scalar_units(
            "suball",
            head + (ownbit, isstart, svl_j, svw_j),
            block_stride=block_stride, length_axis=length_axis,
            out_width=out_width, min_substitute=min_substitute,
            max_substitute=max_substitute, algo=algo, nb=nb,
            num_lanes=num_lanes, interpret=interpret,
            max_val_len=int(val_bytes.shape[1]),
            windowed=win_v is not None,
            num_slots=None if win_v is None else p,
        )

    kernel = _make_suball_kernel(
        g=_G, s=block_stride, p=p,
        length_axis=length_axis, k_opts=k_opts, out_width=out_width,
        min_substitute=min_substitute, max_substitute=max_substitute,
        algo=algo,
        win_k2=None if win_v is None else int(win_v.shape[2]),
        max_val_len=int(val_bytes.shape[1]),
        close_s=None if close_next is None else int(close_next.shape[2]),
    )
    inputs = [tok_b, wlen_b, pradix_b, blk_base, count_b, slotat_b,
              startat_b]
    if win_v is not None:
        inputs.append(win_v[blk_word])
    inputs += [vopt_b, vlen_b]
    if close_next is not None:
        inputs += [close_next[blk_word], close_mul[blk_word]]
    return _launch_fused(
        kernel,
        tuple(inputs),
        nb=nb, stride=block_stride, num_lanes=num_lanes,
        n_state=DIGEST_WORDS[algo], interpret=interpret,
    )

"""On-device digest-set membership: bitmap prefilter + lexicographic search.

The reference never hashes — it streams candidates to stdout and lets hashcat
do lookup (reference ``README.MD:69``, which tunes hashcat's ``--bitmap-max``).
This module is the TPU-side analog of hashcat's matching stage (SURVEY.md §7
step 5): the target digest list lives on device as a **row-sorted uint32
matrix**, candidates' digests are tested in bulk, and only hits ever reach the
host.

Two stages, both branch-free and batch-vectorized:

1. **Bitmap prefilter** (hashcat-style): a bit array of size ``2^bitmap_bits``
   indexed by the digest's low bits. One gather + mask per candidate rejects
   the overwhelming majority of misses before any search. The bitmap is
   ``uint32[2^bitmap_bits / 32]``.
2. **Lexicographic binary search** over the sorted digest rows, comparing all
   K state words (no truncation, no false positives). The loop is a fixed
   ``ceil(log2 D)``-step ``lax.fori_loop`` — compiled once per digest-set
   size, all candidates advance in lockstep.

Digests are compared as tuples of uint32 *state words* (the natural output of
``ops.hashes``) — sort order is an internal detail, consistent between
:func:`build_digest_set` and the device search.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..audit import audited_entry
from .hashes import BIG_ENDIAN_DIGEST, DIGEST_WORDS, digest_to_words


def _bulk_rows(digests, algo: str, k: int) -> "np.ndarray | None":
    """Vectorized digest->uint32-row conversion for the common case — a
    uniform list of raw ``bytes`` (or an ``[N, 4k] uint8`` matrix from the
    CLI's vectorized left-list parser).  Hashmob-scale lists (tens of
    millions of digests) make the per-item ``digest_to_words`` loop a
    minutes-long startup cost; one join + frombuffer is ~50x faster.
    Returns None when the input needs the per-item path."""
    order = ">u4" if BIG_ENDIAN_DIGEST[algo] else "<u4"
    if isinstance(digests, np.ndarray):
        if digests.ndim != 2 or digests.dtype != np.uint8 \
                or digests.shape[1] != 4 * k:
            return None
        return (
            np.ascontiguousarray(digests).reshape(-1).view(order)
            .astype(np.uint32).reshape(-1, k)
        )
    if not digests:
        return np.zeros((0, k), dtype=np.uint32)
    width = 4 * k
    if not all(type(d) is bytes and len(d) == width for d in digests):
        return None
    blob = b"".join(digests)
    return (
        np.frombuffer(blob, dtype=order).astype(np.uint32).reshape(-1, k)
    )

_U32 = jnp.uint32

#: Default bitmap size: 2^24 bits = 2 MiB — comfortably VMEM/HBM-cheap and
#: <0.1% false-positive density for digest lists up to ~1e6 entries.
DEFAULT_BITMAP_BITS = 24


@dataclass(frozen=True)
class DigestSet:
    """A target digest list in device-ready, sorted, prefiltered form."""

    rows: np.ndarray  # uint32 [D, K] — row-sorted lexicographically
    bitmap: np.ndarray  # uint32 [2^bits / 32]
    bitmap_bits: int
    algo: str

    @property
    def size(self) -> int:
        return int(self.rows.shape[0])


def auto_bitmap_bits(n: int) -> int:
    """The default prefilter sizing for an ``n``-digest set:
    ``ceil(log2 n) + 10`` bits (≈0.1% false-positive density) clamped to
    [16, DEFAULT_BITMAP_BITS].  Exposed so the cross-job fuse layer can
    pick ONE common width for its stacked per-segment bitmaps
    (PERF.md §22) without re-deriving the formula."""
    import math

    return min(
        DEFAULT_BITMAP_BITS, max(16, math.ceil(math.log2(max(n, 2))) + 10)
    )


def build_digest_set(
    digests: Iterable,
    algo: str,
    *,
    bitmap_bits: int | None = None,
) -> DigestSet:
    """Compile raw/hex digests into a :class:`DigestSet`.

    Accepts raw ``bytes``, hex strings (hashcat left-list lines), or an
    ``[N, digest_bytes] uint8`` matrix (the CLI's vectorized parser).
    Duplicate digests are collapsed — membership is a set question,
    multiplicity lives on the candidate side (Q7).

    ``bitmap_bits=None`` sizes the prefilter to the digest count:
    ``ceil(log2 D) + 10`` bits (≈0.1% false-positive density), clamped to
    [16, DEFAULT_BITMAP_BITS]. Small digest lists — the common crack-mode
    case — then get a bitmap that fits on-chip vector memory (2^16 bits =
    8 KiB, 2^20 = 128 KiB) instead of the fixed 2 MiB HBM-resident table,
    so every lane's stage-1 probe stops paying an HBM random-gather.
    """
    if not isinstance(digests, np.ndarray):
        digests = list(digests)
    if bitmap_bits is None:
        bitmap_bits = auto_bitmap_bits(len(digests))
    if bitmap_bits < 5:
        raise ValueError("bitmap_bits must be >= 5 (one uint32 word)")
    k = DIGEST_WORDS[algo]
    rows = _bulk_rows(digests, algo, k)
    if rows is None:
        # Per-item path: hex strings, mixed representations, odd widths.
        parsed = [digest_to_words(d, algo) for d in digests]
        if not parsed:
            rows = np.zeros((0, k), dtype=np.uint32)
        else:
            rows = np.stack(parsed).astype(np.uint32)
    # np.unique(axis=0) returns rows in lexicographic order, first column
    # most significant — exactly the device search's comparison order.
    if rows.shape[0]:
        rows = np.unique(rows, axis=0)

    bitmap = np.zeros((max(1, (1 << bitmap_bits) // 32),), dtype=np.uint32)
    if rows.shape[0]:
        idx = rows[:, 0] & np.uint32((1 << bitmap_bits) - 1)
        np.bitwise_or.at(bitmap, idx >> 5, np.uint32(1) << (idx & 31))
    return DigestSet(rows=rows, bitmap=bitmap, bitmap_bits=bitmap_bits, algo=algo)


class HostDigestLookup:
    """Host-side digest membership + the canonical sorted byte blob, over
    EITHER digest form — a list of raw ``bytes`` or an ``[N, W] uint8``
    matrix (the CLI's vectorized left-list parser).

    One object, one sort: the sweep fingerprint (``sorted_blob`` — the
    concatenation of the digests in ascending byte order, identical for
    both forms) and per-hit host membership (``in``) share it, so the
    matrix/list duality lives HERE and nowhere else.  Matrix form keeps a
    sorted void view (binary search, no Python set of tens of millions of
    bytes objects); list form keeps the plain set.
    """

    def __init__(self, digests):
        if isinstance(digests, np.ndarray) and digests.ndim == 2:
            a = np.ascontiguousarray(digests)
            self._width = int(a.shape[1])
            self._rows = np.sort(a.view(f"V{self._width}")[:, 0])
            self._set = None
            self._sorted_list = None
        else:
            lst = list(digests)
            self._rows = None
            self._set = set(lst)
            self._sorted_list = sorted(lst)
            self._width = len(lst[0]) if lst else 0

    def __len__(self) -> int:
        return (
            int(self._rows.shape[0]) if self._rows is not None
            else len(self._sorted_list)
        )

    def __contains__(self, dig: bytes) -> bool:
        if self._set is not None:
            return dig in self._set
        rows = self._rows
        if not rows.shape[0] or len(dig) != self._width:
            return False
        probe = np.frombuffer(dig, dtype=rows.dtype)[0]
        i = int(np.searchsorted(rows, probe))
        return i < rows.shape[0] and bool(rows[i] == probe)

    def sorted_blob(self) -> bytes:
        """Digests concatenated in ascending byte order — the fingerprint
        stream; void-row sort == ``sorted(list_of_bytes)``, so both forms
        of the same set produce identical bytes."""
        if self._rows is not None:
            return self._rows.tobytes()
        return b"".join(self._sorted_list)


def _row_cmp_le(probe: jnp.ndarray, row: jnp.ndarray) -> jnp.ndarray:
    """``row <= probe`` lexicographically; both ``uint32[..., K]``."""
    k = probe.shape[-1]
    lt = jnp.zeros(probe.shape[:-1], dtype=bool)
    eq = jnp.ones(probe.shape[:-1], dtype=bool)
    for i in range(k):
        lt = lt | (eq & (row[..., i] < probe[..., i]))
        eq = eq & (row[..., i] == probe[..., i])
    return lt | eq


def bitmap_probe(digest: jnp.ndarray, bitmap: jnp.ndarray) -> jnp.ndarray:
    """Stage-1 test: ``uint32[N, K] -> bool[N]`` (may have false positives).

    The bitmap's bit count is its (static) length × 32, so the index mask is
    derived from the array itself — callers can't mismatch it.
    """
    bitmap_bits = int(np.log2(bitmap.shape[0])) + 5
    idx = digest[:, 0] & _U32((1 << bitmap_bits) - 1)
    word = bitmap[idx >> _U32(5)]
    return (word >> (idx & _U32(31))) & _U32(1) != 0


@audited_entry("ops.digest_member", kind="integer_stage")
def digest_member(
    digest: jnp.ndarray,  # uint32 [N, K]
    rows: jnp.ndarray,  # uint32 [D, K] row-sorted
    bitmap: jnp.ndarray,  # uint32 [2^bits/32]
) -> jnp.ndarray:
    """Exact membership of each candidate digest: ``bool[N]``.

    All candidates run the bitmap probe; survivors' binary searches execute
    unconditionally (branch-free SIMD — the prefilter prunes *memory traffic*
    expectations, not instructions) and the final verdict ANDs both stages.
    """
    n, k = digest.shape
    d = rows.shape[0]
    if d == 0:
        return jnp.zeros((n,), dtype=bool)

    pre = bitmap_probe(digest, bitmap)

    steps = int(np.ceil(np.log2(max(d, 2)))) + 1
    # Invariant: rows[lo-1] <= probe < rows[hi] (virtual rows at -1/D); when
    # lo == hi the search has converged and further steps must not move it.
    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) >> 1
        le = _row_cmp_le(digest, rows[mid]) & (lo < hi)
        return jnp.where(le, mid + 1, lo), jnp.where(le, hi, mid)

    # Derive the carry init from the probe array (not fresh constants) so its
    # device-variance matches inside shard_map'd callers — fori_loop requires
    # carry input/output types to agree, including the varying-axes tag.
    lo0 = (digest[:, 0] & _U32(0)).astype(jnp.int32)
    hi0 = lo0 + d
    lo, _ = jax.lax.fori_loop(0, steps, body, (lo0, hi0))
    found = jnp.clip(lo - 1, 0, d - 1)
    exact = jnp.all(rows[found] == digest, axis=-1) & (lo > 0)
    return pre & exact


def digest_member_seg(
    digest: jnp.ndarray,  # uint32 [N, K]
    rows: jnp.ndarray,  # uint32 [D_total, K] — per-segment sorted runs
    bitmap: jnp.ndarray,  # uint32 [S, 2^bits/32] — one bitmap per segment
    row_lo: jnp.ndarray,  # int32 [S] — segment row range start (inclusive)
    row_hi: jnp.ndarray,  # int32 [S] — segment row range end (exclusive)
    seg: jnp.ndarray,  # int32 [N] — each lane's segment id
) -> jnp.ndarray:
    """Per-segment exact membership: ``bool[N]`` (PERF.md §22).

    The cross-job packed superstep fuses several tenants' lanes into one
    dispatch; each lane's digest must be tested against its OWN job's
    target set — testing against the union would flag cross-tenant
    false hits and break packed-vs-solo count parity.  ``rows`` is the
    jobs' sorted digest matrices concatenated (segment ``s`` owning rows
    ``[row_lo[s], row_hi[s])`` — each run independently sorted, exactly
    the rows the solo sweep searches), and ``bitmap`` stacks the
    per-segment prefilters at a COMMON ``bitmap_bits`` (the bitmap is a
    prefilter ANDed with the exact search, so a different bitmap size
    than a solo run never changes results).

    This is :func:`digest_member`'s binary search with the (lo, hi)
    carry — already per-lane — initialized from the lane's segment
    bounds instead of ``(0, D)``: each lane's search walks only its own
    segment's sorted run.  An empty segment (``lo == hi``) never moves
    and never matches.
    """
    n, k = digest.shape
    d = rows.shape[0]
    if d == 0:
        return jnp.zeros((n,), dtype=bool)

    bitmap_bits = int(np.log2(bitmap.shape[1])) + 5
    idx = digest[:, 0] & _U32((1 << bitmap_bits) - 1)
    word = bitmap[seg, (idx >> _U32(5)).astype(jnp.int32)]
    pre = (word >> (idx & _U32(31))) & _U32(1) != 0

    # log2 of the TOTAL row count bounds every segment's run; converged
    # lanes are frozen by the (lo < hi) guard, so extra steps are no-ops.
    steps = int(np.ceil(np.log2(max(d, 2)))) + 1
    lo0 = row_lo[seg].astype(jnp.int32)
    hi0 = row_hi[seg].astype(jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) >> 1
        le = _row_cmp_le(digest, rows[mid]) & (lo < hi)
        return jnp.where(le, mid + 1, lo), jnp.where(le, hi, mid)

    lo, _ = jax.lax.fori_loop(0, steps, body, (lo0, hi0))
    found = jnp.clip(lo - 1, 0, d - 1)
    # "Found something" is lo past the segment's OWN virtual -1 row.
    exact = jnp.all(rows[found] == digest, axis=-1) & (lo > lo0)
    return pre & exact


jit_digest_member = jax.jit(digest_member)

"""Batched hash primitives: MD5, SHA-1, MD4 and NTLM over padded byte tensors.

The reference generates candidates only and pipes them into hashcat for
hashing (reference ``README.MD:69``); this framework hashes **on device** so
candidates never leave VMEM and only digest-set hits cross the host boundary
(SURVEY.md §7 step 4). Everything is formulated as uint32 lane arithmetic over
a batch axis — adds mod 2^32, rotates as shift-or — which XLA vectorizes onto
the TPU VPU; the per-round structure is unrolled at trace time (static Python
loops) so the compiler sees one straight-line dataflow per block.

Contract: inputs are ``msg: uint32-aligned uint8[B, W]`` padded with zeros and
``length: int32[B]``; outputs are the raw state words ``uint32[B, 4|5]`` (the
natural form for digest-set membership). ``digest_bytes`` converts to the
canonical byte serialization (little-endian words for MD4/MD5, big-endian for
SHA-1) for interop and tests.

Message schedule: a message of ``length`` bytes occupies
``ceil((length + 9) / 64)`` 64-byte blocks; the kernel always runs the static
``ceil((W + 9) / 64)`` blocks that the padded width admits and masks state
updates for blocks past each message's end, so one compiled program serves
every length in the bucket.

NTLM is MD4 over the UTF-16LE encoding of the password. ``utf16le_expand``
implements the byte->code-unit expansion exactly like hashcat's NTLM kernel
does by default: each candidate BYTE becomes the code unit ``byte | 0x0000``
(naive interleave, no UTF-8 decoding). For pure-ASCII candidates this is
identical to true UTF-16LE; for multi-byte UTF-8 candidates it matches
hashcat's default behavior (hashcat only transcodes under ``--encoding-from``,
which is a separate, host-side concern).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..audit import audited_entry

_U32 = jnp.uint32


def _rotl(x: jnp.ndarray, s: int) -> jnp.ndarray:
    return (x << np.uint32(s)) | (x >> np.uint32(32 - s))


def _blocks_for_width(width: int) -> int:
    """Static number of 64-byte blocks the padded layout needs."""
    return -(-(width + 9) // 64)


def pad_message(
    msg: jnp.ndarray, length: jnp.ndarray, *, big_endian_length: bool
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Lay out Merkle–Damgård padding for a whole batch in one shot.

    Returns ``(words, n_blocks)`` where ``words`` is ``uint32[B, NB*16]``
    (little-endian byte order within each word — SHA-1 byte-swaps later) and
    ``n_blocks`` is ``int32[B]``, the number of blocks each message actually
    uses. The 0x80 terminator lands at byte index ``length`` and the 64-bit
    bit-length at the end of each message's own last block, all computed with
    masks so the whole thing is one fused elementwise pass.
    """
    batch, width = msg.shape
    nb = _blocks_for_width(width)
    total = nb * 64
    length = length.astype(jnp.int32)

    buf = jnp.zeros((batch, total), dtype=jnp.uint8)
    buf = buf.at[:, :width].set(msg)
    pos = jnp.arange(total, dtype=jnp.int32)[None, :]
    len_col = length[:, None]
    # Zero out padding bytes that may carry garbage, add the 0x80 terminator.
    buf = jnp.where(pos < len_col, buf, jnp.uint8(0))
    buf = jnp.where(pos == len_col, jnp.uint8(0x80), buf)

    n_blocks = (length + 9 + 63) // 64
    msg_end = n_blocks[:, None] * 64  # end of each message's own last block
    # 64-bit bit length as two uint32 halves (no uint64 needed: length is
    # int32, so bits = length*8 < 2^34; the high half is bits >> 32).
    bits_lo = (length.astype(_U32) * _U32(8))[:, None]
    bits_hi = (length.astype(_U32) >> _U32(29))[:, None]
    # Byte i of the 8-byte length field sits at msg_end - 8 + i.
    tail_off = pos - (msg_end - 8)
    in_tail = (tail_off >= 0) & (tail_off < 8)
    idx = jnp.where(big_endian_length, 7 - tail_off, tail_off)  # LE byte index
    half = jnp.where(idx < 4, bits_lo, bits_hi)
    shift = ((idx & 3).astype(_U32)) * _U32(8)
    len_byte = ((half >> shift) & _U32(0xFF)).astype(jnp.uint8)
    buf = jnp.where(in_tail, len_byte, buf)

    b = buf.astype(_U32).reshape(batch, total // 4, 4)
    words = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)
    return words, n_blocks


def _byteswap32(x: jnp.ndarray) -> jnp.ndarray:
    return (
        ((x & _U32(0xFF)) << 24)
        | ((x & _U32(0xFF00)) << 8)
        | ((x >> 8) & _U32(0xFF00))
        | (x >> 24)
    )


# ---------------------------------------------------------------------------
# MD5 (RFC 1321)
# ---------------------------------------------------------------------------

_MD5_S = (
    [7, 12, 17, 22] * 4 + [5, 9, 14, 20] * 4 + [4, 11, 16, 23] * 4 + [6, 10, 15, 21] * 4
)
_MD5_K = [int(abs(np.sin(i + 1)) * 2**32) & 0xFFFFFFFF for i in range(64)]
_MD5_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)


def _md5_block(state: Tuple[jnp.ndarray, ...], m: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """One MD5 compression over ``m: uint32[B, 16]`` (already little-endian)."""
    a, b, c, d = state
    a0, b0, c0, d0 = a, b, c, d
    for i in range(64):
        if i < 16:
            f = (b & c) | (~b & d)
            g = i
        elif i < 32:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif i < 48:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        tmp = d
        d = c
        c = b
        rot = a + f + _U32(_MD5_K[i]) + m[:, g]
        b = b + _rotl(rot, _MD5_S[i])
        a = tmp
    return a0 + a, b0 + b, c0 + c, d0 + d


# ---------------------------------------------------------------------------
# MD4 (RFC 1320) — the NTLM core
# ---------------------------------------------------------------------------

_MD4_INIT = _MD5_INIT
_MD4_G = [0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15]
_MD4_H = [0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15]


def _md4_block(state: Tuple[jnp.ndarray, ...], m: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    a, b, c, d = state
    a0, b0, c0, d0 = a, b, c, d

    def round1(a, b, c, d, k, s):
        return _rotl(a + ((b & c) | (~b & d)) + m[:, k], s)

    def round2(a, b, c, d, k, s):
        return _rotl(a + ((b & c) | (b & d) | (c & d)) + m[:, k] + _U32(0x5A827999), s)

    def round3(a, b, c, d, k, s):
        return _rotl(a + (b ^ c ^ d) + m[:, k] + _U32(0x6ED9EBA1), s)

    for r, (rf, shifts, order) in enumerate(
        (
            (round1, (3, 7, 11, 19), list(range(16))),
            (round2, (3, 5, 9, 13), _MD4_G),
            (round3, (3, 9, 11, 15), _MD4_H),
        )
    ):
        for j, k in enumerate(order):
            s = shifts[j % 4]
            a = rf(a, b, c, d, k, s)
            a, b, c, d = d, a, b, c
    return a0 + a, b0 + b, c0 + c, d0 + d


# ---------------------------------------------------------------------------
# SHA-1 (RFC 3174)
# ---------------------------------------------------------------------------

_SHA1_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0)
_SHA1_K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)


def _sha1_block(state: Tuple[jnp.ndarray, ...], m_le: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """One SHA-1 compression; ``m_le`` is the shared little-endian word layout,
    byte-swapped here to SHA-1's big-endian schedule."""
    a, b, c, d, e = state
    w = [_byteswap32(m_le[:, t]) for t in range(16)]
    for t in range(16, 80):
        w.append(_rotl(w[t - 3] ^ w[t - 8] ^ w[t - 14] ^ w[t - 16], 1))
    a0, b0, c0, d0, e0 = a, b, c, d, e
    for t in range(80):
        if t < 20:
            f = (b & c) | (~b & d)
        elif t < 40:
            f = b ^ c ^ d
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
        else:
            f = b ^ c ^ d
        tmp = _rotl(a, 5) + f + e + _U32(_SHA1_K[t // 20]) + w[t]
        e, d, c, b, a = d, c, _rotl(b, 30), a, tmp
    return a0 + a, b0 + b, c0 + c, d0 + d, e0 + e


# ---------------------------------------------------------------------------
# Multi-block drivers
# ---------------------------------------------------------------------------


#: Block counts up to this unroll at trace time (straight-line dataflow for
#: the common short buckets); wider buckets roll into one lax.scan so trace
#: and compile cost stay O(1) in message width.
_UNROLL_BLOCKS = 4


def _run_blocks(block_fn, init, words, n_blocks):
    """Run ``block_fn`` over every static block, masking updates for blocks a
    given message does not use.

    Short layouts (<= ``_UNROLL_BLOCKS`` blocks — width 64 is 2) unroll;
    longer ones run as ``lax.scan`` over the block axis, which compiles the
    compression once regardless of width (a 512-byte bucket would otherwise
    trace 9 copies of the 64-step round structure)."""
    batch = words.shape[0]
    nb = words.shape[1] // 16
    state = tuple(jnp.full((batch,), _U32(x)) for x in init)
    if nb <= _UNROLL_BLOCKS:
        for blk in range(nb):
            m = words[:, blk * 16 : (blk + 1) * 16]
            new_state = block_fn(state, m)
            active = blk < n_blocks
            state = tuple(
                jnp.where(active, ns, s) for ns, s in zip(new_state, state)
            )
        return jnp.stack(state, axis=-1)

    m_seq = jnp.moveaxis(words.reshape(batch, nb, 16), 1, 0)  # [nb, B, 16]

    def step(carry, m):
        blk, st = carry
        new_st = block_fn(st, m)
        active = blk < n_blocks  # [B]
        st = tuple(jnp.where(active, ns, s) for ns, s in zip(new_st, st))
        return (blk + 1, st), None

    (_, state), _ = jax.lax.scan(step, (jnp.int32(0), state), m_seq)
    return jnp.stack(state, axis=-1)


@audited_entry("ops.hashes.md5", kind="integer_stage")
def md5(msg: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """MD5 of each row: ``uint8[B, W], int32[B] -> uint32[B, 4]`` state words."""
    words, n_blocks = pad_message(msg, length, big_endian_length=False)
    return _run_blocks(_md5_block, _MD5_INIT, words, n_blocks)


@audited_entry("ops.hashes.md4", kind="integer_stage")
def md4(msg: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """MD4 of each row: ``uint8[B, W], int32[B] -> uint32[B, 4]`` state words."""
    words, n_blocks = pad_message(msg, length, big_endian_length=False)
    return _run_blocks(_md4_block, _MD4_INIT, words, n_blocks)


@audited_entry("ops.hashes.sha1", kind="integer_stage")
def sha1(msg: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """SHA-1 of each row: ``uint8[B, W], int32[B] -> uint32[B, 5]`` state words."""
    words, n_blocks = pad_message(msg, length, big_endian_length=True)
    return _run_blocks(_sha1_block, _SHA1_INIT, words, n_blocks)


def utf16le_expand(msg: jnp.ndarray, length: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expand bytes to UTF-16LE code units the way hashcat's NTLM kernel does:
    ``uint8[B, W] -> uint8[B, 2W]`` with a zero byte after every input byte."""
    batch, width = msg.shape
    out = jnp.zeros((batch, 2 * width), dtype=jnp.uint8)
    out = out.at[:, 0::2].set(msg)
    return out, length.astype(jnp.int32) * 2


@audited_entry("ops.hashes.ntlm", kind="integer_stage")
def ntlm(msg: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """NTLM: MD4 over the UTF-16LE expansion. ``uint32[B, 4]`` state words."""
    wide, wide_len = utf16le_expand(msg, length)
    return md4(wide, wide_len)


HASH_FNS = {"md5": md5, "sha1": sha1, "md4": md4, "ntlm": ntlm}
DIGEST_WORDS = {"md5": 4, "sha1": 5, "md4": 4, "ntlm": 4}
#: Canonical byte serialization: MD4/MD5 little-endian words, SHA-1 big-endian.
BIG_ENDIAN_DIGEST = {"md5": False, "sha1": True, "md4": False, "ntlm": False}


def digest_bytes(state: np.ndarray, algo: str) -> list:
    """Convert ``uint32[B, K]`` state words to canonical digest bytes."""
    state = np.asarray(state)
    order = ">u4" if BIG_ENDIAN_DIGEST[algo] else "<u4"
    return [row.astype(order).tobytes() for row in state]


def digest_to_words(digest: bytes, algo: str) -> np.ndarray:
    """Parse a canonical digest (raw bytes or hex str) back to uint32 words."""
    if isinstance(digest, str):
        digest = bytes.fromhex(digest)
    order = ">u4" if BIG_ENDIAN_DIGEST[algo] else "<u4"
    return np.frombuffer(digest, dtype=order).astype(np.uint32)


jit_md5 = jax.jit(md5)
jit_sha1 = jax.jit(sha1)
jit_md4 = jax.jit(md4)
jit_ntlm = jax.jit(ntlm)

"""Default- and reverse-mode expansion as index arithmetic over match sets.

The reference's default engine (``processWord``, ``main.go:168-205``) is a
recursive DFS: at each byte position it probes keys longest-first, splices a
replacement, and resumes *after* the inserted text (Q5/Q6). Its reverse engine
(``processWordReverse``, ``main.go:208-261``) materializes C(n, k) position
combos, filters overlaps, and applies first options only (Q2). Both enumerate
the same underlying object: **subsets of pairwise non-overlapping matches** of
the table's keys against the original word —

* default mode: every option per match is available, and the DFS's
  "resume after the replacement" rule means a candidate is exactly a set of
  non-overlapping ``(position, key)`` matches with one option chosen each,
  emitted once per distinct choice set (Q6/Q7; adjacency is allowed);
* reverse mode: the overlap filter (``main.go:283-305``) admits exactly the
  same non-overlapping sets, with only ``subs[0]`` applied (Q2).

So one kernel serves both: enumerate mixed-radix digit vectors over the
word's match list (digit 0 = skip; reverse mode just clamps every radix to
2), mask out vectors whose chosen matches overlap, window on the chosen
count (default mode bumps ``min 0 -> 1`` — Q1 — so the all-skip vector is
never emitted there, while reverse mode emits the original word at
``min == 0``), and splice chosen values by position. Parity is per-word
multiset equality (Q9); enumeration order is rank order, not DFS order.

Reverse-mode outputs follow the *corrected* offset arithmetic (ascending
application) — the reference's Q3 bug is reproduced only by the CPU oracle
under ``bug_compat=True``; an engine proper must not corrupt candidates.
Length-preserving tables (all transliteration fixtures) are unaffected.

Unlike substitute-all there is NO ReplaceAll cascade here, hence no fallback
path: splicing is exact for every word and every table (empty keys can never
match — the reference probes key lengths >= 1 only).

Cost note: the enumeration space is ``Π (options_i + 1)`` over all matches
even when ``max_substitute`` prunes deep counts; lanes outside the count
window are masked, not skipped. With the default ``--table-max 15`` and
dictionary-scale words the window covers most of the space, so waste is
small; the reference pays the analogous cost by materializing C(n, k).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tables.compile import CompiledTable
from .packing import PackedWords


@dataclass(frozen=True)
class MatchPlan:
    """Device-ready per-word match list for default/reverse expansion.

    Axes: B words, M match slots in reference scan order (position ascending,
    key length descending — ``main.go:177``); slot 0 is the least-significant
    mixed-radix digit. Inactive slots have radix 1.

    ``windowed`` plans enumerate ONLY digit vectors whose chosen count lies
    in the substitution window, via the suffix-count DP table ``win_v``
    (VERDICT r3 #4: a tight ``-m 1 -x 1`` window over a 20-match word must
    not burn 2^20 lanes for 20 candidates). ``n_variants`` is then the
    windowed total and block base cursors are scalar ranks, not digit
    vectors.
    """

    tokens: np.ndarray  # uint8 [B, L]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — wordlist ordinals (from PackedWords)
    match_pos: np.ndarray  # int32 [B, M]
    match_len: np.ndarray  # int32 [B, M] — key length, 0 on inactive slots
    match_radix: np.ndarray  # int32 [B, M] — options+1 (default) / 2 (reverse)
    match_val_start: np.ndarray  # int32 [B, M] — CSR row of the key's options
    n_variants: Tuple[int, ...]  # python bigints — Π radix per word, or the
    #                              windowed totals when ``windowed``
    fallback: np.ndarray  # bool [B] — always False; kept for the shared
    # block scheduler's plan interface
    out_width: int  # static candidate-buffer width (uint32-aligned)
    windowed: bool = False  # count-windowed enumeration active
    win_v: "np.ndarray | None" = None  # int32 [B, M+1, K+2] suffix counts:
    #   win_v[b, s, j] = number of digit assignments for slots s.. given j
    #   already chosen, with the final count inside the window

    # Shared-scheduler interface (ops.blocks.make_blocks) --------------------
    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def num_slots(self) -> int:
        return int(self.match_pos.shape[1])

    @property
    def pat_radix(self) -> np.ndarray:
        return self.match_radix


def find_matches(word: bytes, ct: CompiledTable) -> List[Tuple[int, int, int]]:
    """All ``(pos, key_len, key_index)`` matches in reference scan order:
    position ascending, key length descending (``main.go:175-177``)."""
    out: List[Tuple[int, int, int]] = []
    kmax = ct.max_key_len
    for i in range(len(word)):
        for klen in range(min(len(word) - i, kmax), 0, -1):
            ki = ct.key_index(word[i : i + klen])
            if ki >= 0:
                out.append((i, klen, ki))
    return out


def _batch_find_matches(ct: CompiledTable, packed: PackedWords) -> np.ndarray:
    """Vectorized :func:`find_matches` over the whole packed batch.

    Returns ``ki int32[B, L, KL]`` — the matched key index (-1 = none) at
    every (word, position, key-length) site, with the KL axis in
    DESCENDING key-length order so a C-order flatten of ``(L, KL)`` yields
    exactly the reference scan order (position ascending, length
    descending, ``main.go:175-177``). Replaces the per-word Python scan
    that dominated plan construction (7.7 s for a 300k-word dictionary —
    longer than the whole device sweep after the launch-loop fixes).
    """
    tokens, lengths = packed.tokens, packed.lengths
    b, width = tokens.shape
    # Keys longer than the packed width can never match (fit would be
    # all-False anyway, and the shifted-compare slices below would go
    # negative for them).
    lens_desc = sorted(
        {int(l) for l in ct.key_len if 0 < l <= width}, reverse=True
    )
    kl = max(1, len(lens_desc))
    ki_mat = np.full((b, width, kl), -1, dtype=np.int32)
    j = np.arange(width)
    for li, klen in enumerate(lens_desc):
        fit = (j[None, :] + klen) <= lengths[:, None]  # [B, L]
        if klen == 1:
            ki_mat[:, :, li] = np.where(fit, ct.byte_to_key[tokens], -1)
        else:
            acc = np.full((b, width), -1, dtype=np.int32)
            for kidx in np.nonzero(ct.key_len == klen)[0]:
                key = ct.key_bytes[kidx]
                ok = fit.copy()
                for t in range(klen):
                    ok[:, : width - t] &= tokens[:, t:] == key[t]
                    if t:
                        ok[:, width - t :] = False
                acc = np.where(ok, np.int32(kidx), acc)
            ki_mat[:, :, li] = acc
    return ki_mat


#: Windowed-enumeration eligibility bounds: per-word windowed totals must
#: fit comfortably in int32 (block base cursors become scalar ranks) and the
#: window ceiling must keep the DP table narrow.
WINDOWED_MAX_TOTAL = 1 << 30
WINDOWED_MAX_SUBST = 8


def _windowed_tables(
    match_radix: np.ndarray,
    min_substitute: int,
    max_substitute: int,
) -> "Tuple[np.ndarray, List[int]] | Tuple[None, None]":
    """Suffix-count DP for count-windowed enumeration (numpy over words).

    ``v[b, s, j]`` = number of digit assignments for slots ``s..m-1`` given
    ``j`` slots already chosen, such that the final chosen count lands in
    ``[min_substitute, max_substitute]`` (overlap clashes are NOT modeled —
    they stay a device-side mask, exactly as in full enumeration; inactive
    slots have 0 options and contribute nothing).
    Returns ``(v, totals)`` or ``(None, None)`` when any word's windowed
    total overflows the int32 cursor budget.
    """
    mx = max_substitute
    b, m = match_radix.shape
    opts = (match_radix.astype(np.int64) - 1).clip(min=0)  # [B, M]
    v = np.zeros((b, m + 1, mx + 2), dtype=np.int64)
    v[:, m, min_substitute : mx + 1] = 1
    for s in range(m - 1, -1, -1):
        v[:, s, : mx + 1] = (
            v[:, s + 1, : mx + 1] + opts[:, s : s + 1] * v[:, s + 1, 1 : mx + 2]
        )
        if v[:, s].max() > WINDOWED_MAX_TOTAL:
            return None, None
    return v.astype(np.int32), [int(t) for t in v[:, 0, 0]]


def _exact_div(r, rs):
    """Exact ``r // rs`` (floor) for ``|r| < 2**24`` via f32 division + a
    ±1 fixup — the TPU VPU has no native s32 divide, so XLA lowers ``//``
    to a long instruction sequence that dominated the whole fused step
    (three decode fusions = 94% of device self-time at 2^19 lanes; PERF.md
    §3 trace). f32 division is correctly rounded, so after flooring the
    quotient is within ±1; both fixup products stay exact in int32."""
    q = jnp.floor(
        r.astype(jnp.float32) / rs.astype(jnp.float32)
    ).astype(jnp.int32)
    q = q - (q * rs > r).astype(jnp.int32)
    q = q + ((q + 1) * rs <= r).astype(jnp.int32)
    return q


#: Largest per-lane rank for which the f32 decode path is exact (f32
#: represents every integer below 2**24; quotients/products stay exact).
_F32_DECODE_MAX_RANK = 1 << 24


def decode_digits(rank, base, radix, field, win_v, m, *,
                  max_rank: "int | None" = None, radix2: bool = False):
    """Per-lane digit-vector decode shared by both expansion kernels.

    Full enumeration (``win_v is None``): digits = base + mixed-radix(rank),
    slot 0 least significant, with carry. Windowed enumeration: the scalar
    rank ``base[:, 0] + rank`` walks only in-window digit vectors through
    the suffix-count DP — per slot, "skip" covers ``v[s+1][j]`` completions
    and "choose option d" covers ``v[s+1][j+1]`` each; column selection is
    an unrolled compare-sum (K+2 columns), never a per-lane gather.
    Returns ``digits int32[N, M]``.

    ``max_rank`` (static): exclusive bound on in-block ranks. When it fits
    f32's exact-integer range the full-enumeration divides run as f32 + ±1
    fixup (:func:`_exact_div`) and the carry chain is compare/subtract —
    the s32 ``//``/``%`` lowering those replace was 94% of the fused step's
    device time (PERF.md §3).
    """
    if win_v is not None:
        k2 = int(win_v.shape[2])

        def sel(row, jcol):
            acc = jnp.zeros_like(rank)
            for c in range(k2):
                acc = acc + jnp.where(jcol == c, row[:, c], 0)
            return acc

        big_r = base[:, 0] + rank  # scalar windowed rank (host-bounded int32)
        jcnt = jnp.zeros_like(rank)
        digits = []
        for s in range(m):
            row = field(win_v[:, s + 1])  # [N, K+2]
            vn0 = sel(row, jcnt)
            not_chosen = big_r < vn0
            r2 = big_r - vn0
            safe = jnp.maximum(sel(row, jcnt + 1), 1)
            d = jnp.where(not_chosen, 0, 1 + r2 // safe)
            big_r = jnp.where(not_chosen, big_r, r2 % safe)
            # Invalid lanes (rank past the block's count) decode garbage;
            # clamp so downstream value-row lookups stay in range — emit
            # masks them regardless.
            digits.append(jnp.clip(d, 0, radix[:, s] - 1))
            jcnt = jcnt + jnp.where(not_chosen, 0, 1)
        return jnp.stack(digits, axis=1)  # [N, M]
    # Shift amounts >= 32 are implementation-defined in XLA; > 31 active
    # slots can push the bit cursor there, so wide plans keep the general
    # decode (static fact — m is the padded slot count). The Pallas twin
    # is capped harder by its own eligibility (_MAX_SLOTS = 24).
    radix2 = radix2 and m <= 31
    if radix2:
        # K=1 tables (every shipped 1:1 layout map): all radices <= 2, so
        # active slots' digits are successive BITS of the rank — shift/
        # mask + a binary carry replaces even the f32 divide chain
        # (mirrors pallas_expand._decode_tile_radix2; the caller asserts
        # the static fact via k_opts == 1).
        digits = []
        carry = jnp.zeros_like(rank)
        nbits = jnp.zeros_like(rank)
        for s in range(m):
            active = radix[:, s] > 1
            bit = (rank >> nbits) & 1
            t = base[:, s] + jnp.where(active, bit, 0) + carry
            digits.append(jnp.where(active, t & 1, 0))
            carry = jnp.where(active, t >> 1, carry)
            nbits = nbits + active.astype(jnp.int32)
        return jnp.stack(digits, axis=1)  # [N, M]
    digits = []
    carry = jnp.zeros_like(rank)
    r = rank
    fast = max_rank is not None and max_rank <= _F32_DECODE_MAX_RANK
    for s in range(m):
        rs = radix[:, s]
        q = _exact_div(r, rs) if fast else r // rs
        # base and (r mod rs) are both proper digits (< rs) and carry is
        # 0/1, so t < 2*rs: the carry chain reduces to compare/subtract.
        t = base[:, s] + (r - q * rs) + carry
        ge = (t >= rs).astype(jnp.int32)
        digits.append(t - ge * rs)
        carry = ge
        r = q
    return jnp.stack(digits, axis=1)  # [N, M]


def unrank_windowed(
    v_row: np.ndarray, radices: Sequence[int], rank: int
) -> List[int]:
    """Host mirror of the device's windowed unranking: digit vector of
    ``rank`` in word's windowed enumeration. ``v_row`` is ``win_v[word]``
    (``[M+1, K+2]``). Raises ``ValueError`` for ranks past the windowed
    total (mirrors the full-mode decode contract in ``decode_variant``)."""
    digits: List[int] = []
    j = 0
    r = int(rank)
    if r >= int(v_row[0, 0]):
        raise ValueError(f"windowed rank {rank} out of range")
    for s, radix in enumerate(radices):
        vn0 = int(v_row[s + 1, j])
        if r < vn0:
            digits.append(0)
        else:
            r -= vn0
            vn1 = int(v_row[s + 1, j + 1])
            digits.append(r // vn1 + 1)
            r %= vn1
            j += 1
    return digits


def windowed_chunk_terms(
    radix_matrix: np.ndarray,
    n_variants: List[int],
    min_substitute: "int | None",
    max_substitute: "int | None",
    zero_mask: "np.ndarray | None" = None,
) -> "Tuple[bool, np.ndarray | None, List[int] | None, int, int]":
    """The batch-ADDITIVE terms of the windowed-enumeration decision:
    ``(eligible, win_v, win_totals, sum_win, sum_full)``.

    ONE implementation serves both consumers: ``windowed_plan_fields``
    votes ``eligible and windowed_gate(sum_win, sum_full)`` over a whole
    batch, and the streaming prescan (``Sweep._stream_prescan``,
    PERF.md §19) accumulates the sums chunk by chunk and votes the
    identical way over their totals — the decision MUST be computed by
    the same code or streaming and whole-dictionary runs could pick
    different enumeration schemes for the same inputs (different
    fingerprints, renumbered ranks).  ``eligible`` is False on an
    out-of-bounds window or an int32-overflowing per-word DP (per-word
    properties, so chunk-wise conjunction equals the whole-batch test).
    """
    if (
        min_substitute is None
        or max_substitute is None
        or not 0 <= min_substitute <= max_substitute <= WINDOWED_MAX_SUBST
        or radix_matrix.shape[0] == 0
    ):
        return False, None, None, 0, 0
    v, totals = _windowed_tables(radix_matrix, min_substitute, max_substitute)
    if v is None:
        return False, None, None, 0, 0
    if zero_mask is not None:
        totals = [0 if zero_mask[i] else t for i, t in enumerate(totals)]
    full = sum(min(t, 1 << 62) for t in n_variants)
    return True, v, totals, sum(totals), full


def windowed_gate(sum_win: int, sum_full: int) -> bool:
    """The 2x-lane-saving vote: windowed enumeration engages only when
    it at least halves the lane count.  The one place the threshold
    lives (see :func:`windowed_chunk_terms`)."""
    return sum_win * 2 <= sum_full


def windowed_plan_fields(
    radix_matrix: np.ndarray,
    n_variants: List[int],
    min_substitute: "int | None",
    max_substitute: "int | None",
    zero_mask: "np.ndarray | None" = None,
    force: "bool | None" = None,
) -> "Tuple[bool, np.ndarray | None, List[int]]":
    """Shared windowed-enumeration eligibility + table construction for both
    plan builders: bounds check, suffix-count DP, 2x lane-saving gate
    (all via :func:`windowed_chunk_terms` — the streaming prescan votes
    with the same terms).

    ``zero_mask`` marks words whose totals are forced to 0 (suball's
    oracle-routed hazard words). Returns ``(windowed, win_v, n_variants)``
    — unchanged inputs when ineligible.

    ``force`` pins the decision instead of deciding it here: the
    2x-lane-saving gate is a BATCH-level property, so a streaming sweep
    (which sees one chunk at a time) decides once over the whole
    dictionary and forces every chunk plan the same way — rank numbering
    must be chunk-invariant (PERF.md §19).  ``False`` = full enumeration
    unconditionally; ``True`` = windowed, skipping only the saving gate
    (the eligibility bounds still apply — the caller guaranteed them
    globally, and a violated bound here is a caller bug worth raising
    on).
    """
    if force is False:
        return False, None, n_variants
    eligible, v, totals, sum_win, sum_full = windowed_chunk_terms(
        radix_matrix, n_variants, min_substitute, max_substitute,
        zero_mask=zero_mask,
    )
    if not eligible:
        if force:
            raise ValueError(
                "force_windowed=True but this batch is not windowed-"
                f"eligible (window [{min_substitute}, {max_substitute}] "
                "out of bounds, or a word's windowed total overflows the "
                "int32 cursor budget)"
            )
        return False, None, n_variants
    if force is None and not windowed_gate(sum_win, sum_full):
        return False, None, n_variants
    return True, v, totals


def variant_totals(radix_matrix: np.ndarray) -> List[int]:
    """Per-row radix products as EXACT Python ints, shared by both plan
    builders: rows whose log2 sum is comfortably inside int64 take the
    vectorized product; the (rare) rest recompute exactly."""
    radix64 = radix_matrix.astype(np.int64)
    logs = np.sum(np.log2(radix64.astype(np.float64)), axis=1)
    prods = np.prod(radix64, axis=1)
    out: List[int] = [int(x) for x in prods]
    for i in np.nonzero(logs >= 60)[0]:
        total = 1
        for r in radix_matrix[i]:
            total *= int(r)
        out[int(i)] = total
    return out


def rounded_out_width(width: int, max_delta: int) -> int:
    """Candidate-buffer width: packed width + worst growth, uint32-aligned."""
    return max(4, -(-(width + max_delta) // 4) * 4)


def key_deltas(ct: CompiledTable, *, limit_first_option: bool) -> np.ndarray:
    """Worst-case output growth per chosen key (``int64[K]``): the widest
    considered option minus the key length, floored at 0; optionless keys
    grow nothing. ``limit_first_option``: reverse modes apply ``subs[0]``
    only (Q2), so only the first option's width counts there."""
    k = ct.num_keys
    out = np.zeros(max(k, 1), dtype=np.int64)
    for kidx in range(k):
        c = int(ct.val_count[kidx])
        if c == 0:
            continue
        opts = 1 if limit_first_option else c
        widest = max(
            int(ct.val_len[ct.val_start[kidx] + o]) for o in range(opts)
        )
        out[kidx] = max(0, widest - int(ct.key_len[kidx]))
    return out


def build_match_plan(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool = False,
    out_width: int | None = None,
    min_substitute: int | None = None,
    max_substitute: int | None = None,
    force_windowed: bool | None = None,
) -> MatchPlan:
    """Host-side plan construction for default (``first_option_only=False``)
    or reverse (``True``) mode.

    When the EFFECTIVE substitution window ``[min_substitute,
    max_substitute]`` is given and tight (``max_substitute <=
    WINDOWED_MAX_SUBST``, windowed totals < 2^30, and at least a 2x lane
    saving over full enumeration), the plan switches to count-windowed
    enumeration: ranks walk only in-window digit vectors via the ``win_v``
    DP instead of masking the full mixed-radix space.

    ``force_windowed`` pins the enumeration scheme (streaming chunk
    plans: the scheme is a batch-level decision the streaming sweep
    makes once over the whole dictionary; see ``windowed_plan_fields``).
    """
    b, width = packed.tokens.shape

    # Vectorized batch scan (see _batch_find_matches) + dense packing:
    # per-site key indices flatten to reference scan order, per-row ranks
    # become slot columns.
    ki_mat = _batch_find_matches(ct, packed)
    flat = ki_mat.reshape(b, -1)
    valid = flat >= 0
    counts = valid.sum(axis=1)
    m = max(1, int(counts.max()) if b else 0)
    rank = np.cumsum(valid, axis=1) - 1
    rows, cols = np.nonzero(valid)
    slots = rank[rows, cols]
    ki = flat[rows, cols]
    kl_axis = ki_mat.shape[2]

    # Per-key static fields (K is tiny): radix and the worst-case output
    # growth each chosen key can contribute.
    vc = ct.val_count.astype(np.int64)
    if first_option_only:
        key_radix = np.where(vc == 0, 1, 2).astype(np.int32)
    else:
        key_radix = np.where(vc == 0, 1, vc + 1).astype(np.int32)
    delta_per_key = key_deltas(ct, limit_first_option=first_option_only)

    match_pos = np.zeros((b, m), dtype=np.int32)
    match_len = np.zeros((b, m), dtype=np.int32)
    match_radix = np.ones((b, m), dtype=np.int32)
    match_val_start = np.zeros((b, m), dtype=np.int32)
    match_pos[rows, slots] = (cols // kl_axis).astype(np.int32)
    match_len[rows, slots] = ct.key_len[ki]
    match_radix[rows, slots] = key_radix[ki]
    match_val_start[rows, slots] = ct.val_start[ki]

    word_delta = np.zeros(b, dtype=np.int64)
    np.add.at(word_delta, rows, delta_per_key[ki])
    max_delta = int(word_delta.max()) if b else 0

    n_variants = variant_totals(match_radix)

    if out_width is None:
        out_width = rounded_out_width(width, max_delta)

    windowed, win_v, n_variants = windowed_plan_fields(
        match_radix, n_variants, min_substitute, max_substitute,
        force=force_windowed,
    )

    return MatchPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        match_pos=match_pos,
        match_len=match_len,
        match_radix=match_radix,
        match_val_start=match_val_start,
        n_variants=tuple(n_variants),
        fallback=np.zeros((b,), dtype=bool),
        out_width=out_width,
        windowed=windowed,
        win_v=win_v,
    )


def per_lane_broadcast(num_blocks: int, stride: int):
    """Block-field -> lane-field expander for the fixed-stride layout:
    ``x[NB, ...] -> x[NB * stride, ...]`` by tiling each block's row over its
    ``stride`` lanes — a broadcast XLA fuses into consumers, replacing the
    per-lane gathers (``field[blk]``) the variable-offset layout needs."""

    def per_lane(x: jnp.ndarray) -> jnp.ndarray:
        tiled = jnp.broadcast_to(
            x[:, None], (num_blocks, stride) + x.shape[1:]
        )
        return tiled.reshape((num_blocks * stride,) + x.shape[1:])

    return per_lane


def lane_fields(
    blk_word, blk_base, blk_count, blk_offset, *, num_lanes, block_stride
):
    """Lane -> block resolution shared by both expansion kernels.

    Returns ``(rank, lane_ok, w, base, field)``: per-lane in-block rank,
    validity mask, word row, mixed-radix base digits, and ``field(x)``
    expanding a per-word array ``x[B, ...]`` to per-lane ``[N, ...]``.

    ``block_stride`` set (fixed-stride batches, ``make_blocks(fixed_stride)``)
    is the TPU-critical path: lane -> block is one constant divide (XLA
    strength-reduces it) and block fields broadcast over the stride. The
    variable-offset path (``None``) binary-searches ``blk_offset`` per lane —
    on TPU that ``searchsorted`` lowers to a sequential ``while`` loop that
    alone cost 57% of the fused step at 2^19 lanes (PERF.md).
    """
    n = num_lanes
    v = jnp.arange(n, dtype=jnp.int32)
    if block_stride is not None:
        nb = n // block_stride
        if nb * block_stride != n or blk_offset.shape[0] != nb:
            raise ValueError(
                f"block_stride {block_stride} needs num_lanes divisible and "
                f"exactly {n} // stride = {nb} blocks, got "
                f"{blk_offset.shape[0]}"
            )
        per_lane = per_lane_broadcast(nb, block_stride)
        blk = v // np.int32(block_stride)
        rank = v - blk * np.int32(block_stride)
        lane_ok = rank < per_lane(blk_count)
        w = per_lane(blk_word)
        base = per_lane(blk_base)
        field = lambda x: per_lane(x[blk_word])  # noqa: E731
    else:
        blk = jnp.clip(
            jnp.searchsorted(blk_offset, v, side="right").astype(jnp.int32)
            - 1,
            0,
            max(blk_offset.shape[0] - 1, 0),
        )
        rank = v - blk_offset[blk]
        lane_ok = rank < blk_count[blk]
        w = blk_word[blk]
        base = blk_base[blk]
        field = lambda x: x[w]  # noqa: E731
    return rank, lane_ok, w, base, field


def pair_lane_fields(
    blk_word, blk_base, blk_count, *, num_lanes, block_stride
):
    """Lane → block resolution for the pair-lane tier (K=2 candidates
    per lane, PERF.md §24): each lane owns the consecutive CANDIDATE
    ranks ``2r`` and ``2r+1`` of its block, so blocks cover
    ``2 * block_stride`` ranks on ``block_stride`` lanes and
    ``blk_count`` counts CANDIDATES (up to ``2 * block_stride``).

    Returns ``(rank int32[N], ok0 bool[N], ok1 bool[N], w int32[N],
    base int32[N, P], field)`` — the per-lane PAIR rank ``r``,
    per-member validity masks (``2r + p < count``), word row, base
    digits, and the per-word field expander.  Fixed-stride only: the
    pair tier is gated on the stride layout.
    """
    n = num_lanes
    if block_stride is None:
        raise ValueError("the pair-lane tier requires a fixed-stride "
                         "block layout")
    nb = n // block_stride
    if nb * block_stride != n or blk_word.shape[0] != nb:
        raise ValueError(
            f"pair-lane launch needs num_lanes divisible by the stride "
            f"and exactly {n} // {block_stride} = {nb} blocks, got "
            f"{blk_word.shape[0]}"
        )
    per_lane = per_lane_broadcast(nb, block_stride)
    v = jnp.arange(n, dtype=jnp.int32)
    blk = v // np.int32(block_stride)
    rank = v - blk * np.int32(block_stride)
    count = per_lane(blk_count)
    ok0 = rank * 2 < count
    ok1 = rank * 2 + 1 < count
    w = per_lane(blk_word)
    base = per_lane(blk_base)
    field = lambda x: per_lane(x[blk_word])  # noqa: E731
    return rank, ok0, ok1, w, base, field


def interleave_pairs(*arrays):
    """Interleave per-member arrays along a new candidate axis:
    ``(a0[N, ...], a1[N, ...]) -> a[2N, ...]`` with member ``p`` of lane
    ``r`` at row ``2r + p`` — the pair tier's rank attribution
    (PERF.md §24)."""
    stacked = jnp.stack(arrays, axis=1)
    return stacked.reshape((-1,) + stacked.shape[2:])


def splice_pieces_pair(
    schema, tables, field, digits, d0_partner, col_variant, *,
    n, out_width,
):
    """Both pair members' candidate buffers via the shared
    :func:`splice_pieces` walk: the partner's variant vector is the
    base's with the innermost column's index replaced
    (``d0_partner int32[N]``) — the schema's pair gate guarantees only
    that one column differs.  Returns ``(out0 uint8[N, W], len0
    int32[N], out1 uint8[N, W], len1 int32[N])``; XLA CSE
    dedupes the shared selects between the two walks (the Pallas pair
    kernel shares them structurally — this is the parity twin, not the
    budget-pinned path)."""
    out0, len0 = splice_pieces(
        schema, tables, field, col_variant, n=n, out_width=out_width
    )
    cv1 = lambda c: d0_partner if c == 0 else col_variant(c)  # noqa: E731
    out1, len1 = splice_pieces(
        schema, tables, field, cv1, n=n, out_width=out_width
    )
    return out0, len0, out1, len1


def piece_device_tables(pieces) -> dict:
    """Device copies of a :class:`ops.packing.PieceSchema`'s data tables
    for :func:`splice_pieces`: ``pl`` uint8 [B, NGD, V] dynamic-group
    lengths (absent for all-fixed schemas — their lengths are static),
    plus ``pw`` uint32 [B, NG, V, NW] and/or ``pw16`` uint16
    [B, NG16, VM] variant words when present — the same optional-key
    layout as ``models.attack.piece_arrays`` strips into
    ``piece_tables``, as the trace-time-constant fallback for direct
    calls and tests."""
    tabs = {}
    if pieces.gl is not None:
        tabs["pl"] = jnp.asarray(pieces.gl)
    if pieces.gw is not None:
        tabs["pw"] = jnp.asarray(pieces.gw)
    if pieces.gw16 is not None:
        tabs["pw16"] = jnp.asarray(pieces.gw16)
    return tabs


def splice_pieces(schema, tables, field, col_variant, *, n, out_width):
    """Per-slot piece materialization — the XLA twin of the Pallas piece
    kernels (``pallas_expand._make_piece_kernel``; PERF.md §17/§18),
    shared by both expansion paths so CPU fallback, the bench ``xla``
    arm, and the fused kernels stay ONE algorithm.

    Walks the plan's :class:`ops.packing.PieceSchema` groups in output
    order: selects each group's precomputed word(s)/length by the variant
    index (``col_variant(c) -> int32[N]``), unpacks the selected bytes,
    and lands them at the lane-local prefix offset with compare-selects
    over the output columns (never scatters).  Mirrors the kernels'
    hierarchical-placement structure: narrow groups read the u16
    ``pw16`` table, fixed-length groups (``len_fixed``) skip the length
    select, and a run of fixed groups keeps the running offset a Python
    int so their column compares broadcast block-uniform.  The
    terminator pseudo-byte in the tail group's bytes is masked off by
    the trailing ``o < out_len`` zero-fill, so candidate buffers stay
    byte-identical to the unit-scan splice.  Returns
    ``(out uint8[N, W], out_len)``.
    """
    o = jnp.arange(out_width, dtype=jnp.int32)[None, :]  # [1, W]
    out = jnp.zeros((n, out_width), jnp.uint8)
    cum_static = 0
    cum = None  # dynamic offset once any group's length varies
    # ``pl`` ships only the DYNAMIC groups' rows (``grp.gl_idx``); an
    # all-fixed schema ships none (PERF.md §19).
    pl = tables.get("pl")
    pw = tables.get("pw")
    pw16 = tables.get("pw16")
    for gi, grp in enumerate(schema.groups):
        n_var, n_words = grp.n_variants, grp.n_words
        if grp.len_fixed == 0:
            continue  # empty in every launched word: nothing placed
        idx = None
        if n_var > 1:
            sel = grp.sel_cols
            if len(sel) == 1:
                # Clamp: a suball padding column aliases slot 0, whose
                # digit/joint index can exceed this column's variant
                # rows (all of which are empty for the padding word) —
                # select_n with an out-of-range index is undefined.
                idx = jnp.minimum(col_variant(sel[0]), n_var - 1)
            else:  # merged binary columns: packed chosen bits
                idx = jnp.zeros((n,), jnp.int32)
                for i, c in enumerate(sel):
                    idx = idx | (
                        (col_variant(c) > 0).astype(jnp.int32) << i
                    )

        def pick(rows):
            return rows[0] if idx is None else jax.lax.select_n(idx, *rows)

        if grp.packed16:
            words = [pick([
                field(pw16[:, grp.tab_idx, v]) for v in range(n_var)
            ]).astype(jnp.uint32)]
        else:
            words = [
                pick([field(pw[:, grp.tab_idx, v, w])
                      for v in range(n_var)])
                for w in range(n_words)
            ]
        l = grp.len_fixed
        if l is None:
            l = pick([
                field(pl[:, grp.gl_idx, v]).astype(jnp.int32)
                for v in range(n_var)
            ])
        off = cum_static if cum is None else cum
        # Place the selected bytes: piece byte bi lands at output column
        # off + bi when bi < l (a handful of [N, W] compare-selects; the
        # total byte count across groups is the schema's max_out).
        for bi in range(4 * n_words):
            if bi >= out_width:
                break
            if isinstance(l, int) and bi >= l:
                break
            byte = (words[bi // 4] >> jnp.uint32(8 * (bi % 4))).astype(
                jnp.uint8
            )
            if isinstance(off, int):
                if off + bi >= out_width:
                    break
                m = o == (off + bi)
            else:
                m = o == (off + bi)[:, None]
            if not isinstance(l, int):
                m = m & (bi < l)[:, None]
            out = jnp.where(m, byte[:, None], out)
        if isinstance(l, int):
            if cum is None:
                cum_static += l
            else:
                cum = cum + l
        elif cum is not None:
            cum = cum + l
        else:
            cum = l if cum_static == 0 else l + cum_static
    if cum is None:  # every group fixed: the whole length is static
        out_len = jnp.full((n,), cum_static - 1, jnp.int32)
    else:
        out_len = cum - 1  # the placed tail includes the terminator byte
    out = jnp.where(o < out_len[:, None], out, jnp.uint8(0))
    return out, out_len


def expand_matches(
    tokens: jnp.ndarray,  # uint8 [B, L]
    lengths: jnp.ndarray,  # int32 [B]
    match_pos: jnp.ndarray,  # int32 [B, M]
    match_len: jnp.ndarray,  # int32 [B, M]
    match_radix: jnp.ndarray,  # int32 [B, M]
    match_val_start: jnp.ndarray,  # int32 [B, M]
    val_bytes: jnp.ndarray,  # uint8 [V, val_width] — compiled table values
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, M]
    blk_count: jnp.ndarray,  # int32 [NB]
    blk_offset: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
    block_stride: int | None = None,
    win_v: jnp.ndarray | None = None,
    splice_impl: str | None = None,
    radix2: bool = False,
    pieces=None,  # packing.PieceSchema — per-slot emission (PERF.md §17)
    piece_tables: "dict | None" = None,  # device copies of pieces' arrays
    pair_k: "int | None" = None,  # pair-lane tier (K=2, PERF.md §24)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode + materialize ``num_lanes`` variants.

    ``pair_k=2`` selects the pair-lane tier (PERF.md §24): every lane
    covers the two consecutive candidate ranks ``2r``/``2r+1`` (blocks
    then span ``2 * block_stride`` ranks and ``blk_count`` counts
    candidates), the mixed-radix index is decoded ONCE per lane (the
    schema's pair gate guarantees the partner's digit vector is the
    base's with slot 0's digit + 1), and the outputs interleave the
    members — row ``2r + p``.  Requires a pair-eligible ``pieces``
    schema, the fixed-stride layout, and full enumeration.

    Returns ``(cand uint8[N, out_width], cand_len int32[N], word_row int32[N],
    emit bool[N])`` — ``emit`` folds lane validity (rank in range), the
    non-overlap constraint, and the chosen-count window. Callers pass the
    *effective* window: default mode's Q1 bump (``min 0 -> 1``) happens in the
    caller, reverse mode passes ``min`` through.

    ``block_stride``: static lanes-per-block of a fixed-stride batch
    (``make_blocks(fixed_stride=...)``). The TPU-critical path: lane ->
    block becomes one constant divide (XLA strength-reduces it) and every
    block field broadcasts over its stride instead of gathering per lane.
    The variable-offset path (``None``) keeps the per-lane ``searchsorted``
    + gathers — on TPU that binary search lowers to a sequential ``while``
    loop that alone cost 57% of the fused step at 2^19 lanes (PERF.md).

    ``win_v``: the windowed plan's suffix-count DP table (``MatchPlan.win_v``
    as a device array). When given, ranks unrank through the DP — visiting
    ONLY digit vectors whose chosen count is in the window — and block base
    cursors are scalar ranks in slot 0 (``make_blocks`` encodes them so for
    windowed plans).

    ``splice_impl``: ``"compare"`` (TPU formulation) or ``"scatter"`` (CPU
    formulation); ``None`` picks by the trace-time backend. Both are
    semantically identical — see :func:`_splice_compare` /
    :func:`_splice_scatter`.
    """
    n = num_lanes
    m = match_pos.shape[1]
    length_axis = tokens.shape[1]

    if pair_k:
        if pair_k != 2:
            raise ValueError(f"pair_k must be 2 or None, got {pair_k}")
        if pieces is None or not pieces.pair_ok or win_v is not None:
            raise ValueError(
                "the pair-lane tier needs a pair-eligible PieceSchema "
                "and full enumeration; gate via "
                "pallas_expand.pair_for_config"
            )
        rank, ok0, ok1, w, base, field = pair_lane_fields(
            blk_word, blk_base, blk_count,
            num_lanes=n, block_stride=block_stride,
        )
        radix = field(match_radix)
        digits = decode_digits(
            rank * 2, base, radix, field, None, m,
            max_rank=2 * block_stride, radix2=radix2,
        )
        d0 = digits[:, 0]
        d0p = jnp.minimum(d0 + 1, radix[:, 0] - 1)
        tabs = piece_tables or piece_device_tables(pieces)
        out0, len0, out1, len1 = splice_pieces_pair(
            pieces, tabs, field, digits, d0p, lambda c: digits[:, c],
            n=n, out_width=out_width,
        )
        cc0 = jnp.sum((digits > 0).astype(jnp.int32), axis=1)
        cc1 = cc0 + (d0p > 0).astype(jnp.int32) - (d0 > 0).astype(
            jnp.int32
        )
        window = lambda ok, cc: (  # noqa: E731
            ok & (cc >= min_substitute) & (cc <= max_substitute)
        )
        return (
            interleave_pairs(out0, out1),
            interleave_pairs(len0, len1).astype(jnp.int32),
            interleave_pairs(w, w),
            interleave_pairs(window(ok0, cc0), window(ok1, cc1)),
        )

    rank, lane_ok, w, base, field = lane_fields(
        blk_word, blk_base, blk_count, blk_offset,
        num_lanes=n, block_stride=block_stride,
    )
    radix = field(match_radix)  # [N, M]
    pos_w = field(match_pos)  # [N, M]
    len_w = field(match_len)
    mvs_w = field(match_val_start)
    tokens_w = field(tokens)  # [N, L]
    lengths_w = field(lengths)  # [N]

    # In-block ranks are bounded by the stride when fixed (rank = lane mod
    # stride), by the lane count otherwise (rank = lane - offset); the
    # static bound turns the decode divides into f32 + fixup.
    digits = decode_digits(rank, base, radix, field, win_v, m,
                           max_rank=block_stride or n, radix2=radix2)

    chosen = digits > 0  # [N, M]
    chosen_count = jnp.sum(chosen, axis=1)

    if pieces is not None:
        # Per-slot piece emission: schema column c IS match slot c; the
        # schema's static-disjoint-span guarantee makes overlap clashes
        # impossible, so the emit mask needs no clash term.
        tabs = piece_tables or piece_device_tables(pieces)
        out, out_len = splice_pieces(
            pieces, tabs, field, lambda c: digits[:, c],
            n=n, out_width=out_width,
        )
        emit = (
            lane_ok
            & (chosen_count >= min_substitute)
            & (chosen_count <= max_substitute)
        )
        return out, out_len.astype(jnp.int32), w, emit

    # Per-match selected value rows/lengths.
    opt_row = mvs_w + digits - 1  # valid where chosen
    opt_row = jnp.where(chosen, opt_row, 0)
    vlen = jnp.where(chosen, val_len[opt_row], 0)  # [N, M]

    if splice_impl is None:
        # Gathers and small scatters are cheap on CPU and pathological on
        # TPU (PERF.md §1-2); pick per backend at trace time.
        splice_impl = (
            "scatter" if jax.default_backend() == "cpu" else "compare"
        )
    splice = _splice_scatter if splice_impl == "scatter" else _splice_compare
    out, out_len, clash = splice(
        chosen, vlen, opt_row, pos_w, len_w, tokens_w, lengths_w, val_bytes,
        n=n, m=m, length_axis=length_axis, out_width=out_width,
    )

    emit = (
        lane_ok
        & ~clash
        & (chosen_count >= min_substitute)
        & (chosen_count <= max_substitute)
    )
    return out, out_len.astype(jnp.int32), w, emit


def _splice_compare(
    chosen, vlen, opt_row, pos_w, len_w, tokens_w, lengths_w, val_bytes,
    *, n, m, length_axis, out_width,
):
    """Candidate materialization as unrolled compare-and-accumulate over the
    STATIC slot axis M and length axis L — never ``.at[].add`` scatters and
    never per-lane ``searchsorted``. The TPU formulation: XLA lowers
    scatters with duplicate indices to serialized updates there (measured
    ~5 µs/lane at 2^19 lanes — the whole kernel's cost, PERF.md), while
    these compare loops fuse into a handful of vectorized [N, L] passes.

    Output units per original byte position j: a chosen match starting at j
    contributes its value's bytes; an uncovered j contributes the original
    byte. Returns ``(out uint8[N, W], out_len int32[N], clash bool[N])``.
    """
    end_w = pos_w + len_w
    j = jnp.arange(length_axis, dtype=jnp.int32)[None, :]  # [1, L]

    cover_count = jnp.zeros((n, length_axis), dtype=jnp.int32)
    started = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vlen = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vrow = jnp.zeros((n, length_axis), dtype=jnp.int32)
    for s in range(m):
        c_s = chosen[:, s : s + 1]  # [N, 1] bool
        p_s = pos_w[:, s : s + 1]
        inside = (c_s & (j >= p_s) & (j < end_w[:, s : s + 1])).astype(
            jnp.int32
        )
        cover_count = cover_count + inside
        at_start = (c_s & (j == p_s)).astype(jnp.int32)
        started = started + at_start
        start_vlen = start_vlen + at_start * vlen[:, s : s + 1]
        start_vrow = start_vrow + at_start * opt_row[:, s : s + 1]
    covered = cover_count > 0
    # Non-overlap constraint: chosen matches are pairwise disjoint iff no byte
    # is covered twice (adjacency is allowed — touching intervals never share
    # a byte). This replaces any explicit [M, M] interval-pair test.
    clash = jnp.any(cover_count > 1, axis=1)

    in_word = j < lengths_w[:, None]
    # unit_len: a chosen match's start contributes its value's length (the
    # position itself is covered, so no original byte); covered non-start
    # bytes contribute 0; uncovered bytes pass through as 1 original byte.
    unit_len = jnp.where(
        in_word,
        jnp.where(started > 0, start_vlen, jnp.where(covered, 0, 1)),
        0,
    )
    cum = jnp.cumsum(unit_len, axis=1)  # inclusive ends [N, L]
    out_len = cum[:, -1]

    # For each output column o, locate its source unit j and gather that
    # unit's fields — one unrolled pass over L replaces the vmap'd
    # searchsorted AND the four take_along_axis row gathers.
    o = jnp.arange(out_width, dtype=jnp.int32)[None, :]  # [1, W]
    unit_start = cum - unit_len  # output offset where unit j begins
    src_rel = jnp.zeros((n, out_width), dtype=jnp.int32)
    src_is_start = jnp.zeros((n, out_width), dtype=jnp.bool_)
    src_vrow = jnp.zeros((n, out_width), dtype=jnp.int32)
    src_byte = jnp.zeros((n, out_width), dtype=jnp.uint8)
    for jj in range(length_axis):
        sel = (unit_start[:, jj : jj + 1] <= o) & (o < cum[:, jj : jj + 1])
        src_rel = jnp.where(sel, o - unit_start[:, jj : jj + 1], src_rel)
        src_is_start = src_is_start | (sel & (started[:, jj : jj + 1] > 0))
        src_vrow = jnp.where(sel, start_vrow[:, jj : jj + 1], src_vrow)
        src_byte = jnp.where(sel, tokens_w[:, jj : jj + 1], src_byte)
    vw = val_bytes.shape[1]
    from_val = val_bytes[src_vrow, jnp.clip(src_rel, 0, vw - 1)]
    out = jnp.where(src_is_start, from_val, src_byte)
    out = jnp.where(o < out_len[:, None], out, jnp.uint8(0))
    return out, out_len, clash


def _splice_scatter(
    chosen, vlen, opt_row, pos_w, len_w, tokens_w, lengths_w, val_bytes,
    *, n, m, length_axis, out_width,
):
    """The CPU formulation of the same materialization: per-unit fields via
    ``.at[].add`` scatters, source units via a vmap'd ``searchsorted``, and
    ``take_along_axis`` gathers — all cheap on the CPU backend (XLA-CPU
    executes them as plain indexed loops; measured ~2.5x faster there than
    the compare loops, which do strictly more scalar work — PERF.md §2).
    Semantically identical to :func:`_splice_compare` (the parity suite and
    a direct equality test cover both)."""
    lane_idx = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], (n, m)
    )
    end_w = pos_w + len_w
    cov_delta = jnp.zeros((n, length_axis + 1), dtype=jnp.int32)
    cov_delta = cov_delta.at[lane_idx, pos_w].add(chosen.astype(jnp.int32))
    cov_delta = cov_delta.at[lane_idx, end_w].add(-chosen.astype(jnp.int32))
    cover_count = jnp.cumsum(cov_delta[:, :length_axis], axis=1)  # [N, L]
    covered = cover_count > 0
    clash = jnp.any(cover_count > 1, axis=1)

    start_col = jnp.minimum(pos_w, length_axis - 1)
    started = jnp.zeros((n, length_axis), dtype=jnp.int32)
    started = started.at[lane_idx, start_col].add(chosen.astype(jnp.int32))
    start_vlen = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vlen = start_vlen.at[lane_idx, start_col].add(vlen)
    start_vrow = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vrow = start_vrow.at[lane_idx, start_col].add(opt_row)

    j = jnp.arange(length_axis, dtype=jnp.int32)[None, :]
    in_word = j < lengths_w[:, None]
    unit_len = jnp.where(
        in_word,
        jnp.where(started > 0, start_vlen, jnp.where(covered, 0, 1)),
        0,
    )
    cum = jnp.cumsum(unit_len, axis=1)  # inclusive ends [N, L]
    out_len = cum[:, -1]

    o = jnp.arange(out_width, dtype=jnp.int32)
    j_of_o = jax.vmap(lambda c: jnp.searchsorted(c, o, side="right"))(cum)
    j_of_o = jnp.clip(j_of_o, 0, length_axis - 1).astype(jnp.int32)

    take = lambda a: jnp.take_along_axis(a, j_of_o, axis=1)  # noqa: E731
    rel = o[None, :] - (take(cum) - take(unit_len))
    is_start = take(started) > 0
    vrow = take(start_vrow)
    vw = val_bytes.shape[1]
    from_val = val_bytes[vrow, jnp.clip(rel, 0, vw - 1)]
    from_word = take(tokens_w)
    out = jnp.where(is_start, from_val, from_word)
    out = jnp.where(o[None, :] < out_len[:, None], out, jnp.uint8(0))
    return out, out_len, clash

"""Default- and reverse-mode expansion as index arithmetic over match sets.

The reference's default engine (``processWord``, ``main.go:168-205``) is a
recursive DFS: at each byte position it probes keys longest-first, splices a
replacement, and resumes *after* the inserted text (Q5/Q6). Its reverse engine
(``processWordReverse``, ``main.go:208-261``) materializes C(n, k) position
combos, filters overlaps, and applies first options only (Q2). Both enumerate
the same underlying object: **subsets of pairwise non-overlapping matches** of
the table's keys against the original word —

* default mode: every option per match is available, and the DFS's
  "resume after the replacement" rule means a candidate is exactly a set of
  non-overlapping ``(position, key)`` matches with one option chosen each,
  emitted once per distinct choice set (Q6/Q7; adjacency is allowed);
* reverse mode: the overlap filter (``main.go:283-305``) admits exactly the
  same non-overlapping sets, with only ``subs[0]`` applied (Q2).

So one kernel serves both: enumerate mixed-radix digit vectors over the
word's match list (digit 0 = skip; reverse mode just clamps every radix to
2), mask out vectors whose chosen matches overlap, window on the chosen
count (default mode bumps ``min 0 -> 1`` — Q1 — so the all-skip vector is
never emitted there, while reverse mode emits the original word at
``min == 0``), and splice chosen values by position. Parity is per-word
multiset equality (Q9); enumeration order is rank order, not DFS order.

Reverse-mode outputs follow the *corrected* offset arithmetic (ascending
application) — the reference's Q3 bug is reproduced only by the CPU oracle
under ``bug_compat=True``; an engine proper must not corrupt candidates.
Length-preserving tables (all transliteration fixtures) are unaffected.

Unlike substitute-all there is NO ReplaceAll cascade here, hence no fallback
path: splicing is exact for every word and every table (empty keys can never
match — the reference probes key lengths >= 1 only).

Cost note: the enumeration space is ``Π (options_i + 1)`` over all matches
even when ``max_substitute`` prunes deep counts; lanes outside the count
window are masked, not skipped. With the default ``--table-max 15`` and
dictionary-scale words the window covers most of the space, so waste is
small; the reference pays the analogous cost by materializing C(n, k).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tables.compile import CompiledTable
from .packing import PackedWords


@dataclass(frozen=True)
class MatchPlan:
    """Device-ready per-word match list for default/reverse expansion.

    Axes: B words, M match slots in reference scan order (position ascending,
    key length descending — ``main.go:177``); slot 0 is the least-significant
    mixed-radix digit. Inactive slots have radix 1.
    """

    tokens: np.ndarray  # uint8 [B, L]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — wordlist ordinals (from PackedWords)
    match_pos: np.ndarray  # int32 [B, M]
    match_len: np.ndarray  # int32 [B, M] — key length, 0 on inactive slots
    match_radix: np.ndarray  # int32 [B, M] — options+1 (default) / 2 (reverse)
    match_val_start: np.ndarray  # int32 [B, M] — CSR row of the key's options
    n_variants: Tuple[int, ...]  # python bigints — Π radix per word
    fallback: np.ndarray  # bool [B] — always False; kept for the shared
    # block scheduler's plan interface
    out_width: int  # static candidate-buffer width (uint32-aligned)

    # Shared-scheduler interface (ops.blocks.make_blocks) --------------------
    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def num_slots(self) -> int:
        return int(self.match_pos.shape[1])

    @property
    def pat_radix(self) -> np.ndarray:
        return self.match_radix


def find_matches(word: bytes, ct: CompiledTable) -> List[Tuple[int, int, int]]:
    """All ``(pos, key_len, key_index)`` matches in reference scan order:
    position ascending, key length descending (``main.go:175-177``)."""
    out: List[Tuple[int, int, int]] = []
    kmax = ct.max_key_len
    for i in range(len(word)):
        for klen in range(min(len(word) - i, kmax), 0, -1):
            ki = ct.key_index(word[i : i + klen])
            if ki >= 0:
                out.append((i, klen, ki))
    return out


def build_match_plan(
    ct: CompiledTable,
    packed: PackedWords,
    *,
    first_option_only: bool = False,
    out_width: int | None = None,
) -> MatchPlan:
    """Host-side plan construction for default (``first_option_only=False``)
    or reverse (``True``) mode."""
    b, width = packed.tokens.shape
    per_word = [find_matches(packed.word(i), ct) for i in range(b)]
    m = max(1, max((len(x) for x in per_word), default=0))

    match_pos = np.zeros((b, m), dtype=np.int32)
    match_len = np.zeros((b, m), dtype=np.int32)
    match_radix = np.ones((b, m), dtype=np.int32)
    match_val_start = np.zeros((b, m), dtype=np.int32)
    n_variants: List[int] = []
    max_delta = 0

    for i, matches in enumerate(per_word):
        total = 1
        delta = 0
        for s, (pos, klen, ki) in enumerate(matches):
            vc = int(ct.val_count[ki])
            radix = 2 if first_option_only else vc + 1
            if vc == 0:
                radix = 1  # a key with no options can never be chosen
            match_pos[i, s] = pos
            match_len[i, s] = klen
            match_radix[i, s] = radix
            match_val_start[i, s] = ct.val_start[ki]
            total *= radix
            opts = 1 if first_option_only else vc
            widest = max(
                (int(ct.val_len[ct.val_start[ki] + o]) for o in range(opts)),
                default=klen,
            )
            delta += max(0, widest - klen)
        n_variants.append(total)
        max_delta = max(max_delta, delta)

    if out_width is None:
        out_width = max(4, -(-(width + max_delta) // 4) * 4)

    return MatchPlan(
        tokens=packed.tokens,
        lengths=packed.lengths,
        index=packed.index,
        match_pos=match_pos,
        match_len=match_len,
        match_radix=match_radix,
        match_val_start=match_val_start,
        n_variants=tuple(n_variants),
        fallback=np.zeros((b,), dtype=bool),
        out_width=out_width,
    )


def expand_matches(
    tokens: jnp.ndarray,  # uint8 [B, L]
    lengths: jnp.ndarray,  # int32 [B]
    match_pos: jnp.ndarray,  # int32 [B, M]
    match_len: jnp.ndarray,  # int32 [B, M]
    match_radix: jnp.ndarray,  # int32 [B, M]
    match_val_start: jnp.ndarray,  # int32 [B, M]
    val_bytes: jnp.ndarray,  # uint8 [V, val_width] — compiled table values
    val_len: jnp.ndarray,  # int32 [V]
    blk_word: jnp.ndarray,  # int32 [NB]
    blk_base: jnp.ndarray,  # int32 [NB, M]
    blk_count: jnp.ndarray,  # int32 [NB]
    blk_offset: jnp.ndarray,  # int32 [NB]
    *,
    num_lanes: int,
    out_width: int,
    min_substitute: int,
    max_substitute: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decode + materialize ``num_lanes`` variants.

    Returns ``(cand uint8[N, out_width], cand_len int32[N], word_row int32[N],
    emit bool[N])`` — ``emit`` folds lane validity (rank in range), the
    non-overlap constraint, and the chosen-count window. Callers pass the
    *effective* window: default mode's Q1 bump (``min 0 -> 1``) happens in the
    caller, reverse mode passes ``min`` through.
    """
    n = num_lanes
    m = match_pos.shape[1]
    length_axis = tokens.shape[1]

    v = jnp.arange(n, dtype=jnp.int32)
    blk = jnp.clip(
        jnp.searchsorted(blk_offset, v, side="right").astype(jnp.int32) - 1,
        0,
        max(blk_offset.shape[0] - 1, 0),
    )
    rank = v - blk_offset[blk]
    lane_ok = rank < blk_count[blk]
    w = blk_word[blk]  # int32 [N]

    radix = match_radix[w]  # [N, M]
    base = blk_base[blk]  # [N, M]

    # digits = base + mixed-radix(rank), slot 0 least significant, with carry.
    digits = []
    carry = jnp.zeros_like(rank)
    r = rank
    for s in range(m):
        rs = radix[:, s]
        t = base[:, s] + (r % rs) + carry
        digits.append(t % rs)
        carry = t // rs
        r = r // rs
    digits = jnp.stack(digits, axis=1)  # [N, M]

    chosen = digits > 0  # [N, M]
    chosen_count = jnp.sum(chosen, axis=1)

    # Per-match selected value rows/lengths.
    opt_row = match_val_start[w] + digits - 1  # valid where chosen
    opt_row = jnp.where(chosen, opt_row, 0)
    vlen = jnp.where(chosen, val_len[opt_row], 0)  # [N, M]

    # Output units per original byte position j: a chosen match starting at j
    # contributes its value's bytes; an uncovered j contributes tokens[w, j].
    pos_w = match_pos[w]  # [N, M]
    len_w = match_len[w]
    end_w = pos_w + len_w
    lane_idx = jnp.broadcast_to(v[:, None], (n, m))
    cov_delta = jnp.zeros((n, length_axis + 1), dtype=jnp.int32)
    cov_delta = cov_delta.at[lane_idx, pos_w].add(chosen.astype(jnp.int32))
    cov_delta = cov_delta.at[lane_idx, end_w].add(-chosen.astype(jnp.int32))
    cover_count = jnp.cumsum(cov_delta[:, :length_axis], axis=1)  # [N, L]
    covered = cover_count > 0
    # Non-overlap constraint: chosen matches are pairwise disjoint iff no byte
    # is covered twice (adjacency is allowed — touching intervals never share
    # a byte). This replaces any explicit [M, M] interval-pair test.
    clash = jnp.any(cover_count > 1, axis=1)

    started = jnp.zeros((n, length_axis), dtype=jnp.int32)
    started = started.at[lane_idx, jnp.minimum(pos_w, length_axis - 1)].add(
        chosen.astype(jnp.int32)
    )
    start_vlen = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vlen = start_vlen.at[lane_idx, jnp.minimum(pos_w, length_axis - 1)].add(
        vlen
    )
    start_vrow = jnp.zeros((n, length_axis), dtype=jnp.int32)
    start_vrow = start_vrow.at[lane_idx, jnp.minimum(pos_w, length_axis - 1)].add(
        jnp.where(chosen, opt_row, 0)
    )

    j = jnp.arange(length_axis, dtype=jnp.int32)[None, :]
    in_word = j < lengths[w][:, None]
    # unit_len: a chosen match's start contributes its value's length (the
    # position itself is covered, so no original byte); covered non-start
    # bytes contribute 0; uncovered bytes pass through as 1 original byte.
    unit_len = jnp.where(
        in_word,
        jnp.where(started > 0, start_vlen, jnp.where(covered, 0, 1)),
        0,
    )
    cum = jnp.cumsum(unit_len, axis=1)  # inclusive ends [N, L]
    out_len = cum[:, -1]

    # For each output column o, locate its source unit j.
    o = jnp.arange(out_width, dtype=jnp.int32)
    j_of_o = jax.vmap(lambda c: jnp.searchsorted(c, o, side="right"))(cum)
    j_of_o = jnp.clip(j_of_o, 0, length_axis - 1).astype(jnp.int32)

    take = lambda a: jnp.take_along_axis(a, j_of_o, axis=1)  # noqa: E731
    rel = o[None, :] - (take(cum) - take(unit_len))
    is_start = take(started) > 0
    vrow = take(start_vrow)
    vw = val_bytes.shape[1]
    from_val = val_bytes[vrow, jnp.clip(rel, 0, vw - 1)]
    from_word = tokens[w[:, None], j_of_o]
    out = jnp.where(is_start, from_val, from_word)
    out = jnp.where(o[None, :] < out_len[:, None], out, jnp.uint8(0))

    emit = (
        lane_ok
        & ~clash
        & (chosen_count >= min_substitute)
        & (chosen_count <= max_substitute)
    )
    return out, out_len.astype(jnp.int32), w, emit

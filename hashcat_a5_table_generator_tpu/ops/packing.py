"""Wordlist packing: variable-length byte strings -> padded device tensors.

The reference streams the dictionary line by line through ``bufio.Scanner``
(``main.go:72-94``) and hands each word to a goroutine. The TPU path instead
packs words into fixed-shape batches ``uint8[B, width]`` + ``int32[B]``
lengths up front; length bucketing (16/32/64...) keeps padding waste low
across rockyou-class dictionaries.

This module is the numpy implementation; ``native/`` provides a C++ packer
with the same output contract for the file-to-arrays hot path (the analog of
the reference's scanner loop), and transparently falls back to this code.

Faithfulness notes (Q8): the reference's scanner silently ends input on a line
longer than 64 KiB and never checks ``scanner.Err()``. We do NOT copy that
hole: oversized lines raise unless ``max_word_bytes`` is explicitly lifted,
and I/O errors propagate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

#: Go bufio.Scanner default token limit (reference main.go Q8).
DEFAULT_MAX_WORD_BYTES = 64 * 1024

#: Default length-bucket boundaries (words longer than the last bucket get a
#: bucket of exactly their padded power-of-two width).
DEFAULT_BUCKETS = (16, 32, 64)


@dataclass(frozen=True)
class PackedWords:
    """A batch of words as device-ready padded arrays.

    ``tokens[i, :lengths[i]]`` are the word's bytes; the rest is zero padding.
    ``index[i]`` is the word's ordinal in the source wordlist — packing may
    bucket/reorder, and every downstream hit is reported against this index so
    results are always expressed in dictionary order.
    """

    tokens: np.ndarray  # uint8 [B, width]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — position in the original wordlist

    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def width(self) -> int:
        return int(self.tokens.shape[1])

    def word(self, i: int) -> bytes:
        return bytes(self.tokens[i, : self.lengths[i]])

    def words(self) -> List[bytes]:
        return [self.word(i) for i in range(self.batch)]


def aligned_width(longest: int) -> int:
    """The packing width for a longest-word length: smallest multiple of 4
    covering it (uint32 lane alignment for the hash kernels), minimum 4.
    Single source of truth for Python and native packers."""
    return max(4, -(-longest // 4) * 4)


def pack_words(
    words: Sequence[bytes],
    *,
    width: int | None = None,
    start_index: int = 0,
) -> PackedWords:
    """Pack ``words`` into one padded batch of a single width.

    ``width`` defaults to :func:`aligned_width` of the longest word.
    """
    if width is None:
        width = aligned_width(max((len(w) for w in words), default=0))
    tokens = np.zeros((len(words), width), dtype=np.uint8)
    lengths = np.zeros((len(words),), dtype=np.int32)
    for i, w in enumerate(words):
        if len(w) > width:
            raise ValueError(f"word {i} is {len(w)} bytes > width {width}")
        tokens[i, : len(w)] = np.frombuffer(w, dtype=np.uint8)
        lengths[i] = len(w)
    index = np.arange(start_index, start_index + len(words), dtype=np.int64)
    return PackedWords(tokens=tokens, lengths=lengths, index=index)


def validate_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Require strictly-ascending positive bucket boundaries.

    Shared by the Python (`bucket_words`, first-match in caller order) and
    native (`native.bucket_widths`, searchsorted) assignment paths so an
    unsorted tuple cannot make them assign different widths (advisor r2).
    An empty tuple is allowed: every word gets its own power-of-two width.
    """
    if list(buckets) != sorted(set(buckets)) or any(b < 1 for b in buckets):
        raise ValueError(
            f"buckets must be strictly ascending positive widths, got "
            f"{tuple(buckets)}"
        )
    return tuple(buckets)


def bucket_words(
    words: Sequence[bytes],
    *,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
    start_index: int = 0,
) -> Dict[int, PackedWords]:
    """Split ``words`` into length buckets, each packed at its bucket width.

    Returns ``{width: PackedWords}``; original wordlist positions are carried
    in each batch's ``index``. Words longer than the last bucket boundary get
    a power-of-two width of their own; words over ``max_word_bytes`` raise
    (the anti-Q8 guarantee).
    """
    validate_buckets(buckets)
    by_width: Dict[int, List[int]] = {}
    for i, w in enumerate(words):
        if len(w) > max_word_bytes:
            raise ValueError(
                f"word {start_index + i} is {len(w)} bytes > limit "
                f"{max_word_bytes} (Go would silently truncate here — Q8)"
            )
        width = next((b for b in buckets if len(w) <= b), None)
        if width is None:
            width = 4
            while width < len(w):
                width *= 2
        by_width.setdefault(width, []).append(i)

    out: Dict[int, PackedWords] = {}
    for width, idxs in sorted(by_width.items()):
        packed = pack_words([words[i] for i in idxs], width=width)
        out[width] = PackedWords(
            tokens=packed.tokens,
            lengths=packed.lengths,
            index=np.asarray([start_index + i for i in idxs], dtype=np.int64),
        )
    return out


def read_wordlist_lines(
    data: bytes,
    *,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Line structure of a wordlist buffer: (buffer, offsets, lengths),
    ScanLines semantics (see :func:`read_wordlist`). This is the numpy
    reference for the native scanner (``native.scan_wordlist_bytes``)."""
    buf = np.frombuffer(data, dtype=np.uint8)
    if len(data) == 0:
        empty64 = np.zeros(0, dtype=np.int64)
        return buf, empty64, np.zeros(0, dtype=np.int32)
    nl = np.nonzero(buf == 0x0A)[0]
    starts = np.concatenate([[0], nl + 1])
    ends = np.concatenate([nl, [len(data)]])
    if starts[-1] >= len(data) and data.endswith(b"\n"):
        starts, ends = starts[:-1], ends[:-1]
    lengths = ends - starts
    # Drop one trailing '\r' per line.
    has_cr = lengths > 0
    cr_pos = np.where(has_cr, starts + lengths - 1, 0)
    lengths = lengths - (has_cr & (buf[cr_pos] == 0x0D))
    if len(lengths) and int(lengths.max()) > max_word_bytes:
        bad = int(np.argmax(lengths > max_word_bytes))
        raise ValueError(f"line {bad} exceeds {max_word_bytes} bytes (Q8)")
    return buf, starts.astype(np.int64), lengths.astype(np.int32)


def read_wordlist(
    path: str,
    *,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> List[bytes]:
    """Read a dictionary file into a list of words (one per line).

    Mirrors ``bufio.ScanLines``: splits on ``\\n``, drops one trailing ``\\r``
    per line, and a final line without a newline still counts. Unlike the
    reference, an oversized line is an error, not a silent end of input (Q8).
    """
    with open(path, "rb") as fh:
        data = fh.read()
    words: List[bytes] = []
    if not data:
        return words
    for line in data.split(b"\n"):
        if line.endswith(b"\r"):
            line = line[:-1]
        if len(line) > max_word_bytes:
            raise ValueError(
                f"{path}: line {len(words)} exceeds {max_word_bytes} bytes (Q8)"
            )
        words.append(line)
    if data.endswith(b"\n"):
        words.pop()  # split() produced a trailing empty element, not a word
    return words


# ---------------------------------------------------------------------------
# Per-slot piece emission: precomputed piece tables (PERF.md §17)
# ---------------------------------------------------------------------------
#
# The fused kernels' "unit scheme" resolved output bytes per ORIGINAL byte
# position — O(L) per-lane selects even though only the <= S substitution
# slots vary per lane (PERF.md §7a ranked lever 1).  The per-slot scheme
# re-expresses a candidate as a short sequence of PIECES in output order:
# one piece per substitution site (its literal gap from the previous site
# folded in as a block-uniform prefix) plus one literal tail piece (with
# the 0x80 terminator folded into its precomputed bytes).  Everything
# block-uniform — gap bytes, skip bytes, value bytes, their lengths — is
# packed here, on the host, into per-word VARIANT tables: a piece's
# possible byte strings, one row per choice of its slot's digit.  Adjacent
# pieces whose combined worst-case length fits one u32 are merged into one
# GROUP whose variant table enumerates the combined choices, so the kernel
# selects a whole 4-byte group word with ONE N-way select and places it
# with ONE (lo, hi) word-pair scatter; lane-local work per group is the
# variant index, two selects (word + length), and a prefix-sum add.


@dataclass(frozen=True)
class PieceGroup:
    """Static shape of one emission group (see :class:`PieceSchema`).

    ``sel_cols``: the selector column ids (into the schema's column axis)
    whose digits index this group's variant table — low bit / least
    significant factor first; empty for the literal tail group.
    ``n_variants``/``n_words``: live extent inside the padded ``gw``/``gl``
    tables.  ``off_cap``: static upper bound on the group's output byte
    offset (sum of prior groups' data-max placed lengths over launched
    words × reachable variants).  ``off_floor``: the matching static
    LOWER bound.  Together they are the group's reachable byte window —
    the hierarchical-placement lever (PERF.md §18): the kernels place a
    group's words only inside ``[off_floor//4, off_cap//4 (+spill)]``
    instead of scanning from word 0, and a degenerate window
    (``off_floor == off_cap``) collapses the whole dynamic scatter to a
    static shift-OR.  ``len_fixed``: the group's placed length when it is
    the same for every launched word and reachable variant (None =
    varies) — a run of fixed groups keeps the running offset static.
    ``has_term``: the 0x80 terminator byte is folded into this group's
    variant bytes (always the last group), so its table lengths are
    placed-length = candidate bytes + 1.
    ``packed16``/``tab_idx``: where the group's variant words live —
    row ``tab_idx`` of the u16 ``gw16`` table (single-word groups whose
    every variant fits 2 bytes; halves their VMEM footprint) or of the
    u32 ``gw`` table (everything else).
    ``gl_idx``: the group's row in the sliced ``gl`` length table —
    meaningful only for dynamic-length groups (``len_fixed is None``);
    fixed-length groups never read a length row, so the table ships only
    the dynamic rows (the gw/gw16 split applied to lengths, PERF.md §19).
    """

    sel_cols: Tuple[int, ...]
    n_variants: int
    n_words: int
    off_cap: int
    has_term: bool = False
    off_floor: int = 0
    len_fixed: Optional[int] = None
    packed16: bool = False
    tab_idx: int = 0
    gl_idx: int = 0


@dataclass(frozen=True)
class PieceSchema:
    """Host-precomputed per-slot emission plan for one (plan, table) pair.

    Data tables (numpy; gathered per block by the wrappers):
      ``gw`` uint32 [B, NGW, VM, NW] — wide groups' variant words
      (little-endian packed bytes; ``None`` when every group packs to
      u16), ``gw16`` uint16 [B, NG16, VM] — narrow single-word groups
      whose every variant fits 2 bytes (``None`` when no group
      qualifies; the per-group ``packed16`` gate, PERF.md §18),
      ``gl`` uint8 [B, NGD, VM] — placed byte lengths of the
      DYNAMIC-length groups only, in emission order (fixed-length
      groups fold their length into the static prefix offset and never
      read a row; ``None`` when every group is fixed — all-fixed
      schemas ship no length table at all, PERF.md §19).
      ``sel_bit`` uint8 [B, C] — the chosen-bit position of each selector
      column's slot in the packed chosen vector (suball plans; match
      plans' column c IS slot/bit c, so ``None``).
      ``sel_slot`` int32 [B, C] — the decode slot driving each column
      (suball plans; ``None`` = identity).

    ``groups`` is the static emission order; ``closed`` marks cascade-
    closed suball plans (variant index = 1 + joint value index instead of
    the raw digit).  ``max_out`` bounds every lane's placed bytes
    (including the terminator) — the static placement budget.

    Pair-lane tier (PERF.md §24): ``pair_ok`` marks schemas whose
    geometry admits K=2 candidates per hash lane — consecutive
    combination ranks ``2r`` / ``2r+1`` share one index decompose
    (every launched word's innermost slot has EVEN radix, so the
    partner's digit vector is the base's with slot 0's digit + 1) and
    differ only in the variant of ONE static emission group
    (``pair_g0``, the group whose selector columns start with column
    0).  ``pair_dmin``/``pair_dmax`` statically bound the partner-
    minus-base placed-length delta of that group over launched rows ×
    reachable pairs — the kernels widen the suffix groups' placement
    windows by exactly this range (a 0/0 bound collapses the partner
    to a pure patch of the innermost group's words).
    """

    kind: str  # "match" | "suball"
    groups: Tuple[PieceGroup, ...]
    gw: Optional[np.ndarray]
    gl: Optional[np.ndarray]
    gw16: Optional[np.ndarray] = None
    sel_bit: Optional[np.ndarray] = None
    sel_slot: Optional[np.ndarray] = None
    closed: bool = False
    max_out: int = 0
    n_cols: int = 0
    pair_ok: bool = False
    pair_g0: int = 0
    pair_dmin: int = 0
    pair_dmax: int = 0

    @property
    def num_groups(self) -> int:
        return len(self.groups)


#: Grouping caps: a merged group's worst-case bytes must fit one u32, its
#: variant table at most ``_MAX_GROUP_VARIANTS`` rows (memory: tables are
#: per word), and a standalone piece at most ``_MAX_PIECE_WORDS`` u32s
#: (beyond that the per-byte scan is the better formulation anyway).
_MAX_GROUP_BYTES = 4
_MAX_GROUP_VARIANTS = 4
_MAX_PIECE_WORDS = 4
#: Widest single-column variant table (cascade closure's joint tables
#: reach 12 rows + skip).
_MAX_COL_VARIANTS = 13


def _col_val_len(col_opts, col_vstart, val_len, vmax):
    """Per-(word, column, option) value lengths ``[B, C, vmax]`` (0 past a
    column's own option count)."""
    b, c = col_opts.shape
    out = np.zeros((b, c, max(vmax, 1)), np.int32)
    nrows = val_len.shape[0]
    for v in range(vmax):
        row = np.clip(col_vstart + v, 0, max(nrows - 1, 0))
        out[:, :, v] = np.where(col_opts > v, val_len[row], 0)
    return out


def build_piece_schema(
    tokens: np.ndarray,  # uint8 [B, L]
    lengths: np.ndarray,  # int32 [B]
    col_pos: np.ndarray,  # int32 [B, C] — span start (output order)
    col_len: np.ndarray,  # int32 [B, C] — span length, 0 = no span
    col_opts: np.ndarray,  # int32 [B, C] — selectable options (0 = literal)
    col_vstart: np.ndarray,  # int32 [B, C] — value row of option 1
    val_bytes: np.ndarray,  # uint8 [V, W]
    val_len: np.ndarray,  # int32 [V]
    *,
    kind: str,
    sel_slot: "np.ndarray | None" = None,  # int32 [B, C]
    sel_bit: "np.ndarray | None" = None,  # int32 [B, C]
    closed: bool = False,
    launched: "np.ndarray | None" = None,  # bool [B] — device-launched rows
) -> "PieceSchema | None":
    """Build the per-slot piece tables, or None when the plan's geometry
    cannot take the scheme (static spans unsorted/overlapping, a piece
    past the word cap, or a variant table past the row cap).

    Columns are substitution sites in OUTPUT order; each column's piece is
    the literal gap since the previous site (block-uniform bytes) plus the
    site's span — original bytes when skipped (variant 0), the chosen
    option's value bytes otherwise.  A final tail column carries the
    trailing literals plus the 0x80 terminator (for NTLM's UTF-16LE
    expansion the terminator pseudo-byte expands to exactly the padded
    message's ``80 00`` pair, so no kernel terminator scan remains).

    ``launched`` masks the rows the device will actually launch (suball
    plans route hazard words to the oracle): the per-group placement
    windows ``off_floor``/``off_cap`` — and the ``len_fixed`` static-run
    detection — are computed over launched rows × reachable variants
    only, so an oracle-routed word's degenerate columns cannot widen the
    hierarchical-placement windows for everyone else (PERF.md §18).
    """
    b, length_axis = tokens.shape
    c_axis = col_pos.shape[1]
    if b == 0:
        return None
    launched_rows = (
        np.ones(b, bool) if launched is None else np.asarray(launched, bool)
    )
    if not launched_rows.any():
        return None  # every word oracle-routed; the schema would be unused
    lengths = lengths.astype(np.int64)
    has_span = col_len > 0
    # Effective span starts: spanless columns sit at the running cursor so
    # gap arithmetic stays monotone.
    prev_end = np.zeros(b, np.int64)
    gap_start = np.zeros((b, c_axis), np.int64)
    gap_len = np.zeros((b, c_axis), np.int64)
    for c in range(c_axis):
        pos_c = np.where(has_span[:, c], col_pos[:, c].astype(np.int64),
                         prev_end)
        g = pos_c - prev_end
        if (g < 0).any():
            return None  # overlapping or unsorted static spans
        gap_start[:, c] = prev_end
        gap_len[:, c] = g
        end_c = pos_c + np.where(has_span[:, c], col_len[:, c], 0)
        if (end_c > lengths).any():
            return None
        prev_end = end_c
    tail_start = prev_end
    tail_len = lengths - tail_start
    if (tail_len < 0).any():
        return None

    opts_max = [int(col_opts[:, c].max(initial=0)) for c in range(c_axis)]
    if any(o + 1 > _MAX_COL_VARIANTS for o in opts_max):
        return None
    vl3 = _col_val_len(col_opts, col_vstart, val_len, max(opts_max or [0]))

    # --- emission columns: the output-order byte stream ------------------
    # Literal runs (gaps between sites, and the trailing tail + 0x80
    # terminator) are SPLIT into <=4-byte chunks, each a variant-free
    # column — a matchless 16-byte bucket word must not veto the whole
    # plan by demanding one 17-byte piece.  Selector columns carry only
    # their own span (skip) / value variants.
    ecols: List[dict] = []

    def add_lit(start, run_len, *, term):
        total = run_len + (1 if term else 0)  # +1: terminator byte
        for k in range(0, int(total.max(initial=0)), 4):
            ecols.append({
                "kind": "lit", "start": start, "src_len": run_len,
                "off": k, "term": term,
                "max": int(np.clip(total - k, 0, 4).max(initial=0)),
            })

    for c in range(c_axis):
        add_lit(gap_start[:, c], gap_len[:, c], term=False)
        widest = np.maximum(
            np.where(has_span[:, c], col_len[:, c], 0),
            vl3[:, c, : max(opts_max[c], 1)].max(axis=1)
            if opts_max[c] else 0,
        )
        mx = int(widest.max(initial=0))
        if mx == 0 and opts_max[c] == 0:
            continue  # padding column in every word
        ecols.append({"kind": "sel", "c": c, "max": mx})
    add_lit(tail_start, tail_len, term=True)

    # --- static grouping: greedy adjacent packing -----------------------
    # A group merges consecutive emission columns while (a) worst-case
    # bytes fit one u32, (b) the variant product stays small, (c) every
    # merged selector column is binary (the kernel indexes merged groups
    # by packed chosen bits).  A column too wide to merge stands alone
    # with ceil(maxlen/4) words.
    specs: List[List[dict]] = []
    cur: "List[dict] | None" = None

    def col_variants(e):
        return opts_max[e["c"]] + 1 if e["kind"] == "sel" else 1

    def cur_bytes(spec):
        return sum(e["max"] for e in spec)

    def cur_variants(spec):
        v = 1
        for e in spec:
            v *= col_variants(e)
        return v

    for e in ecols:
        v_c = col_variants(e)
        sel_after = (
            [] if cur is None
            else [x for x in cur if col_variants(x) > 1]
        ) + ([e] if v_c > 1 else [])
        can_merge = (
            cur is not None
            and cur_bytes(cur) + e["max"] <= _MAX_GROUP_BYTES
            and cur_variants(cur) * v_c <= _MAX_GROUP_VARIANTS
            and (len(sel_after) <= 1
                 or all(col_variants(x) == 2 for x in sel_after))
        )
        if can_merge:
            cur.append(e)
        else:
            if cur is not None:
                specs.append(cur)
            cur = [e]
    if cur is not None:
        specs.append(cur)
    if not specs:
        return None

    ng = len(specs)
    vmax = max(cur_variants(s) for s in specs)
    nwmax = max(-(-max(cur_bytes(s), 1) // 4) for s in specs)
    if nwmax > _MAX_PIECE_WORDS or vmax > max(
        _MAX_GROUP_VARIANTS, _MAX_COL_VARIANTS
    ):
        return None

    gb = np.zeros((b, ng, vmax, nwmax * 4), np.uint8)
    gl = np.zeros((b, ng, vmax), np.int64)
    #: variant (gi, vi) is reachable for word b — the kernels can select
    #: it on an EMITTED lane (a selector digit d needs col_opts >= d).
    #: Bounds the placement windows; unreachable variants only ever feed
    #: masked garbage lanes.
    reach = np.zeros((b, ng, vmax), bool)
    nrows = val_bytes.shape[0]
    vw = val_bytes.shape[1]
    rows_iota = np.arange(b)

    def emit_bytes(gi, vi, at_len, data, dlen):
        """OR bytes ([B, K] u8 + [B] length) into group (gi, vi) at the
        running per-word offset ``at_len``; returns the new offset."""
        for j in range(data.shape[1]):
            live = j < dlen
            pos = np.clip(at_len + j, 0, nwmax * 4 - 1)
            old = gb[rows_iota, gi, vi, pos]
            gb[rows_iota, gi, vi, pos] = np.where(live, data[:, j], old)
        return at_len + dlen

    def gather_tok(start, width):
        if width == 0:
            return np.zeros((b, 0), np.uint8)
        idx = np.clip(
            start[:, None] + np.arange(width)[None, :], 0, length_axis - 1
        )
        return np.take_along_axis(tokens, idx.astype(np.int64), axis=1)

    def lit_chunk(e):
        """One <=4-byte literal chunk: bytes [off, off+4) of the run
        (plus the 0x80 terminator at the run's own end for the tail)."""
        rel = e["src_len"] - e["off"]  # bytes of the run in/after chunk
        width = e["max"]
        data = gather_tok(e["start"] + e["off"], width)
        for j in range(width):
            dead = rel <= j
            data[:, j] = np.where(dead, 0, data[:, j])
            if e["term"]:
                data[:, j] = np.where(rel == j, 0x80, data[:, j])
        ln = np.clip(rel + (1 if e["term"] else 0), 0, 4)
        return data, ln

    for gi, spec in enumerate(specs):
        sel = [e["c"] for e in spec if col_variants(e) > 1]
        n_var = cur_variants(spec)
        for vi in range(n_var):
            # Decompose the variant index into per-selector digits,
            # low column first (the kernel packs bits the same way).
            digits = {}
            rem = vi
            rch = np.ones(b, bool)
            for c in sel:
                digits[c] = rem % (opts_max[c] + 1)
                rem //= opts_max[c] + 1
                if digits[c] > 0:
                    rch &= col_opts[:, c] >= digits[c]
            reach[:, gi, vi] = rch
            at = np.zeros(b, np.int64)
            for e in spec:
                if e["kind"] == "lit":
                    data, ln = lit_chunk(e)
                    at = emit_bytes(gi, vi, at, data, ln)
                    continue
                c = e["c"]
                d = digits.get(c, 0)
                if d == 0:
                    ln = np.where(has_span[:, c], col_len[:, c], 0
                                  ).astype(np.int64)
                    data = gather_tok(gap_start[:, c] + gap_len[:, c],
                                      int(col_len[:, c].max(initial=0)))
                else:
                    row = np.clip(col_vstart[:, c] + d - 1, 0,
                                  max(nrows - 1, 0))
                    ln = np.where(
                        col_opts[:, c] >= d, vl3[:, c, d - 1], 0
                    ).astype(np.int64)
                    data = val_bytes[row][:, :vw]
                at = emit_bytes(gi, vi, at, data, ln)
            gl[:, gi, vi] = at

    gw = np.zeros((b, ng, vmax, nwmax), np.uint32)
    for w in range(nwmax):
        for k in range(4):
            gw[:, :, :, w] |= gb[:, :, :, 4 * w + k].astype(
                np.uint32
            ) << np.uint32(8 * k)

    # Per-group placed-length extrema over launched rows × reachable
    # variants — the hierarchical-placement windows (PERF.md §18).
    big = 1 << 30
    live = reach[launched_rows]
    glv = gl[launched_rows]
    gwv = gw[launched_rows]
    g_min = np.where(live, glv, big).min(axis=(0, 2))
    g_max = np.where(live, glv, -1).max(axis=(0, 2))

    groups = []
    floor_off = cap_off = 0
    n16 = nwide = n_dyn = 0
    for gi, spec in enumerate(specs):
        sel = tuple(e["c"] for e in spec if col_variants(e) > 1)
        nbytes = cur_bytes(spec)
        n_words = -(-max(nbytes, 1) // 4)
        mn, mx = int(g_min[gi]), int(g_max[gi])
        # 16-bit table gate: single-word groups whose every variant word
        # fits 2 bytes move to the u16 ``gw16`` table (halved VMEM
        # loads).  Like the placement windows above, the gate maxes over
        # launched rows × reachable variants only — a fallback word's or
        # unreachable variant's wide entry must not keep everyone else
        # in the u32 table (each row is read only by its own word, so
        # the u16 cast truncating a masked-out entry is unobservable).
        p16 = n_words == 1 and int(
            np.where(live[:, gi], gwv[:, gi, :, 0], 0).max(initial=0)
        ) < (1 << 16)
        groups.append(
            PieceGroup(
                sel_cols=sel,
                n_variants=cur_variants(spec),
                n_words=n_words,
                off_cap=cap_off,
                has_term=any(e["kind"] == "lit" and e["term"]
                             for e in spec),
                off_floor=floor_off,
                len_fixed=mn if mn == mx else None,
                packed16=p16,
                tab_idx=n16 if p16 else nwide,
                gl_idx=n_dyn if mn != mx else 0,
            )
        )
        if p16:
            n16 += 1
        else:
            nwide += 1
        if mn != mx:
            n_dyn += 1
        floor_off += mn
        cap_off += mx

    # --- pair-lane gate (PERF.md §24) --------------------------------
    # K=2 candidates per hash lane need consecutive ranks 2r / 2r+1 to
    # share one index decompose and differ in ONE static group's
    # variant: (a) every launched word's innermost slot (column 0) has
    # EVEN radix (odd ``col_opts``) — or the word has no variants at
    # all, so its lone partner lane is masked; (b) column 0 is the
    # LOWEST selector factor of its group (construction order
    # guarantees ascending ``sel_cols``, so this is "first"); (c) for
    # suball schemas, slot 0 drives column 0 and ONLY column 0 on
    # every launched row (a pattern occurring twice would patch two
    # groups); closed schemas keep K=1 (the joint index couples
    # columns).  ``pair_dmin/dmax`` bound the partner-minus-base
    # placed-length delta of the pair group over launched rows ×
    # reachable (even, odd) variant pairs.
    pair_ok, pair_g0, pair_dmin, pair_dmax = _pair_gate(
        groups, col_opts, launched_rows, gl, reach,
        kind=kind, closed=closed, sel_slot=sel_slot, sel_bit=sel_bit,
    )

    wide_idx = [gi for gi, grp in enumerate(groups) if not grp.packed16]
    p16_idx = [gi for gi, grp in enumerate(groups) if grp.packed16]
    gw_wide = gw[:, wide_idx] if wide_idx else None
    gw16 = (
        # (index then slice — a list at axis 1 combined with the basic
        # integer 0 at axis 3 would hoist the advanced axes to the front)
        gw[:, p16_idx][..., 0].astype(np.uint16) if p16_idx else None
    )
    # Length-table slicing (PERF.md §19): fixed-length groups fold their
    # length into the static prefix and never read a row, so the shipped
    # ``gl`` keeps only the dynamic groups' rows (the gw/gw16 split
    # applied to lengths); an all-fixed schema ships no table at all.
    dyn_idx = [gi for gi, grp in enumerate(groups)
               if grp.len_fixed is None]
    gl_dyn = gl[:, dyn_idx].astype(np.uint8) if dyn_idx else None

    return PieceSchema(
        kind=kind,
        groups=tuple(groups),
        gw=gw_wide,
        gl=gl_dyn,
        gw16=gw16,
        sel_bit=None if sel_bit is None else sel_bit.astype(np.uint8),
        sel_slot=None if sel_slot is None else sel_slot.astype(np.int32),
        closed=closed,
        max_out=cap_off,
        n_cols=c_axis,
        pair_ok=pair_ok,
        pair_g0=pair_g0,
        pair_dmin=pair_dmin,
        pair_dmax=pair_dmax,
    )


def _pair_gate(groups, col_opts, launched_rows, gl, reach, *,
               kind, closed, sel_slot, sel_bit):
    """The schema-level half of the pair-lane eligibility (see
    :class:`PieceSchema`): returns ``(pair_ok, g0, dmin, dmax)``.
    Wrapper-level facts (hash-block count, windowed decode, env hatch)
    are checked by ``pallas_expand.pair_for_config``."""
    if closed:
        return False, 0, 0, 0
    g0 = next(
        (gi for gi, grp in enumerate(groups) if 0 in grp.sel_cols), None
    )
    if g0 is None:
        return False, 0, 0, 0
    if groups[g0].sel_cols[0] != 0:
        return False, 0, 0, 0
    rows = launched_rows
    opts0 = np.asarray(col_opts)[:, 0]
    inert = (np.asarray(col_opts) == 0).all(axis=1)
    row_ok = (opts0 % 2 == 1) | inert
    if kind == "suball":
        # Column 0 must be driven by slot 0 (bit 0 of the packed
        # chosen vector) and slot 0 by NO other column.
        c_axis = col_opts.shape[1]
        slot0_cols = (np.asarray(sel_slot) == 0) & (
            np.asarray(col_opts) > 0
        )
        drives_only_c0 = slot0_cols[:, 1:].sum(axis=1) == 0 \
            if c_axis > 1 else np.ones(len(opts0), bool)
        col0_is_slot0 = (
            (np.asarray(sel_slot)[:, 0] == 0)
            & (np.asarray(sel_bit)[:, 0] == 0)
        ) | (opts0 == 0)
        row_ok = row_ok & col0_is_slot0 & drives_only_c0
    if not row_ok[rows].all():
        return False, 0, 0, 0
    # Partner-minus-base length delta of the pair group over launched
    # rows × reachable (even, odd) variant pairs.  Column 0 is the
    # lowest factor, so pairs are consecutive variant indices (2i,
    # 2i+1).
    grp = groups[g0]
    if grp.len_fixed is not None:
        return True, g0, 0, 0
    n_var = grp.n_variants
    glv = gl[rows][:, g0, :]
    rch = reach[rows][:, g0, :]
    dmin, dmax = 0, 0
    found = False
    for v in range(0, n_var - 1, 2):
        both = rch[:, v] & rch[:, v + 1]
        if not both.any():
            continue
        d = (glv[:, v + 1] - glv[:, v])[both]
        dmin = int(d.min()) if not found else min(dmin, int(d.min()))
        dmax = int(d.max()) if not found else max(dmax, int(d.max()))
        found = True
    return True, g0, dmin, dmax


def _suball_piece_cols(plan) -> "tuple | None":
    """Per-column arrays for a substitute-all plan: one column per PATTERN
    segment (occurrence), in word order, with gap segments folded into the
    following column's literal prefix by interval arithmetic.  Returns
    ``(pos, ln, opts, vstart, sel_slot, sel_bit, closed)`` or None."""
    seg_pat = np.asarray(plan.seg_pat)
    seg_start = np.asarray(plan.seg_orig_start)
    seg_len = np.asarray(plan.seg_orig_len)
    radix = np.asarray(plan.pat_radix)
    pvs = np.asarray(plan.pat_val_start)
    b, _ = seg_pat.shape
    p = radix.shape[1]
    is_pat = seg_pat >= 0
    fb = np.asarray(plan.fallback)
    if fb.any():
        # Oracle-routed words never reach the device; blank their columns
        # so their (possibly degenerate) segment data can't veto the
        # schema for everyone else.
        is_pat = is_pat & ~fb[:, None]
    c_axis = max(1, int(is_pat.sum(axis=1).max(initial=0)))
    cols = np.cumsum(is_pat, axis=1) - 1
    rows, segs = np.nonzero(is_pat)
    cc = cols[rows, segs]
    pos = np.zeros((b, c_axis), np.int32)
    ln = np.zeros((b, c_axis), np.int32)
    slot = np.zeros((b, c_axis), np.int32)
    pos[rows, cc] = seg_start[rows, segs]
    ln[rows, cc] = seg_len[rows, segs]
    slot[rows, cc] = seg_pat[rows, segs]
    # Joint-closure plans: a slot's value row is indexed by the JOINT
    # digit (own + successors), so the column's variant count is the
    # joint table's row count, not radix - 1.
    closed = getattr(plan, "close_next", None) is not None
    if closed:
        cn = np.asarray(plan.close_next)
        cm = np.asarray(plan.close_mul)
        succ_r = np.where(
            cn >= 0,
            np.take_along_axis(
                radix, np.clip(cn, 0, p - 1).reshape(b, -1), axis=1
            ).reshape(cn.shape),
            1,
        )
        # Own digit d is in [1, radix-1] when the slot is chosen, so the
        # kernel's (d-1)*mul0 term peaks at (radix-2)*mul0.
        jmax = (radix - 2).clip(min=0) * cm[:, :, 0] + (
            (succ_r - 1) * cm[:, :, 1:]
        ).sum(axis=2)
        slot_opts = np.where(radix > 1, jmax + 1, 0)
    else:
        slot_opts = (radix - 1).clip(min=0)
    act = (radix > 1).astype(np.int32)
    bitpos = np.cumsum(act, axis=1) - act
    take = lambda a: np.take_along_axis(a, slot, axis=1)  # noqa: E731
    opts = np.where(ln > 0, take(slot_opts), 0)
    vstart = take(pvs)
    sel_bit = np.where(ln > 0, take(bitpos), 31)
    return pos, ln, opts, vstart, slot, sel_bit, closed


def piece_schema_for(plan, ct, cache_dir: "str | None" = None,
                     max_mb: "float | None" = None
                     ) -> "PieceSchema | None":
    """The per-slot emission gate: a :class:`PieceSchema` when the plan's
    static geometry supports piece emission (and ``A5GEN_EMIT`` doesn't
    opt out), else None — callers fall back to the per-byte unit scan.

    The schema's tables are ``gw uint32 [B, NG, VM, NW]`` group variant
    words and ``gl uint8 [B, NGD, VM]`` placed lengths (plus suball's
    ``sel_slot int32 [B, C]`` / ``sel_bit uint8 [B, C]`` selector
    columns).  Cached on the plan object (plans are frozen, keyed by
    table identity), like ``pallas_expand.scalar_units_fields``.

    ``cache_dir`` (or ``A5GEN_SCHEMA_CACHE``) additionally persists the
    compiled schema on disk, keyed by a digest of the exact build inputs
    (word tokens, column geometry, value tables) + the schema format
    version — repeat sweeps of the same wordlist × table skip the
    compile entirely (the compile-once seam of the service mode,
    ROADMAP item 1).  ``max_mb`` caps the cache directory's size:
    after a write, oldest-atime entries are evicted until it fits
    (:func:`enforce_schema_cache_cap` — long-lived engine hygiene,
    PERF.md §20)."""
    from ..runtime.env import emit_scheme, schema_cache_dir

    if emit_scheme() != "perslot":
        return None
    cache = getattr(plan, "_piece_schema_cache", None)
    if cache is not None and cache[0] is ct:
        return cache[1]
    tokens = np.asarray(plan.tokens)
    lengths = np.asarray(plan.lengths)
    launched = ~np.asarray(plan.fallback, bool)
    build_kw = None
    if getattr(plan, "match_pos", None) is not None:
        radix = np.asarray(plan.match_radix)
        build_kw = dict(
            tokens=tokens, lengths=lengths,
            col_pos=np.asarray(plan.match_pos),
            col_len=np.asarray(plan.match_len),
            col_opts=(radix - 1).clip(min=0),
            col_vstart=np.asarray(plan.match_val_start),
            val_bytes=np.asarray(ct.val_bytes),
            val_len=np.asarray(ct.val_len),
            kind="match", launched=launched,
        )
    else:
        cols = _suball_piece_cols(plan)
        if cols is not None:
            pos, ln, opts, vstart, slot, sel_bit, closed = cols
            vb = getattr(plan, "cval_bytes", None)
            vl = getattr(plan, "cval_len", None)
            if vb is None:
                vb, vl = np.asarray(ct.val_bytes), np.asarray(ct.val_len)
            build_kw = dict(
                tokens=tokens, lengths=lengths,
                col_pos=pos, col_len=ln, col_opts=opts, col_vstart=vstart,
                val_bytes=np.asarray(vb), val_len=np.asarray(vl),
                kind="suball", sel_slot=slot, sel_bit=sel_bit,
                closed=closed, launched=launched,
            )
    if build_kw is None:
        schema = None
    else:
        if cache_dir is None:
            cache_dir = schema_cache_dir()
        if cache_dir:
            key = _schema_cache_key(build_kw)
            hit, schema = load_piece_schema(cache_dir, key)
            if not hit:
                schema = build_piece_schema(**build_kw)
                save_piece_schema(cache_dir, key, schema)
                if max_mb is not None:
                    enforce_schema_cache_cap(cache_dir, max_mb)
        else:
            schema = build_piece_schema(**build_kw)
    try:
        object.__setattr__(plan, "_piece_schema_cache", (ct, schema))
    except AttributeError:  # pragma: no cover - non-dataclass plan stubs
        pass
    return schema


# ---------------------------------------------------------------------------
# On-disk PieceSchema cache (ROADMAP item 1's compile-once seam)
# ---------------------------------------------------------------------------

#: Bump on ANY change to the PieceSchema layout or the grouping rules —
#: the version is part of the cache key, so stale entries are simply
#: never looked up again (no in-place migration).  v2: pair-lane gate
#: fields (PERF.md §24).
SCHEMA_CACHE_VERSION = 2

#: Process-wide on-disk schema-cache instrumentation (PERF.md §20):
#: hits/misses/bytes through :func:`load_piece_schema` /
#: :func:`save_piece_schema` plus LRU-cap evictions.  A long-lived
#: engine process needs these to tell compile-once from
#: compile-every-job; ``SweepResult.schema_cache`` reports per-run
#: deltas and the resident engine reports process totals.  The storage
#: is the process-wide telemetry registry (PERF.md §21) — this module
#: keeps only the derived dict view callers always consumed.
_SCHEMA_CACHE_KEYS = (
    "hits", "misses", "bytes_read", "bytes_written", "evictions",
)


def schema_cache_stats() -> dict:
    """Snapshot of the process-level schema-cache counters — each a
    plain scalar int: hits / misses / bytes read / bytes written /
    evictions.  A derived view of the ``schema_cache.*`` telemetry
    counters (one source of truth; the registry's snapshot/delta/merge
    subsume the old bespoke dict)."""
    from ..runtime.telemetry import counter

    return {
        k: int(counter(f"schema_cache.{k}").value)
        for k in _SCHEMA_CACHE_KEYS
    }


def _count_cache(**deltas: int) -> None:
    from ..runtime.telemetry import counter

    for key, d in deltas.items():
        counter(f"schema_cache.{key}").add(int(d))


def enforce_schema_cache_cap(cache_dir: str, max_mb: float) -> int:
    """LRU size cap for a long-lived process's schema cache: evict
    oldest-ATIME entries until the total bytes of the directory's
    ``*.npz`` entries fit ``max_mb`` (reads touch atime, so
    recently-hit entries survive —
    subject to the filesystem's atime policy, which on relatime mounts
    is granular but monotonic enough for an eviction ORDER).  Returns
    the number of entries evicted; racing processes are tolerated (a
    concurrently-deleted entry is skipped, and eviction of an entry
    another process still wants is just a future miss — corrupt/absent
    entries were already miss-not-error)."""
    import os

    cap = int(max_mb * (1 << 20))
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return 0
    entries = []
    for name in names:
        if not name.endswith(".npz"):
            continue
        path = os.path.join(cache_dir, name)
        try:
            st = os.stat(path)
        except OSError:  # pragma: no cover - concurrent eviction
            continue
        entries.append((st.st_atime, st.st_size, path))
    total = sum(size for _, size, _ in entries)
    if total <= cap:
        return 0
    evicted = 0
    for _atime, size, path in sorted(entries):
        if total <= cap:
            break
        try:
            os.unlink(path)
        except OSError:  # pragma: no cover - concurrent eviction
            continue
        total -= size
        evicted += 1
    if evicted:
        _count_cache(evictions=evicted)
    return evicted

#: PieceGroup fields serialized into a cache entry's JSON header, in
#: constructor order.
_GROUP_FIELDS = ("sel_cols", "n_variants", "n_words", "off_cap", "has_term",
                 "off_floor", "len_fixed", "packed16", "tab_idx", "gl_idx")

_SCHEMA_ARRAYS = ("gw", "gl", "gw16", "sel_bit", "sel_slot")


def _schema_cache_key(build_kw: dict) -> str:
    """Digest of the exact :func:`build_piece_schema` inputs + format
    version: dtype/shape/bytes of every array, the kind/closed flags, and
    the grouping caps (a cap change regroups without a code change to the
    schema layout itself)."""
    import hashlib

    h = hashlib.sha256()
    h.update(
        f"a5gen-piece-schema|v{SCHEMA_CACHE_VERSION}"
        f"|{build_kw['kind']}|{int(bool(build_kw.get('closed')))}"
        f"|{_MAX_GROUP_BYTES},{_MAX_GROUP_VARIANTS}"
        f",{_MAX_PIECE_WORDS},{_MAX_COL_VARIANTS}|".encode()
    )
    for name in ("tokens", "lengths", "col_pos", "col_len", "col_opts",
                 "col_vstart", "val_bytes", "val_len", "sel_slot",
                 "sel_bit", "launched"):
        arr = build_kw.get(name)
        if arr is None:
            h.update(b"|-|")
            continue
        arr = np.ascontiguousarray(arr)
        h.update(f"|{name}:{arr.dtype}:{arr.shape}|".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def save_piece_schema(cache_dir: str, key: str,
                      schema: "PieceSchema | None") -> None:
    """Persist one cache entry atomically AND durably
    (``checkpoint.atomic_write_bytes``: tmp + data fsync + rename +
    directory fsync): the schema's arrays (``gw`` uint32, ``gl``
    uint8, ``gw16`` uint16, ``sel_bit`` uint8, ``sel_slot`` int32 —
    whichever are present) as npz members plus a JSON header with the
    static group structure.  ``None`` (the plan's geometry refuses
    piece emission) is cached too — the refusal walk is not free and
    the answer is as deterministic as the schema.

    The durable-replace discipline is what makes ONE cache directory
    safe as a fleet-wide artifact store (PERF.md §25): N engines
    racing on the same key each rename a fully-synced entry into
    place — a reader sees some complete entry or none, never a torn
    one (tmp names are pid-qualified, so concurrent writers never
    collide on the tmp file either)."""
    import io
    import json
    import os

    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"{key}.npz")
    if schema is None:
        header = {"version": SCHEMA_CACHE_VERSION, "schema": None}
        arrays = {}
    else:
        header = {
            "version": SCHEMA_CACHE_VERSION,
            "schema": {
                "kind": schema.kind,
                "closed": bool(schema.closed),
                "max_out": int(schema.max_out),
                "n_cols": int(schema.n_cols),
                "pair_ok": bool(schema.pair_ok),
                "pair_g0": int(schema.pair_g0),
                "pair_dmin": int(schema.pair_dmin),
                "pair_dmax": int(schema.pair_dmax),
                "groups": [
                    {f: getattr(g, f) for f in _GROUP_FIELDS}
                    for g in schema.groups
                ],
            },
        }
        arrays = {
            name: getattr(schema, name)
            for name in _SCHEMA_ARRAYS
            if getattr(schema, name) is not None
        }
    from ..runtime.checkpoint import atomic_write_bytes

    buf = io.BytesIO()
    np.savez(buf, header=np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8
    ), **arrays)
    blob = buf.getvalue()
    try:
        atomic_write_bytes(path, blob)
        _count_cache(bytes_written=len(blob))
    except OSError:  # pragma: no cover - cache dir races/ENOSPC
        # The cache is an accelerator, never a correctness dependency:
        # a failed write just means the next run recompiles (the
        # writer cleaned its own tmp file).
        pass


def load_piece_schema(cache_dir: str, key: str
                      ) -> "Tuple[bool, PieceSchema | None]":
    """Load one cache entry: ``(hit, schema)``.  A missing, corrupt, or
    version-mismatched entry is a miss (the caller rebuilds and
    overwrites) — never an error."""
    import json
    import os

    path = os.path.join(cache_dir, f"{key}.npz")
    if not os.path.exists(path):
        _count_cache(misses=1)
        return False, None
    try:
        nbytes = os.stat(path).st_size
        with np.load(path, allow_pickle=False) as data:
            header = json.loads(bytes(data["header"]).decode())
            if header.get("version") != SCHEMA_CACHE_VERSION:
                _count_cache(misses=1)
                return False, None
            meta = header["schema"]
            if meta is None:
                _count_cache(hits=1, bytes_read=nbytes)
                return True, None
            groups = tuple(
                PieceGroup(**{
                    **g, "sel_cols": tuple(g["sel_cols"]),
                })
                for g in meta["groups"]
            )
            arrays = {
                name: (np.asarray(data[name]) if name in data else None)
                for name in _SCHEMA_ARRAYS
            }
            _count_cache(hits=1, bytes_read=nbytes)
            return True, PieceSchema(
                kind=meta["kind"],
                groups=groups,
                closed=bool(meta["closed"]),
                max_out=int(meta["max_out"]),
                n_cols=int(meta["n_cols"]),
                pair_ok=bool(meta["pair_ok"]),
                pair_g0=int(meta["pair_g0"]),
                pair_dmin=int(meta["pair_dmin"]),
                pair_dmax=int(meta["pair_dmax"]),
                **arrays,
            )
    except (OSError, KeyError, ValueError, json.JSONDecodeError):
        _count_cache(misses=1)
        return False, None


# ---------------------------------------------------------------------------
# Streaming ingestion: chunked plan compilation (PERF.md §19)
# ---------------------------------------------------------------------------
#
# Hashmob-scale dictionaries (10^8+ words) must not bound resident memory
# or time-to-first-candidate: the sweep runtime splits the packed batch
# into word CHUNKS, compiles each chunk's plan + PieceSchema + device
# arrays on a host worker thread while the device sweeps the previous
# chunk, and frees consumed chunks — resident plan state is O(ring ×
# chunk), independent of dictionary length.  This module owns the
# generic pieces (slicing, sizing, the bounded compile ring); the sweep
# runtime injects the actual compile function (plans are a models-layer
# concern).


def slice_packed(packed: PackedWords, lo: int, hi: int) -> PackedWords:
    """Word rows ``[lo, hi)`` as a zero-copy view batch — ``tokens``
    uint8 [hi-lo, width], ``lengths`` int32 [hi-lo], ``index`` int64
    [hi-lo]: the slice keeps the parent's width and original dictionary
    indices, so hits from a chunk report the same positions the
    whole-batch plan would."""
    return PackedWords(
        tokens=packed.tokens[lo:hi],
        lengths=packed.lengths[lo:hi],
        index=packed.index[lo:hi],
    )


#: Streaming chunk sizing target: ~64 MB of compiled plan per chunk.
DEFAULT_CHUNK_TARGET_BYTES = 64 << 20

#: Conservative compiled-plan bytes per word per packed byte: plan
#: fields + piece tables + device mirrors run tens of times the raw word
#: bytes (gw alone is up to NG×VM×NW×4 per word).
_EST_PLAN_BYTES_PER_TOKEN = 64


def auto_chunk_words(
    width: int, target_bytes: int = DEFAULT_CHUNK_TARGET_BYTES
) -> int:
    """Chunk word count (scalar int) targeting ``target_bytes`` of
    compiled plan for uint8 [B, width] token batches: the per-word byte
    estimate scales with the packed width (wider words grow more
    emission groups and wider windows).  Floor 1024 — tiny chunks drown
    in per-chunk dispatch/compile overhead."""
    est = _EST_PLAN_BYTES_PER_TOKEN * max(4, int(width))
    return max(1024, int(target_bytes) // est)


def chunk_bounds(n_words: int, chunk_words: int) -> List[Tuple[int, int]]:
    """Uniform ``[lo, hi)`` word ranges of ``chunk_words`` (last chunk
    ragged).  Uniform bounds keep the chunk→word mapping arithmetic, so
    a resumed global cursor finds its chunk without replaying the
    split."""
    cw = int(chunk_words)
    if cw < 1:
        raise ValueError(f"chunk_words must be >= 1, got {chunk_words}")
    return [(lo, min(lo + cw, n_words)) for lo in range(0, n_words, cw)]


@dataclass
class PlanChunk:
    """One compiled dictionary chunk, produced by the worker thread.

    ``payload`` carries whatever the injected compile function attached
    (device plan arrays, launch callables, superstep context — the sweep
    runtime's business); ``host_bytes`` is the chunk's resident
    plan-array footprint (host numpy; the device mirrors are the same
    sizes), the number the bounded-memory contract is enforced against.
    ``release()`` frees the chunk exactly once — device arrays deleted,
    host references dropped — via the compile function's releaser.
    """

    index: int
    lo: int
    hi: int
    plan: object = None
    pieces: object = None
    payload: dict = None
    host_bytes: int = 0
    compile_s: float = 0.0
    t_start: float = 0.0
    t_end: float = 0.0
    releaser: "object" = None

    def release(self) -> None:
        rel, self.releaser = self.releaser, None
        if rel is not None:
            rel(self)
        self.plan = self.pieces = self.payload = None


class ChunkCompiler:
    """The bounded chunk-compile ring (PERF.md §19).

    ONE worker thread compiles chunks in word order via the injected
    ``compile_fn(index, lo, hi) -> PlanChunk``; at most ``prefetch``
    (default 1) compiled-or-compiling chunks sit ahead of the chunk the
    caller is currently sweeping, so chunk N+1's host compile (and its
    async host→device transfers, issued inside ``compile_fn`` on the
    worker) overlaps the device sweep of chunk N while resident memory
    stays O(ring × chunk).  Iteration yields chunks in order.

    Worker-death recovery (PERF.md §23): a chunk whose compile raised
    restarts the executor ONCE — fresh worker thread, the failed chunk
    (and everything queued behind it) resubmitted — before the error
    propagates at the consuming ``next()``; a second failure
    propagates.  One-shot transient faults (the ``chunk.compile``
    injection point) recover invisibly; a deterministic compile bug
    still fails after one extra attempt.
    """

    def __init__(self, compile_fn, bounds: Sequence[Tuple[int, int]], *,
                 start: int = 0, prefetch: int = 1) -> None:
        import time as _time
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        self._fn = compile_fn
        self._time = _time
        self._bounds = list(bounds)
        self._next = start
        self._prefetch = max(1, int(prefetch))
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="a5-chunk-compile"
        )
        self._futs = deque()  # (chunk index, Future) in chunk order
        self._restarted = False
        #: per-chunk compile windows [(t_start, t_end)] and their total
        #: wall — the overlap instrument (monotonic clock).
        self.windows: List[Tuple[float, float]] = []
        self.compile_wall_s = 0.0
        self._fill()

    def _fill(self) -> None:
        # The ring bound: the chunk being swept was already popped, so
        # outstanding futures ARE the prefetch window — exactly one
        # chunk compiles/waits ahead at the default depth.
        while (
            self._next < len(self._bounds)
            and len(self._futs) < self._prefetch
        ):
            ci = self._next
            lo, hi = self._bounds[ci]
            self._futs.append((ci, self._ex.submit(self._timed, ci, lo, hi)))
            self._next += 1

    def _timed(self, ci: int, lo: int, hi: int) -> PlanChunk:
        from ..runtime import faults

        if faults.ACTIVE is not None:
            faults.ACTIVE.fire("chunk.compile")
        t0 = self._time.monotonic()
        chunk = self._fn(ci, lo, hi)
        chunk.t_start = t0
        chunk.t_end = self._time.monotonic()
        chunk.compile_s = chunk.t_end - t0
        return chunk

    def _restart_worker(self, failed_ci: int) -> "PlanChunk":
        """Restart-once recovery: rebuild the executor, re-run the
        failed chunk, and block for it (a second failure propagates).
        The worker may already be COMPILING the next chunk when the
        failure is observed — ``shutdown(wait=True, cancel_futures=
        True)`` lets that in-progress compile finish (its completed
        future stays valid and is KEPT, never recompiled) while
        cancelling the never-started queue entries, which alone are
        resubmitted on the fresh executor."""
        from concurrent.futures import ThreadPoolExecutor

        from ..runtime import telemetry

        telemetry.counter("faults.worker_restarts").add(1)
        self._ex.shutdown(wait=True, cancel_futures=True)
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="a5-chunk-compile"
        )
        pending = [(failed_ci, None)] + [
            (ci, None if fut.cancelled() else fut)
            for ci, fut in self._futs
        ]
        self._futs.clear()
        for ci, fut in pending:
            if fut is None:
                lo, hi = self._bounds[ci]
                fut = self._ex.submit(self._timed, ci, lo, hi)
            self._futs.append((ci, fut))
        _ci, fut = self._futs.popleft()
        return fut.result()

    def __iter__(self) -> "Iterable[PlanChunk]":
        from ..runtime import telemetry

        while self._futs:
            ci, fut = self._futs.popleft()
            try:
                chunk = fut.result()
            except BaseException as exc:  # noqa: BLE001 — worker death
                if self._restarted or isinstance(
                    exc, (KeyboardInterrupt, SystemExit)
                ):
                    raise
                self._restarted = True
                chunk = self._restart_worker(ci)
            self.windows.append((chunk.t_start, chunk.t_end))
            self.compile_wall_s += chunk.compile_s
            self._fill()
            if telemetry.enabled():
                # Ring occupancy AFTER the refill: the chunks compiled/
                # compiling ahead of the one being handed out (PERF.md
                # §21; the host-side consume boundary — never a device
                # round trip).
                telemetry.counter("stream.chunks_compiled").add(1)
                telemetry.counter("stream.compile_wall_s").add(
                    chunk.compile_s
                )
                telemetry.histogram("stream.chunk_compile_s").observe(
                    chunk.compile_s
                )
                telemetry.gauge("stream.ring_occupancy").set(
                    len(self._futs)
                )
            yield chunk

    def close(self) -> None:
        """Stop compiling; safe after an aborted sweep.  Chunks already
        compiled are NOT released here — the caller owns consumed chunks
        and an aborted in-flight future still completes on the worker."""
        for _ci, fut in self._futs:
            fut.cancel()
        self._ex.shutdown(wait=True)
        self._futs.clear()

"""Wordlist packing: variable-length byte strings -> padded device tensors.

The reference streams the dictionary line by line through ``bufio.Scanner``
(``main.go:72-94``) and hands each word to a goroutine. The TPU path instead
packs words into fixed-shape batches ``uint8[B, width]`` + ``int32[B]``
lengths up front; length bucketing (16/32/64...) keeps padding waste low
across rockyou-class dictionaries.

This module is the numpy implementation; ``native/`` provides a C++ packer
with the same output contract for the file-to-arrays hot path (the analog of
the reference's scanner loop), and transparently falls back to this code.

Faithfulness notes (Q8): the reference's scanner silently ends input on a line
longer than 64 KiB and never checks ``scanner.Err()``. We do NOT copy that
hole: oversized lines raise unless ``max_word_bytes`` is explicitly lifted,
and I/O errors propagate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

#: Go bufio.Scanner default token limit (reference main.go Q8).
DEFAULT_MAX_WORD_BYTES = 64 * 1024

#: Default length-bucket boundaries (words longer than the last bucket get a
#: bucket of exactly their padded power-of-two width).
DEFAULT_BUCKETS = (16, 32, 64)


@dataclass(frozen=True)
class PackedWords:
    """A batch of words as device-ready padded arrays.

    ``tokens[i, :lengths[i]]`` are the word's bytes; the rest is zero padding.
    ``index[i]`` is the word's ordinal in the source wordlist — packing may
    bucket/reorder, and every downstream hit is reported against this index so
    results are always expressed in dictionary order.
    """

    tokens: np.ndarray  # uint8 [B, width]
    lengths: np.ndarray  # int32 [B]
    index: np.ndarray  # int64 [B] — position in the original wordlist

    @property
    def batch(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def width(self) -> int:
        return int(self.tokens.shape[1])

    def word(self, i: int) -> bytes:
        return bytes(self.tokens[i, : self.lengths[i]])

    def words(self) -> List[bytes]:
        return [self.word(i) for i in range(self.batch)]


def aligned_width(longest: int) -> int:
    """The packing width for a longest-word length: smallest multiple of 4
    covering it (uint32 lane alignment for the hash kernels), minimum 4.
    Single source of truth for Python and native packers."""
    return max(4, -(-longest // 4) * 4)


def pack_words(
    words: Sequence[bytes],
    *,
    width: int | None = None,
    start_index: int = 0,
) -> PackedWords:
    """Pack ``words`` into one padded batch of a single width.

    ``width`` defaults to :func:`aligned_width` of the longest word.
    """
    if width is None:
        width = aligned_width(max((len(w) for w in words), default=0))
    tokens = np.zeros((len(words), width), dtype=np.uint8)
    lengths = np.zeros((len(words),), dtype=np.int32)
    for i, w in enumerate(words):
        if len(w) > width:
            raise ValueError(f"word {i} is {len(w)} bytes > width {width}")
        tokens[i, : len(w)] = np.frombuffer(w, dtype=np.uint8)
        lengths[i] = len(w)
    index = np.arange(start_index, start_index + len(words), dtype=np.int64)
    return PackedWords(tokens=tokens, lengths=lengths, index=index)


def validate_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Require strictly-ascending positive bucket boundaries.

    Shared by the Python (`bucket_words`, first-match in caller order) and
    native (`native.bucket_widths`, searchsorted) assignment paths so an
    unsorted tuple cannot make them assign different widths (advisor r2).
    An empty tuple is allowed: every word gets its own power-of-two width.
    """
    if list(buckets) != sorted(set(buckets)) or any(b < 1 for b in buckets):
        raise ValueError(
            f"buckets must be strictly ascending positive widths, got "
            f"{tuple(buckets)}"
        )
    return tuple(buckets)


def bucket_words(
    words: Sequence[bytes],
    *,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
    start_index: int = 0,
) -> Dict[int, PackedWords]:
    """Split ``words`` into length buckets, each packed at its bucket width.

    Returns ``{width: PackedWords}``; original wordlist positions are carried
    in each batch's ``index``. Words longer than the last bucket boundary get
    a power-of-two width of their own; words over ``max_word_bytes`` raise
    (the anti-Q8 guarantee).
    """
    validate_buckets(buckets)
    by_width: Dict[int, List[int]] = {}
    for i, w in enumerate(words):
        if len(w) > max_word_bytes:
            raise ValueError(
                f"word {start_index + i} is {len(w)} bytes > limit "
                f"{max_word_bytes} (Go would silently truncate here — Q8)"
            )
        width = next((b for b in buckets if len(w) <= b), None)
        if width is None:
            width = 4
            while width < len(w):
                width *= 2
        by_width.setdefault(width, []).append(i)

    out: Dict[int, PackedWords] = {}
    for width, idxs in sorted(by_width.items()):
        packed = pack_words([words[i] for i in idxs], width=width)
        out[width] = PackedWords(
            tokens=packed.tokens,
            lengths=packed.lengths,
            index=np.asarray([start_index + i for i in idxs], dtype=np.int64),
        )
    return out


def read_wordlist_lines(
    data: bytes,
    *,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Line structure of a wordlist buffer: (buffer, offsets, lengths),
    ScanLines semantics (see :func:`read_wordlist`). This is the numpy
    reference for the native scanner (``native.scan_wordlist_bytes``)."""
    buf = np.frombuffer(data, dtype=np.uint8)
    if len(data) == 0:
        empty64 = np.zeros(0, dtype=np.int64)
        return buf, empty64, np.zeros(0, dtype=np.int32)
    nl = np.nonzero(buf == 0x0A)[0]
    starts = np.concatenate([[0], nl + 1])
    ends = np.concatenate([nl, [len(data)]])
    if starts[-1] >= len(data) and data.endswith(b"\n"):
        starts, ends = starts[:-1], ends[:-1]
    lengths = ends - starts
    # Drop one trailing '\r' per line.
    has_cr = lengths > 0
    cr_pos = np.where(has_cr, starts + lengths - 1, 0)
    lengths = lengths - (has_cr & (buf[cr_pos] == 0x0D))
    if len(lengths) and int(lengths.max()) > max_word_bytes:
        bad = int(np.argmax(lengths > max_word_bytes))
        raise ValueError(f"line {bad} exceeds {max_word_bytes} bytes (Q8)")
    return buf, starts.astype(np.int64), lengths.astype(np.int32)


def read_wordlist(
    path: str,
    *,
    max_word_bytes: int = DEFAULT_MAX_WORD_BYTES,
) -> List[bytes]:
    """Read a dictionary file into a list of words (one per line).

    Mirrors ``bufio.ScanLines``: splits on ``\\n``, drops one trailing ``\\r``
    per line, and a final line without a newline still counts. Unlike the
    reference, an oversized line is an error, not a silent end of input (Q8).
    """
    with open(path, "rb") as fh:
        data = fh.read()
    words: List[bytes] = []
    if not data:
        return words
    for line in data.split(b"\n"):
        if line.endswith(b"\r"):
            line = line[:-1]
        if len(line) > max_word_bytes:
            raise ValueError(
                f"{path}: line {len(words)} exceeds {max_word_bytes} bytes (Q8)"
            )
        words.append(line)
    if data.endswith(b"\n"):
        words.pop()  # split() produced a trailing empty element, not a word
    return words

"""``python -m hashcat_a5_table_generator_tpu`` — the a5gen CLI."""

import sys

from .cli import main

sys.exit(main())

"""Isolate the fused launch's per-block cost: time the bare pallas_call
(inputs pre-gathered once, reused) against the full fused_expand_md5 wrapper
(per-launch gathers + mask build) at the same geometry.  Evidence for the
bucketed-launch design: if the bare kernel's wall is ~lane-term only, the
~575 ns/block cost lives in the wrapper's XLA prep, not the kernel."""

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops import pallas_expand as pe
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

LANES = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 22
STRIDE = int(sys.argv[2]) if len(sys.argv) > 2 else 128
BLOCKS = LANES // STRIDE
N = 30


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind}) "
          f"lanes=2^{LANES.bit_length()-1} stride={STRIDE}", file=sys.stderr)
    spec = AttackSpec(mode="default", algo="md5")
    sub_map = get_layout("qwerty-cyrillic").to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(50000)
    plan = build_plan(spec, ct, pack_words(words))
    k_opts = pe.k_opts_for(plan)
    p, t = plan_arrays(plan), table_arrays(ct)
    batch, _, _ = make_blocks(plan, start_word=0, start_rank=0,
                              max_variants=LANES, max_blocks=BLOCKS,
                              fixed_stride=STRIDE)
    b = block_arrays(batch, num_blocks=BLOCKS)

    kw = dict(num_lanes=LANES, out_width=plan.out_width,
              min_substitute=spec.effective_min,
              max_substitute=spec.max_substitute,
              block_stride=STRIDE, k_opts=k_opts)

    # --- arm 1: full wrapper (per-launch gathers + mask build) -----------
    @jax.jit
    def full(p_, t_, b_):
        state, emit = pe.fused_expand_md5(
            p_["tokens"], p_["lengths"], p_["match_pos"], p_["match_len"],
            p_["match_radix"], p_["match_val_start"],
            t_["val_bytes"], t_["val_len"],
            b_["word"], b_["base"], b_["count"], **kw)
        return state[:, 0].sum() + emit.sum().astype(jnp.uint32)

    # --- arm 2: bare kernel (inputs pre-gathered ONCE outside the timer) -
    m = int(p["match_pos"].shape[1])
    length_axis = int(p["tokens"].shape[1])
    blk_word = b["word"]
    tok_b = p["tokens"][blk_word].astype(jnp.int32)
    wlen_b = p["lengths"][blk_word][:, None]
    pos_b = p["match_pos"][blk_word]
    mlen_b = p["match_len"][blk_word]
    radix_b = p["match_radix"][blk_word]
    count_b = b["count"][:, None]
    vopt_b, vlen_b = pe._pack_val_options(
        t["val_bytes"], t["val_len"], p["match_val_start"][blk_word], k_opts)
    jj = jnp.arange(length_axis, dtype=jnp.int32)[None, None, :]
    ps = pos_b[:, :, None]
    inside_b = ((jj >= ps) & (jj < ps + mlen_b[:, :, None])).astype(jnp.int32)
    start_b = (jj == ps).astype(jnp.int32)
    inputs = tuple(jax.device_put(x) for x in (
        tok_b, wlen_b, radix_b, b["base"], count_b,
        inside_b, start_b, vopt_b, vlen_b))
    kernel = pe._make_kernel(
        g=pe._G, s=STRIDE, m=m, length_axis=length_axis, k_opts=k_opts,
        out_width=plan.out_width, min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute, algo="md5")

    @jax.jit
    def bare(*ins):
        state, emit = pe._launch_fused(
            kernel, ins, nb=BLOCKS, stride=STRIDE, num_lanes=LANES,
            n_state=4, interpret=False)
        return state[:, 0].sum() + emit.sum().astype(jnp.uint32)

    # --- arm 3: prep only (gathers + mask build, no kernel) --------------
    @jax.jit
    def prep(p_, t_, b_):
        bw = b_["word"]
        tok = p_["tokens"][bw].astype(jnp.int32)
        wl = p_["lengths"][bw][:, None]
        pos = p_["match_pos"][bw]
        ml = p_["match_len"][bw]
        rx = p_["match_radix"][bw]
        vo, vl = pe._pack_val_options(
            t_["val_bytes"], t_["val_len"], p_["match_val_start"][bw], k_opts)
        jj_ = jnp.arange(length_axis, dtype=jnp.int32)[None, None, :]
        ps_ = pos[:, :, None]
        ins_ = ((jj_ >= ps_) & (jj_ < ps_ + ml[:, :, None])).astype(jnp.int32)
        st_ = (jj_ == ps_).astype(jnp.int32)
        return (tok.sum().astype(jnp.uint32) + wl.sum().astype(jnp.uint32)
                + rx.sum().astype(jnp.uint32) + vo.sum()
                + vl.sum().astype(jnp.uint32) + ins_.sum().astype(jnp.uint32)
                + st_.sum().astype(jnp.uint32))

    # --- arm 4: full wrapper on the scalar-units path (PERF.md §11) ------
    tier = pe.scalar_units_for(plan)
    arms = [("full", full, (p, t, b)),
            ("bare_kernel", bare, inputs),
            ("prep_only", prep, (p, t, b))]
    if tier:
        skw = dict(kw, scalar_units=tier)

        @jax.jit
        def full_scalar(p_, t_, b_):
            state, emit = pe.fused_expand_md5(
                p_["tokens"], p_["lengths"], p_["match_pos"],
                p_["match_len"], p_["match_radix"], p_["match_val_start"],
                t_["val_bytes"], t_["val_len"],
                b_["word"], b_["base"], b_["count"], **skw)
            return state[:, 0].sum() + emit.sum().astype(jnp.uint32)

        arms.append(("full_scalar", full_scalar, (p, t, b)))

    for name, fn, args in arms:
        r = fn(*args)
        r.block_until_ready()
        acc = jnp.zeros((), jnp.uint32)
        t0 = time.perf_counter()
        for _ in range(N):
            acc = acc + fn(*args)
        _ = int(acc)  # honest completion barrier over the whole chain
        el = (time.perf_counter() - t0) / N
        print(f"{name:12s} {el*1e3:8.3f} ms/launch   "
              f"({el/LANES*1e9:.3f} ns/lane, {el/BLOCKS*1e9:.0f} ns/block)")
        sys.stdout.flush()


if __name__ == "__main__":
    main()

"""A/B the fused Pallas expand+MD5 kernel against the XLA expand+hash pair
inside the production fused body on the live device (evidence for PERF.md;
not part of the package). Planted candidate digests make cross-variant
n_hits equality a live correctness check, exactly like probe_pallas.py."""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_fused_body,
    plan_arrays, scalar_units_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.ops.pallas_expand import (
    eligible, k_opts_for, scalar_units_for,
)
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

LANES = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 22
STRIDE = int(sys.argv[2]) if len(sys.argv) > 2 else 128
BLOCKS = LANES // STRIDE


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    sub_map = get_layout("qwerty-cyrillic").to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(50000)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    k_opts = k_opts_for(plan)
    assert eligible(
        mode=spec.mode, algo=spec.algo, windowed=plan.windowed,
        block_stride=STRIDE, num_blocks=BLOCKS, out_width=plan.out_width,
        num_slots=plan.num_slots, token_width=plan.tokens.shape[1],
        max_val_len=ct.max_val_len, max_options=k_opts,
    ), "config not eligible for the fused kernel — A/B would self-compare"

    host_digest = HOST_DIGEST[spec.algo]
    planted = list(iter_candidates(words[0], sub_map, 0, 15))[:3]
    targets = [host_digest(c) for c in planted]
    targets += [host_digest(b"bench-decoy-%d" % i) for i in range(1021)]
    ds = build_digest_set(targets, spec.algo)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    batches = []
    w = rank = 0
    for _ in range(3):
        batch, w, rank = make_blocks(plan, start_word=w, start_rank=rank,
                                     max_variants=LANES, max_blocks=BLOCKS,
                                     fixed_stride=STRIDE)
        batches.append(block_arrays(batch, num_blocks=BLOCKS))

    results = {}
    arms = [("xla", None, False, p), ("pallas_fused", k_opts, False, p)]
    tier = scalar_units_for(plan)
    if tier:
        # Two scalar arms: in-trace prep vs the per-sweep word-level
        # precompute (PERF.md §12) — the A/B of the prep change itself.
        p_aug = dict(p, **scalar_units_arrays(plan, ct))
        arms += [("pallas_scalar", k_opts, tier, p),
                 ("pallas_scalar_pre", k_opts, tier, p_aug)]
    for name, fused, scalar, p_arm in arms:
        p = p_arm
        body = make_fused_body(spec, num_lanes=LANES,
                               out_width=plan.out_width, block_stride=STRIDE,
                               fused_expand_opts=fused,
                               fused_scalar_units=scalar)
        acc = jax.jit(
            lambda p_, t_, b_, d_, tot: tot + body(p_, t_, d_, b_)["n_emitted"]
        )
        step = jax.jit(lambda p_, t_, b_, d_: body(p_, t_, d_, b_)["n_hits"])
        zero = jnp.zeros((), jnp.int32)
        t0 = time.perf_counter()
        nh = int(step(p, t, batches[0], d))
        results[name] = nh
        compile_s = time.perf_counter() - t0
        int(acc(p, t, batches[0], d, zero))  # compile the acc variant too
        n = 30
        t0 = time.perf_counter()
        tot = zero
        for i in range(n):
            tot = acc(p, t, batches[i % 3], d, tot)
        hashed = int(tot)
        el = time.perf_counter() - t0
        print(json.dumps({
            "variant": name, "compile_s": round(compile_s, 1),
            "per_launch_s": round(el / n, 4),
            "hashes_per_sec": round(hashed / el, 1),
            "n_hits_first_launch": nh,
        }))
        sys.stdout.flush()

    assert all(v == results["xla"] for v in results.values()) and (
        results["xla"] >= 1
    ), f"planted-hit mismatch: {results} — fused kernel diverges on-chip"
    print("# planted hits consistent across variants", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Roofline input: count the fused kernel's per-candidate VPU op budget.

The fused Pallas kernel (`ops/pallas_expand.py`) is straight-line
elementwise code on (G, S) = (8, 128k) tiles — every traced op is a VPU
vector instruction processing one op for each lane it covers.  Counting
the kernel jaxpr's equations, weighted by how many (8, 128) native
vregs each op's shape spans, therefore gives ops-per-candidate directly:

    ops/candidate = sum(eqn_vregs) / (G * S / 1024 vregs) / lanes-per-vreg
                  = weighted_eqns * 1024 / (G * S)

(S = block stride; at the headline geometry stride=128, so G*S = one
vreg and ops/candidate = plain weighted eqn count.)

That number divided into the VPU's per-chip op rate brackets the
hashes/s ceiling — see PERF.md §7 for the analysis this feeds.

Usage: python scripts/roofline_count.py [--mode default] [--algo md5]
Runs on CPU (no device needed): only traces, never executes.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def count_kernel_ops(jaxpr, g, s):
    """Weighted eqn count of the pallas kernel jaxpr: each eqn costs
    ceil(elements / 1024) native (8,128) vregs; ops/candidate normalizes
    by the tile's own vreg span so sub-tile ops (e.g. (G,1) scalars that
    still burn a whole vreg) are charged fairly."""
    tile_vregs = max(1, (g * s) // 1024)
    total = 0.0
    by_prim = Counter()

    def walk(jx):
        nonlocal total
        for eqn in jx.eqns:
            # Recurse through call-like wrappers (jnp.where etc. trace as
            # nested jit eqns) — only leaf primitives are instructions.
            sub = eqn.params.get("jaxpr")
            if sub is not None and hasattr(sub, "eqns"):
                walk(sub)
                continue
            if sub is not None and hasattr(getattr(sub, "jaxpr", None),
                                           "eqns"):
                walk(sub.jaxpr)
                continue
            outs = eqn.outvars
            elems = max(
                int(np.prod(v.aval.shape)) if v.aval.shape else 1
                for v in outs
            )
            vregs = max(1, -(-elems // 1024))
            w = vregs / tile_vregs
            total += w
            by_prim[eqn.primitive.name] += w

    walk(jaxpr)
    return total, by_prim


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", default="default")
    ap.add_argument("--algo", default="md5")
    ap.add_argument("--stride", type=int, default=128)
    ap.add_argument("--words", type=int, default=256)
    ap.add_argument("--table", default="qwerty-cyrillic",
                    help="built-in layout (qwerty-azerty produces a "
                         "cascade-CLOSED suball plan — the joint-value "
                         "kernel variant, PERF.md §14)")
    ap.add_argument("--no-scalar-units", action="store_true",
                    help="force the general kernel even when the plan "
                         "qualifies for the K=1 scalar-units path")
    ap.add_argument("--min-substitute", type=int, default=0,
                    help="count-window floor (tight windows produce "
                         "windowed plans — the DP-decode kernel)")
    ap.add_argument("--max-substitute", type=int, default=15,
                    help="count-window ceiling")
    args = ap.parse_args()

    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        build_plan,
    )
    from hashcat_a5_table_generator_tpu.ops import pallas_expand as pe
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
    from hashcat_a5_table_generator_tpu.ops.packing import pack_words
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

    import sys

    sys.path.insert(0, "/root/repo")
    from bench import synth_wordlist

    spec = AttackSpec(mode=args.mode, algo=args.algo,
                      min_substitute=args.min_substitute,
                      max_substitute=args.max_substitute)
    ct = compile_table(get_layout(args.table).to_substitution_map())
    packed = pack_words(synth_wordlist(args.words))
    plan = build_plan(spec, ct, packed)
    k = pe.k_vals_for(plan)  # value-select width (joint closure tables)
    nb = 16
    stride = args.stride
    batch, _, _ = make_blocks(
        plan, start_word=0, start_rank=0, max_variants=nb * stride,
        max_blocks=nb, fixed_stride=stride,
    )
    batch = pad_batch(batch, nb)

    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        plan_arrays,
        table_arrays,
    )

    p, t, b = plan_arrays(plan), table_arrays(ct), block_arrays(batch, num_blocks=nb)
    # Cascade-closed plans carry their own value table + joint fields.
    vb = p.get("cval_bytes", t["val_bytes"])
    vl = p.get("cval_len", t["val_len"])

    common = dict(
        num_lanes=nb * stride, out_width=int(plan.out_width),
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute,
        block_stride=stride, k_opts=k, algo=args.algo, interpret=True,
        scalar_units=(not args.no_scalar_units
                      and pe.scalar_units_for(plan)),
    )
    if args.mode in ("default", "reverse"):
        fn = lambda: pe.fused_expand_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["match_pos"], p["match_len"],
            p["match_radix"], p["match_val_start"],
            t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], **common,
        )
    else:
        fn = lambda: pe.fused_expand_suball_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["pat_radix"], p["pat_val_start"],
            p["seg_orig_start"], p["seg_orig_len"], p["seg_pat"],
            vb, vl,
            b["word"], b["base"], b["count"],
            close_next=p.get("close_next"), close_mul=p.get("close_mul"),
            **common,
        )

    jpr = jax.make_jaxpr(fn)()
    # Find the pallas_call eqn and pull its inner kernel jaxpr.
    inner = None
    for eqn in jpr.eqns:
        if eqn.primitive.name == "pallas_call":
            inner = eqn.params["jaxpr"]
            break
    assert inner is not None, "no pallas_call in trace"
    g = pe._G
    ops, by_prim = count_kernel_ops(inner, g, stride)
    closed = getattr(plan, "closed", None)
    n_closed = int(closed.sum()) if closed is not None else 0
    print(f"mode={args.mode} algo={args.algo} table={args.table} "
          f"stride={stride} slots={plan.num_slots} "
          f"tokens={plan.tokens.shape[1]} K={k} closed_words={n_closed}")
    print(f"kernel vector ops per candidate: {ops:.0f}")
    for name, w in by_prim.most_common(12):
        print(f"  {name:>22}: {w:8.1f}")
    for rate, label in ((1.0e12, "1 op/ALU/cycle (conservative)"),
                        (2.0e12, "2-issue"), (4.0e12, "4-issue VLIW")):
        print(f"ceiling @ VPU {rate:.0e} ops/s ({label}): "
              f"{rate / ops:.2e} hashes/s")


if __name__ == "__main__":
    main()

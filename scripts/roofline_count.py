"""Roofline input: count the fused kernel's per-candidate VPU op budget.

Thin CLI over the repo's ONE op counter —
``tools.graftaudit.counter.count_kernel_ops`` — which also backs the
``KERNEL_BUDGETS.json`` gate (``python -m tools.graftaudit``), so the
roofline numbers, the CI budget pins, and PERF.md §7/§7a can never
drift apart.  See the counter module for the vreg-weighted model.

That number divided into the VPU's per-chip op rate brackets the
hashes/s ceiling — see PERF.md §7 for the analysis this feeds.

Usage: python scripts/roofline_count.py [--mode default] [--algo md5]
Runs on CPU (no device needed): only traces, never executes.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from tools.graftaudit.counter import (  # noqa: E402
    count_kernel_ops,
    kernel_jaxpr_of,
)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", default="default")
    ap.add_argument("--algo", default="md5")
    ap.add_argument("--stride", type=int, default=128)
    ap.add_argument("--words", type=int, default=256)
    ap.add_argument("--word-width", type=int, default=None,
                    help="synthesize WORDS all-lowercase words of this "
                         "exact byte width instead of the rockyou-like "
                         "mix (width 60 reproduces the 2-hash-block "
                         "budget tier: out_width 120)")
    ap.add_argument("--table", default="qwerty-cyrillic",
                    help="built-in layout (qwerty-azerty produces a "
                         "cascade-CLOSED suball plan — the joint-value "
                         "kernel variant, PERF.md §14)")
    ap.add_argument("--no-scalar-units", action="store_true",
                    help="force the general kernel even when the plan "
                         "qualifies for the K=1 scalar-units path")
    ap.add_argument("--emit", choices=("perslot", "bytescan"),
                    default="perslot",
                    help="emission scheme to count: per-slot pieces "
                         "(production default, PERF.md §17) or the "
                         "legacy per-byte unit scan (the A5GEN_EMIT="
                         "bytescan escape hatch)")
    ap.add_argument("--pair", choices=("on", "off", "auto"),
                    default="auto",
                    help="pair-lane tier (K=2 candidates per lane, "
                         "PERF.md §24): 'auto' (production default — "
                         "engage when the schema's pair gate passes), "
                         "'on' (error when ineligible), 'off' (the K=1 "
                         "tier, reproducing the pre-§24 counts modulo "
                         "the shared round/elision cuts)")
    ap.add_argument("--min-substitute", type=int, default=0,
                    help="count-window floor (tight windows produce "
                         "windowed plans — the DP-decode kernel)")
    ap.add_argument("--max-substitute", type=int, default=15,
                    help="count-window ceiling")
    args = ap.parse_args()

    from hashcat_a5_table_generator_tpu.models.attack import (
        AttackSpec,
        build_plan,
    )
    from hashcat_a5_table_generator_tpu.ops import pallas_expand as pe
    from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks, pad_batch
    from hashcat_a5_table_generator_tpu.ops.packing import (
        pack_words,
        piece_schema_for,
    )
    from hashcat_a5_table_generator_tpu.tables.compile import compile_table
    from hashcat_a5_table_generator_tpu.tables.layouts import get_layout

    from bench import synth_wordlist

    spec = AttackSpec(mode=args.mode, algo=args.algo,
                      min_substitute=args.min_substitute,
                      max_substitute=args.max_substitute)
    ct = compile_table(get_layout(args.table).to_substitution_map())
    if args.word_width is not None:
        # The harness's generator, not a copy: --word-width 60 must keep
        # reproducing the pinned 2-hash-block tier.
        from tools.graftaudit.harness import long_wordlist

        words = long_wordlist(args.words, args.word_width)
    else:
        words = synth_wordlist(args.words)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    k = pe.k_vals_for(plan)  # value-select width (joint closure tables)
    nb = 16
    stride = args.stride
    pieces_maybe = (
        piece_schema_for(plan, ct) if args.emit == "perslot" else None
    )
    pair_k = None
    if args.pair != "off":
        pair_k = pe.pair_for_config(
            spec, plan, pieces_maybe, block_stride=stride
        )
        if pair_k is None and args.pair == "on":
            raise SystemExit(
                "--pair on: this plan/config is not pair-eligible "
                "(schema gate, windowed decode, or hash-block count)"
            )
    rank_stride = stride * (pair_k or 1)
    batch, _, _ = make_blocks(
        plan, start_word=0, start_rank=0, max_variants=nb * rank_stride,
        max_blocks=nb, fixed_stride=rank_stride,
    )
    batch = pad_batch(batch, nb)

    from hashcat_a5_table_generator_tpu.models.attack import (
        block_arrays,
        plan_arrays,
        table_arrays,
    )

    p, t, b = plan_arrays(plan), table_arrays(ct), block_arrays(batch, num_blocks=nb)
    # Cascade-closed plans carry their own value table + joint fields.
    vb = p.get("cval_bytes", t["val_bytes"])
    vl = p.get("cval_len", t["val_len"])

    common = dict(
        num_lanes=nb * stride, out_width=int(plan.out_width),
        min_substitute=spec.effective_min,
        max_substitute=spec.max_substitute,
        block_stride=stride, k_opts=k, algo=args.algo, interpret=True,
        scalar_units=(not args.no_scalar_units
                      and pe.scalar_units_for(plan)),
        pieces=pieces_maybe,
        pair=pair_k is not None,
    )
    if args.mode in ("default", "reverse"):
        fn = lambda: pe.fused_expand_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["match_pos"], p["match_len"],
            p["match_radix"], p["match_val_start"],
            t["val_bytes"], t["val_len"],
            b["word"], b["base"], b["count"], **common,
        )
    else:
        fn = lambda: pe.fused_expand_suball_md5(  # noqa: E731
            p["tokens"], p["lengths"], p["pat_radix"], p["pat_val_start"],
            p["seg_orig_start"], p["seg_orig_len"], p["seg_pat"],
            vb, vl,
            b["word"], b["base"], b["count"],
            close_next=p.get("close_next"), close_mul=p.get("close_mul"),
            **common,
        )

    inner = kernel_jaxpr_of(jax.make_jaxpr(fn)())
    g = pe._G
    # The pair tier yields 2 candidates per lane: normalize per
    # CANDIDATE, exactly like the KERNEL_BUDGETS harness.
    ops, by_prim = count_kernel_ops(inner, g, rank_stride)
    closed = getattr(plan, "closed", None)
    n_closed = int(closed.sum()) if closed is not None else 0
    pieces = common["pieces"]
    emit = "perslot" if pieces is not None else "bytescan"
    print(f"mode={args.mode} algo={args.algo} table={args.table} "
          f"stride={stride} slots={plan.num_slots} "
          f"tokens={plan.tokens.shape[1]} K={k} closed_words={n_closed} "
          f"emit={emit} pair={pair_k or 1}"
          + (f" groups={pieces.num_groups}" if pieces is not None else ""))
    print(f"kernel vector ops per candidate: {ops:.0f}")
    for name, w in by_prim.most_common(12):
        print(f"  {name:>22}: {w:8.1f}")
    for rate, label in ((1.0e12, "1 op/ALU/cycle (conservative)"),
                        (2.0e12, "2-issue"), (4.0e12, "4-issue VLIW")):
        print(f"ceiling @ VPU {rate:.0e} ops/s ({label}): "
              f"{rate / ops:.2e} hashes/s")


if __name__ == "__main__":
    main()

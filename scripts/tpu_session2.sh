#!/bin/bash
# Round-5b TPU measurement session: the scalar-units kernel geometry
# matrix (PERF.md §11). Run the moment the tunnel recovers; each step is
# individually time-capped so a re-wedged tunnel fails the step, not the
# session. Produces, under $OUT:
#   probe_s{128,256,512}.txt       - 3-arm A/B/C at 2^22 lanes (probe_fused)
#   probe_s{128,256}_g16.txt       - grid-height 16 variants
#   bench_headline.json            - bench.py default MD5, both arms
#   bench_suball.json              - bench.py -s substitute-all, both arms
#   bench_sha1.json                - bench.py sha1, both arms
#   sweep_cli.txt                  - sustained production CLI crack sweep
set -u
OUT=${OUT:-/tmp/tpu_session_r5b}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ===" | tee -a "$OUT/log"
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  echo "rc=$? $name" | tee -a "$OUT/log"
  tail -3 "$OUT/$name.err" >> "$OUT/log" 2>/dev/null
}

# 1. Scalar-units geometry matrix: 3-arm probe at strides 128/256/512,
#    then G=16 at the two best candidates.
run probe_s128 900 python scripts/probe_fused.py 4194304 128
run probe_s256 900 python scripts/probe_fused.py 4194304 256
run probe_s512 900 python scripts/probe_fused.py 4194304 512
# (env prefix on a bash FUNCTION call can leak into later steps; scope
# the grid-height override to the child process instead.)
run probe_s128_g16 900 env A5GEN_PALLAS_G=16 python scripts/probe_fused.py 4194304 128
run probe_s256_g16 900 env A5GEN_PALLAS_G=16 python scripts/probe_fused.py 4194304 256

# 1b. Per-block/per-lane cost split for the scalar kernel (two strides
#     fit t = nb*C1 + lanes*C2).
run prep_s128 900 python scripts/probe_prep_cost.py 4194304 128
run prep_s512 900 python scripts/probe_prep_cost.py 4194304 512

# 2. Official-bench configs, both arms (per-arm auto geometry).
run bench_headline 700 python bench.py --wall-budget 600 --seconds 10
run bench_suball 700 python bench.py --wall-budget 600 --seconds 10 --mode suball
run bench_sha1 700 python bench.py --wall-budget 600 --seconds 10 --algo sha1

# 2b. BASELINE.json configs[3]/[4] faithful tables.
run bench_czech_ntlm 700 python bench.py --wall-budget 600 --seconds 10 \
    --table czech --algo ntlm
run bench_greek_sha1 700 python bench.py --wall-budget 600 --seconds 10 \
    --table greek-hebrew --algo sha1

# 3. Sustained production CLI crack sweep at the headline config.
OUT="$OUT" python - <<'EOF'
import hashlib, os, sys
sys.path.insert(0, ".")
from bench import synth_wordlist
out = os.environ["OUT"]
words = synth_wordlist(200000)
os.makedirs(out, exist_ok=True)
with open(os.path.join(out, "dict.txt"), "wb") as f:
    f.write(b"\n".join(words) + b"\n")
with open(os.path.join(out, "digests.txt"), "w") as f:
    for i in (0, 1000, 100000):
        f.write(hashlib.md5(words[i]).hexdigest() + "\n")
EOF
run emit_table 120 python -m hashcat_a5_table_generator_tpu \
    --emit-table qwerty-cyrillic --output "$OUT/qc.table" /dev/null
run sweep_cli 900 python -m hashcat_a5_table_generator_tpu \
    "$OUT/dict.txt" -t "$OUT/qc.table" --backend device \
    --digests "$OUT/digests.txt" --progress

echo "=== session done ($(date -u +%H:%M:%S)) ===" | tee -a "$OUT/log"
for f in probe_s128 probe_s256 probe_s512 probe_s128_g16 probe_s256_g16; do
  echo "--- $f"; grep -h hashes_per_sec "$OUT/$f.out" 2>/dev/null
done
for f in bench_headline bench_suball bench_sha1 bench_czech_ntlm \
         bench_greek_sha1; do
  echo "--- $f"; tail -1 "$OUT/$f.out" 2>/dev/null
done
grep -E "hits|candidates hashed" "$OUT/sweep_cli.err" 2>/dev/null | tail -2

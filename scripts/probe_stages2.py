"""Stage decomposition, round 2: on the axon tunnel ``block_until_ready`` can
return immediately, so every measurement here forces a device->host SCALAR
fetch per launch and cycles distinct batches to defeat any result caching.
Evidence for PERF.md; not part of the package."""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_fused_body,
    make_candidates_body, plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.hashes import HASH_FNS
from hashcat_a5_table_generator_tpu.ops.membership import (
    build_digest_set, digest_member,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

LANES = 1 << 19
BLOCKS = 4096
STRIDE = LANES // BLOCKS
REPS = 6


def bench_scalar(fn, argsets):
    """fn returns a SCALAR device array; fetch it per launch (true sync)."""
    # warmup/compile
    t0 = time.perf_counter()
    _ = float(fn(*argsets[0]))
    compile_s = time.perf_counter() - t0
    times = []
    for i in range(REPS):
        args = argsets[i % len(argsets)]
        t0 = time.perf_counter()
        _ = float(fn(*args))
        times.append(time.perf_counter() - t0)
    return compile_s, min(times), times


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    packed = pack_words(synth_wordlist(20000))
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(
        [HOST_DIGEST["md5"](b"bench-decoy-%d" % i) for i in range(1024)], "md5"
    )
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)

    batches = []
    w = rank = 0
    for _ in range(3):
        batch, w, rank = make_blocks(plan, start_word=w, start_rank=rank,
                                     max_variants=LANES, max_blocks=BLOCKS,
                                     fixed_stride=STRIDE)
        batches.append(block_arrays(batch, num_blocks=BLOCKS))
    ow = plan.out_width

    fused = make_fused_body(spec, num_lanes=LANES, out_width=ow,
                            block_stride=STRIDE)
    fused_scalar = jax.jit(
        lambda p_, t_, d_, b_: fused(p_, t_, d_, b_)["n_emitted"]
    )
    c, r, ts = bench_scalar(fused_scalar, [(p, t, d, b) for b in batches])
    print(json.dumps({"stage": "fused", "compile_s": round(c, 1),
                      "launch_s": round(r, 4),
                      "all": [round(x, 3) for x in ts]}))
    sys.stdout.flush()

    expand = make_candidates_body(spec, num_lanes=LANES, out_width=ow,
                                  block_stride=STRIDE)
    expand_scalar = jax.jit(
        lambda p_, t_, b_: expand(p_, t_, b_)[0].astype(jnp.uint32).sum()
        + expand(p_, t_, b_)[1].sum().astype(jnp.uint32)
    )
    c, r, ts = bench_scalar(expand_scalar, [(p, t, b) for b in batches])
    print(json.dumps({"stage": "expand", "compile_s": round(c, 1),
                      "launch_s": round(r, 4),
                      "all": [round(x, 3) for x in ts]}))
    sys.stdout.flush()

    rng = np.random.default_rng(0)
    cands = [jnp.asarray(rng.integers(97, 123, size=(LANES, ow),
                                      dtype=np.uint8)) for _ in range(3)]
    clen = jnp.full((LANES,), ow - 2, dtype=jnp.int32)
    hash_fn = HASH_FNS["md5"]
    hash_scalar = jax.jit(lambda c_, l_: hash_fn(c_, l_).sum())
    c, r, ts = bench_scalar(hash_scalar, [(cand, clen) for cand in cands])
    print(json.dumps({"stage": "hash", "compile_s": round(c, 1),
                      "launch_s": round(r, 4),
                      "all": [round(x, 3) for x in ts]}))
    sys.stdout.flush()

    states = [jnp.asarray(rng.integers(0, 2**32, size=(LANES, 4),
                                       dtype=np.uint64).astype(np.uint32))
              for _ in range(3)]
    mem_scalar = jax.jit(
        lambda s_, rows_, bm_: digest_member(s_, rows_, bm_).sum()
    )
    c, r, ts = bench_scalar(mem_scalar,
                            [(s, d["rows"], d["bitmap"]) for s in states])
    print(json.dumps({"stage": "membership", "compile_s": round(c, 1),
                      "launch_s": round(r, 4),
                      "all": [round(x, 3) for x in ts]}))
    sys.stdout.flush()

    # Pipelined fused throughput: dispatch 2 ahead, fetch behind.
    from collections import deque

    q = deque()
    t0 = time.perf_counter()
    n = 12
    for i in range(n):
        q.append(fused_scalar(p, t, d, batches[i % 3]))
        if len(q) >= 2:
            float(q.popleft())
    while q:
        float(q.popleft())
    el = time.perf_counter() - t0
    print(json.dumps({"stage": "fused_pipelined", "launches": n,
                      "total_s": round(el, 2),
                      "per_launch_s": round(el / n, 4),
                      "lanes_per_s": round(n * LANES / el, 1)}))


if __name__ == "__main__":
    main()

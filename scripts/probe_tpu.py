"""One-off probe: per-launch wall time of the fused crack step on the live
device at several lanes x blocks geometries.  Writes one JSON line per
geometry to stdout.  Not part of the package; evidence-gathering for PERF.md.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_crack_step,
    plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    words = synth_wordlist(20000)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    host_digest = HOST_DIGEST[spec.algo]
    ds = build_digest_set([host_digest(b"bench-decoy-%d" % i) for i in range(1024)],
                          spec.algo)
    t, d = table_arrays(ct), digest_arrays(ds)
    p = plan_arrays(plan)

    geoms = [(1 << 16, 512), (1 << 19, 4096), (1 << 21, 16384), (1 << 22, 32768)]
    for lanes, blocks in geoms:
        step = make_crack_step(spec, num_lanes=lanes, out_width=plan.out_width)
        batch, w, rank = make_blocks(plan, start_word=0, start_rank=0,
                                     max_variants=lanes, max_blocks=blocks)
        b = block_arrays(batch, num_blocks=blocks)
        t0 = time.perf_counter()
        out = step(p, t, b, d)
        n_emitted = int(out["n_emitted"])
        compile_s = time.perf_counter() - t0
        # steady state: 3 timed launches, blocking each
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = step(p, t, b, d)
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        per = min(times)
        print(json.dumps({
            "lanes": lanes, "blocks": blocks, "out_width": plan.out_width,
            "compile_s": round(compile_s, 2), "launch_s": round(per, 4),
            "n_emitted": n_emitted,
            "hashes_per_sec": round(n_emitted / per, 1),
        }))
        sys.stdout.flush()


if __name__ == "__main__":
    main()

"""Stage decomposition on the live device: time expand-only, hash-only,
membership-only, and the full fused step at one geometry.  Evidence for
PERF.md; not part of the package."""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays,
    make_candidates_step, make_crack_step, plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.hashes import HASH_FNS
from hashcat_a5_table_generator_tpu.ops.membership import (
    build_digest_set, digest_member,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

LANES = 1 << 19
BLOCKS = 4096


def timeit(fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return compile_s, min(times)


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    packed = pack_words(synth_wordlist(20000))
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(
        [HOST_DIGEST["md5"](b"bench-decoy-%d" % i) for i in range(1024)], "md5"
    )
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    batch, _, _ = make_blocks(plan, start_word=0, start_rank=0,
                              max_variants=LANES, max_blocks=BLOCKS)
    b = block_arrays(batch, num_blocks=BLOCKS)
    w = plan.out_width

    # Full fused step
    step = make_crack_step(spec, num_lanes=LANES, out_width=w)
    c, r = timeit(step, p, t, b, d)
    print(json.dumps({"stage": "fused", "compile_s": round(c, 1),
                      "launch_s": round(r, 4)}))
    sys.stdout.flush()

    # Expand only
    cstep = make_candidates_step(spec, num_lanes=LANES, out_width=w)
    c, r = timeit(cstep, p, t, b)
    print(json.dumps({"stage": "expand", "compile_s": round(c, 1),
                      "launch_s": round(r, 4)}))
    sys.stdout.flush()

    # Hash only (fixed candidate buffer)
    cand = jnp.asarray(
        np.random.default_rng(0).integers(97, 123, size=(LANES, w),
                                          dtype=np.uint8))
    clen = jnp.full((LANES,), w - 2, dtype=jnp.int32)
    hash_fn = jax.jit(HASH_FNS["md5"])
    c, r = timeit(hash_fn, cand, clen)
    print(json.dumps({"stage": "hash", "compile_s": round(c, 1),
                      "launch_s": round(r, 4)}))
    sys.stdout.flush()

    # Membership only (fixed state)
    state = jnp.asarray(
        np.random.default_rng(1).integers(0, 2**32, size=(LANES, 4),
                                          dtype=np.uint64).astype(np.uint32))
    mem_fn = jax.jit(lambda s, rows, bm: digest_member(s, rows, bm))
    c, r = timeit(mem_fn, state, d["rows"], d["bitmap"])
    print(json.dumps({"stage": "membership", "compile_s": round(c, 1),
                      "launch_s": round(r, 4)}))
    sys.stdout.flush()


if __name__ == "__main__":
    main()

#!/bin/bash
# Round-5 TPU measurement session (run the moment the tunnel recovers).
# Produces, under $OUT:
#   bench_headline.json  - bench.py default MD5, both arms (xla vs pallas)
#   bench_suball.json    - bench.py -s substitute-all, both arms
#   bench_sha1.json      - bench.py sha1, both arms
#   probe_fused.txt      - production-body A/B with planted-hit cross-check
#   sweep_cli.txt        - sustained production CLI crack sweep throughput
# Each step is individually time-capped; a re-wedged tunnel fails the step,
# not the session.
set -u
OUT=${OUT:-/tmp/tpu_session_r5}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ===" | tee -a "$OUT/log"
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  echo "rc=$? $name" | tee -a "$OUT/log"
  tail -3 "$OUT/$name.err" >> "$OUT/log" 2>/dev/null
}

# 1. Production-body A/B with planted-hit correctness cross-check (2^22).
run probe_fused 900 python scripts/probe_fused.py

# 2. Headline bench, both arms, long window.
run bench_headline 700 python bench.py --wall-budget 600 --seconds 10

# 3. Substitute-all flagship (BASELINE configs[3] analog).
run bench_suball 700 python bench.py --wall-budget 600 --seconds 10 --mode suball

# 4. Second algo (BASELINE configs[4] analog).
run bench_sha1 700 python bench.py --wall-budget 600 --seconds 10 --algo sha1

# 4b. Geometry probe: stride 256 (fewer ops/candidate per PERF.md §7 —
#     3254 vs 3597 — but bigger tiles; the A/B settles which wins on chip).
run bench_stride256 700 python bench.py --wall-budget 600 --seconds 10 \
    --blocks 16384

# 4c. Grid-height probe: 16 blocks per Pallas grid step (amortizes
#     per-step block-field loads; parity-pinned in the interpret suite).
A5GEN_PALLAS_G=16 run bench_g16 700 python bench.py --wall-budget 600 \
    --seconds 10 --arm pallas

# 5. Sustained production CLI crack sweep (VERDICT r4 #4): synthetic
#    rockyou-class dictionary, qwerty-cyrillic, MD5 digests, device backend.
OUT="$OUT" python - <<'EOF'
import hashlib, os, sys
sys.path.insert(0, ".")
from bench import synth_wordlist
out = os.environ["OUT"]
words = synth_wordlist(200000)
os.makedirs(out, exist_ok=True)
with open(os.path.join(out, "dict.txt"), "wb") as f:
    f.write(b"\n".join(words) + b"\n")
with open(os.path.join(out, "digests.txt"), "w") as f:
    for i in (0, 1000, 100000):
        f.write(hashlib.md5(words[i]).hexdigest() + "\n")
EOF
run emit_table 120 python -m hashcat_a5_table_generator_tpu \
    --emit-table qwerty-cyrillic --output "$OUT/qc.table" /dev/null
run sweep_cli 900 python -m hashcat_a5_table_generator_tpu \
    "$OUT/dict.txt" -t "$OUT/qc.table" --backend device \
    --digests "$OUT/digests.txt" --progress

echo "=== session done ($(date -u +%H:%M:%S)) ===" | tee -a "$OUT/log"
for f in probe_fused bench_headline bench_suball bench_sha1; do
  echo "--- $f"; tail -2 "$OUT/$f.out" 2>/dev/null
done
grep -E "hits|candidates hashed" "$OUT/sweep_cli.err" 2>/dev/null | tail -2

"""Geometry sweep: pipelined fused throughput across lanes x stride on the
live device. Evidence for PERF.md; not part of the package."""

import json
import os
import sys
import time
from collections import deque

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_fused_body,
    plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    packed = pack_words(synth_wordlist(50000))
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(
        [HOST_DIGEST["md5"](b"bench-decoy-%d" % i) for i in range(1024)], "md5"
    )
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)

    geoms = [
        (1 << 19, 128), (1 << 20, 128), (1 << 21, 128),
        (1 << 20, 256), (1 << 21, 256), (1 << 22, 256),
        (1 << 21, 512),
    ]
    for lanes, stride in geoms:
        blocks = lanes // stride
        fused = make_fused_body(spec, num_lanes=lanes,
                                out_width=plan.out_width, block_stride=stride)
        step = jax.jit(
            lambda p_, t_, d_, b_: fused(p_, t_, d_, b_)["n_emitted"]
        )
        batches = []
        w = rank = 0
        for _ in range(3):
            batch, w, rank = make_blocks(
                plan, start_word=w, start_rank=rank, max_variants=lanes,
                max_blocks=blocks, fixed_stride=stride,
            )
            batches.append(block_arrays(batch, num_blocks=blocks))
        t0 = time.perf_counter()
        emitted = [int(step(p, t, d, b)) for b in batches]
        compile_s = time.perf_counter() - t0
        n = 10
        q = deque()
        hashed = 0
        t0 = time.perf_counter()
        for i in range(n):
            q.append(step(p, t, d, batches[i % 3]))
            if len(q) >= 2:
                hashed += int(q.popleft())
        while q:
            hashed += int(q.popleft())
        el = time.perf_counter() - t0
        print(json.dumps({
            "lanes": lanes, "stride": stride, "blocks": blocks,
            "compile_s": round(compile_s, 1),
            "per_launch_s": round(el / n, 4),
            "hashes_per_sec": round(hashed / el, 1),
            "fill": round(sum(emitted) / (3 * lanes), 3),
        }))
        sys.stdout.flush()


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# The repo's whole static-analysis pass as one command (local + CI).
#
#   scripts/lint.sh            # run everything available
#   scripts/lint.sh --require-all   # fail if ruff/mypy are missing (CI)
#
# Seven layers, any failure fails the script:
#   1. ruff      — pyflakes + pycodestyle errors ([tool.ruff] in pyproject)
#   2. mypy      — typed public API, strict on leaf modules ([tool.mypy])
#   3. graftlint — repo-specific JAX/Pallas AST rules (tools/graftlint),
#                  over the package, tools/, bench.py AND scripts/ —
#                  incl. GL013 (timing accumulation belongs to the
#                  telemetry registry, PERF.md §21)
#   4. graftaudit — jaxpr/HLO-level semantic audits (tools/graftaudit):
#                  kernel op budgets (KERNEL_BUDGETS.json), dead-stage
#                  (DCE) detection, float/transfer purity, Pallas bounds,
#                  and audit_telemetry (registry/timeline calls off the
#                  hot path). Trace/lower only, CPU backend — PERF.md §16.
#   5. graftrace — thread-topology & lock-discipline analysis over the
#                  threaded runtime, the chunk ring, and tools/ itself
#                  (tools/graftrace): unguarded shared writes,
#                  lock-order cycles, queue wait-for cycles, router
#                  passthrough — PERF.md §26.
#   6. graftwire — wire-protocol contract audit over the serve/fleet
#                  JSONL plane (tools/graftwire): emitted/dispatched
#                  docs vs the runtime/protocol.py registry, the
#                  router↔engine handler matrix, required-field and
#                  dead-read checks, envelope-key sprawl, and drift vs
#                  the committed PROTOCOL.json pin — PERF.md §25/§27.
#   7. graftknob — configuration-knob contract audit (tools/graftknob):
#                  every env/cli/config/serve-doc/tune-profile surface
#                  vs the runtime/knobs.py registry, declared roles
#                  traced to the step-cache / pack / affinity /
#                  fingerprint key sites, default drift, README
#                  staleness, and drift vs the committed KNOBS.json
#                  pin — PERF.md §30.
#
# ruff and mypy are OPTIONAL locally (the TPU dev containers bake only the
# jax toolchain; nothing may be pip-installed there) and mandatory in CI
# via --require-all. graftlint, graftrace, graftwire and graftknob are
# stdlib-only and always run; graftaudit needs jax (always present —
# the core dependency).
set -u -o pipefail

cd "$(dirname "$0")/.."

REQUIRE_ALL=0
if [ "${1:-}" = "--require-all" ]; then
    REQUIRE_ALL=1
fi

fail=0

run_optional() {
    local name="$1"
    shift
    if command -v "$name" >/dev/null 2>&1; then
        echo "== $name =="
        if ! "$@"; then
            echo "lint.sh: $name FAILED" >&2
            fail=1
        fi
    elif [ "$REQUIRE_ALL" = 1 ]; then
        echo "lint.sh: $name is required (--require-all) but not installed" >&2
        fail=1
    else
        echo "== $name == SKIPPED (not installed; pip install -e '.[dev]')"
    fi
}

run_optional ruff ruff check .
run_optional mypy mypy

echo "== graftlint =="
if ! python -m tools.graftlint hashcat_a5_table_generator_tpu tools \
        bench.py scripts; then
    echo "lint.sh: graftlint FAILED" >&2
    fail=1
fi

echo "== graftaudit =="
if ! env JAX_PLATFORMS=cpu python -m tools.graftaudit; then
    echo "lint.sh: graftaudit FAILED" >&2
    fail=1
fi

echo "== graftrace =="
if ! python -m tools.graftrace; then
    echo "lint.sh: graftrace FAILED" >&2
    fail=1
fi

echo "== graftwire =="
if ! python -m tools.graftwire; then
    echo "lint.sh: graftwire FAILED" >&2
    fail=1
fi

echo "== graftknob =="
if ! python -m tools.graftknob --check-readme README.md; then
    echo "lint.sh: graftknob FAILED" >&2
    fail=1
fi

if [ "$fail" = 0 ]; then
    echo "lint.sh: all checks passed"
fi
exit "$fail"

#!/bin/bash
# Poll for TPU availability; when it comes up, write /tmp/tpu_status and
# immediately kick off the round-5 measurement session (scripts/tpu_session.sh).
while true; do
  timeout 90 python - <<'PY' > /tmp/tpu_probe.out 2>&1
import jax
ds = jax.devices()
print("OK", jax.default_backend(), [str(d) for d in ds])
PY
  if grep -q '^OK' /tmp/tpu_probe.out 2>/dev/null; then
    if grep -qiE 'tpu|axon' /tmp/tpu_probe.out; then
      cp /tmp/tpu_probe.out /tmp/tpu_status
      echo "$(date -u +%H:%M:%S) UP: $(cat /tmp/tpu_probe.out)" >> /tmp/tpu_watch.log
      OUT=/tmp/tpu_session_r5b bash /root/repo/scripts/tpu_session2.sh \
        >> /tmp/tpu_watch.log 2>&1
      echo "$(date -u +%H:%M:%S) session done" >> /tmp/tpu_watch.log
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) non-tpu: $(cat /tmp/tpu_probe.out)" >> /tmp/tpu_watch.log
  else
    echo "$(date -u +%H:%M:%S) down: $(tail -1 /tmp/tpu_probe.out 2>/dev/null)" >> /tmp/tpu_watch.log
  fi
  sleep 60
done

#!/bin/bash
# Poll for TPU availability; write status to /tmp/tpu_status when it comes up.
while true; do
  timeout 90 python - <<'PY' > /tmp/tpu_probe.out 2>&1
import jax
ds = jax.devices()
print("OK", jax.default_backend(), [str(d) for d in ds])
PY
  if grep -q '^OK' /tmp/tpu_probe.out 2>/dev/null; then
    if grep -q 'cpu' /tmp/tpu_probe.out && ! grep -qiE 'tpu|axon' /tmp/tpu_probe.out; then
      echo "$(date -u +%H:%M:%S) cpu-only: $(cat /tmp/tpu_probe.out)" >> /tmp/tpu_watch.log
    else
      cp /tmp/tpu_probe.out /tmp/tpu_status
      echo "$(date -u +%H:%M:%S) UP: $(cat /tmp/tpu_probe.out)" >> /tmp/tpu_watch.log
      exit 0
    fi
  else
    echo "$(date -u +%H:%M:%S) down: $(tail -1 /tmp/tpu_probe.out 2>/dev/null)" >> /tmp/tpu_watch.log
  fi
  sleep 60
done

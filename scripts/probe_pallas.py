"""A/B the Pallas MD5 kernel against the XLA hash path inside the fused
crack step on the live device. Evidence for PERF.md §3; not part of the
package. Run twice-in-one: both programs built in-process (the A5GEN_PALLAS
env hook is trace-time, so we call maybe_pallas_hash_fn's target directly).
"""

import json
import os
import sys
import time
from collections import deque

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, plan_arrays,
    table_arrays, _expand,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.hashes import HASH_FNS
from hashcat_a5_table_generator_tpu.ops.membership import (
    build_digest_set, digest_member,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.ops.pallas_md5 import md5_pallas
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

LANES = 1 << 19
BLOCKS = 4096
STRIDE = LANES // BLOCKS


def fused_with(hash_fn, spec, ow):
    def body(p, t, d, b):
        cand, cand_len, word_row, emit = _expand(
            spec, p, t, b, num_lanes=LANES, out_width=ow,
            block_stride=STRIDE,
        )
        state = hash_fn(cand, cand_len)
        member = digest_member(state, d["rows"], d["bitmap"])
        hit = member & emit
        return {
            "n_emitted": jnp.sum(emit.astype(jnp.int32)),
            "n_hits": jnp.sum(hit.astype(jnp.int32)),
        }

    return jax.jit(body)


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    packed = pack_words(synth_wordlist(20000))
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(
        [HOST_DIGEST["md5"](b"bench-decoy-%d" % i) for i in range(1024)], "md5"
    )
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    batches = []
    w = rank = 0
    for _ in range(3):
        batch, w, rank = make_blocks(plan, start_word=w, start_rank=rank,
                                     max_variants=LANES, max_blocks=BLOCKS,
                                     fixed_stride=STRIDE)
        batches.append(block_arrays(batch, num_blocks=BLOCKS))

    for name, hash_fn in (("xla_md5", HASH_FNS["md5"]),
                          ("pallas_md5", md5_pallas)):
        step = fused_with(hash_fn, spec, plan.out_width)
        t0 = time.perf_counter()
        e0 = int(step(p, t, d, batches[0])["n_emitted"])
        compile_s = time.perf_counter() - t0
        n = 10
        q = deque()
        hashed = 0
        t0 = time.perf_counter()
        for i in range(n):
            q.append(step(p, t, d, batches[i % 3]))
            if len(q) >= 2:
                hashed += int(q.popleft()["n_emitted"])
        while q:
            hashed += int(q.popleft()["n_emitted"])
        el = time.perf_counter() - t0
        print(json.dumps({
            "variant": name, "compile_s": round(compile_s, 1),
            "per_launch_s": round(el / n, 4),
            "hashes_per_sec": round(hashed / el, 1),
            "hits_consistent": int(step(p, t, d, batches[0])["n_hits"]),
        }))
        sys.stdout.flush()


if __name__ == "__main__":
    main()

"""A/B the Pallas MD5 kernel against the XLA hash path inside the REAL
fused crack step on the live device (PERF.md §3 evidence; not part of the
package).

Fidelity notes (review-driven):
* Both variants build the production program via ``make_crack_step`` — the
  ``A5GEN_PALLAS`` hook is read at trace-build time inside
  ``make_fused_body``, so toggling the env var between the two builds
  yields two full-fidelity programs in one process.
* Eligibility is asserted up front — ``md5_pallas`` silently falls back to
  XLA for ineligible geometries, which would turn the A/B into a
  self-comparison.
* The digest set plants REAL candidate hashes, so ``n_hits`` equality
  between variants is a live correctness signal for the Pallas kernel,
  not a vacuous 0 == 0.
"""

import json
import os
import sys
import time
from collections import deque

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_crack_step,
    plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.ops.pallas_md5 import pallas_supported
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

LANES = 1 << 19
BLOCKS = 4096
STRIDE = LANES // BLOCKS


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    sub_map = get_layout("qwerty-cyrillic").to_substitution_map()
    ct = compile_table(sub_map)
    words = synth_wordlist(20000)
    packed = pack_words(words)
    plan = build_plan(spec, ct, packed)
    assert pallas_supported(LANES, plan.out_width), (
        f"geometry ineligible for Pallas (lanes={LANES}, "
        f"out_width={plan.out_width}) — the A/B would self-compare"
    )
    # The hook must actually select the Pallas kernel on this platform —
    # otherwise both variants compile the identical XLA program and the
    # planted-hit check passes vacuously.
    from hashcat_a5_table_generator_tpu.ops.pallas_md5 import (
        maybe_pallas_hash_fn, md5_pallas,
    )

    os.environ["A5GEN_PALLAS"] = "1"
    assert maybe_pallas_hash_fn("md5", None) is md5_pallas, (
        f"Pallas hook not engaged on platform {dev.platform!r} — "
        "the A/B would self-compare"
    )

    # Plant real hits inside the first launch's lane span so n_hits is a
    # live cross-variant correctness signal.
    host_digest = HOST_DIGEST[spec.algo]
    planted = list(iter_candidates(words[0], sub_map, 0, 15))[:3]
    targets = [host_digest(c) for c in planted]
    targets += [host_digest(b"bench-decoy-%d" % i) for i in range(1021)]
    ds = build_digest_set(targets, spec.algo)
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    batches = []
    w = rank = 0
    for _ in range(3):
        batch, w, rank = make_blocks(plan, start_word=w, start_rank=rank,
                                     max_variants=LANES, max_blocks=BLOCKS,
                                     fixed_stride=STRIDE)
        batches.append(block_arrays(batch, num_blocks=BLOCKS))

    hits_by_variant = {}
    for name, env in (("xla_md5", "0"), ("pallas_md5", "1")):
        os.environ["A5GEN_PALLAS"] = env  # read at trace-build time
        step = make_crack_step(spec, num_lanes=LANES,
                               out_width=plan.out_width, block_stride=STRIDE)
        t0 = time.perf_counter()
        first = step(p, t, batches[0], d)
        hits_by_variant[name] = int(first["n_hits"])
        compile_s = time.perf_counter() - t0
        n = 10
        q = deque()
        hashed = 0
        t0 = time.perf_counter()
        for i in range(n):
            q.append(step(p, t, batches[i % 3], d))
            if len(q) >= 2:
                hashed += int(q.popleft()["n_emitted"])
        while q:
            hashed += int(q.popleft()["n_emitted"])
        el = time.perf_counter() - t0
        print(json.dumps({
            "variant": name, "compile_s": round(compile_s, 1),
            "per_launch_s": round(el / n, 4),
            "hashes_per_sec": round(hashed / el, 1),
            "n_hits_first_launch": hits_by_variant[name],
        }))
        sys.stdout.flush()

    assert hits_by_variant["pallas_md5"] == hits_by_variant["xla_md5"] >= 1, (
        f"planted-hit mismatch: {hits_by_variant} — Pallas digests diverge"
    )
    print("# planted hits consistent across variants", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Capture a jax.profiler trace of the fused crack step on the live device
and print the top XLA ops by device self-time (parsed from the xplane.pb —
the tensorboard_plugin_profile conversion path is broken in this image, so
we aggregate the raw planes ourselves).  Evidence for PERF.md."""

import glob
import json
import os
import sys
import time
from collections import defaultdict

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_a5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import synth_wordlist
from hashcat_a5_table_generator_tpu.models.attack import (
    AttackSpec, block_arrays, build_plan, digest_arrays, make_fused_body,
    plan_arrays, table_arrays,
)
from hashcat_a5_table_generator_tpu.ops.blocks import make_blocks
from hashcat_a5_table_generator_tpu.ops.membership import build_digest_set
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.tables.compile import compile_table
from hashcat_a5_table_generator_tpu.tables.layouts import get_layout
from hashcat_a5_table_generator_tpu.utils.digests import HOST_DIGEST

TRACE_DIR = sys.argv[1] if len(sys.argv) > 1 else "/tmp/a5_trace"
LANES = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 19
STRIDE = int(sys.argv[3]) if len(sys.argv) > 3 else 128
BLOCKS = LANES // STRIDE


def analyze(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    if not paths:
        print(json.dumps({"error": "no xplane.pb found"}))
        return
    xspace = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as fh:
        xspace.ParseFromString(fh.read())
    for plane in xspace.planes:
        if "TPU" not in plane.name and "/device" not in plane.name.lower():
            continue
        ev_names = dict(plane.event_metadata.items())
        totals = defaultdict(lambda: [0.0, 0])
        for line in plane.lines:
            for ev in line.events:
                meta = ev_names.get(ev.metadata_id)
                name = meta.name if meta else str(ev.metadata_id)
                totals[name][0] += ev.duration_ps / 1e12
                totals[name][1] += 1
        top = sorted(totals.items(), key=lambda kv: -kv[1][0])[:25]
        print(f"## plane: {plane.name}")
        for name, (sec, cnt) in top:
            print(f"{sec:9.4f}s  x{cnt:<5d} {name[:110]}")


def main():
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    spec = AttackSpec(mode="default", algo="md5")
    ct = compile_table(get_layout("qwerty-cyrillic").to_substitution_map())
    packed = pack_words(synth_wordlist(20000))
    plan = build_plan(spec, ct, packed)
    ds = build_digest_set(
        [HOST_DIGEST["md5"](b"bench-decoy-%d" % i) for i in range(1024)], "md5"
    )
    p, t, d = plan_arrays(plan), table_arrays(ct), digest_arrays(ds)
    batches = []
    w = rank = 0
    for _ in range(3):
        batch, w, rank = make_blocks(plan, start_word=w, start_rank=rank,
                                     max_variants=LANES, max_blocks=BLOCKS,
                                     fixed_stride=STRIDE)
        batches.append(block_arrays(batch, num_blocks=BLOCKS))

    fused = make_fused_body(spec, num_lanes=LANES, out_width=plan.out_width,
                            block_stride=STRIDE)
    step = jax.jit(lambda p_, t_, d_, b_: fused(p_, t_, d_, b_)["n_emitted"])
    int(step(p, t, d, batches[0]))  # compile

    with jax.profiler.trace(TRACE_DIR):
        for i in range(8):
            int(step(p, t, d, batches[i % 3]))
    print("# trace captured", file=sys.stderr)
    analyze(TRACE_DIR)


if __name__ == "__main__":
    main()

"""Pod-sharded giant-job mode (PERF.md §29): ONE oversized keyspace job
split across a pod via per-device block-cursor stripes.

Stream contract: the union of the shards' hit streams is byte-exact the
single-device stream (each hit found by exactly ONE stripe), every
shard sweeps the FULL dictionary, and the checkpoint cursor stays the
GLOBAL linear (word, rank) cursor — a shard checkpoint resumes under
the single-device path and vice versa.  Most tests run the stripes
in-process (``SweepConfig.pod`` is plain config); the 2-process
``run_crack_giant`` surface runs behind the ``pod_collectives`` guard.
"""

import hashlib
import io
import json
import os
import pathlib
import socket
import subprocess
import sys

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.runtime import (
    CandidateWriter,
    HitRecorder,
    load_checkpoint,
)
from hashcat_a5_table_generator_tpu.runtime.sweep import Sweep, SweepConfig

REPO = pathlib.Path(__file__).resolve().parent.parent

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"xyzzy", b"sass", b"passes"]


def oracle_lines(spec):
    out = []
    for w in WORDS:
        out.extend(iter_candidates(w, LEET, spec.min_substitute,
                                   spec.max_substitute))
    return out


def planted_digests(spec, picks=(0, 2, 5)):
    oracle = oracle_lines(spec)
    planted = sorted({oracle[len(oracle) * i // 7] for i in picks})
    digests = [hashlib.md5(c).digest() for c in planted]
    digests += [hashlib.md5(b"decoy%d" % i).digest() for i in range(20)]
    return planted, digests


def cfg(pod=None, **kw):
    # devices=1 pins one local device per shard: total stripes ==
    # pod process count, matching one-chip-per-process pods.
    kw.setdefault("lanes", 64)
    kw.setdefault("num_blocks", 16)
    kw.setdefault("superstep", 1)
    kw.setdefault("devices", 1)
    return SweepConfig(pod=pod, **kw)


def hit_tuples(res):
    return sorted(
        (h.word_index, h.variant_rank, h.candidate) for h in res.hits
    )


class TestStripeParity:
    # 3-way striping is the slow-tier arm (~7 s: one solo + three shard
    # sweeps); the 2-way arm keeps the parity contract in the default
    # tier.
    @pytest.mark.parametrize(
        "nprocs",
        [2, pytest.param(3, marks=pytest.mark.slow)],
    )
    def test_stripe_union_is_byte_exact_solo_stream(self, nprocs):
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec)
        solo = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        shards = [
            Sweep(spec, LEET, WORDS, digests,
                  config=cfg(pod=(p, nprocs))).run_crack()
            for p in range(nprocs)
        ]
        # Disjoint union: every hit found by exactly one stripe.
        union = [t for s in shards for t in hit_tuples(s)]
        assert len(union) == len(set(union))
        assert sorted(union) == hit_tuples(solo)
        assert {t[2] for t in union} == set(planted)
        assert sum(s.n_emitted for s in shards) == solo.n_emitted
        # Every shard sweeps the FULL dictionary (words_done merges by
        # max across shards, never sum).
        for s in shards:
            assert s.words_done == solo.words_done == len(WORDS)

    def test_geometry_stamp_records_stripe(self):
        spec = AttackSpec(mode="default", algo="md5")
        _, digests = planted_digests(spec)
        res = Sweep(spec, LEET, WORDS, digests,
                    config=cfg(pod=(1, 2))).run_crack()
        assert res.geometry["pod"] == [1, 2]
        solo = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        assert solo.geometry["pod"] is None


class TestPodGuards:
    def test_candidates_mode_raises(self):
        spec = AttackSpec(mode="default", algo="md5")
        sweep = Sweep(spec, LEET, WORDS, [], config=cfg(pod=(0, 2)))
        with pytest.raises(RuntimeError, match="crack-only"):
            sweep.run_candidates(CandidateWriter(io.BytesIO()))

    def test_per_launch_path_raises(self):
        """superstep=0 pins the per-launch pipeline; the striping seam
        IS the superstep block lattice, so pod mode must fail loudly
        instead of sweeping every shard over the whole keyspace."""
        spec = AttackSpec(mode="default", algo="md5")
        _, digests = planted_digests(spec)
        sweep = Sweep(spec, LEET, WORDS, digests,
                      config=cfg(pod=(0, 2), superstep=0))
        with pytest.raises(RuntimeError, match="superstep executor"):
            sweep.run_crack()

    def test_bad_pod_tuple_raises(self):
        spec = AttackSpec(mode="default", algo="md5")
        with pytest.raises(ValueError, match="pod"):
            Sweep(spec, LEET, WORDS, [], config=cfg(pod=(2, 2)))


class TestGiantJobResume:
    def test_mid_stripe_resume_is_byte_exact(self, tmp_path):
        """A shard killed mid-job resumes from its boundary checkpoint
        and finishes with the identical stripe stream."""
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, picks=(0, 1, 2, 4, 6))
        pod = (1, 2)
        want = Sweep(spec, LEET, WORDS, digests,
                     config=cfg(pod=pod)).run_crack()
        assert want.n_hits >= 2, "need >=2 stripe hits to interrupt"

        path = str(tmp_path / "shard1.json")
        ckpt_cfg = cfg(pod=pod, checkpoint_path=path,
                       checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        # Blow up on the SECOND stripe hit: at least one superstep
        # boundary (and its every_s=0 checkpoint) has passed by then.
        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=ckpt_cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None

        second = Sweep(spec, LEET, WORDS, digests, config=ckpt_cfg)
        got = second.run_crack()
        assert got.resumed
        assert hit_tuples(got) == hit_tuples(want)
        assert got.n_emitted == want.n_emitted
        assert got.words_done == want.words_done

    def test_split_to_solo_resume_is_byte_exact(self, tmp_path):
        """Split→solo round-trip (PERF.md §31): a stripe's mid-job
        checkpoint resumes on the SOLO path byte-exactly — the replayed
        prefix is the stripe's checkpointed hits, and the tail is the
        full solo stream from the global cursor on (every stripe's
        share, not just the checkpointing shard's).  This is the
        boundary the fleet router reassigns a dead shard's range from:
        nothing before the acked cursor replays, nothing after it is
        missed."""
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, picks=(0, 1, 2, 4, 6))
        path = str(tmp_path / "shard0.json")
        pod_cfg = cfg(pod=(0, 2), checkpoint_path=path,
                      checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        # Second-hit boom: guarantees a boundary checkpoint exists.
        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=pod_cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None
        boundary = (partial.cursor.word, partial.cursor.rank)

        solo_cfg = cfg(checkpoint_path=path, checkpoint_every_s=0.0)
        got = Sweep(spec, LEET, WORDS, digests,
                    config=solo_cfg).run_crack()
        assert got.resumed
        full = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        want = sorted(
            set(partial.hits)
            | {(h.word_index, h.variant_rank) for h in full.hits
               if (h.word_index, h.variant_rank) >= boundary}
        )
        assert [(h.word_index, h.variant_rank)
                for h in sorted(got.hits,
                                key=lambda h: (h.word_index,
                                               h.variant_rank))] == want

    def test_solo_to_split_resume_is_byte_exact(self, tmp_path):
        """Solo→split round-trip (PERF.md §31): a SOLO mid-job
        checkpoint seeds a full set of pod stripes — exactly the fleet
        router's split scatter, which parks a running solo job and
        hands its checkpoint to every shard.  Each shard replays the
        checkpointed prefix; the stripes' tails are disjoint and their
        union restores the full solo stream byte-exactly."""
        import shutil

        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, picks=(0, 1, 2, 4, 6))
        path = str(tmp_path / "solo.json")
        solo_cfg = cfg(checkpoint_path=path, checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=solo_cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None
        prefix = set(partial.hits)

        shards = []
        for p in range(2):
            # Each shard resumes its own COPY: a resumed sweep keeps
            # writing to its checkpoint path, exactly like the router
            # handing the parked parent's checkpoint to every shard.
            sp = str(tmp_path / f"seed{p}.json")
            shutil.copy(path, sp)
            res = Sweep(spec, LEET, WORDS, digests,
                        config=cfg(pod=(p, 2), checkpoint_path=sp,
                                   checkpoint_every_s=0.0)).run_crack()
            assert res.resumed
            shards.append(res)
        full = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        tails = [
            [(h.word_index, h.variant_rank) for h in s.hits
             if (h.word_index, h.variant_rank) not in prefix]
            for s in shards
        ]
        # Disjoint stripe tails; prefix ∪ tails == the full solo stream.
        assert not set(tails[0]) & set(tails[1])
        assert sorted(prefix | set(tails[0]) | set(tails[1])) == sorted(
            (h.word_index, h.variant_rank) for h in full.hits
        )
        assert {t[2] for t in hit_tuples(full)} == set(planted)

    @pytest.mark.slow  # ~4 s on the tier-1 host; the mid-stripe resume
    # test above keeps the giant-job checkpoint family's default arm
    def test_cursor_interchanges_with_single_device_path(self, tmp_path):
        """The giant job is ONE job: a shard's mid-job checkpoint is a
        plain global (word, rank) cursor, so the single-device sweep
        resumes it — and from that boundary emits exactly the solo
        stream's tail (a superset of the one stripe's tail)."""
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, picks=(0, 1, 2, 4, 6))
        path = str(tmp_path / "shard0.json")
        pod_cfg = cfg(pod=(0, 2), checkpoint_path=path,
                      checkpoint_every_s=0.0)

        class Boom(Exception):
            pass

        # Second-hit boom: guarantees a boundary checkpoint exists.
        class ExplodingRecorder(HitRecorder):
            def emit(self, record):
                super().emit(record)
                if len(self.hits) == 2:
                    raise Boom()

        first = Sweep(spec, LEET, WORDS, digests, config=pod_cfg)
        with pytest.raises(Boom):
            first.run_crack(ExplodingRecorder())
        partial = load_checkpoint(path, first.fingerprint)
        assert partial is not None

        # Resume the SAME checkpoint file on the solo path (pod=None,
        # same fingerprint — geometry/devices are excluded from it).
        solo_cfg = cfg(checkpoint_path=path, checkpoint_every_s=0.0)
        got = Sweep(spec, LEET, WORDS, digests, config=solo_cfg).run_crack()
        assert got.resumed
        assert got.words_done == len(WORDS)
        full = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
        # The resumed tail is a subset of the full solo stream, and
        # nothing before the checkpointed cursor is re-emitted.
        assert set(hit_tuples(got)) <= set(hit_tuples(full))
        assert got.n_emitted <= full.n_emitted


_GIANT_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # one local device per process
import jax
jax.config.update("jax_platforms", "cpu")
import json
pid = int(sys.argv[1])
port = sys.argv[2]
outdir = sys.argv[3]

from hashcat_a5_table_generator_tpu.parallel import multihost
multihost.initialize(f"127.0.0.1:{port}", 2, pid)

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.ops.packing import pack_words
from hashcat_a5_table_generator_tpu.parallel.multihost import run_crack_giant
from hashcat_a5_table_generator_tpu.runtime.sweep import SweepConfig

LEET = {b"a": [b"4", b"@"], b"o": [b"0"], b"s": [b"$", b"5"], b"e": [b"3"]}
WORDS = [b"password", b"sesame", b"octopus", b"zzz", b"a", b"assess",
         b"oboe", b"xyzzy", b"sass", b"passes"]
digests = [bytes.fromhex(h) for h in json.loads(sys.argv[4])]

spec = AttackSpec(mode="default", algo="md5")
res = run_crack_giant(
    spec, LEET, pack_words(WORDS), digests,
    config=SweepConfig(lanes=64, num_blocks=16, superstep=1),
)
with open(os.path.join(outdir, f"out{pid}.json"), "w") as fh:
    json.dump({
        "n_emitted": res.n_emitted,
        "n_hits": res.n_hits,
        "words_done": res.words_done,
        "geometry_pod": res.geometry.get("pod"),
        "hits": [
            [h.word_index, h.variant_rank, h.candidate.hex()]
            for h in res.hits
        ],
    }, fh)
"""


def test_two_process_giant_job_matches_single(tmp_path, pod_collectives):
    """run_crack_giant over a real 2-process pod: both processes return
    the same combined result, byte-exact vs the single-device sweep,
    with words_done covering the FULL dictionary (not a wordlist
    stripe — the giant job splits blocks, not words)."""
    spec = AttackSpec(mode="default", algo="md5")
    planted, digests = planted_digests(spec)
    want = Sweep(spec, LEET, WORDS, digests, config=cfg()).run_crack()
    want_hits = [[h.word_index, h.variant_rank, h.candidate.hex()]
                 for h in sorted(want.hits,
                                 key=lambda h: (h.word_index,
                                                h.variant_rank))]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "child.py"
    script.write_text(_GIANT_CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(p), str(port),
             str(tmp_path), json.dumps([d.hex() for d in digests])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for p in range(2)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err.decode()[-3000:]

    results = [json.load(open(tmp_path / f"out{p}.json"))
               for p in range(2)]
    assert results[0] == results[1]
    assert results[0]["hits"] == want_hits
    assert results[0]["n_emitted"] == want.n_emitted
    assert results[0]["words_done"] == len(WORDS)
    assert results[0]["geometry_pod"] in ([0, 2], [1, 2])

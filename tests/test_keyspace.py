"""Keyspace arithmetic vs the oracle: count_candidates must equal the exact
number of emissions for every mode, including all quirk regimes (overlapping
spans, multi-option keys, min/max windows, early returns)."""

import pytest

from hashcat_a5_table_generator_tpu.oracle.engines import iter_candidates
from hashcat_a5_table_generator_tpu.oracle.keyspace import count_candidates

TABLES = {
    "single": {b"h": [b"H"], b"e": [b"E"], b"l": [b"L"], b"o": [b"O"]},
    "multiopt": {b"a": [b"1", b"2"], b"b": [b"3"], b"c": [b"4", b"5", b"6"]},
    "overlap": {b"s": [b"Z"], b"ss": ["ß".encode()]},
    "lengthy": {b"a": [b"XX"], b"b": [b"YY"]},
    "dup": {b"a": [b"X", b"X"]},
}

WORDS = [b"hello", b"ss", b"sss", b"abc", b"aabbcc", b"a", b"", b"zz", b"abab"]
WINDOWS = [(0, 15), (0, 0), (1, 1), (2, 3), (0, 2), (3, 15), (2, 2)]
MODES = [(False, False), (False, True), (True, False), (True, True)]


@pytest.mark.parametrize("table_name", sorted(TABLES))
@pytest.mark.parametrize("lo,hi", WINDOWS)
@pytest.mark.parametrize("substitute_all,reverse", MODES)
def test_count_matches_oracle(table_name, lo, hi, substitute_all, reverse):
    table = TABLES[table_name]
    for word in WORDS:
        if reverse and not substitute_all:
            # skip vectors that panic the reference (Q3) — counting still
            # counts them as emissions-before-panic is undefined; the panic
            # vector is excluded from the counting contract
            try:
                n = len(list(iter_candidates(
                    word, table, lo, hi,
                    substitute_all=substitute_all, reverse=reverse)))
            except Exception:
                continue
        else:
            n = len(list(iter_candidates(
                word, table, lo, hi,
                substitute_all=substitute_all, reverse=reverse)))
        assert count_candidates(
            word, table, lo, hi, substitute_all=substitute_all, reverse=reverse
        ) == n, (word, table_name, lo, hi, substitute_all, reverse)


def test_q10_closed_forms():
    t = {b"h": [b"H"], b"e": [b"E"], b"l": [b"L"], b"o": [b"O"]}
    assert count_candidates(b"hello", t, 0, 15) == 31  # 2^5 - 1
    p = {c.encode(): [c.upper().encode()] for c in "paswordr"}
    assert count_candidates(b"password", p, 0, 15) == 255  # 2^8 - 1


def test_substitute_all_product_form():
    t = {b"a": [b"1", b"2"], b"b": [b"3"]}
    # prod(r_i + 1) = 3 * 2 over unique patterns
    assert count_candidates(b"ab", t, 0, 15, substitute_all=True) == 6


def test_huge_word_count_is_fast():
    t = {bytes([c]): [b"X"] for c in range(ord("a"), ord("z") + 1)}
    word = (b"abcdefghij" * 10)[:100]
    # 100 substitutable positions, window [1,15]: sum_{k=1}^{15} C(100,k)
    from math import comb

    expected = sum(comb(100, k) for k in range(1, 16))
    assert count_candidates(word, t, 0, 15) == expected

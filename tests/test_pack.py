"""Cross-job physical packing (PERF.md §22): compatible tenants' block
ranges fuse into ONE superstep dispatch with per-job counter rows — and
every per-job surface (hit stream, emitted counts, checkpoints, span
timeline) stays byte-identical to solo runs.  Plus the admission-time
compile offload: builds run on a bounded worker with error propagation
and shutdown drain.

Tier-1 budget: shares the suite's 64-lane × 16-block geometry; each
distinct packed static config compiles one small program.
"""

import hashlib
import json
import pathlib
import subprocess
import sys
import time

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import Sweep, SweepConfig
from hashcat_a5_table_generator_tpu.runtime.engine import Engine
from tests.test_engine import cfg, full_hits, planted_digests
from tests.test_superstep import LEET, WORDS, oracle_lines

#: Distinct tenants over one dictionary shape: same packed token width
#: and match-slot count (the packed-group compatibility the scheduler
#: looks for), different word order and digest sets.
WORDLISTS = [WORDS, WORDS[::-1], WORDS[3:] + WORDS[:3], WORDS[5:] + WORDS[:5]]


def _jobs(spec, n, picks=(0, -1), decoys=8):
    out = []
    for i in range(n):
        words = WORDLISTS[i % len(WORDLISTS)]
        _planted, digests = planted_digests(
            spec, LEET, words, picks, decoys=decoys
        )
        # Per-tenant decoys so no two jobs share a digest set.
        digests += [hashlib.md5(b"tenant-%d" % i).digest()]
        out.append((words, digests))
    return out


def _solo(spec, jobs, config):
    return [
        Sweep(spec, LEET, words, digests, config=config).run_crack(
            resume=False
        )
        for words, digests in jobs
    ]


class TestPackedParity:
    def test_four_job_packed_byte_parity(self):
        """Four distinct tenants fuse into one dispatch group (16
        blocks / 4 segments); every job's hit stream and emitted count
        equals its solo run's, and the packed program compiled exactly
        once."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 4, picks=(0, 4, -1))
        c = cfg(superstep=2)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        stats = eng.stats()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert stats["packed_dispatches"] > 0
        assert 0 < stats["packed_fill"] <= 1.0
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted
            assert g.superstep.get("packed") == 4
        # A second equal batch rides the cached packed program.
        eng2 = Engine(c, auto=False)
        base = eng2.stats()["programs_compiled"]
        handles = [eng2.submit(spec, LEET, w, d) for w, d in jobs]
        eng2.run_until_idle()
        assert eng2.stats()["programs_compiled"] == base
        for h, w in zip(handles, want):
            assert full_hits(h.result(timeout=0)) == full_hits(w)
        eng2.close()

    @pytest.mark.slow  # ~15 s on the tier-1 host; the windowed ×
    # streaming packed mix keeps default coverage via the homogeneous
    # packed-parity arms above and TestRefuse's streaming survivors.
    def test_heterogeneous_batch_windowed_and_streaming(self):
        """A mixed burst: two packable tenants, one WINDOWED job (its
        enumeration scheme is different static trace structure) and one
        STREAMING job (chunked plans never pack).  The compatible pair
        fuses; the others keep the per-job path; every job stays
        byte-identical to solo."""
        spec = AttackSpec(mode="default", algo="md5")
        wspec = AttackSpec(mode="default", algo="md5",
                           min_substitute=1, max_substitute=1)
        jobs = _jobs(spec, 2)
        _pw, wdigests = planted_digests(wspec, LEET, WORDS, (0, -1))
        c = cfg()
        cs = cfg(stream_chunk_words=2)
        want = _solo(spec, jobs, c)
        wsweep = Sweep(wspec, LEET, WORDS, wdigests, config=c)
        assert wsweep.plan.windowed
        want_w = wsweep.run_crack(resume=False)
        want_s = Sweep(
            spec, LEET, jobs[0][0], jobs[0][1], config=cs
        ).run_crack(resume=False)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        hw = eng.submit(wspec, LEET, WORDS, wdigests)
        hs = eng.submit(spec, LEET, jobs[0][0], jobs[0][1], config=cs)
        eng.run_until_idle()
        stats = eng.stats()
        assert stats["packed_dispatches"] > 0  # the pair fused
        for h, w in zip(handles, want):
            got = h.result(timeout=0)
            assert full_hits(got) == full_hits(w)
            assert got.superstep.get("packed") == 2
        got_w = hw.result(timeout=0)
        assert full_hits(got_w) == full_hits(want_w)
        assert "packed" not in got_w.superstep
        got_s = hs.result(timeout=0)
        assert full_hits(got_s) == full_hits(want_s)
        assert got_s.stream["chunks_swept"] == want_s.stream["chunks_swept"]
        eng.close()

    def test_overflow_replays_per_job(self):
        """A packed superstep whose shared hit buffer overflows replays
        each hit-bearing member's own block range through its per-launch
        path — never a dropped hit, per job."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, 1, 2, 7, -1))
        c = cfg(superstep=4, superstep_hit_cap=1)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert any(g.superstep.get("replays", 0) > 0 for g in got)
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted

    def test_uneven_members_release_early(self):
        """Members of different sizes can fuse (the key compares
        trailing shapes, not batch length); a member whose range drains
        early finishes THEN — its superstep count is its own range's,
        never inflated with no-op boundaries while the bigger
        cohabitant keeps sweeping."""
        from tests.test_engine import LONG_WORDS

        spec = AttackSpec(mode="default", algo="md5")
        _ps, dsmall = planted_digests(spec, LEET, WORDS, (0, -1))
        _pb, dbig = planted_digests(spec, LEET, LONG_WORDS, (1, -1))
        c = cfg(superstep=1)
        want_s = Sweep(spec, LEET, WORDS, dsmall,
                       config=c).run_crack(resume=False)
        want_b = Sweep(spec, LEET, LONG_WORDS, dbig,
                       config=c).run_crack(resume=False)
        eng = Engine(c, auto=False)
        hs = eng.submit(spec, LEET, WORDS, dsmall)
        hb = eng.submit(spec, LEET, LONG_WORDS, dbig)
        eng.run_until_idle()
        assert eng.stats()["packed_dispatches"] > 0
        got_s, got_b = hs.result(timeout=0), hb.result(timeout=0)
        eng.close()
        assert full_hits(got_s) == full_hits(want_s)
        assert full_hits(got_b) == full_hits(want_b)
        assert got_s.superstep["packed"] == got_b.superstep["packed"] == 2
        assert (
            got_s.superstep["supersteps"] < got_b.superstep["supersteps"]
        )

    def test_sharded_packed_parity(self):
        """The sharded twin: two tenants fused over a 2-device mesh —
        the segmented counter rows ride the single stacked psum."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, -1))
        c = cfg(devices=2)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        stats = eng.stats()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert stats["packed_dispatches"] > 0
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted


class TestTenantControl:
    def test_pause_mid_fused_dispatch_leaves_cohabitants(self):
        """Pausing one tenant mid-fused-dispatch parks only its segment:
        cohabitants finish byte-identical, and the paused job resumes
        from its checkpoint (on a second engine) to the same stream."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 4, picks=(0, -1))
        c = cfg(superstep=1)  # many small supersteps -> park mid-sweep
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng._admit()
        assert eng.stats()["fused_groups"] == 1
        eng._serve_round()
        victim = handles[1]
        victim.request_pause()
        eng.run_until_idle()
        assert victim.state == "paused"
        ck = victim.checkpoint
        assert ck is not None
        for i, h in enumerate(handles):
            if i == 1:
                continue
            got = h.result(timeout=0)
            assert full_hits(got) == full_hits(want[i])
            assert got.n_emitted == want[i].n_emitted
        # Migrate the paused tenant to a fresh engine.
        eng2 = Engine(c, auto=False)
        w, d = jobs[1]
        resumed = eng2.submit(spec, LEET, w, d, resume_state=ck)
        eng2.run_until_idle()
        got = resumed.result(timeout=0)
        assert full_hits(got) == full_hits(want[1])
        assert got.n_emitted == want[1].n_emitted
        eng.close()
        eng2.close()

    def test_cancel_mid_fused_dispatch_keeps_cohabitants(self):
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, -1))
        c = cfg(superstep=1)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng._admit()
        eng._serve_round()
        handles[0].cancel()
        eng.run_until_idle()
        assert handles[0].state == "cancelled"
        got = handles[1].result(timeout=0)
        assert full_hits(got) == full_hits(want[1])
        assert got.n_emitted == want[1].n_emitted
        eng.close()

    def test_span_attribution_under_fused_dispatch(self):
        """Per-job telemetry: each fused tenant's span timeline records
        ITS OWN consumed boundaries (one per packed superstep it rode),
        not the group's aggregate."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0,))
        c = cfg(superstep=2)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        for h in handles:
            res = h.result(timeout=0)
            spans = h.span_summary
            assert spans["spans"] == res.superstep["supersteps"] > 0
            assert spans["host_gap_s"] >= 0.0
        eng.close()


class TestPackKnobs:
    def test_pack_off_restores_per_job_dispatch(self, monkeypatch):
        """A5GEN_PACK=off: the PR 8 per-job dispatch path, byte-
        identical streams, zero packed dispatches."""
        monkeypatch.setenv("A5GEN_PACK", "off")
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, -1))
        c = cfg(superstep=2)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        stats = eng.stats()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert stats["packed_dispatches"] == 0
        assert stats["fused_groups"] == 0
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert "packed" not in g.superstep

    def test_engine_pack_false_overrides_env(self):
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2)
        eng = Engine(cfg(superstep=2), auto=False, pack=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        assert eng.stats()["packed_dispatches"] == 0
        for h in handles:
            h.result(timeout=0)
        eng.close()


class TestAdmissionWorker:
    def test_build_error_propagates_and_engine_survives(self):
        """A job whose build raises settles FAILED with the worker's
        exception; peers in the same burst still run (and can still
        fuse among themselves)."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0,))
        c = cfg(superstep=2)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        bad = eng.submit(spec, LEET, [b"ok", "not-bytes"],
                         jobs[0][1])
        eng.run_until_idle()
        assert bad.state == "failed"
        assert bad.error is not None
        with pytest.raises(Exception):
            bad.result(timeout=0)
        for h, w in zip(handles, want):
            assert full_hits(h.result(timeout=0)) == full_hits(w)
        eng.close()

    def test_builds_run_off_the_serve_thread(self):
        """The admission offload: the worker thread owns the build
        (observable through the engine's jobs_building gauge while the
        serve thread is parked)."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 1, picks=(0,))
        eng = Engine(cfg(), auto=False)
        assert eng._admit_ex is not None
        h = eng.submit(spec, LEET, *jobs[0])
        # Drain submissions onto the worker without waiting, then wait
        # for the build to land and serve it.
        eng._admit(wait=False)
        eng.run_until_idle()
        h.result(timeout=0)
        eng.close()

    def test_close_drains_pending_builds(self):
        """close() settles every submitted job even when its build is
        still queued — the shutdown drain contract."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 3, picks=(0,))
        eng = Engine(cfg(superstep=2), auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.close()  # default drain: builds land, jobs run to done
        for h in handles:
            assert h.wait(timeout=30)
            assert h.state == "done"

    def test_close_cancel_drops_building_jobs(self):
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 3, picks=(0,))
        eng = Engine(cfg(superstep=2), auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.close(cancel=True)
        for h in handles:
            assert h.wait(timeout=30)
            assert h.state in ("cancelled", "done")

    def test_sync_admission_mode(self):
        """admission_worker=False: builds happen inline in _admit — the
        pre-§22 behavior, still packable."""
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, -1))
        c = cfg(superstep=2)
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False, admission_worker=False)
        assert eng._admit_ex is None
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        assert eng.stats()["packed_dispatches"] > 0
        for h, w in zip(handles, want):
            assert full_hits(h.result(timeout=0)) == full_hits(w)
        eng.close()

#: Long-tenant churn fixtures cached per geometry: the re-fuse tests
#: need work REMAINING after the mid-flight departures, and they share
#: the solo baseline sweeps to keep the tier-1 budget flat.
_CHURN_CACHE: dict = {}


def _churn_fixture(spec, c, n=4, reps=4):
    key = (spec.mode, c.lanes, c.num_blocks, c.superstep, n, reps)
    if key not in _CHURN_CACHE:
        jobs = []
        for i in range(n):
            rot = WORDS[i % len(WORDS):] + WORDS[:i % len(WORDS)]
            words = rot * reps
            _p, digests = planted_digests(
                spec, LEET, words, (0, -1), decoys=4
            )
            digests += [hashlib.md5(b"tenant-%d" % i).digest()]
            jobs.append((words, digests))
        _CHURN_CACHE[key] = (jobs, _solo(spec, jobs, c))
    return _CHURN_CACHE[key]


def _drive_until_idle(eng, max_rounds=400):
    for _ in range(max_rounds):
        eng._serve_round()
        eng._admit(wait=True)  # collects off-thread re-fuse builds too
        if not eng.stats()["jobs_active"]:
            return
    raise AssertionError("engine did not drain")


class TestRefuse:
    def test_refuse_retraces_survivors_byte_exact(self):
        """Two of four fused tenants cancel mid-flight; the thinned
        group's fill drops below the threshold and the engine re-fuses
        the survivors into a tighter group (PERF.md §28) — their hit
        streams stay byte-exact vs solo, the retrace is counted, and
        the per-pump fill instruments record the post-departure
        decay."""
        spec = AttackSpec(mode="default", algo="md5")
        c = cfg(superstep=1)
        jobs, want = _churn_fixture(spec, c)
        eng = Engine(c, auto=False, refuse_below=0.9)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng._admit()
        assert eng.stats()["fused_groups"] == 1
        for _ in range(2):
            eng._serve_round()
        handles[0].cancel()
        handles[1].cancel()
        _drive_until_idle(eng)
        st = eng.stats()
        got = [handles[i].result(timeout=5) for i in (2, 3)]
        eng.close()
        assert st["refuse_total"] >= 1
        assert 0.0 < st["packed_fill_min"] < 1.0
        assert st["packed_fill_last"] > 0.0
        assert handles[0].state == handles[1].state == "cancelled"
        for g, w in zip(got, (want[2], want[3])):
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted

    def test_refuse_disabled_keeps_thinned_group(self):
        """refuse_below=0 pins the pre-§28 behavior: the thinned group
        keeps dispatching with masked lanes (no retrace) and the
        survivors still drain byte-exact."""
        spec = AttackSpec(mode="default", algo="md5")
        c = cfg(superstep=1)
        jobs, want = _churn_fixture(spec, c)
        eng = Engine(c, auto=False, refuse_below=0)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng._admit()
        for _ in range(2):
            eng._serve_round()
        handles[0].cancel()
        handles[1].cancel()
        _drive_until_idle(eng)
        st = eng.stats()
        got = [handles[i].result(timeout=5) for i in (2, 3)]
        eng.close()
        assert st["refuse_total"] == 0
        # The fill instruments still record the decay — the §28
        # observability fix is independent of the re-fuse response.
        assert 0.0 < st["packed_fill_min"] < 1.0
        for g, w in zip(got, (want[2], want[3])):
            assert full_hits(g) == full_hits(w)

    def test_refuse_checkpoint_carry_over(self):
        """Cursor interchangeability across a re-fuse: a survivor
        pauses AFTER riding the retraced group; its checkpoint resumes
        on a second engine to the same stream — rank-stride cursors
        carry over through the re-fuse unchanged."""
        spec = AttackSpec(mode="default", algo="md5")
        c = cfg(superstep=1)
        jobs, want = _churn_fixture(spec, c)
        eng = Engine(c, auto=False, refuse_below=0.9)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng._admit()
        for _ in range(2):
            eng._serve_round()
        handles[0].cancel()
        handles[1].cancel()
        landed = False
        for _ in range(400):
            eng._serve_round()
            eng._admit(wait=True)
            st = eng.stats()
            if st["refuse_total"] and not st["jobs_refusing"]:
                landed = True
                break
        assert landed, "re-fuse never landed while work remained"
        eng._serve_round()  # at least one round on the NEW group
        handles[2].request_pause()
        eng.run_until_idle()
        assert handles[2].state == "paused"
        ck = handles[2].checkpoint
        assert ck is not None
        got3 = handles[3].result(timeout=5)
        assert full_hits(got3) == full_hits(want[3])
        eng.close()
        eng2 = Engine(c, auto=False)
        resumed = eng2.submit(spec, LEET, jobs[2][0], jobs[2][1],
                              resume_state=ck)
        eng2.run_until_idle()
        got2 = resumed.result(timeout=5)
        eng2.close()
        assert full_hits(got2) == full_hits(want[2])
        assert got2.n_emitted == want[2].n_emitted

    def test_refuse_threshold_env_parsing(self, monkeypatch):
        """The A5GEN_REFUSE hatch (GL012: read via runtime.env):
        unset = 0.5, off-spellings disable, a ratio in (0, 1] is
        honored, and garbage warns + keeps the default."""
        from hashcat_a5_table_generator_tpu.runtime.env import (
            refuse_threshold,
        )

        monkeypatch.delenv("A5GEN_REFUSE", raising=False)
        assert refuse_threshold() == 0.5
        for off in ("off", "0", "no"):
            monkeypatch.setenv("A5GEN_REFUSE", off)
            assert refuse_threshold() is None
        monkeypatch.setenv("A5GEN_REFUSE", "0.8")
        assert refuse_threshold() == 0.8
        for bad in ("1.5", "-1", "nonsense"):
            monkeypatch.setenv("A5GEN_REFUSE", bad)
            assert refuse_threshold() == 0.5


class TestPackedPallasFastPath:
    def test_packed_group_rides_fused_kernel(self, monkeypatch):
        """The §28 tentpole: a packed group of compatible jobs compiles
        to the FUSED Pallas kernel tier (PERF.md §11), not the XLA
        fallback — the per-segment scalar-unit tables ride the
        concatenated batch rows.  Fake a TPU so the gates open, force
        interpret-mode pallas, spy the kernel wrapper, and parity-check
        both tenants against solo runs through the same tier."""
        import hashcat_a5_table_generator_tpu.ops.pallas_expand as pe
        from hashcat_a5_table_generator_tpu.runtime import SweepConfig

        monkeypatch.setattr(pe, "_on_tpu", lambda: True)
        monkeypatch.delenv("A5GEN_PALLAS", raising=False)
        monkeypatch.setenv("A5GEN_PALLAS_INTERPRET", "1")

        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2, picks=(0, -1))
        c = SweepConfig(lanes=1024, num_blocks=8, superstep=1)
        # The solo plan must be kernel-eligible at this geometry, or
        # the packed assertion below would test nothing.
        probe = Sweep(spec, LEET, jobs[0][0], jobs[0][1], config=c)
        assert pe.opts_for(
            spec, probe.plan, probe.ct,
            block_stride=c.resolve_block_stride(),
            num_blocks=c.num_blocks,
        ) is not None
        want = _solo(spec, jobs, c)

        calls = []
        real = pe.fused_expand_md5

        def spy(*a, **kw):
            calls.append(kw)
            return real(*a, **kw)

        monkeypatch.setattr(pe, "fused_expand_md5", spy)
        eng = Engine(c, auto=False)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        stats = eng.stats()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert stats["packed_dispatches"] > 0  # the pair fused...
        # ...and the packed program traced THROUGH the fused kernel
        # (an XLA-tier fallback would leave the spy untouched).
        assert calls
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.superstep.get("packed") == 2


REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_bench_pack_ab_record_shape():
    """The §22 measurement instrument: one JSON line, both arms, the
    wall-ratio/fill/ttfc/fairness numbers the acceptance criteria read,
    with per-job emitted counts parity-asserted against solo runs
    inside the bench itself.  Slow-marked: subprocess bench."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pack-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "16", "--pack-jobs", "4"],
        capture_output=True, timeout=540, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "pack_mode_ab"
    assert rec["jobs"] == 4
    assert rec["packed"]["emitted"] == rec["round_robin"]["emitted"]
    assert all(e > 0 for e in rec["packed"]["emitted"])
    assert rec["packed"]["packed_dispatches"] > 0
    assert rec["round_robin"]["packed_dispatches"] == 0
    assert 0 < rec["fill_ratio"] <= 1.0
    for key in ("wall_ratio", "warm_ttfc_batch_s"):
        assert isinstance(rec[key], float) and rec[key] > 0
    for arm in ("packed", "round_robin"):
        assert rec[arm]["wall_s"] > 0
        assert rec[arm]["admit_wall_s"] > 0
    # The §28 post-departure fill instruments ride the same record.
    for arm in ("packed", "round_robin"):
        assert 0.0 <= rec[arm]["fill_min"] <= 1.0
        assert rec[arm]["refuse_total"] >= 0


@pytest.mark.slow
def test_bench_pack_churn_record_shape():
    """The §28 measurement instrument: one JSON line, both churn arms,
    the wall-ratio/fill-recovery numbers the acceptance criteria read,
    with survivors parity-asserted against solo runs inside the bench
    itself (it exits nonzero on divergence OR when the re-fuse arm
    never retraced).  Slow-marked: subprocess bench."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pack-churn",
         "--platform", "cpu", "--lanes", "256", "--blocks", "16",
         "--words", "600", "--pack-jobs", "4", "--churn-waves", "2"],
        capture_output=True, timeout=540, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "pack_churn_ab"
    assert rec["jobs"] == 4
    assert rec["refuse"]["refuse_total"] > 0
    assert rec["control"]["refuse_total"] == 0
    # The control arm keeps the thinned group: its fill never
    # recovers; the re-fuse arm's peak sits back above the trigger.
    assert 0.0 < rec["refuse"]["fill_min"] < 1.0
    assert rec["fill_recovered"] > rec["refuse_below"]
    for arm in ("refuse", "control"):
        assert rec[arm]["wall_s"] > 0
        assert rec[arm]["packed_dispatches"] > 0
        assert rec[arm]["supersteps_served"] > 0
    assert rec["wall_ratio"] > 0


class TestPodNeverFuses:
    """The graftknob GK003 find, regression-pinned: a pod-striped giant
    job advances the block lattice per stripe, and the fused group's
    shared step has no stripe advance — even equal-pod tenants would
    replay each other's stripes.  ``pack_candidate`` refuses pod
    sweeps outright; a pod job through the pack-enabled engine rides
    the solo dispatch path byte-identically."""

    def test_pack_candidate_refuses_pod_sweeps(self):
        from hashcat_a5_table_generator_tpu.runtime.fuse import (
            pack_candidate,
        )

        spec = AttackSpec(mode="default", algo="md5")
        ((words, digests),) = _jobs(spec, 1)
        solo = Sweep(spec, LEET, words, digests,
                     config=cfg(superstep=2))
        assert pack_candidate(solo) is not None
        pod = Sweep(spec, LEET, words, digests,
                    config=cfg(superstep=2, pod=(0, 2)))
        assert pack_candidate(pod) is None

    def test_pod_jobs_demote_to_solo_byte_exact(self):
        spec = AttackSpec(mode="default", algo="md5")
        jobs = _jobs(spec, 2)
        c = cfg(superstep=2, pod=(0, 2))
        want = _solo(spec, jobs, c)
        eng = Engine(c, auto=False, pack=True)
        handles = [eng.submit(spec, LEET, w, d) for w, d in jobs]
        eng.run_until_idle()
        stats = eng.stats()
        got = [h.result(timeout=0) for h in handles]
        eng.close()
        assert stats["packed_dispatches"] == 0
        for g, w in zip(got, want):
            assert full_hits(g) == full_hits(w)
            assert g.n_emitted == w.n_emitted
            assert g.superstep.get("packed") is None

"""Digest-set membership: build, bitmap prefilter, exact search vs hashlib."""

import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from hashcat_a5_table_generator_tpu.ops.hashes import (
    digest_bytes,
    jit_md5,
    jit_ntlm,
    jit_sha1,
)
from hashcat_a5_table_generator_tpu.ops.membership import (
    DigestSet,
    bitmap_probe,
    build_digest_set,
    digest_member,
    jit_digest_member,
)
from hashcat_a5_table_generator_tpu.ops.packing import pack_words


def _member(ds: DigestSet, probes: np.ndarray) -> np.ndarray:
    return np.asarray(
        jit_digest_member(
            jnp.asarray(probes, dtype=jnp.uint32),
            jnp.asarray(ds.rows),
            jnp.asarray(ds.bitmap),
        )
    )


def _md5_words(data: bytes) -> np.ndarray:
    return np.frombuffer(hashlib.md5(data).digest(), dtype="<u4").astype(np.uint32)


class TestBuildDigestSet:
    def test_sorted_and_deduped(self):
        digs = [hashlib.md5(bytes([i])).hexdigest() for i in range(50)]
        ds = build_digest_set(digs + digs[:10], "md5")
        assert ds.size == 50
        rows = ds.rows
        for i in range(1, ds.size):
            assert tuple(rows[i - 1]) < tuple(rows[i])

    def test_accepts_raw_and_hex(self):
        raw = hashlib.sha1(b"x").digest()
        ds = build_digest_set([raw, raw.hex()], "sha1")
        assert ds.size == 1
        assert ds.rows.shape == (1, 5)

    def test_empty(self):
        ds = build_digest_set([], "md5")
        assert ds.size == 0
        probes = np.stack([_md5_words(b"a")])
        assert not _member(ds, probes).any()


class TestBitmap:
    def test_members_always_pass_prefilter(self):
        digs = [hashlib.md5(b"w%d" % i).digest() for i in range(200)]
        ds = build_digest_set(digs, "md5", bitmap_bits=12)
        probes = np.stack([_md5_words(b"w%d" % i) for i in range(200)])
        pre = np.asarray(bitmap_probe(jnp.asarray(probes), jnp.asarray(ds.bitmap)))
        assert pre.all()

    def test_nondefault_bitmap_bits_membership(self):
        # Regression: probe derives the mask from the bitmap's own size, so a
        # DigestSet built with non-default bits still finds every member.
        digs = [hashlib.md5(b"nb%d" % i).digest() for i in range(64)]
        ds = build_digest_set(digs, "md5", bitmap_bits=12)
        probes = np.stack([_md5_words(b"nb%d" % i) for i in range(64)])
        assert _member(ds, probes).all()

    def test_prefilter_rejects_most_misses(self):
        ds = build_digest_set([hashlib.md5(b"only").digest()], "md5")
        probes = np.stack([_md5_words(b"m%d" % i) for i in range(512)])
        pre = np.asarray(bitmap_probe(jnp.asarray(probes), jnp.asarray(ds.bitmap)))
        # One digest in a 2^24 bitmap: essentially every miss is pruned.
        assert pre.sum() <= 1


class TestExactMembership:
    @pytest.mark.parametrize("set_size", [1, 2, 3, 7, 100, 1000])
    def test_hits_and_misses(self, set_size):
        members = [hashlib.md5(b"in%d" % i).digest() for i in range(set_size)]
        ds = build_digest_set(members, "md5")
        hit_probes = np.stack([_md5_words(b"in%d" % i) for i in range(set_size)])
        miss_probes = np.stack([_md5_words(b"out%d" % i) for i in range(64)])
        assert _member(ds, hit_probes).all()
        assert not _member(ds, miss_probes).any()

    def test_first_word_collision_not_false_positive(self):
        # Same leading word, different tail: full-row compare must reject.
        base = _md5_words(b"target")
        twisted = base.copy()
        twisted[3] ^= np.uint32(1)
        rows = np.stack([base])
        ds = build_digest_set([hashlib.md5(b"target").digest()], "md5")
        assert _member(ds, np.stack([base]))[0]
        assert not _member(ds, np.stack([twisted]))[0]

    def test_boundary_probes(self):
        # Probes below the smallest and above the largest row.
        ds = build_digest_set(
            [hashlib.md5(b"mid%d" % i).digest() for i in range(32)], "md5"
        )
        lo = np.zeros((1, 4), dtype=np.uint32)
        hi = np.full((1, 4), 0xFFFFFFFF, dtype=np.uint32)
        assert not _member(ds, lo)[0]
        assert not _member(ds, hi)[0]

    def test_sha1_five_words(self):
        members = [hashlib.sha1(b"s%d" % i).digest() for i in range(33)]
        ds = build_digest_set(members, "sha1")
        probes = np.stack(
            [
                np.frombuffer(hashlib.sha1(b"s%d" % i).digest(), dtype=">u4")
                .astype(np.uint32)
                for i in range(33)
            ]
        )
        assert _member(ds, probes).all()
        probes[:, 4] ^= 1
        assert not _member(ds, probes).any()


class TestEndToEndHashMembership:
    """Device-hash → device-membership round trips against hashlib."""

    def test_md5_pipeline(self):
        words = [b"password", b"hello", b"p@ssw0rd", b"zzz"]
        targets = [hashlib.md5(w).digest() for w in words[:2]]
        ds = build_digest_set(targets, "md5")
        packed = pack_words(words)
        state = jit_md5(jnp.asarray(packed.tokens), jnp.asarray(packed.lengths))
        got = _member(ds, np.asarray(state))
        assert got.tolist() == [True, True, False, False]

    def test_ntlm_pipeline(self):
        from tests.test_hashes import _ref_md4

        words = [b"admin", b"letmein", b"root"]
        targets = [
            _ref_md4(w.decode().encode("utf-16-le")) for w in words[1:]
        ]
        ds = build_digest_set(targets, "ntlm")
        packed = pack_words(words)
        state = jit_ntlm(jnp.asarray(packed.tokens), jnp.asarray(packed.lengths))
        got = _member(ds, np.asarray(state))
        assert got.tolist() == [False, True, True]

    def test_sha1_pipeline_digest_bytes_roundtrip(self):
        words = [b"alpha", b"beta"]
        packed = pack_words(words)
        state = np.asarray(
            jit_sha1(jnp.asarray(packed.tokens), jnp.asarray(packed.lengths))
        )
        ds = build_digest_set(digest_bytes(state, "sha1"), "sha1")
        assert _member(ds, state).all()

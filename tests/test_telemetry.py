"""Unified telemetry (PERF.md §21): the metrics registry's
counter/gauge/histogram semantics, snapshot/delta/merge algebra (incl.
the fixed-order merge the multihost exchange rides), the superstep span
timeline's ring bound and fetch-gap accounting, the
``A5GEN_TELEMETRY=off`` hatch (results identical, instrumentation
gone), the serve-mode ``metrics`` op (JSON + Prometheus) with per-job
span summaries, progress-line enrichment, and the ``--metrics-json``
writer.

The registry is process-wide and the suite shares one process: every
assertion against live counters is a DELTA between snapshots, never an
absolute value.  Fast tier only — the sweeps reuse the suite's 64-lane
× 16-block geometry so the process step cache serves them all; the
``--telemetry-ab`` subprocess bench is slow-marked.
"""

import io
import json
import pathlib
import subprocess
import sys
import time

import pytest

from hashcat_a5_table_generator_tpu.models.attack import AttackSpec
from hashcat_a5_table_generator_tpu.runtime import telemetry
from hashcat_a5_table_generator_tpu.runtime.engine import (
    Engine,
    serve_stdio,
)
from hashcat_a5_table_generator_tpu.runtime.progress import ProgressReporter
from hashcat_a5_table_generator_tpu.runtime.sweep import (
    Sweep,
    SweepConfig,
    step_cache_stats,
)
from tests.test_engine import cfg, full_hits, planted_digests
from tests.test_superstep import LEET, WORDS

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Registry primitives
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_accumulates(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("t.count")
        c.add()
        c.add(4)
        assert c.value == 5
        assert reg.counter("t.count") is c  # get-or-create

    def test_float_counter(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("t.wall_s")
        c.add(0.25)
        c.add(0.5)
        assert c.value == pytest.approx(0.75)

    def test_type_conflict_raises(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("t.x")
        with pytest.raises(TypeError):
            reg.gauge("t.x")

    def test_gauge_agg_validated(self):
        reg = telemetry.MetricsRegistry()
        with pytest.raises(ValueError):
            reg.gauge("t.g", agg="median")

    def test_histogram_bucket_edges(self):
        """``le`` semantics: a value exactly ON an edge lands in that
        edge's bucket; past the last edge lands in the overflow slot."""
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("t.h", edges=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 1.0, 10.0, 11.0):
            h.observe(v)
        snap = reg.snapshot()["t.h"]
        assert snap["edges"] == [0.1, 1.0, 10.0]
        assert snap["counts"] == [2, 2, 1, 1]  # le=.1, le=1, le=10, +Inf
        assert snap["count"] == 6
        assert snap["sum"] == pytest.approx(22.65)

    def test_histogram_rejects_unsorted_edges(self):
        reg = telemetry.MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("t.bad", edges=(1.0, 1.0))


class TestSnapshotAlgebra:
    def _reg(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("c").add(3)
        reg.gauge("g", agg="max").set(7)
        reg.histogram("h", edges=(1.0, 2.0)).observe(1.5)
        return reg

    def test_delta_roundtrip(self):
        reg = self._reg()
        before = reg.snapshot()
        reg.counter("c").add(2)
        reg.histogram("h", edges=(1.0, 2.0)).observe(0.5)
        reg.gauge("g", agg="max").set(4)
        d = telemetry.delta(before, reg.snapshot())
        assert d["c"]["value"] == 2
        assert d["h"]["counts"] == [1, 0, 0]
        assert d["h"]["count"] == 1
        assert d["g"]["value"] == 4  # gauges pass through
        # Unchanged metrics don't appear.
        reg2 = self._reg()
        assert telemetry.delta(reg2.snapshot(), reg2.snapshot()) == {}

    def test_merge_sums_and_aggs(self):
        a, b = self._reg().snapshot(), self._reg().snapshot()
        b["g"]["value"] = 11
        m = telemetry.merge([a, b])
        assert m["c"]["value"] == 6
        assert m["h"]["counts"] == [0, 2, 0]
        assert m["h"]["count"] == 2
        assert m["g"]["value"] == 11  # declared agg: max

    def test_merge_fixed_order_deterministic(self):
        """The multihost exchange merges every host's snapshot; the
        result must not depend on per-host dict insertion order."""
        a = {"x": {"type": "counter", "value": 1},
             "y": {"type": "counter", "value": 2}}
        b = {"y": {"type": "counter", "value": 20},
             "x": {"type": "counter", "value": 10}}
        m1, m2 = telemetry.merge([a, b]), telemetry.merge([b, a])
        assert m1 == m2
        assert list(m1) == sorted(m1)

    def test_merge_rejects_mismatched_edges(self):
        a = {"h": {"type": "histogram", "edges": [1.0], "counts": [1, 0],
                   "sum": 0.5, "count": 1}}
        b = {"h": {"type": "histogram", "edges": [2.0], "counts": [1, 0],
                   "sum": 0.5, "count": 1}}
        with pytest.raises(ValueError):
            telemetry.merge([a, b])

    def test_single_process_multihost_reduce(self):
        """``allgather_metrics`` at pod size 1: the degenerate exchange
        must return exactly the registry's own merge of one snapshot."""
        from hashcat_a5_table_generator_tpu.parallel.multihost import (
            allgather_metrics,
        )

        snap = {"c": {"type": "counter", "value": 5},
                "g": {"type": "gauge", "value": 2.5, "agg": "max"}}
        assert allgather_metrics(snap) == telemetry.merge([snap])

    def test_prometheus_exposition(self):
        reg = self._reg()
        text = telemetry.to_prometheus(reg.snapshot())
        assert "# TYPE a5gen_c counter" in text
        assert "a5gen_c 3" in text
        assert "# TYPE a5gen_g gauge" in text
        assert "# TYPE a5gen_h histogram" in text
        # Cumulative le buckets + the +Inf/sum/count trio.
        assert 'a5gen_h_bucket{le="1"} 0' in text
        assert 'a5gen_h_bucket{le="2"} 1' in text
        assert 'a5gen_h_bucket{le="+Inf"} 1' in text
        assert "a5gen_h_count 1" in text


class TestMergeSpecs:
    def test_superstep_spec_matches_bucketed_semantics(self):
        merged = telemetry.SUPERSTEP_MERGE.merge([
            {"supersteps": 2, "launches": 32, "replays": 0,
             "launches_per_fetch": 16, "pipelined": 1},
            {"supersteps": 3, "launches": 24, "replays": 1,
             "launches_per_fetch": 8, "pipelined": 0},
        ])
        assert merged == {"supersteps": 5, "launches": 56, "replays": 1,
                          "launches_per_fetch": 16, "pipelined": 1}

    def test_stream_spec_first_and_derived(self):
        merged = telemetry.STREAM_MERGE.merge([
            {"chunks": 2, "ttfc_s": 1.5, "overlap_ratio": 0.9,
             "peak_resident_plan_bytes": 100},
            {"chunks": 3, "ttfc_s": 9.0, "overlap_ratio": 0.1,
             "peak_resident_plan_bytes": 400},
        ])
        assert merged["chunks"] == 5
        assert merged["ttfc_s"] == 1.5  # first contributor only
        assert merged["peak_resident_plan_bytes"] == 400
        assert "overlap_ratio" not in merged  # derived: recomputed


# ---------------------------------------------------------------------------
# Span timeline
# ---------------------------------------------------------------------------


class TestSpanTimeline:
    def test_ring_bound_and_summary(self):
        clock = iter(float(i) for i in range(100))
        tl = telemetry.SpanTimeline(capacity=4, clock=lambda: next(clock))
        for i in range(10):
            tl.record_fetch(index=i, inflight=1 if i % 2 else 0,
                            emitted=5)
        spans = tl.spans()
        assert len(spans) == 4  # ring bound
        assert [s["index"] for s in spans] == [6, 7, 8, 9]
        s = tl.summary()
        assert s["spans"] == 10 and s["dropped"] == 6
        # 9 unit gaps; the even-indexed fetches (inflight 0) are dead.
        assert s["host_gap_s"] == pytest.approx(9.0)
        assert s["dead_host_s"] == pytest.approx(4.0)
        assert s["dead_share"] == pytest.approx(4.0 / 9.0, abs=1e-4)
        assert s["max_inflight"] == 1

    def test_queued_time_and_markers(self):
        clock = iter([10.0, 11.0])
        tl = telemetry.SpanTimeline(clock=lambda: next(clock))
        tl.record_fetch(dispatched_at=9.5, hits=2, hit_occupancy=0.5,
                        replayed=True, chunk=3)
        (rec,) = tl.spans()
        assert rec["queued_s"] == pytest.approx(0.5)
        assert rec["hit_occupancy"] == 0.5
        assert rec["replayed"] is True
        assert rec["chunk"] == 3

    def test_off_hatch_records_nothing(self, monkeypatch):
        monkeypatch.setenv("A5GEN_TELEMETRY", "off")
        tl = telemetry.SpanTimeline()
        tl.record_fetch(emitted=100)
        assert tl.spans() == [] and tl.summary() == {}

    def test_empty_summary(self):
        assert telemetry.SpanTimeline().summary() == {}


# ---------------------------------------------------------------------------
# Env hatch
# ---------------------------------------------------------------------------


class TestEnvHatch:
    def test_off_spellings(self, monkeypatch):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            telemetry_enabled,
        )

        for off in ("off", "0", "no"):
            monkeypatch.setenv("A5GEN_TELEMETRY", off)
            assert not telemetry_enabled()
        for on in ("", "on", "1", "auto"):
            monkeypatch.setenv("A5GEN_TELEMETRY", on)
            assert telemetry_enabled()

    def test_typo_warns_once_and_keeps_default(self, monkeypatch, capsys):
        from hashcat_a5_table_generator_tpu.runtime.env import (
            telemetry_enabled,
        )

        monkeypatch.setenv("A5GEN_TELEMETRY", "offf-typo-telemetry")
        assert telemetry_enabled()  # typo keeps the default (on)
        assert telemetry_enabled()
        err = capsys.readouterr().err
        assert err.count("unrecognized A5GEN_TELEMETRY") == 1


# ---------------------------------------------------------------------------
# Sweep integration
# ---------------------------------------------------------------------------


class TestSweepIntegration:
    def test_off_hatch_parity_and_instrumentation(self, monkeypatch):
        """The hatch changes observability, never results: identical
        hit streams and counts, spans only on the instrumented arm."""
        spec = AttackSpec(mode="default", algo="md5")
        _planted, digests = planted_digests(spec, LEET, WORDS, (0, -1))

        def run():
            sweep = Sweep(spec, LEET, WORDS, digests,
                          config=cfg(superstep=1))
            return sweep, sweep.run_crack(resume=False)

        monkeypatch.setenv("A5GEN_TELEMETRY", "off")
        s_off, r_off = run()
        monkeypatch.delenv("A5GEN_TELEMETRY")
        before = telemetry.snapshot()
        s_on, r_on = run()
        d = telemetry.delta(before, telemetry.snapshot())
        assert full_hits(r_off) == full_hits(r_on)
        assert r_off.n_emitted == r_on.n_emitted
        assert s_off.timeline.summary() == {}
        on_summary = s_on.timeline.summary()
        assert on_summary["spans"] > 0
        assert d["sweep.candidates"]["value"] == r_on.n_emitted
        assert d["sweep.hits"]["value"] == r_on.n_hits
        assert d["sweep.fetches.superstep"]["value"] == on_summary["spans"]

    def test_result_counters_are_registry_views(self):
        """The deprecation shims: schema/step cache stats derive from
        registry counters (one source of truth)."""
        from hashcat_a5_table_generator_tpu.ops.packing import (
            schema_cache_stats,
        )

        before_steps = step_cache_stats()
        telemetry.counter("step_cache.hits").add(2)
        after = step_cache_stats()
        assert after["hits"] - before_steps["hits"] == 2
        before_schema = schema_cache_stats()
        telemetry.counter("schema_cache.misses").add(3)
        assert (schema_cache_stats()["misses"]
                - before_schema["misses"]) == 3

    def test_checkpoint_counters(self, tmp_path):
        from hashcat_a5_table_generator_tpu.runtime.checkpoint import (
            CheckpointState,
            save_checkpoint,
        )

        before = telemetry.snapshot()
        save_checkpoint(str(tmp_path / "ck.json"),
                        CheckpointState(fingerprint="f" * 8))
        d = telemetry.delta(before, telemetry.snapshot())
        assert d["checkpoint.saves"]["value"] == 1
        assert d["checkpoint.bytes_written"]["value"] > 0


# ---------------------------------------------------------------------------
# Progress enrichment
# ---------------------------------------------------------------------------


class TestProgressEnrichment:
    def test_hits_per_sec_windowed(self):
        clock = iter([0.0, 0.0, 10.0, 20.0])
        out = io.StringIO()
        rep = ProgressReporter(100, every_s=0.0, stream=out,
                               clock=lambda: next(clock))
        rep.update(words_done=10, emitted=50, hits=5)
        rep.update(words_done=20, emitted=150, hits=25)
        lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
        assert lines[1]["progress"]["hits_per_sec"] == pytest.approx(2.0)
        assert lines[1]["progress"]["cand_per_sec"] == pytest.approx(10.0)

    def test_seed_hits_baselines_resumed_window(self):
        """A resumed crack sweep re-reports checkpointed hits up front;
        seed_hits keeps them out of this process's first rate window
        (seed_emitted's twin)."""
        clock = iter([0.0, 10.0])
        out = io.StringIO()
        rep = ProgressReporter(100, every_s=0.0, stream=out,
                               clock=lambda: next(clock))
        rep.seed_emitted(500)
        rep.seed_hits(1000)
        rep.update(words_done=50, emitted=600, hits=1002)
        line = json.loads(out.getvalue())["progress"]
        assert line["hits_per_sec"] == pytest.approx(0.2)
        assert line["cand_per_sec"] == pytest.approx(10.0)

    def test_telemetry_block_present_only_when_on(self, monkeypatch):
        # Give the registry some signal so the block is non-empty.
        telemetry.counter("sweep.host_gap_s").add(1.0)
        telemetry.counter("sweep.dead_host_s").add(0.25)

        def one_line():
            clock = iter([0.0, 1.0])
            out = io.StringIO()
            rep = ProgressReporter(10, every_s=0.0, stream=out,
                                   clock=lambda: next(clock))
            rep.update(words_done=1, emitted=1, hits=0)
            return json.loads(out.getvalue())["progress"]

        body = one_line()
        assert "dead_share" in body["telemetry"]
        assert 0.0 <= body["telemetry"]["dead_share"] <= 1.0
        monkeypatch.setenv("A5GEN_TELEMETRY", "off")
        assert "telemetry" not in one_line()


# ---------------------------------------------------------------------------
# Serve-mode metrics op + per-job spans
# ---------------------------------------------------------------------------


class TestServeMetrics:
    def test_metrics_op_without_jobs(self):
        """The observability surface of a running engine: one op, JSON
        snapshot + Prometheus text, no job required."""
        telemetry.counter("engine.test_marker").add(1)
        eng = Engine(cfg(), auto=False)
        reqs = io.StringIO(json.dumps({"op": "metrics"}) + "\n"
                           + json.dumps({"op": "shutdown"}) + "\n")
        out = io.StringIO()
        try:
            serve_stdio(eng, reqs, out)
        finally:
            eng.close()
        events = [json.loads(ln) for ln in out.getvalue().splitlines()]
        assert [e["event"] for e in events] == ["metrics", "bye"]
        m = events[0]["metrics"]
        assert m["engine.test_marker"]["type"] == "counter"
        assert "a5gen_engine_test_marker 1" in events[0]["prometheus"]
        # Snapshot keys arrive sorted (the fixed-order contract).
        assert list(m) == sorted(m)

    def test_done_event_carries_span_summary(self):
        spec = AttackSpec(mode="default", algo="md5")
        planted, digests = planted_digests(spec, LEET, WORDS, (0,))
        eng = Engine(cfg(superstep=1))
        reqs = io.StringIO(json.dumps({
            "op": "submit", "id": "t1",
            "words": [w.decode() for w in WORDS],
            "table_map": {
                k.decode(): [v.decode() for v in vs]
                for k, vs in LEET.items()
            },
            "digest_list": [d.hex() for d in digests],
        }) + "\n" + json.dumps({"op": "shutdown"}) + "\n")
        out = io.StringIO()
        try:
            serve_stdio(eng, reqs, out)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if '"done"' in out.getvalue():
                    break
                time.sleep(0.05)
        finally:
            eng.close()
        events = [json.loads(ln) for ln in out.getvalue().splitlines()
                  if ln.strip()]
        (done,) = [e for e in events if e["event"] == "done"]
        assert done["spans"]["spans"] > 0
        assert "dead_host_s" in done["spans"]


# ---------------------------------------------------------------------------
# --metrics-json writer
# ---------------------------------------------------------------------------


class TestMetricsJson:
    def test_writer_snapshot_and_spans(self, tmp_path):
        from hashcat_a5_table_generator_tpu.cli import _write_metrics_json

        spec = AttackSpec(mode="default", algo="md5")
        _planted, digests = planted_digests(spec, LEET, WORDS, (0,))
        sweep = Sweep(spec, LEET, WORDS, digests, config=cfg(superstep=1))
        sweep.run_crack(resume=False)
        path = tmp_path / "metrics.json"
        _write_metrics_json(str(path), [sweep])
        doc = json.loads(path.read_text())
        assert doc["spans"]["sweep"]["spans"] > 0
        assert doc["metrics"]["sweep.candidates"]["type"] == "counter"

    def test_cli_flags_parse(self):
        from hashcat_a5_table_generator_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["words.txt", "-t", "x.table", "--metrics-json", "m.json",
             "--profile-dir", "prof"]
        )
        assert args.metrics_json == "m.json"
        assert args.profile == "prof"  # alias of --profile


@pytest.mark.slow
def test_bench_telemetry_ab_record_shape():
    """The §21 measurement instrument: one JSON line, both arms with
    their honesty guards (instrumented arm recorded spans, off arm
    none, identical emitted counts), and the overhead ratio against
    the ≤1% bar.  Slow-marked: it times a subprocess bench."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--telemetry-ab",
         "--platform", "cpu", "--lanes", "2048", "--blocks", "32",
         "--words", "2000", "--seconds", "6"],
        capture_output=True, timeout=540, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    assert rec["metric"] == "telemetry_overhead_ab"
    assert rec["instrumented"]["fetch_spans"] > 0
    assert rec["off"]["fetch_spans"] == 0
    assert rec["instrumented"]["runs"] == rec["off"]["runs"] >= 1
    assert rec["bar"] == 0.01
    # CPU-host noise allowance in the SHAPE test; the pinned §21 claim
    # is measured at bench length.
    assert rec["overhead_ratio"] < 0.25
